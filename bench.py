"""Benchmark: boosting iterations/sec on a Higgs-like binary problem, one chip.

Reference baseline (BASELINE.md): LightGBM CPU trains Higgs (10.5M rows x 28
features, num_leaves=255, 500 iters) at ~3.84 iters/s on 2x Xeon E5-2690v4
(docs/Experiments.rst:113). This bench runs the same FULL configuration —
binary logloss, 28 dense float features, 10.5M rows, 255 leaves, 255 bins —
on the TPU chip the driver exposes (round 1 ran a 10x-smaller config; the
compact grower made the full shape tractable, see ops/grower_compact.py).

Env knobs (BENCH_ROWS/FEATURES/NUM_LEAVES/MAX_BIN/ITERS/WARMUP) scale it
down for quick runs.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import os
import sys
import time

import numpy as np

ROWS = int(float(os.environ.get("BENCH_ROWS", 10_500_000)))
FEATURES = int(os.environ.get("BENCH_FEATURES", 28))
NUM_LEAVES = int(os.environ.get("BENCH_NUM_LEAVES", 255))
MAX_BIN = int(os.environ.get("BENCH_MAX_BIN", 255))
ITERS = int(os.environ.get("BENCH_ITERS", 15))
WARMUP = int(os.environ.get("BENCH_WARMUP", 2))
BASELINE_ITERS_PER_SEC = 3.84  # Higgs-10.5M CPU, docs/Experiments.rst:113


def make_higgs_like(n, f, seed=7):
    """Dense float features + nonlinear binary target (Higgs-shaped)."""
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    w1 = rng.randn(f) / np.sqrt(f)
    w2 = rng.randn(f) / np.sqrt(f)
    logits = X @ w1 + 0.7 * np.abs(X @ w2) - 0.4 + 0.5 * rng.randn(n)
    y = (logits > 0).astype(np.float64)
    return X, y


def main():
    import jax
    # persistent compile cache: the full-config tree program takes ~2 min to
    # compile cold; warm runs of the bench (and of users' jobs) skip it
    cache_dir = os.environ.get(
        "BENCH_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_bench_cache"))
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    import lightgbm_tpu as lgb

    dev = jax.devices()[0]
    X, y = make_higgs_like(ROWS, FEATURES)

    params = {
        "objective": "binary",
        "metric": "auc",
        "num_leaves": NUM_LEAVES,
        "max_bin": MAX_BIN,
        "learning_rate": 0.1,
        "min_data_in_leaf": 100,
        "verbosity": -1,
        # bench runs sync-free; one stop check at the end
        "stop_check_freq": 10_000,
    }
    t0 = time.time()
    ds = lgb.Dataset(X, label=y, params=params)
    ds.construct()
    construct_s = time.time() - t0

    bst = lgb.Booster(params, ds)
    t0 = time.time()
    for _ in range(WARMUP):
        bst.update()
    bst._gbdt._flush_trees()
    warmup_s = time.time() - t0

    t0 = time.time()
    for _ in range(ITERS):
        bst.update()
    bst._gbdt._flush_trees()  # materialize: forces all device work to finish
    train_s = time.time() - t0

    iters_per_sec = ITERS / train_s
    # AUC sanity on the training data (separability check, not a quality bench)
    auc = None
    try:
        from sklearn.metrics import roc_auc_score
        sample = slice(0, min(ROWS, 200_000))
        auc = float(roc_auc_score(y[sample], bst.predict(X[sample])))
    except Exception:
        pass

    # warmup minus two steady-state iterations approximates compile+cache time
    compile_s = max(0.0, warmup_s - WARMUP / max(iters_per_sec, 1e-9))
    sys.stderr.write(
        f"[bench] device={dev} rows={ROWS} features={FEATURES} "
        f"leaves={NUM_LEAVES} bins={MAX_BIN}\n"
        f"[bench] construct={construct_s:.1f}s warmup({WARMUP})={warmup_s:.1f}s "
        f"compile~={compile_s:.1f}s train({ITERS})={train_s:.1f}s auc={auc}\n")
    print(json.dumps({
        "metric": f"synthetic-higgs{ROWS // 1_000_000}M-"
                  f"{NUM_LEAVES}leaf boosting throughput",
        "value": round(iters_per_sec, 3),
        "unit": "iters/sec/chip",
        "vs_baseline": round(iters_per_sec / BASELINE_ITERS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
