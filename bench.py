"""Benchmark: boosting iterations/sec on a Higgs-like binary problem, one chip.

Reference baseline (BASELINE.md): LightGBM CPU trains Higgs (10.5M rows x 28
features, num_leaves=255, 500 iters) at ~3.84 iters/s on 2x Xeon E5-2690v4
(docs/Experiments.rst:113). This bench runs the same FULL configuration —
binary logloss, 28 dense float features, 10.5M rows, 255 leaves, 255 bins —
on the TPU chip the driver exposes (round 1 ran a 10x-smaller config; the
compact grower made the full shape tractable, see ops/grower_compact.py).

Env knobs (BENCH_ROWS/FEATURES/NUM_LEAVES/MAX_BIN/ITERS/WARMUP) scale it
down for quick runs.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import os
import sys
import time

import numpy as np

def _cli_override(flag, default):
    """``--rows 5e5``-style CLI overrides (the env knobs predate them).
    The row override exists so scaled-down runs are explicit in the
    command line AND normalized: every recorded shape now carries a
    rows/s column, so a 500k-row Allstate number is never quoted next to
    the reference's full 13.2M-row wall without a per-row figure.

    Runs at import time (bench.py is also imported for its dataset
    makers), so a missing or unparseable value must not crash the host
    process — it warns and keeps the default."""
    if flag not in sys.argv:
        return default
    idx = sys.argv.index(flag)
    try:
        return int(float(sys.argv[idx + 1]))
    except (IndexError, ValueError):
        sys.stderr.write(f"[bench] ignoring {flag}: expected a numeric "
                         "value after the flag\n")
        return default


ROWS = _cli_override("--rows", int(float(os.environ.get("BENCH_ROWS",
                                                        10_500_000))))
FEATURES = int(os.environ.get("BENCH_FEATURES", 28))
NUM_LEAVES = int(os.environ.get("BENCH_NUM_LEAVES", 255))
MAX_BIN = int(os.environ.get("BENCH_MAX_BIN", 255))
ITERS = int(os.environ.get("BENCH_ITERS", 15))
WARMUP = int(os.environ.get("BENCH_WARMUP", 2))
BASELINE_ITERS_PER_SEC = 3.84  # Higgs-10.5M CPU, docs/Experiments.rst:113


def _clear_backend_cache(jax_mod):
    """Drop jax's (possibly partially-populated) backend cache.

    When plugin discovery initializes CPU first and the TPU plugin then
    fails, xla_bridge has already cached ``_backends={'cpu'}`` before
    raising — a plain ``jax.devices()`` retry would silently return that
    CPU backend and the bench would publish a CPU number as a TPU result.
    Clearing forces a genuine re-init on the next attempt."""
    if getattr(jax_mod, "__name__", None) != "jax":
        return      # test doubles manage their own state
    try:
        from jax._src import xla_bridge
        xla_bridge._clear_backends()
    except Exception:  # pragma: no cover - private API may move
        pass


# transient backend-init / device-enumeration failure signatures: TPU
# runtimes mid-restart, gRPC channels to the TPU worker not yet up, libtpu
# still claiming the chips from a previous process (the r05 bench death:
# the retry loop matched only the first two patterns and the run died on a
# "failed to connect" enumeration error the loop never saw). The canonical
# list lives in lightgbm_tpu.parallel.multihost.TRANSIENT_ERRORS (shared
# with the collective watchdog's retry classifier); the literal below is
# only the standalone-bench fallback.
try:
    from lightgbm_tpu.parallel.multihost import (
        TRANSIENT_ERRORS as _TRANSIENT_BACKEND_ERRORS)
except ImportError:  # standalone bench without the package on sys.path
    _TRANSIENT_BACKEND_ERRORS = (
        "Unable to initialize backend",
        "UNAVAILABLE", "Unavailable",
        "DEADLINE_EXCEEDED", "Deadline Exceeded",
        "failed to connect", "Failed to connect",
        "Connection reset", "Socket closed",
        "already in use",
        "No visible TPU", "device enumeration",
    )


def _init_backend_with_retry(jax_mod, attempts=None, base_delay_s=5.0):
    """Return the default device, retrying transient backend-init AND
    device-enumeration failures.

    TPU runtimes are occasionally mid-restart when the bench launches;
    init errors then clear within seconds. Device ENUMERATION can also
    fail transiently (a gRPC connect error out of ``jax.devices()``, or a
    backend that comes up with an empty device list while the worker
    restarts) — the r05 bench run died on exactly that despite the init
    retry, so enumeration failures retry through the same loop. Each
    retry clears the backend cache first (see _clear_backend_cache) so
    the re-init is real. Non-transient errors re-raise immediately; the
    last transient attempt re-raises too, and main() converts the raise
    into a structured failure stub so the BENCH row is never silently
    absent."""
    if attempts is None:
        # env override rounded + re-guarded, never trusted raw (same
        # convention as LGBM_TPU_FUSED_BS): a 0/negative/garbage value
        # must not turn the retry loop into a silent None return
        try:
            attempts = int(os.environ.get("BENCH_INIT_ATTEMPTS", 5))
        except ValueError:
            sys.stderr.write("[bench] ignoring non-numeric "
                             "BENCH_INIT_ATTEMPTS; using 5 attempts\n")
            attempts = 5
    attempts = max(1, attempts)
    for attempt in range(attempts):
        try:
            _fire_fault("backend_init", attempt=attempt + 1)
            devices = jax_mod.devices()
            if not devices:
                raise RuntimeError(
                    "device enumeration returned an empty device list")
            return devices[0]
        except Exception as err:  # noqa: BLE001 - classified below
            msg = str(err)
            transient = any(t in msg for t in _TRANSIENT_BACKEND_ERRORS)
            if not transient or attempt == attempts - 1:
                raise
            delay = base_delay_s * (2 ** attempt)
            sys.stderr.write(
                f"[bench] backend init failed (attempt {attempt + 1}/"
                f"{attempts}): {msg.splitlines()[0][:200]}; retrying in "
                f"{delay:.0f}s\n")
            _clear_backend_cache(jax_mod)
            time.sleep(delay)


def _fire_fault(site, **ctx):
    """Chaos hook (lightgbm_tpu/analysis/faultinject.py): lets the
    fault-injection tests exercise the bench's backend-retry and
    checkpoint-resume paths deterministically. A no-op when the package
    is absent (bench.py stays runnable standalone) or no spec is armed."""
    try:
        from lightgbm_tpu.analysis.faultinject import active_plan
    except ImportError:  # pragma: no cover - standalone bench
        return
    active_plan().fire(site, **ctx)


def _resumable_update_loop(bst, make_booster, target_iters, ckpt_dir,
                           ckpt_freq=5, keep=2, max_retries=5,
                           base_delay_s=5.0):
    """Advance ``bst`` to ``target_iters`` total iterations, checkpointing
    every ``ckpt_freq`` and RESUMING from the latest snapshot after a
    transient backend death instead of restarting from iteration 0 (the
    r05/r06 death mode the init-retry loop alone could not close: a run
    that died mid-boosting lost every completed iteration). A failure
    that keeps recurring with NO forward progress gives up after
    ``max_retries`` resume attempts (with exponential backoff between
    them) so a persistently-down backend falls through to the structured
    failure stub instead of busy-looping. Returns the (possibly rebuilt)
    booster at ``target_iters``."""
    from lightgbm_tpu.io import checkpoint as ckpt_mod
    retries, last_progress = 0, -1
    while bst.current_iteration() < target_iters:
        try:
            _fire_fault("bench_update", iteration=bst.current_iteration() + 1)
            bst.update()
            done = bst.current_iteration()
            if ckpt_dir and done % ckpt_freq == 0:
                bst.save_checkpoint(ckpt_dir, keep=keep)
        except Exception as err:  # noqa: BLE001 - classified below
            msg = str(err)
            transient = any(t in msg for t in _TRANSIENT_BACKEND_ERRORS)
            if not ckpt_dir or not transient:
                raise
            reached = bst.current_iteration()
            if reached > last_progress:
                retries, last_progress = 0, reached
            retries += 1
            if retries > max_retries:
                sys.stderr.write(
                    f"[bench] giving up after {max_retries} resume "
                    f"attempts with no progress past iteration "
                    f"{last_progress}\n")
                raise
            delay = base_delay_s * (2 ** (retries - 1))
            sys.stderr.write(
                f"[bench] transient failure mid-run at iteration "
                f"{reached}: {msg.splitlines()[0][:200]}; resuming from "
                f"checkpoint in {delay:.0f}s "
                f"(attempt {retries}/{max_retries})\n")
            time.sleep(delay)
            bst = make_booster()
            state = ckpt_mod.load_latest(ckpt_dir)
            if state is not None:
                try:
                    bst._restore_checkpoint(state)
                except ValueError as verr:
                    sys.stderr.write(f"[bench] ignoring incompatible "
                                     f"checkpoint: {verr}\n")
            sys.stderr.write(f"[bench] resumed at iteration "
                             f"{bst.current_iteration()}\n")
    return bst


def _emit_failure_stub(stage: str, err: BaseException) -> None:
    """Print a STRUCTURED failure row and record it in BENCH_SHAPES.json.

    The driver records the bench's one-line JSON; before round 6 a
    backend that never came up raised straight through and the BENCH_r0x
    row was silently absent (the r05 gap). Now the row always exists —
    with ``value: null`` and the error inline — and the process still
    exits nonzero so automation sees the failure."""
    first_line = str(err).splitlines()[0][:300] if str(err) else repr(err)
    payload = {
        "stage": stage,
        "error": first_line,
        "error_type": type(err).__name__,
        "time": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    try:
        _record_shape("last_failure", payload)
    except Exception as rec_err:  # noqa: BLE001 - the stub must not sink
        sys.stderr.write(f"[bench] failed to record failure stub: "
                         f"{rec_err}\n")
    print(json.dumps({
        "metric": f"bench-failed ({stage})",
        "value": None,
        "unit": "iters/sec/chip",
        "vs_baseline": None,
        "error": first_line,
    }))


def _timed_mean(fn, *args, reps=10):
    """THE warm-up/rep timing discipline for fixed-rep microbench cells
    (2 warm calls cover compile + cache fill, then the mean of ``reps``
    back-to-back dispatches with one trailing sync). Every fixed-rep
    section shares this helper so a change to the discipline cannot make
    recorded BENCH_SHAPES cells inconsistent across sections."""
    fn(*args).block_until_ready()
    fn(*args).block_until_ready()
    t0 = time.time()
    for _ in range(reps):
        out = fn(*args)
    out.block_until_ready()
    return (time.time() - t0) / reps


def make_higgs_like(n, f, seed=7):
    """Dense float features + nonlinear binary target (Higgs-shaped)."""
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    w1 = rng.randn(f) / np.sqrt(f)
    w2 = rng.randn(f) / np.sqrt(f)
    logits = X @ w1 + 0.7 * np.abs(X @ w2) - 0.4 + 0.5 * rng.randn(n)
    y = (logits > 0).astype(np.float64)
    return X, y


def make_allstate_like(n, f, card=8, seed=7):
    """Sparse one-hot blocks (Allstate F=4228 shape) — exercises EFB.

    Generated group by group to avoid a dense [n, f] float64 intermediate."""
    rng = np.random.RandomState(seed)
    X = np.zeros((n, f), np.float32)
    logits = 0.5 * rng.randn(n)
    off = 0
    while off < f:
        w = min(card, f - off)           # remainder becomes a smaller group
        cats = rng.randint(0, w, size=n)
        X[np.arange(n), off + cats] = 1.0
        wg = rng.randn(w) * 0.3
        logits += wg[cats]
        off += w
    y = (logits > 0).astype(np.float64)
    return X, y


def make_msltr_like(n, f, docs_per_query=120, seed=7):
    """MS-LTR-shaped ranking data: graded labels 0-4, query groups
    (BASELINE.md MS-LTR row: 2.27M docs x 137 features,
    ref docs/Experiments.rst:117)."""
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    w = rng.randn(f) / np.sqrt(f)
    rel = X @ w + 0.8 * rng.randn(n)
    # graded relevance by global quantiles
    qs = np.quantile(rel, [0.55, 0.75, 0.9, 0.97])
    y = np.digitize(rel, qs).astype(np.float64)
    n_q = n // docs_per_query
    group = np.full(n_q, docs_per_query, np.int64)
    rest = n - n_q * docs_per_query
    if rest:
        group = np.concatenate([group, [rest]])
    return X, y, group


def _record_shape(key, payload):
    rec_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_SHAPES.json")
    rec = {}
    if os.path.exists(rec_path):
        with open(rec_path) as fh:
            rec = json.load(fh)
    rec[key] = payload
    with open(rec_path, "w") as fh:
        json.dump(rec, fh, indent=1, sort_keys=True)


def _arm_autotune(params):
    """BENCH_AUTOTUNE=1: route the round through the startup microbench
    autotuner (lightgbm_tpu/engines/autotune.py) with a bench-local
    cache — the recorded row then reflects MEASURED per-shape engine
    selection (tagged ``autotuned: true``) and the cache's sweep tables
    land in BENCH_SHAPES.json["autotune"]. The cache persists across
    rounds (the point: round 2 resolves with zero microbenches), so a
    deliberate re-sweep is BENCH_AUTOTUNE_MODE=always. Returns the
    cache path, or None when unarmed."""
    if os.environ.get("BENCH_AUTOTUNE", "") != "1":
        return None
    cache = os.environ.get(
        "BENCH_AUTOTUNE_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".bench_autotune.json"))
    params["tpu_autotune"] = os.environ.get("BENCH_AUTOTUNE_MODE",
                                            "first_run")
    params["tpu_autotune_cache"] = cache
    return cache


def _record_autotune_tables(cache):
    """Copy the autotune cache's decision blocks (winner + full sweep
    table per shape-class) into BENCH_SHAPES.json["autotune"]. Best
    effort — never sinks a round that already measured throughput."""
    if not cache:
        return
    try:
        from lightgbm_tpu.engines import autotune as eng_autotune
        tables = eng_autotune.sweep_tables(cache)
        if tables:
            _record_shape("autotune", tables)
            sys.stderr.write(f"[bench] autotune decisions recorded for "
                             f"{sorted(tables)}\n")
    except Exception as err:  # noqa: BLE001 - accounting best-effort
        sys.stderr.write(f"[bench] autotune table recording failed: "
                         f"{err}\n")


def run_hist_microbench(print_json=True):
    """BENCH_HIST_MICRO=1: the tentpole's speed claim, measured directly —
    the quantized int8 one-hot contraction (int8 x int8 -> int32,
    preferred_element_type=int32) vs the fp32-HIGHEST one-hot einsum it
    replaces, on the SAME [N, F] x B histogram shape and channel count.
    Records BENCH_SHAPES.json["hist_micro"] with both timings and the
    speedup (acceptance: >= 2x on TPU)."""
    import functools

    import jax
    import jax.numpy as jnp

    dev = _init_backend_with_retry(jax)
    from lightgbm_tpu.ops.histogram import histogram_block

    n = int(float(os.environ.get("BENCH_HIST_ROWS", 1 << 20)))
    f = int(os.environ.get("BENCH_HIST_FEATURES", 28))
    b = int(os.environ.get("BENCH_HIST_BINS", 256))
    reps = int(os.environ.get("BENCH_HIST_REPS", 10))
    rng = np.random.RandomState(0)
    binned = jnp.asarray(rng.randint(0, b, (n, f)).astype(np.uint8))
    ch_f32 = jnp.asarray(rng.randn(n, 4).astype(np.float32))
    codes = rng.randint(-8, 9, (n, 4)).astype(np.int8)
    codes[:, 2:] = 1                       # count channels
    ch_int8 = jnp.asarray(codes)

    # f32 baseline pinned to the chunked fp32-HIGHEST einsum (the exact
    # path the int8 pipeline replaces); the int path uses the same auto
    # dispatch the trainer uses (Mosaic int8 kernel on TPU, XLA on CPU)
    f32_fn = jax.jit(lambda bn, ch: histogram_block(bn, ch, b, impl="xla"))
    int_fn = jax.jit(lambda bn, ch: histogram_block(bn, ch, b, impl="auto"))

    def bench_one(fn, ch):
        return _timed_mean(fn, binned, ch, reps=reps)

    t_f32 = bench_one(f32_fn, ch_f32)
    t_int = bench_one(int_fn, ch_int8)
    speedup = t_f32 / t_int
    sys.stderr.write(
        f"[bench-hist] platform={dev.platform} shape=[{n}, {f}] B={b} "
        f"f32-HIGHEST={t_f32 * 1e3:.2f}ms int8={t_int * 1e3:.2f}ms "
        f"speedup={speedup:.2f}x\n")

    # batched-M sweep (tpu_hist_mbatch): K row blocks per one-hot
    # contraction -> M = 8K MXU rows (ops/fused_split.py hist_flush);
    # per-K timings of both channel layouts land in BENCH_SHAPES.json
    mb_sweep = {}
    for kb in (1, 8, 16):
        fn_k = jax.jit(functools.partial(
            histogram_block, num_bins=b, impl="auto", mbatch=kb))
        t_kf = bench_one(fn_k, ch_f32)
        t_ki = bench_one(fn_k, ch_int8)
        mb_sweep[str(kb)] = {
            "f32_ms": round(t_kf * 1e3, 3),
            "int8_ms": round(t_ki * 1e3, 3),
            "int8_rows_per_sec": round(n / t_ki),
        }
        sys.stderr.write(
            f"[bench-hist] mbatch={kb}: f32={t_kf * 1e3:.2f}ms "
            f"int8={t_ki * 1e3:.2f}ms ({n / t_ki / 1e6:.1f} Mrows/s)\n")
    layout_sweep = _run_layout_sweep(jax, dev, n, f, reps)
    _record_shape("hist_micro", {
        "platform": dev.platform, "rows": n, "features": f, "bins": b,
        "f32_highest_ms": round(t_f32 * 1e3, 3),
        "int8_ms": round(t_int * 1e3, 3),
        "int8_speedup": round(speedup, 3),
        "mbatch_sweep": mb_sweep,
        "layout_sweep": layout_sweep,
    })
    if print_json:
        print(json.dumps({
            "metric": f"hist-micro [{n // 1024}k x {f}] B={b} int8 speedup",
            "value": round(speedup, 3),
            "unit": "x vs fp32-HIGHEST einsum",
            "vs_baseline": round(speedup / 2.0, 3),  # acceptance target 2x
        }))


def _run_layout_sweep(jax, dev, n, f, reps):
    """{u8, pack4} x {lane, sublane} x {f32, int8, int16-narrowed} at a
    pack4-eligible shape (B=16) — the autotuner's data (ROADMAP item 5).

    Every cell records rows/s plus its speedup vs the u8-lane-f32 cell of
    the SAME shape, so "which engine wins where" is a table lookup, not
    folklore. Cells whose engine needs a TPU backend (the sublane Mosaic
    layout off-TPU) record a skip marker instead of silently vanishing —
    a missing cell reads as "covered", a marked one as "not measured
    here". Narrowed cells use quant_max=9 (num_grad_quant_bins=8 + the
    stochastic-rounding +1)."""
    import functools

    import jax.numpy as jnp
    from lightgbm_tpu.ops.histogram import histogram_block
    from lightgbm_tpu.ops.pallas_histogram import pallas_available

    b = 16                      # pack4- and sublane-eligible bin width
    qmax = 9
    rng = np.random.RandomState(1)
    binned = rng.randint(0, b, (n, f)).astype(np.uint8)
    from lightgbm_tpu.io.dataset import pack4_matrix
    packed = pack4_matrix(binned)   # the trainer's canonical nibble order
    codes = rng.randint(-qmax // 2, qmax // 2 + 1, (n, 4)).astype(np.int8)
    codes[:, 1] = rng.randint(0, qmax, n)       # hess codes >= 0
    codes[:, 2:] = 1
    ch = {"f32": jnp.asarray(rng.randn(n, 4).astype(np.float32)),
          "int8": jnp.asarray(codes), "int16n": jnp.asarray(codes)}
    bins = {"u8": jnp.asarray(binned), "pack4": jnp.asarray(packed)}
    on_tpu = pallas_available()

    cells = {}
    base_rps = None
    for pk in ("u8", "pack4"):
        for lay in ("lane", "sublane"):
            for eng in ("f32", "int8", "int16n"):
                key = f"{pk}-{lay}-{eng}"
                if lay == "sublane" and eng == "int16n":
                    cells[key] = {"skipped": "the narrowed engine is "
                                             "XLA-side; register layout "
                                             "does not apply"}
                    continue
                if lay == "sublane" and not on_tpu:
                    cells[key] = {"skipped": "sublane is a Mosaic layout; "
                                             "needs a TPU backend"}
                    continue
                kw = dict(num_bins=b,
                          impl="pallas" if lay == "sublane" else "auto",
                          layout=lay,
                          packed4_features=f if pk == "pack4" else 0)
                if eng == "int16n":
                    kw.update(acc_bits=16, quant_max=qmax)
                fn = jax.jit(functools.partial(histogram_block, **kw))
                try:
                    dt = _timed_mean(fn, bins[pk], ch[eng], reps=reps)
                except Exception as err:  # noqa: BLE001 - record, move on
                    cells[key] = {"error": str(err).splitlines()[0][:200]}
                    continue
                rps = n / dt
                cells[key] = {"ms": round(dt * 1e3, 3),
                              "rows_per_sec": round(rps)}
                if key == "u8-lane-f32":
                    base_rps = rps
                sys.stderr.write(f"[bench-hist] {key}: {dt * 1e3:.2f}ms "
                                 f"({rps / 1e6:.1f} Mrows/s)\n")
    if base_rps:
        for key, cell in cells.items():
            if "rows_per_sec" in cell:
                cell["speedup_vs_f32"] = round(
                    cell["rows_per_sec"] / base_rps, 3)
    quant_cells = {k: c.get("speedup_vs_f32") for k, c in cells.items()
                   if ("int8" in k or "int16n" in k)
                   and c.get("speedup_vs_f32")}
    best_q = max(quant_cells, key=quant_cells.get) if quant_cells else None
    if best_q:
        sys.stderr.write(
            f"[bench-hist] best quantized/narrowed cell: {best_q} "
            f"({quant_cells[best_q]}x vs u8-lane-f32)\n")
    return {"platform": dev.platform, "rows": n, "features": f, "bins": b,
            "quant_max": qmax, "baseline_cell": "u8-lane-f32",
            "cells": cells, "best_quantized_cell": best_q,
            "best_quantized_speedup": quant_cells.get(best_q)
            if best_q else None}


_CONTRIB_CPU_BASELINE_QPS = 18.0  # single-row pred_contrib on the CPU
                                  # LightGBM reference (ISSUE 20)


def _contrib_qps_row(g, binned_all):
    """pred_contrib throughput row for BENCH_SHAPES["predict_micro"]:
    the per-row UNWIND loop kernel (tpu_shap_tables=off) raced against
    the precomputed-table kernel (tpu_shap_tables=on), both through the
    real serving entry (predict_contrib_padded). Rows/s is the QPS of
    row-sized requests, compared against the 18 QPS CPU baseline. A
    failure emits the structured stub and returns the error row rather
    than sinking the whole predict stage."""
    n = int(float(os.environ.get("BENCH_CONTRIB_ROWS", 1000)))
    req = binned_all[:n]
    row = {"rows": n, "cpu_baseline_qps": _CONTRIB_CPU_BASELINE_QPS}
    try:
        for label, mode in (("loop", "off"), ("tables", "on")):
            g.config.set({"tpu_shap_tables": mode})
            g._shap_tables_cache = None
            fn = (lambda: np.asarray(
                g.predict_contrib_padded(req)).sum())
            t1 = time.time()
            fn()  # warm: table build + compile land here
            once = time.time() - t1
            reps = max(1, min(5, int(2.0 / max(once, 1e-9))))
            t1 = time.time()
            for _ in range(reps):
                fn()
            dt = (time.time() - t1) / reps
            row[label + "_s"] = round(dt, 4)
            row[label + "_rows_per_sec"] = round(n / dt, 1)
            sys.stderr.write(
                f"[bench-predict] contrib/{label} N={n}: "
                f"{dt * 1e3:.1f}ms ({n / dt:.0f} rows/s)\n")
    except Exception as err:  # noqa: BLE001 - keep the predict row
        row["error"] = f"{type(err).__name__}: {err}"
        _emit_failure_stub("predict-contrib", err)
    finally:
        g.config.set({"tpu_shap_tables": "auto"})
        g._shap_tables_cache = None
    if row.get("tables_rows_per_sec") and row.get("loop_rows_per_sec"):
        row["tables_speedup"] = round(
            row["tables_rows_per_sec"] / row["loop_rows_per_sec"], 2)
        row["qps_vs_cpu_baseline"] = round(
            row["tables_rows_per_sec"] / _CONTRIB_CPU_BASELINE_QPS, 1)
    return row


def run_predict_microbench(print_json=True):
    """BENCH_PREDICT=1: races every serving engine per shape — the
    depth-batched walk ("batched"), the pre-change serial tree scan
    ("scan"), the level-order heap relayout ("level"), and the level
    engine over int8 quantized leaf slabs ("qleaf") — measured end to
    end at the gbdt serving entry on already-binned requests.

    Sweeps batch sizes {1k, 10k, 100k, 1M} x tree counts {100, 500}
    (255-leaf trees) and records, per cell, rows/s for every engine
    plus the compile events each leg spent across its whole sweep — the
    old path compiles one program per (T, N) shape, the bucketed
    engines one per (row rung, tree bucket). Acceptance (ISSUE 5):
    >= 5x rows/s at T=500, N=100k on the CPU backend. A pred_contrib
    QPS row (UNWIND loop kernel vs precomputed tables, vs the 18 QPS
    CPU baseline) rides along. Results land in
    BENCH_SHAPES.json["predict_micro"].

    Trees are real (trained on a Higgs-like shape); larger tree counts
    tile the trained base model — traversal cost per tree is
    structure-dependent, not value-dependent, so tiling preserves the
    measured work while keeping the bench's training phase short.
    """
    import jax

    dev = _init_backend_with_retry(jax)
    import lightgbm_tpu as lgb
    from lightgbm_tpu.analysis import guards

    train_rows = int(float(os.environ.get("BENCH_PREDICT_TRAIN_ROWS",
                                          30_000)))
    feats = int(os.environ.get("BENCH_FEATURES", 28))
    leaves = int(os.environ.get("BENCH_NUM_LEAVES", 255))
    base_trees = int(os.environ.get("BENCH_PREDICT_BASE_TREES", 50))
    tree_sweep = [int(t) for t in os.environ.get(
        "BENCH_PREDICT_TREES", "100,500").split(",")]
    rows_sweep = [int(float(t)) for t in os.environ.get(
        "BENCH_PREDICT_ROWS", "1000,10000,100000,1000000").split(",")]
    budget_s = float(os.environ.get("BENCH_PREDICT_BUDGET_S", 120.0))
    if any(t % base_trees for t in tree_sweep):
        raise SystemExit("BENCH_PREDICT_TREES entries must be multiples of "
                         f"BENCH_PREDICT_BASE_TREES ({base_trees})")

    X, y = make_higgs_like(train_rows, feats)
    params = {
        "objective": "binary", "num_leaves": leaves, "max_bin": 255,
        "learning_rate": 0.1, "min_data_in_leaf": 20, "verbosity": -1,
        "stop_check_freq": 10_000,
    }
    t0 = time.time()
    bst = lgb.train(params, lgb.Dataset(X, label=y, params=params),
                    base_trees)
    g = bst._gbdt
    g._flush_trees()
    sys.stderr.write(f"[bench-predict] trained {len(g.models)} x "
                     f"{leaves}-leaf trees in {time.time() - t0:.1f}s "
                     f"(depth {g._models_max_depth(g.models)})\n")
    base_models = list(g.models)

    rng = np.random.RandomState(3)
    n_max = max(rows_sweep)
    Xq = rng.randn(min(n_max, 1 << 20), feats).astype(np.float32)
    binned_all = g.bin_matrix(np.resize(Xq, (n_max, feats)))

    def timed(fn, n_rows):
        t1 = time.time()
        fn()
        once = time.time() - t1
        reps = max(1, min(5, int(2.0 / max(once, 1e-9))))
        t1 = time.time()
        for _ in range(reps):
            fn()
        dt = (time.time() - t1) / reps
        return dt, n_rows / dt

    # Engine legs raced per shape cell. "batched" is the depth-batched
    # walk, "scan" the pre-change serial tree loop, "level" the
    # breadth-first heap relayout, "qleaf" the level engine over int8
    # quantized leaf slabs (the compiled-forest serving stack). A leg
    # that dies records a structured per-engine error and the others
    # keep racing — the row is never silently absent.
    engine_legs = (
        ("batched", {"tpu_predict_engine": "batched"}),
        ("scan", {"tpu_predict_engine": "scan"}),
        ("level", {"tpu_predict_engine": "level"}),
        ("qleaf", {"tpu_predict_engine": "level",
                   "tpu_leaf_quant": "int8"}),
    )
    cells = {}
    compile_events = {}
    engine_errors = {}
    for engine, overrides in engine_legs:
        g.config.set(dict({"tpu_leaf_quant": "off"}, **overrides))
        try:
            with guards.compile_counter() as cc:
                for t_count in tree_sweep:
                    g.models = base_models * (t_count // base_trees)
                    g._invalidate_device_trees()
                    skip_rest = False
                    for n in sorted(rows_sweep):
                        key = f"t{t_count}_n{n}"
                        cell = cells.setdefault(key, {"trees": t_count,
                                                      "rows": n})
                        if skip_rest:
                            cell[engine + "_s"] = None
                            continue
                        req = binned_all[:n]
                        fn = (lambda: np.asarray(
                            g.predict_raw_device(req)).sum())
                        dt, rps = timed(fn, n)
                        cell[engine + "_s"] = round(dt, 4)
                        cell[engine + "_rows_per_sec"] = round(rps)
                        sys.stderr.write(
                            f"[bench-predict] {engine} T={t_count} "
                            f"N={n}: {dt * 1e3:.1f}ms "
                            f"({rps / 1e6:.2f} Mrows/s)\n")
                        # the serial scan is O(T*L*N); stop a sweep leg
                        # that would blow the budget and record the gap
                        # honestly
                        if dt * 10 > budget_s:
                            skip_rest = True
            compile_events[engine] = cc.lowerings
        except Exception as err:  # noqa: BLE001 - race the other legs
            engine_errors[engine] = f"{type(err).__name__}: {err}"
            _emit_failure_stub(f"predict-{engine}", err)
    g.config.set({"tpu_predict_engine": "batched",
                  "tpu_leaf_quant": "off"})
    g.models = base_models
    g._invalidate_device_trees()

    for cell in cells.values():
        if cell.get("scan_s") and cell.get("batched_s"):
            cell["speedup"] = round(cell["scan_s"] / cell["batched_s"], 2)
        for eng in ("level", "qleaf"):
            if cell.get(eng + "_s") and cell.get("batched_s"):
                cell[eng + "_vs_batched"] = round(
                    cell["batched_s"] / cell[eng + "_s"], 3)
    t_top = max(tree_sweep)
    accept = cells.get(f"t{t_top}_n100000", {}).get("speedup")
    sys.stderr.write(
        f"[bench-predict] compile events: "
        + " ".join(f"{k}={v}" for k, v in compile_events.items())
        + f"; T={t_top} N=100k speedup={accept}x\n")
    contrib = _contrib_qps_row(g, binned_all)
    _record_shape("predict_micro", {
        "platform": dev.platform, "leaves": leaves,
        "train_rows": train_rows, "features": feats,
        "cells": cells, "compile_events": compile_events,
        "engine_errors": engine_errors or None,
        "contrib": contrib,
        "t500_n100k_speedup": accept,
    })
    if print_json:
        print(json.dumps({
            "metric": f"predict-micro {t_top}x{leaves}-leaf trees "
                      "N=100k engine speedup",
            "value": accept,
            "unit": "x vs serial tree scan",
            "vs_baseline": round((accept or 0) / 5.0, 3),  # acceptance 5x
        }))


def run_serving_bench(print_json=True):
    """BENCH_SERVING=1: sustained-QPS sweep through the micro-batch
    coalescer (lightgbm_tpu/serving/) with mixed request sizes.

    Open-loop offered load: BENCH_SERVING_THREADS client threads pace
    submissions to each BENCH_SERVING_QPS level for
    BENCH_SERVING_DURATION_S, cycling BENCH_SERVING_SIZES rows per
    request, WITHOUT waiting for responses — so queue pressure (and load
    shedding) is real. Per level: p50/p99 end-to-end latency (submit ->
    completion, from the ServeFuture timestamps), achieved QPS,
    shed/timeout rates. The whole traffic phase runs post-warmup under a
    compile counter — the serving steady state must lower NOTHING
    (compile_events_steady == 0 is the acceptance gate from ISSUE 9).
    Results land in BENCH_SHAPES.json["serving"]; a failure emits the
    structured stub row like every other stage."""
    import jax

    dev = _init_backend_with_retry(jax)
    import lightgbm_tpu as lgb
    from lightgbm_tpu.analysis import guards
    from lightgbm_tpu.serving import ServerOverloaded, ServingTimeout

    train_rows = int(float(os.environ.get("BENCH_SERVING_TRAIN_ROWS",
                                          20_000)))
    feats = int(os.environ.get("BENCH_FEATURES", 28))
    leaves = int(os.environ.get("BENCH_SERVING_LEAVES", 63))
    rounds = int(os.environ.get("BENCH_SERVING_TREES", 20))
    ladder = os.environ.get("BENCH_SERVING_BUCKETS", "256,1024,4096")
    tick_ms = float(os.environ.get("BENCH_SERVING_TICK_MS", 2.0))
    deadline_ms = float(os.environ.get("BENCH_SERVING_DEADLINE_MS", 2000.0))
    queue_max = int(os.environ.get("BENCH_SERVING_QUEUE_MAX", 16384))
    duration_s = float(os.environ.get("BENCH_SERVING_DURATION_S", 3.0))
    threads = int(os.environ.get("BENCH_SERVING_THREADS", 8))
    qps_levels = [int(float(q)) for q in os.environ.get(
        "BENCH_SERVING_QPS", "100,300,1000").split(",")]
    sizes = [int(s) for s in os.environ.get(
        "BENCH_SERVING_SIZES", "1,8,64,256").split(",")]

    endpoints = [e.strip() for e in os.environ.get(
        "BENCH_SERVING_ENDPOINTS", "predict,leaf,contrib").split(",")
        if e.strip()]
    featurize_mode = os.environ.get("BENCH_SERVING_FEATURIZE", "device")

    X, y = make_higgs_like(train_rows, feats)
    params = {
        "objective": "binary", "num_leaves": leaves, "max_bin": 63,
        "learning_rate": 0.1, "min_data_in_leaf": 20, "verbosity": -1,
        "stop_check_freq": 10_000, "tpu_predict_buckets": ladder,
        "tpu_serve_endpoints": ",".join(endpoints),
        "tpu_serve_featurize": featurize_mode,
    }
    t0 = time.time()
    bst = lgb.train(params, lgb.Dataset(X, label=y, params=params), rounds)
    sys.stderr.write(f"[bench-serving] trained {rounds} x {leaves}-leaf "
                     f"trees in {time.time() - t0:.1f}s\n")

    server = bst.serve(tick_ms=tick_ms, queue_max=queue_max,
                       deadline_ms=deadline_ms)
    warm = server.registry.warm_stats()
    sys.stderr.write(f"[bench-serving] warm: rungs={warm['rungs']} "
                     f"in {warm['seconds']}s ({warm['lowerings']} "
                     f"lowerings)\n")

    # featurize attribution: host seconds vs device seconds for one
    # top-rung batch — the hoist ISSUE 13 claims, as a recorded number.
    # Host = the bin_columns sweep predict_serving used to run per tick;
    # device = the jitted raw->binned program (ops/device_bin.py), timed
    # blocked so it is device work, not dispatch.
    import jax as _jax
    import threading as _threading
    rng = np.random.RandomState(5)
    inner = bst._gbdt
    top_rung = int(max(warm["rungs"]))
    fprobe = rng.randn(top_rung, feats).astype(np.float32)
    reps = int(os.environ.get("BENCH_SERVING_FEATURIZE_REPS", 20))
    _jax.block_until_ready(inner.featurize_rung(fprobe))     # warm
    t0 = time.perf_counter()
    for _ in range(reps):
        inner.bin_matrix(fprobe)
    host_s = (time.perf_counter() - t0) / reps
    t0 = time.perf_counter()
    for _ in range(reps):
        _jax.block_until_ready(inner.featurize_rung(fprobe))
    dev_s = (time.perf_counter() - t0) / reps
    featurize_row = {
        "rows": top_rung, "mode": featurize_mode,
        "featurize_host_seconds": round(host_s, 6),
        "featurize_device_seconds": round(dev_s, 6),
        "host_over_device": round(host_s / max(dev_s, 1e-9), 3),
    }
    sys.stderr.write(f"[bench-serving] featurize {top_rung} rows: "
                     f"host {host_s*1e3:.2f}ms vs device program "
                     f"{dev_s*1e3:.2f}ms\n")

    pool = rng.randn(max(sizes), feats).astype(np.float32)

    def _run_level(srv, endpoint, qps):
        """One open-loop offered-load level against ``srv``; returns the
        recorded cell (shared by the main sweep and the drift-overhead
        comparison below)."""
        futs, sheds, misc_errors = [], [0], [0]
        mu = _threading.Lock()
        t_end = time.monotonic() + duration_s
        interval = threads / max(qps, 1)

        def client(idx):
            k = idx
            nxt = time.monotonic()
            while True:
                now = time.monotonic()
                if now >= t_end:
                    return
                if now < nxt:
                    time.sleep(min(nxt - now, 0.01))
                    continue
                nxt += interval
                size = sizes[k % len(sizes)]
                k += threads
                try:
                    f = srv.submit(pool[:size], kind=endpoint)
                    with mu:
                        futs.append(f)
                except ServerOverloaded:
                    with mu:
                        sheds[0] += 1
                except Exception:  # noqa: BLE001 - counted below
                    with mu:
                        misc_errors[0] += 1

        ts = [_threading.Thread(target=client, args=(i,))
              for i in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        # settle: every admitted request completes or times out
        lat, timeouts, failed, rows_done = [], 0, 0, 0
        for f in futs:
            try:
                f.result()
                lat.append(f.latency_s)
                rows_done += f.n
            except ServingTimeout:
                timeouts += 1
            except Exception:  # noqa: BLE001 - recorded as failure
                failed += 1
        offered = len(futs) + sheds[0] + misc_errors[0]
        lat_ms = np.asarray(lat) * 1e3 if lat else np.array([])
        return {
            "offered_qps": round(offered / duration_s, 1),
            "achieved_qps": round(len(lat) / duration_s, 1),
            # rows actually served, not completed-count x mean size:
            # shedding is size-biased (big submits shed first), which
            # would otherwise overstate rows/s exactly under overload
            "rows_per_sec": round(rows_done / duration_s),
            "p50_ms": round(float(np.percentile(lat_ms, 50)), 2)
            if lat else None,
            "p99_ms": round(float(np.percentile(lat_ms, 99)), 2)
            if lat else None,
            "shed_rate": round(sheds[0] / max(offered, 1), 4),
            "timeout_rate": round(timeouts / max(offered, 1), 4),
            "failed": failed + misc_errors[0],
        }

    levels = {}
    with guards.compile_counter() as steady_cc:
        # per-endpoint levels: the same open-loop sweep drives each
        # enabled endpoint (predict / leaf / contrib) through the shared
        # coalescer ladder
        for endpoint, qps in [(e, q) for e in endpoints
                              for q in qps_levels]:
            cell = _run_level(server, endpoint, qps)
            cell["endpoint"] = endpoint
            key = (str(qps) if endpoint == "predict"
                   else f"{endpoint}@{qps}")   # predict keeps the legacy key
            levels[key] = cell
            # same schema as the training rows: when BENCH_METRICS_PATH is
            # armed, each level also lands in the unified metrics stream
            # (shed-rate beside compile counts — scripts/obs reads both)
            if os.environ.get("BENCH_METRICS_PATH"):
                from lightgbm_tpu.obs import metrics as obs_metrics
                s = obs_metrics.stream_for(os.environ["BENCH_METRICS_PATH"])
                if s is not None:
                    s.emit("serving_level", qps=qps, endpoint=endpoint,
                           **cell)
            sys.stderr.write(
                f"[bench-serving] {endpoint} qps={qps}: achieved="
                f"{cell['achieved_qps']} p50={cell['p50_ms']}ms "
                f"p99={cell['p99_ms']}ms shed={cell['shed_rate']:.1%} "
                f"timeout={cell['timeout_rate']:.1%}\n")
    server.close(drain=True)
    stats = server.stats
    sys.stderr.write(f"[bench-serving] steady compile events: "
                     f"{steady_cc.lowerings} (must be 0); "
                     f"coalescer stats: {stats}\n")
    top = levels[str(qps_levels[-1])]

    # drift/SLO overhead (ISSUE 14): re-run the recorded top predict
    # level with the serving-quality monitors ARMED — sustained QPS and
    # p99 with observation on vs off, so the "observe" pillar's cost is
    # a recorded number, and the monitors' own zero-recompile contract
    # is re-proven under load. A failure here stubs structurally
    # (stage "serving-drift") without losing the main serving row.
    drift_row = None
    if os.environ.get("BENCH_SERVING_DRIFT", "1") != "0":
        try:
            top_qps = qps_levels[-1]
            flush_every = int(os.environ.get("BENCH_SERVING_DRIFT_FLUSH",
                                             50))
            srv_on = bst.serve(tick_ms=tick_ms, queue_max=queue_max,
                               deadline_ms=deadline_ms,
                               drift_flush_every=flush_every,
                               slo_ms=deadline_ms / 2)
            try:
                with guards.compile_counter() as drift_cc:
                    cell_on = _run_level(srv_on, "predict", top_qps)
                mon = srv_on.observer.drift
                keys = ("achieved_qps", "rows_per_sec", "p50_ms",
                        "p99_ms")
                drift_row = {
                    "qps": top_qps, "flush_every": flush_every,
                    "off": {k: top.get(k) for k in keys},
                    "on": {k: cell_on.get(k) for k in keys},
                    "p99_overhead_ms": (
                        round(cell_on["p99_ms"] - top["p99_ms"], 2)
                        if cell_on.get("p99_ms") is not None
                        and top.get("p99_ms") is not None else None),
                    "flushes": mon.flushes,
                    "host_syncs": mon.host_syncs,
                    "max_psi": mon.gauges().get("max_psi"),
                    "slo": srv_on.observer.slo.snapshot(),
                    "compile_events_steady": drift_cc.lowerings,
                }
            finally:
                srv_on.close(drain=True)
            sys.stderr.write(
                f"[bench-serving] drift_overhead @ {top_qps} qps: "
                f"p99 {top.get('p99_ms')}ms off -> "
                f"{cell_on.get('p99_ms')}ms on "
                f"({drift_row['flushes']} flushes, "
                f"{drift_row['compile_events_steady']} steady "
                f"compiles)\n")
        except Exception as err:  # noqa: BLE001 - stub, keep the main row
            _emit_failure_stub("serving-drift", err)
            drift_row = None

    _record_shape("serving", {
        "platform": dev.platform, "trees": rounds, "leaves": leaves,
        "features": feats, "ladder": warm["rungs"],
        "endpoints": endpoints,
        "tick_ms": tick_ms, "deadline_ms": deadline_ms,
        "queue_max_rows": queue_max, "sizes": sizes,
        "duration_s": duration_s, "levels": levels,
        "warmup": warm,
        "featurize": featurize_row,
        "drift_overhead": drift_row,
        "compile_events_steady": steady_cc.lowerings,
        "coalescer": stats,
    })
    if print_json:
        print(json.dumps({
            "metric": f"serving p99 @ {qps_levels[-1]} qps "
                      f"(mixed sizes {sizes})",
            "value": top["p99_ms"],
            "unit": "ms",
            # acceptance: 0 steady-state compiles; encode it in the row
            "vs_baseline": steady_cc.lowerings,
        }))


def run_ranking_bench():
    """Lambdarank at MS-LTR scale: pair-block chunking + NDCG under load."""
    import jax
    jax.config.update("jax_compilation_cache_dir", os.environ.get(
        "BENCH_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_bench_cache")))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    _init_backend_with_retry(jax)
    import lightgbm_tpu as lgb

    rows = int(float(os.environ.get("BENCH_ROWS", 2_270_000)))
    feats = int(os.environ.get("BENCH_FEATURES", 137))
    iters = int(os.environ.get("BENCH_ITERS", 10))
    X, y, group = make_msltr_like(rows, feats)
    params = {
        "objective": "lambdarank", "metric": "ndcg", "eval_at": [10],
        "num_leaves": int(os.environ.get("BENCH_NUM_LEAVES", 255)),
        "max_bin": int(os.environ.get("BENCH_MAX_BIN", 255)),
        "learning_rate": 0.1, "min_data_in_leaf": 50, "verbosity": -1,
        "stop_check_freq": 10_000,
    }
    ds = lgb.Dataset(X, label=y, group=group, params=params)
    bst = lgb.Booster(params, ds)
    t0 = time.time()
    for _ in range(WARMUP):
        bst.update()
    bst._gbdt._flush_trees()
    warm = time.time() - t0
    t0 = time.time()
    for _ in range(iters):
        bst.update()
    bst._gbdt._flush_trees()
    dt = time.time() - t0
    (_, name, ndcg, _), = bst.eval_train()
    sys.stderr.write(f"[bench-ranking] rows={rows} features={feats} "
                     f"warmup={warm:.1f}s train({iters})={dt:.1f}s "
                     f"{name}={ndcg:.5f}\n")
    _record_shape("ranking", {
        "rows": rows, "features": feats, "leaves": params["num_leaves"],
        "iters_per_sec": round(iters / dt, 3),
        "rows_per_sec": round(rows * iters / dt),
        "ndcg": round(float(ndcg), 5),
    })
    # MS-LTR CPU baseline: ref Experiments.rst:117 xgb_hist/LightGBM table
    # does not publish iters/sec for MS-LTR; report absolute throughput
    print(json.dumps({
        "metric": f"synthetic-msltr{rows // 1_000_000}M-"
                  f"{params['num_leaves']}leaf lambdarank throughput",
        "value": round(iters / dt, 3),
        "unit": "iters/sec/chip",
        "vs_baseline": round(float(ndcg), 5),
    }))


def _record_scaling_ledger(jax, trace_dir, shape, iters_per_sec,
                           timed_iters):
    """BENCH_LEDGER=1: parse the round's profiler trace and record the
    scaling-efficiency block (obs/ledger.py) into COMM_ACCOUNTING.json
    (+ BENCH_MULTICHIP_PATH when set). Best-effort — the ledger must
    never sink a bench round that already measured its throughput."""
    try:
        from lightgbm_tpu.obs import ledger as obs_ledger
        from lightgbm_tpu.obs import tracing as obs_tracing
        analysis = obs_tracing.analyze_trace_dir(trace_dir)
        if analysis is None:
            sys.stderr.write(f"[bench] ledger: no trace artifact under "
                             f"{trace_dir}\n")
            return
        n_chips = len(jax.devices())
        contract_mode = os.environ.get(
            "BENCH_LEDGER_CONTRACT",
            "data_scatter" if n_chips > 1 else "serial_compact")
        contract = obs_ledger.load_contract(contract_mode)
        comm_path = os.environ.get(
            "BENCH_COMM_ACCOUNTING",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "COMM_ACCOUNTING.json"))
        block = obs_ledger.ledger_block(
            shape, n_chips, iters_per_sec, analysis=analysis,
            contract=contract, steps=timed_iters,
            prior_rows=obs_ledger.prior_rows(comm_path, shape))
        key = f"{shape}_x{n_chips}"
        obs_ledger.record(comm_path, key, block)
        mc_path = os.environ.get("BENCH_MULTICHIP_PATH", "")
        if mc_path:
            obs_ledger.record(mc_path, key, block)
        mvm = block.get("measured_vs_model", {})
        sys.stderr.write(
            f"[bench] ledger[{key}]: efficiency="
            f"{block['scaling'][-1].get('efficiency')} comm_fraction="
            f"{mvm.get('measured', {}).get('comm_fraction')} -> "
            f"{comm_path}\n")
    except Exception as err:  # noqa: BLE001 - never sink the bench row
        sys.stderr.write(f"[bench] ledger failed: {err}\n")


def _bench_stage() -> str:
    """The ONE env-precedence chain both the dispatcher and the failure
    stub key on — a new bench mode added here is automatically labeled
    correctly in "last_failure" rows."""
    if os.environ.get("BENCH_HIST_MICRO", "") == "1":
        return "hist-micro"
    if os.environ.get("BENCH_PREDICT", "") == "1":
        return "predict-micro"
    if os.environ.get("BENCH_SERVING", "") == "1":
        return "serving"
    if os.environ.get("BENCH_RANKING", "") == "1":
        return "ranking"
    return "train"


def main():
    """Dispatch wrapper: any unhandled failure — the backend never coming
    up after retries, an OOM mid-run — emits a structured stub row
    (value null + the error inline, also recorded in BENCH_SHAPES.json
    "last_failure") before re-raising, so the BENCH_r0x row is never
    silently absent (the r05 gap)."""
    stage = _bench_stage()
    try:
        return _main(stage)
    except BaseException as err:
        if isinstance(err, (KeyboardInterrupt, SystemExit)):
            raise
        _emit_failure_stub(stage, err)
        raise


def _main(stage=None):
    stage = stage or _bench_stage()
    if stage == "hist-micro":
        return run_hist_microbench()
    if stage == "predict-micro":
        return run_predict_microbench()
    if stage == "serving":
        return run_serving_bench()
    if stage == "ranking":
        return run_ranking_bench()
    import jax
    # persistent compile cache: the full-config tree program takes ~2 min to
    # compile cold; warm runs of the bench (and of users' jobs) skip it
    cache_dir = os.environ.get(
        "BENCH_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_bench_cache"))

    import lightgbm_tpu as lgb
    from lightgbm_tpu.analysis.guards import (cache_counter, compile_counter,
                                              configure_compile_cache)
    configure_compile_cache(cache_dir)

    dev = _init_backend_with_retry(jax)
    # announce up front so a silent CPU fallback is visible in the artifact
    sys.stderr.write(f"[bench] backend platform: {dev.platform}\n")
    if dev.platform in ("tpu", "axon") \
            and not os.environ.get("BENCH_SKIP_HIST_MICRO"):
        # cheap (~seconds): every TPU bench run refreshes the int8-vs-f32
        # histogram microbench record alongside the training throughput
        try:
            run_hist_microbench(print_json=False)
        except Exception as err:  # noqa: BLE001 - never sink the main bench
            sys.stderr.write(f"[bench-hist] microbench failed: {err}\n")
    sparse = os.environ.get("BENCH_SPARSE", "") == "1"
    if sparse:
        X, y = make_allstate_like(ROWS, FEATURES)
    else:
        X, y = make_higgs_like(ROWS, FEATURES)

    # unified telemetry (ISSUE 10): the per-iteration metrics stream is
    # the ONE source the BENCH row's counters come from — the booster
    # emits cumulative phase-keyed compile counts per update, bench adds
    # window marks, and obs/summarize.bench_counters diffs them (the
    # inline compile_counter guards below stay as the fallback when the
    # stream is absent)
    metrics_path = os.environ.get(
        "BENCH_METRICS_PATH",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".bench_metrics.jsonl"))
    from lightgbm_tpu.obs import metrics as obs_metrics
    from lightgbm_tpu.obs import summarize as obs_summarize
    mstream = obs_metrics.stream_for(metrics_path)

    def _mark(name):
        from lightgbm_tpu.analysis import guards as _g
        if mstream is not None:
            mstream.emit("mark", name=name,
                         compiles=_g.phase_compile_counts(),
                         cache=_g.global_cache_counts())

    params = {
        "objective": "binary",
        "metric": "auc",
        "num_leaves": NUM_LEAVES,
        "max_bin": MAX_BIN,
        "learning_rate": 0.1,
        "min_data_in_leaf": 100,
        "verbosity": -1,
        # bench runs sync-free; one stop check at the end
        "stop_check_freq": 10_000,
        "tpu_metrics_path": metrics_path,
    }
    if sparse:
        # binary one-hot features: a small sample fully determines the bins,
        # and the host-side mapper loop over F=4228 dominates construct time
        params["bin_construct_sample_cnt"] = 20_000
    autotune_cache = _arm_autotune(params)
    t0 = time.time()
    ds = lgb.Dataset(X, label=y, params=params)
    ds.construct()
    construct_s = time.time() - t0

    # resume-aware long rounds (BENCH_CHECKPOINT_DIR): the booster
    # checkpoints every BENCH_CHECKPOINT_FREQ iterations and a transient
    # backend death mid-run — or a fresh bench invocation after a process
    # death — resumes from the last snapshot instead of iteration 0
    ckpt_dir = os.environ.get("BENCH_CHECKPOINT_DIR", "")
    ckpt_freq = max(1, int(os.environ.get("BENCH_CHECKPOINT_FREQ", "5")))

    def make_booster():
        return lgb.Booster(params, ds)

    bst = make_booster()
    if ckpt_dir:
        from lightgbm_tpu.io import checkpoint as ckpt_mod
        state = ckpt_mod.load_latest(ckpt_dir)
        if state is not None:
            try:
                bst._restore_checkpoint(state)
                sys.stderr.write(f"[bench] resumed from checkpoint at "
                                 f"iteration {bst.current_iteration()}\n")
            except ValueError as err:  # stale dir from a different shape
                sys.stderr.write(f"[bench] ignoring incompatible "
                                 f"checkpoint in {ckpt_dir}: {err}\n")
    t_run0 = time.time()
    t0 = time.time()
    _mark("warmup_start")
    # count warmup lowerings + persistent-cache lookups: with the step
    # ladder (tpu_step_buckets) compile_events is the O(1) rung budget, and
    # a warm BENCH_CACHE_DIR shows cache hits == requests (backend compile
    # skipped) — the compile-time win lands in the BENCH row, not just it/s
    with compile_counter() as warm_cc, cache_counter() as warm_cache:
        if ckpt_dir:
            warm_from = bst.current_iteration()
            bst = _resumable_update_loop(bst, make_booster, WARMUP,
                                         ckpt_dir, ckpt_freq)
            if bst.current_iteration() == warm_from \
                    and warm_from < WARMUP + ITERS:
                # the restore already covered WARMUP, so the loop above
                # performed 0 updates and nothing lowered yet — run ONE
                # update inside the warm window so the step-program
                # compiles land in warmup_seconds/compile_events instead
                # of the timed loop (compile_events_steady must stay 0,
                # and iters/sec must not absorb compile time). A restore
                # that already covers the FULL run gets no extra update:
                # the timed loop will do 0 updates and the row records
                # 0.0 with the stderr note, not a model one iteration
                # longer than the config declares
                bst.update()
            elif bst.current_iteration() >= WARMUP + ITERS:
                sys.stderr.write("[bench] checkpoint already covers the "
                                 "full run; timed loop will perform 0 "
                                 "updates (stale BENCH_CHECKPOINT_DIR?)\n")
        else:
            for _ in range(WARMUP):
                bst.update()
        bst._gbdt._flush_trees()
    warmup_s = time.time() - t0
    _mark("warmup_end")

    # scaling-efficiency ledger (BENCH_LEDGER=1, obs/ledger.py): the
    # timed loop runs under a full profiler trace_session so the
    # device-time analytics can measure the collective durations the
    # byte model only predicts — the measured_vs_model block lands in
    # COMM_ACCOUNTING.json (and BENCH_MULTICHIP_PATH when set) with the
    # round, attribution built in
    import contextlib
    ledger_on = os.environ.get("BENCH_LEDGER", "") == "1"
    ledger_trace_dir = None
    ledger_session = contextlib.nullcontext()
    if ledger_on:
        from lightgbm_tpu.obs import spans as obs_spans
        ledger_trace_dir = os.environ.get(
            "BENCH_TRACE_DIR",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".bench_trace"))
        ledger_session = obs_spans.trace_session(ledger_trace_dir, "full")
    t0 = time.time()
    timed_from = bst.current_iteration()
    with ledger_session:
        with compile_counter() as steady_cc:
            if ckpt_dir:
                bst = _resumable_update_loop(bst, make_booster,
                                             WARMUP + ITERS,
                                             ckpt_dir, ckpt_freq)
            else:
                for _ in range(ITERS):
                    bst.update()
            bst._gbdt._flush_trees()  # materialize: device work finishes
    train_s = time.time() - t0
    _mark("steady_end")
    # the unified-schema counters: derived from the metrics stream (the
    # booster's cumulative per-iteration records + the marks above); the
    # inline counters remain the fallback for a missing/partial stream.
    # Gated on THIS run's stream being live — a stale file from a prior
    # invocation would otherwise hand the row the old run's numbers
    stream_row = (obs_summarize.bench_counters(metrics_path)
                  if mstream is not None else None) or {}
    if stream_row:
        sys.stderr.write(
            f"[bench] counters from metrics stream {metrics_path}: "
            f"{json.dumps(stream_row)}\n")

    # rate over the updates ACTUALLY performed this invocation: a resumed
    # round runs fewer than ITERS in the timed loop, and dividing by the
    # nominal count would record inflated throughput in the BENCH_r0x row
    timed_iters = bst.current_iteration() - timed_from
    if ckpt_dir and timed_iters < ITERS:
        sys.stderr.write(f"[bench] timed loop ran {timed_iters}/{ITERS} "
                         "updates (checkpoint resume); rate uses the "
                         "actual count\n")
    iters_per_sec = (timed_iters / train_s) if timed_iters > 0 else 0.0
    # AUC sanity on the training data (separability check, not a quality bench)
    auc = None
    sample = slice(0, min(ROWS, 200_000))
    try:
        from sklearn.metrics import roc_auc_score
        auc = float(roc_auc_score(y[sample], bst.predict(X[sample])))
    except Exception:
        pass

    # time-to-accuracy: wall clock from construct start (construct + compile
    # + train + eval) until AUC >= TTA_AUC on a 200k train slice — makes
    # compile/construct latency visible next to steady-state it/s. The 0.84
    # default target is higgs-specific; other shapes skip TTA unless
    # BENCH_TTA_AUC is set explicitly
    has_tta = ("BENCH_TTA_AUC" in os.environ or not sparse) \
        and not os.environ.get("LGBM_TPU_FUSED_HIST_DEBUG")
    tta_target = float(os.environ.get("BENCH_TTA_AUC", 0.84))
    wall_to_auc = None
    if auc is not None and has_tta:
        cur = auc
        extra = 0
        while cur < tta_target and extra < 300:
            for _ in range(15):
                bst.update()
            bst._gbdt._flush_trees()
            extra += 15
            from sklearn.metrics import roc_auc_score
            cur = float(roc_auc_score(y[sample], bst.predict(X[sample])))
        if cur >= tta_target:
            wall_to_auc = round(construct_s + (time.time() - t_run0), 1)

    # warmup minus two steady-state iterations approximates compile+cache time
    compile_s = max(0.0, warmup_s - WARMUP / max(iters_per_sec, 1e-9))
    sys.stderr.write(
        f"[bench] device={dev} rows={ROWS} features={FEATURES} "
        f"leaves={NUM_LEAVES} bins={MAX_BIN}\n"
        f"[bench] construct={construct_s:.1f}s warmup({WARMUP})={warmup_s:.1f}s "
        f"compile~={compile_s:.1f}s train({ITERS})={train_s:.1f}s auc={auc}\n"
        f"[bench] compile events: warmup={warm_cc.lowerings} "
        f"(backend={warm_cc.backend_compiles}) steady={steady_cc.lowerings}; "
        f"cache {warm_cache.hits}/{warm_cache.requests} hit\n")
    if os.environ.get("LGBM_TPU_FUSED_HIST_DEBUG"):
        # hist-debug runs produce INVALID results; never record them
        sys.stderr.write("[bench] hist-debug mode: NOT recording shapes\n")
        return
    shape = "allstate" if sparse else "higgs"
    if MAX_BIN != 255:
        # low-bin runs (the reference's GPU learner defaults to 63 bins,
        # docs/GPU-Performance.rst:133) record under their own key
        shape = f"{shape}-b{MAX_BIN}"
    if ledger_on and ledger_trace_dir:
        # same shape key as BENCH_SHAPES so ledger rows and throughput
        # rows join on it
        _record_scaling_ledger(jax, ledger_trace_dir, shape,
                               iters_per_sec, timed_iters)
    # every run also records its result in BENCH_SHAPES.json so the sparse
    # and ranking shape numbers live in files, not prose (run the other
    # shapes via BENCH_SPARSE=1 / BENCH_RANKING=1)
    _record_shape(shape, {
        "rows": ROWS, "features": FEATURES, "leaves": NUM_LEAVES,
        "bins": MAX_BIN, "iters_per_sec": round(iters_per_sec, 3),
        # normalized per-row throughput: rows scanned per second of
        # boosting (iterations x rows) — comparable across row counts
        "rows_per_sec": round(ROWS * iters_per_sec),
        "construct_s": round(construct_s, 1),
        "compile_s": round(compile_s, 1), "auc": auc,
        "wall_to_auc_s": wall_to_auc,
        "wall_to_auc_target": tta_target,
        # compile-time ladder accounting (ISSUE 8) via the unified metrics
        # stream (ISSUE 10): distinct programs lowered during warmup (the
        # rung budget under tpu_step_buckets) WITH phase attribution,
        # steady-state lowerings (must be 0), and persistent-cache
        # hit/miss so warm BENCH_CACHE_DIR rounds are distinguishable
        "warmup_seconds": stream_row.get("warmup_seconds",
                                         round(warmup_s, 1)),
        "compile_events": stream_row.get("compile_events",
                                         warm_cc.lowerings),
        "compile_events_by_phase": stream_row.get("compile_events_by_phase"),
        "compile_events_steady": stream_row.get("compile_events_steady",
                                                steady_cc.lowerings),
        "compile_cache": stream_row.get(
            "compile_cache", {"requests": warm_cache.requests,
                              "hits": warm_cache.hits,
                              "misses": warm_cache.misses}),
        "metrics_stream": metrics_path if stream_row else None,
        # BENCH_LEDGER rounds time the loop UNDER a full profiler
        # session (the ledger needs the trace): per-op tracing overhead
        # loads the number, so the row says so — comparing a ledgered
        # round's it/s against untraced history would be a silent lie
        **({"profiler_loaded": True} if ledger_on else {}),
        # BENCH_AUTOTUNE rounds trained under measured per-shape engine
        # selection; the sweep tables live under the "autotune" key
        **({"autotuned": True} if autotune_cache else {}),
    })
    _record_autotune_tables(autotune_cache)
    print(json.dumps({
        "metric": f"synthetic-{shape}{ROWS // 1_000_000}M-"
                  f"{NUM_LEAVES}leaf boosting throughput",
        "value": round(iters_per_sec, 3),
        "unit": "iters/sec/chip",
        "vs_baseline": round(iters_per_sec / BASELINE_ITERS_PER_SEC, 3),
        "warmup_seconds": stream_row.get("warmup_seconds",
                                         round(warmup_s, 1)),
        "compile_events": stream_row.get("compile_events",
                                         warm_cc.lowerings),
        "compile_cache_hits": stream_row.get(
            "compile_cache", {}).get("hits", warm_cache.hits),
        "compile_cache_misses": stream_row.get(
            "compile_cache", {}).get("misses", warm_cache.misses),
    }))


if __name__ == "__main__":
    main()
