"""Microbench of TPU primitives that decide the compacted-grower design."""
import time
import jax
import jax.numpy as jnp
import numpy as np

N = 10_500_000
F = 28
rng = np.random.RandomState(0)

binned = jnp.asarray(rng.randint(0, 255, size=(N, F), dtype=np.uint8))
idx = jnp.asarray(rng.permutation(N).astype(np.int32))
vals = jnp.asarray(rng.randn(N).astype(np.float32))
keys = jnp.asarray(rng.randint(0, 1 << 30, size=N, dtype=np.int32))


def bench(name, fn, *args, reps=5):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    print(f"{name:40s} {dt*1e3:9.2f} ms   {N/dt/1e9:8.2f} Gelem/s")
    return dt


@jax.jit
def gather_rows(b, i):
    return jnp.take(b, i, axis=0)


@jax.jit
def gather_1d(v, i):
    return jnp.take(v, i)


@jax.jit
def scatter_1d(v, i, x):
    return v.at[i].set(x, unique_indices=True, mode="drop")


@jax.jit
def scatter_add_1d(v, i, x):
    return v.at[i].add(x, mode="drop")


@jax.jit
def cumsum_1d(v):
    return jnp.cumsum(v)


@jax.jit
def sort_kv(k, v):
    return jax.lax.sort((k, v), num_keys=1)


@jax.jit
def argsort_1bit(k):
    # stable partition via argsort of a 0/1 key
    return jnp.argsort(k & 1, stable=True)


print(f"N={N} F={F} device={jax.devices()[0]}")
bench("gather rows [N,28] u8", gather_rows, binned, idx)
bench("gather 1d f32", gather_1d, vals, idx)
bench("scatter 1d set f32 (unique)", scatter_1d, vals, idx, vals)
bench("scatter 1d add f32", scatter_add_1d, vals, idx, vals)
bench("cumsum 1d f32", cumsum_1d, vals)
bench("sort 1d i32 key + i32 payload", sort_kv, keys, idx)
bench("argsort 1-bit stable (partition)", argsort_1bit, keys)
