import time
import jax, jax.numpy as jnp, numpy as np
from jax import lax

N, C = 10_500_000, 64
R = 30000
rng = np.random.RandomState(0)
work0 = jnp.asarray(rng.randint(0, 255, size=(N, C), dtype=np.uint8))
offs = jnp.asarray(rng.randint(0, N - 8192, size=R, dtype=np.int32))

@jax.jit
def empty(work):
    return work[0, 0].astype(jnp.float32)

def rtt():
    s = empty(work0); float(s)
    t0 = time.perf_counter()
    float(empty(work0))
    return time.perf_counter() - t0

base = min(rtt() for _ in range(3))
print(f"dispatch floor ~{base*1e3:.0f} ms")

def make(BS):
    @jax.jit
    def run(work, offs):
        iota2 = jnp.arange(2 * BS, dtype=jnp.int32)
        def body(i, work):
            o = offs[i]
            blk = lax.dynamic_slice(work, (o, 0), (BS, C))
            colv = blk[:, 0].astype(jnp.int32)
            pred = colv < 128
            rl = jnp.cumsum(pred.astype(jnp.int32)) - pred
            rr = jnp.cumsum((~pred).astype(jnp.int32)) - (~pred)
            dest = jnp.where(pred, rl, BS + rr)
            oh = (dest[None, :] == iota2[:, None]).astype(jnp.bfloat16)
            comp = lax.dot_general(oh, blk.astype(jnp.bfloat16),
                                   (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)
            work = lax.dynamic_update_slice(work, comp[:BS].astype(jnp.uint8), (o, 0))
            return work
        work = lax.fori_loop(0, R, body, work)
        return work[0, 0].astype(jnp.float32) + work[N - 1, 0].astype(jnp.float32)
    return run

for BS in (1024, 2048, 4096):
    run = make(BS)
    s = run(work0, offs); float(s)
    t0 = time.perf_counter()
    s = run(work0, offs); float(s)
    dt = (time.perf_counter() - t0 - base) / R
    print(f"BS={BS:5d}: {dt*1e6:8.2f} us/block  {BS/dt/1e6:8.1f} Mrows/s")
