"""Microbench: chain R reps on-device in one dispatch (data-dependent)."""
import time
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

N = 10_500_000
F = 28
R = 20
rng = np.random.RandomState(0)

binned = jnp.asarray(rng.randint(0, 255, size=(N, F), dtype=np.uint8))
idx = jnp.asarray(rng.permutation(N).astype(np.int32))
vals = jnp.asarray(rng.randn(N).astype(np.float32))
keys = jnp.asarray(rng.randint(0, 1 << 30, size=N, dtype=np.int32))


def bench(name, fn, *args, elems=N):
    s = fn(*args); float(s)
    t0 = time.perf_counter()
    s = fn(*args); float(s)
    dt = (time.perf_counter() - t0 - 0.13) / R   # subtract ~RTT
    print(f"{name:40s} {dt*1e3:9.2f} ms   {elems/dt/1e9:8.2f} Gelem/s")


def loopy(body):
    @jax.jit
    def run(*args):
        def step(i, carry):
            return body(i, carry, *args)
        out = lax.fori_loop(0, R, step, jnp.float32(0))
        return out
    return run

g_rows = loopy(lambda i, c, b, ix: c + jnp.take(b, (ix + i) % N, axis=0).sum(dtype=jnp.int32).astype(jnp.float32))
g_1d   = loopy(lambda i, c, v, ix: c + jnp.take(v, (ix + i) % N).sum())
s_set  = loopy(lambda i, c, v, ix: c + (v + c).at[(ix + i) % N].set(v, unique_indices=True, mode="drop").sum())
s_add  = loopy(lambda i, c, v, ix: c + (v + c).at[(ix + i) % N].add(v, mode="drop").sum())
csum   = loopy(lambda i, c, v: c + jnp.cumsum(v + c)[-1] * 1e-9)
srt    = loopy(lambda i, c, k, v: c + lax.sort(((k + i.astype(jnp.int32)), v), num_keys=1)[1][-1].astype(jnp.float32) * 1e-9)

print(f"N={N} F={F} R={R} device={jax.devices()[0]}")
bench("gather rows [N,28] u8", g_rows, binned, idx, elems=N)
bench("gather 1d f32", g_1d, vals, idx)
bench("scatter 1d set f32 (unique)", s_set, vals, idx)
bench("scatter 1d add f32", s_add, vals, idx)
bench("cumsum 1d f32", csum, vals)
bench("sort 1d i32 key + i32 payload", srt, keys, idx)
