"""Tune pallas_histogram vs XLA at bench shapes."""
import time, itertools
import jax, jax.numpy as jnp, numpy as np
from jax import lax
import sys
sys.path.insert(0, "/root/repo")
from lightgbm_tpu.ops.pallas_histogram import pallas_histogram
from lightgbm_tpu.ops.histogram import _xla_histogram

N = 1 << 20   # 1M rows per call
F, B, K = 28, 256, 3
rng = np.random.RandomState(0)
bins = jnp.asarray(rng.randint(0, B, size=(N, F), dtype=np.uint8))
ch = jnp.asarray(rng.randn(N, K).astype(np.float32))
oh_elems = N * F * B

def bench(name, fn, reps=5):
    try:
        out = fn()
        jax.block_until_ready(out); float(jnp.sum(out))
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn()
        float(jnp.sum(out))
        dt = (time.perf_counter() - t0 - 0.13) / reps
        print(f"{name:52s} {dt*1e3:8.2f} ms  {oh_elems/dt/1e12:7.3f} Telem/s")
    except Exception as e:
        print(f"{name:52s} FAIL {type(e).__name__}: {str(e)[:120]}")

bench("xla one-hot einsum (HIGHEST)", lambda: _xla_histogram(bins, ch, B))
for rb, fc, fast in itertools.product([1024, 2048, 4096, 8192], [2, 4, 7, 14, 28], [True]):
    bench(f"pallas rb={rb} fc={fc} fast={fast}",
          lambda rb=rb, fc=fc, fast=fast: pallas_histogram(bins, ch, B, row_block=rb, f_chunk=fc, fast=fast))
bench("pallas rb=2048 fc=4 fast=False",
      lambda: pallas_histogram(bins, ch, B, row_block=2048, f_chunk=4, fast=False))
