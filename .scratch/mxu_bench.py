"""One-hot histogram contraction throughput: orientation x dtype."""
import time
import jax, jax.numpy as jnp, numpy as np
from jax import lax

BS = 131072      # rows per block
F, B, K = 28, 256, 8
FB = F * B
R = 20
rng = np.random.RandomState(0)
bins = jnp.asarray(rng.randint(0, B, size=(BS, F), dtype=np.uint8))
ch = jnp.asarray(rng.randn(BS, K).astype(np.float32))

def bench(name, fn, *args, oh_elems=BS*FB):
    s = fn(*args); jax.block_until_ready(s); float(jnp.sum(s))
    t0 = time.perf_counter()
    s = fn(*args)
    float(jnp.sum(s))
    dt = (time.perf_counter() - t0 - 0.13) / R
    print(f"{name:46s} {dt*1e3:8.2f} ms  {oh_elems/dt/1e12:7.2f} Telem/s")

def loopy(body):
    @jax.jit
    def run(*args):
        def step(i, acc):
            return acc + body(i, *args)
        return lax.fori_loop(0, R, step, jnp.zeros((FB, K), jnp.float32))
    return run

iota = jnp.arange(B, dtype=jnp.int32)

def make(dtype, prec, transpose=False):
    def body(i, bins, ch):
        b32 = (bins + (i % 2).astype(jnp.uint8)).astype(jnp.int32)
        oh = (b32[:, :, None] == iota).astype(dtype).reshape(BS, FB)
        c = ch.astype(dtype)
        if transpose:
            out = lax.dot_general(c, oh, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)  # [K, FB]
            return out.T
        return lax.dot_general(oh, c, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32,
                               precision=prec)
    return loopy(body)

print(f"BS={BS} F={F} B={B} K={K}")
bench("oh[BS,FB]^T @ ch[BS,8]  f32 HIGHEST", make(jnp.float32, lax.Precision.HIGHEST), bins, ch)
bench("oh[BS,FB]^T @ ch[BS,8]  f32 DEFAULT", make(jnp.float32, lax.Precision.DEFAULT), bins, ch)
bench("oh[BS,FB]^T @ ch[BS,8]  bf16", make(jnp.bfloat16, lax.Precision.DEFAULT), bins, ch)
bench("ch.T[8,BS] @ oh[BS,FB]  bf16 (K-major)", make(jnp.bfloat16, None, transpose=True), bins, ch)
bench("oh^T @ ch  int8->int32", make(jnp.int8, lax.Precision.DEFAULT), bins, jnp.ones((BS, K), jnp.float32))
