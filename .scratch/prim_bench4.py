"""Sorted vs random gather; row width variants."""
import time
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

N = 10_500_000
R = 10
rng = np.random.RandomState(0)

binned28 = jnp.asarray(rng.randint(0, 255, size=(N, 28), dtype=np.uint8))
binned32 = jnp.asarray(rng.randint(0, 255, size=(N, 32), dtype=np.uint8))
packed8  = jnp.asarray(rng.randint(0, 2**31, size=(N, 8), dtype=np.int32))
vals = jnp.asarray(rng.randn(N).astype(np.float32))

M = N // 2
sub_sorted = jnp.asarray(np.sort(rng.choice(N, size=M, replace=False)).astype(np.int32))
sub_rand = jnp.asarray(rng.choice(N, size=M, replace=False).astype(np.int32))


def bench(name, fn, *args, elems):
    s = fn(*args); float(s)
    t0 = time.perf_counter()
    s = fn(*args); float(s)
    dt = (time.perf_counter() - t0 - 0.13) / R
    print(f"{name:44s} {dt*1e3:9.2f} ms   {elems/dt/1e9:8.3f} Grows/s")


def loopy(body):
    @jax.jit
    def run(*args):
        return lax.fori_loop(0, R, lambda i, c: body(i, c, *args), jnp.float32(0))
    return run

g28 = loopy(lambda i, c, b, ix: c + jnp.take(b, jnp.minimum(ix + i, N - 1), axis=0).sum(dtype=jnp.int32).astype(jnp.float32))
g1d = loopy(lambda i, c, v, ix: c + jnp.take(v, jnp.minimum(ix + i, N - 1)).sum())

print(f"N={N} M={M} device={jax.devices()[0]}")
bench("gather rows u8[.,28] SORTED idx", g28, binned28, sub_sorted, elems=M)
bench("gather rows u8[.,28] RANDOM idx", g28, binned28, sub_rand, elems=M)
bench("gather rows u8[.,32] SORTED idx", g28, binned32, sub_sorted, elems=M)
bench("gather rows i32[.,8] SORTED idx", g28, packed8, sub_sorted, elems=M)
bench("gather 1d f32 SORTED idx", g1d, vals, sub_sorted, elems=M)
