"""dynamic_slice + dynamic_update_slice + small einsum loop cost (partition body shape)."""
import time
import jax, jax.numpy as jnp, numpy as np
from jax import lax

N, C, BS = 10_500_000, 64, 2048
R = 2000
rng = np.random.RandomState(0)
work = jnp.asarray(rng.randint(0, 255, size=(N, C), dtype=np.uint8))
offs = jnp.asarray(rng.randint(0, N - 2 * BS, size=R, dtype=np.int32))

@jax.jit
def run(work, offs):
    iota2 = jnp.arange(2 * BS, dtype=jnp.int32)
    def body(i, carry):
        work, acc = carry
        o = offs[i]
        blk = lax.dynamic_slice(work, (o, 0), (BS, C))          # read
        colv = blk[:, 0].astype(jnp.int32)
        pred = colv < 128
        rl = jnp.cumsum(pred.astype(jnp.int32)) - pred
        rr = jnp.cumsum((~pred).astype(jnp.int32)) - (~pred)
        dest = jnp.where(pred, rl, BS + rr)
        oh = (dest[None, :] == iota2[:, None]).astype(jnp.bfloat16)   # [2BS, BS]
        comp = lax.dot_general(oh, blk.astype(jnp.bfloat16),
                               (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
        comp8 = comp.astype(jnp.uint8)
        work = lax.dynamic_update_slice(work, comp8[:BS], (o, 0))     # write
        return work, acc + comp[0, 0]
    work, acc = lax.fori_loop(0, R, body, (work, jnp.float32(0)))
    return acc

s = run(work, offs); float(s)
t0 = time.perf_counter()
s = run(work, offs); float(s)
dt = (time.perf_counter() - t0 - 0.13) / R
print(f"partition-body step BS={BS} C={C}: {dt*1e6:.1f} us/block -> {BS/dt/1e6:.1f} Mrows/s")
