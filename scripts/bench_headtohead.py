"""Head-to-head: lightgbm_tpu (one TPU chip) vs the REAL LightGBM (CPU).

Same synthetic data, same config, held-out quality + wall-clock for both
sides (VERDICT r3 item 4: turn the accuracy and speed claims into
measurements). The reference build comes from /root/reference compiled into
.refsrc/lib_lightgbm.so (see tests/golden/README.md); it runs on THIS host's
CPU — note the core count in the output when comparing against the
28-thread numbers in BASELINE.md (docs/Experiments.rst).

Shapes (reference: Experiments.rst:113-121 table):
  higgs    dense 28-feature binary        (10.5M rows full size)
  sparse   one-hot wide binary, EFB territory (4228 raw features)
  ranking  lambdarank, 137 features, 50-doc queries

Writes BENCH_COMPARE.json and prints one line per (shape, side).

Env knobs: H2H_ROWS / H2H_SPARSE_ROWS / H2H_RANK_ROWS, H2H_ITERS,
H2H_SHAPES=higgs,sparse,ranking
"""
import json
import os
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, ".refpkg"))
sys.path.insert(0, ROOT)

ITERS = int(os.environ.get("H2H_ITERS", 15))
LEAVES = 255
BINS = 255


def _auc(y, p):
    from sklearn.metrics import roc_auc_score
    return float(roc_auc_score(y, p))


def _ndcg10(y, p, qsize):
    n = (len(y) // qsize) * qsize
    rel = y[:n].reshape(-1, qsize)
    sc = p[:n].reshape(-1, qsize)
    order = np.argsort(-sc, axis=1)
    g = np.take_along_axis(2.0 ** rel - 1, order, axis=1)[:, :10]
    disc = 1.0 / np.log2(np.arange(2, 12))
    dcg = (g * disc).sum(axis=1)
    ig = np.sort(2.0 ** rel - 1, axis=1)[:, ::-1][:, :10]
    idcg = np.maximum((ig * disc).sum(axis=1), 1e-12)
    return float((dcg / idcg).mean())


def _higgs_data(n, holdout):
    rng = np.random.RandomState(42)
    tot = n + holdout
    X = rng.randn(tot, 28).astype(np.float32)
    w = rng.randn(28) * 0.4
    logits = X @ w + 0.8 * np.sin(X[:, 0] * X[:, 1]) + 0.5 * rng.randn(tot)
    y = (logits > 0).astype(np.float64)
    return X[:n], y[:n], X[n:], y[n:]


def _sparse_data(n, holdout, groups=528, card=8, dense=4):
    rng = np.random.RandomState(7)
    tot = n + holdout
    cats = rng.randint(0, card, size=(tot, groups))
    X = np.zeros((tot, groups * card + dense), np.float32)
    for g in range(groups):
        X[np.arange(tot), g * card + cats[:, g]] = 1.0
    X[:, groups * card:] = rng.randn(tot, dense).astype(np.float32)
    w = rng.randn(X.shape[1]) * 0.3
    y = ((X @ w + 0.6 * rng.randn(tot)) > 0).astype(np.float64)
    return X[:n], y[:n], X[n:], y[n:]


def _rank_data(n, holdout, f=137, qsize=50):
    rng = np.random.RandomState(11)
    tot = (n + holdout) // qsize * qsize
    X = rng.randn(tot, f).astype(np.float32)
    w = rng.randn(f) * 0.3
    score = X @ w + rng.randn(tot)
    rel = np.clip(np.digitize(score, [-1.5, 0.0, 1.5, 2.5]), 0, 4)
    y = rel.astype(np.float64)
    n = n // qsize * qsize
    return X[:n], y[:n], X[n:], y[n:], qsize


def _train(side, shape, params, Xtr, ytr, Xho, group=None):
    if side == "ref":
        import lightgbm as lgb
    else:
        import lightgbm_tpu as lgb
    ds = lgb.Dataset(Xtr, label=ytr, group=group)
    t0 = time.perf_counter()
    bst = lgb.train(params, ds, 2)            # warmup / compile
    warm = time.perf_counter() - t0
    t0 = time.perf_counter()
    bst = lgb.train(params, ds, ITERS)
    dt = time.perf_counter() - t0
    pred = bst.predict(Xho)
    return bst, ITERS / dt, warm, pred


def _flush(out):
    # write after every shape: a crash (e.g. the TPU tunnel restarting
    # mid-run) must not lose completed measurements
    path = os.path.join(ROOT, "BENCH_COMPARE.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(out, fh, indent=1, sort_keys=True)
    os.replace(tmp, path)


def main():
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          os.path.join(ROOT, ".jax_bench_cache"))
    shapes = os.environ.get("H2H_SHAPES", "higgs,sparse,ranking").split(",")
    out = {"host_cpus": os.cpu_count(), "leaves": LEAVES,
           "bins": BINS, "shapes": {}}
    path = os.path.join(ROOT, "BENCH_COMPARE.json")
    if os.path.exists(path):
        try:
            with open(path) as fh:
                prev = json.load(fh)
            out["shapes"].update(prev.get("shapes", {}))
        except ValueError:
            pass  # truncated file from a crashed run; start fresh
    base = {"objective": "binary", "num_leaves": LEAVES, "max_bin": BINS,
            "learning_rate": 0.1, "verbose": -1, "min_data_in_leaf": 100}

    if "higgs" in shapes:
        n = int(float(os.environ.get("H2H_ROWS", 10_500_000)))
        Xtr, ytr, Xho, yho = _higgs_data(n, 500_000)
        res = {}
        for side in ("tpu", "ref"):
            _, ips, warm, pred = _train(side, "higgs", dict(base), Xtr, ytr,
                                        Xho)
            res[side] = {"iters_per_sec": round(ips, 4),
                         "warmup_s": round(warm, 1),
                         "holdout_auc": round(_auc(yho, pred), 6)}
            print(f"higgs {side}: {res[side]}", flush=True)
        res["auc_delta"] = round(res["tpu"]["holdout_auc"]
                                 - res["ref"]["holdout_auc"], 6)
        out["shapes"]["higgs"] = {"rows": n, "features": 28,
                                   "iters": ITERS, **res}
        _flush(out)

    if "sparse" in shapes:
        n = int(float(os.environ.get("H2H_SPARSE_ROWS", 500_000)))
        Xtr, ytr, Xho, yho = _sparse_data(n, 100_000)
        res = {}
        for side in ("tpu", "ref"):
            _, ips, warm, pred = _train(side, "sparse", dict(base), Xtr, ytr,
                                        Xho)
            res[side] = {"iters_per_sec": round(ips, 4),
                         "warmup_s": round(warm, 1),
                         "holdout_auc": round(_auc(yho, pred), 6)}
            print(f"sparse {side}: {res[side]}", flush=True)
        res["auc_delta"] = round(res["tpu"]["holdout_auc"]
                                 - res["ref"]["holdout_auc"], 6)
        out["shapes"]["sparse"] = {"rows": n, "features": Xtr.shape[1],
                                   "iters": ITERS, **res}
        _flush(out)

    if "ranking" in shapes:
        n = int(float(os.environ.get("H2H_RANK_ROWS", 2_270_000)))
        Xtr, ytr, Xho, yho, qsize = _rank_data(n, 250_000)
        rp = {"objective": "lambdarank", "num_leaves": LEAVES,
              "max_bin": BINS, "learning_rate": 0.1, "verbose": -1,
              "min_data_in_leaf": 50, "lambdarank_truncation_level": 30}
        grp = np.full(len(ytr) // qsize, qsize, np.int64)
        res = {}
        for side in ("tpu", "ref"):
            _, ips, warm, pred = _train(side, "ranking", dict(rp), Xtr, ytr,
                                        Xho, group=grp)
            res[side] = {"iters_per_sec": round(ips, 4),
                         "warmup_s": round(warm, 1),
                         "holdout_ndcg10": round(_ndcg10(yho, pred, qsize),
                                                 6)}
            print(f"ranking {side}: {res[side]}", flush=True)
        res["ndcg_delta"] = round(res["tpu"]["holdout_ndcg10"]
                                  - res["ref"]["holdout_ndcg10"], 6)
        out["shapes"]["ranking"] = {"rows": len(ytr),
                                    "features": Xtr.shape[1],
                                    "iters": ITERS, **res}
        _flush(out)

    _flush(out)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
