"""Repro / bisect harness for the fused+EFB TPU worker fault (round 4).

Known-failing shape: allstate-like one-hot data, 4228 raw features (EFB
bundles to ~532 stored columns), 255 leaves, ~120k rows, 3 iterations.
Round 3's copy-back kernel ran this; round 4's dual-residency kernel
faults the TPU worker.

Usage: REPRO_ROWS=120000 REPRO_LEAVES=255 REPRO_ITERS=3 \
       LGBM_TPU_FORCE_FUSED_EFB=1 python scripts/repro_fused_efb.py
Prints REPRO_OK as the last line when training survives.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROWS = int(os.environ.get("REPRO_ROWS", 120_000))
FEATS = int(os.environ.get("REPRO_FEATS", 4228))
LEAVES = int(os.environ.get("REPRO_LEAVES", 255))
ITERS = int(os.environ.get("REPRO_ITERS", 3))

import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir",
                  os.environ.get("REPRO_CACHE", "/tmp/.jax_repro_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from bench import make_allstate_like  # noqa: E402
import lightgbm_tpu as lgb  # noqa: E402

params = {
    "objective": "binary",
    "num_leaves": LEAVES,
    "max_bin": 255,
    "learning_rate": 0.1,
    "min_data_in_leaf": 100,
    "verbosity": 1,
    "stop_check_freq": 10_000,
    "bin_construct_sample_cnt": 20_000,
}
for k in ("tpu_fused_block", "tpu_grower", "tpu_fused"):
    if os.environ.get(f"REPRO_{k.upper()}"):
        v = os.environ[f"REPRO_{k.upper()}"]
        params[k] = int(v) if v.lstrip("-").isdigit() else v

print(f"[repro] rows={ROWS} feats={FEATS} leaves={LEAVES} iters={ITERS} "
      f"params={params}", flush=True)
t0 = time.time()
X, y = make_allstate_like(ROWS, FEATS)
print(f"[repro] datagen {time.time() - t0:.1f}s", flush=True)
t0 = time.time()
ds = lgb.Dataset(X, label=y, params=params)
ds.construct()
print(f"[repro] construct {time.time() - t0:.1f}s "
      f"cols={ds._inner.binned.shape[1]}", flush=True)
bst = lgb.Booster(params, ds)
for i in range(ITERS):
    t0 = time.time()
    bst.update()
    bst._gbdt._flush_trees()
    print(f"[repro] iter {i} done {time.time() - t0:.1f}s", flush=True)
print("REPRO_OK", flush=True)
