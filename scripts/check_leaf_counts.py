"""Invariant check for the fused+EFB shape: the scan's per-leaf row counts
(recorded in the model as leaf_count) must equal an independent re-routing
of the training data through the saved tree.

If the split scan's n_left ever disagrees with the kernel's routing, the
partition writes drift — in dual-residency mode that drift becomes
out-of-bounds DMA (the open TPU fault); in copy-back mode it would show up
here as count mismatches.

Usage: REPRO_ROWS=120000 python scripts/check_leaf_counts.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROWS = int(os.environ.get("REPRO_ROWS", 120_000))
FEATS = int(os.environ.get("REPRO_FEATS", 4228))
LEAVES = int(os.environ.get("REPRO_LEAVES", 255))
ITERS = int(os.environ.get("REPRO_ITERS", 2))

import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir",
                  os.environ.get("REPRO_CACHE", "/tmp/.jax_repro_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from bench import make_allstate_like  # noqa: E402
import lightgbm_tpu as lgb  # noqa: E402

params = {
    "objective": "binary", "num_leaves": LEAVES, "max_bin": 255,
    "learning_rate": 0.1, "min_data_in_leaf": 100, "verbosity": -1,
    "stop_check_freq": 10_000, "bin_construct_sample_cnt": 20_000,
}
X, y = make_allstate_like(ROWS, FEATS)
ds = lgb.Dataset(X, label=y, params=params)
ds.construct()
print(f"[check] construct done, cols={ds._inner.binned.shape[1]}", flush=True)
bst = lgb.Booster(params, ds)
for i in range(ITERS):
    bst.update()
bst._gbdt._flush_trees()

leaves = bst.predict(X, pred_leaf=True)          # [N, T] raw-space routing
bad = 0
for t, m in enumerate(bst._gbdt.models):
    counts = np.bincount(leaves[:, t], minlength=m.num_leaves)
    model_counts = np.asarray(m.leaf_count[: m.num_leaves]).astype(np.int64)
    if not np.array_equal(counts[: m.num_leaves], model_counts):
        diff = counts[: m.num_leaves] - model_counts
        nz = np.nonzero(diff)[0]
        print(f"[check] tree {t}: MISMATCH at leaves {nz[:10]} "
              f"(delta {diff[nz][:10]}, total |delta| {np.abs(diff).sum()})",
              flush=True)
        bad += 1
print(f"[check] {'FAIL' if bad else 'OK'}: {bad}/{len(bst._gbdt.models)} "
      f"trees with count mismatches", flush=True)
