#!/usr/bin/env python3
"""Regenerate/verify the learner-mode HLO contracts from a CPU lowering.

    scripts/verify_contracts.py            # lower all modes, diff against
                                           #   analysis/contracts/*.json
                                           #   (exit 1 on drift or violation)
    scripts/verify_contracts.py --update   # rewrite the contract files

Update workflow: when a comm-protocol or dtype change is INTENDED, rerun
with ``--update``, review the JSON diff (it is the machine-checked form
of the README's comm/dtype/residency claims), and commit it with the
change. Tier-1 (tests/test_hlo_check.py) runs the no-update path, so a
silent comm-shape drift — a new collective, a budget blowout, a dropped
``preferred_element_type`` — fails the suite with an actionable finding.

Exec-delegates to ``scripts/tpulint hlo`` (the single place that sets the
CPU-backend env BEFORE jax imports); kept as its own script so CI and
humans have an obvious name for the contract-regeneration step.
"""
import os
import sys

if __name__ == "__main__":
    tpulint = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "tpulint")
    os.execv(sys.executable,
             [sys.executable, tpulint, "hlo"] + sys.argv[1:])
