"""Generate interop golden files from the REAL LightGBM library.

Builds deterministic synthetic datasets, trains the reference LightGBM
(built from /root/reference into .refsrc/lib_lightgbm.so — see
tests/golden/README.md) and records:

  * the reference's saved model text      -> tests/golden/<case>.model.txt
  * its predictions + the input data      -> tests/golden/<case>.npz
  * generation-time two-way checks        -> tests/golden/interop_report.json
      - "theirs_in_ours": reference model loaded by lightgbm_tpu, max |diff|
      - "ours_in_theirs": lightgbm_tpu model loaded by the reference lib,
        max |diff| (the direction that can only be verified when the native
        lib is present)

Run from the repo root:  python scripts/gen_interop_goldens.py
"""
import json
import os
import sys
import zlib

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, ".refpkg"))
sys.path.insert(0, ROOT)

import lightgbm as real_lgb          # noqa: E402  (reference build)
import lightgbm_tpu as tpu_lgb       # noqa: E402

GOLDEN = os.path.join(ROOT, "tests", "golden")
os.makedirs(GOLDEN, exist_ok=True)


def _binary_case(rng):
    n = 800
    X = rng.randn(n, 6)
    X[rng.rand(n, 6) < 0.1] = np.nan          # exercise NaN routing
    logits = np.nan_to_num(X[:, 0]) + 0.8 * np.nan_to_num(X[:, 1] * X[:, 2])
    y = (logits + 0.3 * rng.randn(n) > 0).astype(np.float64)
    return X, y, {"objective": "binary", "metric": "binary_logloss"}


def _regression_case(rng):
    n = 700
    X = rng.randn(n, 5)
    y = X[:, 0] * 2.0 + np.abs(X[:, 1]) - 1.5 * (X[:, 2] > 0) \
        + 0.2 * rng.randn(n)
    return X, y, {"objective": "regression", "metric": "l2"}


def _multiclass_case(rng):
    n = 900
    X = rng.randn(n, 5)
    y = (np.argmax(X[:, :3] + 0.4 * rng.randn(n, 3), axis=1)).astype(
        np.float64)
    return X, y, {"objective": "multiclass", "num_class": 3}


def _categorical_case(rng):
    n = 800
    cat = rng.randint(0, 10, n).astype(np.float64)
    high = np.isin(cat, [1, 4, 5, 8])
    y = np.where(high, 2.0, -2.0) + 0.4 * rng.randn(n)
    X = np.column_stack([cat, rng.randn(n)])
    return X, y, {"objective": "regression", "categorical_feature": [0],
                  "min_data_per_group": 10, "cat_smooth": 2.0}


def _ranking_case(rng):
    n, q = 1000, 20
    X = rng.randn(n, 5)
    w = rng.randn(5) * 0.6
    sc = X @ w + rng.randn(n)
    y = np.clip(np.digitize(sc, [-1.0, 0.3, 1.2, 2.2]), 0, 4).astype(
        np.float64)
    return X, y, {"objective": "lambdarank", "metric": "ndcg",
                  "group": np.full(n // q, q, np.int64),
                  "lambdarank_truncation_level": 15}


CASES = {
    "binary_nan": _binary_case,
    "regression": _regression_case,
    "multiclass": _multiclass_case,
    "categorical": _categorical_case,
    "ranking": _ranking_case,
}

BASE = {"verbosity": -1, "num_leaves": 15, "max_bin": 63,
        "min_data_in_leaf": 5, "learning_rate": 0.1, "deterministic": True,
        "force_row_wise": True}


def main():
    report = {}
    for name, make in CASES.items():
        # stable per-case seed: str hash() is salted per process
        rng = np.random.RandomState(
            zlib.crc32(name.encode()) % (2 ** 31))
        X, y, extra = make(rng)
        params = dict(BASE, **extra)
        cat = params.pop("categorical_feature", "auto")
        group = params.pop("group", None)

        # ---- reference model + predictions -> goldens
        ds = real_lgb.Dataset(X, label=y, categorical_feature=cat,
                              group=group, free_raw_data=False)
        ref = real_lgb.train(params, ds, 12)
        ref_pred = ref.predict(X)
        model_path = os.path.join(GOLDEN, f"{name}.model.txt")
        ref.save_model(model_path)
        extra_arrays = ({"group": group} if group is not None else {})
        np.savez_compressed(os.path.join(GOLDEN, f"{name}.npz"),
                            X=X.astype(np.float64), y=y,
                            pred=np.asarray(ref_pred, np.float64),
                            **extra_arrays)

        # ---- direction 1: reference model loaded by lightgbm_tpu
        ours = tpu_lgb.Booster(model_file=model_path)
        ours_pred = np.asarray(ours.predict(X), np.float64)
        d1 = float(np.max(np.abs(ours_pred - ref_pred)))

        # ---- direction 2: lightgbm_tpu model loaded by the reference lib
        tpu_ds = tpu_lgb.Dataset(X, label=y, categorical_feature=cat,
                                 group=group)
        tpu_bst = tpu_lgb.train(params, tpu_ds, 12)
        tpu_pred = np.asarray(tpu_bst.predict(X), np.float64)
        tpu_model = os.path.join(GOLDEN, f"{name}.tpu_model.txt")
        with open(tpu_model, "w") as f:
            f.write(tpu_bst.model_to_string())
        theirs = real_lgb.Booster(model_file=tpu_model)
        theirs_pred = np.asarray(theirs.predict(X), np.float64)
        d2 = float(np.max(np.abs(theirs_pred - tpu_pred)))

        # ---- same-data quality comparison (binning deliberately differs,
        # so this is a model-quality check, not bit parity)
        if params.get("num_class", 1) > 1:
            q_ref = float(np.mean(np.argmax(ref_pred, 1) == y))
            q_tpu = float(np.mean(np.argmax(tpu_pred, 1) == y))
        elif params["objective"] == "binary":
            q_ref = float(np.mean((ref_pred > 0.5) == y))
            q_tpu = float(np.mean((tpu_pred > 0.5) == y))
        elif params["objective"] == "lambdarank":
            # uniform groups by construction; derive the size from the
            # group array saved alongside the goldens
            def _ndcg5(p, qsz=int(group[0])):
                rel = y.reshape(-1, qsz)
                o = np.argsort(-p.reshape(-1, qsz), axis=1)
                g = np.take_along_axis(2.0 ** rel - 1, o, axis=1)[:, :5]
                dsc = 1.0 / np.log2(np.arange(2, 7))
                ig = np.sort(2.0 ** rel - 1, 1)[:, ::-1][:, :5]
                return float(np.mean((g * dsc).sum(1)
                                     / np.maximum((ig * dsc).sum(1),
                                                  1e-12)))
            q_ref = _ndcg5(np.asarray(ref_pred))
            q_tpu = _ndcg5(np.asarray(tpu_pred))
        else:
            q_ref = float(np.mean((ref_pred - y) ** 2))
            q_tpu = float(np.mean((tpu_pred - y) ** 2))

        report[name] = {
            "theirs_in_ours_maxdiff": d1,
            "ours_in_theirs_maxdiff": d2,
            "ref_quality": q_ref,
            "tpu_quality": q_tpu,
        }
        print(f"{name:12s} theirs_in_ours={d1:.3e} ours_in_theirs={d2:.3e} "
              f"q_ref={q_ref:.4f} q_tpu={q_tpu:.4f}")

    with open(os.path.join(GOLDEN, "interop_report.json"), "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
    print("goldens written to", GOLDEN)


if __name__ == "__main__":
    main()
