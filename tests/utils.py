"""Shared test fixtures/generators.

Mirrors the reference's tests/python_package_test/utils.py (memoized dataset
loaders, make_synthetic_regression, make_ranking) at a smaller scale so the
XLA-on-CPU test path stays fast.
"""
from __future__ import annotations

import functools

import numpy as np
from sklearn.datasets import make_blobs, make_classification, make_regression

# small defaults: CPU XLA histograms are the slow path; TPU is the target
FAST_PARAMS = {"max_bin": 31, "min_data_in_leaf": 5, "num_leaves": 15,
               "verbosity": -1}


@functools.lru_cache(maxsize=None)
def binary_data(n=600, f=10, seed=42):
    X, y = make_classification(
        n_samples=n, n_features=f, n_informative=max(2, f // 2),
        random_state=seed)
    return X, y.astype(np.float64)


@functools.lru_cache(maxsize=None)
def regression_data(n=600, f=10, seed=42):
    X, y = make_regression(n_samples=n, n_features=f, noise=5.0,
                           random_state=seed)
    return X, y


@functools.lru_cache(maxsize=None)
def multiclass_data(n=600, f=10, k=3, seed=42):
    X, y = make_blobs(n_samples=n, n_features=f, centers=k,
                      cluster_std=6.0, random_state=seed)
    return X, y.astype(np.float64)


def make_ranking(n_queries=40, docs_per_query=20, f=8, seed=42):
    """Relevance in {0,1,2}; returns X, y, group sizes
    (reference: utils.py make_ranking)."""
    rng = np.random.RandomState(seed)
    n = n_queries * docs_per_query
    X = rng.randn(n, f)
    w = rng.randn(f)
    scores = X @ w + 0.5 * rng.randn(n)
    y = np.zeros(n)
    for q in range(n_queries):
        s = scores[q * docs_per_query:(q + 1) * docs_per_query]
        r = np.argsort(np.argsort(s))
        y[q * docs_per_query:(q + 1) * docs_per_query] = np.where(
            r >= docs_per_query - 3, 2, np.where(r >= docs_per_query - 8, 1, 0))
    group = np.full(n_queries, docs_per_query)
    return X, y, group


def train_test_split_simple(X, y, test_frac=0.25, seed=0):
    rng = np.random.RandomState(seed)
    n = len(X)
    idx = rng.permutation(n)
    cut = int(n * (1 - test_frac))
    tr, te = idx[:cut], idx[cut:]
    return X[tr], y[tr], X[te], y[te]
