"""Quantized-gradient integer histogram pipeline (ops/histogram.py int8
path, ops/grower_compact.py quant_hist, boosting/gbdt._discretize_gradients).

Covers the PR's acceptance contract on CPU:
  * int-path histograms are EXACT int32 code sums and dequantize to within
    the quantization-error bound of the f32 histograms;
  * end-to-end synthetic-higgs quality: quantized training with
    quant_train_renew_leaf stays within 1e-3 AUC of the f32 path;
  * the post-warmup steady-state guard (0 recompiles, 0 d2h) holds with
    the quantized path enabled;
  * the data-parallel reduce-scatter histogram reduction produces
    bit-identical trees to the all-reduce path, with and without
    quantization.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.analysis import guards
from lightgbm_tpu.boosting.gbdt import _discretize_gradients
from lightgbm_tpu.ops.histogram import (_xla_histogram, dequantize_hist,
                                        histogram_block)


def _higgs_like(n, f, seed=7):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    w1 = rng.randn(f) / np.sqrt(f)
    w2 = rng.randn(f) / np.sqrt(f)
    logits = X @ w1 + 0.7 * np.abs(X @ w2) - 0.4 + 0.5 * rng.randn(n)
    y = (logits > 0).astype(np.float64)
    return X, y


# ------------------------------------------------- histogram-level parity
class TestIntHistogram:
    def test_int_hist_exact_vs_f32_codes(self):
        """The int8 contraction sums the SAME codes as the f32 einsum —
        bit-exact int32, on both the XLA and Pallas-interpret engines."""
        rng = np.random.RandomState(0)
        n, f, b = 6000, 7, 64
        binned = jnp.asarray(rng.randint(0, b, (n, f)).astype(np.uint8))
        qg = rng.randint(-8, 9, n).astype(np.int8)
        qh = rng.randint(0, 17, n).astype(np.int8)
        inbag = (rng.rand(n) < 0.8).astype(np.int8)
        ch = jnp.asarray(np.stack(
            [qg * inbag, qh * inbag, inbag, np.ones(n)], axis=1)
            .astype(np.int8))
        h_int = _xla_histogram(binned, ch, b)
        assert h_int.dtype == jnp.int32
        h_f32 = _xla_histogram(binned, ch.astype(jnp.float32), b)
        np.testing.assert_array_equal(np.asarray(h_int),
                                      np.asarray(h_f32).astype(np.int64))
        from lightgbm_tpu.ops.pallas_histogram import pallas_histogram
        h_pl = pallas_histogram(binned, ch, b, mode="int8", interpret=True)
        np.testing.assert_array_equal(np.asarray(h_pl), np.asarray(h_int))

    def test_dequantized_hist_within_quant_error_bound(self):
        """|dequantized int sums - true f32 sums| <= per-bin row count *
        scale per channel (each row's discretization error is < 1 code)."""
        rng = np.random.RandomState(3)
        n, f, b = 8000, 5, 32
        binned = jnp.asarray(rng.randint(0, b, (n, f)).astype(np.uint8))
        grad = jnp.asarray(rng.randn(n).astype(np.float32))
        hess = jnp.asarray((rng.rand(n) * 0.25).astype(np.float32))
        qg, qh, g_s, h_s = _discretize_gradients(
            grad[None], hess[None], jax.random.PRNGKey(0), 16, True, False)
        ones = jnp.ones((n,), jnp.int8)
        ch_q = jnp.stack([qg[0].astype(jnp.int8), qh[0].astype(jnp.int8),
                          ones, ones], axis=1)
        hist_q = histogram_block(binned, ch_q, b, impl="xla")
        assert hist_q.dtype == jnp.int32
        dq = np.asarray(dequantize_hist(hist_q, g_s, h_s))
        onesf = jnp.ones((n,), jnp.float32)
        hist_f = np.asarray(histogram_block(
            binned, jnp.stack([grad, hess, onesf, onesf], axis=1), b,
            impl="xla"))
        counts = hist_f[:, :, 3]
        g_err = np.abs(dq[:, :, 0] - hist_f[:, :, 0])
        h_err = np.abs(dq[:, :, 1] - hist_f[:, :, 1])
        assert (g_err <= counts * float(g_s) + 1e-4).all()
        assert (h_err <= counts * float(h_s) + 1e-4).all()
        # count channels are exact
        np.testing.assert_allclose(dq[:, :, 2:], hist_f[:, :, 2:])

    def test_quantized_histogram_requires_preferred_int32(self):
        """The einsum without preferred_element_type would wrap at +-127;
        prove the pipeline's sums exceed the int8 range (i.e. the pin is
        load-bearing, not decorative)."""
        rng = np.random.RandomState(1)
        n, b = 4000, 4
        binned = jnp.zeros((n, 1), jnp.uint8)      # all rows -> one bin
        ch = jnp.asarray(np.stack([np.full(n, 3), np.full(n, 2),
                                   np.ones(n), np.ones(n)], axis=1)
                         .astype(np.int8))
        h = _xla_histogram(binned, ch, b)
        assert int(h[0, 0, 0]) == 3 * n            # >> 127
        assert int(h[0, 0, 1]) == 2 * n


# ------------------------------------------------------- end-to-end AUC
class TestQuantizedTraining:
    def test_synthetic_higgs_auc_within_1e3(self):
        from sklearn.metrics import roc_auc_score
        X, y = _higgs_like(9000, 10)
        Xt, yt, Xv, yv = X[:7000], y[:7000], X[7000:], y[7000:]
        base = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
                "verbosity": -1, "tpu_grower": "compact",
                "min_data_in_leaf": 20, "learning_rate": 0.1}
        b_f = lgb.train(dict(base), lgb.Dataset(Xt, label=yt, params=base),
                        40)
        qp = dict(base, use_quantized_grad=True, num_grad_quant_bins=16,
                  quant_train_renew_leaf=True)
        b_q = lgb.train(dict(qp), lgb.Dataset(Xt, label=yt, params=qp), 40)
        auc_f = roc_auc_score(yv, b_f.predict(Xv))
        auc_q = roc_auc_score(yv, b_q.predict(Xv))
        assert abs(auc_f - auc_q) <= 1e-3, (auc_f, auc_q)
        # sanity: the quantized model actually learned
        assert auc_q > 0.8

    def test_quant_compact_matches_masked_shim_statistics(self):
        """The compact int path and the masked dequantize-shim implement
        the same discretization; with deterministic rounding and a fixed
        bag their models agree closely."""
        from sklearn.metrics import roc_auc_score
        X, y = _higgs_like(4000, 8, seed=11)
        base = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
                "verbosity": -1, "min_data_in_leaf": 20,
                "use_quantized_grad": True, "num_grad_quant_bins": 32,
                "stochastic_rounding": False}
        b_c = lgb.train(dict(base, tpu_grower="compact"),
                        lgb.Dataset(X, label=y, params=base), 10)
        b_m = lgb.train(dict(base, tpu_grower="masked"),
                        lgb.Dataset(X, label=y, params=base), 10)
        a_c = roc_auc_score(y, b_c.predict(X))
        a_m = roc_auc_score(y, b_m.predict(X))
        assert abs(a_c - a_m) < 5e-3, (a_c, a_m)


# ---------------------------------------------------- steady-state guard
class TestQuantizedSteadyState:
    @pytest.fixture(scope="class")
    def warm_quant_booster(self):
        X, y = _higgs_like(1500, 10)
        params = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
                  "learning_rate": 0.1, "min_data_in_leaf": 20,
                  "verbosity": -1, "tpu_grower": "compact",
                  "use_quantized_grad": True, "num_grad_quant_bins": 8,
                  "quant_train_renew_leaf": True,
                  "stop_check_freq": 10_000}
        ds = lgb.Dataset(X, label=y, params=params)
        bst = lgb.Booster(params, ds)
        for _ in range(2):
            bst.update()
        return bst

    def test_quantized_boosting_no_recompiles_no_transfers(
            self, warm_quant_booster):
        """The acceptance criterion: 5 post-warmup iterations of the
        QUANTIZED compact step — zero lowerings, zero backend compiles,
        zero device->host transfers (np.asarray funnel armed too)."""
        bst = warm_quant_booster
        with guards.steady_state_guard("5 quantized iterations") as cc:
            for _ in range(5):
                bst.update()
        assert cc.lowerings == 0
        assert cc.backend_compiles == 0
        bst._gbdt._flush_trees()
        assert bst._gbdt.num_total_trees >= 7


# ------------------------------------------- data-parallel reduce-scatter
@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs >= 4 devices")
class TestHistScatter:
    def _train(self, X, y, extra, n_iter=6):
        params = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
                  "verbosity": -1, "tree_learner": "data",
                  "tpu_grower": "compact", "min_data_in_leaf": 5}
        params.update(extra)
        return lgb.train(dict(params),
                         lgb.Dataset(X, label=y, params=params), n_iter)

    def test_scatter_matches_allreduce_trees(self):
        """psum_scatter over the feature axis + best-split all-gather
        produces the same trees as the full-histogram all-reduce."""
        X, y = _higgs_like(2048, 10, seed=3)
        b_off = self._train(X, y, {"tpu_hist_scatter": "off"})
        b_on = self._train(X, y, {"tpu_hist_scatter": "on"})
        np.testing.assert_allclose(b_on.predict(X), b_off.predict(X),
                                   atol=1e-6)

    def test_scatter_quantized_trains(self):
        from sklearn.metrics import roc_auc_score
        X, y = _higgs_like(2048, 10, seed=5)
        bst = self._train(X, y, {"use_quantized_grad": True,
                                 "num_grad_quant_bins": 16})
        assert bst._gbdt.grower_params is not None
        assert roc_auc_score(y, bst.predict(X)) > 0.8

    def test_scatter_incompatible_configs_fall_back(self):
        """EFB bundles keep the all-reduce (a shard's slice cannot serve
        a bundled feature whose column lives elsewhere); the config knob
        warns instead of crashing."""
        rng = np.random.RandomState(2)
        n, G, card = 2048, 10, 8
        cats = rng.randint(0, card, size=(n, G))
        X = np.zeros((n, G * card), np.float32)
        for g in range(G):
            X[np.arange(n), g * card + cats[:, g]] = 1.0
        y = (X @ (rng.randn(G * card) * 0.5) > 0).astype(np.float64)
        bst = self._train(X, y, {"tpu_hist_scatter": "on"}, n_iter=3)
        assert np.isfinite(bst.predict(X)).all()
