"""Interop against the REAL LightGBM (golden files).

The reference's saved models must load here and predict identically, and our
saved models must load in the reference library (verified at golden
generation time and re-verified live when the built lib is present).
Reference format: src/boosting/gbdt_model_text.cpp, src/io/tree.cpp.
"""
import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb

GOLDEN = os.path.join(os.path.dirname(__file__), "golden")
CASES = ["binary_nan", "regression", "multiclass", "categorical",
         "ranking"]


def _load(name):
    data = np.load(os.path.join(GOLDEN, f"{name}.npz"))
    with open(os.path.join(GOLDEN, f"{name}.model.txt")) as f:
        model_text = f.read()
    return data["X"], data["y"], data["pred"], model_text


@pytest.mark.parametrize("name", CASES)
def test_reference_model_loads_and_predicts_identically(name):
    X, _, ref_pred, model_text = _load(name)
    bst = lgb.Booster(model_str=model_text)
    pred = np.asarray(bst.predict(X), np.float64)
    np.testing.assert_allclose(pred, ref_pred, rtol=1e-5, atol=2e-6)


@pytest.mark.parametrize("name", CASES)
def test_generation_time_two_way_interchange(name):
    """The recorded two-way check: our models loaded by the real lib (and
    theirs by us) agreed to float32 precision when the goldens were made."""
    with open(os.path.join(GOLDEN, "interop_report.json")) as f:
        report = json.load(f)
    entry = report[name]
    assert entry["theirs_in_ours_maxdiff"] < 1e-5
    assert entry["ours_in_theirs_maxdiff"] < 1e-5
    # same-data quality parity (binning differs by design; quality must not)
    if name == "regression":
        assert entry["tpu_quality"] < entry["ref_quality"] * 1.1
    elif name == "categorical":
        assert entry["tpu_quality"] < entry["ref_quality"] * 1.2
    else:
        assert entry["tpu_quality"] > entry["ref_quality"] - 0.03


@pytest.mark.parametrize("name", CASES)
def test_our_model_text_reparses_reference_style(name):
    """Round-trip our own saved model for the same case (golden provenance
    file) — guards against format drift in either direction."""
    path = os.path.join(GOLDEN, f"{name}.tpu_model.txt")
    with open(path) as f:
        text = f.read()
    X, _, _, _ = _load(name)
    bst = lgb.Booster(model_str=text)
    pred = bst.predict(X)
    assert np.isfinite(pred).all()


_REF_LIB = os.path.join(os.path.dirname(__file__), "..", ".refpkg")


@pytest.mark.skipif(not os.path.isdir(_REF_LIB),
                    reason="reference LightGBM build not present")
@pytest.mark.parametrize("name", ["binary_nan", "regression"])
def test_live_ours_in_reference(name):
    """When the reference build exists, verify the reverse direction live."""
    import sys
    sys.path.insert(0, os.path.abspath(_REF_LIB))
    import lightgbm as real_lgb
    X, y, _, _ = _load(name)
    params = {"objective": "binary" if name == "binary_nan" else "regression",
              "verbosity": -1, "num_leaves": 15, "max_bin": 63,
              "min_data_in_leaf": 5}
    ours = lgb.train(params, lgb.Dataset(X, label=y), 8)
    text = ours.model_to_string()
    theirs = real_lgb.Booster(model_str=text)
    np.testing.assert_allclose(
        np.asarray(theirs.predict(X), np.float64),
        np.asarray(ours.predict(X), np.float64), rtol=1e-5, atol=2e-6)


@pytest.mark.skipif(not os.path.isdir(_REF_LIB),
                    reason="reference LightGBM build not present")
@pytest.mark.parametrize("name", ["binary_nan", "regression", "multiclass"])
def test_live_pred_contrib_parity(name):
    """Model-only TreeSHAP parity: the same reference-trained model text,
    loaded dataset-free in both libraries, must attribute identically
    (reference: Tree::PredictContrib, include/LightGBM/tree.h:668)."""
    import sys
    sys.path.insert(0, os.path.abspath(_REF_LIB))
    import lightgbm as real_lgb
    X, _, _, model_text = _load(name)
    theirs = real_lgb.Booster(model_str=model_text)
    ours = lgb.Booster(model_str=model_text)
    ref = np.asarray(theirs.predict(X[:50], pred_contrib=True), np.float64)
    got = np.asarray(ours.predict(X[:50], pred_contrib=True), np.float64)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-6)
