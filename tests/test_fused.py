"""Fused per-split Mosaic kernel (ops/fused_split.py) vs the XLA reference.

Runs the kernel in Pallas interpret mode on the CPU test backend; the
partition must match ops/compact.py partition_segment byte-for-byte, the
histogram count channels must be exact, and grad/hess must sit within the
hi/lo-bf16 split tolerance (same contract as ops/pallas_histogram.py).

Reference analogue: the CUDA per-split kernels
(src/treelearner/cuda/cuda_data_partition.cu:288,679,907 and
cuda_histogram_constructor.cu:17-68) are validated by the reference's
test_engine.py end-to-end runs; here we check the fused kernel directly
against the independently-tested XLA implementation.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops.compact import (RowLayout, pack_rows,
                                      partition_segment, segment_histogram)
from lightgbm_tpu.ops.fused_split import fused_split

i32 = jnp.int32


def _make_work(rng, n, f, b, extra=1):
    layout = RowLayout(num_features=f, num_extra=extra)
    binned = rng.randint(0, b, size=(n, f)).astype(np.uint8)
    g = rng.randn(n).astype(np.float32)
    h = np.abs(rng.randn(n)).astype(np.float32)
    cnt = (rng.rand(n) > 0.25).astype(np.float32)
    extras = rng.randn(extra, n).astype(np.float32)
    work = jax.jit(pack_rows, static_argnames=("layout", "pad_rows"))(
        jnp.asarray(binned), jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(cnt), jnp.asarray(extras), layout, 256)
    return layout, np.asarray(work)


def _run_fused(work0, layout, b, mode, start, count, n_left, feat, bin_,
               default_left=0, nan_bin=0, is_cat=0, bits=None, bs=128,
               dual=True):
    bits = (jnp.zeros((8,), jnp.uint32) if bits is None
            else jnp.asarray(bits, jnp.uint32))
    return fused_split(
        jnp.asarray(work0), jnp.zeros((work0.shape), jnp.uint8),
        jnp.asarray(mode, i32), jnp.asarray(start, i32),
        jnp.asarray(count, i32), jnp.asarray(n_left, i32),
        jnp.asarray(feat, i32), jnp.asarray(bin_, i32),
        jnp.asarray(default_left, i32), jnp.asarray(nan_bin, i32),
        jnp.asarray(is_cat, i32), bits, layout, b, bs, 8, interpret=True,
        dual=dual)


def _merged(wf, sf, start, count, n_left, dual=True):
    """Dual residency: the right child lives in the scratch array at its
    final offsets; merge for comparison against the single-array reference.
    The copy-back variant (dual=False) already holds everything in work."""
    out = np.asarray(wf).copy()
    if dual:
        rs, re = start + n_left, start + count
        out[rs:re] = np.asarray(sf)[rs:re]
    return out


def _run_ref(work0, b, layout, start, count, n_left, feat, bin_,
             default_left=False, nan_bin=0, is_cat=False, bits=None):
    bits = (jnp.zeros((8,), jnp.uint32) if bits is None
            else jnp.asarray(bits, jnp.uint32))
    wr, _ = partition_segment(
        jnp.asarray(work0), jnp.zeros(work0.shape, jnp.uint8),
        jnp.asarray(start, i32), jnp.asarray(count, i32),
        jnp.asarray(n_left, i32), jnp.asarray(feat, i32),
        jnp.asarray(bin_, i32), jnp.asarray(default_left),
        jnp.asarray(nan_bin, i32), jnp.asarray(is_cat), bits, 128)
    n_right = count - n_left
    s_small = start if n_left <= n_right else start + n_left
    m_small = min(n_left, n_right)
    href = segment_histogram(wr, jnp.asarray(s_small, i32),
                             jnp.asarray(m_small, i32), layout, b, 128, "xla")
    return np.asarray(wr), np.asarray(href)


class TestFusedSplit:
    @pytest.mark.parametrize("dual", [True, False])
    @pytest.mark.parametrize("start,count", [(0, 3000), (37, 2219), (96, 128),
                                             (500, 1), (200, 0)])
    def test_partition_and_hist_parity(self, rng, start, count, dual):
        n, f, b = 3000, 5, 256
        layout, work0 = _make_work(rng, n, f, b)
        feat, bin_ = 2, 100
        sub = work0[start:start + count, feat]
        n_left = int((sub <= bin_).sum())
        wf, sf, hf = _run_fused(work0, layout, b, 0, start, count, n_left,
                                feat, bin_, dual=dual)
        wr, href = _run_ref(work0, b, layout, start, count, n_left, feat,
                            bin_)
        wm = _merged(wf, sf, start, count, n_left, dual)
        np.testing.assert_array_equal(wm[:n], wr[:n])
        hf = np.asarray(hf)
        np.testing.assert_array_equal(hf[:, :, 2:], href[:, :, 2:])
        np.testing.assert_allclose(hf[:, :, :2], href[:, :, :2], atol=2e-2)

    def test_nan_default_left(self, rng):
        n, f, b = 2000, 4, 64
        layout, work0 = _make_work(rng, n, f, b)
        feat, bin_, nan_bin = 1, 20, 63
        col = work0[:, feat]
        gl = (col <= bin_) | (col == nan_bin)
        n_left = int(gl.sum())
        wf, sf, _ = _run_fused(work0, layout, b, 0, 0, n, n_left, feat, bin_,
                               default_left=1, nan_bin=nan_bin)
        wr, _ = _run_ref(work0, b, layout, 0, n, n_left, feat, bin_,
                         default_left=True, nan_bin=nan_bin)
        np.testing.assert_array_equal(_merged(wf, sf, 0, n, n_left)[:n],
                                      wr[:n])

    @pytest.mark.parametrize("dual", [True, False])
    def test_categorical_bitset(self, rng, dual):
        n, f, b = 1500, 4, 256
        layout, work0 = _make_work(rng, n, f, b)
        feat = 3
        bits = np.zeros(8, np.uint32)
        for cat in (3, 17, 100, 255):
            bits[cat // 32] |= np.uint32(1) << (cat % 32)
        col = work0[:, feat]
        gl = (bits[col // 32] >> (col % 32)) & 1
        n_left = int(gl.sum())
        wf, sf, _ = _run_fused(work0, layout, b, 0, 0, n, n_left, feat, 0,
                               is_cat=1, bits=bits, dual=dual)
        wr, _ = _run_ref(work0, b, layout, 0, n, n_left, feat, 0,
                         is_cat=True, bits=bits)
        np.testing.assert_array_equal(_merged(wf, sf, 0, n, n_left, dual)[:n],
                                      wr[:n])

    def test_mode1_root_histogram(self, rng):
        n, f, b = 2500, 5, 256
        layout, work0 = _make_work(rng, n, f, b)
        start, count = 41, 2300
        _, _, hf = _run_fused(work0, layout, b, 1, start, count, 0, 0, 0)
        href = segment_histogram(
            jnp.asarray(work0), jnp.asarray(start, i32),
            jnp.asarray(count, i32), layout, b, 128, "xla")
        hf, href = np.asarray(hf), np.asarray(href)
        np.testing.assert_array_equal(hf[:, :, 2:], href[:, :, 2:])
        np.testing.assert_allclose(hf[:, :, :2], href[:, :, :2], atol=2e-2)

    @pytest.mark.parametrize("dual", [True, False])
    def test_untouched_outside_segment(self, rng, dual):
        n, f, b = 2000, 4, 128
        layout, work0 = _make_work(rng, n, f, b)
        start, count = 600, 700
        sub = work0[start:start + count, 0]
        n_left = int((sub <= 40).sum())
        wf, sf, _ = _run_fused(work0, layout, b, 0, start, count, n_left,
                               0, 40, dual=dual)
        wf = np.asarray(wf)
        np.testing.assert_array_equal(wf[:start], work0[:start])
        np.testing.assert_array_equal(wf[start + count:n],
                                      work0[start + count:n])
        # the left child stays in place in the parent's array
        np.testing.assert_array_equal(wf[start:start + n_left],
                                      _run_ref(work0, b, layout, start,
                                               count, n_left, 0, 40)[0]
                                      [start:start + n_left])
