"""Regression tests for the ADVICE r5 hazard fixes (the tpulint seed
cases) + the bench backend-init retry."""
import importlib.util
import os
import re

import jax.numpy as jnp
import numpy as np
import pytest

from lightgbm_tpu.boosting.gbdt import _validated_fused_block_env
from lightgbm_tpu.ops.compact import RowLayout
from lightgbm_tpu.ops.fused_split import fused_split
from lightgbm_tpu.parallel.comm_accounting import collective_bytes

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------- ADVICE #1: comm accounting
HLO = """\
ENTRY %main {
  %p = f32[16]{0} parameter(0)
  %ag = (f32[16]{0}, f32[128]{0}) all-gather-start(f32[16]{0} %p)
  %agd = f32[128]{0} all-gather-done((f32[16]{0}, f32[128]{0}) %ag)
  %ar = (f32[32]{0}, f32[32]{0}) all-reduce-start(f32[32]{0} %p2)
  %ard = f32[32]{0} all-reduce-done((f32[32]{0}, f32[32]{0}) %ar)
  %rs = f32[8]{0} reduce-scatter(f32[64]{0} %p3)
}
"""


def test_all_gather_start_counts_result_shape():
    """8-device all-gather: result is 8x the operand; bytes must reflect
    the gathered (result) payload, not the pre-transfer operand."""
    out = collective_bytes(HLO)
    assert out["all-gather-start"] == 128 * 4        # NOT 16 * 4
    assert out["all-reduce-start"] == 32 * 4         # operand == result
    assert out["reduce-scatter"] == 8 * 4
    assert out["count"] == 3                         # -done ops not counted
    assert out["total"] == 128 * 4 + 32 * 4 + 8 * 4


def test_collective_permute_start_counts_result_shape():
    hlo = ("%cp = (f32[64]{0}, f32[64]{0}) "
           "collective-permute-start(f32[64]{0} %x)")
    out = collective_bytes(hlo)
    assert out["collective-permute-start"] == 64 * 4


def test_reduce_scatter_start_counts_result_shape():
    """The psum_scatter path gone async (R005 extension seed): the
    reduce-scatter result is operand/num_devices — counting the operand
    would over-report 8x, and missing the kind entirely (the pre-fix
    inventory) reports 0."""
    hlo = ("%rs = (f32[64,8]{1,0}, f32[8,8]{1,0}) "
           "reduce-scatter-start(f32[64,8]{1,0} %x)\n"
           "%rsd = f32[8,8]{1,0} reduce-scatter-done("
           "(f32[64,8]{1,0}, f32[8,8]{1,0}) %rs)\n"
           "%aa = (f32[16]{0}, f32[16]{0}) all-to-all-start(f32[16]{0} %y)")
    out = collective_bytes(hlo)
    assert out["reduce-scatter-start"] == 8 * 8 * 4   # result, not operand
    assert out["all-to-all-start"] == 16 * 4
    assert out["count"] == 2                          # -done carries nothing


# ------------------------------------------- ADVICE #2: fused pad contract
def test_fused_split_raises_on_short_pad():
    layout = RowLayout(num_features=10, num_extra=2)
    C = layout.num_cols
    work = jnp.zeros((96, C), jnp.uint8)
    scratch = jnp.zeros((96, C), jnp.uint8)
    z = jnp.asarray(0, jnp.int32)
    with pytest.raises(ValueError, match="pad contract"):
        fused_split(work, scratch, jnp.asarray(1, jnp.int32), z,
                    jnp.asarray(64, jnp.int32), z, z, z, z, z, z,
                    jnp.zeros((8,), jnp.uint32), layout, 64,
                    block_size=64, num_rows=80)       # pad 16 < 64


# ------------------------------------------- ADVICE #3: env override guard
def test_env_override_rounded_to_32_multiple():
    assert _validated_fused_block_env("100", 128, 384) == 96
    assert _validated_fused_block_env("5", 128, 384) == 32
    assert _validated_fused_block_env("256", 128, 384) == 256


def test_env_override_clamped_to_vmem_cap():
    """An oversize override must not recreate the VMEM blowup the scoped
    guard prevents (pre-fix: accepted raw)."""
    assert _validated_fused_block_env("8192", 128, 384) == 384
    assert _validated_fused_block_env("512", 2048, 64) == 64


# ------------------------------------------- ADVICE #4: docstring accuracy
def test_hist_matmuls_docstring_matches_implementation():
    src = open(os.path.join(
        REPO, "lightgbm_tpu", "ops", "fused_split.py")).read()
    doc = re.search(r"def hist_matmuls.*?\"\"\"(.*?)\"\"\"", src,
                    re.DOTALL).group(1)
    assert "constant-index lane gather" not in doc
    assert "per-feature compare" in doc


# --------------------------------------------- bench backend-init retry
def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_for_test", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_retries_transient_backend_errors(monkeypatch):
    bench = _load_bench()
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)

    class FlakyJax:
        calls = 0

        def devices(self):
            FlakyJax.calls += 1
            if FlakyJax.calls < 3:
                raise RuntimeError("Unable to initialize backend 'tpu': "
                                   "UNAVAILABLE: connection reset")
            return ["tpu:0"]

    assert bench._init_backend_with_retry(FlakyJax()) == "tpu:0"
    assert FlakyJax.calls == 3


def test_bench_reraises_non_transient_errors(monkeypatch):
    bench = _load_bench()
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)

    class BrokenJax:
        calls = 0

        def devices(self):
            BrokenJax.calls += 1
            raise RuntimeError("no module named libtpu")

    with pytest.raises(RuntimeError, match="libtpu"):
        bench._init_backend_with_retry(BrokenJax())
    assert BrokenJax.calls == 1               # no pointless retries


def test_bench_gives_up_after_transient_attempts(monkeypatch):
    bench = _load_bench()
    sleeps = []
    monkeypatch.setattr(bench.time, "sleep", sleeps.append)

    class DownJax:
        calls = 0

        def devices(self):
            DownJax.calls += 1
            raise RuntimeError("Unable to initialize backend 'tpu'")

    with pytest.raises(RuntimeError, match="Unable to initialize"):
        bench._init_backend_with_retry(DownJax())
    assert DownJax.calls == 5                 # hardened round-6 default
    assert sleeps == [5.0, 10.0, 20.0, 40.0]  # exponential backoff


def test_bench_retries_enumeration_failures(monkeypatch):
    """The r05 gap: device ENUMERATION died on a gRPC connect error the
    init retry never matched, and an empty device list slipped through —
    both now retry through the same loop."""
    bench = _load_bench()
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)

    class EnumFlaky:
        calls = 0

        def devices(self):
            EnumFlaky.calls += 1
            if EnumFlaky.calls == 1:
                raise RuntimeError("failed to connect to all addresses")
            if EnumFlaky.calls == 2:
                return []                     # worker mid-restart
            return ["tpu:0"]

    assert bench._init_backend_with_retry(EnumFlaky()) == "tpu:0"
    assert EnumFlaky.calls == 3


def test_bench_failure_stub_recorded(monkeypatch, tmp_path):
    """An unrecoverable failure emits the structured stub row (value null
    + error inline) AND records it in BENCH_SHAPES.json, so the BENCH_r0x
    row is never silently absent."""
    import json as _json
    bench = _load_bench()
    rec = tmp_path / "BENCH_SHAPES.json"
    monkeypatch.setattr(bench.os.path, "dirname", lambda p: str(tmp_path))
    out = []
    monkeypatch.setattr("builtins.print", out.append)
    bench._emit_failure_stub("train", RuntimeError("backend never up"))
    row = _json.loads(out[-1])
    assert row["value"] is None
    assert "backend never up" in row["error"]
    recorded = _json.loads(rec.read_text())["last_failure"]
    assert recorded["stage"] == "train"
    assert recorded["error_type"] == "RuntimeError"
