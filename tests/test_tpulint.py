"""tpulint: tier-1 wiring + per-rule fixture tests + allowlist workflow.

The whole-package test IS the tier-1 gate: any non-allowlisted finding in
lightgbm_tpu/ fails the suite. The fixture snippets encode each rule's
seed case (the pre-fix code from ADVICE r5) so a regression of the
analyzer — or of the fixed code — fails loudly.
"""
import os
import textwrap

import lightgbm_tpu
from lightgbm_tpu.analysis.tpulint import (DEFAULT_ALLOWLIST, apply_allowlist,
                                           check_allowlist_staleness,
                                           lint_paths, load_allowlist, main)

PKG_DIR = os.path.dirname(lightgbm_tpu.__file__)


def lint_snippet(tmp_path, source, name="snippet.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    findings, errors = lint_paths([str(p)])
    assert not errors, errors
    return findings


def codes(findings):
    return sorted(f.rule for f in findings)


# ---------------------------------------------------------------- tier-1
def test_package_is_clean():
    """The shipped tree has zero non-allowlisted findings, and every
    allowlist entry carries a justification and is actually used."""
    findings, errors = lint_paths([PKG_DIR])
    assert not errors, errors
    entries, allow_errors = load_allowlist(DEFAULT_ALLOWLIST)
    assert not allow_errors, allow_errors
    remaining = apply_allowlist(findings, entries)
    assert not remaining, "\n".join(f.render() for f in remaining)
    unused = [e.render() for e in entries if not e.used]
    assert not unused, f"unused allowlist entries: {unused}"


def test_cli_exit_zero_on_package():
    assert main([PKG_DIR]) == 0


# ---------------------------------------------------------------- R001
def test_r001_host_sync_flagged(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            v = float(x)
            a = np.asarray(x)
            jax.device_get(x)
            i = x.sum().item()
            return v, a, i
    """)
    assert codes(findings).count("R001") >= 4


def test_r001_host_constants_not_flagged(tmp_path):
    """float() on trace-time host config (closures, module constants) is
    fine — only traced values sync."""
    findings = lint_snippet(tmp_path, """
        import jax

        ALPHA = "0.5"

        def build(cfg):
            @jax.jit
            def step(x):
                return x * float(ALPHA) + float(cfg.beta)
            return step
    """)
    assert not findings


def test_r001_host_code_not_flagged(tmp_path):
    """Un-jitted host code may sync freely (treeshap-style host loops)."""
    findings = lint_snippet(tmp_path, """
        import numpy as np

        def host_summary(arr):
            return float(np.asarray(arr).sum())
    """)
    assert not findings


def test_r001_snapshot_io_in_jit_flagged(tmp_path):
    """Seed: checkpoint/snapshot file I/O (open, pickle.dump, fsync)
    reachable from jit-traced code is a host-sync finding."""
    findings = lint_snippet(tmp_path, """
        import os
        import pickle

        import jax

        @jax.jit
        def step_with_snapshot(x):
            with open("/tmp/snap.ckpt", "wb") as fh:
                pickle.dump(x, fh)
                os.fsync(fh.fileno())
            return x * 2
    """)
    assert codes(findings).count("R001") >= 3


def test_r001_snapshot_io_reached_from_jit_flagged(tmp_path):
    """Same hazard one call away: a snapshot helper referenced from a
    jitted step is jit-reachable and its file I/O is flagged."""
    findings = lint_snippet(tmp_path, """
        import pickle

        import jax

        def save_state(path, state):
            with open(path, "wb") as fh:
                pickle.dump(state, fh)

        @jax.jit
        def step(x):
            save_state("/tmp/s.ckpt", x)
            return x
    """)
    assert "R001" in codes(findings)


def test_r001_snapshot_writer_pinned_even_off_jit(tmp_path):
    """A pickle-and-fsync writer is a snapshot-writer site even in host
    code: every such function must be a reviewed, deliberate tick (the
    shipped io/checkpoint.py::write_snapshot carries the allowlist
    anchor)."""
    findings = lint_snippet(tmp_path, """
        import os
        import pickle

        def write_state(path, state):
            blob = pickle.dumps(state)
            with open(path, "wb") as fh:
                fh.write(blob)
                fh.flush()
                os.fsync(fh.fileno())
    """)
    assert "R001" in codes(findings)
    assert "snapshot-writer site" in findings[0].message


def test_r001_snapshot_reader_not_flagged(tmp_path):
    """Reading a snapshot on the host is fine: no pickle.dump, no jit."""
    findings = lint_snippet(tmp_path, """
        import pickle

        def read_state(path):
            with open(path, "rb") as fh:
                return pickle.loads(fh.read())
    """)
    assert not findings


# ---------------------------------------------------------------- R002
def test_r002_jit_in_loop(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        def build_all(fns):
            out = []
            for f in fns:
                out.append(jax.jit(f))
            return out
    """)
    assert "R002" in codes(findings)


def test_r002_unhashable_static_default(tmp_path):
    findings = lint_snippet(tmp_path, """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("opts",))
        def run(x, opts=[]):
            return x
    """)
    assert "R002" in codes(findings)


def test_r002_tracer_branch(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        @jax.jit
        def step(x, flag):
            if flag:
                return x + 1
            return x
    """)
    assert "R002" in codes(findings)


def test_r002_unbucketed_predict_entry(tmp_path):
    """Sub-check (d) seed: a serving entry point feeding the raw request
    into a jitted callable keys the compiled program on the request
    shape — every distinct batch size recompiles (the 26-97s serving
    stalls the bucketed engine removed)."""
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def _scores(x):
            return x * 2

        def predict(data):
            arr = jnp.asarray(data)
            return _scores(arr)
    """)
    assert "R002" in codes(findings)


def test_r002_bucketed_predict_entry_clean(tmp_path):
    """Flowing the request through a bucket/pad-named call clears the
    taint: the padded shape is a ladder rung, not the raw request size."""
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def _scores(x):
            return x * 2

        def predict(data, rung):
            arr = pad_to_bucket(jnp.asarray(data), rung)
            return _scores(arr)
    """)
    assert "R002" not in codes(findings)


def test_r002_unbucketed_nonpredict_entry_not_flagged(tmp_path):
    """Training-loop callers are not serving entries; raw-shape jit args
    there are the normal fixed-shape train step."""
    findings = lint_snippet(tmp_path, """
        import jax

        @jax.jit
        def _step(x):
            return x + 1

        def train_one_iter(batch):
            return _step(batch)
    """)
    assert "R002" not in codes(findings)


def test_r002_static_shape_branch_not_flagged(tmp_path):
    """x.shape is static at trace time — branching on it is fine even
    when x itself is traced."""
    findings = lint_snippet(tmp_path, """
        import jax

        @jax.jit
        def step(x):
            if x.shape[0] > 4:
                return x[:4]
            return x
    """)
    assert not findings


def test_r002_static_branch_not_flagged(tmp_path):
    """Branching on declared static args is deliberate jax style."""
    findings = lint_snippet(tmp_path, """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("mode",))
        def step(x, mode):
            if mode == "fast":
                return x
            return -x
    """)
    assert not findings


def test_r002_interprocedural_static_helper_not_flagged(tmp_path):
    """A helper only ever called with static values stays static — but the
    same helper fed a traced value is flagged."""
    clean = lint_snippet(tmp_path, """
        import jax

        def helper(n):
            if n > 4:
                return 1.0
            return 2.0

        @jax.jit
        def step(x):
            return x * helper(3)
    """, name="clean.py")
    assert not clean
    dirty = lint_snippet(tmp_path, """
        import jax

        def helper(n):
            if n > 4:
                return 1.0
            return 2.0

        @jax.jit
        def step(x):
            return x * helper(x.sum())
    """, name="dirty.py")
    assert "R002" in codes(dirty)


def test_r002_unbucketed_grower_key(tmp_path):
    """Sub-check (e) seed: the raw config (num_leaves, max_depth) entering
    the GrowerParams jit key compiles one step program per exact tree
    shape — the 35-97 s training warmups the bucketed step ladder
    removed."""
    findings = lint_snippet(tmp_path, """
        def setup(cfg):
            gp = GrowerParams(
                num_leaves=int(cfg.get("num_leaves", 31)),
                max_depth=int(cfg.get("max_depth", -1)))
            return gp
    """)
    assert "R002" in codes(findings)


def test_r002_rung_mapped_grower_key_clean(tmp_path):
    """Flowing the budgets through a rung/bucket-named mapping clears the
    taint: the jit key carries the ladder rung, not the raw budget."""
    findings = lint_snippet(tmp_path, """
        def leaf_rung(n):
            r = 2
            while r < n:
                r *= 2
            return r

        def setup(cfg):
            rung = leaf_rung(int(cfg.get("num_leaves", 31)))
            gp = GrowerParams(num_leaves=rung, max_depth=-1)
            return gp
    """)
    assert "R002" not in codes(findings)


def test_r002_grower_key_replace_update(tmp_path):
    """The _replace-style key update (basic.py reset_parameter) is a sink
    too: re-keying on a raw budget mid-run recompiles just like the
    initial construction."""
    findings = lint_snippet(tmp_path, """
        def reset(self, booster):
            booster.grower_params = booster.grower_params._replace(
                num_leaves=int(self.config.num_leaves))
            return booster
    """)
    assert "R002" in codes(findings)


def test_r002_jitted_step_fed_raw_budget(tmp_path):
    """A jitted grower step called with a leaf-count-derived argument keys
    the program on the exact budget; the rung belongs in the key and the
    budget in a traced scalar."""
    findings = lint_snippet(tmp_path, """
        import jax

        @jax.jit
        def grow_step(binned, budget):
            return binned

        def train(binned, cfg):
            leaves = int(cfg.get("num_leaves", 31))
            return grow_step(binned, leaves)
    """)
    assert "R002" in codes(findings)


def test_r002_raw_return_in_rung_mapping(tmp_path):
    """Sub-check (e) also pins the escape hatch: a rung/bucket mapping
    returning the raw budget IS the exact-keyed path and must carry an
    allowlist anchor (the shipped tpu_step_buckets=off branch in
    gbdt.bucketed_tree_shape does)."""
    findings = lint_snippet(tmp_path, """
        def tree_shape_bucket(bucketed, num_leaves, max_depth):
            if bucketed:
                return 2 * num_leaves, 1
            return num_leaves, max_depth
    """)
    assert "R002" in codes(findings)


# ---------------------------------------------------------------- R003
def test_r003_dtype_drift(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def step(x):
            y = np.sum(x)
            z = x.astype("float64")
            w = jnp.zeros(3, dtype="float64")
            q = x * jnp.float64(2.0)
            return y, z, w, q
    """)
    assert codes(findings).count("R003") >= 4


def test_r003_host_numpy_not_flagged(tmp_path):
    findings = lint_snippet(tmp_path, """
        import numpy as np

        def host_stats(values):
            arr = np.asarray(values, np.float64)
            return np.sum(arr)
    """)
    assert not findings


def test_r003_int_matmul_needs_preferred_element_type(tmp_path):
    """The int-packing contract: int8 histogram contraction without
    preferred_element_type=int32 wraps the sums at +-127."""
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def hist(binned, codes):
            onehot = (binned[:, :, None] == jnp.arange(8)).astype(jnp.int8)
            ch = codes.astype(jnp.int8)
            return jnp.einsum("rfb,rk->fbk", onehot, ch)
    """)
    assert "R003" in codes(findings)


def test_r003_int_matmul_with_preferred_ok(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp
        from jax import lax

        @jax.jit
        def hist(binned, codes):
            onehot = (binned[:, :, None] == jnp.arange(8)).astype(jnp.int8)
            return jnp.einsum("rfb,rk->fbk", onehot,
                              codes.astype(jnp.int8),
                              preferred_element_type=jnp.int32)

        @jax.jit
        def perm(lt, sel):
            return lax.dot_general(
                lt, sel.astype("int8"),
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
    """)
    assert not findings


def test_r003_dequantize_without_scale_flagged(tmp_path):
    """The dequantize contract: a bare f32 cast of a quantized histogram
    yields raw code sums, silently off by the per-iteration scale."""
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def gains(qhist):
            g = qhist[:, :, 0].astype(jnp.float32)
            return g.sum()
    """)
    assert "R003" in codes(findings)


def test_r003_dequantize_with_scale_ok(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def gains(qhist, g_scale):
            g = qhist[:, :, 0].astype(jnp.float32) * g_scale
            h = g_scale * qhist[:, :, 1].astype(jnp.float32)
            return g.sum() + h.sum()
    """)
    assert not findings


# ---------------------------------------------------------------- R004
def test_r004_env_override_unvalidated(tmp_path):
    """The seed case: boosting/gbdt.py:945 pre-fix (ADVICE r5 #3)."""
    findings = lint_snippet(tmp_path, """
        import os

        def pick_block(default_bs):
            bs = default_bs
            if os.environ.get("LGBM_TPU_FUSED_BS", ""):
                bs = int(os.environ["LGBM_TPU_FUSED_BS"])
            return bs
    """)
    assert "R004" in codes(findings)


def test_r004_validated_env_override_ok(tmp_path):
    findings = lint_snippet(tmp_path, """
        import os

        def _validated_block(value, cap):
            v = max(32, (int(value) // 32) * 32)
            return min(v, cap)

        def pick_block(cap):
            bs = _validated_block(os.environ["LGBM_TPU_FUSED_BS"], cap)
            return bs
    """)
    assert not findings


def test_r004_block_size_literal_and_num_rows(tmp_path):
    findings = lint_snippet(tmp_path, """
        def caller(work, scratch, args):
            return fused_split(work, scratch, *args, block_size=100)
    """)
    r4 = [f for f in findings if f.rule == "R004"]
    assert len(r4) == 2           # non-32-multiple AND missing num_rows
    clean = lint_snippet(tmp_path, """
        def caller(work, scratch, args, n):
            return fused_split(work, scratch, *args, block_size=128,
                               num_rows=n)
    """, name="clean_r4.py")
    assert not clean


def test_r004_mbatch_exceeds_mxu_rows(tmp_path):
    """8*mbatch must fit the 128 MXU rows (batched-M contract)."""
    findings = lint_snippet(tmp_path, """
        def caller(work, scratch, args, n):
            return fused_split(work, scratch, *args, block_size=128,
                               num_rows=n, mbatch=32)
    """)
    r4 = [f for f in findings if f.rule == "R004"]
    assert len(r4) == 1 and "MXU rows" in r4[0].message


def test_r004_mbatch_ring_over_vmem_budget(tmp_path):
    """pending_depth x block_size residency (ring slots + flush
    transients) must stay under the scoped-VMEM ring budget."""
    findings = lint_snippet(tmp_path, """
        def caller(work, scratch, args, n):
            return fused_split(work, scratch, *args, block_size=1024,
                               num_rows=n, mbatch=16)
    """)
    r4 = [f for f in findings if f.rule == "R004"]
    assert len(r4) == 1 and "scoped VMEM" in r4[0].message
    clean = lint_snippet(tmp_path, """
        def caller(work, scratch, args, n):
            return fused_split(work, scratch, *args, block_size=256,
                               num_rows=n, mbatch=8)
    """, name="clean_ring.py")
    assert not clean


def test_r004_pending_ring_missing_drain(tmp_path):
    """The missing-drain seed: a kernel staging histogram blocks into a
    pending ring keyed off mbatch, with no pushes % mbatch drain — the
    last partial batch would be silently dropped."""
    findings = lint_snippet(tmp_path, """
        from jax import lax

        def kernel(pendbuf, pendch, smem, mbatch):
            def hist_accum(rows, ch):
                pushes = smem[0]
                cur = lax.rem(pushes, mbatch)
                pendbuf[cur] = rows
                pendch[cur] = ch
                smem[0] = pushes + 1
            return hist_accum
    """)
    r4 = [f for f in findings if f.rule == "R004"]
    assert len(r4) == 1 and "drain" in r4[0].message
    clean = lint_snippet(tmp_path, """
        from jax import lax

        def kernel(pendbuf, pendch, smem, mbatch, flush):
            def hist_accum(rows, ch):
                pushes = smem[0]
                cur = lax.rem(pushes, mbatch)
                pendbuf[cur] = rows
                pendch[cur] = ch
                smem[0] = pushes + 1

            def hist_drain():
                pushes = smem[0]
                pending = lax.rem(pushes, mbatch)
                flush(pending)
            return hist_accum, hist_drain
    """, name="clean_drain.py")
    assert not clean


def test_r004_sublane_layout_bins_bound(tmp_path):
    """Bins-on-sublanes needs num_bins <= 64 (round 6): a constant
    sublane call with wider bins is a static contract violation."""
    findings = lint_snippet(tmp_path, """
        def caller(binned, ch):
            return pallas_histogram(binned, ch, num_bins=256,
                                    hist_layout="sublane")
    """)
    r4 = [f for f in findings if f.rule == "R004"]
    assert len(r4) == 1 and "sublane" in r4[0].message
    clean = lint_snippet(tmp_path, """
        def caller(binned, ch):
            return pallas_histogram(binned, ch, num_bins=64,
                                    hist_layout="sublane")
    """, name="clean_sublane.py")
    assert not clean


def test_r004_sublane_ring_budget_charged(tmp_path):
    """The sublane layout's row-major channel slots pad to 128 lanes —
    a block size that fits the lane ring must still be rejected when the
    call selects sublane and the padded slots blow the budget."""
    lane_ok = lint_snippet(tmp_path, """
        def caller(work, scratch, args, n):
            return fused_split(work, scratch, *args, block_size=384,
                               num_rows=n, mbatch=8,
                               hist_layout="lane")
    """, name="lane_ring.py")
    assert not [f for f in lane_ok if "VMEM" in f.message]
    sub = lint_snippet(tmp_path, """
        def caller(work, scratch, args, n):
            return fused_split(work, scratch, *args, block_size=384,
                               num_rows=n, mbatch=8,
                               hist_layout="sublane")
    """, name="sub_ring.py")
    r4 = [f for f in sub if f.rule == "R004" and "VMEM" in f.message]
    assert len(r4) == 1, [f.render() for f in sub]


def test_r004_engine_kwargs_outside_registry(tmp_path):
    """Engine-registry ownership seed (round 12): GrowerParams/._replace
    setting an engine knob outside lightgbm_tpu/engines from anything
    but a registry resolution re-opens a second selection site."""
    findings = lint_snippet(tmp_path, """
        def setup(cfg):
            return GrowerParams(num_leaves=31, hist_impl="pallas",
                                hist_mbatch=16)
    """)
    r4 = [f for f in findings if f.rule == "R004"
          and "registry" in f.message]
    assert len(r4) == 2, [f.render() for f in findings]
    clean = lint_snippet(tmp_path, """
        def setup(cfg, resolved):
            return GrowerParams(num_leaves=31,
                                hist_impl=resolved.hist_impl,
                                hist_mbatch=resolved.hist_mbatch,
                                fused_block=resolved.fused_block)
    """, name="clean_engine_kwargs.py")
    assert not [f for f in clean if "registry" in f.message]
    repl = lint_snippet(tmp_path, """
        def reset(gp, k):
            return gp._replace(hist_layout="sublane", hist_block=k)
    """, name="replace_engine.py")
    assert len([f for f in repl if f.rule == "R004"
                and "registry" in f.message]) == 1


def test_r004_engine_chooser_outside_registry(tmp_path):
    """A function choosing between engine-impl constants is selection
    POLICY — outside engines/ it is unowned (the ops/histogram.py
    _resolve_impl trace-time escape hatch is the one allowlist anchor)."""
    findings = lint_snippet(tmp_path, """
        def pick_engine(num_bins):
            if num_bins >= 128:
                return "pallas"
            return "xla"
    """)
    r4 = [f for f in findings if f.rule == "R004"]
    assert len(r4) == 1 and "engine" in r4[0].message
    # the same policy INSIDE the registry package is its home
    pkg = tmp_path / "engines"
    pkg.mkdir()
    (pkg / "registry.py").write_text(textwrap.dedent("""
        def pick_engine(num_bins):
            if num_bins >= 128:
                return "pallas"
            return "xla"
    """))
    in_registry, errors = lint_paths([str(pkg / "registry.py")])
    assert not errors
    assert not [f for f in in_registry if f.rule == "R004"]


def test_r004_constant_impl_callsite(tmp_path):
    """A histogram call pinning impl=/layout= to a constant hardcodes
    the engine at the callsite, bypassing the measured decision."""
    findings = lint_snippet(tmp_path, """
        def build(binned, ch, b):
            return histogram_block(binned, ch, b, impl="pallas",
                                   layout="sublane")
    """)
    r4 = [f for f in findings if f.rule == "R004"
          and "engine selection" in f.message]
    assert len(r4) == 2, [f.render() for f in findings]
    clean = lint_snippet(tmp_path, """
        def build(binned, ch, b, params):
            return histogram_block(binned, ch, b, impl=params.hist_impl,
                                   layout=params.hist_layout)
    """, name="clean_impl_passthrough.py")
    assert not clean
    # "auto" is not a selection — it defers to the anchored dispatch
    auto = lint_snippet(tmp_path, """
        def build(binned, ch, b):
            return histogram_block(binned, ch, b, impl="auto")
    """, name="auto_impl.py")
    assert not auto


def test_r004_engine_ownership_package_anchor():
    """The shipped tree's ONE engine-selection site outside engines/ is
    ops/histogram.py::_resolve_impl, carried by its allowlist anchor —
    with the allowlist applied the package is clean (the tier-1 test),
    without it exactly that site surfaces."""
    path = os.path.join(PKG_DIR, "ops", "histogram.py")
    findings, errors = lint_paths([path])
    assert not errors
    r4 = [f for f in findings if f.rule == "R004"]
    assert len(r4) == 1 and r4[0].func == "_resolve_impl", \
        [f.render() for f in r4]
    entries, _ = load_allowlist(DEFAULT_ALLOWLIST)
    assert not apply_allowlist(r4, entries)


def test_r004_pack4_nibble_mask_detector(tmp_path):
    """pack4 unpack sites must mask with & 0xF (round 6): the unmasked
    shift leaves the neighbour feature's nibble in the high bits."""
    findings = lint_snippet(tmp_path, """
        def unpack_bins(packed_byte, feature):
            lo = packed_byte & 0xF
            hi = packed_byte >> 4
            return lo, hi
    """)
    r4 = [f for f in findings if f.rule == "R004"]
    assert len(r4) == 1 and "0xF" in r4[0].message
    dyn = lint_snippet(tmp_path, """
        def bin_col(packed_bins, j):
            byte = packed_bins[:, j // 2]
            return byte >> ((j & 1) * 4)
    """, name="dyn_shift.py")
    assert [f for f in dyn if f.rule == "R004"]
    clean = lint_snippet(tmp_path, """
        import jax.numpy as jnp

        def unpack_bins(packed_byte, feature):
            lo = packed_byte & jnp.uint8(0x0F)
            hi = (packed_byte >> 4) & jnp.uint8(0x0F)
            dyn = (packed_byte >> ((feature & 1) * 4)) & 0xF
            return lo, hi, dyn
    """, name="clean_nibble.py")
    assert not clean
    # unrelated shifts (word indices, radix unpacks) stay out of scope
    unrelated = lint_snippet(tmp_path, """
        def radix_unpack(sums):
            word = sums >> 5
            hi = sums >> 12
            return word, hi
    """, name="unrelated_shift.py")
    assert not unrelated


def test_r004_serving_entry_contract_coverage(tmp_path):
    """Serving-engine contract coverage seed (round 20): a serving
    EngineEntry must name an HLO contract id or a contract_exempt
    justification that points at the pinning test."""
    findings = lint_snippet(tmp_path, """
        SERVING_ENTRIES = (
            EngineEntry(id="serve_fast", impl="level", layout="heap",
                        description="no contract, no exemption"),
        )
    """)
    r4 = [f for f in findings if f.rule == "R004"
          and "serving EngineEntry" in f.message]
    assert len(r4) == 1 and "serve_fast" in r4[0].message
    vague = lint_snippet(tmp_path, """
        SERVING_ENTRIES = (
            EngineEntry(id="serve_q", impl="level", layout="heap",
                        contract_exempt="trust me"),
        )
    """, name="vague_exempt.py")
    r4 = [f for f in vague if f.rule == "R004"
          and "serving EngineEntry" in f.message]
    assert len(r4) == 1 and "pinning test" in r4[0].message
    clean = lint_snippet(tmp_path, """
        SERVING_ENTRIES = (
            EngineEntry(id="serve_walk", impl="walk", layout="packed",
                        contracts=("serve_walk",)),
            EngineEntry(id="serve_qleaf", impl="level", layout="heap",
                        contract_exempt="output pinned by the recorded "
                        "bound + tests/test_level_engine.py"),
            EngineEntry(id="xla_lane", impl="xla", layout="lane"),
        )
    """, name="clean_serving.py")
    assert not [f for f in clean if "serving EngineEntry" in f.message]


def test_r004_quant_bound_discarded(tmp_path):
    """Quantized-leaf recorded-bound seed (round 20): an unpack that
    drops quantize_leaves' bound, or a hand-rolled /127 scale with no
    bound/err assignment, serves quantized scores with no accuracy
    contract."""
    findings = lint_snippet(tmp_path, """
        def stack_quant(leaf_value, class_ids):
            slab, scale = quantize_leaves(leaf_value, class_ids, "int8")
            return slab, scale
    """)
    r4 = [f for f in findings if f.rule == "R004" and "bound" in f.message]
    assert len(r4) == 1
    underscore = lint_snippet(tmp_path, """
        def stack_quant(leaf_value, class_ids):
            slab, scale, _ = quantize_leaves(leaf_value, class_ids,
                                             "int8")
            return slab, scale
    """, name="underscore_bound.py")
    assert [f for f in underscore
            if f.rule == "R004" and "bound" in f.message]
    handrolled = lint_snippet(tmp_path, """
        import jax.numpy as jnp

        def quantize(v):
            amax = jnp.max(jnp.abs(v), axis=1)
            scale = jnp.where(amax > 0, amax / 127.0, 1.0)
            slab = jnp.round(v / scale[:, None]).astype(jnp.int8)
            return slab, scale
    """, name="handrolled_scale.py")
    r4 = [f for f in handrolled
          if f.rule == "R004" and "bound" in f.message]
    assert len(r4) == 1 and "127" not in r4[0].message.split(":")[0]
    clean = lint_snippet(tmp_path, """
        import jax.numpy as jnp

        def quantize(v):
            amax = jnp.max(jnp.abs(v), axis=1)
            scale = jnp.where(amax > 0, amax / 127.0, 1.0)
            q = jnp.clip(jnp.round(v / scale[:, None]), -127, 127)
            err_t = jnp.max(jnp.abs(q * scale[:, None] - v), axis=1)
            return q.astype(jnp.int8), scale, jnp.max(err_t)

        def stack_quant(leaf_value, class_ids):
            slab, scale, bound = quantize_leaves(leaf_value, class_ids,
                                                 "int8")
            return slab, scale, float(bound)
    """, name="clean_quant.py")
    assert not [f for f in clean
                if f.rule == "R004" and "bound" in f.message]


# ---------------------------------------------------------------- R005
def test_r005_operand_shape_counting(tmp_path):
    """The seed case: parallel/comm_accounting.py:65 pre-fix (ADVICE r5
    #1) — async starts counted by operand shape."""
    findings = lint_snippet(tmp_path, """
        def collective_bytes(entries):
            total = 0
            for kind, shapes in entries:
                if kind.endswith("-start") and shapes:
                    shapes = shapes[:1]
                total += sum(shapes)
            return total
    """)
    assert "R005" in codes(findings)


def test_r005_result_shape_counting_ok(tmp_path):
    findings = lint_snippet(tmp_path, """
        RESULT_KINDS = ("all-gather-start", "collective-permute-start")

        def collective_bytes(entries):
            total = 0
            for kind, shapes in entries:
                if kind.endswith("-start") and shapes:
                    if kind in RESULT_KINDS:
                        shapes = shapes[1:2] if len(shapes) > 1 \\
                            else shapes[:1]
                    else:
                        shapes = shapes[:1]
                total += sum(shapes)
            return total
    """)
    assert not findings


def test_r004_fixed_gbdt_clean():
    """The LGBM_TPU_FUSED_BS override now routes through
    _validated_fused_block_env (ADVICE r5 #3) — no R004 findings."""
    path = os.path.join(PKG_DIR, "boosting", "gbdt.py")
    findings, errors = lint_paths([path])
    assert not errors
    assert not [f for f in findings if f.rule == "R004"], \
        [f.render() for f in findings]


def test_r005_fixed_module_clean():
    path = os.path.join(PKG_DIR, "parallel", "comm_accounting.py")
    findings, errors = lint_paths([path])
    assert not errors
    assert not [f for f in findings if f.rule == "R005"], \
        [f.render() for f in findings]


# ------------------------------------------------------- R005 extensions
def test_r005_inventory_missing_async_twin(tmp_path):
    """PR 2's psum_scatter lowers to reduce-scatter; an inventory with
    -start twins for other kinds but not reduce-scatter drops its bytes
    the day the HLO goes async."""
    findings = lint_snippet(tmp_path, """
        KINDS = ("all-reduce-start", "all-gather-start", "reduce-scatter",
                 "all-reduce", "all-gather")
    """)
    r5 = [f for f in findings if f.rule == "R005"]
    assert len(r5) == 1 and "reduce-scatter-start" in r5[0].message


def test_r005_inventory_with_twins_clean(tmp_path):
    findings = lint_snippet(tmp_path, """
        KINDS = ("all-reduce-start", "all-gather-start",
                 "reduce-scatter-start", "all-reduce", "all-gather",
                 "reduce-scatter")
    """)
    assert not findings


def test_r005_done_counting_flagged(tmp_path):
    findings = lint_snippet(tmp_path, """
        def count(entries):
            total = 0
            for kind, nbytes in entries:
                if kind.endswith("-start"):
                    total += nbytes
                if kind.endswith("-done"):
                    total += nbytes
            return total
    """)
    assert any(f.rule == "R005" and "-done" in f.message for f in findings)


def test_r005_fixed_parser_module_clean():
    """analysis/hlo.py (the extracted parser) carries every async twin and
    counts result shapes — no R005 findings."""
    path = os.path.join(PKG_DIR, "analysis", "hlo.py")
    findings, errors = lint_paths([path])
    assert not errors
    assert not [f for f in findings if f.rule == "R005"], \
        [f.render() for f in findings]


# ---------------------------------------------------------------- R006
def test_r006_unknown_axis_name(tmp_path):
    findings = lint_snippet(tmp_path, """
        from jax import lax
        from jax.sharding import Mesh

        def make(devs):
            return Mesh(devs, axis_names=("data",))

        def step(x):
            return lax.psum_scatter(x, "dta")
    """)
    r6 = [f for f in findings if f.rule == "R006"]
    assert len(r6) == 1 and "'dta'" in r6[0].message


def test_r006_dimension_kwarg_does_not_mask_axis_name(tmp_path):
    """all_gather's `axis=` kwarg is an integer DIMENSION — it must not
    swallow a typo'd positional axis name."""
    findings = lint_snippet(tmp_path, """
        from jax import lax
        from jax.sharding import Mesh

        def make(devs):
            return Mesh(devs, axis_names=("data",))

        def step(x):
            return lax.all_gather(x, "dta", axis=0, tiled=True)
    """)
    r6 = [f for f in findings if f.rule == "R006"]
    assert len(r6) == 1 and "'dta'" in r6[0].message


def test_r006_declared_axis_and_dynamic_clean(tmp_path):
    findings = lint_snippet(tmp_path, """
        from jax import lax
        from jax.sharding import Mesh

        DATA_AXIS = "data"

        def make(devs):
            return Mesh(devs, axis_names=(DATA_AXIS,))

        def step(x, gp):
            a = lax.psum(x, DATA_AXIS)
            b = lax.psum(x, gp.axis_name)      # dynamic: skipped
            return a + b + lax.axis_index(DATA_AXIS)
    """)
    assert not [f for f in findings if f.rule == "R006"]


def test_r006_sharded_readback_without_gather(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        import numpy as np

        def bad(x, mesh, row_sharding):
            v = jax.device_put(x, row_sharding(mesh))
            return np.asarray(v)

        def gathered(x, mesh, row_sharding):
            v = jax.device_put(x, row_sharding(mesh))
            v = jax.device_get(v)
            return np.asarray(v)

        def replicated_ok(x, mesh, replicated):
            v = jax.device_put(x, replicated(mesh))
            return np.asarray(v)

        def named_replicated_ok(x, mesh):
            from jax.sharding import NamedSharding, PartitionSpec as P
            v = jax.device_put(x, NamedSharding(mesh, P()))
            return np.asarray(v)

        def named_sharded_bad(x, mesh):
            from jax.sharding import NamedSharding, PartitionSpec as P
            v = jax.device_put(x, NamedSharding(mesh, P("data")))
            return np.asarray(v)
    """)
    r6 = [f for f in findings if f.rule == "R006"]
    assert sorted(f.func for f in r6) == ["bad", "named_sharded_bad"]


# ---------------------------------------------------------------- R007
def test_r007_unlocked_public_method(tmp_path):
    findings = lint_snippet(tmp_path, """
        class Booster:
            def __init__(self):
                self._api_lock = RWLock()
                self.cache = None

            def predict(self, x):
                return x
    """)
    r7 = [f for f in findings if f.rule == "R007"]
    assert len(r7) == 1 and "predict" in r7[0].message


def test_r007_mutation_under_read_lock(tmp_path):
    """The _device_trees_cache pattern: a cache fill in a read-locked
    method interleaves with concurrent readers."""
    findings = lint_snippet(tmp_path, """
        class Booster:
            def __init__(self):
                self._api_lock = RWLock()
                self.cache = None

            @read_locked
            def predict(self, x):
                self.cache = x
                return x

            @write_locked
            def update(self):
                self.cache = None
    """)
    r7 = [f for f in findings if f.rule == "R007"]
    assert len(r7) == 1 and "READ lock" in r7[0].message


def test_r007_lockless_shared_class_flagged(tmp_path):
    findings = lint_snippet(tmp_path, """
        class Dataset:
            def __init__(self):
                self.data = None

            def construct(self):
                self.data = 1
    """)
    r7 = [f for f in findings if f.rule == "R007"]
    assert len(r7) == 1 and "_api_lock" in r7[0].message


def test_r007_properly_locked_clean(tmp_path):
    findings = lint_snippet(tmp_path, """
        class Dataset:
            def __init__(self):
                self._api_lock = RWLock()
                self._inner = None

            @write_locked
            def construct(self):
                self._inner = 1
                return self

            @read_locked
            def num_data(self):
                return 0

            def _internal(self):
                self._inner = None     # private: caller holds the lock
    """)
    assert not [f for f in findings if f.rule == "R007"]


def test_r007_shipped_api_is_locked():
    """basic.py itself: every public Booster/Dataset method decorated."""
    path = os.path.join(PKG_DIR, "basic.py")
    findings, errors = lint_paths([path])
    assert not errors
    assert not [f for f in findings if f.rule == "R007"], \
        [f.render() for f in findings]


# ---------------------------------------------------------------- R008
def test_r008_unbounded_queue_flagged(tmp_path):
    """Seed: a serving class enqueuing into a maxsize-less queue — the
    slow-tick overload turns into unbounded latency instead of shedding."""
    findings = lint_snippet(tmp_path, """
        import queue

        class RequestServer:
            def __init__(self):
                self.q = queue.Queue()

            def submit(self, req):
                self.q.put_nowait(req)
    """)
    r8 = [f for f in findings if f.rule == "R008"]
    assert len(r8) == 1 and "maxsize" in r8[0].message


def test_r008_simplequeue_and_unbounded_deque_flagged(tmp_path):
    findings = lint_snippet(tmp_path, """
        import collections
        import queue

        class Coalescer:
            def __init__(self):
                self.q = collections.deque()
                self.sq = queue.SimpleQueue()
    """)
    r8 = [f for f in findings if f.rule == "R008"]
    assert len(r8) == 2
    assert any("maxlen" in f.message for f in r8)
    assert any("SimpleQueue" in f.message for f in r8)


def test_r008_blocking_without_timeout_flagged(tmp_path):
    """Seed: request-path waits with no deadline — a wedged tick then
    wedges every caller instead of raising ServingTimeout."""
    findings = lint_snippet(tmp_path, """
        def serve_one(q, out, fut, fut2, ev):
            item = q.get()
            also = q.get(True)          # queue block flag, not a timeout
            out.put(item)
            late = fut2.result(None)    # explicit-None timeout blocks too
            ev.wait(timeout=None)
            return fut.result(), item, also, late
    """)
    r8 = [f for f in findings if f.rule == "R008"]
    assert len(r8) == 6
    assert all("timeout" in f.message for f in r8)
    assert any(".put()" in f.message for f in r8)   # producer-side twin


def test_r008_bounded_and_deadlined_clean(tmp_path):
    """Bounded queues + deadline-carrying waits are the contract; also:
    dict-style .get(key) and positional-timeout waits are not findings."""
    findings = lint_snippet(tmp_path, """
        import collections
        import queue

        class PredictionServer:
            def __init__(self, cfg):
                self.q = queue.Queue(maxsize=64)
                self.dq = collections.deque(maxlen=cfg.get("cap", 8))

            def submit(self, req, done, table):
                self.q.put(req, timeout=0.5)
                self.q.put(req, False)
                done.wait(0.5)
                table.get(req)              # dict-style get: not a wait
                return req.result(timeout=1.0)
    """)
    assert not [f for f in findings if f.rule == "R008"]


def test_r008_non_serving_scope_not_flagged(tmp_path):
    """The rule is scoped: the same patterns outside serving-named
    modules/classes/functions (training workers, IO pools) are not
    serving entry points."""
    findings = lint_snippet(tmp_path, """
        import queue

        class TrainWorker:
            def __init__(self):
                self.q = queue.Queue()

            def run(self, fut):
                return fut.result()
    """)
    assert not [f for f in findings if f.rule == "R008"]


def test_r008_shipped_serving_layer_needs_only_the_drain_anchor():
    """The shipped serving package has exactly one R008 finding — the
    deliberate graceful-drain join — and it is allowlist-anchored."""
    path = os.path.join(PKG_DIR, "serving")
    findings, errors = lint_paths([path])
    assert not errors
    r8 = [f for f in findings if f.rule == "R008"]
    assert len(r8) == 1 and r8[0].func.endswith("close"), \
        [f.render() for f in r8]
    entries, _ = load_allowlist(DEFAULT_ALLOWLIST)
    assert not apply_allowlist(r8, entries)


# ------------------------------------------------- R008 (c): featurize
def test_r008_host_featurize_in_tick_flagged(tmp_path):
    """Seed: a coalescer tick binning on the host — every tick pays the
    O(rows*features) numpy sweep the device featurizer replaces."""
    findings = lint_snippet(tmp_path, """
        from binning import bin_columns

        class MicroBatchCoalescer:
            def _tick(self, batch, mappers):
                return bin_columns(mappers, batch)
    """)
    r8 = [f for f in findings if "featurization" in f.message]
    assert len(r8) == 1 and "bin_columns" in r8[0].message


def test_r008_host_featurize_reachable_from_serve_entry_flagged(tmp_path):
    """Seed: the searchsorted sweep hides one call deep behind a serve
    entry — the reachability walk still pins it (at the helper)."""
    findings = lint_snippet(tmp_path, """
        import numpy as np

        def _bin_request(mappers, arr):
            return np.searchsorted(mappers, arr)

        def predict_serving(self, data):
            return _bin_request(self.mappers, data)
    """)
    r8 = [f for f in findings if "featurization" in f.message]
    assert len(r8) == 1 and "searchsorted" in r8[0].message
    assert r8[0].func.endswith("_bin_request")


def test_r008_host_featurize_outside_serving_clean(tmp_path):
    """The same calls outside serving scope (dataset construction, model
    export) are not findings — construct-time binning is the design."""
    findings = lint_snippet(tmp_path, """
        import numpy as np

        def fit_mappers(values, bounds):
            return np.searchsorted(bounds, values)

        def export_model(mapper, thr):
            return mapper.value_to_bin(thr)
    """)
    assert not [f for f in findings if "featurization" in f.message]


def test_r008_host_featurize_behind_train_boundary_clean(tmp_path):
    """The walk stops at train/construct entries: scripts/serve trains
    before taking traffic, and that boot-time bin pass is legitimate."""
    findings = lint_snippet(tmp_path, """
        from binning import bin_columns

        def train(data, mappers):
            return bin_columns(mappers, data)

        def serve_main(data, mappers):
            model = train(data, mappers)
            return model
    """)
    assert not [f for f in findings if "featurization" in f.message]


def test_r008_shipped_host_featurize_hatch_is_anchored():
    """The one shipped host-featurize site on a serving path is the
    tpu_serve_featurize=host escape hatch (GBDT.bin_matrix), and it is
    allowlist-anchored."""
    findings, errors = lint_paths([PKG_DIR])
    assert not errors
    feat = [f for f in findings if f.rule == "R008"
            and "featurization" in f.message]
    assert len(feat) == 1 and feat[0].func.endswith("bin_matrix"), \
        [f.render() for f in feat]
    entries, _ = load_allowlist(DEFAULT_ALLOWLIST)
    assert not apply_allowlist(feat, entries)


# ------------------------------------------------------------ allowlist
def test_allowlist_suppresses_and_tracks_usage(tmp_path):
    snippet = tmp_path / "mod.py"
    snippet.write_text(textwrap.dedent("""
        import jax

        @jax.jit
        def step(x):
            return float(x)
    """))
    allow = tmp_path / "allow.txt"
    allow.write_text(
        "R001 mod.py::step  # deliberate: scalar debug readback\n"
        "R003 other.py::nope  # never matches\n")
    findings, _ = lint_paths([str(snippet)])
    assert findings
    entries, errors = load_allowlist(str(allow))
    assert not errors
    remaining = apply_allowlist(findings, entries)
    assert not remaining
    assert entries[0].used and not entries[1].used


def test_allowlist_requires_justification(tmp_path):
    allow = tmp_path / "allow.txt"
    allow.write_text("R001 mod.py::step\n")
    entries, errors = load_allowlist(str(allow))
    assert not entries
    assert errors and "justification" in errors[0]


def test_allowlist_cli_errors_exit_2(tmp_path):
    snippet = tmp_path / "ok.py"
    snippet.write_text("x = 1\n")
    allow = tmp_path / "allow.txt"
    allow.write_text("R001 mod.py::step\n")
    assert main([str(snippet), "--allowlist", str(allow)]) == 2


# ------------------------------------------------- allowlist staleness
def test_check_allow_flags_dead_anchor(tmp_path):
    """Entries whose file::func anchor no longer matches the source are
    staleness errors — the allowlist cannot rot as code moves."""
    mod = tmp_path / "mod.py"
    mod.write_text("def live():\n    return 1\n")
    entries, errors = load_allowlist_text(
        tmp_path,
        "R001 mod.py::live  # still anchored\n"
        "R001 mod.py::dead_func  # function was deleted\n"
        "R002 gone.py::anything  # file was deleted\n")
    assert not errors
    stale = check_allowlist_staleness(entries, [str(tmp_path)])
    assert len(stale) == 2
    assert any("dead_func" in s for s in stale)
    assert any("gone.py" in s for s in stale)
    # wildcard funcs only need the file to exist
    entries2, _ = load_allowlist_text(tmp_path, "R003 mod.py::*  # module\n")
    assert not check_allowlist_staleness(entries2, [str(tmp_path)])


def load_allowlist_text(tmp_path, text):
    allow = tmp_path / "allow_stale.txt"
    allow.write_text(text)
    return load_allowlist(str(allow))


def test_check_allow_subset_lint_does_not_false_flag(tmp_path):
    """Linting a subtree must not report entries anchored elsewhere in
    the allowlist's package as stale — anchors resolve against the
    allowlist's own root too."""
    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()
    (tmp_path / "a" / "mod.py").write_text("def live():\n    return 1\n")
    (tmp_path / "b" / "other.py").write_text("x = 1\n")
    allow = tmp_path / "allow.txt"
    allow.write_text("R001 a/mod.py::live  # anchored outside the subset\n")
    entries, _ = load_allowlist(str(allow))
    assert not check_allowlist_staleness(
        entries, [str(tmp_path / "b")], str(allow))
    # a genuinely dead anchor is still stale in the subset run
    allow.write_text("R001 a/mod.py::dead  # function deleted\n")
    entries, _ = load_allowlist(str(allow))
    assert check_allowlist_staleness(
        entries, [str(tmp_path / "b")], str(allow))


def test_check_allow_cli_exit_2(tmp_path):
    mod = tmp_path / "mod.py"
    mod.write_text("x = 1\n")
    allow = tmp_path / "allow.txt"
    allow.write_text("R001 mod.py::deleted_fn  # anchor died\n")
    assert main([str(tmp_path), "--allowlist", str(allow),
                 "--check-allow"]) == 2
    # without the flag the entry is only an unused-entry warning
    assert main([str(tmp_path), "--allowlist", str(allow)]) == 0
    # an audit run (--no-allowlist) must still validate the anchors
    assert main([str(tmp_path), "--allowlist", str(allow),
                 "--no-allowlist", "--check-allow"]) == 2


def test_package_allowlist_staleness_clean():
    """Tier-1 wiring: the shipped allowlist has no stale anchors."""
    entries, errors = load_allowlist(DEFAULT_ALLOWLIST)
    assert not errors
    assert not check_allowlist_staleness(entries, [PKG_DIR])


# ---------------------------------------------------------------- R009
def test_r009_timing_in_jit_reachable_flagged(tmp_path):
    """Host-clock reads under jit (alias-aware) are findings: the values
    are trace-time constants at best, dispatch-time lies at worst."""
    findings = lint_snippet(tmp_path, """
        import time
        import time as _time
        from time import perf_counter
        import jax

        @jax.jit
        def step(x):
            t0 = time.time()
            t1 = _time.monotonic()
            t2 = perf_counter()
            return x * (t1 - t0) * t2
    """)
    assert codes(findings).count("R009") >= 3


def test_r009_manual_span_close_in_jit_flagged(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        from lightgbm_tpu.obs.spans import span

        @jax.jit
        def step(x):
            s = span("hist_build")
            y = x + 1
            s.close()
            return y
    """)
    assert any(f.rule == "R009" and "span" in f.message for f in findings)


def test_r009_clock_plus_dispatch_pinned(tmp_path):
    """Tick-site pinning: timing around a dispatching call without
    block_until_ready is a finding even OUTSIDE jit-reachable code."""
    findings = lint_snippet(tmp_path, """
        import time

        def bench_loop(booster):
            t0 = time.perf_counter()
            booster.train_step()
            return time.perf_counter() - t0
    """)
    r9 = [f for f in findings if f.rule == "R009"]
    assert r9 and "block_until_ready" in r9[0].message


def test_r009_block_until_ready_exempts(tmp_path):
    """The honest-timing escape: materializing before reading the clock
    again makes the measurement real — no finding."""
    findings = lint_snippet(tmp_path, """
        import time
        import jax

        def bench_loop(booster):
            t0 = time.perf_counter()
            out = booster.train_step()
            jax.block_until_ready(out)
            return time.perf_counter() - t0
    """)
    assert "R009" not in codes(findings)


def test_r009_plain_host_timing_clean(tmp_path):
    """A clock with no device dispatch in sight (queue bookkeeping, JSONL
    timestamps) is none of R009's business."""
    findings = lint_snippet(tmp_path, """
        import time

        def record(ring, fields):
            rec = {"t": time.time()}
            rec.update(fields)
            ring.append(rec)
    """)
    assert "R009" not in codes(findings)


def test_r009_with_span_under_jit_clean(tmp_path):
    """The with-scoped span form is the SUPPORTED spelling in traced
    code (named_scope at trace time) — not a finding."""
    findings = lint_snippet(tmp_path, """
        import jax
        from lightgbm_tpu.obs.spans import span

        @jax.jit
        def step(x):
            with span("hist_build"):
                return x + 1
    """)
    assert "R009" not in codes(findings)


def test_r009c_trace_import_in_jit_reachable_module_flagged(tmp_path):
    """Sub-check (c): obs.tracing (the xplane parse) imported into a
    module that contains jit-reachable code is a finding — artifact
    analytics must stay off the hot path (post-run only)."""
    findings = lint_snippet(tmp_path, """
        import jax
        from lightgbm_tpu.obs import tracing

        @jax.jit
        def step(x):
            return x + 1
    """)
    r9 = [f for f in findings if f.rule == "R009"]
    assert r9 and "post-run" in r9[0].message


def test_r009c_function_level_trace_import_flagged(tmp_path):
    """The lazy-import spelling does not launder it: a function-level
    import inside a module with jit-reachable code is flagged too."""
    findings = lint_snippet(tmp_path, """
        import jax

        @jax.jit
        def step(x):
            return x * 2

        def emit_summary(path):
            import lightgbm_tpu.obs.tracing as tracing
            return tracing.analyze_trace_dir(path)
    """)
    assert any(f.rule == "R009" and "tracing" in f.message
               for f in findings)


def test_r009c_trace_import_without_jit_code_clean(tmp_path):
    """Post-run consumers (engine's post-session emit, scripts/obs,
    bench's ledger step) have no jit-reachable code — importing the
    analytics there is the DESIGN, not a finding."""
    findings = lint_snippet(tmp_path, """
        from lightgbm_tpu.obs import tracing

        def summarize_run(trace_dir):
            return tracing.analyze_trace_dir(trace_dir)
    """)
    assert "R009" not in codes(findings)


def test_r009c_taxonomy_constant_import_clean(tmp_path):
    """The ALL-CAPS taxonomy tuple is shared vocabulary, not parse
    machinery — importing it next to jitted code is fine (obs/spans.py
    does exactly this)."""
    findings = lint_snippet(tmp_path, """
        import jax
        from lightgbm_tpu.obs.tracing import SPAN_TAXONOMY

        @jax.jit
        def step(x):
            return x + len(SPAN_TAXONOMY)
    """)
    assert "R009" not in codes(findings)


# ---------------------------------------------------------------- R010
def test_r010_rank_guarded_collective_flagged(tmp_path):
    """The canonical pod deadlock: rank 0 joins a rendezvous its peers
    never enter."""
    findings = lint_snippet(tmp_path, """
        import jax
        from jax.experimental import multihost_utils as mu

        def sync_stats(x):
            if jax.process_index() == 0:
                return mu.process_allgather(x)
            return x
    """)
    assert "R010" in codes(findings)
    (f,) = [f for f in findings if f.rule == "R010"]
    assert "unmatched collective sequences" in f.message


def test_r010_env_rank_loop_bound_flagged(tmp_path):
    """Rank-var-derived loop trip counts disagree across the pod."""
    findings = lint_snippet(tmp_path, """
        import os
        import jax

        def drain(xs):
            rank = int(os.environ.get("LIGHTGBM_TPU_PROCESS_ID", "0"))
            for _ in range(rank):
                xs = jax.lax.psum(xs, "data")
            return xs
    """)
    assert "R010" in codes(findings)
    (f,) = [f for f in findings if f.rule == "R010"]
    assert "iteration count" in f.message


def test_r010_rank_guarded_early_exit_flagged(tmp_path):
    """A rank-conditional early return skips the barrier every other
    rank blocks in later."""
    findings = lint_snippet(tmp_path, """
        import os
        from lightgbm_tpu.parallel.mesh import sync_barrier

        def checkpoint(state):
            rank = int(os.environ["LIGHTGBM_TPU_PROCESS_ID"])
            if rank != 0:
                return None
            path = write_snapshot(state)
            sync_barrier("ckpt")
            return path
    """)
    assert "R010" in codes(findings)
    (f,) = [f for f in findings if f.rule == "R010"]
    assert "early exit" in f.message


def test_r010_while_on_rank_flagged(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        def settle(x):
            budget = jax.process_index() + 1
            while budget > 0:
                x = jax.lax.psum(x, "data")
                budget -= 1
            return x
    """)
    assert "R010" in codes(findings)


def test_r010_matched_arms_clean(tmp_path):
    """Every rank syncs, THEN branches on the gathered result — the
    reference's fixed-schedule discipline; both arms run the same
    collective sequence."""
    findings = lint_snippet(tmp_path, """
        import jax
        from jax.experimental import multihost_utils as mu

        def agree(x):
            r = jax.process_index()
            if r == 0:
                flag = mu.process_allgather(x)
            else:
                flag = mu.process_allgather(x * 0)
            return flag
    """)
    assert "R010" not in codes(findings)


def test_r010_process_count_guard_clean(tmp_path):
    """The ubiquitous distributed-at-all guard is uniform: when ranks
    could disagree on it there is no second rank to deadlock with
    (pool_bin_sample's own shape)."""
    findings = lint_snippet(tmp_path, """
        import jax
        from jax.experimental import multihost_utils as mu

        def pool(sample):
            if jax.process_count() <= 1:
                return sample
            return mu.process_allgather(sample)
    """)
    assert "R010" not in codes(findings)


def test_r010_nontrivial_process_count_flow_flagged(tmp_path):
    """process_count is only exempt in the literal distributed-at-all
    guard — arithmetic flows into a collective-bearing loop still
    fire (a half-configured launch makes it rank-varying)."""
    findings = lint_snippet(tmp_path, """
        import jax

        def ring(x):
            hops = jax.process_count() - 1
            for _ in range(hops):
                x = jax.lax.ppermute(x, "data", [(0, 1)])
            return x
    """)
    assert "R010" in codes(findings)


def test_r010_shipped_parallel_layer_needs_only_the_bootstrap_anchor():
    """The shipped multi-host plane lints R010-clean except the
    documented pre-bootstrap validation exit in init_distributed."""
    findings, errors = lint_paths(
        [os.path.join(PKG_DIR, "parallel"), os.path.join(PKG_DIR, "io")])
    assert not errors
    r010 = [f for f in findings if f.rule == "R010"]
    assert [f.func for f in r010] == ["init_distributed"]


# ---------------------------------------------------------------- R011
def r011(findings):
    return [f for f in findings if f.rule == "R011"]


def test_r011_lock_order_cycle_flagged(tmp_path):
    """Seed: two functions acquiring the same pair of module locks in
    opposite orders — the classic AB/BA deadlock, reported once with
    both witness chains."""
    findings = lint_snippet(tmp_path, """
        import threading

        MU_A = threading.Lock()
        MU_B = threading.Lock()

        def left():
            with MU_A:
                with MU_B:
                    pass

        def right():
            with MU_B:
                with MU_A:
                    pass
    """)
    cyc = [f for f in r011(findings) if "lock-order cycle" in f.message]
    assert len(cyc) == 1, [f.render() for f in findings]
    assert "left" in cyc[0].message and "right" in cyc[0].message


def test_r011_blocking_join_under_lock_flagged(tmp_path):
    """Seed: an untimed thread join while holding a lock — any other
    path into that lock now waits on the joined thread too."""
    findings = lint_snippet(tmp_path, """
        import threading

        class Worker:
            def __init__(self):
                self._mu = threading.Lock()
                self._thread = threading.Thread(target=print)

            def stop(self):
                with self._mu:
                    self._thread.join()
    """)
    hits = [f for f in r011(findings)
            if "blocking call under lock" in f.message
            and "join" in f.message]
    assert hits and hits[0].func == "stop"


def test_r011_blocking_reached_through_helper_flagged(tmp_path):
    """Interprocedural: the sleep sits two calls away from the lock —
    the finding lands at the holder and carries the call chain."""
    findings = lint_snippet(tmp_path, """
        import threading
        import time

        MU = threading.Lock()

        def backoff():
            time.sleep(1.0)

        def retry_step():
            backoff()

        def retry_under_lock():
            with MU:
                retry_step()
    """)
    hits = [f for f in r011(findings) if "time.sleep" in f.message]
    assert hits and hits[0].func == "retry_under_lock"
    assert "backoff" in hits[0].message and "retry_step" in hits[0].message


def test_r011_dispatch_under_write_lock_flagged(tmp_path):
    """Seed: jitted dispatch under an explicitly-taken write lock (the
    'hold the registry write lock across a device compile' class)."""
    findings = lint_snippet(tmp_path, """
        import jax
        from lightgbm_tpu.utils.rwlock import RWLock

        @jax.jit
        def kernel(x):
            return x * 2

        class Holder:
            def __init__(self):
                self._lock = RWLock()

            def swap(self, x):
                with self._lock.write():
                    return kernel(x)
    """)
    hits = [f for f in r011(findings)
            if "jitted dispatch under lock" in f.message]
    assert hits and hits[0].func == "swap"


def test_r011_read_write_upgrade_flagged(tmp_path):
    """Seed: a read-locked public method calling a write-locked one —
    RWLock raises at runtime; R011 finds the path statically."""
    findings = lint_snippet(tmp_path, """
        from lightgbm_tpu.utils.rwlock import RWLock, read_locked, \\
            write_locked

        class Store:
            def __init__(self):
                self._api_lock = RWLock()
                self.v = None

            @write_locked
            def commit(self, v):
                self.v = v

            @read_locked
            def peek(self):
                self.commit(None)
                return self.v
    """)
    hits = [f for f in r011(findings)
            if "read->write upgrade" in f.message]
    assert hits and hits[0].func == "peek"
    assert "commit" in hits[0].message


def test_r011_cv_wait_outside_loop_flagged(tmp_path):
    """Seed: Condition.wait under `if` instead of a predicate `while`
    loop — spurious wakeups and missed signals slip through."""
    findings = lint_snippet(tmp_path, """
        import threading

        class Box:
            def __init__(self):
                self._cv = threading.Condition()
                self.ready = False

            def take(self):
                with self._cv:
                    if not self.ready:
                        self._cv.wait(1.0)
                    return self.ready
    """)
    hits = [f for f in r011(findings)
            if "outside a predicate loop" in f.message]
    assert hits and hits[0].func == "take"


def test_r011_clean_patterns_not_flagged(tmp_path):
    """Negative: while-looped timed cv wait, notify under the cv,
    consistent AB ordering, and re-entrant same-lock nesting are all
    the blessed patterns — zero findings."""
    findings = lint_snippet(tmp_path, """
        import threading

        class Pipeline:
            def __init__(self):
                self._cv = threading.Condition()
                self._mu = threading.Lock()
                self.items = []

            def produce(self, x):
                with self._cv:
                    self.items.append(x)
                    self._cv.notify()

            def consume(self):
                with self._cv:
                    while not self.items:
                        self._cv.wait(0.1)
                    return self.items.pop(0)

        MU_A = threading.Lock()
        MU_B = threading.Lock()

        def first():
            with MU_A:
                with MU_B:
                    pass

        def second():
            with MU_A:
                with MU_B:
                    pass
    """)
    assert not r011(findings), [f.render() for f in r011(findings)]


def test_r011_anchors_used_and_not_stale():
    """The new R011 anchors resolve against the shipped tree (the
    staleness pass accepts them) and every one is exercised."""
    entries, errs = load_allowlist(DEFAULT_ALLOWLIST)
    assert not errs, errs
    r011_entries = [e for e in entries if e.rule == "R011"]
    assert len(r011_entries) >= 4
    stale = check_allowlist_staleness(entries, [PKG_DIR],
                                      DEFAULT_ALLOWLIST)
    assert not stale, stale
    findings, errors = lint_paths([PKG_DIR])
    assert not errors
    apply_allowlist(findings, entries)
    unused = [e.render() for e in r011_entries if not e.used]
    assert not unused, f"unused R011 anchors: {unused}"


# ====================================================== R012 (resources)
def r012(findings):
    return [f for f in findings if f.rule == "R012"]


def test_r012_thread_without_join_vs_daemon(tmp_path):
    """Seed: a named, started thread nobody joins is a finding; the
    daemon spelling of the same thread is a deliberate non-finding."""
    findings = lint_snippet(tmp_path, """
        import threading

        def spawn(work):
            t = threading.Thread(target=work, name="leak")
            t.start()

        def background(work):
            t = threading.Thread(target=work, daemon=True)
            t.start()
    """)
    bad = r012(findings)
    assert len(bad) == 1, [f.render() for f in bad]
    assert "never released" in bad[0].message
    assert bad[0].func == "spawn"


def test_r012_open_outside_with_on_exception_edge(tmp_path):
    """Seed: file opened, a raising call, THEN the try/finally — the
    PR-10 shape with a plain fd instead of a profiler."""
    findings = lint_snippet(tmp_path, """
        def dump(path, payload):
            fh = open(path, "w")
            encoded = encode(payload)
            try:
                fh.write(encoded)
            finally:
                fh.close()
    """)
    bad = r012(findings)
    assert len(bad) == 1, [f.render() for f in bad]
    assert "can raise and skip the release" in bad[0].message


def test_r012_listener_registered_never_unregistered(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        def install(on_event):
            jax.monitoring.register_event_listener(on_event)
    """)
    bad = r012(findings)
    assert len(bad) == 1, [f.render() for f in bad]
    assert "listener registered" in bad[0].message


def test_r012_unbounded_float_keyed_jitted_cache(tmp_path):
    """Seed: the PR 14 _score_accum_fn bug — lru_cache(maxsize=None)
    over unannotated/float keys retaining one jitted program per model
    version forever. The int/bool-annotated twin is clean."""
    findings = lint_snippet(tmp_path, """
        import functools
        import jax

        @functools.lru_cache(maxsize=None)
        def accum_fn(lo, hi, bins):
            return jax.jit(lambda x: x * (hi - lo))

        @functools.lru_cache(maxsize=None)
        def accum_fn_keyed(bins: int, weighted: bool):
            return jax.jit(lambda x: x)

        @functools.lru_cache(maxsize=32)
        def accum_fn_bounded(lo, hi):
            return jax.jit(lambda x: x * (hi - lo))
    """)
    bad = r012(findings)
    assert len(bad) == 1, [f.render() for f in bad]
    assert bad[0].func == "accum_fn"
    assert "PR 14" in bad[0].message


def test_r012_unbounded_per_version_metric_series(tmp_path):
    findings = lint_snippet(tmp_path, """
        _SERIES = {}

        def record(version, value):
            series = _SERIES.setdefault(version, ScoreHistogram())
            series.add(value)
    """)
    bad = r012(findings)
    assert len(bad) == 1, [f.render() for f in bad]
    assert "no statically visible bound" in bad[0].message


def test_r012_pruned_program_cache_is_clean(tmp_path):
    """An eviction call anywhere in the module is the statically visible
    bound the checker wants."""
    findings = lint_snippet(tmp_path, """
        import jax

        _PROGRAM_CACHE = {}

        def program_for(rows):
            if rows not in _PROGRAM_CACHE:
                while len(_PROGRAM_CACHE) >= 32:
                    _PROGRAM_CACHE.pop(next(iter(_PROGRAM_CACHE)))
                _PROGRAM_CACHE[rows] = jax.jit(lambda x: x)
            return _PROGRAM_CACHE[rows]
    """)
    assert not r012(findings), [f.render() for f in r012(findings)]


def test_r012_rung_keyed_series_is_clean(tmp_path):
    """Keys mapped through a rung/bucket ladder have a bounded domain
    even without an eviction call."""
    findings = lint_snippet(tmp_path, """
        _BY_RUNG = {}

        def window_for(rows):
            rung = rung_of(rows)
            if rung not in _BY_RUNG:
                _BY_RUNG[rung] = LatencyWindow()
            return _BY_RUNG[rung]
    """)
    assert not r012(findings), [f.render() for f in r012(findings)]


def test_r012_anchors_used_and_not_stale():
    """The R012 anchor resolves against the shipped tree and is
    exercised (the process-lifetime jax.monitoring listener latch)."""
    entries, errs = load_allowlist(DEFAULT_ALLOWLIST)
    assert not errs, errs
    r012_entries = [e for e in entries if e.rule == "R012"]
    assert 1 <= len(r012_entries) <= 8
    stale = check_allowlist_staleness(entries, [PKG_DIR],
                                      DEFAULT_ALLOWLIST)
    assert not stale, stale
    findings, errors = lint_paths([PKG_DIR])
    assert not errors
    apply_allowlist(findings, entries)
    unused = [e.render() for e in r012_entries if not e.used]
    assert not unused, f"unused R012 anchors: {unused}"


# ==================================================== knob-drift lint
def test_knobs_lint_package_is_clean():
    """Every tpu_* knob in config.PARAMS is read somewhere in the
    package AND documented in README.md — dead knobs and doc drift are
    findings (satellite 2)."""
    from lightgbm_tpu.analysis import knobs
    problems, found = knobs.check_knobs()
    assert not problems, problems
    assert len(found) > 30      # sanity: the parse actually saw PARAMS


# ================================================= aggregate all --json
def test_main_all_json_aggregate_schema(tmp_path, capsys):
    """`scripts/tpulint all --json` (satellite 3): one parseable object
    with per-stage exits/findings and a max-exit summary, over the
    jax-free stage subset."""
    import json
    from lightgbm_tpu.analysis.tpulint import main_all
    rc = main_all(["--json", "--only", "ast,resources,knobs"], PKG_DIR)
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert set(payload) == {"stages", "exit"}
    assert payload["exit"] == 0
    assert set(payload["stages"]) == {"ast", "resources", "knobs"}
    for stage in payload["stages"].values():
        assert stage["exit"] == 0
    assert isinstance(payload["stages"]["ast"]["findings"], list)
    assert isinstance(payload["stages"]["resources"]["findings"], list)
    assert payload["stages"]["knobs"]["report"]["problems"] == []
