"""tpulint: tier-1 wiring + per-rule fixture tests + allowlist workflow.

The whole-package test IS the tier-1 gate: any non-allowlisted finding in
lightgbm_tpu/ fails the suite. The fixture snippets encode each rule's
seed case (the pre-fix code from ADVICE r5) so a regression of the
analyzer — or of the fixed code — fails loudly.
"""
import os
import textwrap

import lightgbm_tpu
from lightgbm_tpu.analysis.tpulint import (DEFAULT_ALLOWLIST, apply_allowlist,
                                           lint_paths, load_allowlist, main)

PKG_DIR = os.path.dirname(lightgbm_tpu.__file__)


def lint_snippet(tmp_path, source, name="snippet.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    findings, errors = lint_paths([str(p)])
    assert not errors, errors
    return findings


def codes(findings):
    return sorted(f.rule for f in findings)


# ---------------------------------------------------------------- tier-1
def test_package_is_clean():
    """The shipped tree has zero non-allowlisted findings, and every
    allowlist entry carries a justification and is actually used."""
    findings, errors = lint_paths([PKG_DIR])
    assert not errors, errors
    entries, allow_errors = load_allowlist(DEFAULT_ALLOWLIST)
    assert not allow_errors, allow_errors
    remaining = apply_allowlist(findings, entries)
    assert not remaining, "\n".join(f.render() for f in remaining)
    unused = [e.render() for e in entries if not e.used]
    assert not unused, f"unused allowlist entries: {unused}"


def test_cli_exit_zero_on_package():
    assert main([PKG_DIR]) == 0


# ---------------------------------------------------------------- R001
def test_r001_host_sync_flagged(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            v = float(x)
            a = np.asarray(x)
            jax.device_get(x)
            i = x.sum().item()
            return v, a, i
    """)
    assert codes(findings).count("R001") >= 4


def test_r001_host_constants_not_flagged(tmp_path):
    """float() on trace-time host config (closures, module constants) is
    fine — only traced values sync."""
    findings = lint_snippet(tmp_path, """
        import jax

        ALPHA = "0.5"

        def build(cfg):
            @jax.jit
            def step(x):
                return x * float(ALPHA) + float(cfg.beta)
            return step
    """)
    assert not findings


def test_r001_host_code_not_flagged(tmp_path):
    """Un-jitted host code may sync freely (treeshap-style host loops)."""
    findings = lint_snippet(tmp_path, """
        import numpy as np

        def host_summary(arr):
            return float(np.asarray(arr).sum())
    """)
    assert not findings


# ---------------------------------------------------------------- R002
def test_r002_jit_in_loop(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        def build_all(fns):
            out = []
            for f in fns:
                out.append(jax.jit(f))
            return out
    """)
    assert "R002" in codes(findings)


def test_r002_unhashable_static_default(tmp_path):
    findings = lint_snippet(tmp_path, """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("opts",))
        def run(x, opts=[]):
            return x
    """)
    assert "R002" in codes(findings)


def test_r002_tracer_branch(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax

        @jax.jit
        def step(x, flag):
            if flag:
                return x + 1
            return x
    """)
    assert "R002" in codes(findings)


def test_r002_static_shape_branch_not_flagged(tmp_path):
    """x.shape is static at trace time — branching on it is fine even
    when x itself is traced."""
    findings = lint_snippet(tmp_path, """
        import jax

        @jax.jit
        def step(x):
            if x.shape[0] > 4:
                return x[:4]
            return x
    """)
    assert not findings


def test_r002_static_branch_not_flagged(tmp_path):
    """Branching on declared static args is deliberate jax style."""
    findings = lint_snippet(tmp_path, """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("mode",))
        def step(x, mode):
            if mode == "fast":
                return x
            return -x
    """)
    assert not findings


def test_r002_interprocedural_static_helper_not_flagged(tmp_path):
    """A helper only ever called with static values stays static — but the
    same helper fed a traced value is flagged."""
    clean = lint_snippet(tmp_path, """
        import jax

        def helper(n):
            if n > 4:
                return 1.0
            return 2.0

        @jax.jit
        def step(x):
            return x * helper(3)
    """, name="clean.py")
    assert not clean
    dirty = lint_snippet(tmp_path, """
        import jax

        def helper(n):
            if n > 4:
                return 1.0
            return 2.0

        @jax.jit
        def step(x):
            return x * helper(x.sum())
    """, name="dirty.py")
    assert "R002" in codes(dirty)


# ---------------------------------------------------------------- R003
def test_r003_dtype_drift(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def step(x):
            y = np.sum(x)
            z = x.astype("float64")
            w = jnp.zeros(3, dtype="float64")
            q = x * jnp.float64(2.0)
            return y, z, w, q
    """)
    assert codes(findings).count("R003") >= 4


def test_r003_host_numpy_not_flagged(tmp_path):
    findings = lint_snippet(tmp_path, """
        import numpy as np

        def host_stats(values):
            arr = np.asarray(values, np.float64)
            return np.sum(arr)
    """)
    assert not findings


def test_r003_int_matmul_needs_preferred_element_type(tmp_path):
    """The int-packing contract: int8 histogram contraction without
    preferred_element_type=int32 wraps the sums at +-127."""
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def hist(binned, codes):
            onehot = (binned[:, :, None] == jnp.arange(8)).astype(jnp.int8)
            ch = codes.astype(jnp.int8)
            return jnp.einsum("rfb,rk->fbk", onehot, ch)
    """)
    assert "R003" in codes(findings)


def test_r003_int_matmul_with_preferred_ok(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp
        from jax import lax

        @jax.jit
        def hist(binned, codes):
            onehot = (binned[:, :, None] == jnp.arange(8)).astype(jnp.int8)
            return jnp.einsum("rfb,rk->fbk", onehot,
                              codes.astype(jnp.int8),
                              preferred_element_type=jnp.int32)

        @jax.jit
        def perm(lt, sel):
            return lax.dot_general(
                lt, sel.astype("int8"),
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
    """)
    assert not findings


def test_r003_dequantize_without_scale_flagged(tmp_path):
    """The dequantize contract: a bare f32 cast of a quantized histogram
    yields raw code sums, silently off by the per-iteration scale."""
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def gains(qhist):
            g = qhist[:, :, 0].astype(jnp.float32)
            return g.sum()
    """)
    assert "R003" in codes(findings)


def test_r003_dequantize_with_scale_ok(tmp_path):
    findings = lint_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def gains(qhist, g_scale):
            g = qhist[:, :, 0].astype(jnp.float32) * g_scale
            h = g_scale * qhist[:, :, 1].astype(jnp.float32)
            return g.sum() + h.sum()
    """)
    assert not findings


# ---------------------------------------------------------------- R004
def test_r004_env_override_unvalidated(tmp_path):
    """The seed case: boosting/gbdt.py:945 pre-fix (ADVICE r5 #3)."""
    findings = lint_snippet(tmp_path, """
        import os

        def pick_block(default_bs):
            bs = default_bs
            if os.environ.get("LGBM_TPU_FUSED_BS", ""):
                bs = int(os.environ["LGBM_TPU_FUSED_BS"])
            return bs
    """)
    assert "R004" in codes(findings)


def test_r004_validated_env_override_ok(tmp_path):
    findings = lint_snippet(tmp_path, """
        import os

        def _validated_block(value, cap):
            v = max(32, (int(value) // 32) * 32)
            return min(v, cap)

        def pick_block(cap):
            bs = _validated_block(os.environ["LGBM_TPU_FUSED_BS"], cap)
            return bs
    """)
    assert not findings


def test_r004_block_size_literal_and_num_rows(tmp_path):
    findings = lint_snippet(tmp_path, """
        def caller(work, scratch, args):
            return fused_split(work, scratch, *args, block_size=100)
    """)
    r4 = [f for f in findings if f.rule == "R004"]
    assert len(r4) == 2           # non-32-multiple AND missing num_rows
    clean = lint_snippet(tmp_path, """
        def caller(work, scratch, args, n):
            return fused_split(work, scratch, *args, block_size=128,
                               num_rows=n)
    """, name="clean_r4.py")
    assert not clean


# ---------------------------------------------------------------- R005
def test_r005_operand_shape_counting(tmp_path):
    """The seed case: parallel/comm_accounting.py:65 pre-fix (ADVICE r5
    #1) — async starts counted by operand shape."""
    findings = lint_snippet(tmp_path, """
        def collective_bytes(entries):
            total = 0
            for kind, shapes in entries:
                if kind.endswith("-start") and shapes:
                    shapes = shapes[:1]
                total += sum(shapes)
            return total
    """)
    assert "R005" in codes(findings)


def test_r005_result_shape_counting_ok(tmp_path):
    findings = lint_snippet(tmp_path, """
        RESULT_KINDS = ("all-gather-start", "collective-permute-start")

        def collective_bytes(entries):
            total = 0
            for kind, shapes in entries:
                if kind.endswith("-start") and shapes:
                    if kind in RESULT_KINDS:
                        shapes = shapes[1:2] if len(shapes) > 1 \\
                            else shapes[:1]
                    else:
                        shapes = shapes[:1]
                total += sum(shapes)
            return total
    """)
    assert not findings


def test_r004_fixed_gbdt_clean():
    """The LGBM_TPU_FUSED_BS override now routes through
    _validated_fused_block_env (ADVICE r5 #3) — no R004 findings."""
    path = os.path.join(PKG_DIR, "boosting", "gbdt.py")
    findings, errors = lint_paths([path])
    assert not errors
    assert not [f for f in findings if f.rule == "R004"], \
        [f.render() for f in findings]


def test_r005_fixed_module_clean():
    path = os.path.join(PKG_DIR, "parallel", "comm_accounting.py")
    findings, errors = lint_paths([path])
    assert not errors
    assert not [f for f in findings if f.rule == "R005"], \
        [f.render() for f in findings]


# ------------------------------------------------------------ allowlist
def test_allowlist_suppresses_and_tracks_usage(tmp_path):
    snippet = tmp_path / "mod.py"
    snippet.write_text(textwrap.dedent("""
        import jax

        @jax.jit
        def step(x):
            return float(x)
    """))
    allow = tmp_path / "allow.txt"
    allow.write_text(
        "R001 mod.py::step  # deliberate: scalar debug readback\n"
        "R003 other.py::nope  # never matches\n")
    findings, _ = lint_paths([str(snippet)])
    assert findings
    entries, errors = load_allowlist(str(allow))
    assert not errors
    remaining = apply_allowlist(findings, entries)
    assert not remaining
    assert entries[0].used and not entries[1].used


def test_allowlist_requires_justification(tmp_path):
    allow = tmp_path / "allow.txt"
    allow.write_text("R001 mod.py::step\n")
    entries, errors = load_allowlist(str(allow))
    assert not entries
    assert errors and "justification" in errors[0]


def test_allowlist_cli_errors_exit_2(tmp_path):
    snippet = tmp_path / "ok.py"
    snippet.write_text("x = 1\n")
    allow = tmp_path / "allow.txt"
    allow.write_text("R001 mod.py::step\n")
    assert main([str(snippet), "--allowlist", str(allow)]) == 2
