"""Resilient serving layer (lightgbm_tpu/serving/): micro-batch
coalescing, deadlines/shedding, atomic hot-swap + rollback, probes.

The ISSUE 9 acceptance surface: under injected faults (hang mid-swap,
slow tick, worker kill) the server returns structured errors or rolls
back — never a wedged queue or a mixed-model response — and the
post-warmup steady state compiles nothing. Faults are driven by
analysis/faultinject.py's serving sites (coalesce_tick / swap / warmup /
request) with the same count/disarm semantics training uses.
"""
import json
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.analysis import faultinject, guards
from lightgbm_tpu.ops.predict import parse_bucket_ladder, warmup_rungs
from lightgbm_tpu.serving import (ModelRegistry, ServerClosed,
                                  ServerOverloaded, ServeFuture,
                                  ServingError, ServingTimeout, SwapFailed)

from utils import FAST_PARAMS, binary_data, multiclass_data

#: a tiny two-rung ladder so warmup compiles exactly two predict programs
LADDER = "32,256"


def _params(**kw):
    return dict(FAST_PARAMS, objective="binary",
                tpu_predict_buckets=LADDER, **kw)


@pytest.fixture(scope="module")
def boosters():
    X, y = binary_data()
    b1 = lgb.train(_params(), lgb.Dataset(X, label=y), 8)
    b2 = lgb.train(_params(), lgb.Dataset(X, label=y), 12)
    return b1, b2, X


@pytest.fixture
def server(boosters):
    b1, _, _ = boosters
    srv = b1.serve(tick_ms=1.0, queue_max=512, deadline_ms=3000.0)
    yield srv
    srv.close(drain=False, timeout_s=5.0)


# ------------------------------------------------------------ enumeration
def test_warmup_rungs_enumeration():
    ladder = parse_bucket_ladder("32,256,1024")
    assert warmup_rungs(ladder) == (32, 256, 1024)
    assert warmup_rungs(ladder, max_rows=300) == (32, 256)
    assert warmup_rungs(ladder, max_rows=0) == (32, 256, 1024)
    # a cap below every rung still yields a usable batch bound
    assert warmup_rungs(ladder, max_rows=8) == (32,)


def test_warm_predict_ladder_stats(boosters):
    b1, _, _ = boosters
    stats = b1.warm_predict_ladder()
    assert stats["rungs"] == [32, 256]
    assert set(stats["cache"]) == {"requests", "hits", "misses"}
    # re-warm in the same process: the jit cache is already hot
    again = b1.warm_predict_ladder()
    assert again["lowerings"] == 0 and again["backend_compiles"] == 0


# ------------------------------------------------------- serving fast path
def test_predict_serving_padded_parity(boosters):
    b1, _, X = boosters
    out, n = b1.predict_serving(X[:10])
    assert out.shape == (32,) and n == 10        # padded to the rung
    np.testing.assert_array_equal(out[:n], b1.predict(X[:10]))
    raw, _ = b1.predict_serving(X[:10], raw_score=True)
    np.testing.assert_array_equal(raw[:n], b1.predict(X[:10],
                                                      raw_score=True))


def test_predict_serving_honors_predict_window_params(boosters):
    """predict()'s params-level window overrides
    (num_iteration_predict / start_iteration_predict) apply to the
    serving path too — parity is bit-for-bit, windows included."""
    _, _, X = boosters
    y = (X[:, 1] > 0).astype(float)
    bst = lgb.train(_params(num_iteration_predict=2),
                    lgb.Dataset(X, label=y), 6)
    out, n = bst.predict_serving(X[:9])
    np.testing.assert_array_equal(out[:n], bst.predict(X[:9]))
    # and the override really is a 2-iteration window, not the full model
    assert not np.array_equal(out[:n], bst.predict(X[:9],
                                                   num_iteration=6))


def test_predict_serving_honors_pred_early_stop(boosters):
    """pred_early_stop is per-row, so its approximation survives
    batching — serving parity includes it."""
    _, _, X = boosters
    y = (X[:, 2] > 0).astype(float)
    bst = lgb.train(_params(pred_early_stop=True,
                            pred_early_stop_margin=0.5,
                            pred_early_stop_freq=2),
                    lgb.Dataset(X, label=y), 8)
    out, n = bst.predict_serving(X[:15])
    np.testing.assert_array_equal(out[:n], bst.predict(X[:15]))


def test_scan_engine_booster_rejected_by_serving(boosters):
    """tpu_predict_engine=scan recompiles per shape by design: a server
    on it could never reach readiness, so deploy refuses up front."""
    _, _, X = boosters
    y = (X[:, 0] > 0).astype(float)
    bst = lgb.train(_params(tpu_predict_engine="scan"),
                    lgb.Dataset(X, label=y), 2)
    with pytest.raises(SwapFailed, match="scan"):
        bst.serve()
    assert "skipped" in bst.warm_predict_ladder()   # library API still up


def test_predict_serving_multiclass_shape():
    X, y = multiclass_data()
    params = dict(FAST_PARAMS, objective="multiclass", num_class=3,
                  tpu_predict_buckets=LADDER)
    bst = lgb.train(params, lgb.Dataset(X, label=y), 3)
    out, n = bst.predict_serving(X[:7])
    assert out.shape == (32, 3) and n == 7
    np.testing.assert_array_equal(out[:n], bst.predict(X[:7]))


def test_coalescer_batches_concurrent_requests(server, boosters):
    b1, _, X = boosters
    refs = {s: b1.predict(X[:s]) for s in (3, 17, 40)}
    barrier = threading.Barrier(12)
    results, errors = {}, []

    def client(i):
        try:
            s = (3, 17, 40)[i % 3]
            barrier.wait()
            results[i] = (s, server.submit(X[:s]).result())
        except Exception as err:  # pragma: no cover - failure path
            errors.append(err)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(12)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    for i, (s, out) in results.items():
        np.testing.assert_array_equal(out, refs[s])
    stats = server.stats
    # coalescing happened: 12 concurrent requests took fewer ticks
    assert stats["served_requests"] == 12
    assert stats["ticks"] < 12


def test_sync_predict_equals_booster_predict(server, boosters):
    b1, _, X = boosters
    np.testing.assert_array_equal(server.predict(X[:5]), b1.predict(X[:5]))
    one = server.predict(X[0])                   # 1-row request path
    np.testing.assert_array_equal(one, b1.predict(X[:1]))


def test_zero_steady_state_recompiles_mixed_sizes(server, boosters):
    _, _, X = boosters
    server.predict(X[:40])                        # touch both rungs once
    server.predict(X[:200])
    with guards.compile_counter() as cc:
        for _ in range(3):
            futs = [server.submit(X[:s]) for s in (1, 5, 17, 32, 64, 200)]
            for f in futs:
                f.result()
    cc.assert_no_compiles("post-warmup serving steady state")


# --------------------------------------------------- deadlines & shedding
def test_future_result_is_deadline_bounded():
    fut = ServeFuture(np.zeros((1, 4)), deadline_s=0.05, deadline_ms=50.0)
    t0 = time.monotonic()
    with pytest.raises(ServingTimeout):
        fut.result(timeout=0.1)
    assert time.monotonic() - t0 < 5.0
    # the synthesized timeout IS the future's outcome (completion is a
    # CAS): a worker finishing later cannot overwrite it, and repeat
    # reads agree with the first
    fut._complete("v", 1.0)
    with pytest.raises(ServingTimeout):
        fut.result()
    ok = ServeFuture(np.zeros((1, 4)), deadline_s=5.0, deadline_ms=5000.0)
    ok._complete("v", 1.0)
    assert ok.result() == 1.0 and ok.version == "v"
    assert ok.latency_s is not None


def test_request_expired_in_queue_gets_structured_timeout(boosters):
    b1, _, X = boosters
    srv = b1.serve(tick_ms=1.0, queue_max=64, deadline_ms=3000.0)
    try:
        with faultinject.inject("hang@coalesce_tick=1:seconds=0.5"):
            first = srv.submit(X[:1])             # pops + hangs the tick
            time.sleep(0.05)
            doomed = srv.submit(X[:1], deadline_ms=100.0)
            with pytest.raises(ServingTimeout):
                doomed.result()
            assert np.isfinite(first.result(timeout=5.0)).all()
        assert srv.stats["timeouts"] >= 1
    finally:
        srv.close(drain=False, timeout_s=5.0)


def test_slow_tick_sheds_instead_of_growing_queue(boosters):
    """ISSUE 9 satellite: a slow tick (injected hang@coalesce_tick) must
    convert overload into ServerOverloaded at the admission edge; the
    queue never exceeds tpu_serve_queue_max rows, and the server serves
    normally once the fault disarms."""
    b1, _, X = boosters
    srv = b1.serve(tick_ms=1.0, queue_max=8, deadline_ms=3000.0)
    try:
        with faultinject.inject(
                "hang@coalesce_tick=1:count=2:seconds=0.4") as plan:
            srv.submit(X[:1])                     # tick 1 pops this, hangs
            time.sleep(0.05)
            shed, admitted = 0, []
            for _ in range(30):
                try:
                    admitted.append(srv.submit(X[:1]))
                except ServerOverloaded:
                    shed += 1
            assert shed > 0
            assert srv.stats["max_queue_rows"] <= 8
            for f in admitted:                    # bounded completion
                assert np.isfinite(f.result(timeout=10.0)).all()
            assert plan.faults[0].fired >= 1
        # recovery: fault disarmed, normal service
        np.testing.assert_array_equal(srv.predict(X[:3]), b1.predict(X[:3]))
        assert srv.stats["shed"] == shed
    finally:
        srv.close(drain=False, timeout_s=5.0)


def test_killed_worker_respawns_and_queue_keeps_draining(boosters):
    b1, _, X = boosters
    srv = b1.serve(tick_ms=1.0, queue_max=64, deadline_ms=3000.0)
    try:
        with faultinject.inject("kill@coalesce_tick=1"):
            doomed = srv.submit(X[:2])
            with pytest.raises(ServingError):
                doomed.result()
        deadline = time.monotonic() + 5.0
        while (not srv.stats["worker_restarts"]
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert srv.stats["worker_restarts"] >= 1
        assert srv.health()["worker_alive"]
        np.testing.assert_array_equal(srv.predict(X[:4]), b1.predict(X[:4]))
    finally:
        srv.close(drain=False, timeout_s=5.0)


def test_transient_request_fault_surfaces_at_submit(server, boosters):
    _, _, X = boosters
    with faultinject.inject("transient@request=1"):
        with pytest.raises(RuntimeError, match="injected transient"):
            server.submit(X[:1])
    assert np.isfinite(server.predict(X[:1])).all()


# ----------------------------------------------------- hot-swap / rollback
def test_hot_swap_serves_exactly_one_version(boosters, lock_order_witness):
    b1, b2, X = boosters
    ref1, ref2 = b1.predict(X[:20]), b2.predict(X[:20])
    assert not np.array_equal(ref1, ref2)
    srv = b1.serve(tick_ms=1.0, deadline_ms=3000.0)
    try:
        stop, results, errors = threading.Event(), [], []

        def hammer():
            while not stop.is_set():
                f = srv.submit(X[:20])
                try:
                    results.append((f.result(), f.version))
                except Exception as err:  # pragma: no cover
                    errors.append(err)

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.1)
        srv.deploy("v2", b2)                     # mid-stream atomic swap
        time.sleep(0.1)
        stop.set()
        for t in threads:
            t.join()
        assert not errors, errors[:2]
        versions = {v for _, v in results}
        assert versions <= {"v0", "v2"} and "v2" in versions
        for out, v in results:
            np.testing.assert_array_equal(out, ref1 if v == "v0" else ref2)
        assert srv.health()["active_version"] == "v2"
        # rollback re-activates v0
        assert srv.rollback() == "v0"
        np.testing.assert_array_equal(srv.predict(X[:20]), ref1)
    finally:
        srv.close(drain=False, timeout_s=5.0)


def test_hang_mid_swap_rolls_back(boosters, lock_order_witness):
    """ISSUE 9 acceptance: a swap commit that hangs past its deadline is
    abandoned via the epoch token — SwapFailed, the old model stays
    active, and the abandoned commit can never land later."""
    b1, b2, X = boosters
    srv = b1.serve(tick_ms=1.0, deadline_ms=3000.0)
    try:
        with faultinject.inject("hang@swap=1:seconds=3"):
            with pytest.raises(SwapFailed, match="did not commit"):
                srv.deploy("v2", b2, deadline_s=0.5)
        h = srv.health()
        assert h["active_version"] == "v0" and h["failed_swaps"] == 1
        np.testing.assert_array_equal(srv.predict(X[:6]), b1.predict(X[:6]))
        time.sleep(3.0)                          # abandoned worker wakes...
        assert srv.health()["active_version"] == "v0"   # ...token refused
        srv.deploy("v2", b2)                     # clean swap still works
        assert srv.health()["active_version"] == "v2"
    finally:
        srv.close(drain=False, timeout_s=5.0)


def test_failed_warmup_rolls_back(boosters):
    b1, b2, X = boosters
    srv = b1.serve(tick_ms=1.0, deadline_ms=3000.0)
    try:
        with faultinject.inject("transient@warmup=1"):
            with pytest.raises(SwapFailed, match="warmup/health"):
                srv.deploy("v2", b2)
        assert srv.health()["active_version"] == "v0"
        assert srv.health()["failed_swaps"] == 1
        np.testing.assert_array_equal(srv.predict(X[:4]), b1.predict(X[:4]))
    finally:
        srv.close(drain=False, timeout_s=5.0)


def test_registry_guards():
    reg = ModelRegistry()
    with pytest.raises(ServingError, match="no active model"):
        reg.active()
    with pytest.raises(ServingError, match="no previous"):
        reg.rollback()
    with pytest.raises(SwapFailed, match="cannot take the device"):
        reg.deploy("v0", object())


def test_registry_version_conflict_and_retire(boosters):
    b1, b2, _ = boosters
    reg = ModelRegistry()
    reg.deploy("a", b1, warm=False, health_check=False)
    with pytest.raises(SwapFailed, match="already deployed"):
        reg.deploy("a", b2, warm=False, health_check=False)
    reg.deploy("b", b2, warm=False, health_check=False)
    with pytest.raises(ServingError, match="cannot retire the active"):
        reg.retire("b")
    reg.retire("a")
    assert reg.versions() == ["b"]


# -------------------------------------------------- drain / close / probes
def test_graceful_drain_completes_everything(boosters):
    b1, _, X = boosters
    srv = b1.serve(tick_ms=5.0, queue_max=512, deadline_ms=5000.0)
    futs = [srv.submit(X[:3]) for _ in range(20)]
    srv.close(drain=True)                         # blocking drain
    assert all(f.done() for f in futs)
    ref = b1.predict(X[:3])
    for f in futs:
        np.testing.assert_array_equal(f.result(), ref)
    with pytest.raises(ServerClosed):
        srv.submit(X[:1])
    assert not srv.ready()


def test_close_without_drain_fails_queued_structurally(boosters):
    b1, _, X = boosters
    srv = b1.serve(tick_ms=1.0, deadline_ms=3000.0)
    with faultinject.inject("hang@coalesce_tick=1:seconds=0.3"):
        srv.submit(X[:1])
        time.sleep(0.05)
        queued = [srv.submit(X[:1]) for _ in range(4)]
        srv.close(drain=False, timeout_s=5.0)
    done = [f for f in queued if f.done()]
    for f in done:
        with pytest.raises(ServerClosed):
            f.result()


def test_health_and_readiness_probes(boosters):
    b1, _, X = boosters
    srv = b1.serve(tick_ms=1.0, warm=False)
    try:
        h = srv.health()
        assert h["device"]["ok"] and h["device"]["platform"] == "cpu"
        assert h["active_version"] == "v0" and not h["warm_rungs"]
        assert not h["ready"]                     # unwarmed != ready
        stats = srv.warm()
        assert stats["rungs"] == [32, 256]
        assert srv.ready()
        assert srv.health()["max_batch_rows"] == 256
        assert json.dumps(srv.health(), default=str)   # probe serializes
    finally:
        srv.close(drain=False, timeout_s=5.0)


def test_oversized_and_malformed_requests_rejected(server, boosters):
    _, _, X = boosters
    with pytest.raises(ValueError, match="largest warmed"):
        server.submit(np.zeros((1000, X.shape[1])))
    with pytest.raises(ValueError, match="features"):
        server.submit(np.zeros((2, X.shape[1] + 3)))
    with pytest.raises(ValueError, match="empty"):
        server.submit(np.zeros((0, X.shape[1])))


# The compile-cache-across-restarts satellite test lives in
# tests/test_zz_serving_cache.py: its jax.clear_caches() calls (the
# process-restart stand-in) would force every LATER-collected test file
# to re-lower its programs, so it must run at the end of the suite.


# ----------------------------------------------------------- bench & CLI
def test_bench_stage_labels_serving(monkeypatch):
    import bench
    monkeypatch.setenv("BENCH_SERVING", "1")
    monkeypatch.delenv("BENCH_HIST_MICRO", raising=False)
    monkeypatch.delenv("BENCH_PREDICT", raising=False)
    assert bench._bench_stage() == "serving"


def test_cli_probe_reports_ready(tmp_path, capsys):
    from lightgbm_tpu.serving.cli import main
    rng = np.random.RandomState(0)
    X = rng.randn(80, 4)
    y = (X[:, 0] > 0).astype(float)
    csv = tmp_path / "train.csv"
    np.savetxt(csv, np.column_stack([y, X]), delimiter=",")
    rc = main([str(csv), "--rounds", "2", "--probe",
               "--param", "objective=binary", "--param", "max_bin=15",
               "--param", "num_leaves=4", "--param", "min_data_in_leaf=5",
               "--param", f"tpu_predict_buckets={LADDER}"])
    assert rc == 0
    health = json.loads(capsys.readouterr().out)
    assert health["ready"] and health["warm_rungs"] == [32, 256]


# ------------------------------------------- R012 leak regressions
def test_close_after_hung_tick_leaves_no_worker_thread(
        boosters, resource_leak_witness):
    """Closing the server while a tick is hung must still join the
    coalescer worker and stop the metrics plane — the runtime complement
    of tpulint R012's ownership check on PredictionServer."""
    b1, _, X = boosters
    srv = b1.serve(tick_ms=1.0, deadline_ms=3000.0)
    try:
        with faultinject.inject("hang@coalesce_tick=1:seconds=0.3"):
            srv.submit(X[:1])
            time.sleep(0.05)
    finally:
        srv.close(drain=False, timeout_s=5.0)
    assert not srv.health()["worker_alive"]
