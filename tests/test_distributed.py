"""Distributed (data-parallel) training tests on the virtual 8-device mesh.

Mirrors the reference's distributed test strategy
(reference: tests/distributed/_test_distributed.py — N local CLI processes with
partitioned data, asserting accuracy and identical models across workers). Here
the 8 XLA CPU devices form a real `jax.sharding.Mesh`; GSPMD partitions the
histogram build over rows and inserts the ICI collectives the reference did
with socket ReduceScatter (data_parallel_tree_learner.cpp:223-300).
"""
import jax
import numpy as np
import pytest
from sklearn.metrics import roc_auc_score

import lightgbm_tpu as lgb

from utils import FAST_PARAMS, binary_data, train_test_split_simple


def _params(**kw):
    p = dict(FAST_PARAMS)
    p.update(kw)
    return p


@pytest.fixture(autouse=True)
def need_devices():
    if len(jax.devices()) < 2:
        pytest.skip("needs multi-device backend")


def test_data_parallel_quality():
    X, y = binary_data()
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    bst = lgb.train(_params(objective="binary", tree_learner="data"),
                    lgb.Dataset(Xtr, label=ytr), 30)
    assert roc_auc_score(yte, bst.predict(Xte)) > 0.93
    # the mesh really was used: training score is sharded over the data axis
    g = bst._gbdt
    assert g.mesh is not None
    assert len(g.mesh.devices.ravel()) == len(jax.devices())


def test_data_parallel_matches_serial_auc():
    X, y = binary_data()
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    p_serial = lgb.train(_params(objective="binary"),
                         lgb.Dataset(Xtr, label=ytr), 20).predict(Xte)
    p_data = lgb.train(_params(objective="binary", tree_learner="data"),
                       lgb.Dataset(Xtr, label=ytr), 20).predict(Xte)
    # split decisions can differ on fp ties; model quality must match
    assert abs(roc_auc_score(yte, p_serial) - roc_auc_score(yte, p_data)) < 0.01


def test_data_parallel_uneven_rows():
    # row count not divisible by the device count: padding path
    X, y = binary_data()
    n = len(y) - 5  # 595: not divisible by 8
    X, y = X[:n], y[:n]
    bst = lgb.train(_params(objective="binary", tree_learner="data"),
                    lgb.Dataset(X, label=y), 10)
    p = bst.predict(X)
    assert len(p) == n
    assert roc_auc_score(y, p) > 0.95


def test_data_parallel_with_valid_and_weights():
    X, y = binary_data()
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    w = np.where(ytr > 0, 2.0, 1.0)
    ds = lgb.Dataset(Xtr, label=ytr, weight=w)
    dv = ds.create_valid(Xte, label=yte)
    hist = {}
    bst = lgb.train(_params(objective="binary", tree_learner="data",
                            metric="binary_logloss"),
                    ds, 15, valid_sets=[dv],
                    callbacks=[lgb.record_evaluation(hist)])
    assert len(hist["valid_0"]["binary_logloss"]) == 15
    assert hist["valid_0"]["binary_logloss"][-1] < \
        hist["valid_0"]["binary_logloss"][0]


def test_voting_parallel_alias_runs():
    # voting-parallel currently shares the data-parallel path (full histogram
    # psum; the top-k comm optimization is meaningless under GSPMD until the
    # explicit shard_map learner lands)
    X, y = binary_data()
    bst = lgb.train(_params(objective="binary", tree_learner="voting"),
                    lgb.Dataset(X, label=y), 8)
    assert roc_auc_score(y, bst.predict(X)) > 0.95


def test_multiclass_data_parallel():
    from utils import multiclass_data
    X, y = multiclass_data()
    bst = lgb.train(
        _params(objective="multiclass", num_class=3, tree_learner="data"),
        lgb.Dataset(X, label=y), 10)
    p = bst.predict(X)
    assert (p.argmax(1) == y).mean() > 0.9
