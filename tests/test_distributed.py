"""Distributed (data-parallel) training tests on the virtual 8-device mesh.

Mirrors the reference's distributed test strategy
(reference: tests/distributed/_test_distributed.py — N local CLI processes with
partitioned data, asserting accuracy and identical models across workers). Here
the 8 XLA CPU devices form a real `jax.sharding.Mesh`; GSPMD partitions the
histogram build over rows and inserts the ICI collectives the reference did
with socket ReduceScatter (data_parallel_tree_learner.cpp:223-300).
"""
import jax
import numpy as np
import pytest
from sklearn.metrics import roc_auc_score

import lightgbm_tpu as lgb

from utils import FAST_PARAMS, binary_data, train_test_split_simple


def _params(**kw):
    p = dict(FAST_PARAMS)
    p.update(kw)
    return p


@pytest.fixture(autouse=True)
def need_devices():
    if len(jax.devices()) < 2:
        pytest.skip("needs multi-device backend")


def test_data_parallel_quality():
    X, y = binary_data()
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    bst = lgb.train(_params(objective="binary", tree_learner="data"),
                    lgb.Dataset(Xtr, label=ytr), 30)
    assert roc_auc_score(yte, bst.predict(Xte)) > 0.93
    # the mesh really was used: training score is sharded over the data axis
    g = bst._gbdt
    assert g.mesh is not None
    assert len(g.mesh.devices.ravel()) == len(jax.devices())


def test_data_parallel_matches_serial_auc():
    X, y = binary_data()
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    p_serial = lgb.train(_params(objective="binary"),
                         lgb.Dataset(Xtr, label=ytr), 20).predict(Xte)
    p_data = lgb.train(_params(objective="binary", tree_learner="data"),
                       lgb.Dataset(Xtr, label=ytr), 20).predict(Xte)
    # split decisions can differ on fp ties; model quality must match
    assert abs(roc_auc_score(yte, p_serial) - roc_auc_score(yte, p_data)) < 0.01


def test_data_parallel_uneven_rows():
    # row count not divisible by the device count: padding path
    X, y = binary_data()
    n = len(y) - 5  # 595: not divisible by 8
    X, y = X[:n], y[:n]
    bst = lgb.train(_params(objective="binary", tree_learner="data"),
                    lgb.Dataset(X, label=y), 10)
    p = bst.predict(X)
    assert len(p) == n
    assert roc_auc_score(y, p) > 0.95


def test_data_parallel_with_valid_and_weights():
    X, y = binary_data()
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    w = np.where(ytr > 0, 2.0, 1.0)
    ds = lgb.Dataset(Xtr, label=ytr, weight=w)
    dv = ds.create_valid(Xte, label=yte)
    hist = {}
    bst = lgb.train(_params(objective="binary", tree_learner="data",
                            metric="binary_logloss"),
                    ds, 15, valid_sets=[dv],
                    callbacks=[lgb.record_evaluation(hist)])
    assert len(hist["valid_0"]["binary_logloss"]) == 15
    assert hist["valid_0"]["binary_logloss"][-1] < \
        hist["valid_0"]["binary_logloss"][0]


def test_voting_parallel_alias_runs():
    # voting-parallel currently shares the data-parallel path (full histogram
    # psum; the top-k comm optimization is meaningless under GSPMD until the
    # explicit shard_map learner lands)
    X, y = binary_data()
    bst = lgb.train(_params(objective="binary", tree_learner="voting"),
                    lgb.Dataset(X, label=y), 8)
    assert roc_auc_score(y, bst.predict(X)) > 0.95


def test_multiclass_data_parallel():
    from utils import multiclass_data
    X, y = multiclass_data()
    bst = lgb.train(
        _params(objective="multiclass", num_class=3, tree_learner="data"),
        lgb.Dataset(X, label=y), 10)
    p = bst.predict(X)
    assert (p.argmax(1) == y).mean() > 0.9


def test_data_parallel_model_equality_with_serial():
    """Bit-level split parity: same binning + exactly-representable
    gradients => identical trees serial vs data-parallel (the reference's
    distributed tests assert per-worker model-file equality,
    ref tests/distributed/_test_distributed.py:168)."""
    X, y = binary_data()
    # first-iteration gradients of l2 with boost_from_average=False are
    # exactly -y (integers): histogram sums are exact in any order
    params = _params(objective="regression", boost_from_average=False,
                     learning_rate=1.0, num_leaves=8)
    serial = lgb.train(params, lgb.Dataset(X, label=y), 1)
    data = lgb.train(dict(params, tree_learner="data"),
                     lgb.Dataset(X, label=y), 1)
    ts = serial._gbdt.models[0]
    td = data._gbdt.models[0]
    np.testing.assert_array_equal(ts.split_feature, td.split_feature)
    np.testing.assert_array_equal(ts.split_bin, td.split_bin)
    np.testing.assert_array_equal(ts.left_child, td.left_child)
    np.testing.assert_allclose(ts.leaf_value, td.leaf_value,
                               rtol=1e-6, atol=1e-7)
    # and the full-model text agrees after multiple iterations within fp noise
    s5 = lgb.train(params, lgb.Dataset(X, label=y), 5)
    d5 = lgb.train(dict(params, tree_learner="data"),
                   lgb.Dataset(X, label=y), 5)
    np.testing.assert_allclose(d5.predict(X), s5.predict(X),
                               rtol=1e-4, atol=1e-5)


def test_feature_parallel_learner():
    """Feature-parallel: data replicated, split finding sharded by feature
    (reference: feature_parallel_tree_learner.cpp)."""
    X, y = binary_data()
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    bst = lgb.train(_params(objective="binary", tree_learner="feature"),
                    lgb.Dataset(Xtr, label=ytr), 20)
    assert roc_auc_score(yte, bst.predict(Xte)) > 0.93
    # serial parity on the first exactly-representable tree
    params = _params(objective="regression", boost_from_average=False,
                     learning_rate=1.0, num_leaves=8)
    s1 = lgb.train(params, lgb.Dataset(Xtr, label=ytr), 1)
    f1 = lgb.train(dict(params, tree_learner="feature"),
                   lgb.Dataset(Xtr, label=ytr), 1)
    np.testing.assert_array_equal(s1._gbdt.models[0].split_feature,
                                  f1._gbdt.models[0].split_feature)


def test_voting_parallel_caps_features_and_learns():
    """Voting-parallel: per-shard top-k vote; only elected features carry
    reduced histograms (reference: voting_parallel_tree_learner.cpp:151).
    With 2k >= F every feature is elected and the result must equal the
    data-parallel learner; harder vote caps still learn (PV-Tree is a
    large-shard approximation, so toy-scale quality degrades)."""
    X, y = binary_data()
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    p_all = lgb.train(_params(objective="binary", tree_learner="voting",
                              top_k=5), lgb.Dataset(Xtr, label=ytr), 20)
    p_data = lgb.train(_params(objective="binary", tree_learner="data"),
                       lgb.Dataset(Xtr, label=ytr), 20)
    np.testing.assert_allclose(p_all.predict(Xte), p_data.predict(Xte),
                               rtol=1e-4, atol=1e-5)
    g = p_all._gbdt
    assert g.grower_params.voting_k == 5
    assert g.grower_params.voting_shards == len(jax.devices())
    capped = lgb.train(_params(objective="binary", tree_learner="voting",
                               top_k=3), lgb.Dataset(Xtr, label=ytr), 20)
    assert roc_auc_score(yte, capped.predict(Xte)) > 0.65


def test_multihost_config_parsing():
    """Multi-host bootstrap plumbing (reference: linkers_socket.cpp machine
    list parsing; actual multi-process init needs real hosts)."""
    from lightgbm_tpu.parallel.multihost import (_parse_machines,
                                                 infer_process_id)
    ms = _parse_machines("10.0.0.1:12400, 10.0.0.2:12400", "")
    assert ms == ["10.0.0.1:12400", "10.0.0.2:12400"]
    assert infer_process_id(["10.9.9.9:1", "127.0.0.1:2"]) == 1
    import os
    os.environ["LIGHTGBM_TPU_PROCESS_ID"] = "0"
    try:
        assert infer_process_id(ms) == 0
    finally:
        del os.environ["LIGHTGBM_TPU_PROCESS_ID"]
    # num_machines=1 is a no-op
    from lightgbm_tpu.parallel.multihost import init_distributed
    from lightgbm_tpu.config import Config
    assert init_distributed(Config({"num_machines": 1})) is False
    # inconsistent machine list raises
    import pytest as _pytest
    with _pytest.raises(ValueError):
        init_distributed(Config({"num_machines": 3,
                                 "machines": "a:1,b:2"}))


class TestMeshCompact:
    """Data-parallel COMPACT grower: shard-local physical partitions with
    psum-ed histograms (reference: DataParallelTreeLearner keeps the local
    partition beside global_data_count_in_leaf_,
    data_parallel_tree_learner.cpp:223-340). The serial compact model is the
    golden reference — split decisions must agree because both scan the same
    (summed) histograms."""

    def _data(self, n=20_003, f=6, seed=3):
        rng = np.random.RandomState(seed)
        X = rng.randn(n, f).astype(np.float32)
        y = ((X[:, 0] - 0.4 * X[:, 2] + 0.3 * rng.randn(n)) > 0).astype(
            np.float64)
        return X, y

    def test_matches_serial_compact(self):
        X, y = self._data()                    # n % 8 != 0: pad rows live
        base = _params(objective="binary", tpu_grower="compact",
                       num_leaves=31)
        b_ser = lgb.train(dict(base), lgb.Dataset(X, label=y), 6)
        b_mesh = lgb.train(dict(base, tree_learner="data"),
                           lgb.Dataset(X, label=y), 6)
        assert b_mesh._gbdt.mesh is not None
        assert b_mesh._gbdt._use_compact
        d = np.abs(b_ser.predict(X) - b_mesh.predict(X)).max()
        assert d < 1e-4                        # psum reassociation only

    def test_bagging_and_eval(self):
        X, y = self._data(12_007)
        params = _params(objective="binary", metric="auc",
                         tpu_grower="compact", tree_learner="data",
                         bagging_fraction=0.6, bagging_freq=1)
        bst = lgb.Booster(params, lgb.Dataset(X, label=y))
        for _ in range(5):
            bst.update()
        (_, name, val, _), = bst.eval_train()
        assert name == "auc" and val > 0.9

    def test_multiclass(self):
        X, _ = self._data(9_000)
        y3 = np.digitize(X[:, 1], [-0.4, 0.6]).astype(np.float64)
        bst = lgb.train(_params(objective="multiclass", num_class=3,
                                tpu_grower="compact", tree_learner="data",
                                num_leaves=15),
                        lgb.Dataset(X, label=y3), 4)
        acc = (bst.predict(X).argmax(1) == y3).mean()
        assert acc > 0.97

    def test_fused_kernel_under_mesh_interpret(self):
        # the Mosaic kernel inside shard_map, in Pallas interpret mode —
        # validates the multi-chip fused path without multi-chip hardware
        X, y = self._data(4_099, seed=9)
        base = _params(objective="binary", tpu_grower="compact",
                       num_leaves=15)
        b_ref = lgb.train(dict(base, tree_learner="data"),
                          lgb.Dataset(X, label=y), 3)
        b_fus = lgb.train(dict(base, tree_learner="data", tpu_fused="on",
                               tpu_fused_interpret=True, tpu_fused_block=128),
                          lgb.Dataset(X, label=y), 3)
        d = np.abs(b_ref.predict(X) - b_fus.predict(X)).max()
        assert d < 2e-3                        # hi/lo-bf16 histogram split
