"""End-to-end training tests across objectives and training features.

Mirrors the reference's main correctness net
(reference: tests/python_package_test/test_engine.py — metric-threshold
assertions per objective, early stopping, bagging, DART/RF modes, model
reload equality).
"""
import numpy as np
import pytest
from sklearn.metrics import log_loss, mean_squared_error, roc_auc_score

import lightgbm_tpu as lgb

from utils import (FAST_PARAMS, binary_data, make_ranking, multiclass_data,
                   regression_data, train_test_split_simple)


def _params(**kw):
    p = dict(FAST_PARAMS)
    p.update(kw)
    return p


def test_binary(rng):
    X, y = binary_data()
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    ds = lgb.Dataset(Xtr, label=ytr)
    bst = lgb.train(_params(objective="binary", metric="binary_logloss"),
                    ds, num_boost_round=40)
    p = bst.predict(Xte)
    assert roc_auc_score(yte, p) > 0.93
    assert log_loss(yte, p) < 0.35
    # predictions are probabilities
    assert p.min() >= 0 and p.max() <= 1


def test_binary_early_stopping(rng):
    X, y = binary_data()
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    ds = lgb.Dataset(Xtr, label=ytr)
    dv = ds.create_valid(Xte, label=yte)
    bst = lgb.train(_params(objective="binary"), ds, num_boost_round=100,
                    valid_sets=[dv],
                    callbacks=[lgb.early_stopping(5, verbose=False)])
    assert bst.best_iteration > 0
    assert bst.current_iteration() <= 100


def test_regression(rng):
    X, y = regression_data()
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    bst = lgb.train(_params(objective="regression"),
                    lgb.Dataset(Xtr, label=ytr), 60)
    p = bst.predict(Xte)
    base = mean_squared_error(yte, np.full_like(yte, ytr.mean()))
    assert mean_squared_error(yte, p) < base * 0.35


@pytest.mark.parametrize("objective", ["regression_l1", "huber", "fair",
                                       "quantile", "mape"])
def test_robust_regression_objectives(objective):
    X, y = regression_data()
    # standardize: fair/huber gradients are capped at ~alpha, so raw labels
    # spanning hundreds would need hundreds of iterations (same as reference)
    y = y / y.std()
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    bst = lgb.train(_params(objective=objective), lgb.Dataset(Xtr, label=ytr), 40)
    p = bst.predict(Xte)
    # sanity: beats the constant-median predictor on MAE
    base = np.abs(yte - np.median(ytr)).mean()
    if objective == "quantile":
        return  # quantile predicts the 0.9 quantile, MAE not comparable
    assert np.abs(yte - p).mean() < base


@pytest.mark.parametrize("objective", ["poisson", "gamma", "tweedie"])
def test_positive_regression_objectives(objective):
    X, y = regression_data()
    y = np.abs(y) + 1.0
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    bst = lgb.train(_params(objective=objective), lgb.Dataset(Xtr, label=ytr), 40)
    p = bst.predict(Xte)
    assert np.all(p > 0)
    base = mean_squared_error(yte, np.full_like(yte, ytr.mean()))
    assert mean_squared_error(yte, p) < base


def test_multiclass(rng):
    X, y = multiclass_data()
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    bst = lgb.train(_params(objective="multiclass", num_class=3),
                    lgb.Dataset(Xtr, label=ytr), 30)
    p = bst.predict(Xte)
    assert p.shape == (len(yte), 3)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-5)
    assert (p.argmax(1) == yte).mean() > 0.85


def test_multiclassova(rng):
    X, y = multiclass_data()
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    bst = lgb.train(_params(objective="multiclassova", num_class=3),
                    lgb.Dataset(Xtr, label=ytr), 30)
    p = bst.predict(Xte)
    assert p.shape == (len(yte), 3)
    assert (p.argmax(1) == yte).mean() > 0.85


def test_cross_entropy(rng):
    X, y = binary_data()
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    bst = lgb.train(_params(objective="cross_entropy"),
                    lgb.Dataset(Xtr, label=ytr), 40)
    p = bst.predict(Xte)
    assert roc_auc_score(yte, p) > 0.9


def test_lambdarank():
    X, y, group = make_ranking()
    ds = lgb.Dataset(X, label=y, group=group)
    bst = lgb.train(
        _params(objective="lambdarank", metric="ndcg", eval_at=[5],
                min_data_in_leaf=2),
        ds, 30, valid_sets=[ds], valid_names=["train"])
    assert "train" in bst.best_score
    ndcg = bst.best_score["train"]["ndcg@5"]
    assert ndcg > 0.75


def test_rank_xendcg():
    X, y, group = make_ranking()
    ds = lgb.Dataset(X, label=y, group=group)
    bst = lgb.train(
        _params(objective="rank_xendcg", metric="ndcg", eval_at=[5],
                min_data_in_leaf=2),
        ds, 30, valid_sets=[ds], valid_names=["train"])
    assert bst.best_score["train"]["ndcg@5"] > 0.7


def test_bagging_and_feature_fraction(rng):
    X, y = binary_data()
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    bst = lgb.train(
        _params(objective="binary", bagging_fraction=0.6, bagging_freq=1,
                feature_fraction=0.7),
        lgb.Dataset(Xtr, label=ytr), 40)
    assert roc_auc_score(yte, bst.predict(Xte)) > 0.9


def test_goss(rng):
    X, y = binary_data()
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    bst = lgb.train(
        _params(objective="binary", data_sample_strategy="goss",
                learning_rate=0.15),
        lgb.Dataset(Xtr, label=ytr), 40)
    assert roc_auc_score(yte, bst.predict(Xte)) > 0.9


def test_dart(rng):
    X, y = binary_data()
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    bst = lgb.train(_params(objective="binary", boosting="dart"),
                    lgb.Dataset(Xtr, label=ytr), 30)
    assert roc_auc_score(yte, bst.predict(Xte)) > 0.9


def test_rf(rng):
    X, y = binary_data()
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    bst = lgb.train(
        _params(objective="binary", boosting="rf", bagging_fraction=0.7,
                bagging_freq=1),
        lgb.Dataset(Xtr, label=ytr), 25)
    p = bst.predict(Xte)
    assert roc_auc_score(yte, p) > 0.9
    # RF output is an average of per-tree probabilities-ish scores
    assert p.min() >= 0 and p.max() <= 1


def test_weights_change_model(rng):
    X, y = binary_data()
    w = np.where(y > 0, 5.0, 1.0)
    b1 = lgb.train(_params(objective="binary"), lgb.Dataset(X, label=y), 10)
    b2 = lgb.train(_params(objective="binary"),
                   lgb.Dataset(X, label=y, weight=w), 10)
    assert not np.allclose(b1.predict(X), b2.predict(X))


def test_custom_objective(rng):
    X, y = regression_data()
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)

    def l2_obj(preds, dataset):
        label = np.asarray(dataset.get_label())
        return preds - label, np.ones_like(preds)

    p = _params(objective=l2_obj, metric="l2")
    bst = lgb.train(p, lgb.Dataset(Xtr, label=ytr), 50)
    pred = bst.predict(Xte)
    base = mean_squared_error(yte, np.full_like(yte, ytr.mean()))
    assert mean_squared_error(yte, pred) < base * 0.5


def test_reset_parameter_callback(rng):
    X, y = binary_data()
    lrs = [0.2] * 5 + [0.05] * 5
    bst = lgb.train(
        _params(objective="binary"), lgb.Dataset(X, label=y), 10,
        callbacks=[lgb.reset_parameter(learning_rate=lrs)])
    shrinks = [m.shrinkage for m in bst._gbdt.models]
    assert shrinks[0] == pytest.approx(0.2)
    assert shrinks[-1] == pytest.approx(0.05)


def test_record_evaluation(rng):
    X, y = binary_data()
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    ds = lgb.Dataset(Xtr, label=ytr)
    dv = ds.create_valid(Xte, label=yte)
    hist = {}
    lgb.train(_params(objective="binary", metric="binary_logloss"), ds, 10,
              valid_sets=[dv], callbacks=[lgb.record_evaluation(hist)])
    assert len(hist["valid_0"]["binary_logloss"]) == 10
    # loss decreases over training
    assert hist["valid_0"]["binary_logloss"][-1] < \
        hist["valid_0"]["binary_logloss"][0]


def test_rollback_one_iter(rng):
    X, y = binary_data()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(_params(objective="binary"), ds)
    for _ in range(5):
        bst.update()
    p5 = bst.predict(X)
    bst.update()
    bst.rollback_one_iter()
    np.testing.assert_allclose(bst.predict(X), p5, rtol=1e-6)


def test_missing_values(rng):
    X, y = binary_data()
    X = X.copy()
    X[rng.rand(*X.shape) < 0.15] = np.nan
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    bst = lgb.train(_params(objective="binary"), lgb.Dataset(Xtr, label=ytr), 40)
    p = bst.predict(Xte)
    assert roc_auc_score(yte, p) > 0.85


def test_categorical_features(rng):
    n = 800
    cat = rng.randint(0, 5, n).astype(np.float64)
    noise = rng.randn(n)
    y = (cat >= 3).astype(np.float64)
    X = np.stack([cat, noise], axis=1)
    bst = lgb.train(
        _params(objective="binary", min_data_in_leaf=2),
        lgb.Dataset(X, label=y, categorical_feature=[0]), 20)
    p = bst.predict(X)
    assert roc_auc_score(y, p) > 0.99


def test_cv(rng):
    X, y = binary_data(n=402)
    res = lgb.cv(_params(objective="binary", metric="binary_logloss"),
                 lgb.Dataset(X, label=y, free_raw_data=False),
                 num_boost_round=10, nfold=3)
    assert "valid binary_logloss-mean" in res
    # per-iteration curves, one entry per boosting round (reference contract:
    # engine.py:611 — len(results[...]) is used to pick num_boost_round)
    assert len(res["valid binary_logloss-mean"]) == 10
    assert len(res["valid binary_logloss-stdv"]) == 10
    curve = res["valid binary_logloss-mean"]
    assert curve[-1] < 0.69  # better than chance
    assert curve[-1] < curve[0]  # loss decreases over iterations


def test_cv_early_stopping_and_callback_reuse(rng):
    """Early stopping acts on the CV aggregate and truncates curves; a single
    early_stopping callback object shared across train() calls re-inits its
    state each run (advisor finding: one-shot 'inited' flag)."""
    X, y = binary_data(n=402)
    res = lgb.cv(_params(objective="binary", metric="binary_logloss",
                         early_stopping_round=3),
                 lgb.Dataset(X, label=y, free_raw_data=False),
                 num_boost_round=200, nfold=3, return_cvbooster=True)
    cvb = res["cvbooster"]
    n_iters = len(res["valid binary_logloss-mean"])
    assert n_iters <= 200
    if cvb.best_iteration > 0:  # stopped early: curves truncated to best
        assert n_iters == cvb.best_iteration

    # reuse one callback object across two train() runs
    cb = lgb.early_stopping(2, verbose=False)
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    for _ in range(2):
        ds = lgb.Dataset(Xtr, label=ytr)
        bst = lgb.train(_params(objective="binary"), ds, 50,
                        valid_sets=[ds.create_valid(Xte, label=yte)],
                        callbacks=[cb])
        # a stale fold-1 best_iter would make the second run stop instantly
        assert bst.best_iteration == 0 or bst.best_iteration > 1


def test_valid_set_scores_match_predict(rng):
    """Cached valid scores must equal fresh predictions — catches both the
    missing set_reference rebinning and the double init-score application."""
    X, y = binary_data()
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    ds = lgb.Dataset(Xtr, label=ytr)
    # valid WITHOUT reference= (the reference API silently rebinds it)
    dv = lgb.Dataset(Xte, label=yte)
    bst = lgb.train(_params(objective="binary", metric="binary_logloss"),
                    ds, 5, valid_sets=[dv])
    vs = bst._gbdt.valid_sets[0]
    cached_raw = np.asarray(vs.score)[0][: vs.n_real]
    fresh_raw = bst.predict(Xte, raw_score=True)
    np.testing.assert_allclose(cached_raw, fresh_raw, rtol=1e-5, atol=1e-5)


def test_valid_constructed_and_freed_raises(rng):
    X, y = binary_data()
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    dv = lgb.Dataset(Xte, label=yte)
    dv.construct()  # binned with its own mappers, raw data freed
    with pytest.raises(ValueError, match="reference"):
        lgb.train(_params(objective="binary"), lgb.Dataset(Xtr, label=ytr),
                  3, valid_sets=[dv])


class TestCategoricalSplits:
    """Sorted many-category splits (reference:
    FindBestThresholdCategoricalInner, feature_histogram.cpp:144-339)."""

    def _cat_problem(self, n=1200, n_cats=12, seed=3):
        rng = np.random.RandomState(seed)
        cat = rng.randint(0, n_cats, size=n)
        # group half the categories as "high"; one-hot (single-category left)
        # cannot express this split, the sorted scan can
        high = np.isin(cat, [0, 3, 4, 7, 9, 11])
        noise = rng.randn(n)
        y = np.where(high, 3.0, -3.0) + 0.3 * noise
        X = np.column_stack([cat.astype(np.float64), rng.randn(n)])
        return X, y

    def test_sorted_beats_onehot(self):
        import lightgbm_tpu as lgb
        X, y = self._cat_problem()
        params = dict(FAST_PARAMS, objective="regression", num_leaves=4,
                      min_data_per_group=10, cat_smooth=2.0)
        ds = lgb.Dataset(X, label=y, categorical_feature=[0])
        bst = lgb.train(params, ds, 20)
        mse_sorted = float(np.mean((bst.predict(X) - y) ** 2))
        # crippled: force one-vs-rest by keeping max_cat_to_onehot high
        ds2 = lgb.Dataset(X, label=y, categorical_feature=[0])
        bst2 = lgb.train(dict(params, max_cat_to_onehot=64), ds2, 20)
        mse_onehot = float(np.mean((bst2.predict(X) - y) ** 2))
        assert mse_sorted < mse_onehot * 0.9
        assert mse_sorted < 1.0

    def test_multi_category_model_roundtrip(self, tmp_path):
        import lightgbm_tpu as lgb
        X, y = self._cat_problem()
        params = dict(FAST_PARAMS, objective="regression", num_leaves=4,
                      min_data_per_group=10, cat_smooth=2.0)
        ds = lgb.Dataset(X, label=y, categorical_feature=[0])
        bst = lgb.train(params, ds, 10)
        text = bst.model_to_string()
        # at least one multi-category bitset split was emitted
        assert "num_cat=" in text
        cat_lines = [l for l in text.splitlines()
                     if l.startswith("cat_threshold=")]
        assert cat_lines, "no categorical thresholds in model text"
        multi = any(bin(int(w)).count("1") > 1
                    for l in cat_lines for w in l.split("=")[1].split())
        assert multi, "expected a multi-category (sorted) split"
        p0 = bst.predict(X)
        loaded = lgb.Booster(model_str=text)
        np.testing.assert_allclose(loaded.predict(X), p0, rtol=1e-5, atol=1e-6)

    def test_compact_grower_categorical_parity(self):
        import lightgbm_tpu as lgb
        X, y = self._cat_problem()
        base = dict(FAST_PARAMS, objective="regression", num_leaves=6,
                    min_data_per_group=10, cat_smooth=2.0,
                    tpu_part_block=128, tpu_hist_block=256)
        preds = {}
        for mode in ("masked", "compact"):
            ds = lgb.Dataset(X, label=y, categorical_feature=[0])
            bst = lgb.train(dict(base, tpu_grower=mode), ds, 10)
            preds[mode] = bst.predict(X)
        np.testing.assert_allclose(preds["compact"], preds["masked"],
                                   rtol=1e-4, atol=1e-5)


class TestConstraints:
    """Monotone/interaction constraints + per-node sampling (reference:
    monotone_constraints.hpp BasicLeafConstraints, col_sampler.hpp)."""

    def _mono_problem(self, seed=0, n=2000):
        rng = np.random.RandomState(seed)
        x0 = rng.rand(n)
        X = np.column_stack([x0, rng.randn(n)])
        y = 2 * x0 + 0.5 * np.sin(8 * x0) + 0.1 * rng.randn(n)
        return X, y

    @pytest.mark.parametrize("grower", ["masked", "compact"])
    def test_monotone_increasing(self, grower):
        import lightgbm_tpu as lgb
        X, y = self._mono_problem()
        params = {"objective": "regression", "num_leaves": 31,
                  "verbosity": -1, "monotone_constraints": [1, 0],
                  "min_data_in_leaf": 5, "tpu_grower": grower,
                  "tpu_part_block": 128, "tpu_hist_block": 256}
        bst = lgb.train(params, lgb.Dataset(X, label=y), 40)
        grid = np.column_stack([np.linspace(0, 1, 200), np.zeros(200)])
        p = bst.predict(grid)
        assert (np.diff(p) >= -1e-9).all()
        # constrained model still fits the monotone trend
        assert np.corrcoef(p, grid[:, 0])[0, 1] > 0.8

    def test_monotone_decreasing(self):
        import lightgbm_tpu as lgb
        X, y = self._mono_problem()
        params = {"objective": "regression", "num_leaves": 31,
                  "verbosity": -1, "monotone_constraints": "-1,0",
                  "min_data_in_leaf": 5}
        bst = lgb.train(params, lgb.Dataset(X, label=-y), 40)
        grid = np.column_stack([np.linspace(0, 1, 200), np.zeros(200)])
        assert (np.diff(bst.predict(grid)) <= 1e-9).all()

    def test_monotone_intermediate(self):
        # intermediate method (reference: IntermediateLeafConstraints,
        # monotone_constraints.hpp:516): sibling-output bounds + the
        # contiguous-leaf walk must keep monotonicity while fitting better
        # than the conservative basic method
        import lightgbm_tpu as lgb
        rng = np.random.RandomState(0)
        n = 8000
        X = rng.randn(n, 4).astype(np.float32)
        y = (2.0 * X[:, 0] - 1.5 * X[:, 1]
             + 0.5 * np.sin(3 * X[:, 2]) + 0.3 * rng.randn(n))
        base = {"objective": "regression", "verbosity": -1,
                "num_leaves": 31, "tpu_grower": "compact",
                "monotone_constraints": [1, -1, 0, 0],
                "min_data_in_leaf": 20}
        mse = {}
        for meth in ("basic", "intermediate"):
            bst = lgb.train(dict(base, monotone_constraints_method=meth),
                            lgb.Dataset(X, label=y), 25)
            probe = np.tile(X[:40], (21, 1, 1))
            sweep = np.linspace(-3, 3, 21)
            for f, sign in ((0, 1), (1, -1)):
                pv = probe.copy()
                pv[:, :, f] = sweep[:, None]
                pr = bst.predict(pv.reshape(-1, 4)).reshape(21, 40)
                assert (sign * np.diff(pr, axis=0) >= -1e-9).all(), \
                    (meth, f)
            mse[meth] = float(np.mean((bst.predict(X) - y) ** 2))
        # the whole point of the intermediate method: tighter-but-valid
        # bounds recover accuracy the basic method gives up
        assert mse["intermediate"] <= mse["basic"] + 1e-9, mse

    def test_interaction_constraints(self):
        import lightgbm_tpu as lgb
        from tests.utils import FAST_PARAMS, regression_data
        X, y = regression_data()
        params = dict(FAST_PARAMS, objective="regression",
                      interaction_constraints=[[0, 1, 2], [3, 4, 5, 6]])
        bst = lgb.train(params, lgb.Dataset(X, label=y), 15)
        # every tree's features must come from a single constraint group
        dumped = bst.dump_model()
        groups = [{0, 1, 2}, {3, 4, 5, 6}]

        def tree_feats(node, acc):
            if "split_feature" in node:
                acc.add(node["split_feature"])
                tree_feats(node["left_child"], acc)
                tree_feats(node["right_child"], acc)
            return acc

        for t in dumped["tree_info"]:
            feats = tree_feats(t["tree_structure"], set())
            assert any(feats <= g for g in groups), feats

    def test_feature_fraction_bynode_and_path_smooth(self):
        import lightgbm_tpu as lgb
        from tests.utils import FAST_PARAMS, regression_data
        X, y = regression_data()
        params = dict(FAST_PARAMS, objective="regression",
                      feature_fraction_bynode=0.5, path_smooth=10.0)
        bst = lgb.train(params, lgb.Dataset(X, label=y), 15)
        mse = float(np.mean((bst.predict(X) - y) ** 2))
        assert mse < np.var(y)  # learns something under both knobs

    def test_rf_with_interaction_constraints(self):
        # regression test: RF must forward constraint args to the grower
        import lightgbm_tpu as lgb
        from tests.utils import FAST_PARAMS, regression_data
        X, y = regression_data()
        params = dict(FAST_PARAMS, objective="regression", boosting="rf",
                      bagging_fraction=0.7, bagging_freq=1,
                      interaction_constraints=[[0, 1, 2], [3, 4, 5, 6]])
        bst = lgb.train(params, lgb.Dataset(X, label=y), 10)
        pred = bst.predict(X)
        assert float(np.std(pred)) > 1e-3  # not an all-stump forest

    def test_custom_feval_on_train_with_compact(self):
        # regression test: feval sees original-order train predictions
        import lightgbm_tpu as lgb
        from tests.utils import FAST_PARAMS, binary_data
        X, y = binary_data()

        def acc(preds, data):
            lbl = data.get_label()
            return "acc", float(((preds > 0) == (lbl > 0)).mean()), True

        results = {}
        for mode in ("masked", "compact"):
            ds = lgb.Dataset(X, label=y)
            rec = {}
            bst = lgb.train(
                dict(FAST_PARAMS, objective="binary", tpu_grower=mode,
                     tpu_part_block=128, tpu_hist_block=256, metric="None"),
                ds, 15, valid_sets=[ds], valid_names=["train"], feval=acc,
                callbacks=[lgb.record_evaluation(rec)])
            results[mode] = rec["train"]["acc"][-1]
        assert results["compact"] > 0.9
        assert abs(results["compact"] - results["masked"]) < 0.05


class TestRankingScale:
    def test_lambdarank_large_queries(self):
        """MS-LTR-shaped queries (1000 docs) must train without a [Q,M,M]
        pair tensor (reference device design: cuda_rank_objective.cu)."""
        import lightgbm_tpu as lgb
        rng = np.random.RandomState(0)
        n_q, m = 12, 1000
        n = n_q * m
        X = rng.randn(n, 6)
        w = rng.randn(6)
        rel_score = X @ w + 0.8 * rng.randn(n)
        y = np.zeros(n)
        for q in range(n_q):
            sl = slice(q * m, (q + 1) * m)
            r = np.argsort(np.argsort(rel_score[sl]))
            y[sl] = np.where(r >= m - 10, 2, np.where(r >= m - 100, 1, 0))
        ds = lgb.Dataset(X, label=y, group=np.full(n_q, m))
        params = dict(objective="lambdarank", metric="ndcg", eval_at=[10],
                      num_leaves=15, min_data_in_leaf=5, verbosity=-1,
                      max_bin=63)
        rec = {}
        bst = lgb.train(params, ds, 10, valid_sets=[ds], valid_names=["t"],
                        callbacks=[lgb.record_evaluation(rec)])
        ndcg = rec["t"]["ndcg@10"]
        assert ndcg[-1] > 0.45
        assert ndcg[-1] > ndcg[0]

    def test_lambdarank_quality_unchanged_after_rewrite(self):
        """Bounded-pair rewrite must match the reference's enumeration
        semantics: NDCG on the standard small ranking set stays strong."""
        import lightgbm_tpu as lgb
        from tests.utils import make_ranking
        X, y, group = make_ranking()
        ds = lgb.Dataset(X, label=y, group=group)
        rec = {}
        bst = lgb.train(dict(objective="lambdarank", metric="ndcg",
                             eval_at=[5], num_leaves=15, min_data_in_leaf=5,
                             verbosity=-1, max_bin=31),
                        ds, 30, valid_sets=[ds], valid_names=["t"],
                        callbacks=[lgb.record_evaluation(rec)])
        assert rec["t"]["ndcg@5"][-1] > 0.9


class TestCEGB:
    """Cost-effective gradient boosting (reference:
    cost_effective_gradient_boosting.hpp)."""

    def test_coupled_penalty_limits_features(self):
        import lightgbm_tpu as lgb
        from tests.utils import FAST_PARAMS, regression_data
        X, y = regression_data()
        base = dict(FAST_PARAMS, objective="regression")
        plain = lgb.train(base, lgb.Dataset(X, label=y), 10)
        pen = lgb.train(dict(base, cegb_tradeoff=1.0,
                             cegb_penalty_feature_coupled=[1e5] * X.shape[1]),
                        lgb.Dataset(X, label=y), 10)

        def nfeat(bst):
            return len(set(int(f) for m in bst._gbdt.models
                           for f in m.split_feature[:m.num_nodes]))

        assert nfeat(pen) < nfeat(plain)
        # still learns with the features it pays for
        assert np.mean((pen.predict(X) - y) ** 2) < np.var(y)

    def test_lazy_penalty_charges_rows_once(self):
        # reference: CalculateOndemandCosts / feature_used_in_data_,
        # cost_effective_gradient_boosting.hpp:139,125 — per-(row, feature)
        # costs paid once; heavy penalties concentrate the model on free
        # features, near-zero penalties change nothing
        import lightgbm_tpu as lgb
        rng = np.random.RandomState(0)
        n = 5000
        X = rng.randn(n, 6).astype(np.float32)
        y = (X[:, 0] + 0.8 * X[:, 1] + 0.5 * X[:, 2]
             + 0.5 * rng.randn(n) > 0).astype(np.float64)
        base = {"objective": "binary", "verbosity": -1, "num_leaves": 15,
                "min_data_in_leaf": 20}

        def nfeat(bst):
            return int((bst.feature_importance(
                importance_type="split") > 0).sum())

        plain = lgb.train(base, lgb.Dataset(X, label=y), 8)
        pen = lgb.train(dict(base, cegb_tradeoff=1.0,
                             cegb_penalty_feature_lazy=[0.0] + [5.0] * 5),
                        lgb.Dataset(X, label=y), 8)
        assert nfeat(pen) < nfeat(plain)
        tiny = lgb.train(dict(base, cegb_tradeoff=1.0,
                              cegb_penalty_feature_lazy=[1e-9] * 6),
                         lgb.Dataset(X, label=y), 8)
        np.testing.assert_allclose(tiny.predict(X), plain.predict(X),
                                   atol=1e-5)

    def test_split_penalty_prunes(self):
        import lightgbm_tpu as lgb
        from tests.utils import FAST_PARAMS, regression_data
        X, y = regression_data()
        base = dict(FAST_PARAMS, objective="regression")
        plain = lgb.train(base, lgb.Dataset(X, label=y), 10)
        pen = lgb.train(dict(base, cegb_penalty_split=1e4),
                        lgb.Dataset(X, label=y), 10)
        assert sum(m.num_nodes for m in pen._gbdt.models) < \
            sum(m.num_nodes for m in plain._gbdt.models)


class TestQuantizedTraining:
    """use_quantized_grad (reference: gradient_discretizer.cpp)."""

    @pytest.mark.parametrize("grower", ["masked", "compact"])
    def test_quantized_matches_quality(self, grower):
        import lightgbm_tpu as lgb
        from sklearn.metrics import roc_auc_score
        from tests.utils import FAST_PARAMS, binary_data, \
            train_test_split_simple
        X, y = binary_data()
        Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
        base = dict(FAST_PARAMS, objective="binary", tpu_grower=grower,
                    tpu_part_block=128, tpu_hist_block=256)
        full = lgb.train(base, lgb.Dataset(Xtr, label=ytr), 25)
        quant = lgb.train(dict(base, use_quantized_grad=True),
                          lgb.Dataset(Xtr, label=ytr), 25)
        a_full = roc_auc_score(yte, full.predict(Xte))
        a_quant = roc_auc_score(yte, quant.predict(Xte))
        assert a_quant > a_full - 0.02            # coarse grads, close quality
        # quantization really happened: different trees
        assert not np.allclose(quant.predict(Xte), full.predict(Xte))

    def test_renew_leaf_is_newton_optimal(self):
        """With identical quantized growth, renewed leaf values are the true
        Newton outputs, so one full-step iteration cannot fit worse
        (reference: RenewIntGradTreeOutput)."""
        import lightgbm_tpu as lgb
        from tests.utils import FAST_PARAMS, regression_data
        X, y = regression_data()
        base = dict(FAST_PARAMS, objective="regression",
                    use_quantized_grad=True, num_grad_quant_bins=4,
                    learning_rate=1.0, boost_from_average=False)
        plain = lgb.train(base, lgb.Dataset(X, label=y), 1)
        renew = lgb.train(dict(base, quant_train_renew_leaf=True),
                          lgb.Dataset(X, label=y), 1)
        tq, tr = plain._gbdt.models[0], renew._gbdt.models[0]
        np.testing.assert_array_equal(tq.split_feature, tr.split_feature)
        assert not np.allclose(tq.leaf_value, tr.leaf_value)
        mse_plain = float(np.mean((plain.predict(X) - y) ** 2))
        mse_renew = float(np.mean((renew.predict(X) - y) ** 2))
        assert mse_renew <= mse_plain + 1e-6


class TestLinearTrees:
    """linear_tree=true (reference: linear_tree_learner.cpp)."""

    def _linear_problem(self, seed=0, n=1500):
        rng = np.random.RandomState(seed)
        X = rng.rand(n, 3) * 4
        seg = (X[:, 0] > 2).astype(float)
        y = np.where(seg > 0, 3.0 * X[:, 1] + 1.0, -2.0 * X[:, 1] + 5.0) \
            + 0.05 * rng.randn(n)
        return X, y

    def test_linear_beats_constant_leaves(self):
        import lightgbm_tpu as lgb
        X, y = self._linear_problem()
        params = dict(objective="regression", num_leaves=4, max_bin=31,
                      min_data_in_leaf=20, verbosity=-1, learning_rate=0.5)
        const = lgb.train(params, lgb.Dataset(X, label=y), 20)
        lin = lgb.train(dict(params, linear_tree=True),
                        lgb.Dataset(X, label=y), 20)
        mse_const = float(np.mean((const.predict(X) - y) ** 2))
        mse_lin = float(np.mean((lin.predict(X) - y) ** 2))
        assert mse_lin < mse_const * 0.7   # piecewise-linear target
        assert mse_lin < 0.5  # leaves only use path features (ref behavior)

    def test_linear_model_roundtrip_and_nan_fallback(self):
        import lightgbm_tpu as lgb
        X, y = self._linear_problem()
        lin = lgb.train(dict(objective="regression", num_leaves=4, max_bin=31,
                             min_data_in_leaf=20, verbosity=-1,
                             linear_tree=True, learning_rate=0.5),
                        lgb.Dataset(X, label=y), 10)
        text = lin.model_to_string()
        assert "is_linear=1" in text and "leaf_coeff=" in text
        loaded = lgb.Booster(model_str=text)
        np.testing.assert_allclose(loaded.predict(X), lin.predict(X),
                                   rtol=1e-4, atol=1e-5)
        # NaN in a linear feature falls back to the constant leaf value
        Xn = X.copy()
        Xn[:5, 1] = np.nan
        p = lin.predict(Xn)
        assert np.isfinite(p).all()

    def test_linear_with_valid_early_stopping(self):
        import lightgbm_tpu as lgb
        X, y = self._linear_problem()
        ds = lgb.Dataset(X[:1000], label=y[:1000], params={"linear_tree": True})
        dv = ds.create_valid(X[1000:], label=y[1000:])
        bst = lgb.train(dict(objective="regression", metric="l2",
                             num_leaves=4, max_bin=31, min_data_in_leaf=20,
                             verbosity=-1, linear_tree=True),
                        ds, 30, valid_sets=[dv],
                        callbacks=[lgb.early_stopping(5, verbose=False)])
        mse = float(np.mean((bst.predict(X[1000:]) - y[1000:]) ** 2))
        assert mse < 2.0


class TestMiscTreeKnobs:
    def test_extra_trees_randomizes_thresholds(self):
        import lightgbm_tpu as lgb
        from tests.utils import FAST_PARAMS, binary_data
        from sklearn.metrics import roc_auc_score
        X, y = binary_data()
        base = dict(FAST_PARAMS, objective="binary")
        plain = lgb.train(base, lgb.Dataset(X, label=y), 15)
        et = lgb.train(dict(base, extra_trees=True), lgb.Dataset(X, label=y), 15)
        assert not np.allclose(et.predict(X), plain.predict(X))
        assert roc_auc_score(y, et.predict(X)) > 0.9

    def test_feature_contri_discourages_feature(self):
        import lightgbm_tpu as lgb
        from tests.utils import FAST_PARAMS, binary_data
        X, y = binary_data()
        base = dict(FAST_PARAMS, objective="binary")
        plain = lgb.train(base, lgb.Dataset(X, label=y), 15)
        imp = plain.feature_importance("split")
        top = int(np.argmax(imp))
        contri = [1.0] * X.shape[1]
        contri[top] = 0.01
        pen = lgb.train(dict(base, feature_contri=contri),
                        lgb.Dataset(X, label=y), 15)
        assert pen.feature_importance("split")[top] < imp[top]

    def test_forced_bins_and_max_bin_by_feature(self, tmp_path):
        import json
        import lightgbm_tpu as lgb
        rng = np.random.RandomState(0)
        X = rng.rand(500, 2) * 10
        y = (X[:, 0] > 3.3333).astype(float)
        fb = tmp_path / "forced.json"
        fb.write_text(json.dumps(
            [{"feature": 0, "bin_upper_bound": [3.3333]}]))
        ds = lgb.Dataset(X, label=y,
                         params={"forcedbins_filename": str(fb),
                                 "max_bin_by_feature": [16, 4]})
        ds.construct()
        m0, m1 = ds._inner.mappers
        assert np.any(np.isclose(m0.bin_upper_bounds, 3.3333))
        assert m1.num_bins <= 5
        bst = lgb.train({"objective": "binary", "verbosity": -1,
                         "num_leaves": 4, "min_data_in_leaf": 5,
                         "forcedbins_filename": str(fb)}, ds, 5)
        assert ((bst.predict(X) > 0.5) == y).mean() > 0.99


class TestPositionBias:
    def test_lambdarank_position_bias_learns(self):
        """Position-bias correction (reference: rank_objective.hpp
        pos_biases_ / UpdatePositionBiasFactors)."""
        import lightgbm_tpu as lgb
        rng = np.random.RandomState(0)
        n_q, m = 60, 10
        n = n_q * m
        X = rng.randn(n, 5)
        w = rng.randn(5)
        true_rel = (X @ w > 0.5).astype(float)
        # clicks biased by display position: early positions over-labeled
        pos = np.tile(np.arange(m), n_q)
        click_prob = np.clip(0.4 * true_rel + 0.5 / (1 + pos), 0, 1)
        y = (rng.rand(n) < click_prob).astype(float)
        ds = lgb.Dataset(X, label=y, group=np.full(n_q, m), position=pos)
        bst = lgb.train(dict(objective="lambdarank", verbosity=-1,
                             num_leaves=15, min_data_in_leaf=5, max_bin=31,
                             lambdarank_position_bias_regularization=0.001),
                        ds, 15)
        biases = np.asarray(bst._gbdt.objective.pos_biases)
        assert np.isfinite(biases).all()
        assert np.abs(biases).max() > 1e-3          # something was learned
        # earlier positions absorb larger (more positive) bias than later
        assert biases[0] > biases[-1]
        assert np.isfinite(bst.predict(X)).all()


class TestForcedSplits:
    def test_forced_tree_prefix(self, tmp_path):
        """forcedsplits_filename dictates the first splits (reference:
        SerialTreeLearner::ForceSplits)."""
        import json
        import lightgbm_tpu as lgb
        from tests.utils import FAST_PARAMS, binary_data
        X, y = binary_data()
        fs = tmp_path / "forced.json"
        fs.write_text(json.dumps({
            "feature": 3, "threshold": 0.0,
            "left": {"feature": 5, "threshold": 0.5},
        }))
        bst = lgb.train(dict(FAST_PARAMS, objective="binary",
                             forcedsplits_filename=str(fs)),
                        lgb.Dataset(X, label=y), 8)
        d = bst.dump_model()
        for t in d["tree_info"]:
            root = t["tree_structure"]
            assert root["split_feature"] == 3
            assert root["left_child"].get("split_feature") == 5
        from sklearn.metrics import roc_auc_score
        assert roc_auc_score(y, bst.predict(X)) > 0.9
