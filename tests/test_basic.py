"""Dataset construction + Booster lifecycle + model IO tests.

Mirrors the reference's tests/python_package_test/test_basic.py (Dataset
paths, field get/set, save/load equality) and the C++ serialization
round-trip test (tests/cpp_tests/test_serialize.cpp).
"""
import numpy as np
import pytest
from sklearn.metrics import roc_auc_score

import lightgbm_tpu as lgb
from lightgbm_tpu.io.binning import find_bin_numerical, find_bin_categorical

from utils import FAST_PARAMS, binary_data, multiclass_data, \
    train_test_split_simple


def _params(**kw):
    p = dict(FAST_PARAMS)
    p.update(kw)
    return p


class TestBinning:
    def test_simple_numerical(self):
        vals = np.concatenate([np.zeros(50), np.arange(1, 101)])
        m = find_bin_numerical(vals, len(vals), max_bin=16)
        bins = m.value_to_bin(vals)
        assert bins.max() < m.num_bins
        # zero gets its own bin
        zero_bin = m.value_to_bin(np.array([0.0]))[0]
        small_bin = m.value_to_bin(np.array([1.0]))[0]
        assert zero_bin != small_bin
        # monotonic: larger values -> same or larger bins
        v = np.sort(vals)
        b = m.value_to_bin(v)
        assert np.all(np.diff(b) >= 0)

    def test_nan_gets_last_bin(self):
        vals = np.concatenate([np.arange(100.0), [np.nan] * 10])
        m = find_bin_numerical(vals, len(vals), max_bin=16)
        assert m.missing_type == 2  # MISSING_NAN
        nb = m.value_to_bin(np.array([np.nan]))[0]
        assert nb == m.num_bins - 1

    def test_low_cardinality_exact(self):
        vals = np.repeat([1.0, 2.0, 3.0], 50)
        m = find_bin_numerical(vals, len(vals), max_bin=16, min_data_in_bin=3)
        b = m.value_to_bin(np.array([1.0, 2.0, 3.0]))
        assert len(set(b.tolist())) == 3  # each value its own bin

    def test_categorical(self):
        vals = np.array([3.0] * 50 + [7.0] * 30 + [1.0] * 20)
        m = find_bin_categorical(vals, max_bin=16)
        b = m.value_to_bin(np.array([3.0, 7.0, 1.0, 99.0]))
        assert b[0] == 1  # most frequent first
        assert b[3] == 0  # unseen -> bin 0

    def test_trivial_constant_feature(self):
        m = find_bin_numerical(np.full(100, 5.0), 100, max_bin=16)
        # one distinct value -> still has a real bin structure or is trivial;
        # binning must not crash and must map consistently
        b = m.value_to_bin(np.array([5.0, 5.0]))
        assert b[0] == b[1]


class TestDataset:
    def test_fields(self):
        X, y = binary_data()
        w = np.random.RandomState(0).rand(len(y))
        ds = lgb.Dataset(X, label=y, weight=w)
        ds.construct()
        np.testing.assert_allclose(ds.get_label(), y, rtol=1e-6)
        np.testing.assert_allclose(ds.get_weight(), w, rtol=1e-6)
        assert ds.num_data() == len(y)
        assert ds.num_feature() == X.shape[1]

    def test_valid_shares_mappers(self):
        X, y = binary_data()
        ds = lgb.Dataset(X[:200], label=y[:200])
        dv = ds.create_valid(X[200:], label=y[200:])
        dv.construct()
        assert dv._inner.mappers is ds._inner.mappers

    def test_feature_names(self):
        X, y = binary_data()
        names = [f"feat{i}" for i in range(X.shape[1])]
        ds = lgb.Dataset(X, label=y, feature_name=names)
        assert ds.get_feature_name() == names

    def test_group_validation(self):
        X, y = binary_data()
        ds = lgb.Dataset(X, label=y, group=[300, 301])  # sums to 601 != 600
        with pytest.raises(ValueError):
            ds.construct()


class TestModelIO:
    def test_save_load_roundtrip(self, tmp_path):
        X, y = binary_data()
        Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
        bst = lgb.train(_params(objective="binary"),
                        lgb.Dataset(Xtr, label=ytr), 20)
        p1 = bst.predict(Xte)

        path = tmp_path / "model.txt"
        bst.save_model(str(path))
        bst2 = lgb.Booster(model_file=str(path))
        p2 = bst2.predict(Xte)
        np.testing.assert_allclose(p1, p2, rtol=1e-5, atol=1e-6)

    def test_roundtrip_with_nans(self, tmp_path):
        rng = np.random.RandomState(7)
        X, y = binary_data()
        X = X.copy()
        X[rng.rand(*X.shape) < 0.2] = np.nan
        bst = lgb.train(_params(objective="binary"), lgb.Dataset(X, label=y), 15)
        s = bst.model_to_string()
        bst2 = lgb.Booster(model_str=s)
        np.testing.assert_allclose(bst.predict(X), bst2.predict(X),
                                   rtol=1e-5, atol=1e-6)

    def test_roundtrip_multiclass(self):
        X, y = multiclass_data()
        bst = lgb.train(_params(objective="multiclass", num_class=3),
                        lgb.Dataset(X, label=y), 10)
        bst2 = lgb.Booster(model_str=bst.model_to_string())
        np.testing.assert_allclose(bst.predict(X), bst2.predict(X),
                                   rtol=1e-5, atol=1e-6)

    def test_roundtrip_categorical(self):
        rng = np.random.RandomState(3)
        n = 400
        cat = rng.randint(0, 6, n).astype(np.float64)
        y = (cat >= 3).astype(np.float64)
        X = np.stack([cat, rng.randn(n)], axis=1)
        bst = lgb.train(_params(objective="binary", min_data_in_leaf=2),
                        lgb.Dataset(X, label=y, categorical_feature=[0]), 10)
        bst2 = lgb.Booster(model_str=bst.model_to_string())
        np.testing.assert_allclose(bst.predict(X), bst2.predict(X),
                                   rtol=1e-5, atol=1e-6)

    def test_dump_model_json(self):
        X, y = binary_data()
        bst = lgb.train(_params(objective="binary"), lgb.Dataset(X, label=y), 5)
        d = bst.dump_model()
        assert d["num_class"] == 1
        assert len(d["tree_info"]) == 5
        root = d["tree_info"][0]["tree_structure"]
        assert "split_feature" in root or "leaf_value" in root

    def test_model_text_format_headers(self):
        X, y = binary_data()
        bst = lgb.train(_params(objective="binary"), lgb.Dataset(X, label=y), 3)
        s = bst.model_to_string()
        assert s.startswith("tree\n")
        assert "version=v4" in s
        assert "objective=binary" in s
        assert "Tree=0" in s and "Tree=2" in s
        assert "end of trees" in s


class TestBooster:
    def test_feature_importance(self):
        X, y = binary_data()
        bst = lgb.train(_params(objective="binary"), lgb.Dataset(X, label=y), 10)
        imp_split = bst.feature_importance("split")
        imp_gain = bst.feature_importance("gain")
        assert imp_split.sum() > 0
        assert imp_gain.sum() > 0
        assert len(imp_split) == X.shape[1]

    def test_pred_leaf(self):
        X, y = binary_data()
        bst = lgb.train(_params(objective="binary"), lgb.Dataset(X, label=y), 7)
        leaves = bst.predict(X, pred_leaf=True)
        assert leaves.shape == (len(y), 7)
        assert leaves.min() >= 0

    def test_raw_score(self):
        X, y = binary_data()
        bst = lgb.train(_params(objective="binary"), lgb.Dataset(X, label=y), 10)
        raw = bst.predict(X, raw_score=True)
        p = bst.predict(X)
        np.testing.assert_allclose(1 / (1 + np.exp(-raw)), p, rtol=1e-5)

    def test_num_trees(self):
        X, y = binary_data()
        bst = lgb.train(_params(objective="binary"), lgb.Dataset(X, label=y), 8)
        assert bst.num_trees() == 8
        assert bst.current_iteration() == 8
        assert bst.num_model_per_iteration() == 1


class TestConfigWarnings:
    """Accepted-but-unimplemented params must warn loudly, never be silent
    (VERDICT: silent divergence from reference models; the reference instead
    rejects inconsistent configs, src/io/config.cpp:286)."""

    def test_unimplemented_param_warns(self, caplog):
        import logging
        from lightgbm_tpu.config import Config
        from lightgbm_tpu.utils import log as _log
        _log.set_verbosity(1)  # earlier tests may have silenced warnings
        with caplog.at_level(logging.WARNING, logger="lightgbm_tpu"):
            Config({"pre_partition": True})
        text = caplog.text
        for name in ("pre_partition",):
            assert f"{name}=" in text and "NOT implemented" in text, \
                f"no warning for {name}: {text!r}"

    def test_default_values_do_not_warn(self, caplog):
        import logging
        from lightgbm_tpu.config import Config
        from lightgbm_tpu.utils import log as _log
        _log.set_verbosity(1)
        with caplog.at_level(logging.WARNING, logger="lightgbm_tpu"):
            Config({"num_leaves": 31, "linear_tree": False,
                    "snapshot_freq": -1})
        assert "NOT implemented" not in caplog.text

    def test_implemented_params_not_in_table(self):
        """Anything the training path actually consumes must not be listed."""
        from lightgbm_tpu.config import UNIMPLEMENTED_PARAMS
        for implemented in ("num_leaves", "learning_rate", "bagging_fraction",
                            "feature_fraction", "lambda_l1", "max_bin",
                            "is_unbalance", "tree_learner", "max_depth",
                            "two_round"):
            assert implemented not in UNIMPLEMENTED_PARAMS


class TestPredictionExtras:
    def test_start_iteration(self):
        X, y = binary_data()
        bst = lgb.train(_params(objective="binary"), lgb.Dataset(X, label=y), 10)
        full = bst.predict(X, raw_score=True)
        head = bst.predict(X, raw_score=True, num_iteration=4)
        tail = bst.predict(X, raw_score=True, start_iteration=4)
        np.testing.assert_allclose(head + tail, full, rtol=1e-5, atol=1e-6)

    def test_pred_contrib_sums_to_raw(self):
        X, y = binary_data(n=200)
        bst = lgb.train(_params(objective="binary"), lgb.Dataset(X, label=y), 5)
        contrib = bst.predict(X[:40], pred_contrib=True)
        assert contrib.shape == (40, X.shape[1] + 1)
        raw = bst.predict(X[:40], raw_score=True)
        # SHAP local accuracy: contributions + expected value == raw score
        np.testing.assert_allclose(contrib.sum(axis=1), raw,
                                   rtol=1e-4, atol=1e-4)
        # informative features dominate attributions
        imp = np.abs(contrib[:, :-1]).mean(0)
        assert imp.max() > 0

    def test_pred_contrib_model_only(self, tmp_path):
        # SHAP on a Booster(model_file=...) with no dataset attached: the
        # model-only raw-threshold path must agree with the trained-booster
        # bin-space path (reference computes contribs from tree arrays
        # alone, Tree::PredictContrib tree.h:668)
        X, y = binary_data(n=300)
        X = X.copy()
        X[::7, 0] = np.nan                      # exercise missing routing
        bst = lgb.train(_params(objective="binary"), lgb.Dataset(X, label=y), 6)
        want = bst.predict(X[:30], pred_contrib=True)
        path = tmp_path / "m.txt"
        bst.save_model(str(path))
        loaded = lgb.Booster(model_file=str(path))
        got = loaded.predict(X[:30], pred_contrib=True)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
        # local accuracy holds on the loaded path too
        raw = loaded.predict(X[:30], raw_score=True)
        np.testing.assert_allclose(got.sum(axis=1), raw, rtol=1e-4, atol=1e-4)

    def test_loaded_scalar_decision_matches_route(self):
        # decision_scalar (TreeSHAP) and route (predict) must agree node by
        # node on the same loaded model — pins the two implementations
        X, y = binary_data(n=400)
        X = X.copy()
        X[::5, 1] = np.nan
        bst = lgb.train(_params(objective="binary"), lgb.Dataset(X, label=y), 5)
        loaded = lgb.Booster(model_str=bst.model_to_string())
        for t in loaded._gbdt.models:
            leaves_vec = t.route(X[:60])
            for r in range(60):
                node = 0
                while node >= 0:
                    node = (t.left_child[node]
                            if t.decision_scalar(node, X[r])
                            else t.right_child[node])
                assert -(node + 1) == leaves_vec[r]

    def test_pred_contrib_single_row_and_efb(self):
        # 1-D input works on the model-only path, and EFB-bundled training
        # routes SHAP with ORIGINAL-space nan/cat arrays
        rng = np.random.RandomState(5)
        n, groups, card = 2000, 40, 8
        cats = rng.randint(0, card, size=(n, groups))
        X = np.zeros((n, groups * card), np.float32)
        for g in range(groups):
            X[np.arange(n), g * card + cats[:, g]] = 1.0
        w = rng.randn(X.shape[1]) * 0.5
        y = ((X @ w) > 0).astype(np.float64)
        # dense NaN-bearing passthrough features: their column index differs
        # from their original index under EFB, so routing with column-space
        # nan arrays would misattribute
        dense = rng.randn(n, 3).astype(np.float32)
        dense[::4] = np.nan
        X = np.concatenate([dense, X], axis=1)
        y = ((np.nan_to_num(dense[:, 0]) + X[:, 3:] @ w) > 0).astype(
            np.float64)
        ds = lgb.Dataset(X, label=y)
        bst = lgb.train(_params(objective="binary", num_leaves=15), ds, 4)
        assert ds._inner.bundle_info is not None      # EFB active
        contrib = bst.predict(X[:15], pred_contrib=True)
        raw = bst.predict(X[:15], raw_score=True)
        np.testing.assert_allclose(contrib.sum(axis=1), raw,
                                   rtol=1e-4, atol=1e-4)
        loaded = lgb.Booster(model_str=bst.model_to_string())
        one = loaded.predict(X[0], pred_contrib=True)     # 1-D input
        np.testing.assert_allclose(np.atleast_2d(one)[0], contrib[0],
                                   rtol=1e-4, atol=1e-4)

    def test_pred_contrib_linear_tree(self):
        # matches the reference: TreeSHAP attributes the constant leaf
        # outputs (leaf_value_), never the leaf coefficients (tree.cpp)
        X, y = binary_data(n=300)
        bst = lgb.train(_params(objective="regression", linear_tree=True),
                        lgb.Dataset(X, label=y.astype(np.float64)), 4)
        contrib = bst.predict(X[:20], pred_contrib=True)
        assert contrib.shape == (20, X.shape[1] + 1)
        assert np.isfinite(contrib).all()

    def test_pred_contrib_continue_trained(self):
        X, y = binary_data(n=300)
        b1 = lgb.train(_params(objective="binary"), lgb.Dataset(X, label=y), 4)
        b2 = lgb.train(_params(objective="binary"), lgb.Dataset(X, label=y), 3,
                       init_model=b1)
        contrib = b2.predict(X[:25], pred_contrib=True)
        raw = b2.predict(X[:25], raw_score=True)
        np.testing.assert_allclose(contrib.sum(axis=1), raw,
                                   rtol=1e-4, atol=1e-4)

    def test_pred_contrib_multiclass(self):
        X, y = multiclass_data()
        bst = lgb.train(_params(objective="multiclass", num_class=3),
                        lgb.Dataset(X, label=y), 4)
        contrib = bst.predict(X[:20], pred_contrib=True)
        assert contrib.shape == (20, 3 * (X.shape[1] + 1))
        raw = bst.predict(X[:20], raw_score=True)
        sums = contrib.reshape(20, 3, X.shape[1] + 1).sum(axis=2)
        np.testing.assert_allclose(sums, raw, rtol=1e-4, atol=1e-4)

    def test_pred_early_stop(self):
        X, y = binary_data()
        bst = lgb.train(_params(objective="binary"), lgb.Dataset(X, label=y), 30)
        full = bst.predict(X)
        stopped = bst.predict(X, pred_early_stop=True,
                              pred_early_stop_margin=1.5,
                              pred_early_stop_freq=5)
        # decisions agree even though accumulation stops early
        assert ((full > 0.5) == (stopped > 0.5)).mean() > 0.98
        # and a huge margin disables stopping entirely
        same = bst.predict(X, pred_early_stop=True,
                           pred_early_stop_margin=1e9,
                           pred_early_stop_freq=5)
        np.testing.assert_allclose(same, full, rtol=1e-6)


class TestSubset:
    def test_subset_trains_with_shared_bins(self):
        from utils import binary_data
        import lightgbm_tpu as lgb
        X, y = binary_data()
        ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
        ds.construct()
        idx = np.arange(0, len(y), 2)
        sub = ds.subset(idx)
        assert sub._inner.num_data == len(idx)
        # mappers shared: binning identical to the parent's rows
        np.testing.assert_array_equal(sub._inner.binned,
                                      ds._inner.binned[idx])
        bst = lgb.train({"objective": "binary", "num_leaves": 15,
                         "verbose": -1, "min_data_in_leaf": 5}, sub, 5)
        from sklearn.metrics import roc_auc_score
        assert roc_auc_score(y[idx], bst.predict(X[idx])) > 0.9
