"""Exclusive Feature Bundling (io/efb.py).

Reference: FeatureGroup / Dataset::Construct FindGroups
(include/LightGBM/feature_group.h, src/io/dataset.cpp). The strongest
property of conflict-free bundling is LOSSLESSNESS: training on the bundled
matrix must reproduce dense training exactly (same splits, same leaves) —
asserted here as the golden test, like the reference's EFB regression tests
compare against unbundled runs.
"""
import numpy as np
import pytest
from sklearn.metrics import roc_auc_score

import lightgbm_tpu as lgb


def _onehot_data(n=6000, groups=40, card=8, dense=4, seed=0):
    rng = np.random.RandomState(seed)
    cats = rng.randint(0, card, size=(n, groups))
    X = np.zeros((n, groups * card), np.float32)
    for g in range(groups):
        X[np.arange(n), g * card + cats[:, g]] = 1.0
    X = np.concatenate([X, rng.randn(n, dense).astype(np.float32)], axis=1)
    w = rng.randn(X.shape[1]) * 0.5
    y = ((X @ w + 0.4 * rng.randn(n)) > 0).astype(np.float64)
    return X, y


PARAMS = {"objective": "binary", "num_leaves": 31, "verbose": -1,
          "tpu_grower": "compact", "min_data_in_leaf": 10}


class TestEFB:
    def test_lossless_vs_dense(self):
        # the DENSE twin trains all 324 one-hot columns through the
        # compact grower — the suite's single most expensive call. The
        # 5-round models are a tree PREFIX of the original 8-round pair
        # (round count changes no split decision), so losslessness is
        # proven identically at 5/8 of the tier-1 cost. Rows stay 6000:
        # the prediction tolerance is tuned to this seed's near-tie
        # structure (a 4000-row slice flips one early near-tie split)
        X, y = _onehot_data()
        b_off = lgb.train(dict(PARAMS),
                          lgb.Dataset(X, label=y,
                                      params={"enable_bundle": False}), 5)
        ds = lgb.Dataset(X, label=y)
        b_on = lgb.train(dict(PARAMS), ds, 5)
        info = ds._inner.bundle_info
        assert info is not None and info.n_columns < X.shape[1] // 4
        # bundling is exact in exact arithmetic; gains cumsum over
        # differently-shaped arrays, so fp reassociation can flip near-tie
        # split choices — compare predictions, not bit patterns
        p_off, p_on = b_off.predict(X), b_on.predict(X)
        assert np.abs(p_off - p_on).mean() < 1e-3
        assert abs(roc_auc_score(y, p_off) - roc_auc_score(y, p_on)) < 2e-3

    def test_valid_sets_and_early_stopping(self):
        X, y = _onehot_data(seed=3)
        ds = lgb.Dataset(X[:5000], label=y[:5000])
        dv = ds.create_valid(X[5000:], label=y[5000:])
        bst = lgb.train(dict(PARAMS, metric="auc"), ds, 15, valid_sets=[dv],
                        callbacks=[lgb.early_stopping(5, verbose=False)])
        assert roc_auc_score(y[5000:], bst.predict(X[5000:])) > 0.7

    def test_model_roundtrip_and_importance(self, tmp_path):
        X, y = _onehot_data(seed=5)
        bst = lgb.train(dict(PARAMS), lgb.Dataset(X, label=y), 5)
        p = bst.predict(X[:500])
        path = tmp_path / "m.txt"
        bst.save_model(str(path))
        p2 = lgb.Booster(model_file=str(path)).predict(X[:500])
        np.testing.assert_allclose(p, p2, atol=1e-6)
        imp = bst.feature_importance()
        assert imp.shape == (X.shape[1],)       # ORIGINAL feature space
        assert imp.sum() > 0

    def test_dart_replay_routing(self):
        # DART score replay routes over the BUNDLED matrix via col_of
        X, y = _onehot_data(n=4000, seed=7)
        bst = lgb.train(dict(PARAMS, boosting="dart", drop_rate=0.3,
                             num_leaves=15),
                        lgb.Dataset(X, label=y), 6)
        assert roc_auc_score(y, bst.predict(X)) > 0.7

    def test_binary_dataset_roundtrip(self, tmp_path):
        X, y = _onehot_data(n=3000, seed=9)
        ds = lgb.Dataset(X, label=y)
        ds.construct()
        path = tmp_path / "d.bin"
        ds._inner.save_binary(str(path))
        ds2 = lgb.Dataset(str(path), label=y)
        bst = lgb.train(dict(PARAMS, num_leaves=15), ds2, 3)
        assert np.isfinite(bst.predict(X[:100])).all()

    def test_incompatible_knobs_fall_back_losslessly(self):
        # monotone constraints are not supported in bundle space: training
        # must WARN, unbundle, and still work (previously trainable configs
        # keep training)
        X, y = _onehot_data(n=3000, seed=11)
        ds = lgb.Dataset(X, label=y)
        bst = lgb.train(dict(PARAMS, num_leaves=7,
                             monotone_constraints=[1] * X.shape[1]), ds, 2)
        assert ds._inner.bundle_info is None       # fell back to dense
        assert np.isfinite(bst.predict(X[:50])).all()

    def test_fused_copyback_efb_parity(self):
        # the fused kernel on EFB-bundled data auto-selects the copy-back
        # variant (dual residency has an open TPU fault there, gbdt
        # _setup_compact_state); interpret mode runs the same program on CPU
        # and must match the XLA-walk compact grower
        X, y = _onehot_data(n=3000, seed=13)
        base = dict(PARAMS, num_leaves=31, min_data_in_leaf=5)
        b_xla = lgb.train(dict(base), lgb.Dataset(X, label=y), 4)
        b_fus = lgb.train(dict(base, tpu_fused="on", tpu_fused_interpret=True,
                               tpu_fused_block=128),
                          lgb.Dataset(X, label=y), 4)
        gp = b_fus._gbdt.grower_params
        assert gp.fused_block and not gp.fused_dual   # copy-back selected
        np.testing.assert_allclose(b_xla.predict(X[:800]),
                                   b_fus.predict(X[:800]), atol=2e-4)

    def test_bounded_conflict_bundling(self):
        # reference: FindGroups packs features whose conflicts stay under
        # total_sample_cnt/10000 per group (src/io/dataset.cpp:115); rows
        # with two nonzero members keep the first-placed member's value
        from lightgbm_tpu.io.efb import build_bundle_info, plan_bundles
        rng = np.random.RandomState(0)
        n, groups, card = 20000, 40, 8
        cats = rng.randint(0, card, size=(n, groups))
        X = np.zeros((n, groups * card), np.float32)
        for g in range(groups):
            X[np.arange(n), g * card + cats[:, g]] = 1.0
        # sprinkle conflicts: a few rows get a SECOND hot feature per block
        for g in range(groups):
            rows = rng.choice(n, size=n // 15000, replace=False)
            X[rows, g * card + rng.randint(0, card)] = 1.0
        sb = (X > 0).astype(np.uint8)
        nbins = np.full(X.shape[1], 2, np.int32)
        dbins = np.zeros(X.shape[1], np.int32)
        ok = np.ones(X.shape[1], bool)
        none = plan_bundles(sb, nbins, dbins, ok, max_conflict_rate=0.0,
                            min_features=8)
        some = plan_bundles(sb, nbins, dbins, ok, max_conflict_rate=1e-4,
                            min_features=8)
        n_none = sum(len(b) for b in none) if none else 0
        n_some = sum(len(b) for b in some) if some else 0
        assert n_some > n_none, (n_some, n_none)

        # end-to-end: training on conflicted one-hot data still bundles and
        # stays accurate
        w = rng.randn(X.shape[1]) * 0.5
        y = ((X @ w + 0.4 * rng.randn(n)) > 0).astype(np.float64)
        ds = lgb.Dataset(X, label=y)
        bst = lgb.train(dict(PARAMS, num_leaves=15), ds, 6)
        info = ds._inner.bundle_info
        assert info is not None and info.n_columns < X.shape[1] // 2
        assert roc_auc_score(y, bst.predict(X)) > 0.75
