"""Edge-coverage: interaction combinations and parameter validation the
reference's test_engine.py exercises heavily (missing-type x categorical x
monotone x EFB x continued-training), asserting behavior — not just "runs".
"""
import numpy as np
import pytest
from sklearn.metrics import roc_auc_score

import lightgbm_tpu as lgb


def _mixed_data(n=3000, seed=0, nan_frac=0.15):
    """Numerical + categorical + NaN-bearing features with a known signal."""
    rng = np.random.RandomState(seed)
    num = rng.randn(n, 3)
    cat = rng.randint(0, 12, size=(n, 2)).astype(np.float64)
    nanny = rng.randn(n, 2)
    nanny[rng.rand(n, 2) < nan_frac] = np.nan
    X = np.concatenate([num, cat, nanny], axis=1)
    y = ((num[:, 0] + 0.8 * (cat[:, 0] % 3 == 1)
          + 0.6 * np.nan_to_num(nanny[:, 0]) + 0.4 * rng.randn(n)) > 0.3)
    return X, y.astype(np.float64)


BASE = {"objective": "binary", "verbosity": -1, "num_leaves": 15,
        "min_data_in_leaf": 5}


class TestInteractionMatrix:
    def test_missing_x_categorical_x_monotone(self):
        X, y = _mixed_data()
        params = dict(BASE, categorical_feature=[3, 4],
                      monotone_constraints=[1, 0, 0, 0, 0, -1, 0])
        bst = lgb.train(params, lgb.Dataset(
            X, label=y, categorical_feature=[3, 4]), 15)
        p = bst.predict(X)
        assert roc_auc_score(y, p) > 0.75
        # monotone direction actually holds on feature 0 (others at median)
        grid = np.tile(np.nanmedian(X, axis=0), (20, 1))
        grid[:, 0] = np.linspace(np.nanmin(X[:, 0]), np.nanmax(X[:, 0]), 20)
        g = bst.predict(grid, raw_score=True)
        assert (np.diff(g) >= -1e-6).all(), "monotone(+) violated"
        # NaN rows route without error and predict finitely
        assert np.isfinite(bst.predict(X[np.isnan(X[:, 5])])).all()

    def test_missing_nan_vs_zero_as_missing(self):
        X, y = _mixed_data(nan_frac=0.3)
        b_nan = lgb.train(dict(BASE), lgb.Dataset(X, label=y), 8)
        Xz = np.nan_to_num(X, nan=0.0)
        ds = lgb.Dataset(Xz, label=y, params={"zero_as_missing": True})
        b_zero = lgb.train(dict(BASE), ds, 8)
        # both train to signal; zero-as-missing treats exact zeros as missing
        assert roc_auc_score(y, b_nan.predict(X)) > 0.72
        assert roc_auc_score(y, b_zero.predict(Xz)) > 0.7

    def test_efb_x_continued_training(self):
        rng = np.random.RandomState(2)
        n, G, card = 3000, 40, 8
        cats = rng.randint(0, card, size=(n, G))
        X = np.zeros((n, G * card), np.float32)
        for g in range(G):
            X[np.arange(n), g * card + cats[:, g]] = 1.0
        y = ((X @ (rng.randn(G * card) * .5)) > 0).astype(np.float64)
        ds = lgb.Dataset(X, label=y)
        b1 = lgb.train(dict(BASE), ds, 5)
        assert ds._inner.bundle_info is not None
        # continue training on a FRESH dataset (re-bundled independently)
        b2 = lgb.train(dict(BASE), lgb.Dataset(X, label=y), 5, init_model=b1)
        assert b2.num_trees() == 10
        auc1 = roc_auc_score(y, b1.predict(X))
        auc2 = roc_auc_score(y, b2.predict(X))
        assert auc2 >= auc1 - 1e-9, (auc1, auc2)
        # model text round-trips through the merge
        b3 = lgb.Booster(model_str=b2.model_to_string())
        np.testing.assert_allclose(b3.predict(X[:200]), b2.predict(X[:200]),
                                   atol=1e-6)

    def test_efb_x_missing_nan_features_stay_unbundled(self):
        rng = np.random.RandomState(3)
        n, G, card = 3000, 40, 8
        cats = rng.randint(0, card, size=(n, G))
        X = np.zeros((n, G * card + 1), np.float32)
        for g in range(G):
            X[np.arange(n), g * card + cats[:, g]] = 1.0
        X[:, -1] = rng.randn(n)
        X[rng.rand(n) < 0.2, -1] = np.nan        # NaN feature: not bundleable
        y = ((np.nan_to_num(X[:, -1]) + X[:, 0]) > 0).astype(np.float64)
        ds = lgb.Dataset(X, label=y)
        bst = lgb.train(dict(BASE), ds, 5)
        info = ds._inner.bundle_info
        assert info is not None
        assert info.offset_of[-1] == -1          # NaN feature passthrough
        assert roc_auc_score(y, bst.predict(X)) > 0.8

    def test_categorical_x_continued_training_x_predict_leaf(self):
        X, y = _mixed_data()
        ds = lgb.Dataset(X, label=y, categorical_feature=[3, 4])
        b1 = lgb.train(dict(BASE), ds, 4)
        b2 = lgb.train(dict(BASE), lgb.Dataset(
            X, label=y, categorical_feature=[3, 4]), 3, init_model=b1)
        leaves = b2.predict(X[:50], pred_leaf=True)
        assert leaves.shape == (50, 7)
        assert (leaves >= 0).all()

    def test_monotone_x_bagging_x_valid(self):
        X, y = _mixed_data(seed=5)
        params = dict(BASE, monotone_constraints=[1] + [0] * 6,
                      bagging_fraction=0.7, bagging_freq=1, metric="auc")
        ds = lgb.Dataset(X[:2400], label=y[:2400])
        dv = ds.create_valid(X[2400:], label=y[2400:])
        ev = {}
        bst = lgb.train(params, ds, 12, valid_sets=[dv],
                        callbacks=[lgb.record_evaluation(ev)])
        assert len(ev["valid_0"]["auc"]) == 12
        assert ev["valid_0"]["auc"][-1] > 0.7


class TestParamValidation:
    def test_label_length_mismatch(self):
        X = np.random.randn(100, 4)
        with pytest.raises((ValueError, Exception), match="[Ll]abel|length"):
            lgb.train(dict(BASE), lgb.Dataset(X, label=np.zeros(50)), 2)

    def test_predict_wrong_feature_count(self):
        X, y = _mixed_data(n=500)
        bst = lgb.train(dict(BASE), lgb.Dataset(X, label=y), 2)
        with pytest.raises(ValueError, match="features"):
            bst.predict(X[:, :3])

    def test_unknown_objective(self):
        from lightgbm_tpu.utils.log import LightGBMError
        X, y = _mixed_data(n=300)
        with pytest.raises(LightGBMError, match="objective"):
            lgb.train({"objective": "no_such_objective", "verbosity": -1},
                      lgb.Dataset(X, label=y), 2)

    def test_garbage_model_string(self):
        with pytest.raises(ValueError, match="model"):
            lgb.Booster(model_str="definitely not a model")

    def test_monotone_constraints_wrong_length(self):
        X, y = _mixed_data(n=400)
        with pytest.raises((ValueError, Exception)):
            lgb.train(dict(BASE, monotone_constraints=[1, -1]),
                      lgb.Dataset(X, label=y), 2)

    def test_num_boost_round_zero(self):
        X, y = _mixed_data(n=300)
        bst = lgb.train(dict(BASE), lgb.Dataset(X, label=y), 0)
        assert bst.num_trees() == 0
        # constant prediction (init score only, converted)
        p = bst.predict(X[:10])
        assert np.allclose(p, p[0])

    def test_group_sum_mismatch_for_ranking(self):
        X = np.random.randn(200, 5)
        y = np.random.randint(0, 3, 200).astype(np.float64)
        with pytest.raises((ValueError, Exception)):
            lgb.train({"objective": "lambdarank", "verbosity": -1},
                      lgb.Dataset(X, label=y, group=[50, 50]), 2)

    def test_max_bin_by_feature_wrong_length(self):
        X, y = _mixed_data(n=300)
        with pytest.raises(ValueError, match="max_bin_by_feature"):
            ds = lgb.Dataset(X, label=y,
                             params={"max_bin_by_feature": [15, 31]})
            ds.construct()

    def test_feature_names_length_mismatch(self):
        X, y = _mixed_data(n=300)
        with pytest.raises(ValueError, match="feature_names"):
            lgb.Dataset(X, label=y, feature_name=["a", "b"]).construct()
