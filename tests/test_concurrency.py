"""Concurrent Booster API: the rwlock keeps 16 predict threads and
interleaved updates consistent, and the R007 runtime sanitizer catches a
seeded lock-bypass mutation in detector mode.

The reference serializes the same surface behind its C API shared mutex
(src/c_api.cpp:163, yamc shared lock: concurrent predicts, exclusive
update); utils/rwlock.py + the @read_locked/@write_locked decorators in
basic.py are this repo's equivalent, and analysis/guards.api_race_sanitizer
is the detector that proves the lock is actually doing the work.
"""
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.analysis import guards
from lightgbm_tpu.utils.rwlock import NullLock, RWLock

from utils import FAST_PARAMS, binary_data

N_THREADS = 16


def _train(num_boost_round=10, **kw):
    X, y = binary_data()
    params = dict(FAST_PARAMS, objective="binary", **kw)
    return lgb.train(params, lgb.Dataset(X, label=y), num_boost_round), X


# --------------------------------------------------------------- rwlock
class TestRWLock:
    def test_concurrent_readers_exclusive_writer(self):
        lock = RWLock()
        state = {"readers": 0, "max_readers": 0, "writer_saw_readers": False}
        mu = threading.Lock()
        stop = threading.Event()

        def reader():
            while not stop.is_set():
                with lock.read():
                    with mu:
                        state["readers"] += 1
                        state["max_readers"] = max(state["max_readers"],
                                                   state["readers"])
                    # dwell inside the read section so reader overlap is
                    # actually observable (a bare inc/dec window loses to
                    # the GIL switch interval and flakes)
                    time.sleep(0.001)
                    with mu:
                        state["readers"] -= 1

        rs = [threading.Thread(target=reader) for _ in range(4)]
        for t in rs:
            t.start()
        # phase 1: readers only — they must genuinely overlap
        deadline = time.monotonic() + 5.0
        while state["max_readers"] < 2 and time.monotonic() < deadline:
            time.sleep(0.005)

        # phase 2: a writer must never observe an active reader
        def writer():
            for _ in range(50):
                with lock.write():
                    if state["readers"]:
                        state["writer_saw_readers"] = True

        w = threading.Thread(target=writer)
        w.start()
        w.join()
        stop.set()
        for t in rs:
            t.join()
        assert not state["writer_saw_readers"]
        assert state["max_readers"] >= 2   # readers really were concurrent

    def test_reentrant_nesting(self):
        lock = RWLock()
        with lock.read(), lock.read():
            pass
        with lock.write(), lock.write(), lock.read():
            pass

    def test_read_to_write_upgrade_raises(self):
        lock = RWLock()
        with lock.read():
            with pytest.raises(RuntimeError, match="upgrade"):
                lock.acquire_write()

    def test_non_lifo_release_raises(self):
        """Dropping the write while a nested read is still held would
        underflow the reader count and wedge all future writers — it must
        fail loudly instead."""
        lock = RWLock()
        lock.acquire_write()
        lock.acquire_read()
        with pytest.raises(RuntimeError, match="LIFO"):
            lock.release_write()
        lock.release_read()
        lock.release_write()        # LIFO order releases cleanly
        with lock.write():          # and the lock is still serviceable
            pass


# ----------------------------------------------------- predict vs update
def test_concurrent_predict_with_interleaved_update():
    """16 threads hammer predict while the main thread keeps boosting.
    Every concurrent prediction must exactly match the serial prediction
    of SOME tree-count snapshot — a torn read (cache from one model
    state, trees from another) matches none of them."""
    bst, X = _train(10)
    extra = 6
    # serial reference predictions for every reachable snapshot
    snapshots = [bst.predict(X)]

    results, errors = [], []
    started = threading.Barrier(N_THREADS + 1)

    def hammer():
        try:
            started.wait()
            for _ in range(4):
                results.append(bst.predict(X))
        except Exception as err:  # pragma: no cover - the failure path
            errors.append(err)

    threads = [threading.Thread(target=hammer) for _ in range(N_THREADS)]
    for t in threads:
        t.start()
    started.wait()
    for _ in range(extra):
        bst.update()
        snapshots.append(bst.predict(X))
    for t in threads:
        t.join()

    assert not errors, errors
    assert len(results) == N_THREADS * 4
    for p in results:
        assert p.shape == snapshots[0].shape
        assert np.isfinite(p).all()
        assert any(np.allclose(p, s, atol=1e-6) for s in snapshots), \
            "a concurrent prediction matches no consistent model snapshot"
    assert bst.num_trees() == 16


def test_concurrent_predict_mixed_batch_sizes():
    """16 threads serve MIXED batch sizes through the bucketed inference
    engine (ops/predict.py): every thread's result must equal its serial
    reference bit-for-bit, and the append-pad device-tree cache must
    survive concurrent rung warmups (the jit cache and the tree cache
    are both shared mutable state under the read lock)."""
    bst, X = _train(10)
    rng = np.random.RandomState(11)
    Xq = np.concatenate([X] * 3)[: 1400]
    sizes = [7, 64, 333, 1400]           # spans two bucket rungs
    ref = {s: bst.predict(Xq[:s]) for s in sizes}

    errors = []
    started = threading.Barrier(N_THREADS)

    def serve(i):
        try:
            started.wait()
            for j in range(3):
                s = sizes[(i + j) % len(sizes)]
                out = bst.predict(Xq[:s])
                if not np.array_equal(out, ref[s]):
                    raise AssertionError(
                        f"thread {i}: size-{s} prediction diverged from "
                        "the serial reference")
        except Exception as err:  # pragma: no cover - the failure path
            errors.append(err)

    with guards.api_race_sanitizer() as san:
        threads = [threading.Thread(target=serve, args=(i,))
                   for i in range(N_THREADS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert not errors, errors
    san.assert_no_races("16-thread mixed-batch predict")


def test_concurrent_predict_matches_serial_exactly():
    bst, X = _train(8)
    want = bst.predict(X)
    got, errors = [], []

    def hammer():
        try:
            for _ in range(3):
                got.append(bst.predict(X))
        except Exception as err:  # pragma: no cover
            errors.append(err)

    threads = [threading.Thread(target=hammer) for _ in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    for p in got:
        np.testing.assert_allclose(p, want, rtol=0, atol=0)


def test_deepcopy_of_trained_booster_still_works():
    """The locks must not break model snapshotting: RWLock/Mutex
    deep-copy as fresh locks (hold state is meaningless in a copy)."""
    import copy
    bst, X = _train(5)
    snap = copy.deepcopy(bst)
    np.testing.assert_allclose(snap.predict(X), bst.predict(X))
    bst.update()
    assert bst.num_trees() == 6
    assert snap.num_trees() == 5        # the snapshot is independent
    ds = lgb.Dataset(X, label=np.zeros(len(X)))
    assert copy.deepcopy(ds) is not ds


# ----------------------------------------------- serving coalescer traffic
def test_coalescer_hotswap_mixed_sizes_under_sanitizer():
    """ISSUE 9: 16 threads push MIXED batch sizes through the serving
    coalescer while a hot-swap lands mid-stream. Every request must get a
    response from EXACTLY ONE model version (bit-equal to that version's
    serial prediction), the rwlock discipline must stay race-free under
    the sanitizer, and the post-warmup steady state — including the
    pre-warmed swap itself — must compile nothing."""
    bst1, X = _train(8, tpu_predict_buckets="32,256")
    bst2, _ = _train(13, tpu_predict_buckets="32,256")
    Xq = np.concatenate([X] * 2)[:200]
    sizes = [1, 7, 33, 200]                  # spans both bucket rungs
    ref1 = {s: bst1.predict(Xq[:s]) for s in sizes}
    ref2 = {s: bst2.predict(Xq[:s]) for s in sizes}
    # pre-warm BOTH models' ladders (and conversion programs) so the
    # guarded region below — traffic AND the mid-stream deploy — holds
    # the zero-recompile serving contract end to end
    bst1.warm_predict_ladder()
    bst2.warm_predict_ladder()

    # the lock-order witness wraps server CONSTRUCTION too, so the
    # coalescer cv / registry locks are created instrumented (R011's
    # runtime half: any cross-thread order inversion fails with stacks)
    with guards.lock_witness() as lw:
        srv = bst1.serve(tick_ms=1.0, queue_max=4096, deadline_ms=5000.0)
        results, errors = [], []
        started = threading.Barrier(N_THREADS + 1)

        def client(i):
            try:
                started.wait()
                for j in range(6):
                    s = sizes[(i + j) % len(sizes)]
                    fut = srv.submit(Xq[:s])
                    results.append((s, fut.result(), fut.version))
            except Exception as err:  # pragma: no cover - failure path
                errors.append(err)

        try:
            with guards.api_race_sanitizer() as san, \
                    guards.compile_counter() as cc:
                threads = [threading.Thread(target=client, args=(i,))
                           for i in range(N_THREADS)]
                for t in threads:
                    t.start()
                started.wait()
                srv.deploy("v2", bst2)       # hot-swap lands mid-stream
                for t in threads:
                    t.join()
            assert not errors, errors[:3]
            assert len(results) == N_THREADS * 6
            versions = {v for _, _, v in results}
            assert versions and versions <= {"v0", "v2"}
            for s, out, v in results:
                ref = ref1 if v == "v0" else ref2
                assert np.array_equal(out, ref[s]), \
                    f"size-{s} response is not version {v}'s " \
                    "prediction — a mixed-model or torn response"
            san.assert_no_races("16-thread coalesced serving + hot-swap")
            cc.assert_no_compiles(
                "serving steady state across a hot-swap")
            assert srv.stats["ticks"] < len(results)  # batching happened
        finally:
            srv.close(drain=False, timeout_s=5.0)
    assert lw.acquires > 0
    lw.assert_no_cycles("16-thread coalesced serving + hot-swap")


# ------------------------------------------------------------- sanitizer
def test_sanitizer_quiet_under_real_lock():
    bst, X = _train(5)
    with guards.api_race_sanitizer() as san:
        threads = [threading.Thread(
            target=lambda: [bst.predict(X) for _ in range(3)])
            for _ in range(6)]
        up = threading.Thread(target=lambda: [bst.update()
                                              for _ in range(3)])
        for t in threads:
            t.start()
        up.start()
        for t in threads:
            t.join()
        up.join()
    san.assert_no_races("locked concurrent predict/update")
    assert san.races == []


def test_sanitizer_catches_seeded_lock_bypass():
    """The seeded R007 mutation: swap the Booster's rwlock for a no-op
    and the detector must observe writer/reader overlap."""
    bst, X = _train(5)
    bst._api_lock = NullLock()          # the seeded bypass
    detected = False
    for _ in range(3):                  # overlap is stochastic; retry
        with guards.api_race_sanitizer() as san:
            threads = [threading.Thread(
                target=lambda: [bst.predict(X) for _ in range(6)])
                for _ in range(8)]
            up = threading.Thread(
                target=lambda: [bst.update() for _ in range(6)])
            for t in threads:
                t.start()
            up.start()
            for t in threads:
                t.join()
            up.join()
        if san.races:
            detected = True
            break
    assert detected, "sanitizer missed the unlocked predict/update overlap"
    with pytest.raises(guards.ApiRaceError, match="unsynchronized"):
        san.assert_no_races()


def test_sanitizer_raise_on_race_leaves_no_phantom_hold():
    """A raising enter() must not register a hold — otherwise every later
    (correctly serialized) access is indicted against a dead entry."""
    san = guards.ApiRaceSanitizer(raise_on_race=True)
    obj = object()
    tok = {}
    t = threading.Thread(
        target=lambda: tok.setdefault("w", san.enter(obj, "write", "update")))
    t.start()
    t.join()
    with pytest.raises(guards.ApiRaceError):
        san.enter(obj, "read", "predict")   # overlaps the writer's hold
    san.exit_(tok["w"])
    token = san.enter(obj, "write", "update")   # must be clean now
    san.exit_(token)
    assert len(san.races) == 1


def test_sanitizer_ignores_same_thread_nesting():
    """save_model -> model_to_string nests read-in-read on one thread;
    not a race."""
    bst, X = _train(3)
    with guards.api_race_sanitizer() as san:
        bst.predict(X)
        s = bst.model_to_string()
        bst.update()
        assert len(s) > 0
    assert san.races == []


# ------------------------------------------------- metrics scrape (ISSUE 14)
def test_metrics_scrape_mid_traffic_under_sanitizer():
    """ISSUE 14: 16 threads split between serving traffic and /metrics +
    /healthz scrapes while drift + SLO monitors are armed. Every scrape
    must return a parseable body (Prometheus text with escaped labels /
    JSON), the rwlock discipline stays race-free under the sanitizer,
    and the scrapes themselves compile nothing."""
    import json as _json
    import urllib.request

    bst, X = _train(5, tpu_predict_buckets="32,256")
    bst.warm_predict_ladder()
    srv = bst.serve(tick_ms=1.0, queue_max=4096, deadline_ms=5000.0,
                    drift_flush_every=3, slo_ms=5000.0, metrics_port=0)
    port = srv.metrics_port
    assert port
    errors = []
    bodies = []
    started = threading.Barrier(N_THREADS + 1)

    def client(i):
        try:
            started.wait()
            if i % 2 == 0:                   # traffic half
                for j in range(6):
                    srv.submit(X[: 1 + (i + j) % 64]).result()
            else:                            # scrape half
                for j in range(6):
                    path = "/metrics" if j % 2 == 0 else "/healthz"
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{port}{path}",
                            timeout=10) as resp:
                        bodies.append((path, resp.read().decode()))
        except Exception as err:  # pragma: no cover - the failure path
            errors.append(err)

    try:
        # prime every rung once so the guarded window is steady-state
        for s in (1, 64, 200):
            srv.predict(X[:s])
        with guards.api_race_sanitizer() as san, \
                guards.compile_counter() as cc:
            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(N_THREADS)]
            for t in threads:
                t.start()
            started.wait()
            for t in threads:
                t.join()
        assert not errors, errors[:3]
        assert len(bodies) == (N_THREADS // 2) * 6
        for path, body in bodies:
            if path == "/metrics":
                assert "lgbm_tpu_ready" in body
                # every sample line parses as `name[{labels}] value`
                for ln in body.splitlines():
                    if not ln or ln.startswith("#"):
                        continue
                    float(ln.rsplit(" ", 1)[1])
            else:
                assert _json.loads(body)["active_version"] == "v0"
        san.assert_no_races("16-thread traffic + /metrics scrapes")
        cc.assert_no_compiles("metrics scrape mid-traffic")
    finally:
        srv.close(drain=False, timeout_s=5.0)
