"""Real multi-process training: 2 jax.distributed processes on CPU.

Mirrors the reference's distributed test harness
(reference: tests/distributed/_test_distributed.py:53 DistributedMockup —
spawns N local CLI processes with partitioned data and a shared machine
list, then asserts accuracy and per-worker model equality :168).

Each subprocess gets HALF the rows; bin mappers must come out identical on
both ranks (sample pooling at construct), the global arrays are assembled
from per-process shards, and the two ranks' model files must match.
"""
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r"""
import os, sys
import numpy as np
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
port, outdir = sys.argv[1], sys.argv[2]
rank = int(os.environ["LIGHTGBM_TPU_PROCESS_ID"])
import lightgbm_tpu as lgb
rng = np.random.RandomState(0)
N = 4000
X = rng.randn(N, 5).astype(np.float32)
y = ((X[:, 0] + 0.5 * X[:, 1] + 0.3 * rng.randn(N)) > 0).astype(np.float64)
half = N // 2
Xl = X[rank * half:(rank + 1) * half]
yl = y[rank * half:(rank + 1) * half]
params = {"objective": "binary", "tree_learner": "data", "num_leaves": 15,
          "verbose": -1, "num_machines": 2,
          "machines": f"127.0.0.1:{port},127.0.0.1:{int(port) + 1}"}
bst = lgb.train(params, lgb.Dataset(Xl, label=yl), 5)
bst.save_model(os.path.join(outdir, f"model_{rank}.txt"))
np.save(os.path.join(outdir, f"pred_{rank}.npy"), bst.predict(X[:500]))
print("rank", rank, "done")

# compact (physically partitioned) grower under the multi-host mesh:
# per-process shard-local segments, psum-ed histograms
bst2 = lgb.train({**params, "tpu_grower": "compact"},
                 lgb.Dataset(Xl, label=yl), 5)
bst2.save_model(os.path.join(outdir, f"model_compact_{rank}.txt"))
np.save(os.path.join(outdir, f"pred_compact_{rank}.npy"),
        bst2.predict(X[:500]))
print("rank", rank, "compact done")

# multi-host lambdarank: whole queries per process, boundaries gathered
# with running offsets (Metadata::CheckOrPartition contract)
yr = (np.clip(X[:, 0] + 0.4 * rng.randn(N), -2, 2) > 0.5).astype(np.float64)
yrl = yr[rank * half:(rank + 1) * half]
group = np.full(half // 50, 50, np.int64)
bst3 = lgb.train({**params, "objective": "lambdarank",
                  "lambdarank_truncation_level": 20},
                 lgb.Dataset(Xl, label=yrl, group=group), 5)
bst3.save_model(os.path.join(outdir, f"model_rank_{rank}.txt"))
print("rank", rank, "lambdarank done")
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
@pytest.mark.skip(reason="multihost_utils.process_allgather (and the XLA "
                  "collective under sync_global_devices) is UNIMPLEMENTED "
                  "on the multiprocess CPU backend in jax 0.4.37 — "
                  "pool_bin_sample's cross-process gather aborts rank "
                  "workers. The coordination-service KV barrier "
                  "(mesh.sync_barrier) covers barriers only, not data "
                  "gathers; unskip when jax's CPU collectives land or the "
                  "test moves to a real multi-host backend.")
def test_two_process_training_identical_models(tmp_path):
    port = _free_port()
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)          # 1 CPU device per process
        env["JAX_PLATFORMS"] = "cpu"
        env["LIGHTGBM_TPU_PROCESS_ID"] = str(rank)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        procs.append(subprocess.Popen(
            [sys.executable, str(worker), str(port), str(tmp_path)],
            env=env, cwd=repo, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=900)
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"

    m0 = (tmp_path / "model_0.txt").read_text()
    m1 = (tmp_path / "model_1.txt").read_text()
    assert m0 == m1, "ranks produced different models"
    mc0 = (tmp_path / "model_compact_0.txt").read_text()
    mc1 = (tmp_path / "model_compact_1.txt").read_text()
    assert mc0 == mc1, "compact grower ranks produced different models"
    mr0 = (tmp_path / "model_rank_0.txt").read_text()
    mr1 = (tmp_path / "model_rank_1.txt").read_text()
    assert mr0 == mr1, "lambdarank ranks produced different models"

    # golden: the same global data trained in ONE process
    import jax
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(0)
    N = 4000
    X = rng.randn(N, 5).astype(np.float32)
    y = ((X[:, 0] + 0.5 * X[:, 1] + 0.3 * rng.randn(N)) > 0).astype(
        np.float64)
    ref = lgb.train({"objective": "binary", "num_leaves": 15, "verbose": -1},
                    lgb.Dataset(X, label=y), 5)
    p_ref = ref.predict(X[:500])
    p_mh = np.load(tmp_path / "pred_0.npy")
    # identical binning (pooled sample == full data) and identical split
    # logic; differences are f32 reduction order only
    assert np.abs(p_ref - p_mh).max() < 1e-3
