"""Real multi-process training: 2 jax.distributed processes on CPU.

Mirrors the reference's distributed test harness
(reference: tests/distributed/_test_distributed.py:53 DistributedMockup —
spawns N local CLI processes with partitioned data and a shared machine
list, then asserts accuracy and per-worker model equality :168).

Each subprocess gets HALF the rows; bin mappers must come out identical on
both ranks (sample pooling at construct), the global arrays are assembled
from per-process shards, and the two ranks' model files must match.
"""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r"""
import os, sys
import numpy as np
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
port, outdir = sys.argv[1], sys.argv[2]
rank = int(os.environ["LIGHTGBM_TPU_PROCESS_ID"])
import lightgbm_tpu as lgb
rng = np.random.RandomState(0)
N = 4000
X = rng.randn(N, 5).astype(np.float32)
y = ((X[:, 0] + 0.5 * X[:, 1] + 0.3 * rng.randn(N)) > 0).astype(np.float64)
half = N // 2
Xl = X[rank * half:(rank + 1) * half]
yl = y[rank * half:(rank + 1) * half]
params = {"objective": "binary", "tree_learner": "data", "num_leaves": 15,
          "verbose": -1, "num_machines": 2,
          "machines": f"127.0.0.1:{port},127.0.0.1:{int(port) + 1}"}
bst = lgb.train(params, lgb.Dataset(Xl, label=yl), 5)
bst.save_model(os.path.join(outdir, f"model_{rank}.txt"))
np.save(os.path.join(outdir, f"pred_{rank}.npy"), bst.predict(X[:500]))
print("rank", rank, "done")

# compact (physically partitioned) grower under the multi-host mesh:
# per-process shard-local segments, psum-ed histograms
bst2 = lgb.train({**params, "tpu_grower": "compact"},
                 lgb.Dataset(Xl, label=yl), 5)
bst2.save_model(os.path.join(outdir, f"model_compact_{rank}.txt"))
np.save(os.path.join(outdir, f"pred_compact_{rank}.npy"),
        bst2.predict(X[:500]))
print("rank", rank, "compact done")

# multi-host lambdarank: whole queries per process, boundaries gathered
# with running offsets (Metadata::CheckOrPartition contract)
yr = (np.clip(X[:, 0] + 0.4 * rng.randn(N), -2, 2) > 0.5).astype(np.float64)
yrl = yr[rank * half:(rank + 1) * half]
group = np.full(half // 50, 50, np.int64)
bst3 = lgb.train({**params, "objective": "lambdarank",
                  "lambdarank_truncation_level": 20},
                 lgb.Dataset(Xl, label=yrl, group=group), 5)
bst3.save_model(os.path.join(outdir, f"model_rank_{rank}.txt"))
print("rank", rank, "lambdarank done")
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_training_identical_models(tmp_path):
    # construct-time sample pooling rides the coordination-service KV
    # plane on multiprocess CPU (pool_bin_sample -> kv_allgather), and
    # init_distributed switches the CPU backend's XLA collectives to
    # gloo for the in-jit psums — jax 0.4.37's default CPU backend has
    # no cross-process collectives at all (ISSUE 15)
    port = _free_port()
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)          # 1 CPU device per process
        env["JAX_PLATFORMS"] = "cpu"
        env["LIGHTGBM_TPU_PROCESS_ID"] = str(rank)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        procs.append(subprocess.Popen(
            [sys.executable, str(worker), str(port), str(tmp_path)],
            env=env, cwd=repo, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=900)
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"

    m0 = (tmp_path / "model_0.txt").read_text()
    m1 = (tmp_path / "model_1.txt").read_text()
    assert m0 == m1, "ranks produced different models"
    mc0 = (tmp_path / "model_compact_0.txt").read_text()
    mc1 = (tmp_path / "model_compact_1.txt").read_text()
    assert mc0 == mc1, "compact grower ranks produced different models"
    mr0 = (tmp_path / "model_rank_0.txt").read_text()
    mr1 = (tmp_path / "model_rank_1.txt").read_text()
    assert mr0 == mr1, "lambdarank ranks produced different models"

    # golden: the same global data trained in ONE process
    import jax
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(0)
    N = 4000
    X = rng.randn(N, 5).astype(np.float32)
    y = ((X[:, 0] + 0.5 * X[:, 1] + 0.3 * rng.randn(N)) > 0).astype(
        np.float64)
    ref = lgb.train({"objective": "binary", "num_leaves": 15, "verbose": -1},
                    lgb.Dataset(X, label=y), 5)
    p_ref = ref.predict(X[:500])
    p_mh = np.load(tmp_path / "pred_0.npy")
    # identical binning (pooled sample == full data) and identical split
    # logic; differences are f32 reduction order only
    assert np.abs(p_ref - p_mh).max() < 1e-3


# ---------------------------------------------------------------- ISSUE 11
_STRAGGLER_WORKER = r"""
import json, os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
port, outdir = sys.argv[1], sys.argv[2]
rank = int(os.environ["LIGHTGBM_TPU_PROCESS_ID"])
# the PR 7 KV harness: 2 coordination-service processes, no XLA
# collectives (process_allgather is unimplemented on multiprocess CPU —
# the rank-attribution plane deliberately needs only the KV)
jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=2, process_id=rank)
# rank-tagged flight dumps: one shared env path, per-rank suffixed
os.environ["LGBM_TPU_FLIGHT_PATH"] = os.path.join(outdir, "flight.jsonl")
from lightgbm_tpu.analysis import faultinject
from lightgbm_tpu.obs import flight
from lightgbm_tpu.obs.ranks import RankStats

rs = RankStats(every=1, straggler_factor=3.0, deadline_s=60.0)
assert rs.world == 2 and rs.rank == rank, (rs.rank, rs.world)
spec = "hang@step=3:seconds=1.5" if rank == 1 else ""
with faultinject.inject(spec):
    plan = faultinject.active_plan()
    for i in range(1, 7):
        t0 = time.perf_counter()
        plan.fire("step", iteration=i)      # rank 1 sleeps 1.5s at i=3
        time.sleep(0.02)                    # the simulated step
        rs.sample_step(i, time.perf_counter() - t0)
dump = flight.dump("dryrun end")
print("DUMP", dump)
if rank == 0:
    with open(os.path.join(outdir, "r0.json"), "w") as fh:
        json.dump({"latest": rs.latest_tree(),
                   "stragglers": [e for e in flight.recorder().events()
                                  if e["event"] == "straggler"]}, fh)
print("rank", rank, "done")
"""


@pytest.mark.slow
def test_two_process_straggler_dryrun(tmp_path):
    """ISSUE 11 acceptance: 2-process CPU dryrun over the
    coordination-service KV — an injected hang@step on rank 1 produces
    a straggler event on rank 0, rank-tagged flight dumps on BOTH
    ranks, and a `scripts/obs merge` timeline ordered by (time, rank)."""
    port = _free_port()
    worker = tmp_path / "worker.py"
    worker.write_text(_STRAGGLER_WORKER)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["LIGHTGBM_TPU_PROCESS_ID"] = str(rank)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        procs.append(subprocess.Popen(
            [sys.executable, str(worker), str(port), str(tmp_path)],
            env=env, cwd=repo, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    outs = [p.communicate(timeout=300)[0] for p in procs]
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"

    # rank 0 flagged rank 1 at the injected iteration
    r0 = json.loads((tmp_path / "r0.json").read_text())
    st = r0["stragglers"]
    assert st, "no straggler event on rank 0"
    assert st[-1]["rank"] == 1 and st[-1]["iteration"] == 3
    assert st[-1]["slow_s"] > 1.0
    assert r0["latest"]["world"] == 2
    # rank 0 also SAW the wait: its collective-wait probe blocked on
    # rank 1's late barrier arrival at the hung iteration
    assert r0["latest"]["per_rank"]["0"]["iteration"] == 6

    # rank-tagged dumps on both ranks, merged into one timeline
    d0 = tmp_path / "flight_rank0.jsonl"
    d1 = tmp_path / "flight_rank1.jsonl"
    assert d0.exists() and d1.exists(), list(tmp_path.iterdir())
    from lightgbm_tpu.obs import summarize
    merged = summarize.merge_ranks([str(d0), str(d1)])
    assert {r["src_rank"] for r in merged} == {0, 1}
    keys = [(float(r.get("t", 0) or 0), r["src_rank"]) for r in merged]
    assert keys == sorted(keys)
    kinds = {r.get("event") for r in merged}
    assert "rank_sample" in kinds
    # rank 0's flag, in context — still naming rank 1 as the straggler
    st = [r for r in merged if r.get("event") == "straggler"]
    assert st and st[-1]["src_rank"] == 0 and st[-1]["rank"] == 1
    assert any(r.get("event") == "fault_fire" and r["src_rank"] == 1
               for r in merged)            # rank 1's hang, same timeline
