"""Batched-M histogram parity (ISSUE 4 tentpole acceptance).

The K-deep pending ring (ops/fused_split.py hist_flush), the Mosaic
kernel's window partition (ops/pallas_histogram.py), and the XLA engine's
chunk widening (ops/histogram.py) must all be EXACT-parity engines:

  * counts (in-bag + raw) bit-identical to the K=1 sync path at every K;
  * int32 quantized histograms bit-identical at every K;
  * bf16/f32 grad/hess sums within 2^-17 relative (the f32 accumulation
    regroups across the batch boundary, nothing more);
  * the drain flushes partial batches exactly at non-multiple block
    counts (pushes % K remainder blocks);
  * the steady-state guard holds with tpu_hist_mbatch set: 0 recompiles,
    0 device->host transfers post warmup.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.analysis import guards
from lightgbm_tpu.ops.compact import RowLayout, pack_rows
from lightgbm_tpu.ops.fused_split import (fused_block_cap, fused_ring_bytes,
                                          fused_split)
from lightgbm_tpu.ops.histogram import _xla_histogram, histogram_block
from lightgbm_tpu.ops.pallas_histogram import pallas_histogram

REL_BOUND = 2.0 ** -17
I32 = jnp.int32


def _mk_rows(n, f, b, seed=0, quant=False):
    rng = np.random.RandomState(seed)
    binned = rng.randint(0, b, (n, f)).astype(np.uint8)
    if quant:
        g = rng.randint(-63, 64, n).astype(np.float32)
        h = rng.randint(0, 64, n).astype(np.float32)
    else:
        g = rng.randn(n).astype(np.float32)
        h = (rng.rand(n) + 0.5).astype(np.float32)
    cnt = (rng.rand(n) > 0.25).astype(np.float32)
    return binned, g, h, cnt


def _fused_hist(binned, g, h, cnt, b, bs, mbatch, quant=False):
    n, f = binned.shape
    layout = RowLayout(num_features=f, num_extra=1)
    extras = np.zeros((1, n), np.float32)
    work = pack_rows(jnp.asarray(binned), jnp.asarray(g), jnp.asarray(h),
                     jnp.asarray(cnt), jnp.asarray(extras), layout,
                     pad_rows=bs + 32)
    zero = jnp.asarray(0, I32)
    _, _, hist = fused_split(
        work, jnp.zeros_like(work), jnp.asarray(1, I32), zero,
        jnp.asarray(n, I32), zero, zero, zero, zero, zero, zero,
        jnp.zeros((1,), jnp.uint32), layout, b, bs, 1, interpret=True,
        num_rows=n, quant=quant, mbatch=mbatch)
    return np.asarray(hist)


# ------------------------------------------------------------ fused kernel
@pytest.mark.parametrize("mbatch", [4, 8, 16])
def test_fused_counts_bit_exact_vs_sync(mbatch):
    # 11 blocks of 128 rows: 11 % K != 0 for every K — the drain flushes
    # a partial batch on each configuration
    binned, g, h, cnt = _mk_rows(1408 - 37, 5, 16)
    sync = _fused_hist(binned, g, h, cnt, 16, 128, 1)
    out = _fused_hist(binned, g, h, cnt, 16, 128, mbatch)
    np.testing.assert_array_equal(sync[:, :, 2], out[:, :, 2])
    np.testing.assert_array_equal(sync[:, :, 3], out[:, :, 3])
    # raw counts also match an independent numpy histogram
    for j in range(binned.shape[1]):
        np.testing.assert_array_equal(
            out[j, :, 3], np.bincount(binned[:, j], minlength=16))


@pytest.mark.parametrize("mbatch", [4, 8])
def test_fused_grad_hess_within_2p17(mbatch):
    binned, g, h, cnt = _mk_rows(1408 - 37, 5, 16, seed=3)
    sync = _fused_hist(binned, g, h, cnt, 16, 128, 1)
    out = _fused_hist(binned, g, h, cnt, 16, 128, mbatch)
    # relative to the magnitude of the summands (signed sums cancel)
    mag_g = np.zeros_like(sync[:, :, 0])
    mag_h = np.zeros_like(mag_g)
    for j in range(binned.shape[1]):
        for bb in range(16):
            sel = binned[:, j] == bb
            mag_g[j, bb] = np.abs(g[sel]).sum()
            mag_h[j, bb] = np.abs(h[sel]).sum()
    dg = np.abs(out[:, :, 0] - sync[:, :, 0]) / np.maximum(mag_g, 1e-6)
    dh = np.abs(out[:, :, 1] - sync[:, :, 1]) / np.maximum(mag_h, 1e-6)
    assert dg.max() <= REL_BOUND
    assert dh.max() <= REL_BOUND


@pytest.mark.parametrize("mbatch", [4, 8, 16])
def test_fused_quantized_int32_bit_exact(mbatch):
    binned, g, h, cnt = _mk_rows(1100, 4, 8, seed=5, quant=True)
    sync = _fused_hist(binned, g, h, cnt, 8, 128, 1, quant=True)
    out = _fused_hist(binned, g, h, cnt, 8, 128, mbatch, quant=True)
    assert out.dtype == np.int32 and sync.dtype == np.int32
    np.testing.assert_array_equal(sync, out)


def test_fused_partial_drain_single_block():
    """count < one block: the drain is the ONLY flush (pushes=1 < K)."""
    binned, g, h, cnt = _mk_rows(90, 4, 8, seed=7)
    sync = _fused_hist(binned, g, h, cnt, 8, 128, 1)
    out = _fused_hist(binned, g, h, cnt, 8, 128, 8)
    np.testing.assert_array_equal(sync[:, :, 3], out[:, :, 3])
    assert out[0, :, 3].sum() == 90


def test_fused_split_mode_parity_with_mbatch():
    """mode=0 (partition + smaller-child histogram) agrees across K."""
    n, f, b, bs = 700, 4, 8, 128
    binned, g, h, cnt = _mk_rows(n, f, b, seed=11)
    layout = RowLayout(num_features=f, num_extra=1)
    extras = np.zeros((1, n), np.float32)
    outs = {}
    for mb in (1, 8):
        work = pack_rows(jnp.asarray(binned), jnp.asarray(g),
                         jnp.asarray(h), jnp.asarray(cnt),
                         jnp.asarray(extras), layout, pad_rows=bs + 32)
        zero = jnp.asarray(0, I32)
        n_left = int((binned[:, 1] <= 3).sum())
        w, s, hist = fused_split(
            work, jnp.zeros_like(work), zero, zero, jnp.asarray(n, I32),
            jnp.asarray(n_left, I32), jnp.asarray(1, I32),
            jnp.asarray(3, I32), zero, zero, zero,
            jnp.zeros((1,), jnp.uint32), layout, b, bs, 1, interpret=True,
            num_rows=n, mbatch=mb)
        outs[mb] = (np.asarray(w), np.asarray(s), np.asarray(hist))
    np.testing.assert_array_equal(outs[1][0], outs[8][0])   # partition
    np.testing.assert_array_equal(outs[1][2][:, :, 2:], outs[8][2][:, :, 2:])


# --------------------------------------------------- standalone Mosaic
@pytest.mark.parametrize("mbatch", [2, 4, 8])
def test_pallas_histogram_split_parity(mbatch):
    rng = np.random.RandomState(2)
    n, f, b = 3000, 6, 32
    binned = jnp.asarray(rng.randint(0, b, (n, f)).astype(np.uint8))
    ch = jnp.asarray(rng.randn(n, 4).astype(np.float32))
    base = np.asarray(pallas_histogram(binned, ch, b, row_block=512,
                                       interpret=True, mbatch=1))
    out = np.asarray(pallas_histogram(binned, ch, b, row_block=512,
                                      interpret=True, mbatch=mbatch))
    mag = np.asarray(_xla_histogram(binned, jnp.abs(ch), b))
    rel = np.abs(out - base) / np.maximum(mag, 1e-6)
    assert rel.max() <= REL_BOUND
    # integer channels: bit-exact
    ci = jnp.asarray((rng.rand(n, 4) > 0.5).astype(np.float32))
    a = np.asarray(pallas_histogram(binned, ci, b, row_block=512,
                                    interpret=True, mbatch=1))
    bb = np.asarray(pallas_histogram(binned, ci, b, row_block=512,
                                     interpret=True, mbatch=mbatch))
    np.testing.assert_array_equal(a, bb)


@pytest.mark.parametrize("mbatch", [4, 16])
def test_pallas_histogram_int8_bit_exact(mbatch):
    rng = np.random.RandomState(4)
    n, f, b = 2500, 5, 16
    binned = jnp.asarray(rng.randint(0, b, (n, f)).astype(np.uint8))
    codes = rng.randint(-16, 17, (n, 4)).astype(np.int8)
    codes[:, 2:] = 1
    ch = jnp.asarray(codes)
    outs = [np.asarray(pallas_histogram(binned, ch, b, row_block=512,
                                        mode="int8", interpret=True,
                                        mbatch=mb)) for mb in (1, mbatch)]
    np.testing.assert_array_equal(outs[0], outs[1])
    np.testing.assert_array_equal(
        outs[1], np.asarray(_xla_histogram(binned, ch, b)))


def test_pallas_mbatch_clamps_to_divisor():
    """row_block % mbatch != 0 rounds K down to a divisor instead of
    mis-partitioning windows."""
    rng = np.random.RandomState(6)
    n, f, b = 1000, 3, 8
    binned = jnp.asarray(rng.randint(0, b, (n, f)).astype(np.uint8))
    ch = jnp.asarray(rng.randn(n, 4).astype(np.float32))
    out = np.asarray(pallas_histogram(binned, ch, b, row_block=384,
                                      interpret=True, mbatch=7))
    base = np.asarray(pallas_histogram(binned, ch, b, row_block=384,
                                       interpret=True, mbatch=1))
    np.testing.assert_allclose(out, base, rtol=1e-4, atol=1e-4)


# ------------------------------------------------------------ XLA engine
def test_xla_engine_mbatch_parity():
    rng = np.random.RandomState(8)
    n, f, b = 4000, 5, 16
    binned = jnp.asarray(rng.randint(0, b, (n, f)).astype(np.uint8))
    codes = rng.randint(-8, 9, (n, 4)).astype(np.int8)
    ch = jnp.asarray(codes)
    a = np.asarray(_xla_histogram(binned, ch, b, mbatch=1))
    for mb in (8, 16):
        np.testing.assert_array_equal(
            a, np.asarray(_xla_histogram(binned, ch, b, mbatch=mb)))
    # dispatch wrapper threads mbatch
    d = np.asarray(histogram_block(binned, ch, b, impl="xla", mbatch=8))
    np.testing.assert_array_equal(a, d)


# --------------------------------------------------------- VMEM contract
def test_fused_block_cap_accounts_for_ring_depth():
    """The pending ring multiplies VMEM residency by K: a deeper ring
    must never produce a LARGER block cap, and the chosen cap's ring must
    fit the budget for both channel layouts."""
    from lightgbm_tpu.ops.fused_split import _VMEM_RING_BUDGET
    caps = [fused_block_cap(128, k) for k in (1, 2, 8, 16)]
    assert caps == sorted(caps, reverse=True)
    for k in (1, 8, 16):
        bs = fused_block_cap(128, k)
        assert bs % 32 == 0 and bs >= 32
        if bs > 32:
            assert fused_ring_bytes(bs, 128, k) <= _VMEM_RING_BUDGET
            assert fused_ring_bytes(bs, 128, k, quant=True) \
                <= _VMEM_RING_BUDGET
    # wide EFB-bundled records stay at least as constrained as before
    assert fused_block_cap(640, 8) <= fused_block_cap(128, 8)


# ------------------------------------------------------ steady-state guard
def test_steady_state_guard_with_mbatch_set():
    """5 post-warmup compact iterations with tpu_hist_mbatch=4: zero
    lowerings, zero backend compiles, zero d2h transfers."""
    rng = np.random.RandomState(17)
    n, f = 1200, 8
    X = rng.randn(n, f).astype(np.float32)
    y = (X[:, 0] - 0.3 * X[:, 2] + 0.4 * rng.randn(n) > 0).astype(
        np.float64)
    params = {
        "objective": "binary", "num_leaves": 15, "max_bin": 63,
        "learning_rate": 0.1, "min_data_in_leaf": 20, "verbosity": -1,
        "tpu_grower": "compact", "tpu_hist_mbatch": 4,
        "stop_check_freq": 10_000,
    }
    ds = lgb.Dataset(X, label=y, params=params)
    bst = lgb.Booster(params, ds)
    assert bst._gbdt.grower_params.hist_mbatch == 4
    for _ in range(2):
        bst.update()
    with guards.steady_state_guard("5 mbatch iterations") as cc:
        for _ in range(5):
            bst.update()
    assert cc.lowerings == 0
    assert cc.backend_compiles == 0
    bst._gbdt._flush_trees()
    assert bst._gbdt.num_total_trees >= 7


def test_hist_mbatch_env_override_validated():
    """Round-12 resolve order (engines/registry.py): an explicit user
    knob beats the env override, the env override beats the default —
    and out-of-range env values are still clamped to [1, 16]."""
    import os
    from lightgbm_tpu.boosting.gbdt import _pick_hist_mbatch
    assert _pick_hist_mbatch({"tpu_hist_mbatch": 12}) == 12
    os.environ["LGBM_TPU_HIST_MBATCH"] = "99"
    try:
        # explicit user knob wins over the env override
        assert _pick_hist_mbatch({"tpu_hist_mbatch": 4}) == 4
        # env override (validated: 99 clamps to 16) wins over the default
        assert _pick_hist_mbatch({}) == 16
        os.environ["LGBM_TPU_HIST_MBATCH"] = "5"
        assert _pick_hist_mbatch({}) == 5
    finally:
        del os.environ["LGBM_TPU_HIST_MBATCH"]
