"""Runtime guard rails: compile counter + host-transfer guard, and the
acceptance proof — the jitted compact step in boosting/gbdt.py runs 5
post-warmup boosting iterations with zero recompilations and zero
device-to-host transfers on the CPU backend."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.analysis import guards


# ------------------------------------------------------- compile counter
def test_compile_counter_zero_on_cache_hit():
    @jax.jit
    def f(x):
        return x * 2 + 1

    x = jnp.ones(3)
    f(x)                                  # warm
    with guards.compile_counter() as cc:
        f(x)
    assert cc.lowerings == 0
    assert cc.backend_compiles == 0
    cc.assert_no_compiles()               # does not raise


def test_compile_counter_sees_recompile():
    @jax.jit
    def g(x):
        return x - 1

    x3, x5 = jnp.ones(3), jnp.ones(5)
    g(x3)
    with guards.compile_counter() as cc:
        g(x5)                             # new shape -> retrace + lower
    assert cc.lowerings >= 1
    with pytest.raises(AssertionError, match="zero recompilations"):
        cc.assert_no_compiles("shape change")


def test_compile_counter_deactivates_after_exit():
    @jax.jit
    def h(x):
        return x + 3

    with guards.compile_counter() as cc:
        pass
    h(jnp.ones(7))                        # compiles AFTER the region
    assert cc.lowerings == 0


# --------------------------------------------------- host transfer guard
def test_no_host_transfers_blocks_sync_idioms():
    x = jnp.arange(4.0)
    for sync in (lambda: float(x[0]),
                 lambda: x.sum().item(),
                 lambda: x.tolist(),
                 lambda: jax.device_get(x)):
        with pytest.raises(guards.HostTransferError):
            with guards.no_host_transfers():
                sync()


def test_no_host_transfers_blocks_np_buffer_protocol_path():
    """np.asarray(jax_array) on CPU materializes zero-copy via the C buffer
    protocol WITHOUT calling jax.Array.__array__ — the numpy entry points
    themselves must funnel (the regression the airtight zero-d2h proof
    needs)."""
    x = jnp.arange(4.0)
    for name in ("asarray", "array", "ascontiguousarray", "asanyarray"):
        with pytest.raises(guards.HostTransferError, match=name):
            with guards.no_host_transfers():
                getattr(np, name)(x)
    # numpy restored on exit: both for plain numpy data and jax arrays
    assert np.asarray(x).shape == (4,)
    assert np.asarray([1, 2]).sum() == 3


def test_no_host_transfers_numpy_still_works_on_host_data():
    with guards.no_host_transfers():
        a = np.asarray([1.0, 2.0])          # host data: allowed
        b = np.array(a) * 2
        c = np.ascontiguousarray(b)
    np.testing.assert_allclose(c, [2.0, 4.0])


def test_no_host_transfers_allows_device_work():
    x = jnp.arange(8.0)
    with guards.no_host_transfers():
        y = (x * 2).sum()                 # pure device compute
        z = jnp.asarray(np.ones(3))      # host->device is fine
    assert float(y) == 56.0               # guard restored on exit
    assert z.shape == (3,)


def test_steady_state_guard_composes():
    @jax.jit
    def f(x):
        return x * x

    x = jnp.ones(6)
    f(x)
    with guards.steady_state_guard("steady f") as cc:
        f(x)
    assert cc.lowerings == 0


# ----------------------------------------------- the acceptance criterion
@pytest.fixture(scope="module")
def warm_booster():
    rng = np.random.RandomState(7)
    n, f = 1500, 10
    X = rng.randn(n, f).astype(np.float32)
    y = (X[:, 0] + 0.4 * X[:, 1] + 0.5 * rng.randn(n) > 0).astype(
        np.float64)
    params = {
        "objective": "binary",
        "num_leaves": 15,
        "max_bin": 63,
        "learning_rate": 0.1,
        "min_data_in_leaf": 20,
        "verbosity": -1,
        "tpu_grower": "compact",     # the physically-partitioned hot path
        "stop_check_freq": 10_000,   # no mid-loop host flush
    }
    ds = lgb.Dataset(X, label=y, params=params)
    bst = lgb.Booster(params, ds)
    for _ in range(2):               # warmup: compiles + first-iter paths
        bst.update()
    return bst


def test_boosting_steady_state_no_recompiles_no_transfers(warm_booster):
    """5 post-warmup iterations of the jitted compact step: zero
    lowerings, zero backend compiles, zero device->host transfers."""
    bst = warm_booster
    with guards.steady_state_guard("5 post-warmup iterations") as cc:
        for _ in range(5):
            bst.update()
    assert cc.lowerings == 0
    assert cc.backend_compiles == 0
    bst._gbdt._flush_trees()
    assert bst._gbdt.num_total_trees >= 7


def test_guard_pytest_fixtures(warm_booster, compile_guard, no_d2h_guard):
    """The conftest fixtures wrap a whole test in both guards."""
    warm_booster.update()
    assert compile_guard.lowerings == 0
