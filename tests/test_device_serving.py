"""Device-resident serving hot path (ISSUE 13).

Acceptance surface: (1) the device featurizer (ops/device_bin.py) is
bit-identical to the host ``bin_columns`` path across NaN /
MissingType-Zero / categorical / EFB-bundled / pack4-stored models and
non-rung row counts — so a serving request is ONE host->device copy of
raw float32; (2) the device TreeSHAP engine (ops/treeshap_device.py)
matches the numpy reference (ops/treeshap.py) within f32 tolerance and
sums to the raw score, multiclass and windowed models included; (3) the
``pred_leaf`` endpoint equals reference routing bit-for-bit; (4) the
steady state serves mixed batch sizes and a mid-stream hot-swap on all
three endpoints with 0 recompiles and 0 host featurize calls.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.analysis import guards
from lightgbm_tpu.io import binning

from utils import FAST_PARAMS, binary_data, multiclass_data

#: tiny two-rung ladder: warmup compiles two programs per endpoint
LADDER = "32,128"


def _params(**kw):
    return dict(FAST_PARAMS, objective="binary", verbosity=-1,
                tpu_predict_buckets=LADDER, **kw)


def _featurize_both(bst, x32):
    """(host bins [n, F], device bins [rung, ...]) for one f32 request."""
    g = bst._gbdt
    return g.bin_matrix(x32), np.asarray(g.featurize_rung(x32))


@pytest.fixture(scope="module")
def nan_booster():
    X, y = binary_data()
    X = X.copy()
    X[::7, 3] = np.nan                       # MissingType NaN on col 3
    bst = lgb.train(_params(), lgb.Dataset(X, label=y), 8)
    return bst, X


# ---------------------------------------------------- featurize bit-parity
def test_featurize_parity_nan(nan_booster):
    bst, X = nan_booster
    x = X[:50].astype(np.float32)
    host, dev = _featurize_both(bst, x)
    assert dev.shape[0] == 128               # padded to the rung
    np.testing.assert_array_equal(dev[:50], host)
    assert not dev[50:].any()                # pad rows bin to 0, like host


def test_featurize_parity_missing_zero():
    X, y = binary_data()
    X = X.copy()
    X[::5, 2] = np.nan
    p = _params(zero_as_missing=True)
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p), 5)
    ms = bst._gbdt.train_set.mappers
    assert any(m.missing_type == binning.MISSING_ZERO for m in ms)
    x = X[:30].astype(np.float32)
    host, dev = _featurize_both(bst, x)
    np.testing.assert_array_equal(dev[:30], host)


def test_featurize_parity_categorical_edge_values():
    rng = np.random.RandomState(3)
    X, y = binary_data()
    Xc = X.copy()
    Xc[:, 5] = rng.randint(0, 8, len(X))
    p = _params()
    bst = lgb.train(p, lgb.Dataset(Xc, label=y, params=p,
                                   categorical_feature=[5]), 6)
    assert bst._gbdt.train_set.mappers[5].is_categorical
    q = Xc[:40].copy()
    q[0, 5] = 999.0                          # unseen category -> bin 0
    q[1, 5] = -3.0                           # negative code -> bin 0
    q[2, 5] = np.inf                         # non-finite -> bin 0
    q[3, 5] = np.nan
    q[4, 5] = 3.7                            # truncates toward zero
    q[5, 5] = 4.0e9                          # outside int32 -> no match
    host, dev = _featurize_both(bst, q.astype(np.float32))
    np.testing.assert_array_equal(dev[:40], host)


def test_featurize_parity_efb_bundled():
    """EFB-bundled TRAINING matrix; prediction inputs bin per ORIGINAL
    feature, and the device featurizer must match that layout."""
    rng = np.random.RandomState(2)
    n, groups, card = 600, 50, 6             # 300 one-hot cols (EFB >= 256)
    X = np.zeros((n, groups * card), np.float64)
    for g in range(groups):
        X[np.arange(n), g * card + rng.randint(0, card, n)] = 1.0
    y = (X[:, ::card].sum(1) + 0.3 * rng.randn(n) > 0.5).astype(np.float64)
    p = _params(enable_bundle=True)
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p), 6)
    assert bst._gbdt._efb is not None, "test did not exercise EFB"
    x = X[:25].astype(np.float32)
    host, dev = _featurize_both(bst, x)
    np.testing.assert_array_equal(dev[:25], host)
    out, nv = bst.predict_serving(X[:25])
    np.testing.assert_array_equal(out[:nv], bst.predict(x))


def test_featurize_parity_pack4_packed_layout():
    X, y = binary_data()
    p = _params(max_bin=15, tpu_bin_pack4=True)
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p), 6)
    assert bst._gbdt._pred_pack4
    from lightgbm_tpu.io.dataset import pack4_matrix
    x = X[:40].astype(np.float32)
    host, dev = _featurize_both(bst, x)
    padded = np.zeros((128, host.shape[1]), host.dtype)
    padded[:40] = host
    np.testing.assert_array_equal(dev, pack4_matrix(padded))
    out, nv = bst.predict_serving(X[:40])
    np.testing.assert_array_equal(out[:nv], bst.predict(x))


def test_featurize_non_rung_row_counts(nan_booster):
    bst, X = nan_booster
    for n in (1, 31, 32, 33, 100):
        x = X[:n].astype(np.float32)
        host, dev = _featurize_both(bst, x)
        np.testing.assert_array_equal(dev[:n], host)
        out, nv = bst.predict_serving(X[:n])
        assert nv == n
        np.testing.assert_array_equal(out[:n], bst.predict(x))


def test_featurize_host_escape_hatch_byte_identical(nan_booster):
    """tpu_serve_featurize=host is a PARITY hatch: flipping it changes
    nothing, padding rows included."""
    bst, X = nan_booster
    g = bst._gbdt
    out_d, _ = bst.predict_serving(X[:40])
    g.config.set({"tpu_serve_featurize": "host"})
    try:
        out_h, _ = bst.predict_serving(X[:40])
    finally:
        g.config.set({"tpu_serve_featurize": "device"})
    np.testing.assert_array_equal(out_d, out_h)


def test_featurize_ineligible_categorical_falls_back_to_host():
    """Categorical codes outside int32 cannot be looked up on device;
    serving demotes to the host binner and still answers correctly."""
    rng = np.random.RandomState(4)
    X, y = binary_data()
    Xc = X.copy()
    Xc[:, 0] = rng.choice([3.0e9, 4.0e9, 5.0e9], len(X))
    p = _params()
    bst = lgb.train(p, lgb.Dataset(Xc, label=y, params=p,
                                   categorical_feature=[0]), 4)
    g = bst._gbdt
    assert g.train_set.mappers[0].is_categorical
    assert g._serve_featurize_mode() == "host"
    with pytest.raises(ValueError, match="not device-featurizable"):
        g.featurize_rung(Xc[:4].astype(np.float32))
    out, nv = bst.predict_serving(Xc[:10])
    np.testing.assert_array_equal(out[:nv],
                                  bst.predict(Xc[:10].astype(np.float32)))


# ------------------------------------------------------- device TreeSHAP
def test_device_treeshap_matches_numpy_reference(nan_booster):
    bst, X = nan_booster
    x = X[:40].astype(np.float32)
    contrib, nv = bst.predict_contrib_serving(x)
    ref = bst.predict(x, pred_contrib=True)
    np.testing.assert_allclose(contrib[:nv], ref, rtol=2e-5, atol=2e-5)
    raw = bst.predict(x, raw_score=True)
    np.testing.assert_allclose(contrib[:nv].sum(axis=1), raw,
                               rtol=1e-5, atol=1e-5)


def test_device_treeshap_categorical():
    rng = np.random.RandomState(5)
    X, _ = binary_data()
    Xc = X.copy()
    Xc[:, 4] = rng.randint(0, 6, len(X))
    # category drives the label so the trees actually split on it
    y = (np.isin(Xc[:, 4], (1, 3, 5)).astype(float)
         + 0.3 * X[:, 1] > 0.6).astype(np.float64)
    p = _params()
    bst = lgb.train(p, lgb.Dataset(Xc, label=y, params=p,
                                   categorical_feature=[4]), 8)
    assert any(np.any(m.cat_bitset) for m in bst._gbdt.models), \
        "test did not exercise categorical splits"
    x = Xc[:30].astype(np.float32)
    contrib, nv = bst.predict_contrib_serving(x)
    ref = bst.predict(x, pred_contrib=True)
    np.testing.assert_allclose(contrib[:nv], ref, rtol=2e-5, atol=2e-5)


def test_device_treeshap_multiclass_and_sum():
    X, y = multiclass_data()
    p = dict(FAST_PARAMS, objective="multiclass", num_class=3,
             tpu_predict_buckets=LADDER, verbosity=-1)
    bst = lgb.train(p, lgb.Dataset(X, label=y), 4)
    x = X[:20].astype(np.float32)
    contrib, nv = bst.predict_contrib_serving(x)
    ref = bst.predict(x, pred_contrib=True)
    np.testing.assert_allclose(contrib[:nv], ref, rtol=2e-5, atol=2e-5)
    raw = bst.predict(x, raw_score=True)                 # [n, K]
    sums = contrib[:nv].reshape(nv, 3, -1).sum(axis=2)
    np.testing.assert_allclose(sums, raw, rtol=1e-5, atol=1e-5)


def test_device_treeshap_windowed_model(nan_booster):
    bst, X = nan_booster
    x = X[:25].astype(np.float32)
    for kw in ({"num_iteration": 3}, {"start_iteration": 2},
               {"start_iteration": 2, "num_iteration": 3}):
        dev, nv = bst.predict_contrib_serving(x, **kw)
        ref = bst.predict(x, pred_contrib=True, **kw)
        np.testing.assert_allclose(dev[:nv], ref, rtol=2e-5, atol=2e-5)


# ------------------------------------- pred_contrib start_iteration lift
def test_pred_contrib_start_iteration_additivity(nan_booster):
    """SHAP is additive over trees: the window pieces sum EXACTLY (f64
    host path) to the full model's contributions."""
    bst, X = nan_booster
    x = X[:20]
    full = bst.predict(x, pred_contrib=True)
    head = bst.predict(x, pred_contrib=True, num_iteration=3)
    tail = bst.predict(x, pred_contrib=True, start_iteration=3)
    np.testing.assert_allclose(head + tail, full, rtol=1e-12, atol=1e-12)
    mid = bst.predict(x, pred_contrib=True, start_iteration=3,
                      num_iteration=2)
    tail2 = bst.predict(x, pred_contrib=True, start_iteration=5)
    np.testing.assert_allclose(head + mid + tail2, full,
                               rtol=1e-12, atol=1e-12)


def test_pred_contrib_start_iteration_loaded_model(nan_booster):
    """The model-only (loaded-from-text) contrib path windows the same
    way — raw-value routing, same additivity."""
    bst, X = nan_booster
    loaded = lgb.Booster(model_str=bst.model_to_string())
    x = X[:15]
    full = loaded.predict(x, pred_contrib=True)
    head = loaded.predict(x, pred_contrib=True, num_iteration=3)
    tail = loaded.predict(x, pred_contrib=True, start_iteration=3)
    np.testing.assert_allclose(head + tail, full, rtol=1e-12, atol=1e-12)


# ----------------------------------------------------- pred_leaf endpoint
def test_pred_leaf_serving_parity(nan_booster):
    bst, X = nan_booster
    x = X[:40].astype(np.float32)
    leaves, nv = bst.predict_leaf_serving(x)
    assert leaves.shape == (128, bst.num_trees())
    np.testing.assert_array_equal(leaves[:nv],
                                  bst.predict(x, pred_leaf=True))
    # windowed
    lw, nv = bst.predict_leaf_serving(x, start_iteration=2,
                                      num_iteration=3)
    np.testing.assert_array_equal(
        lw[:nv], bst.predict(x, pred_leaf=True, start_iteration=2,
                             num_iteration=3))


# --------------------------------------------- endpoints through the server
@pytest.fixture(scope="module")
def endpoint_boosters():
    X, y = binary_data()
    p = _params(tpu_serve_endpoints="predict,leaf,contrib")
    b1 = lgb.train(p, lgb.Dataset(X, label=y, params=p), 8)
    b2 = lgb.train(p, lgb.Dataset(X, label=y, params=p), 8)
    return b1, b2, X


def test_endpoints_served_through_coalescer(endpoint_boosters):
    b1, _, X = endpoint_boosters
    srv = b1.serve(tick_ms=1.0, deadline_ms=5000.0)
    try:
        assert sorted(srv.health()["endpoints"]) == \
            ["contrib", "leaf", "predict"]
        x32 = X[:20].astype(np.float32)
        np.testing.assert_array_equal(srv.predict(X[:20]),
                                      b1.predict(x32))
        np.testing.assert_array_equal(srv.predict_leaf(X[:20]),
                                      b1.predict(x32, pred_leaf=True))
        np.testing.assert_allclose(srv.predict_contrib(X[:20]),
                                   b1.predict(x32, pred_contrib=True),
                                   rtol=2e-5, atol=2e-5)
        warm = srv.registry.warm_stats()
        assert sorted(warm["endpoints"]) == ["contrib", "leaf", "predict"]
    finally:
        srv.close(drain=True)


def test_unlisted_endpoint_rejected_structurally():
    X, y = binary_data()
    bst = lgb.train(_params(), lgb.Dataset(X, label=y), 3)
    srv = bst.serve(tick_ms=1.0)
    try:
        with pytest.raises(ValueError, match="tpu_serve_endpoints"):
            srv.predict_contrib(X[:3])
        with pytest.raises(ValueError, match="tpu_serve_endpoints"):
            srv.submit_leaf(X[:3])
    finally:
        srv.close(drain=False, timeout_s=5.0)


def test_queued_kind_unserved_by_swapped_model_fails_structurally(
        endpoint_boosters):
    """A contrib request admitted under model A must not be served COLD
    by a swapped-in model whose endpoints exclude contrib (compiling in
    the request path); it fails structurally like the oversized-rows
    case."""
    from lightgbm_tpu.serving import ServingError
    from lightgbm_tpu.serving.coalescer import ServeFuture
    b1, _, X = endpoint_boosters
    p = _params()                              # default: predict only
    bp = lgb.train(p, lgb.Dataset(X, label=(X[:, 0] > 0).astype(float),
                                  params=p), 3)
    srv = b1.serve(tick_ms=1.0)
    try:
        srv.deploy("v2", bp)
        # a future that was queued BEFORE the swap (kind now unserved)
        fut = ServeFuture(X[:3].astype(np.float32), 5.0, 5000.0,
                          kind="contrib")
        with pytest.raises(ServingError, match="tpu_serve_endpoints"):
            srv._serve_batch([fut])
        # and fresh submits are rejected at the admission edge
        with pytest.raises(ValueError, match="tpu_serve_endpoints"):
            srv.submit_contrib(X[:3])
    finally:
        srv.close(drain=False, timeout_s=5.0)


def test_steady_state_guard_all_endpoints_with_hot_swap(endpoint_boosters):
    """THE acceptance guard: after warmup, mixed batch sizes on all
    three endpoints — across a mid-stream hot-swap — compile NOTHING
    and do NO host featurization work."""
    b1, b2, X = endpoint_boosters
    srv = b1.serve(tick_ms=1.0, deadline_ms=5000.0)
    try:
        # prime every (endpoint, rung) program once
        for s in (3, 40):
            srv.predict(X[:s]); srv.predict_leaf(X[:s])
            srv.predict_contrib(X[:s])
        host0 = binning.host_featurize_calls()
        with guards.compile_counter() as cc:
            futs = []
            for s in (1, 17, 32, 100):
                futs += [srv.submit(X[:s]), srv.submit_leaf(X[:s]),
                         srv.submit_contrib(X[:s])]
            for f in futs:
                f.result()
            srv.deploy("v2", b2)            # mid-stream hot-swap
            futs = []
            for s in (5, 64):
                futs += [srv.submit(X[:s]), srv.submit_leaf(X[:s]),
                         srv.submit_contrib(X[:s])]
            versions = {f.result() is not None and f.version
                        for f in futs}
        assert cc.lowerings == 0, \
            f"steady serving lowered {cc.lowerings} programs"
        assert binning.host_featurize_calls() == host0, \
            "steady serving did host featurization work"
        assert versions == {"v2"}
        x32 = X[:5].astype(np.float32)
        np.testing.assert_array_equal(srv.predict(X[:5]), b2.predict(x32))
    finally:
        srv.close(drain=True)
