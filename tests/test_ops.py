"""Unit tests for the device ops: histogram, split finding, routing.

Mirrors the reference's kernel-level checks (the CUDA learner is validated
end-to-end in test_engine.py there; here the TPU ops get direct golden tests
against numpy references).
"""
import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops.histogram import histogram
from lightgbm_tpu.ops.split import SplitParams, best_split, leaf_output
from lightgbm_tpu.ops.grower import GrowerParams, grow_tree
from lightgbm_tpu.ops.predict import route_one_tree


def _np_histogram(binned, channels, num_bins):
    n, f = binned.shape
    k = channels.shape[1]
    out = np.zeros((f, num_bins, k), np.float64)
    for j in range(f):
        for b in range(num_bins):
            m = binned[:, j] == b
            out[j, b] = channels[m].sum(axis=0)
    return out


def test_histogram_matches_numpy(rng):
    n, f, b = 500, 7, 16
    binned = rng.randint(0, b, size=(n, f)).astype(np.uint8)
    channels = rng.randn(n, 3).astype(np.float32)
    got = np.asarray(histogram(jnp.asarray(binned), jnp.asarray(channels), b))
    want = _np_histogram(binned, channels, b)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_histogram_chunked_path(rng):
    # force the lax.scan chunked path with a large-ish row count
    n, f, b = 5000, 40, 64
    binned = rng.randint(0, b, size=(n, f)).astype(np.uint8)
    channels = rng.randn(n, 2).astype(np.float32)
    got = np.asarray(histogram(jnp.asarray(binned), jnp.asarray(channels), b))
    want = _np_histogram(binned, channels, b)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def _np_best_split_numeric(hist, pg, ph, pc, p: SplitParams):
    """Exhaustive scan over all (feature, bin) numeric thresholds (no NaN)."""
    f, b, _ = hist.shape
    best = (-1e30, -1, -1)
    for j in range(f):
        cg = ch = cc = 0.0
        for t in range(b - 1):
            cg += hist[j, t, 0]
            ch += hist[j, t, 1]
            cc += hist[j, t, 2]
            rg, rh, rc = pg - cg, ph - ch, pc - cc
            if cc < p.min_data_in_leaf or rc < p.min_data_in_leaf:
                continue
            if ch < p.min_sum_hessian_in_leaf or rh < p.min_sum_hessian_in_leaf:
                continue
            gain = cg * cg / (ch + p.lambda_l2 + 1e-15) \
                + rg * rg / (rh + p.lambda_l2 + 1e-15) \
                - pg * pg / (ph + p.lambda_l2 + 1e-15)
            if gain > best[0]:
                best = (gain, j, t)
    return best


def test_best_split_matches_exhaustive(rng):
    f, b = 5, 16
    hist = np.abs(rng.randn(f, b, 3)).astype(np.float32)
    hist[:, :, 0] = rng.randn(f, b)  # gradients signed
    hist[:, :, 2] = rng.randint(1, 20, size=(f, b))  # counts
    pg = float(hist[0, :, 0].sum())
    ph = float(hist[0, :, 1].sum())
    pc = float(hist[0, :, 2].sum())
    # make parent sums consistent: use feature 0 as the truth for all features
    for j in range(1, f):
        scale_g = pg / max(hist[j, :, 0].sum(), 1e-9)
        hist[j, :, 0] *= scale_g
        hist[j, :, 1] *= ph / max(hist[j, :, 1].sum(), 1e-9)
        hist[j, :, 2] *= pc / max(hist[j, :, 2].sum(), 1e-9)

    p = SplitParams(min_data_in_leaf=1.0, min_sum_hessian_in_leaf=1e-3)
    num_bins = jnp.full((f,), b, jnp.int32)
    nan_bin = jnp.full((f,), b - 1, jnp.int32)
    has_nan = jnp.zeros((f,), bool)
    is_cat = jnp.zeros((f,), bool)
    mask = jnp.ones((f,), bool)
    sp = best_split(jnp.asarray(hist), pg, ph, pc, num_bins, nan_bin,
                    has_nan, is_cat, mask, p)
    want_gain, want_f, want_t = _np_best_split_numeric(hist, pg, ph, pc, p)
    got_gain = float(sp.gain)
    # gains measured relative to different baselines (shift); compare choice
    assert int(sp.feature) == want_f
    assert int(sp.bin) == want_t


def test_grow_tree_pure_feature(rng):
    """A single perfectly separating feature should produce a one-split tree
    routing rows exactly."""
    n = 400
    x = (np.arange(n) % 2).astype(np.uint8)  # bins 0/1
    binned = np.stack([x, rng.randint(0, 4, n).astype(np.uint8)], axis=1)
    grad = np.where(x == 0, 1.0, -1.0).astype(np.float32)
    hess = np.ones(n, np.float32)
    params = GrowerParams(num_leaves=4, num_bins=8, min_data_in_leaf=1.0)
    tree, row_leaf = grow_tree(
        jnp.asarray(binned), jnp.asarray(grad), jnp.asarray(hess),
        jnp.ones(n, jnp.float32),
        jnp.asarray([2, 4], jnp.int32), jnp.asarray([0, 0], jnp.int32),
        jnp.zeros(2, bool), jnp.zeros(2, bool), jnp.ones(2, bool), params)
    assert int(tree.num_nodes) >= 1
    assert int(tree.split_feature[0]) == 0
    # leaf values must have opposite signs matching -grad direction
    rl = np.asarray(row_leaf)
    lv = np.asarray(tree.leaf_value)
    vals = lv[rl]
    assert np.all(vals[x == 0] < 0)
    assert np.all(vals[x == 1] > 0)


def test_route_matches_training_partition(rng):
    n, f, b = 600, 6, 16
    binned = rng.randint(0, b, size=(n, f)).astype(np.uint8)
    grad = rng.randn(n).astype(np.float32)
    hess = np.ones(n, np.float32)
    params = GrowerParams(num_leaves=8, num_bins=b, min_data_in_leaf=5.0)
    num_bins = jnp.full((f,), b, jnp.int32)
    nan_bin = jnp.full((f,), b - 1, jnp.int32)
    has_nan = jnp.zeros((f,), bool)
    is_cat = jnp.zeros((f,), bool)
    tree, row_leaf = grow_tree(
        jnp.asarray(binned), jnp.asarray(grad), jnp.asarray(hess),
        jnp.ones(n, jnp.float32), num_bins, nan_bin, has_nan, is_cat,
        jnp.ones(f, bool), params)
    routed = route_one_tree(
        jnp.asarray(binned), tree.split_feature, tree.split_bin,
        tree.cat_bitset, tree.default_left, tree.left_child,
        tree.right_child, tree.num_nodes, nan_bin, is_cat)
    np.testing.assert_array_equal(np.asarray(routed), np.asarray(row_leaf))
