"""Test configuration: force CPU backend with 8 virtual devices.

Mirrors the reference's test strategy (SURVEY.md §4): correctness tests run
against a host build; distributed tests simulate a cluster on one machine
(reference: tests/distributed/_test_distributed.py spawns N local CLI
processes). Here the 8 virtual XLA CPU devices stand in for an 8-chip TPU
slice so sharding/collective paths compile and execute for real.
"""
import os

# must happen before any backend initialization; override any ambient platform
# (the dev box exposes the TPU via an "axon" platform whose sitecustomize sets
# jax.config directly — the env var alone is not enough, so force the config)
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.devices()[0].platform == "cpu"

# persistent compile cache: the suite is compile-dominated on CPU
_cache_dir = os.path.join(os.path.dirname(__file__), ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _route_flight_dumps_to_tmp(tmp_path, monkeypatch):
    """Flight dumps must never land in the checkout: a test that trips a
    crash dump without LGBM_TPU_FLIGHT_PATH or a checkpoint dir used to
    fall back to the CWD (a stray lgbm_tpu_flight_*.jsonl once sat at
    the repo root). Point the recorder's last-resort fallback directory
    at the test's tmpdir; explicit env/path/dump-dir routing (what the
    flight tests assert) is untouched."""
    from lightgbm_tpu.obs import flight
    monkeypatch.setattr(flight, "_FALLBACK_DIR", str(tmp_path))


@pytest.fixture
def rng():
    return np.random.RandomState(42)


@pytest.fixture
def compile_guard():
    """Count jit compilations inside a test; call
    ``compile_guard.assert_no_compiles()`` (or read ``.lowerings``) after
    the steady-state region (lightgbm_tpu.analysis.guards)."""
    from lightgbm_tpu.analysis import guards
    with guards.compile_counter() as counts:
        yield counts


@pytest.fixture
def lock_order_witness():
    """Instrument every lock created inside the test with the runtime
    lock-order witness (lightgbm_tpu.analysis.guards.lock_witness); at
    teardown the test fails if any cross-thread lock-order cycle was
    observed. Arm it by listing the fixture BEFORE constructing servers
    or boosters so their locks are created instrumented."""
    from lightgbm_tpu.analysis import guards
    with guards.lock_witness() as w:
        yield w
    w.assert_no_cycles("lock_order_witness fixture")


@pytest.fixture
def resource_leak_witness():
    """Snapshot live threads / open fds / entered trace sessions /
    retained-program cache sizes at fixture setup; at teardown the test
    fails (guards.ResourceLeakError) if the scope did not give
    everything back — the runtime half of tpulint R012. Warm compiles
    and long-lived fixtures must happen BEFORE this fixture in the
    argument list (or inside the test before the chaos region) so cache
    warms don't read as leaks."""
    from lightgbm_tpu.analysis import guards
    with guards.resource_witness() as w:
        yield w
    w.assert_no_leaks("resource_leak_witness fixture")


@pytest.fixture
def no_d2h_guard():
    """Fail the test on any device->host materialization
    (lightgbm_tpu.analysis.guards.no_host_transfers)."""
    from lightgbm_tpu.analysis import guards
    with guards.no_host_transfers():
        yield
