"""Pod-scale static flight check: tier-1 gate + memory-model fixtures.

The fast lane runs the 4-chip flight check over the default contract set
(ISSUE 15): every distributed learner-mode step program lowered under a
faked 4-chip mesh verifies replication/schedule/inventory/memory against
the checked-in contracts, the GSPMD serving dispatch verifies alongside,
and the full-Allstate 8-chip shape (13.2M x 4228) must statically fit
the 16 GiB/chip go/no-go budget — all on the CPU backend, no hardware.

Seeded-regression tests prove the check CATCHES what it claims to: a
deliberately replicated row-sharded operand (the serial lowering's
global-row parameters presented as a 4-shard per-chip program), a
contract memory budget overrun, inventory creep, and per-rank schedule
drift each produce a failing, actionable finding.

The memory model itself is pinned by hand-built HLO fixtures with known
buffer liveness (disjoint / overlapping / donated / while-carried /
conditional-aliased) asserting EXACT peak-byte estimates.

The 32-chip and 2-D mesh sweeps are slow-lane (the 32-way fold needs its
own virtual-device env, so it runs through the ``scripts/tpulint spmd``
CLI in a subprocess — which also covers the CLI path end to end).
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

from lightgbm_tpu.analysis import memory, spmd_check
from lightgbm_tpu.analysis.hlo_check import load_contract, verify_mode

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MiB = 1 << 20


def _jax_device_count():
    import jax
    return len(jax.devices())


# ---------------------------------------------------------------------------
# memory-model fixtures: hand-built HLO with known liveness, exact peaks
# ---------------------------------------------------------------------------
def _module(body, alias=""):
    head = "HloModule fixture"
    if alias:
        head += f", input_output_alias={{ {alias} }}"
    return head + "\n\n" + textwrap.dedent(body)


# 1 MiB f32 buffer spelled as a shape
BUF = "f32[512,512]"
BUF_B = 512 * 512 * 4


def test_memory_disjoint_lifetimes_reuse():
    """Two big temporaries with DISJOINT lifetimes: the first dies at its
    last use before the second is born, so the peak holds one at a time
    (plus the live parameter and the root)."""
    text = _module(f"""
        ENTRY %main (p0: {BUF}) -> {BUF} {{
          %p0 = {BUF}{{1,0}} parameter(0)
          %t1 = {BUF}{{1,0}} add({BUF}{{1,0}} %p0, {BUF}{{1,0}} %p0)
          %s1 = f32[] reduce({BUF}{{1,0}} %t1)
          %t2 = {BUF}{{1,0}} multiply({BUF}{{1,0}} %p0, {BUF}{{1,0}} %p0)
          ROOT %r = {BUF}{{1,0}} subtract({BUF}{{1,0}} %t2, {BUF}{{1,0}} %t2)
        }}
    """)
    est = memory.estimate(text)
    # t1 dies at %s1, before t2 is born; the peak sits at ROOT with
    # p0 + t2 + r coexisting (3 buffers — t1's slot came back)
    assert est.peak_bytes == 3 * BUF_B
    assert est.argument_bytes == BUF_B
    assert est.output_bytes == BUF_B


def test_memory_overlapping_lifetimes_sum():
    """Both temporaries live into the root: they must coexist."""
    text = _module(f"""
        ENTRY %main (p0: {BUF}) -> {BUF} {{
          %p0 = {BUF}{{1,0}} parameter(0)
          %t1 = {BUF}{{1,0}} add({BUF}{{1,0}} %p0, {BUF}{{1,0}} %p0)
          %t2 = {BUF}{{1,0}} multiply({BUF}{{1,0}} %p0, {BUF}{{1,0}} %p0)
          ROOT %r = {BUF}{{1,0}} subtract({BUF}{{1,0}} %t1, {BUF}{{1,0}} %t2)
        }}
    """)
    est = memory.estimate(text)
    assert est.peak_bytes == 4 * BUF_B        # p0 + t1 + t2 + r


def test_memory_donated_param_updates_in_place():
    """A donated parameter's in-place update chain allocates nothing:
    the output IS the input buffer (input_output_alias)."""
    text = _module(f"""
        ENTRY %main (p0: {BUF}) -> {BUF} {{
          %p0 = {BUF}{{1,0}} parameter(0)
          ROOT %upd = {BUF}{{1,0}} dynamic-update-slice({BUF}{{1,0}} %p0, f32[1,512]{{1,0}} %p0, s32[] %p0)
        }}
    """, alias="{}: (0, {}, must-alias)")
    est = memory.estimate(text)
    assert est.peak_bytes == BUF_B            # one buffer, ever
    assert est.output_bytes == 0              # aliased away


def test_memory_undonated_same_update_doubles():
    """The SAME program without donation: the update is a fresh copy."""
    text = _module(f"""
        ENTRY %main (p0: {BUF}) -> {BUF} {{
          %p0 = {BUF}{{1,0}} parameter(0)
          ROOT %upd = {BUF}{{1,0}} dynamic-update-slice({BUF}{{1,0}} %p0, f32[1,512]{{1,0}} %p0, s32[] %p0)
        }}
    """)
    est = memory.estimate(text)
    assert est.peak_bytes == 2 * BUF_B


def test_memory_while_carry_aliases():
    """A while's carried tuple is updated in place: body iterations do
    not double the carry, and the loop's result aliases its operand."""
    text = _module(f"""
        %body (bp: ({BUF}, s32[])) -> ({BUF}, s32[]) {{
          %bp = ({BUF}{{1,0}}, s32[]) parameter(0)
          %w = {BUF}{{1,0}} get-tuple-element(({BUF}{{1,0}}, s32[]) %bp), index=0
          %i = s32[] get-tuple-element(({BUF}{{1,0}}, s32[]) %bp), index=1
          %w2 = {BUF}{{1,0}} dynamic-update-slice({BUF}{{1,0}} %w, f32[1,512]{{1,0}} %w, s32[] %i)
          ROOT %out = ({BUF}{{1,0}}, s32[]) tuple({BUF}{{1,0}} %w2, s32[] %i)
        }}

        %cond (cp: ({BUF}, s32[])) -> pred[] {{
          %cp = ({BUF}{{1,0}}, s32[]) parameter(0)
          ROOT %lt = pred[] compare(s32[] %cp, s32[] %cp), direction=LT
        }}

        ENTRY %main (p0: {BUF}) -> {BUF} {{
          %p0 = {BUF}{{1,0}} parameter(0)
          %iv = s32[] constant(0)
          %init = ({BUF}{{1,0}}, s32[]) tuple({BUF}{{1,0}} %p0, s32[] %iv)
          %loop = ({BUF}{{1,0}}, s32[]) while(({BUF}{{1,0}}, s32[]) %init), condition=%cond, body=%body
          ROOT %res = {BUF}{{1,0}} get-tuple-element(({BUF}{{1,0}}, s32[]) %loop), index=0
        }}
    """)
    est = memory.estimate(text)
    # p0 (the carry slot, updated in place) + the s32 iv + the cond
    # computation's pred[] byte; the body's dynamic-update-slice
    # consumes the carried slot at its own byte size, so it allocates
    # nothing — the whole loop costs one predicate over its carry
    assert est.peak_bytes == BUF_B + 4 + 1


def test_memory_conditional_result_aliases_branch_operand():
    """A conditional's result aliases its branch operands (the ISSUE 15
    pod-gate fix): the taken branch's in-place update returns the
    caller's buffer, not a second copy."""
    text = _module(f"""
        %true_b (tp: ({BUF})) -> ({BUF}) {{
          %tp = ({BUF}{{1,0}}) parameter(0)
          %tw = {BUF}{{1,0}} get-tuple-element(({BUF}{{1,0}}) %tp), index=0
          %tu = {BUF}{{1,0}} dynamic-update-slice({BUF}{{1,0}} %tw, f32[1,512]{{1,0}} %tw, s32[] %tw)
          ROOT %tr = ({BUF}{{1,0}}) tuple({BUF}{{1,0}} %tu)
        }}

        %false_b (fp: ({BUF})) -> ({BUF}) {{
          %fp = ({BUF}{{1,0}}) parameter(0)
          ROOT %fr = ({BUF}{{1,0}}) tuple(({BUF}{{1,0}}) %fp)
        }}

        ENTRY %main (p0: {BUF}, pr: s32[]) -> {BUF} {{
          %p0 = {BUF}{{1,0}} parameter(0)
          %pr = s32[] parameter(1)
          %arg = ({BUF}{{1,0}}) tuple({BUF}{{1,0}} %p0)
          %sel = ({BUF}{{1,0}}) conditional(s32[] %pr, ({BUF}{{1,0}}) %arg, ({BUF}{{1,0}}) %arg), branch_computations={{%true_b, %false_b}}
          ROOT %res = {BUF}{{1,0}} get-tuple-element(({BUF}{{1,0}}) %sel), index=0
        }}
    """)
    est = memory.estimate(text)
    assert est.peak_bytes == BUF_B + 4        # p0 + the predicate


def test_memory_called_transient_adds_at_callsite():
    """A call target's INTERNAL temporary raises the caller's peak at
    the call site, then dies with the call."""
    text = _module(f"""
        %helper (hp: {BUF}) -> f32[] {{
          %hp = {BUF}{{1,0}} parameter(0)
          %big = {BUF}{{1,0}} add({BUF}{{1,0}} %hp, {BUF}{{1,0}} %hp)
          ROOT %sum = f32[] reduce({BUF}{{1,0}} %big)
        }}

        ENTRY %main (p0: {BUF}) -> f32[] {{
          %p0 = {BUF}{{1,0}} parameter(0)
          ROOT %c = f32[] call({BUF}{{1,0}} %p0), to_apply=%helper
        }}
    """)
    est = memory.estimate(text)
    # p0 + the call's own f32 result + the helper's transient at the
    # call site (%big plus its f32 ROOT)
    assert est.peak_bytes == 2 * BUF_B + 8


def test_contract_budgets_are_sticky():
    """contract_block keeps a previously recorded budget verbatim, so an
    estimate creeping past it FAILS check instead of re-basing."""
    text = _module(f"""
        ENTRY %main (p0: {BUF}) -> {BUF} {{
          %p0 = {BUF}{{1,0}} parameter(0)
          ROOT %r = {BUF}{{1,0}} add({BUF}{{1,0}} %p0, {BUF}{{1,0}} %p0)
        }}
    """)
    prior = {"budget_bytes": 123456789}
    block = memory.contract_block(text, prior=prior)
    assert block["budget_bytes"] == 123456789
    fresh = memory.contract_block(text)
    assert fresh["budget_bytes"] >= fresh["estimate_bytes"]


# ---------------------------------------------------------------------------
# seeded regressions (pure text: the checks must CATCH these)
# ---------------------------------------------------------------------------
def _fake_cap(hlo_text, row_dims, num_shards, mode="seeded", mesh="4"):
    return spmd_check.FlightCapture(mode, mesh, "step", hlo_text,
                                    set(row_dims), num_shards)


def test_seeded_replicated_operand_is_caught():
    """A per-chip program whose parameter still carries the GLOBAL row
    dimension = the accidental-replication OOM; the flight check must
    name the parameter and the fix."""
    text = _module("""
        ENTRY %main (p0: u8[4096,64]) -> f32[] {
          %p0 = u8[4096,64]{1,0} parameter(0)
          ROOT %s = f32[] reduce(u8[4096,64]{1,0} %p0)
        }
    """)
    findings = spmd_check.check_row_replication(
        text, {4096}, 4, "seeded", "4")
    assert len(findings) == 1
    f = findings[0]
    assert f.check == "spmd-replication"
    assert "GLOBAL row dimension 4096" in f.message
    assert "4x" in f.message
    # the healthy per-shard program (4096/4 rows) is clean
    ok = text.replace("4096", "1024")
    assert not spmd_check.check_row_replication(ok, {4096}, 4,
                                                "seeded", "4")


def test_seeded_memory_budget_overrun_fails_check():
    """An estimate above the contract's recorded budget is a failing
    memory finding (the budget only moves by deliberate edit)."""
    text = _module(f"""
        ENTRY %main (p0: {BUF}) -> {BUF} {{
          %p0 = {BUF}{{1,0}} parameter(0)
          %t1 = {BUF}{{1,0}} add({BUF}{{1,0}} %p0, {BUF}{{1,0}} %p0)
          ROOT %r = {BUF}{{1,0}} multiply({BUF}{{1,0}} %t1, {BUF}{{1,0}} %t1)
        }}
    """)
    contract = {"memory": {"4": {"budget_bytes": 2 * BUF_B,
                                 "estimate_bytes": 2 * BUF_B}}}
    findings = spmd_check.check_flight_memory(text, contract, "seeded", "4")
    assert len(findings) == 1
    assert findings[0].check == "memory"
    assert "exceeds" in findings[0].message
    # raising the budget (the deliberate human edit) clears it
    contract["memory"]["4"]["budget_bytes"] = 4 * BUF_B
    assert not spmd_check.check_flight_memory(text, contract, "seeded", "4")


def test_seeded_inventory_creep_is_caught():
    text = _module("""
        ENTRY %main (p0: f32[1024]) -> f32[1024] {
          %p0 = f32[1024]{0} parameter(0)
          ROOT %ag = f32[1024]{0} all-gather(f32[256]{0} %p0), replica_groups={{0,1,2,3}}, dimensions={0}
        }
    """)
    contract = {"spmd": {"4": {"collectives": ["all-reduce"]}}}
    findings = spmd_check.check_inventory(text, contract, "seeded", "4")
    assert len(findings) == 1
    assert "all-gather" in findings[0].message
    assert "tpulint spmd --update" in findings[0].message


def test_seeded_schedule_drift_is_caught():
    text = _module("""
        ENTRY %main (p0: f32[1024]) -> f32[1024] {
          %p0 = f32[1024]{0} parameter(0)
          ROOT %ar = f32[1024]{0} all-reduce(f32[1024]{0} %p0), replica_groups={}
        }
    """)
    contract = {"spmd": {"4": {
        "collectives": ["all-reduce", "reduce-scatter"],
        "schedule": [["reduce-scatter", 4096], ["all-reduce", 4096]]}}}
    findings = spmd_check.check_schedule_drift(text, contract, "seeded", "4")
    assert len(findings) == 1
    assert "schedule drifted" in findings[0].message


def test_ragged_and_partial_replica_groups_are_caught():
    part = _module("""
        ENTRY %main (p0: f32[1024]) -> f32[1024] {
          ROOT %ar = f32[1024]{0} all-reduce(f32[1024]{0} %p0), replica_groups={{0,1},{2}}
        }
    """)
    # num_partitions defaults to 1 without the header attr; force 4
    part = part.replace("HloModule fixture",
                        "HloModule fixture, num_partitions=4")
    findings = spmd_check.check_rank_schedule(part, "seeded", "4")
    msgs = "\n".join(f.message for f in findings)
    assert "missing [3]" in msgs
    assert "ragged replica groups" in msgs


def test_iota_replica_groups_resolve():
    from lightgbm_tpu.analysis.hlo import parse_instructions, replica_groups_of
    text = _module("""
        ENTRY %main (p0: f32[8]) -> f32[8] {
          ROOT %ar = f32[8]{0} all-reduce(f32[8]{0} %p0), replica_groups=[2,4]<=[8]
        }
    """)
    (instr,) = [i for i in parse_instructions(text)
                if i.opcode == "all-reduce"]
    assert replica_groups_of(instr) == [[0, 1, 2, 3], [4, 5, 6, 7]]
    text_t = text.replace("[2,4]<=[8]", "[2,4]<=[4,2]T(1,0)")
    (instr,) = [i for i in parse_instructions(text_t)
                if i.opcode == "all-reduce"]
    assert replica_groups_of(instr) == [[0, 2, 4, 6], [1, 3, 5, 7]]


# ---------------------------------------------------------------------------
# tier-1 gate: the 4-chip flight check on the default contract set
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def flights():
    """Lower every flight mode under the 4-chip fake mesh, once."""
    if _jax_device_count() < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    return {mode: spmd_check.capture_flight(mode, "4")
            for mode in spmd_check.FLIGHT_MODES}


def test_flight_check_clean_on_default_meshes(flights):
    for mode, cap in flights.items():
        contract = load_contract(mode)
        findings = spmd_check.check_flight(cap, contract)
        assert not findings, "\n".join(f.render() for f in findings)
        # the captured program really is per-chip: 4 row shards
        assert cap.num_shards == 4


def test_flight_captures_match_recorded_blocks(flights):
    """The checked-in spmd blocks are the live lowering's facts — drift
    means scripts/tpulint spmd --update was skipped after a comm
    change."""
    for mode, cap in flights.items():
        spmd = load_contract(mode)["spmd"]["4"]
        assert spmd["schedule"] == spmd_check.schedule_of(cap.hlo_text)


def test_serial_lowering_presented_as_sharded_fails(flights):
    """The harness-level replication seed: a single-chip lowering's
    parameters carry GLOBAL row dims; presenting it as a 4-shard
    program must raise spmd-replication findings (this is exactly what
    an accidentally replicated bin matrix looks like per chip)."""
    from lightgbm_tpu.analysis.hlo_check import capture_mode
    cap = capture_mode("serial_compact")
    g = cap.gbdt
    row_dims = {int(g.num_data)}
    c = getattr(g, "_compact", None)
    if c and c.get("work") is not None:
        row_dims.add(int(c["work"].shape[0]))
    findings = spmd_check.check_row_replication(
        cap.hlo_text, row_dims, 4, "serial_compact", "4")
    assert findings, "global-row parameters must be flagged as replicated"
    assert all(f.check == "spmd-replication" for f in findings)


def test_sharded_serving_dispatch_clean(flights):
    findings = spmd_check.verify_serving("4")
    assert not findings, "\n".join(f.render() for f in findings)


def test_allstate_pod_gate_passes_16gib(flights):
    """ROADMAP 2's static go/no-go: the full 13.2M x 4228 pod shape fits
    16 GiB/chip, the contract records the estimate, and the gate run
    itself is clean."""
    contract = load_contract("allstate_pod")
    block = contract["memory"]["8"]
    assert block["budget_bytes"] == 16 * (1 << 30)
    assert 0 < block["estimate_bytes"] <= block["budget_bytes"]
    assert block["headroom_bytes"] == \
        block["budget_bytes"] - block["estimate_bytes"]
    findings = spmd_check.verify_flight_shape("allstate_pod")
    assert not findings, "\n".join(f.render() for f in findings)


def test_allstate_pod_budget_overrun_fails(flights, tmp_path, monkeypatch):
    """Seeded budget regression through the REAL verify path: shrink the
    recorded budget below the estimate and verify_flight_shape must
    fail with the memory finding (what verify_contracts/tier-1 would
    show after a footprint regression)."""
    from lightgbm_tpu.analysis import hlo_check
    src = load_contract("allstate_pod")
    doctored = json.loads(json.dumps(src))
    doctored["memory"]["8"]["budget_bytes"] = \
        doctored["memory"]["8"]["estimate_bytes"] // 2
    (tmp_path / "allstate_pod.json").write_text(json.dumps(doctored))
    real_path = hlo_check.contract_path

    def fake_path(name):
        if name == "allstate_pod":
            return str(tmp_path / "allstate_pod.json")
        return real_path(name)

    monkeypatch.setattr(hlo_check, "contract_path", fake_path)
    monkeypatch.setattr(spmd_check, "contract_path", fake_path)
    # the spec's own budget is the FLOOR default; the doctored contract
    # must win (budgets are the contract's, not the spec's, once set)
    findings = spmd_check.verify_flight_shape("allstate_pod")
    mem = [f for f in findings if f.check == "memory"]
    assert mem, "halved budget must fail the gate"
    assert "exceeds" in mem[0].message


def test_native_memory_regression_fails_verify_mode():
    """hlo_check's native-mesh half of the budget gate: verify_mode on a
    contract whose recorded budget sits below the live estimate fails
    (the seeded diff verify_contracts.py must catch)."""
    from lightgbm_tpu.analysis.hlo_check import capture_mode
    if _jax_device_count() < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    cap = capture_mode("serial_compact")
    contract = json.loads(json.dumps(load_contract("serial_compact")))
    est = contract["memory"]["1"]["estimate_bytes"]
    contract["memory"]["1"]["budget_bytes"] = est // 2
    findings = verify_mode("serial_compact", contract, cap)
    assert any(f.check == "memory" and "exceeds" in f.message
               for f in findings)


# ---------------------------------------------------------------------------
# slow lane: 2-D mesh fold in-process, 32-chip sweep via the CLI
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_2d_mesh_fold_clean():
    """4x2 rows x features: the masked GSPMD grower's bin matrix shards
    over BOTH axes; the same static checks must hold (no recorded
    blocks for this mesh -> inventory falls back to the native allow)."""
    if _jax_device_count() < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    for mode in ("data_scatter", "voting"):
        cap = spmd_check.capture_flight(mode, "4x2")
        contract = load_contract(mode)
        findings = spmd_check.check_flight(cap, contract)
        assert not findings, "\n".join(f.render() for f in findings)
        assert cap.num_shards == 4            # the row factor only


@pytest.mark.slow
def test_32_chip_sweep_via_cli():
    """The 32-way fold needs 32 virtual devices, so it runs through the
    CLI (which sizes xla_force_host_platform_device_count from --mesh):
    the full mode matrix must come back clean."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "tpulint"),
         "spmd", "--mesh", "32", "--no-shapes", "--no-serving"],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=900)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "flight check clean" in proc.stdout
