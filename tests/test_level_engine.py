"""Serving engines (ROADMAP item 4): level-order relayout, quantized
leaf slabs, precomputed TreeSHAP UNWIND tables, background contrib lane.

The acceptance surface this file pins:

  * the level engine is BIT-IDENTICAL to the depth-batched walk across
    the full parity matrix — NaN defaults, categorical bitsets, EFB
    col_of, multiclass, iteration windows, pred_leaf;
  * trees deeper than tpu_level_depth_cap fall back to the walk per
    bucket (resolve-level demotion with a warning), answers unchanged;
  * resolve_serving_engine honors the user > env > autotune > heuristic
    order, and the autotuner's serving race persists + reuses winners;
  * quantized serving stays within the RECORDED max-score-error bound
    (leaf_quant_bound), the bound is exact/tight on a single tree, and
    quantized scores are identical across the walk and level routers;
  * the precomputed UNWIND tables are bit-identical to the per-row loop
    kernel, match the host reference, sum to the raw score, respect
    the tpu_shap_table_mb budget gate, and their cache is bounded by
    the R012 resource witness via the registered cache probe;
  * the background contrib lane only cuts a batch when no live
    foreground request is queued and never reorders foreground FIFO;
  * mixed-endpoint chaos traffic with a mid-stream hot-swap lowers 0
    programs and survives the lock-order + resource-leak witnesses.
"""
import collections
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.analysis import guards
from lightgbm_tpu.engines import autotune, registry
from lightgbm_tpu.ops.predict import quantize_leaves
from lightgbm_tpu.serving.coalescer import MicroBatchCoalescer, ServeFuture

from utils import FAST_PARAMS, binary_data, multiclass_data

LADDER = "32,256"


def _params(**kw):
    # max_depth pins the stack under tpu_level_depth_cap (default 10) so
    # the parity matrix genuinely exercises the level router instead of
    # silently demoting to the walk
    return dict(FAST_PARAMS, objective="binary", max_depth=8,
                tpu_predict_buckets=LADDER, **kw)


def _engines(bst, fn):
    """(level_result, walk_result) of ``fn(bst)`` under each router."""
    g = bst._gbdt
    g.config.set({"tpu_predict_engine": "level"})
    try:
        lvl = fn(bst)
        memo = getattr(g, "_serve_engine_memo", None) or {}
        assert "level" in memo.values(), \
            "level engine never engaged — parity run is vacuous"
    finally:
        g.config.set({"tpu_predict_engine": "batched"})
    return lvl, fn(bst)


# ----------------------------------------------------- level parity matrix
def test_level_parity_nan_defaults():
    X, y = binary_data()
    Xn = np.array(X, np.float64)
    rng = np.random.RandomState(0)
    Xn[rng.rand(*Xn.shape) < 0.08] = np.nan
    p = _params(use_missing=True)
    bst = lgb.train(p, lgb.Dataset(Xn, label=y, params=p), 12)
    q = Xn[:257]
    (raw_l, leaf_l), (raw_w, leaf_w) = _engines(
        bst, lambda b: (b.predict(q, raw_score=True),
                        b.predict(q, pred_leaf=True)))
    np.testing.assert_array_equal(raw_l, raw_w)
    np.testing.assert_array_equal(leaf_l, leaf_w)


def test_level_parity_categorical_bitsets():
    rng = np.random.RandomState(1)
    n = 900
    Xc = rng.randn(n, 6)
    Xc[:, 0] = rng.randint(0, 40, n)   # wide cats -> multi-word bitset
    Xc[:, 1] = rng.randint(0, 6, n)
    y = ((np.isin(Xc[:, 0], [1, 3, 5, 8, 13, 21, 34])
          | (Xc[:, 1] > 3)) ^ (rng.rand(n) < 0.05)).astype(np.float64)
    p = _params(max_cat_to_onehot=2)
    bst = lgb.train(p, lgb.Dataset(Xc, label=y, params=p,
                                   categorical_feature=[0, 1]), 12)
    assert any(np.any(m.cat_bitset) for m in bst._gbdt.models), \
        "test did not exercise categorical splits"
    q = Xc[:300]
    (raw_l, leaf_l), (raw_w, leaf_w) = _engines(
        bst, lambda b: (b.predict(q, raw_score=True),
                        b.predict(q, pred_leaf=True)))
    np.testing.assert_array_equal(raw_l, raw_w)
    np.testing.assert_array_equal(leaf_l, leaf_w)


def test_level_parity_efb_col_of():
    rng = np.random.RandomState(2)
    n, groups, card = 900, 50, 6       # 300 one-hot cols (EFB needs >= 256)
    X = np.zeros((n, groups * card), np.float64)
    for g in range(groups):
        X[np.arange(n), g * card + rng.randint(0, card, n)] = 1.0
    y = (X[:, ::card].sum(1) + 0.3 * rng.randn(n) > 0.5).astype(np.float64)
    p = _params(enable_bundle=True)
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p), 8)
    assert bst._gbdt._efb is not None, "test did not exercise EFB"
    q = X[:200]
    raw_l, raw_w = _engines(bst, lambda b: b.predict(q, raw_score=True))
    np.testing.assert_array_equal(raw_l, raw_w)


def test_level_parity_multiclass():
    X, y = multiclass_data()
    p = dict(FAST_PARAMS, objective="multiclass", num_class=3,
             max_depth=8, tpu_predict_buckets=LADDER)
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p), 6)
    q = X[:200]
    lvl, walk = _engines(bst, lambda b: b.predict(q))
    np.testing.assert_array_equal(lvl, walk)


def test_level_parity_windowed():
    X, y = binary_data()
    p = _params()
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p), 10)
    q = X[:100]
    for kw in ({"num_iteration": 4}, {"start_iteration": 3},
               {"start_iteration": 2, "num_iteration": 5}):
        lvl, walk = _engines(
            bst, lambda b: b.predict(q, raw_score=True, **kw))
        np.testing.assert_array_equal(lvl, walk)


def test_level_depth_cap_demotes_to_walk():
    # registry level: an explicit level request over the cap keeps the
    # walk (with the quantized entry id when a slab rides along)
    res = registry.resolve_serving_engine(
        {"tpu_predict_engine": "level"}, depth=12, level_cap=10,
        tree_bucket=16, platform="cpu")
    assert (res.engine, res.source) == ("walk", "user")
    res = registry.resolve_serving_engine(
        {"tpu_predict_engine": "level"}, depth=5, level_cap=10,
        tree_bucket=16, platform="cpu")
    assert (res.engine, res.entry_id) == ("level", "serve_level")
    res = registry.resolve_serving_engine(
        {"tpu_predict_engine": "level"}, depth=5, level_cap=10,
        tree_bucket=16, platform="cpu", quant="int8")
    assert (res.engine, res.entry_id) == ("level", "serve_qleaf")
    # end to end: a cap below the stacked depth serves via the walk
    # fallback and still answers exactly
    X, y = binary_data()
    p = _params()
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p), 8)
    ref = bst.predict(X[:64], raw_score=True)
    g = bst._gbdt
    g.config.set({"tpu_predict_engine": "level",
                  "tpu_level_depth_cap": 1})
    try:
        g._serve_engine_memo = None
        np.testing.assert_array_equal(
            bst.predict(X[:64], raw_score=True), ref)
    finally:
        g.config.set({"tpu_predict_engine": "batched",
                      "tpu_level_depth_cap": 10})
        g._serve_engine_memo = None


# ------------------------------------------------ resolve order + race
def test_serving_resolve_order_user_env_heuristic(monkeypatch):
    monkeypatch.setenv("LGBM_TPU_PREDICT_ENGINE", "level")
    # user beats env
    res = registry.resolve_serving_engine(
        {"tpu_predict_engine": "walk"}, depth=4, level_cap=10,
        platform="cpu")
    assert (res.engine, res.source) == ("walk", "user")
    # env beats the heuristic when the knob is unset
    res = registry.resolve_serving_engine({}, depth=4, level_cap=10,
                                          platform="cpu")
    assert (res.engine, res.source) == ("level", "env")
    monkeypatch.delenv("LGBM_TPU_PREDICT_ENGINE")
    # auto, unarmed: shallow stacks take the level heuristic, deep the walk
    res = registry.resolve_serving_engine(
        {"tpu_predict_engine": "auto"}, depth=4, level_cap=10,
        platform="cpu")
    assert (res.engine, res.source) == ("level", "default")
    res = registry.resolve_serving_engine(
        {"tpu_predict_engine": "auto"}, depth=12, level_cap=10,
        platform="cpu")
    assert (res.engine, res.source) == ("walk", "default")


def test_serving_autotune_race_persists_winner(tmp_path, monkeypatch):
    """auto + armed cache: the race times the real runners once, the
    winner persists, and the next resolve reuses it without re-racing."""
    times = iter([0.004, 0.001])        # walk slow, level fast
    monkeypatch.setattr(autotune, "_time_candidate",
                        lambda fn, reps=0: next(times))
    cfg = {"tpu_predict_engine": "auto", "tpu_autotune": "first_run",
           "tpu_autotune_cache": str(tmp_path / "at.json")}
    calls = []

    def racer():
        calls.append(1)
        return ({"walk": lambda: None, "level": lambda: None}, 2048)

    res = registry.resolve_serving_engine(cfg, depth=5, level_cap=10,
                                          tree_bucket=16, platform="cpu",
                                          racer=racer)
    assert (res.engine, res.source) == ("level", "autotune")
    assert len(calls) == 1
    # second resolve: cache hit, no second race (the stub timer is
    # exhausted — a re-race would raise StopIteration)
    res2 = registry.resolve_serving_engine(cfg, depth=5, level_cap=10,
                                           tree_bucket=16, platform="cpu",
                                           racer=racer)
    assert (res2.engine, res2.source) == ("level", "autotune")
    assert len(calls) == 1


# -------------------------------------------------- quantized leaf slabs
@pytest.fixture(scope="module")
def quant_booster():
    X, y = binary_data()
    p = _params()
    return lgb.train(p, lgb.Dataset(X, label=y, params=p), 10), X


def _with_quant(bst, mode, fn):
    g = bst._gbdt
    g.config.set({"tpu_leaf_quant": mode})
    g._invalidate_device_trees()
    try:
        return fn(bst)
    finally:
        g.config.set({"tpu_leaf_quant": "off"})
        g._invalidate_device_trees()


@pytest.mark.parametrize("mode", ["int8", "f16"])
def test_quant_within_recorded_bound(quant_booster, mode):
    bst, X = quant_booster
    ref = bst.predict(X[:256], raw_score=True)
    q_raw, bound = _with_quant(
        bst, mode, lambda b: (b.predict(X[:256], raw_score=True),
                              b._gbdt.leaf_quant_bound()))
    assert bound is not None and bound >= 0.0
    diff = np.max(np.abs(q_raw - ref))
    assert diff <= bound + 1e-6, (diff, bound)
    if mode == "int8":
        assert diff > 0.0, "int8 quantization changed nothing — vacuous"


def test_quant_identical_across_routers(quant_booster):
    """The slab and scale are shared state: walk and level serve the
    SAME quantized scores bit for bit."""
    bst, X = quant_booster
    lvl, walk = _with_quant(
        bst, "int8",
        lambda b: _engines(b, lambda bb: bb.predict(X[:128],
                                                    raw_score=True)))
    np.testing.assert_array_equal(lvl, walk)


def test_quant_bound_exact_and_tight():
    """ops level: the recorded bound equals the numpy-recomputed exact
    per-tree worst case; model level: on a single tree the bound is
    ACHIEVED by the rows landing in the worst-error leaf."""
    rng = np.random.RandomState(7)
    lv = rng.randn(3, 8).astype(np.float32) * np.array(
        [[1.0], [0.01], [5.0]], np.float32)
    cid = np.zeros(3, np.int32)
    slab, scale, bound = quantize_leaves(jnp.asarray(lv),
                                         jnp.asarray(cid), "int8")
    slab, scale, bound = (np.asarray(slab), np.asarray(scale),
                          float(bound))
    amax = np.abs(lv).max(axis=1)
    exp_scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    np.testing.assert_allclose(scale, exp_scale, rtol=1e-6)
    deq = slab.astype(np.float32) * scale[:, None]
    exp_bound = np.abs(deq - lv).max(axis=1).sum()
    np.testing.assert_allclose(bound, exp_bound, rtol=1e-6)
    # tightness on one tree: the train rows cover every leaf, so the
    # max observed |q_score - f32_score| IS the single tree's bound
    X, y = binary_data()
    p = _params()
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p), 1)
    ref = bst.predict(X, raw_score=True)
    q_raw, b1 = _with_quant(
        bst, "int8", lambda b: (b.predict(X, raw_score=True),
                                b._gbdt.leaf_quant_bound()))
    observed = np.max(np.abs(q_raw - ref))
    np.testing.assert_allclose(observed, b1, rtol=1e-5, atol=1e-9)


# ------------------------------------------- precomputed TreeSHAP tables
@pytest.fixture(scope="module")
def shap_booster():
    X, y = binary_data()
    Xn = np.array(X, np.float64)
    rng = np.random.RandomState(3)
    Xn[rng.rand(*Xn.shape) < 0.05] = np.nan
    Xn[:, 2] = rng.randint(0, 5, len(Xn))
    p = _params(use_missing=True,
                tpu_serve_endpoints="predict,leaf,contrib")
    bst = lgb.train(p, lgb.Dataset(Xn, label=y, params=p,
                                   categorical_feature=[2]), 8)
    return bst, Xn


def _contrib_with_tables(bst, x, mode, **kw):
    g = bst._gbdt
    g.config.set({"tpu_shap_tables": mode})
    g._shap_tables_cache = None
    try:
        return bst.predict_contrib_serving(x, **kw)
    finally:
        g.config.set({"tpu_shap_tables": "auto"})
        g._shap_tables_cache = None


def test_shap_tables_bit_identical_to_loop_kernel(shap_booster):
    bst, X = shap_booster
    x = X[:60].astype(np.float32)
    tab, nv = _contrib_with_tables(bst, x, "on")
    loop, nv2 = _contrib_with_tables(bst, x, "off")
    assert nv == nv2 == 60
    np.testing.assert_array_equal(tab, loop)   # same f32 op sequence
    ref = bst.predict(x, pred_contrib=True)
    np.testing.assert_allclose(tab[:nv], ref, rtol=2e-5, atol=2e-5)
    raw = bst.predict(x, raw_score=True)
    np.testing.assert_allclose(tab[:nv].sum(axis=1), raw,
                               rtol=1e-5, atol=1e-5)


def test_shap_tables_windowed_and_multiclass(shap_booster):
    bst, X = shap_booster
    x = X[:25].astype(np.float32)
    for kw in ({"num_iteration": 3}, {"start_iteration": 2},
               {"start_iteration": 2, "num_iteration": 3}):
        tab, nv = _contrib_with_tables(bst, x, "on", **kw)
        loop, _ = _contrib_with_tables(bst, x, "off", **kw)
        np.testing.assert_array_equal(tab, loop)
    Xm, ym = multiclass_data()
    p = dict(FAST_PARAMS, objective="multiclass", num_class=3,
             tpu_predict_buckets=LADDER,
             tpu_serve_endpoints="predict,contrib")
    mb = lgb.train(p, lgb.Dataset(Xm, label=ym, params=p), 4)
    xm = Xm[:20].astype(np.float32)
    tab, nv = _contrib_with_tables(mb, xm, "on")
    loop, _ = _contrib_with_tables(mb, xm, "off")
    np.testing.assert_array_equal(tab, loop)
    raw = mb.predict(xm, raw_score=True)
    sums = tab[:nv].reshape(nv, 3, -1).sum(axis=2)
    np.testing.assert_allclose(sums, raw, rtol=1e-5, atol=1e-5)


def test_shap_tables_budget_gate(shap_booster):
    bst, X = shap_booster
    x = X[:20].astype(np.float32)
    g = bst._gbdt
    g.config.set({"tpu_shap_table_mb": 0})
    try:
        # auto: over-budget falls back to the loop kernel, answers stand
        out, nv = _contrib_with_tables(bst, x, "auto")
        ref = bst.predict(x, pred_contrib=True)
        np.testing.assert_allclose(out[:nv], ref, rtol=2e-5, atol=2e-5)
        # on: over-budget is a structured refusal, not a silent downgrade
        with pytest.raises(ValueError, match="tpu_shap_table_mb"):
            _contrib_with_tables(bst, x, "on")
    finally:
        g.config.set({"tpu_shap_table_mb": 64})
        g._shap_tables_cache = None


def test_shap_table_cache_probe_and_witness(shap_booster):
    """R012 integration: the table cache reports its entry count through
    the registered witness probe, invalidation returns it to zero, and a
    WARM serving pass holds the resource witness."""
    bst, X = shap_booster
    x = X[:20].astype(np.float32)
    g = bst._gbdt
    g.config.set({"tpu_shap_tables": "on"})
    try:
        g._invalidate_device_trees()

        def probed():
            return sum(p() for p in guards._witness_cache_probes)

        base = probed()
        bst.predict_contrib_serving(x)            # builds one table entry
        assert probed() == base + 1
        assert len(g._shap_tables_cache) == 1
        with guards.resource_witness() as w:
            bst.predict_contrib_serving(x)        # warm: no growth
        w.assert_no_leaks("warm table-backed contrib")
        g._invalidate_device_trees()
        assert probed() == base
    finally:
        g.config.set({"tpu_shap_tables": "auto"})
        g._invalidate_device_trees()


# ------------------------------------------------- background contrib lane
def _mk_coalescer(bg=()):
    """A lock-stepped coalescer: no worker thread, zero tick window —
    _pop_batch_locked is driven directly so lane order is deterministic."""
    co = object.__new__(MicroBatchCoalescer)
    co._cv = threading.Condition()
    co._closing = False
    co._tick_s = 0.0
    co._max_batch_rows = 32
    co._background_kinds = frozenset(bg)
    co._q = collections.deque()
    co._rows = 0
    return co


def _put(co, n, kind):
    r = ServeFuture(np.zeros((n, 2), np.float32), None, 1000.0, kind=kind)
    co._q.append(r)
    co._rows += n
    return r


def test_background_lane_defers_until_foreground_idle():
    co = _mk_coalescer(bg=("contrib",))
    c1 = _put(co, 2, "contrib")
    p1 = _put(co, 3, "predict")
    c2 = _put(co, 1, "contrib")
    p2 = _put(co, 4, "predict")
    # tick 1: foreground queued -> only the predicts cut, background
    # skipped IN PLACE (order kept)
    batch = co._pop_batch_locked([])
    assert [r is x for r, x in zip(batch, (p1, p2))] == [True, True]
    assert list(co._q) == [c1, c2]
    # tick 2: foreground idle -> the background batch serves, FIFO
    batch = co._pop_batch_locked([])
    assert batch == [c1, c2]
    assert not co._q and co._rows == 0


def test_background_lane_preserves_foreground_fifo():
    co = _mk_coalescer(bg=("contrib",))
    l1 = _put(co, 2, "leaf")
    _put(co, 2, "contrib")
    p1 = _put(co, 3, "predict")
    # one endpoint per tick: leaf cuts first, predict stays QUEUED AHEAD
    # of nothing it didn't already trail — strict foreground FIFO
    batch = co._pop_batch_locked([])
    assert batch == [l1]
    assert [r.kind for r in co._q] == ["contrib", "predict"]
    batch = co._pop_batch_locked([])
    assert batch == [p1]
    assert [r.kind for r in co._q] == ["contrib"]


def test_background_kinds_knob_rejects_predict():
    """predict is never demotable; unknown kinds warn and drop."""
    from lightgbm_tpu.serving.server import PredictionServer
    kinds = PredictionServer._background_kinds(
        {"tpu_serve_background_kinds": "contrib,predict,bogus"})
    assert kinds == frozenset({"contrib"})
    assert PredictionServer._background_kinds({}) == frozenset()


# ------------------------------------------------ mixed-endpoint chaos
@pytest.fixture(scope="module")
def chaos_boosters():
    """Two boosters serving all three endpoints with the contrib lane
    demoted to background — pre-warmed (programs AND shap-table caches)
    so the witness-armed chaos test reads warm state end to end."""
    X, y = binary_data()
    p = _params(tpu_serve_endpoints="predict,leaf,contrib",
                tpu_serve_background_kinds="contrib")
    b1 = lgb.train(p, lgb.Dataset(X, label=y, params=p), 8)
    b2 = lgb.train(p, lgb.Dataset(X, label=y, params=p), 8)
    srv = b1.serve(tick_ms=1.0, deadline_ms=8000.0)
    try:
        for s in (3, 40):
            srv.predict(X[:s])
            srv.predict_leaf(X[:s])
            srv.predict_contrib(X[:s])
        srv.deploy("warm2", b2)        # warms b2's programs + caches
        srv.predict_contrib(X[:5])
    finally:
        srv.close(drain=True)
    return b1, b2, X


def test_mixed_endpoint_chaos_hot_swap_zero_recompile(
        chaos_boosters, lock_order_witness, resource_leak_witness):
    """THE serving-engine acceptance guard: mixed predict/leaf/contrib
    traffic with the contrib lane in the background tier, across a
    mid-stream hot-swap, completes every request, lowers ZERO programs,
    and holds both runtime witnesses (lock order, resource leaks)."""
    b1, b2, X = chaos_boosters
    srv = b1.serve(tick_ms=1.0, deadline_ms=8000.0)
    try:
        for s in (3, 40):               # re-touch every (kind, rung)
            srv.predict(X[:s])
            srv.predict_leaf(X[:s])
            srv.predict_contrib(X[:s])
        stop = threading.Event()
        errors = []
        served = collections.Counter()
        mu = threading.Lock()

        def hammer(kind, sizes):
            submit = {"predict": srv.submit, "leaf": srv.submit_leaf,
                      "contrib": srv.submit_contrib}[kind]
            i = 0
            while not stop.is_set():
                fut = submit(X[:sizes[i % len(sizes)]])
                try:
                    fut.result()
                    with mu:
                        served[kind] += 1
                except Exception as err:  # pragma: no cover
                    errors.append((kind, err))
                    return
                i += 1

        with guards.compile_counter() as cc:
            threads = [threading.Thread(target=hammer, args=a)
                       for a in (("predict", (1, 17, 32)),
                                 ("predict", (5, 40)),
                                 ("leaf", (3, 29)),
                                 ("contrib", (2, 11)))]
            for t in threads:
                t.start()
            time.sleep(0.15)
            srv.deploy("v2", b2)        # mid-stream atomic hot-swap
            time.sleep(0.15)
            stop.set()
            for t in threads:
                t.join()
        assert not errors, errors[:2]
        assert cc.lowerings == 0, \
            f"chaos traffic lowered {cc.lowerings} programs"
        assert served["predict"] > 0 and served["leaf"] > 0
        assert served["contrib"] > 0, \
            "background contrib lane starved under foreground load"
        assert srv.health()["active_version"] == "v2"
        np.testing.assert_array_equal(srv.predict(X[:5]),
                                      b2.predict(X[:5]))
    finally:
        srv.close(drain=True)
