"""Concurrency flight check: R011 analyzer unit coverage + runtime
lock-order witness.

The static half (lightgbm_tpu/analysis/locks.py) is exercised on
synthetic modules covering every acquisition spelling and on the shipped
package (whose order graph must be acyclic — that IS the invariant
ROADMAP items 2-3 build on). The runtime half (guards.lock_witness) is
exercised with a synthetic two-thread order inversion and by re-running
an existing 16-thread concurrency test under the witness at zero
findings.
"""
import os
import textwrap
import threading

import pytest

import lightgbm_tpu
from lightgbm_tpu.analysis import guards
from lightgbm_tpu.analysis.locks import analyze_paths, main as locks_main
from lightgbm_tpu.utils.rwlock import Mutex, RWLock

import test_concurrency

PKG_DIR = os.path.dirname(lightgbm_tpu.__file__)


def analyze_snippet(tmp_path, source, name="mod_under_test.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    analysis, errors = analyze_paths([str(p)])
    assert not errors, errors
    return analysis


# ------------------------------------------------- graph construction
def test_lock_discovery_and_edges_across_spellings(tmp_path):
    """One module using every acquisition spelling — decorator, `with`,
    rwlock side views, bare acquire/release — discovers every lock and
    draws the same kind of order edge for each."""
    analysis = analyze_snippet(tmp_path, """
        import threading
        from lightgbm_tpu.utils.rwlock import RWLock, Mutex, \\
            read_locked, write_locked

        GLOBAL_MU = threading.Lock()

        class Engine:
            def __init__(self):
                self._api_lock = RWLock()
                self._cv = threading.Condition()
                self._mu = Mutex()
                self.ready = False

            @write_locked
            def refresh(self):
                with self._mu:
                    pass

            def drain(self):
                with self._cv:
                    while not self.ready:
                        self._cv.wait(0.1)

            def manual(self):
                GLOBAL_MU.acquire()
                try:
                    with self._mu:
                        pass
                finally:
                    GLOBAL_MU.release()

            def sides(self):
                with self._api_lock.read():
                    with self._cv:
                        self.ready = True
    """)
    keys = set(analysis.locks)
    assert {"mod_under_test.GLOBAL_MU", "Engine._api_lock",
            "Engine._cv", "Engine._mu"} <= keys
    assert analysis.locks["Engine._api_lock"].kind == "rwlock"
    assert analysis.locks["Engine._cv"].kind == "condition"
    # decorator spelling, floating-acquire spelling, with-spelling
    assert ("Engine._api_lock", "Engine._mu") in analysis.edges
    assert ("mod_under_test.GLOBAL_MU", "Engine._mu") in analysis.edges
    assert ("Engine._api_lock", "Engine._cv") in analysis.edges
    assert not analysis.cycles
    assert not analysis.findings, \
        [f.render() for f in analysis.findings]


def test_interprocedural_chain_reported(tmp_path):
    """The acquisition two calls below the holder still draws the edge,
    and the edge's witness chain names every hop."""
    analysis = analyze_snippet(tmp_path, """
        import threading

        MU = threading.Lock()
        LOG_MU = threading.Lock()

        def log_note():
            with LOG_MU:
                pass

        def flush_logs():
            log_note()

        def commit():
            with MU:
                flush_logs()
    """)
    edge = analysis.edges[("mod_under_test.MU", "mod_under_test.LOG_MU")]
    desc = edge.describe()
    assert "commit" in desc
    assert "flush_logs" in desc and "log_note" in desc


def test_cross_order_cycle_reported_with_both_chains(tmp_path):
    analysis = analyze_snippet(tmp_path, """
        import threading

        MU_A = threading.Lock()
        MU_B = threading.Lock()

        def ab():
            with MU_A:
                with MU_B:
                    pass

        def ba():
            with MU_B:
                with MU_A:
                    pass
    """)
    assert len(analysis.cycles) == 1
    cyc = [f for f in analysis.findings
           if "lock-order cycle" in f.message]
    assert len(cyc) == 1
    assert "ab" in cyc[0].message and "ba" in cyc[0].message


def test_shipped_package_graph_is_acyclic():
    """The whole shipped tree: every lock discovered, zero order cycles
    — the invariant future fleet/refit PRs must preserve."""
    analysis, errors = analyze_paths([PKG_DIR])
    assert not errors, errors
    keys = set(analysis.locks)
    assert {"Booster._api_lock", "Dataset._api_lock", "GBDT._trees_mu",
            "MicroBatchCoalescer._cv", "ModelRegistry._deploy_mu",
            "ModelRegistry._lock", "PredictionServer._mu"} <= keys
    assert not analysis.cycles, analysis.cycles
    # the deploy serialization order is part of the design
    assert ("ModelRegistry._deploy_mu", "ModelRegistry._lock") \
        in analysis.edges


def test_cli_dot_output(capsys):
    rc = locks_main([PKG_DIR, "--dot"])
    out = capsys.readouterr().out
    assert rc == 0
    assert out.startswith("digraph lock_order {")
    assert '"ModelRegistry._deploy_mu" -> "ModelRegistry._lock"' in out


# ------------------------------------------------- runtime witness
def test_witness_detects_cross_thread_cycle():
    """Two threads acquire the same pair in opposite orders (run to
    completion sequentially — no real deadlock needed): the witness
    records the cycle with both stacks and assert_no_cycles raises."""
    with guards.lock_witness() as w:
        mu_a = threading.Lock()
        mu_b = threading.Lock()

        def ab():
            with mu_a:
                with mu_b:
                    pass

        def ba():
            with mu_b:
                with mu_a:
                    pass

        for fn in (ab, ba):
            t = threading.Thread(target=fn)
            t.start()
            t.join()
    assert len(w.cycles) == 1
    assert "lock-order cycle observed" in w.cycles[0]
    assert "held at" in w.cycles[0] and "acquired at" in w.cycles[0]
    with pytest.raises(guards.LockOrderError):
        w.assert_no_cycles("synthetic inversion")


def test_witness_quiet_on_consistent_order_and_reentrancy():
    """Consistent A->B order from many threads, re-entrant RWLock/Mutex
    nesting, and read-inside-write never record a cycle — and same-name
    sibling instances never self-edge."""
    with guards.lock_witness() as w:
        rw = RWLock()
        mu = Mutex()

        def worker():
            with rw.read():
                with mu:
                    with mu:            # re-entrant nesting
                        pass
        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        with rw.write():
            with rw.read():             # read nested under own write
                with mu:
                    pass
    w.assert_no_cycles("consistent order")
    assert w.acquires > 0
    assert all(a != b for (a, b) in w.edges)


def test_witness_notes_only_outer_transitions():
    """Nested re-entrant holds of the same lock report one acquire —
    depth bookkeeping, not per-entry spam."""
    with guards.lock_witness() as w:
        mu = Mutex()
        with mu:
            before = w.acquires
            with mu:
                pass
            assert w.acquires == before
    assert w.acquires == 1


def test_witness_16_thread_concurrency_rerun_clean():
    """Witness-enabled rerun of the existing 16-thread predict/update
    test: the full Booster/GBDT lock stack under real contention
    observes zero order cycles (and the witness actually saw traffic)."""
    with guards.lock_witness() as w:
        test_concurrency.test_concurrent_predict_with_interleaved_update()
    assert w.acquires > 0
    w.assert_no_cycles("16-thread predict/update under witness")
    assert not w.cycles
