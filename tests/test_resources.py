"""Resource-lifecycle flight check: R012 analyzer unit coverage +
runtime resource-leak witness.

The static half (lightgbm_tpu/analysis/resources.py) is exercised on
synthetic modules covering every acquisition spelling, the PR-10
exception-edge shape, the narrow-tempfile-handler shape, and ownership
discovery/verification; and on the shipped package (whose ownership
graph must resolve — that IS the invariant ROADMAP items 2-3 build on).
The runtime half (guards.resource_witness) is exercised with deliberate
thread/fd/session/cache leaks and their clean counterparts.
"""
import json
import os
import textwrap
import threading
import time

import pytest

import lightgbm_tpu
from lightgbm_tpu.analysis import guards
from lightgbm_tpu.analysis.resources import (analyze_paths,
                                             main as resources_main)
from lightgbm_tpu.obs import spans

PKG_DIR = os.path.dirname(lightgbm_tpu.__file__)


def analyze_snippet(tmp_path, source, name="mod_under_test.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    analysis, errors = analyze_paths([str(p)])
    assert not errors, errors
    return analysis


def r012(analysis):
    return [f.render() for f in analysis.findings]


# ------------------------------------------------- acquisition discovery
def test_discovery_across_spellings(tmp_path):
    """One module acquiring through every spelling — `with`, try/finally,
    daemon thread, escape-by-return — discovers every resource with the
    right kind and verdict, at zero findings."""
    analysis = analyze_snippet(tmp_path, """
        import threading
        from http.server import ThreadingHTTPServer, BaseHTTPRequestHandler

        def scoped_read(path):
            with open(path) as fh:
                return fh.read()

        def scoped_thread(work):
            t = threading.Thread(target=work, name="w")
            t.start()
            try:
                work()
            finally:
                t.join()

        def background(work):
            threading.Thread(target=work, daemon=True).start()

        def serve_once(port):
            httpd = ThreadingHTTPServer(("127.0.0.1", port),
                                        BaseHTTPRequestHandler)
            try:
                httpd.handle_request()
            finally:
                httpd.server_close()

        def stream_for(path):
            fh = open(path, "a")
            return fh
    """)
    assert not r012(analysis), r012(analysis)
    by_kind = {}
    for r in analysis.resources:
        by_kind.setdefault(r.kind, []).append(r.status)
    assert "with" in by_kind["file"]
    assert "escape" in by_kind["file"]
    assert set(by_kind["thread"]) == {"finally", "daemon"}
    assert by_kind["server"] == ["finally"]


def test_unbound_thread_without_daemon_is_a_finding(tmp_path):
    analysis = analyze_snippet(tmp_path, """
        import threading

        def spawn(work):
            threading.Thread(target=work).start()
    """)
    msgs = r012(analysis)
    assert len(msgs) == 1 and "without a binding" in msgs[0], msgs


# ------------------------------------------------- the PR-10 edge shape
def test_hazard_between_acquire_and_try_is_a_finding(tmp_path):
    """The exact PR-10 leak: profiler session entered, a raising call,
    THEN the try/finally — the exception edge skips the release."""
    analysis = analyze_snippet(tmp_path, """
        import jax

        def traced_run(log_dir, work):
            sess = jax.profiler.trace(log_dir)
            sess.__enter__()
            prepare_inputs()
            try:
                work()
            finally:
                sess.__exit__(None, None, None)
    """)
    msgs = r012(analysis)
    assert len(msgs) == 1, msgs
    assert "can raise and skip the release" in msgs[0]
    assert "PR-10" in msgs[0]


def test_acquire_adjacent_to_try_is_clean(tmp_path):
    """Same code with the acquisition moved next to its try: clean."""
    analysis = analyze_snippet(tmp_path, """
        import jax

        def traced_run(log_dir, work):
            prepare_inputs()
            sess = jax.profiler.trace(log_dir)
            try:
                sess.__enter__()
                work()
            finally:
                sess.__exit__(None, None, None)
    """)
    assert not r012(analysis), r012(analysis)


def test_with_by_name_profiler_session_is_clean(tmp_path):
    """The engine.py idiom: build the session object (construction does
    not acquire — __enter__ does), hazards in between, then
    `with sess:` — the lazy acquisition makes this exception-safe."""
    analysis = analyze_snippet(tmp_path, """
        import contextlib
        import jax

        def traced_run(log_dir, work):
            sess = (jax.profiler.trace(log_dir) if log_dir
                    else contextlib.nullcontext())
            prepare_inputs()
            with sess:
                work()
    """)
    assert not r012(analysis), r012(analysis)


# ------------------------------------------- tempfile narrow handlers
def test_narrow_tempfile_handler_is_a_finding(tmp_path):
    """The ledger/autotune bug shape: mkstemp cleanup behind
    `except OSError` — a serializer TypeError or SimulatedKill mid-dump
    orphans the temp file."""
    analysis = analyze_snippet(tmp_path, """
        import os
        import tempfile

        def persist(directory, final, payload):
            fd, tmp = tempfile.mkstemp(dir=directory)
            try:
                os.write(fd, payload)
                os.close(fd)
                os.replace(tmp, final)
            except OSError:
                os.unlink(tmp)
                raise
    """)
    msgs = r012(analysis)
    assert len(msgs) == 1, msgs
    assert "orphans the temp file" in msgs[0]
    assert "except OSError" in msgs[0]


def test_catchall_tempfile_handler_is_clean(tmp_path):
    analysis = analyze_snippet(tmp_path, """
        import os
        import tempfile

        def persist(directory, final, payload):
            fd, tmp = tempfile.mkstemp(dir=directory)
            try:
                os.write(fd, payload)
                os.close(fd)
                os.replace(tmp, final)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
    """)
    assert not r012(analysis), r012(analysis)


# ------------------------------------------------- ownership discovery
OWNER_CLEAN = """
    import threading

    class Pump:
        def __init__(self):
            self._thread = threading.Thread(target=self._run,
                                            name="pump")
            self._thread.start()

        def _run(self):
            pass

        def close(self):
            thread, self._thread = self._thread, None
            if thread is not None:
                thread.join(timeout=5.0)
"""


def test_owner_class_with_release_complete_close_is_clean(tmp_path):
    analysis = analyze_snippet(tmp_path, OWNER_CLEAN)
    assert not r012(analysis), r012(analysis)
    assert analysis.owner_classes == {"Pump": {"_thread": "thread"}}
    assert analysis.owner_release[("Pump", "_thread")] == "close"
    lines = "\n".join(analysis.ownership_lines())
    assert "Pump._thread" in lines and "released by close()" in lines


def test_owner_class_without_release_surface_is_a_finding(tmp_path):
    analysis = analyze_snippet(tmp_path, """
        import threading

        class Pump:
            def __init__(self):
                self._thread = threading.Thread(target=self._run)
                self._thread.start()

            def _run(self):
                pass
    """)
    msgs = r012(analysis)
    assert len(msgs) == 1, msgs
    assert "no release-surface method" in msgs[0]
    dot = analysis.to_dot()
    assert "LEAK" in dot and dot.startswith("digraph")


def test_release_through_self_method_fixpoint(tmp_path):
    """close() -> self._shutdown() -> join: the release chain resolves
    through intermediate self-method calls."""
    analysis = analyze_snippet(tmp_path, """
        import threading

        class Pump:
            def __init__(self):
                self._thread = threading.Thread(target=self._run)
                self._thread.start()

            def _run(self):
                pass

            def _shutdown(self):
                self._thread.join(timeout=5.0)

            def close(self):
                self._shutdown()
    """)
    assert not r012(analysis), r012(analysis)
    assert analysis.owner_release[("Pump", "_thread")] == "close"


def test_raising_init_after_acquisition_is_a_finding(tmp_path):
    """The MetricsServer/PredictionServer bug shape: __init__ acquires,
    then a later init step raises — the partially built object is
    dropped with the resource live."""
    analysis = analyze_snippet(tmp_path, """
        from http.server import ThreadingHTTPServer

        class Exporter:
            def __init__(self, handler, port):
                self._httpd = ThreadingHTTPServer(("127.0.0.1", port),
                                                  handler)
                self._port = announce(self._httpd.server_address[1])

            def stop(self):
                self._httpd.shutdown()
                self._httpd.server_close()
    """)
    msgs = r012(analysis)
    assert len(msgs) == 1, msgs
    assert "__init__" in msgs[0] and "partially built object" in msgs[0]


def test_init_guarded_by_catchall_release_is_clean(tmp_path):
    analysis = analyze_snippet(tmp_path, """
        from http.server import ThreadingHTTPServer

        class Exporter:
            def __init__(self, handler, port):
                self._httpd = ThreadingHTTPServer(("127.0.0.1", port),
                                                  handler)
                try:
                    self._port = announce(self._httpd.server_address[1])
                except BaseException:
                    self._httpd.server_close()
                    raise

            def stop(self):
                self._httpd.shutdown()
                self._httpd.server_close()
    """)
    assert not r012(analysis), r012(analysis)


# --------------------------------------------------- shipped-tree facts
def test_shipped_package_ownership_graph_resolves():
    """The real tree: every owned resource attr has a release-surface
    method, and the serving/metrics owners the chaos tests rely on are
    in the graph."""
    analysis, errors = analyze_paths([PKG_DIR])
    assert not errors, errors
    owners = analysis.owner_classes
    assert "PredictionServer" in owners
    assert "MetricsServer" in owners
    assert "MicroBatchCoalescer" in owners
    for cls, owned in owners.items():
        for attr in owned:
            assert (cls, attr) in analysis.owner_release, \
                f"{cls}.{attr} has no releasing surface method"


def test_cli_exit_codes_and_json(tmp_path, capsys):
    leaky = tmp_path / "leaky.py"
    leaky.write_text(textwrap.dedent("""
        import threading

        def spawn(work):
            threading.Thread(target=work).start()
    """))
    clean = tmp_path / "clean.py"
    clean.write_text(textwrap.dedent("""
        def read(path):
            with open(path) as fh:
                return fh.read()
    """))
    assert resources_main([str(clean)]) == 0
    assert resources_main([str(leaky), "--no-allowlist"]) == 1
    capsys.readouterr()
    rc = resources_main([str(leaky), "--no-allowlist", "--json"])
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload and payload[0]["rule"] == "R012"


# =============================================== runtime leak witness
def test_witness_names_leaked_thread_and_clears_after_join():
    stop = threading.Event()
    with guards.resource_witness() as w:
        t = threading.Thread(target=stop.wait, name="unit-leaky-thread",
                             daemon=True)
        t.start()
        with pytest.raises(guards.ResourceLeakError,
                           match="unit-leaky-thread"):
            w.assert_no_leaks("thread unit", settle_s=0.2)
        stop.set()
        t.join(timeout=5.0)
    w.assert_no_leaks("thread unit")


def test_witness_exempts_deliberate_process_lifetime_threads():
    stop = threading.Event()
    t = threading.Thread(target=stop.wait, name="lgbm-tpu-watchdog-unit",
                         daemon=True)
    try:
        w = guards.ResourceWitness()
        t.start()
        time.sleep(0.05)
        assert "threads" not in w.deltas()
    finally:
        stop.set()
        t.join(timeout=5.0)


def test_witness_counts_fd_growth_and_clears_after_close():
    if guards._witness_fds() is None:
        pytest.skip("no /proc/self/fd on this platform")
    with guards.resource_witness() as w:
        r, wfd = os.pipe()
        assert w.deltas().get("fds", 0) >= 2
        os.close(r)
        os.close(wfd)
    w.assert_no_leaks("fd unit")


def test_witness_counts_open_trace_sessions():
    w = guards.ResourceWitness()
    ctx = spans.trace_session(None, "annotations")
    ctx.__enter__()
    try:
        assert w.deltas().get("sessions") == 1
    finally:
        ctx.__exit__(None, None, None)
    w.assert_no_leaks("session unit")


def test_witness_sums_registered_cache_probes():
    size = [0]
    probe = lambda: size[0]                      # noqa: E731
    guards.register_witness_cache_probe(probe)
    try:
        w = guards.ResourceWitness()
        size[0] = 3
        assert w.deltas().get("jit_cache") == 3
        size[0] = 0
        w.assert_no_leaks("cache unit")
    finally:
        guards._witness_cache_probes.remove(probe)


def test_witness_fixture_is_wired(resource_leak_witness):
    """The pytest fixture arms the witness around the test body; a
    balanced scope passes (the assert runs in fixture teardown)."""
    stop = threading.Event()
    t = threading.Thread(target=stop.wait, name="fixture-balanced",
                         daemon=True)
    t.start()
    stop.set()
    t.join(timeout=5.0)
