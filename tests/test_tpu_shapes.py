"""TPU-hardware regression tests for shapes that only fault on real Mosaic.

The round-4 fused+EFB fault (dual-residency kernel crashing the TPU worker
on EFB-bundled 255-leaf trees) was invisible to the CPU suite because
interpret mode never triggered it. These tests run the failing shape in a
fresh subprocess against the real TPU backend (the in-process suite is
pinned to CPU by conftest) and are skipped where no TPU is attached.
"""
import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tpu_platform() -> str:
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=120, cwd=_ROOT)
        return out.stdout.strip().splitlines()[-1] if out.stdout else ""
    except Exception:
        return ""


_PLATFORM = _tpu_platform()


@pytest.mark.skipif(_PLATFORM not in ("tpu", "axon"),
                    reason="needs a real TPU backend (Mosaic)")
def test_fused_efb_deep_tree_shape():
    """The Allstate-like shape: ~4228 one-hot features EFB-bundled to ~529
    columns, 255 leaves, fused kernel on. Round 4's dual-residency kernel
    reproducibly crashed the TPU worker here; the copy-back variant must
    train it to completion (BENCH_SHAPES.json 'allstate')."""
    env = dict(os.environ, REPRO_ROWS="60000", REPRO_ITERS="2",
               REPRO_LEAVES="255")
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "scripts", "repro_fused_efb.py")],
        capture_output=True, text=True, timeout=1500, env=env, cwd=_ROOT)
    assert "REPRO_OK" in out.stdout, (
        f"fused EFB deep-tree training did not complete\n"
        f"stdout:\n{out.stdout[-3000:]}\nstderr:\n{out.stderr[-3000:]}")
