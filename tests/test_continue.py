"""Continue-training / refit / snapshots.

Mirrors the reference's continue-train coverage (test_engine.py
test_continue_train*, gbdt.cpp:250-258 snapshots, GBDT::RefitTree)."""
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb

from utils import FAST_PARAMS, binary_data, regression_data, \
    train_test_split_simple


def _params(**kw):
    p = dict(FAST_PARAMS)
    p.update(kw)
    return p


class TestContinueTraining:
    def test_continue_matches_uninterrupted(self):
        X, y = regression_data()
        params = _params(objective="regression", learning_rate=0.1,
                         boost_from_average=False)
        # one uninterrupted 20-round run
        full = lgb.train(params, lgb.Dataset(X, label=y), 20)
        # 10 rounds, save, resume for 10 more
        first = lgb.train(params, lgb.Dataset(X, label=y), 10)
        resumed = lgb.train(params,
                            lgb.Dataset(X, label=y, free_raw_data=False), 10,
                            init_model=first)
        np.testing.assert_allclose(resumed.predict(X), full.predict(X),
                                   rtol=1e-4, atol=1e-5)
        assert resumed.num_trees() == 20

    def test_continue_from_file(self, tmp_path):
        X, y = binary_data()
        params = _params(objective="binary")
        first = lgb.train(params, lgb.Dataset(X, label=y), 8)
        path = str(tmp_path / "m.txt")
        first.save_model(path)
        resumed = lgb.train(params,
                            lgb.Dataset(X, label=y, free_raw_data=False), 7,
                            init_model=path)
        assert resumed.num_trees() == 15
        # saved resumed model contains all trees and round-trips
        text = resumed.model_to_string()
        assert text.count("Tree=") == 15
        re_loaded = lgb.Booster(model_str=text)
        np.testing.assert_allclose(re_loaded.predict(X), resumed.predict(X),
                                   rtol=1e-5, atol=1e-6)

    def test_continue_improves_metric(self):
        X, y = binary_data()
        Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
        from sklearn.metrics import log_loss
        params = _params(objective="binary")
        first = lgb.train(params, lgb.Dataset(Xtr, label=ytr), 5)
        l1 = log_loss(yte, first.predict(Xte))
        resumed = lgb.train(
            params, lgb.Dataset(Xtr, label=ytr, free_raw_data=False), 15,
            init_model=first)
        l2 = log_loss(yte, resumed.predict(Xte))
        assert l2 < l1


class TestRefit:
    def test_refit_adapts_leaf_values(self):
        X, y = regression_data()
        params = _params(objective="regression")
        bst = lgb.train(params, lgb.Dataset(X, label=y), 15)
        # refit on shifted labels moves predictions toward the new targets
        y2 = y + 50.0
        refitted = bst.refit(X, y2, decay_rate=0.0)
        assert np.mean(refitted.predict(X)) > np.mean(bst.predict(X)) + 25
        # structure unchanged
        assert refitted.num_trees() == bst.num_trees()

    def test_refit_decay(self):
        X, y = regression_data()
        bst = lgb.train(_params(objective="regression"),
                        lgb.Dataset(X, label=y), 10)
        same = bst.refit(X, y + 50.0, decay_rate=1.0)  # keep old values
        np.testing.assert_allclose(same.predict(X), bst.predict(X),
                                   rtol=1e-5, atol=1e-5)


class TestSnapshots:
    def test_snapshot_files_written(self, tmp_path):
        X, y = binary_data()
        out = str(tmp_path / "model.txt")
        params = _params(objective="binary", snapshot_freq=4,
                         output_model=out)
        lgb.train(params, lgb.Dataset(X, label=y), 10)
        snaps = sorted(os.listdir(tmp_path))
        assert f"model.txt.snapshot_iter_4" in "".join(snaps)
        assert f"model.txt.snapshot_iter_8" in "".join(snaps)
        snap = lgb.Booster(model_file=out + ".snapshot_iter_8")
        assert snap.num_trees() == 8


class TestContinueNumIteration:
    def test_num_iteration_counts_from_loaded_trees(self):
        # reference semantics: iteration cuts start at the loaded model
        X, y = regression_data()
        params = _params(objective="regression", boost_from_average=False)
        first = lgb.train(params, lgb.Dataset(X, label=y), 10)
        resumed = lgb.train(params,
                            lgb.Dataset(X, label=y, free_raw_data=False), 10,
                            init_model=first)
        # cutting at 10 iterations == the loaded model alone
        np.testing.assert_allclose(resumed.predict(X, num_iteration=10),
                                   first.predict(X), rtol=1e-5, atol=1e-6)
        # serialized cut agrees with in-memory cut
        text10 = resumed.model_to_string(num_iteration=10)
        assert text10.count("Tree=") == 10
        reload10 = lgb.Booster(model_str=text10)
        np.testing.assert_allclose(reload10.predict(X),
                                   resumed.predict(X, num_iteration=10),
                                   rtol=1e-5, atol=1e-6)


class TestContinueStartIteration:
    def test_start_iteration_counts_from_loaded_trees(self):
        X, y = regression_data()
        params = _params(objective="regression", boost_from_average=False)
        first = lgb.train(params, lgb.Dataset(X, label=y), 10)
        resumed = lgb.train(params,
                            lgb.Dataset(X, label=y, free_raw_data=False), 5,
                            init_model=first)
        full = resumed.predict(X, raw_score=True)
        head = resumed.predict(X, raw_score=True, num_iteration=10)
        tail = resumed.predict(X, raw_score=True, start_iteration=10)
        # the window starting after the loaded trees == only the new trees
        np.testing.assert_allclose(head + tail, full, rtol=1e-5, atol=1e-5)
        mid = resumed.predict(X, raw_score=True, start_iteration=8,
                              num_iteration=4)
        rest = (resumed.predict(X, raw_score=True, num_iteration=8)
                + resumed.predict(X, raw_score=True, start_iteration=12))
        np.testing.assert_allclose(mid + rest, full, rtol=1e-5, atol=1e-5)
