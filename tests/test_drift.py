"""Serving-quality observability (ISSUE 14).

Acceptance surface: (1) drift monitors + SLO tracker ENABLED add 0
steady-state recompiles and 0 per-tick host transfers — the window
accumulators are pure on-device adds, d2h happens only at the declared
flush cadence (``host_syncs`` == flushes); (2) injected covariate shift
on a served feature raises a ``drift_detected`` flight event naming that
feature (and a nonzero PSI gauge) within ONE flush, while unshifted
traffic stays quiet across >= 3 flushes; (3) events are hysteresis-gated
(no re-fire while drifted, cleared only below half the threshold);
(4) per-request latency attribution phases + per-(kind, version)
histograms; (5) SLO burn rates + ``slo_burn`` events; (6) Prometheus
label escaping survives hostile feature names; (7) per-endpoint-kind
coalescer stats; (8) the jax-free ``scripts/obs drift`` summary.
"""
import json
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.analysis import guards
from lightgbm_tpu.io import binning
from lightgbm_tpu.obs import drift as drift_mod
from lightgbm_tpu.obs import flight
from lightgbm_tpu.obs import metrics as obs_metrics
from lightgbm_tpu.obs import summarize
from lightgbm_tpu.obs.drift import (DriftMonitor, LatencyHistogram,
                                    SloTracker, equal_mass_groups,
                                    group_counts, kl_rows, psi_rows)

from utils import FAST_PARAMS

LADDER = "64,256"


def _params(**kw):
    return dict(FAST_PARAMS, objective="binary", verbosity=-1,
                tpu_predict_buckets=LADDER, **kw)


def _data(n=600, f=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 2] > 0).astype(np.float64)
    return X, y


@pytest.fixture(scope="module")
def drift_booster():
    X, y = _data()
    bst = lgb.train(_params(), lgb.Dataset(X, label=y), 5)
    return bst, X


def _wait_flushes(mon, n, timeout_s=10.0):
    """The flush runs on the serving worker AFTER the futures complete;
    a client must poll, not assert immediately."""
    end = time.monotonic() + timeout_s
    while mon.flushes < n:
        if time.monotonic() >= end:
            raise AssertionError(
                f"flushes stuck at {mon.flushes}, wanted {n}")
        time.sleep(0.005)


def _events_since(seq0, names):
    return [e for e in flight.recorder().events()
            if e["seq"] > seq0 and e["event"] in names]


# ----------------------------------------------------------- divergence math
def test_psi_zero_on_identical():
    p = np.array([[0.5, 0.3, 0.2], [0.1, 0.6, 0.3]])
    np.testing.assert_allclose(psi_rows(p, p), 0.0, atol=1e-12)
    np.testing.assert_allclose(kl_rows(p, p), 0.0, atol=1e-12)


def test_psi_positive_on_shift():
    p = np.array([[0.5, 0.3, 0.2]])
    q = np.array([[0.1, 0.2, 0.7]])
    assert psi_rows(p, q)[0] > 0.2
    assert kl_rows(p, q)[0] > 0.0
    # PSI is symmetric in (p, q) exchange; KL is not
    np.testing.assert_allclose(psi_rows(p, q), psi_rows(q, p))


def test_equal_mass_groups_monotone_and_balanced():
    rng = np.random.RandomState(1)
    p = rng.dirichlet(np.ones(100), size=3)
    gid = equal_mass_groups(p, 10)
    assert gid.shape == p.shape
    assert (np.diff(gid, axis=1) >= 0).all()          # monotone
    assert gid.min() == 0 and gid.max() == 9
    g = group_counts(p, gid, 10)
    # ~equal mass per group (each group holds >= ~half its fair share)
    assert (g > 0.04).all() and (g < 0.25).all()


def test_equal_mass_groups_few_bins_identity():
    p = np.array([[0.7, 0.3]])
    gid = equal_mass_groups(p, 16)
    # 2 bins cannot fill 16 groups; bins stay separated
    assert gid[0, 0] != gid[0, 1]


# ------------------------------------------------- reference distribution
def test_reference_distribution_matches_bincount(drift_booster):
    bst, X = drift_booster
    ds = bst._gbdt.train_set
    probs, nb = ds.reference_bin_distribution()
    assert probs.shape[0] == ds.num_total_features
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-5)
    # ground truth: histogram the per-feature binned matrix directly
    raw = binning.bin_columns(ds.mappers, X, ds.binned.dtype)
    for j in range(ds.num_total_features):
        h = np.bincount(raw[:, j], minlength=probs.shape[1])
        np.testing.assert_allclose(
            probs[j], h[:probs.shape[1]] / len(X), atol=1e-6)
    # and it is cached (ships with the model through the registry)
    assert ds.reference_bin_distribution() is ds.reference_bin_distribution()


def test_bin_occupancy_efb_bundle_decode():
    """EFB-bundled matrices decode member features through their bundle
    offset ranges — occupancy must match the UNBUNDLED per-feature
    histogram exactly on a conflict-free one-hot block (bundling at
    construct needs >= 256 features; build the plan by hand)."""
    from lightgbm_tpu.io import efb
    rng = np.random.RandomState(3)
    n = 400
    hot = rng.randint(0, 4, n)
    X = np.zeros((n, 6))
    for k in range(4):                       # mutually exclusive block
        X[:, k] = (hot == k).astype(float)
    X[:, 4] = rng.randn(n)
    X[:, 5] = rng.randn(n)
    ds = lgb.Dataset(X, label=(X[:, 4] > 0).astype(float),
                     params=dict(FAST_PARAMS)).construct()
    inner = ds._inner
    assert inner.bundle_info is None         # too few features for EFB
    binned = inner.binned
    nb = inner.feature_num_bins()
    dflt = np.array([m.default_bin for m in inner.mappers], np.int32)
    info = efb.build_bundle_info([[0, 1, 2, 3]], nb, 6)
    bundled, conflicts = efb.bundle_chunk(binned, info, dflt)
    assert conflicts == 0
    counts, nb2 = binning.bin_occupancy(bundled, inner.mappers, info)
    truth, _ = binning.bin_occupancy(binned, inner.mappers, None)
    np.testing.assert_allclose(counts, truth, atol=1e-9)


# ------------------------------------------------------- monitor mechanics
def test_monitor_device_accumulate_no_host_transfers(drift_booster):
    """THE per-tick transfer guard: device-binned observes are pure
    on-device adds — nothing materializes on the host until flush, and
    flush is exactly one sync."""
    import jax.numpy as jnp
    bst, X = drift_booster
    mon = DriftMonitor("vg", bst, flush_every=8, psi_threshold=0.2,
                       score_bins=16)
    mon.warm([64])
    g = bst._gbdt
    dev_bins = g.featurize_rung(X[:50].astype(np.float32))
    dev_scores = jnp.zeros((1, 64), jnp.float32)
    with guards.compile_counter() as cc:
        with guards.no_host_transfers():
            for _ in range(5):
                mon.observe_binned(dev_bins, 50)
                mon.observe_scores(dev_scores, 50)
    assert cc.lowerings == 0, "observe lowered a program post-warm"
    assert mon.host_syncs == 0
    rec = mon.flush()
    assert mon.host_syncs == 1               # the ONE declared d2h
    assert rec["window_rows"] == 250


def test_monitor_host_hatch_accumulate(drift_booster):
    """tpu_serve_featurize=host bins land in the host twin accumulator
    and flush identically (no device arrays involved)."""
    bst, X = drift_booster
    mon = DriftMonitor("vh", bst, flush_every=4, psi_threshold=0.2,
                       score_bins=16)
    host_bins = bst._gbdt.bin_matrix(X.astype(np.float32))
    mon.observe_binned(host_bins, len(X))
    rec = mon.flush()
    assert rec["window_rows"] == len(X)
    assert mon.host_syncs == 0               # nothing ever hit a device
    assert rec["max_psi"] < 0.2              # training rows: no drift


def test_monitor_hysteresis_band(drift_booster):
    """drift_detected fires ONCE on crossing; a PSI inside the
    (exit, enter) band keeps the drifted state without re-firing; only
    below HALF the threshold does drift_cleared fire."""
    bst, X = drift_booster
    mon = DriftMonitor("vband", bst, flush_every=4, psi_threshold=0.2,
                       score_bins=16)
    shifted = X.copy()
    shifted[:, 2] += 3.0
    bins = bst._gbdt.bin_matrix(shifted.astype(np.float32))
    name = mon.feature_names[2]

    mon.observe_binned(bins, len(bins))
    r1 = mon.flush()
    psi = r1["psi"][name]
    assert psi >= 0.2
    assert {(e["event"], e["feature"]) for e in r1["events"]} >= {
        ("drift_detected", name)}
    # same shifted window again: still drifted, NO second event
    mon.observe_binned(bins, len(bins))
    r2 = mon.flush()
    assert not [e for e in r2["events"] if e["feature"] == name]
    assert name in r2["drifted"]
    # in-band (exit < psi < enter): state holds, no event either way
    mon.threshold, mon.exit_threshold = psi * 2.0, psi * 0.5
    mon.observe_binned(bins, len(bins))
    r3 = mon.flush()
    assert not [e for e in r3["events"] if e["feature"] == name]
    assert name in r3["drifted"]
    # below the exit band: cleared exactly once
    mon.exit_threshold = psi * 2.0
    mon.observe_binned(bins, len(bins))
    r4 = mon.flush()
    assert [e for e in r4["events"]
            if e["feature"] == name and e["event"] == "drift_cleared"]
    assert name not in r4["drifted"]


def test_monitor_low_traffic_window_fires_no_events(drift_booster):
    """PSI sampling noise ~ (G-1)/rows: a window below min_rows must
    update gauges but NOT fire events — a low-traffic service does not
    cry wolf. A big-enough shifted window then fires normally."""
    bst, X = drift_booster
    mon = DriftMonitor("vlow", bst, flush_every=4, psi_threshold=0.2,
                       score_bins=16)
    assert mon.min_rows == 20 * mon._G       # auto default
    shifted = X.copy()
    shifted[:, 2] += 3.0
    bins = bst._gbdt.bin_matrix(shifted.astype(np.float32))
    mon.observe_binned(bins[:40], 40)        # well under min_rows
    rec = mon.flush()
    assert rec["low_traffic"] is True
    assert rec["max_psi"] > 0                # gauges still update
    assert not rec["events"] and not rec["drifted"]
    mon.observe_binned(bins, len(bins))      # 600 rows: gate open
    rec2 = mon.flush()
    assert rec2["low_traffic"] is False
    assert [e for e in rec2["events"] if e["event"] == "drift_detected"]


# -------------------------------------------------- serving integration
def test_injected_shift_detected_within_one_flush(drift_booster):
    """Train on one distribution, serve a shifted one: the right feature
    raises drift_detected within ONE flush; unshifted traffic first
    stays quiet across >= 3 flushes."""
    bst, X = drift_booster
    seq0 = flight.recorder().events()[-1]["seq"] \
        if flight.recorder().events() else 0
    srv = bst.serve(tick_ms=1.0, deadline_ms=10_000.0,
                    drift_flush_every=2)
    try:
        mon = srv.observer.drift
        assert mon is not None and mon.version == "v0"
        # unshifted: 3 full flush windows of diverse training rows
        i = 0
        while mon.flushes < 3:
            a = (i * 200) % 400
            srv.predict(X[a:a + 200])
            i += 1
        _wait_flushes(mon, 3)
        assert not _events_since(seq0, ("drift_detected",)), \
            "unshifted traffic raised drift"
        g = mon.gauges()
        assert g["max_psi"] < mon.threshold and not g["drifted"]

        # covariate shift on feature 2: detected within ONE flush
        shifted = X.copy()
        shifted[:, 2] += 3.0
        f0 = mon.flushes
        i = 0
        while mon.flushes < f0 + 1:
            a = (i * 200) % 400
            srv.predict(shifted[a:a + 200])
            i += 1
        _wait_flushes(mon, f0 + 1)
        evs = _events_since(seq0, ("drift_detected",))
        names = {e["feature"] for e in evs}
        assert mon.feature_names[2] in names, f"wrong features: {names}"
        g = mon.gauges()
        assert g["psi"][mon.feature_names[2]] >= mon.threshold
        # the Prometheus gauge is nonzero for the drifted feature
        text = srv.metrics_text()
        line = [ln for ln in text.splitlines()
                if ln.startswith("lgbm_tpu_drift_psi{")
                and f'feature="{mon.feature_names[2]}"' in ln]
        assert line and float(line[0].rsplit(" ", 1)[1]) >= mon.threshold
    finally:
        srv.close(drain=True)


def test_steady_state_guard_with_monitors_on(drift_booster):
    """Acceptance: drift + SLO enabled add 0 steady-state recompiles,
    and d2h syncs happen ONLY at the flush cadence."""
    bst, X = drift_booster
    srv = bst.serve(tick_ms=1.0, deadline_ms=10_000.0,
                    drift_flush_every=4, slo_ms=5_000.0)
    try:
        mon = srv.observer.drift
        # prime each rung once through the full observe path
        srv.predict(X[:20])
        srv.predict(X[:200])
        _wait_flushes(mon, 0)                 # no flush yet (2 ticks)
        with guards.compile_counter() as cc:
            for i in range(10):               # 12 ticks total -> 3 flushes
                srv.predict(X[(i * 37) % 300:(i * 37) % 300 + 40])
        _wait_flushes(mon, 3)
        assert cc.lowerings == 0, \
            f"monitors lowered {cc.lowerings} programs in steady state"
        assert mon.flushes == 3
        assert mon.host_syncs == mon.flushes, \
            "d2h outside the declared flush ticks"
        assert srv.observer.slo is not None
        assert srv.observer.slo.good_total >= 12
    finally:
        srv.close(drain=True)


def test_hot_swap_resets_drift_window(drift_booster):
    """A deploy re-attaches the monitor to the new model; ticks pinned
    to the OLD version must not feed the new monitor."""
    bst, X = drift_booster
    X2, y2 = _data(seed=7)
    b2 = lgb.train(_params(), lgb.Dataset(X2, label=y2), 3)
    srv = bst.serve(tick_ms=1.0, drift_flush_every=2)
    try:
        m1 = srv.observer.drift
        srv.predict(X[:50])
        srv.deploy("v2", b2)
        m2 = srv.observer.drift
        assert m2 is not m1 and m2.version == "v2"
        assert srv.observer.drift_for("v0") is None
        assert srv.observer.drift_for("v2") is m2
        # the candidate's reference materialized during the WARM phase
        # even though ITS config never armed drift (the server's
        # override decides): the cached baselines already exist
        assert b2._gbdt.train_set._ref_dist is not None
        assert getattr(b2._gbdt, "_drift_score_host", None) is not None
        srv.predict(X2[:50])
        srv.predict(X2[:50])
        _wait_flushes(m2, 1)
    finally:
        srv.close(drain=True)


# ---------------------------------------------------- latency attribution
def test_phase_times_and_histograms(drift_booster):
    bst, X = drift_booster
    srv = bst.serve(tick_ms=1.0, deadline_ms=10_000.0)
    try:
        fut = srv.submit(X[:8])
        fut.result()
        ph = fut.phase_times()
        assert set(ph) == {"queue_wait_s", "serve_s", "complete_s"}
        assert all(v >= 0 for v in ph.values())
        assert abs(sum(ph.values()) - fut.latency_s) < 1e-6
        # completed requests land in the (kind, version) histogram
        end = time.monotonic() + 5.0          # observer runs post-complete
        while ("predict", "v0") not in srv.observer._hists:
            assert time.monotonic() < end
            time.sleep(0.005)
        h = srv.observer._hists[("predict", "v0")]
        assert h.count >= 1
        assert sum(h.counts) == h.count
        assert h.sum_ms > 0
        text = srv.observer.prometheus_text()
        assert 'lgbm_tpu_serve_latency_ms_bucket{kind="predict"' in text
        assert 'le="+Inf"' in text
        assert "lgbm_tpu_serve_phase_seconds_total" in text
    finally:
        srv.close(drain=True)


def test_latency_histogram_buckets():
    h = LatencyHistogram()
    for ms in (0.5, 1.0, 3.0, 9000.0):
        h.observe(ms)
    assert h.count == 4
    # le=1.0 bucket holds 0.5 AND the exact 1.0 (le semantics)
    assert h.counts[0] == 2
    assert h.counts[-1] == 1                 # overflow past 5000ms
    lines = obs_metrics.render_histogram(
        "m", {"k": "v"}, drift_mod.LATENCY_BUCKETS_MS, h.counts,
        h.sum_ms, h.count)
    inf = [ln for ln in lines if 'le="+Inf"' in ln]
    assert inf and inf[0].endswith(" 4")
    assert any(ln.startswith('m_bucket{k="v",le="1"} 2') for ln in lines)


# ----------------------------------------------------------------- SLO
def test_slo_tracker_windows_and_burn():
    t = SloTracker(slo_ms=100.0, target=0.9)   # budget: 10% bad
    now = 10_000.0
    for _ in range(90):
        t.record(True, now)
    for _ in range(10):
        t.record(False, now)
    # exactly at budget: burn rate 1.0
    assert abs(t.burn_rate(300.0, now) - 1.0) < 1e-9
    assert t.good_total == 90 and t.bad_total == 10
    # all-bad second bucket pushes the short window over budget
    for _ in range(50):
        t.record(False, now + 10.0)
    assert t.burn_rate(300.0, now + 10.0) > 1.0
    # outside the window the counts retire
    g, b = t.window_counts(300.0, now + 10_000.0)
    assert (g, b) == (0, 0)
    assert t.burn_rate(300.0, now + 10_000.0) == 0.0
    # ring wrap: a slot reused an hour later forgets the old counts
    t2 = SloTracker(100.0, 0.99)
    t2.record(False, 0.0)
    t2.record(True, SloTracker.HORIZON_S)    # same slot, new id
    assert t2.window_counts(3600.0, SloTracker.HORIZON_S) == (1, 0)


def test_slo_counts_sheds_as_bad_and_alerts_without_ticks():
    """Requests shed at the admission edge never become futures and a
    total outage serves no ticks — the SLO must burn AND page anyway
    (overload is exactly what it exists for)."""
    from lightgbm_tpu.obs.drift import ServingObserver
    seq0 = flight.recorder().events()[-1]["seq"] \
        if flight.recorder().events() else 0
    obs = ServingObserver({}, slo_ms=100.0, slo_target=0.9)
    obs.on_shed("predict")
    obs.on_shed("leaf")
    assert obs.slo.bad_total == 2 and obs.slo.good_total == 0
    assert obs.slo.burn_rate(300.0) > 1.0
    # the alert fired from the shed path itself — no on_tick_served ran
    assert obs.slo.alerting
    assert len(_events_since(seq0, ("slo_burn",))) == 1


def test_phase_times_clamped_on_client_timeout_race():
    """A client-side result() timeout can complete the future BEFORE the
    worker stamps served_at; phases must clamp non-negative and still
    sum to the latency."""
    from lightgbm_tpu.serving.coalescer import ServeFuture
    fut = ServeFuture(np.zeros((2, 3), np.float32), None, 0.0)
    fut.popped_at = fut.created_at + 0.010
    fut._fail(RuntimeError("client timeout"))      # stamps completed_at
    fut.served_at = fut.completed_at + 0.050       # worker, later
    ph = fut.phase_times()
    assert all(v >= 0 for v in ph.values()), ph
    assert abs(sum(ph.values()) - fut.latency_s) < 1e-9
    # and a future completed while still queued (popped after) clamps too
    fut2 = ServeFuture(np.zeros((1, 3), np.float32), None, 0.0)
    fut2._fail(RuntimeError("expired"))
    fut2.popped_at = fut2.completed_at + 0.020
    ph2 = fut2.phase_times()
    assert all(v >= 0 for v in ph2.values()), ph2


def test_drift_reference_refreshes_after_continued_training(
        drift_booster):
    """A booster that keeps training after a drift-armed deploy must not
    ship the stale score baseline on redeploy."""
    X, y = _data(seed=11)
    p = _params()
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p), 3)
    g = bst._gbdt
    _, _, s1 = g.drift_reference()
    bst.update()                                   # continue training
    _, _, s2 = g.drift_reference()
    assert s2.shape != s1.shape or not np.array_equal(s1, s2)


def test_latency_histograms_pruned_across_swaps(drift_booster):
    """A continuous-refit server swaps forever; /metrics cardinality
    must not grow one histogram family per retired version."""
    bst, X = drift_booster
    from lightgbm_tpu.obs.drift import LatencyHistogram, ServingObserver
    obs = ServingObserver({})
    for v in ("v0", "v1", "v2", "v3", "v4", "v5"):
        obs._hists[("predict", v)] = LatencyHistogram()
        obs.attach_model(v, bst, [])               # drift off: prune only
    keys = {k[1] for k in obs._hists}
    assert keys == {"v2", "v3", "v4", "v5"}        # last 4 attaches kept


def test_slo_burn_alert_fires(drift_booster):
    """An unmeetable SLO (1 microsecond) burns both windows -> one
    slo_burn flight event + the alerting gauge."""
    bst, X = drift_booster
    seq0 = flight.recorder().events()[-1]["seq"] \
        if flight.recorder().events() else 0
    srv = bst.serve(tick_ms=1.0, deadline_ms=10_000.0, slo_ms=0.001)
    try:
        for _ in range(5):
            srv.predict(X[:8])
        time.sleep(0.05)
        s = srv.observer.slo
        assert s.bad_total >= 5 and s.good_total == 0
        assert s.alerting
        evs = _events_since(seq0, ("slo_burn",))
        assert len(evs) == 1                  # transition-gated, no spam
        assert "lgbm_tpu_serve_slo_alerting 1" in srv.metrics_text()
        snap = srv.observer.snapshot()
        assert snap["slo"]["burn_5m"] > 1.0
    finally:
        srv.close(drain=True)


# ------------------------------------------------- per-kind stats (coalescer)
def test_per_kind_stats_breakdown(drift_booster):
    bst, X = drift_booster
    p = _params(tpu_serve_endpoints="predict,leaf")
    X2, y2 = _data(seed=1)
    b = lgb.train(p, lgb.Dataset(X2, label=y2, params=p), 3)
    srv = b.serve(tick_ms=1.0, deadline_ms=10_000.0)
    try:
        srv.predict(X2[:10])
        srv.predict_leaf(X2[:10])
        srv.predict(X2[:5])
        st = srv.stats
        assert st["kinds"]["predict"]["served_requests"] == 2
        assert st["kinds"]["predict"]["served_rows"] == 15
        assert st["kinds"]["leaf"]["served_requests"] == 1
        assert st["kinds"]["leaf"]["served_rows"] == 10
        # aggregates stay the compatible flat keys
        assert st["served_requests"] == 3
        assert st["served_rows"] == 25
        # the snapshot must not alias live dicts
        st["kinds"]["predict"]["served_requests"] = 999
        assert srv.stats["kinds"]["predict"]["served_requests"] == 2
        # nested kinds flatten into /metrics gauges
        flat = obs_metrics.flatten_metrics(srv.health())
        assert flat["stats_kinds_leaf_served_rows"] == 10.0
    finally:
        srv.close(drain=True)


def test_per_kind_timeout_counter(drift_booster):
    bst, X = drift_booster
    srv = bst.serve(tick_ms=40.0)
    try:
        fut = srv.submit(X[:4], deadline_ms=1.0)
        with pytest.raises(Exception):
            fut.result()
        end = time.monotonic() + 5.0
        while time.monotonic() < end:
            if srv.stats["kinds"].get("predict", {}).get("timeouts"):
                break
            time.sleep(0.01)
        st = srv.stats
        assert st["kinds"]["predict"]["timeouts"] >= 1
        assert st["timeouts"] >= 1
    finally:
        srv.close(drain=False, timeout_s=5.0)


# --------------------------------------------------- label escaping hygiene
def test_escape_label_value_hostile():
    assert obs_metrics.escape_label_value('a"b') == 'a\\"b'
    assert obs_metrics.escape_label_value("a\\b") == "a\\\\b"
    assert obs_metrics.escape_label_value("a\nb") == "a\\nb"
    # order matters: the backslash introduced by the quote escape must
    # not be re-escaped
    assert obs_metrics.escape_label_value('\\"') == '\\\\\\"'
    lab = obs_metrics.render_labels({"f": 'x"y\nz\\w', "bad name!": "v"})
    assert lab == '{f="x\\"y\\nz\\\\w",bad_name_="v"}'


def test_prometheus_hostile_feature_names():
    """Feature names with quotes/backslashes/newlines come straight from
    user data; the exposition must stay parseable."""
    rng = np.random.RandomState(5)
    X = rng.randn(300, 3)
    y = (X[:, 0] > 0).astype(np.float64)
    names = ['fe"at', 'ba\\ck', 'new\nline']
    p = _params()
    bst = lgb.train(p, lgb.Dataset(X, label=y, feature_name=names,
                                   params=p), 3)
    srv = bst.serve(tick_ms=1.0, drift_flush_every=1)
    try:
        srv.predict(X[:100])
        _wait_flushes(srv.observer.drift, 1)
        text = srv.metrics_text()
        psi_lines = [ln for ln in text.splitlines()
                     if ln.startswith("lgbm_tpu_drift_psi{")]
        assert len(psi_lines) == 3
        joined = "\n".join(psi_lines)
        assert 'feature="fe\\"at"' in joined
        assert 'feature="ba\\\\ck"' in joined
        assert 'feature="new\\nline"' in joined
        # every sample line still parses as name{labels} value
        for ln in psi_lines:
            assert ln.count("{") == 1 and ln.rsplit(" ", 1)[1]
            float(ln.rsplit(" ", 1)[1])
    finally:
        srv.close(drain=True)


# --------------------------------------------------------- scripts/obs drift
def test_obs_drift_cli(tmp_path, capsys):
    path = tmp_path / "stream.jsonl"
    recs = [
        {"t": 1.0, "kind": "drift_flush", "version": "v0", "flush": 1,
         "window_rows": 256, "threshold": 0.2,
         "psi": {"f0": 0.01, "f2": 0.91, "f1": 0.05},
         "kl": {"f0": 0.005, "f2": 0.6, "f1": 0.02},
         "max_psi": 0.91, "max_feature": "f2", "score_psi": 0.4,
         "score_drifted": True, "drifted": ["f2"]},
        {"t": 1.1, "event": "drift_detected", "feature": "f2",
         "psi": 0.91, "version": "v0", "flush": 1},
        {"t": 1.2, "kind": "slo", "slo_ms": 50.0, "target": 0.99,
         "good_total": 90, "bad_total": 30, "burn_5m": 25.0,
         "burn_1h": 25.0, "alerting": True},
    ]
    path.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    assert summarize.drift_main([str(path), "--top", "2"]) == 0
    out = capsys.readouterr().out
    assert "f2" in out and "DRIFTED" in out
    assert "0.91" in out
    assert "score drift" in out
    assert "25.0" in out                      # burn tail rendered
    assert "f0" not in out.split("drift/SLO events")[0]  # top-2 cut
    # --json emits the machine-readable summary
    assert summarize.drift_main([str(path), "--json"]) == 0
    js = json.loads(capsys.readouterr().out)
    assert js["psi_table"][0]["feature"] == "f2"
    assert js["slo_tail"][0]["burn_5m"] == 25.0
    # missing file is a structured failure
    assert summarize.drift_main([str(tmp_path / "nope.jsonl")]) == 2


def test_obs_drift_summary_dedups_stream_and_flight_twin(tmp_path):
    """The same flush appears in BOTH the stream (full psi map) and the
    flight dump (compact twin); given both files the summary must count
    it once and prefer the psi-bearing record."""
    stream = tmp_path / "s.jsonl"
    dump = tmp_path / "f.jsonl"
    stream.write_text(json.dumps(
        {"t": 1.0, "kind": "drift_flush", "version": "v0", "flush": 1,
         "window_rows": 500, "threshold": 0.2, "psi": {"a": 0.5},
         "kl": {"a": 0.3}, "max_psi": 0.5, "max_feature": "a",
         "drifted": ["a"]}) + "\n")
    dump.write_text(json.dumps(
        {"t": 1.0, "seq": 9, "event": "drift_flush", "version": "v0",
         "flush": 1, "window_rows": 500, "max_psi": 0.5,
         "max_feature": "a", "drifted": 1}) + "\n")
    s = summarize.drift_summary([str(dump), str(stream)])
    assert s["flushes"] == 1
    assert s["latest"]["threshold"] == 0.2   # the stream record won
    assert s["psi_table"][0]["feature"] == "a"


def test_obs_drift_cli_reads_flight_dump(tmp_path, drift_booster,
                                         capsys):
    """The flight-ring twins (summary fields only) render the header
    even without a psi map."""
    bst, X = drift_booster
    srv = bst.serve(tick_ms=1.0, drift_flush_every=1)
    try:
        srv.predict(X[:50])
        _wait_flushes(srv.observer.drift, 1)
    finally:
        srv.close(drain=True)
    dump = flight.dump("test", path=str(tmp_path / "f.jsonl"))
    assert summarize.drift_main([dump]) == 0
    out = capsys.readouterr().out
    assert "drift flushes:" in out
