"""Depth-batched inference engine (ops/predict.py).

Covers the tentpole's contracts:
  * bit-exact leaf-index parity of the depth walk vs the node-sweep
    reference (numeric NaN defaults, categorical bitsets, EFB col_of,
    multiclass), raw-score parity within float-accumulation tolerance;
  * early-stop margin/freq semantics preserved under tree batching
    (chunk boundaries land on the reference's iteration checkpoints);
  * the bucket ladders (rows / trees / depth) and the zero-recompile
    serving proof: after one warmup per rung, predicts at distinct batch
    sizes compile nothing and move nothing device->host;
  * the _device_trees_cache append-pad fix: mid-train predicts extend
    the padded stack instead of re-uploading the whole model;
  * 4-bit packed serving (tpu_bin_pack4): bit-identical predictions,
    packed histogram gathers, host round-trip.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.analysis import guards
from lightgbm_tpu.ops import predict as P
from lightgbm_tpu.io.dataset import (pack4_eligible, pack4_matrix,
                                     unpack4_matrix)

from utils import FAST_PARAMS, binary_data, multiclass_data


def _train(params=None, X=None, y=None, rounds=12):
    if X is None:
        X, y = binary_data()
    p = dict(FAST_PARAMS, objective="binary", **(params or {}))
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p), rounds)
    return bst, X


def _both_engines(bst, fn):
    """(batched_result, scan_result) of ``fn(bst)`` under each engine."""
    out_new = fn(bst)
    bst._gbdt.config.set({"tpu_predict_engine": "scan"})
    try:
        out_old = fn(bst)
    finally:
        bst._gbdt.config.set({"tpu_predict_engine": "batched"})
    return out_new, out_old


# ------------------------------------------------------------- ladders
def test_bucket_ladder_helpers():
    ladder = P.parse_bucket_ladder("auto")
    assert ladder[0] == 1024 and ladder[-1] == 1 << 20
    assert all(b == 2 * a for a, b in zip(ladder, ladder[1:]))
    assert P.parse_bucket_ladder("4000,1000,2000") == (1000, 2000, 4000)
    assert P.bucket_rows(1, ladder) == 1024
    assert P.bucket_rows(1024, ladder) == 1024
    assert P.bucket_rows(1025, ladder) == 2048
    assert P.bucket_rows((1 << 20) + 1, ladder) is None
    with pytest.raises(ValueError):
        P.parse_bucket_ladder("0,-5")

    assert P.tree_bucket(1, 16) == 16
    assert P.tree_bucket(17, 16) == 32
    assert P.tree_bucket(500, 16) == 512
    assert P.depth_bucket(0) == 4
    assert P.depth_bucket(9) == 16


def test_early_stop_tbatch_alignment():
    # chunks are k * (divisor of freq) <= the configured batch, so every
    # iteration multiple of freq is a chunk boundary
    assert P.early_stop_tbatch(1, 10, 16) == 10
    assert P.early_stop_tbatch(3, 10, 16) == 15   # 3 * 5, 5 | 10
    assert P.early_stop_tbatch(1, 7, 16) == 7
    assert P.early_stop_tbatch(1, 64, 16) == 16   # 16 | 64
    assert P.early_stop_tbatch(5, 7, 16) == 5     # only f=1 fits
    for k, freq, tb in [(1, 10, 16), (3, 4, 16), (2, 9, 8), (4, 25, 12)]:
        c = P.early_stop_tbatch(k, freq, tb)
        assert c % k == 0 and (k * freq) % c == 0


# ------------------------------------------------------------- parity
def test_leaf_and_raw_parity_nan_defaults():
    X, y = binary_data()
    Xn = np.array(X, np.float64)
    rng = np.random.RandomState(0)
    Xn[rng.rand(*Xn.shape) < 0.08] = np.nan
    bst, _ = _train({"use_missing": True}, Xn, y, rounds=15)
    q = Xn[:257]
    (leaf_new, raw_new), (leaf_old, raw_old) = _both_engines(
        bst, lambda b: (b.predict(q, pred_leaf=True),
                        b.predict(q, raw_score=True)))
    assert np.array_equal(leaf_new, leaf_old)
    np.testing.assert_allclose(raw_new, raw_old, atol=1e-5)


def test_leaf_parity_categorical_bitsets():
    rng = np.random.RandomState(1)
    n = 900
    Xc = rng.randn(n, 6)
    Xc[:, 0] = rng.randint(0, 40, n)   # wide cats -> multi-word bitset
    Xc[:, 1] = rng.randint(0, 6, n)
    # label driven by category membership so bitset splits actually win
    y = ((np.isin(Xc[:, 0], [1, 3, 5, 8, 13, 21, 34])
          | (Xc[:, 1] > 3)) ^ (rng.rand(n) < 0.05)).astype(np.float64)
    p = dict(FAST_PARAMS, objective="binary", max_cat_to_onehot=2)
    bst = lgb.train(p, lgb.Dataset(Xc, label=y, params=p,
                                   categorical_feature=[0, 1]), 15)
    assert any(np.any(m.cat_bitset) for m in bst._gbdt.models), \
        "test did not exercise categorical splits"
    q = Xc[:300]
    new, old = _both_engines(bst, lambda b: b.predict(q, pred_leaf=True))
    assert np.array_equal(new, old)


def test_walk_parity_efb_col_of():
    """The walk's per-node EFB column translation (col_of) lands the same
    leaves as route_one_tree on a bundled matrix."""
    rng = np.random.RandomState(2)
    n, groups, card = 900, 50, 6       # 300 one-hot cols (EFB needs >= 256)
    X = np.zeros((n, groups * card), np.float64)
    for g in range(groups):
        X[np.arange(n), g * card + rng.randint(0, card, n)] = 1.0
    y = (X[:, ::card].sum(1) + 0.3 * rng.randn(n) > 0.5).astype(np.float64)
    p = dict(FAST_PARAMS, objective="binary", enable_bundle=True)
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p), 10)
    g = bst._gbdt
    assert g._efb is not None, "test did not exercise EFB"
    # route the BUNDLED training matrix with col_of through both paths
    binned = np.asarray(g._routing_binned())
    trees, t_real = g._device_trees_plain()
    nan_a, cat_a, col_of = g._route_args()
    dev = jnp.asarray(binned)
    old = [np.asarray(P.route_one_tree(
        dev, trees.split_feature[i], trees.split_bin[i],
        trees.cat_bitset[i], trees.default_left[i], trees.left_child[i],
        trees.right_child[i], trees.num_nodes[i], nan_a, cat_a, col_of))
        for i in range(t_real)]
    depth = P.depth_bucket(g._models_max_depth(g.models))
    st = lgb.boosting.gbdt.stack_trees(
        g.models, trees.max_nodes, trees.leaf_value.shape[1],
        pad_to=P.tree_bucket(t_real, 8))
    new = np.asarray(P.predict_leaf_batched(
        dev, st, nan_a, cat_a, depth=depth, tbatch=8, any_cat=True,
        col_of=col_of))[:t_real]
    assert np.array_equal(np.stack(old), new)


def test_raw_parity_multiclass():
    X, y = multiclass_data()
    p = dict(FAST_PARAMS, objective="multiclass", num_class=3)
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p), 8)
    q = X[:200]
    new, old = _both_engines(bst, lambda b: b.predict(q))
    np.testing.assert_allclose(new, old, atol=1e-6)
    assert np.allclose(new.sum(1), 1.0, atol=1e-5)


@pytest.mark.parametrize("freq", [1, 2, 3, 7])
def test_early_stop_parity_under_tree_batching(freq):
    bst, X = _train(rounds=20)
    kw = dict(pred_early_stop=True, pred_early_stop_margin=0.4,
              pred_early_stop_freq=freq)
    q = X[:400]
    new, old = _both_engines(bst, lambda b: b.predict(q, **kw))
    np.testing.assert_allclose(new, old, atol=1e-6)
    # and it genuinely fires (otherwise this test proves nothing)
    assert not np.allclose(bst.predict(q), new)


def test_rf_average_output_uses_real_tree_count():
    X, y = binary_data()
    p = dict(FAST_PARAMS, objective="binary", boosting="rf",
             bagging_fraction=0.7, bagging_freq=1)
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p), 9)
    q = X[:100]
    new, old = _both_engines(bst, lambda b: b.predict(q))
    # tree-count padding must not leak into the averaging divisor
    np.testing.assert_allclose(new, old, atol=1e-6)


# ------------------------------------------------- serving cache proof
def test_steady_state_zero_recompile_zero_d2h_mixed_batches():
    """The acceptance criterion: one warmup per bucket rung, then
    predicts at 3 distinct batch sizes trigger 0 compile events and 0
    host transfers."""
    rng = np.random.RandomState(3)
    X = rng.randn(6000, 10)
    y = (X[:, 0] + 0.5 * rng.randn(6000) > 0).astype(np.float64)
    p = dict(FAST_PARAMS, objective="binary")
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p), 10)
    g = bst._gbdt
    for n in (600, 1500, 3500):          # warm rungs 1024, 2048, 4096
        g.predict_raw_device(g.bin_matrix(X[:n])).block_until_ready()
    with guards.steady_state_guard("mixed-batch serving") as cc:
        outs = [g.predict_raw_device(g.bin_matrix(X[:n]))
                for n in (900, 1800, 3000)]
        for o in outs:
            o.block_until_ready()
    assert cc.lowerings == 0 and cc.backend_compiles == 0
    # the padded device results agree with the host predict path
    for n, o in zip((900, 1800, 3000), outs):
        np.testing.assert_allclose(np.asarray(o)[0, :n],
                                   bst.predict(X[:n], raw_score=True),
                                   atol=1e-6)


def test_predict_device_api():
    bst, X = _train()
    d = bst.predict_device(X[:77])
    assert isinstance(d, jax.Array) and d.shape == (77,)
    np.testing.assert_allclose(np.asarray(d),
                               bst.predict(X[:77], raw_score=True),
                               atol=1e-6)


def test_predict_device_oversize_slices_on_device():
    bst, X = _train()
    bst._gbdt.config.set({"tpu_predict_buckets": "64,128"})
    try:
        d = bst.predict_device(X[:500])      # 500 rows >> max rung 128
        assert isinstance(d, jax.Array) and d.shape == (500,)
        ref = bst.predict(X[:500], raw_score=True)
    finally:
        bst._gbdt.config.set({"tpu_predict_buckets": "auto"})
    np.testing.assert_allclose(np.asarray(d), ref, atol=1e-6)


def test_predict_device_rejects_continue_trained(tmp_path):
    X, y = binary_data()
    p = dict(FAST_PARAMS, objective="binary")
    b1 = lgb.train(p, lgb.Dataset(X, label=y, params=p), 5)
    path = str(tmp_path / "base.txt")
    b1.save_model(path)
    b2 = lgb.train(p, lgb.Dataset(X, label=y, params=p), 3,
                   init_model=path)
    if getattr(b2, "_pre_model", None) is None:
        pytest.skip("continue-training did not attach a base model")
    with pytest.raises(NotImplementedError, match="continue-trained"):
        b2.predict_device(X[:10])


def test_oversize_request_slices_through_ladder():
    bst, X = _train()
    bst._gbdt.config.set({"tpu_predict_buckets": "64,128"})
    try:
        q = np.tile(X, (1, 1))[:600]      # 600 rows >> max rung 128
        out = bst.predict(q)
        bst._gbdt.config.set({"tpu_predict_buckets": "auto"})
        ref = bst.predict(q)
    finally:
        bst._gbdt.config.set({"tpu_predict_buckets": "auto"})
    np.testing.assert_allclose(out, ref, atol=1e-6)


def test_device_trees_cache_appends_not_rebuilds():
    """Satellite: mid-train predict must append-pad the cached stack."""
    bst, X = _train(rounds=5)
    g = bst._gbdt
    key = (g._predict_cfg()[0], 0, None)
    bst.predict(X[:50])
    c0 = g._device_trees_cache[key]
    assert c0 is not None and c0["t_real"] == 5
    base_leaf = c0["st"].leaf_value
    for _ in range(3):
        bst.update()
    bst.predict(X[:50])
    c1 = g._device_trees_cache[key]
    assert c1 is c0 and c1["t_real"] == 8
    assert c1["t_bucket"] >= 8 and c1["t_bucket"] % key[0] == 0
    # same bucket -> the padded arrays were updated in place, and the
    # window beyond the old fill now holds the new trees
    if c1["t_bucket"] == c0["t_bucket"]:
        assert c1["st"].leaf_value.shape == base_leaf.shape
    ref = np.asarray(P.predict_raw_scan(
        jnp.asarray(g.bin_matrix(X[:50])), g._device_trees_plain()[0],
        *g._pred_route_args(), np.int32(1), 1))
    np.testing.assert_allclose(bst.predict(X[:50], raw_score=True),
                               ref[0], atol=1e-6)


def test_zero_row_predict_and_leaf():
    bst, X = _train(rounds=5)
    empty = X[:0]
    assert bst.predict(empty).shape == (0,)
    assert bst.predict(empty, pred_leaf=True).shape == (0, 5)


def test_alternating_early_stop_does_not_thrash_cache(monkeypatch):
    """Plain and early-stop predicts use different tree-chunk sizes; each
    must keep its own cache slot instead of restacking the model per
    call."""
    import lightgbm_tpu.boosting.gbdt as gbdt_mod
    bst, X = _train(rounds=8)
    kw = dict(pred_early_stop=True, pred_early_stop_margin=0.4,
              pred_early_stop_freq=10)
    bst.predict(X[:50])
    bst.predict(X[:50], **kw)          # fill both slots
    calls = []
    orig = gbdt_mod.stack_trees
    monkeypatch.setattr(gbdt_mod, "stack_trees",
                        lambda *a, **k: calls.append(1) or orig(*a, **k))
    for _ in range(3):
        bst.predict(X[:50])
        bst.predict(X[:50], **kw)
    assert not calls, "alternating predicts restacked the model"


def test_rollback_invalidates_cache():
    bst, X = _train(rounds=6)
    before = bst.predict(X[:40], raw_score=True)
    bst._gbdt.rollback_one_iter()
    after = bst.predict(X[:40], raw_score=True)
    key = (bst._gbdt._predict_cfg()[0], 0, None)
    assert bst._gbdt._device_trees_cache[key]["t_real"] == 5
    assert not np.allclose(before, after)


def test_windowed_predict_matches_scan():
    bst, X = _train(rounds=10)
    q = X[:120]
    new, old = _both_engines(
        bst, lambda b: b.predict(q, start_iteration=3, num_iteration=4,
                                 raw_score=True))
    np.testing.assert_allclose(new, old, atol=1e-6)


def test_best_iteration_windowed_serving_is_cached(monkeypatch):
    """Booster.predict defaults num_iteration=best_iteration after
    early-stopped training — THE common serving window. It must hit the
    keyed device-tree cache, not restack the model per call."""
    import lightgbm_tpu.boosting.gbdt as gbdt_mod
    bst, X = _train(rounds=10)
    bst.best_iteration = 7                 # as early stopping would set
    bst.predict(X[:50])                    # fills the windowed slot
    calls = []
    orig = gbdt_mod.stack_trees
    monkeypatch.setattr(gbdt_mod, "stack_trees",
                        lambda *a, **k: calls.append(1) or orig(*a, **k))
    ref = bst.predict(X[:50])
    for _ in range(3):
        np.testing.assert_array_equal(bst.predict(X[:50]), ref)
    assert not calls, "windowed serving restacked the model per call"
    bst.best_iteration = -1
    np.testing.assert_allclose(
        bst.predict(X[:50], num_iteration=7), ref, atol=1e-7)


# ------------------------------------------------------- 4-bit packing
def test_pack4_roundtrip_and_eligibility():
    rng = np.random.RandomState(4)
    for f in (6, 7):
        m = rng.randint(0, 16, (40, f)).astype(np.uint8)
        assert np.array_equal(unpack4_matrix(pack4_matrix(m), f), m)
    with pytest.raises(ValueError):
        pack4_matrix(np.zeros((3, 2), np.uint16))


def test_pack4_predict_bit_identical():
    X, y = binary_data()
    base = dict(FAST_PARAMS, objective="binary", max_bin=15)
    b0 = lgb.train(base, lgb.Dataset(X, label=y, params=base), 10)
    p4 = dict(base, tpu_bin_pack4=True)
    b1 = lgb.train(p4, lgb.Dataset(X, label=y, params=p4), 10)
    assert b1._gbdt._pred_pack4
    assert pack4_eligible(b1._gbdt.train_set.mappers)
    q = X[:300]
    assert np.array_equal(b0.predict(q), b1.predict(q))
    assert np.array_equal(b0.predict(q, pred_leaf=True),
                          b1.predict(q, pred_leaf=True))


def test_pack4_falls_back_when_ineligible():
    X, y = binary_data()
    p = dict(FAST_PARAMS, objective="binary", max_bin=31,
             tpu_bin_pack4=True)       # 31 bins do not fit a nibble
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p), 5)
    assert not bst._gbdt._pred_pack4
    assert bst.predict(X[:50]).shape == (50,)


def test_pack4_histogram_block_parity():
    from lightgbm_tpu.ops.histogram import histogram_block
    rng = np.random.RandomState(5)
    n, f, b = 512, 9, 16
    binned = rng.randint(0, b, (n, f)).astype(np.uint8)
    ch = rng.randn(n, 4).astype(np.float32)
    full = histogram_block(jnp.asarray(binned), jnp.asarray(ch), b,
                           impl="xla")
    packed = histogram_block(jnp.asarray(pack4_matrix(binned)),
                             jnp.asarray(ch), b, impl="xla",
                             packed4_features=f)
    assert np.array_equal(np.asarray(full), np.asarray(packed))
