"""Sparse ingestion, file loading, CLI, plotting.

Mirrors the reference's test_basic.py Dataset construction paths,
test_consistency.py (CLI-config vs Python parity) and test_plotting.py."""
import os
import subprocess
import sys

import matplotlib
matplotlib.use("Agg")

import numpy as np
import pytest
import scipy.sparse as sp

import lightgbm_tpu as lgb

from utils import FAST_PARAMS, binary_data


def _params(**kw):
    p = dict(FAST_PARAMS)
    p.update(kw)
    return p


class TestSparse:
    def test_csr_train_and_predict(self):
        X, y = binary_data()
        Xs = sp.csr_matrix(X)
        bst = lgb.train(_params(objective="binary"), lgb.Dataset(Xs, label=y), 10)
        p_sparse = bst.predict(sp.csr_matrix(X))
        p_dense = bst.predict(X)
        np.testing.assert_allclose(p_sparse, p_dense, rtol=1e-6)
        # same model as dense input (dense is the canonical layout)
        bst_d = lgb.train(_params(objective="binary"), lgb.Dataset(X, label=y), 10)
        np.testing.assert_allclose(bst_d.predict(X), p_dense, rtol=1e-6)

    def test_csc_input(self):
        X, y = binary_data()
        bst = lgb.train(_params(objective="binary"),
                        lgb.Dataset(sp.csc_matrix(X), label=y), 5)
        from sklearn.metrics import roc_auc_score
        assert roc_auc_score(y, bst.predict(X)) > 0.9


class TestFileLoading:
    def test_csv_roundtrip(self, tmp_path):
        X, y = binary_data()
        path = tmp_path / "train.csv"
        np.savetxt(path, np.column_stack([y, X]), delimiter=",", fmt="%.9g")
        from lightgbm_tpu.io.loader import load_text_file
        X2, y2, w, g, names = load_text_file(str(path))
        np.testing.assert_allclose(X2, X, rtol=1e-6)
        np.testing.assert_allclose(y2, y)
        assert w is None and g is None

    def test_tsv_with_header_and_columns(self, tmp_path):
        X, y = binary_data(n=100, f=4)
        w = np.random.RandomState(0).rand(100)
        path = tmp_path / "train.tsv"
        header = "target\tw\tc0\tc1\tc2\tc3"
        np.savetxt(path, np.column_stack([y, w, X]), delimiter="\t",
                   fmt="%.9g", header=header, comments="")
        from lightgbm_tpu.io.loader import load_text_file
        X2, y2, w2, _, names = load_text_file(
            str(path), has_header=True, label_column="name:target",
            weight_column="name:w")
        np.testing.assert_allclose(X2, X, rtol=1e-6)
        np.testing.assert_allclose(w2, w, rtol=1e-6)
        assert names == ["c0", "c1", "c2", "c3"]

    def test_libsvm(self, tmp_path):
        path = tmp_path / "train.svm"
        path.write_text("1 0:1.5 2:3.0\n0 1:2.0\n1 0:0.5 1:1.0 2:-1\n")
        from lightgbm_tpu.io.loader import load_text_file
        X, y, _, _, _ = load_text_file(str(path))
        assert X.shape == (3, 3)
        np.testing.assert_allclose(y, [1, 0, 1])
        np.testing.assert_allclose(X[0], [1.5, 0, 3.0])


class TestCLI:
    def test_train_and_predict_tasks(self, tmp_path):
        X, y = binary_data()
        data = tmp_path / "train.csv"
        np.savetxt(data, np.column_stack([y, X]), delimiter=",", fmt="%.9g")
        conf = tmp_path / "train.conf"
        model = tmp_path / "model.txt"
        conf.write_text(
            f"task = train\ndata = {data}\nobjective = binary\n"
            f"num_iterations = 10\nnum_leaves = 15\nmax_bin = 31\n"
            f"min_data_in_leaf = 5\noutput_model = {model}\n"
            "verbosity = -1\n")
        from lightgbm_tpu.cli import run
        assert run([f"config={conf}"]) == 0
        assert model.exists()
        out = tmp_path / "pred.txt"
        assert run([f"task=predict", f"data={data}",
                    f"input_model={model}", f"output_result={out}"]) == 0
        pred = np.loadtxt(out)
        bst = lgb.Booster(model_file=str(model))
        np.testing.assert_allclose(pred, bst.predict(X), rtol=1e-5, atol=1e-6)


class TestPlotting:
    def test_plot_importance_and_metric(self):
        X, y = binary_data()
        rec = {}
        ds = lgb.Dataset(X, label=y)
        bst = lgb.train(_params(objective="binary", metric="binary_logloss"),
                        ds, 10, valid_sets=[ds], valid_names=["t"],
                        callbacks=[lgb.record_evaluation(rec)])
        ax = lgb.plot_importance(bst)
        assert ax is not None
        ax2 = lgb.plot_metric(rec)
        assert ax2 is not None
        ax3 = lgb.plot_split_value_histogram(bst, 0) if \
            bst.feature_importance()[0] > 0 else None

    def test_create_tree_digraph(self):
        X, y = binary_data()
        bst = lgb.train(_params(objective="binary"), lgb.Dataset(X, label=y), 3)
        g = lgb.create_tree_digraph(bst, 0)
        assert "leaf" in g.source


class TestBinaryDatasetAndArrow:
    def test_save_binary_roundtrip(self, tmp_path):
        X, y = binary_data()
        w = np.random.RandomState(1).rand(len(y))
        ds = lgb.Dataset(X, label=y, weight=w)
        path = str(tmp_path / "ds.npz")
        ds.save_binary(path)
        ds2 = lgb.Dataset(path)
        bst1 = lgb.train(_params(objective="binary"), ds, 10)
        bst2 = lgb.train(_params(objective="binary"), ds2, 10)
        np.testing.assert_allclose(bst2.predict(X), bst1.predict(X),
                                   rtol=1e-5, atol=1e-6)

    def test_arrow_table_input(self):
        import pyarrow as pa
        X, y = binary_data()
        table = pa.table({f"c{i}": X[:, i] for i in range(X.shape[1])})
        bst = lgb.train(_params(objective="binary"),
                        lgb.Dataset(table, label=y), 10)
        from sklearn.metrics import roc_auc_score
        assert roc_auc_score(y, bst.predict(X)) > 0.95

    def test_save_binary_bin_extension_and_arrow_names(self, tmp_path):
        import pyarrow as pa
        X, y = binary_data()
        ds = lgb.Dataset(X, label=y)
        path = str(tmp_path / "train.bin")     # reference's canonical name
        ds.save_binary(path)
        assert os.path.exists(path)
        bst = lgb.train(_params(objective="binary"), lgb.Dataset(path), 5)
        assert bst.num_trees() == 5
        table = pa.table({"alpha": X[:, 0], "beta": X[:, 1]})
        ds2 = lgb.Dataset(table, label=y)
        ds2.construct()
        assert ds2._inner.feature_names == ["alpha", "beta"]


class TestCLITasks:
    """The reference CLI's 5 tasks (include/LightGBM/config.h:34):
    train/predict covered above; save_binary, refit, convert_model here
    (reference: Application::Run, application.cpp:168-285)."""

    def _train_files(self, tmp_path):
        from utils import binary_data
        X, y = binary_data()
        data = tmp_path / "train.csv"
        np.savetxt(data, np.column_stack([y, X]), delimiter=",", fmt="%.9g")
        model = tmp_path / "model.txt"
        from lightgbm_tpu.cli import run
        assert run([f"task=train", f"data={data}", "objective=binary",
                    "num_iterations=6", "num_leaves=15", "max_bin=31",
                    "min_data_in_leaf=5", f"output_model={model}",
                    "verbosity=-1"]) == 0
        return X, y, data, model

    def test_save_binary_task(self, tmp_path):
        from lightgbm_tpu.cli import run
        X, y, data, model = self._train_files(tmp_path)
        out = tmp_path / "train.bin"
        assert run([f"task=save_binary", f"data={data}", "max_bin=31",
                    f"output_model={out}"]) == 0
        ds = lgb.Dataset(str(out))
        ds.construct()
        assert ds._inner.num_data == len(y)

    def test_refit_task(self, tmp_path):
        from lightgbm_tpu.cli import run
        X, y, data, model = self._train_files(tmp_path)
        out = tmp_path / "refit.txt"
        assert run([f"task=refit", f"data={data}", f"input_model={model}",
                    "refit_decay_rate=0.5", f"output_model={out}"]) == 0
        p0 = lgb.Booster(model_file=str(model)).predict(X)
        p1 = lgb.Booster(model_file=str(out)).predict(X)
        assert np.abs(p0 - p1).max() > 0          # leaves actually changed
        from sklearn.metrics import roc_auc_score
        assert roc_auc_score(y, p1) > 0.85        # and still predictive

    def test_convert_model_compiles_and_matches(self, tmp_path):
        import shutil
        import subprocess
        from lightgbm_tpu.cli import run
        X, y, data, model = self._train_files(tmp_path)
        src = tmp_path / "pred.cpp"
        assert run([f"task=convert_model", f"input_model={model}",
                    f"convert_model={src}"]) == 0
        code = src.read_text()
        assert "PredictTree0" in code and "void Predict" in code
        gxx = shutil.which("g++")
        if gxx is None:
            pytest.skip("no g++ available")
        # compile the generated if-else model and compare with predict()
        main = tmp_path / "main.cpp"
        main.write_text(
            '#include <cstdio>\n#include "pred.cpp"\n'
            "int main() {\n"
            "  double x[64]; double out[4];\n"
            "  while (true) {\n"
            f"    for (int j = 0; j < {X.shape[1]}; ++j)\n"
            '      if (scanf("%lf", &x[j]) != 1) return 0;\n'
            "    lightgbm_tpu_model::Predict(x, out);\n"
            '    printf("%.9g\\n", out[0]);\n'
            "  }\n}\n")
        exe = tmp_path / "pred_bin"
        subprocess.run([gxx, "-O1", "-o", str(exe), str(main)], check=True,
                       cwd=tmp_path)
        rows = X[:100]
        inp = "\n".join(" ".join(f"{v:.9g}" for v in r) for r in rows)
        res = subprocess.run([str(exe)], input=inp, capture_output=True,
                             text=True, check=True)
        got = np.array([float(v) for v in res.stdout.split()])
        bst = lgb.Booster(model_file=str(model))
        raw = bst.predict(rows, raw_score=True)
        np.testing.assert_allclose(got, raw, rtol=1e-6, atol=1e-7)
