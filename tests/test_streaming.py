import numpy as np
import pytest

import lightgbm_tpu as lgb


class _GenSeq(lgb.Sequence):
    """Rows generated on demand from a seed — no [N, F] matrix exists."""
    batch_size = 1000

    def __init__(self, n, f, seed):
        self.n, self.f, self.seed = n, f, seed

    def _rows(self, idx):
        out = np.empty((len(idx), self.f), np.float32)
        for k, i in enumerate(idx):
            rng = np.random.RandomState(self.seed + int(i))
            out[k] = rng.randn(self.f)
        return out

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return self._rows(range(*idx.indices(self.n)))
        return self._rows([idx])[0]

    def __len__(self):
        return self.n


class TestSequenceConstruction:
    def test_streaming_matches_in_memory(self):
        n, f = 5000, 12
        seq = _GenSeq(n, f, 7)
        dense = np.asarray(seq[0:n])
        w = np.random.RandomState(0).randn(f)
        y = ((dense @ w) > 0).astype(np.float64)
        params = {"objective": "binary", "verbosity": -1, "num_leaves": 15,
                  "bin_construct_sample_cnt": 2000}
        ds_s = lgb.Dataset(seq, label=y, params=params)
        ds_m = lgb.Dataset(dense, label=y, params=params)
        ds_s.construct(); ds_m.construct()
        # same sampled-bin construction -> identical packed matrices
        np.testing.assert_array_equal(ds_s._inner.binned, ds_m._inner.binned)
        b_s = lgb.train(dict(params), ds_s, 5)
        b_m = lgb.train(dict(params), ds_m, 5)
        np.testing.assert_allclose(b_s.predict(dense[:200]),
                                   b_m.predict(dense[:200]), atol=1e-6)

    def test_multiple_sequences_and_valid(self):
        n1, n2, f = 3000, 2000, 8
        s1, s2 = _GenSeq(n1, f, 1), _GenSeq(n2, f, 500)
        dense = np.concatenate([np.asarray(s1[0:n1]), np.asarray(s2[0:n2])])
        y = (dense[:, 0] + dense[:, 1] > 0).astype(np.float64)
        params = {"objective": "binary", "verbosity": -1, "num_leaves": 15}
        ds = lgb.Dataset([s1, s2], label=y, params=params)
        dv = ds.create_valid(dense[:500], label=y[:500])
        bst = lgb.train(dict(params), ds, 5, valid_sets=[dv])
        assert np.isfinite(bst.predict(dense[:50])).all()

    @pytest.mark.slow
    def test_streaming_memory_bound(self):
        # peak RSS growth during construct stays under ~2x the packed bin
        # matrix (the raw [N, F] float64 would be 16x it). Slow lane: a
        # 200k-row resource-profiling measurement (~35s, the suite's #2
        # cost) — the streaming-construction CORRECTNESS tests in this
        # file stay tier-1
        import resource
        n, f = 200_000, 40
        seq = _GenSeq(n, f, 11)
        params = {"verbosity": -1, "bin_construct_sample_cnt": 2000,
                  "enable_bundle": False}
        before = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        ds = lgb.Dataset(seq, label=np.zeros(n), params=params)
        ds.construct()
        after = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        binned_kb = ds._inner.binned.nbytes / 1024
        growth_kb = after - before
        raw_kb = n * f * 8 / 1024
        assert growth_kb < max(2 * binned_kb, 0.35 * raw_kb), \
            (growth_kb, binned_kb, raw_kb)

    def test_two_round_text_loading(self, tmp_path):
        """two_round text loading: pass 1 records byte offsets + metadata,
        pass 2 streams batches through the Sequence construction path —
        the dense [N, F] float64 matrix never materializes."""
        from lightgbm_tpu.io.loader import TextFileSequence, load_text_file
        rng = np.random.RandomState(5)
        n, f = 3000, 6
        X = rng.randn(n, f)
        w = rng.rand(n) + 0.5
        y = ((X @ rng.randn(f)) > 0).astype(np.float64)
        path = tmp_path / "train.csv"
        header = "label," + ",".join(f"f{j}" for j in range(f)) + ",wt"
        rows = [header] + [
            ",".join([f"{y[i]:.0f}"] + [f"{X[i, j]:.7g}" for j in range(f)]
                     + [f"{w[i]:.7g}"])
            for i in range(n)]
        path.write_text("\n".join(rows) + "\n")

        seq, label, weight, group, names = load_text_file(
            str(path), has_header=True, label_column="name:label",
            weight_column="name:wt", two_round=True)
        assert isinstance(seq, TextFileSequence)
        assert isinstance(seq, lgb.Sequence)
        assert len(seq) == n
        np.testing.assert_allclose(label, y)
        np.testing.assert_allclose(weight, w, rtol=1e-6)
        assert names == [f"f{j}" for j in range(f)]
        # second round parses on demand, bit-equal to the one-round load
        Xd, yd, wd, _, _ = load_text_file(
            str(path), has_header=True, label_column="name:label",
            weight_column="name:wt")
        np.testing.assert_allclose(np.asarray(seq[0:n]), Xd, rtol=1e-6)
        np.testing.assert_allclose(seq[17], Xd[17], rtol=1e-6)
        # and the Sequence feeds streaming Dataset construction + training
        params = {"objective": "binary", "verbosity": -1, "num_leaves": 15}
        ds_s = lgb.Dataset(seq, label=label, weight=weight, params=params)
        ds_d = lgb.Dataset(Xd, label=yd, weight=wd, params=params)
        ds_s.construct(); ds_d.construct()
        np.testing.assert_array_equal(ds_s._inner.binned, ds_d._inner.binned)
        b = lgb.train(dict(params), ds_s, 5)
        from sklearn.metrics import roc_auc_score
        assert roc_auc_score(y, b.predict(Xd)) > 0.8

    def test_two_round_metadata_and_slicing_edge_cases(self, tmp_path):
        """Empty metadata cells parse as NaN (genfromtxt parity with the
        one-round loader) and non-unit/negative slice steps work."""
        from lightgbm_tpu.io.loader import load_text_file
        path = tmp_path / "edge.csv"
        path.write_text("1,0.5,2.0\n"
                        ",1.5,3.0\n"      # empty label cell
                        "0,2.5,4.0\n")
        seq, label, _, _, _ = load_text_file(str(path), two_round=True)
        assert np.isnan(label[1]) and label[0] == 1.0
        dense = np.asarray(seq[0:3])
        np.testing.assert_allclose(seq[::-1], dense[::-1])
        np.testing.assert_allclose(seq[::2], dense[::2])
        np.testing.assert_allclose(seq[2:0:-1], dense[2:0:-1])
        assert np.asarray(seq[3:3]).shape == (0, 2)
        np.testing.assert_allclose(seq[-1], dense[-1])
        with pytest.raises(IndexError):
            seq[3]
        # junk feature cells are NaN, like np.genfromtxt in one-round mode
        path2 = tmp_path / "junk.csv"
        path2.write_text("1,0.5,NULL\n0,,4.0\n")
        seq2, _, _, _, _ = load_text_file(str(path2), two_round=True)
        row = np.asarray(seq2[0])
        assert row[0] == 0.5 and np.isnan(row[1])
        assert np.isnan(np.asarray(seq2[1])[0])

    def test_streaming_efb(self):
        rng = np.random.RandomState(3)
        n, G, card = 4000, 40, 8
        cats = rng.randint(0, card, size=(n, G))
        dense = np.zeros((n, G * card), np.float32)
        for g in range(G):
            dense[np.arange(n), g * card + cats[:, g]] = 1.0

        class _MatSeq(lgb.Sequence):
            batch_size = 700

            def __init__(self, m):
                self.m = m

            def __getitem__(self, idx):
                return self.m[idx]

            def __len__(self):
                return len(self.m)

        y = (dense @ (rng.randn(G * card) * .5) > 0).astype(np.float64)
        params = {"objective": "binary", "verbosity": -1, "num_leaves": 15}
        ds = lgb.Dataset(_MatSeq(dense), label=y, params=params)
        ds.construct()
        assert ds._inner.bundle_info is not None
        assert ds._inner.bundle_info.n_columns < G * card // 4
        bst = lgb.train(dict(params), ds, 4)
        from sklearn.metrics import roc_auc_score
        assert roc_auc_score(y, bst.predict(dense)) > 0.75
