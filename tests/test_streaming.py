import numpy as np
import pytest

import lightgbm_tpu as lgb


class _GenSeq(lgb.Sequence):
    """Rows generated on demand from a seed — no [N, F] matrix exists."""
    batch_size = 1000

    def __init__(self, n, f, seed):
        self.n, self.f, self.seed = n, f, seed

    def _rows(self, idx):
        out = np.empty((len(idx), self.f), np.float32)
        for k, i in enumerate(idx):
            rng = np.random.RandomState(self.seed + int(i))
            out[k] = rng.randn(self.f)
        return out

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return self._rows(range(*idx.indices(self.n)))
        return self._rows([idx])[0]

    def __len__(self):
        return self.n


class TestSequenceConstruction:
    def test_streaming_matches_in_memory(self):
        n, f = 5000, 12
        seq = _GenSeq(n, f, 7)
        dense = np.asarray(seq[0:n])
        w = np.random.RandomState(0).randn(f)
        y = ((dense @ w) > 0).astype(np.float64)
        params = {"objective": "binary", "verbosity": -1, "num_leaves": 15,
                  "bin_construct_sample_cnt": 2000}
        ds_s = lgb.Dataset(seq, label=y, params=params)
        ds_m = lgb.Dataset(dense, label=y, params=params)
        ds_s.construct(); ds_m.construct()
        # same sampled-bin construction -> identical packed matrices
        np.testing.assert_array_equal(ds_s._inner.binned, ds_m._inner.binned)
        b_s = lgb.train(dict(params), ds_s, 5)
        b_m = lgb.train(dict(params), ds_m, 5)
        np.testing.assert_allclose(b_s.predict(dense[:200]),
                                   b_m.predict(dense[:200]), atol=1e-6)

    def test_multiple_sequences_and_valid(self):
        n1, n2, f = 3000, 2000, 8
        s1, s2 = _GenSeq(n1, f, 1), _GenSeq(n2, f, 500)
        dense = np.concatenate([np.asarray(s1[0:n1]), np.asarray(s2[0:n2])])
        y = (dense[:, 0] + dense[:, 1] > 0).astype(np.float64)
        params = {"objective": "binary", "verbosity": -1, "num_leaves": 15}
        ds = lgb.Dataset([s1, s2], label=y, params=params)
        dv = ds.create_valid(dense[:500], label=y[:500])
        bst = lgb.train(dict(params), ds, 5, valid_sets=[dv])
        assert np.isfinite(bst.predict(dense[:50])).all()

    def test_streaming_memory_bound(self):
        # peak RSS growth during construct stays under ~2x the packed bin
        # matrix (the raw [N, F] float64 would be 16x it)
        import resource
        n, f = 200_000, 40
        seq = _GenSeq(n, f, 11)
        params = {"verbosity": -1, "bin_construct_sample_cnt": 2000,
                  "enable_bundle": False}
        before = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        ds = lgb.Dataset(seq, label=np.zeros(n), params=params)
        ds.construct()
        after = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        binned_kb = ds._inner.binned.nbytes / 1024
        growth_kb = after - before
        raw_kb = n * f * 8 / 1024
        assert growth_kb < max(2 * binned_kb, 0.35 * raw_kb), \
            (growth_kb, binned_kb, raw_kb)

    def test_streaming_efb(self):
        rng = np.random.RandomState(3)
        n, G, card = 4000, 40, 8
        cats = rng.randint(0, card, size=(n, G))
        dense = np.zeros((n, G * card), np.float32)
        for g in range(G):
            dense[np.arange(n), g * card + cats[:, g]] = 1.0

        class _MatSeq(lgb.Sequence):
            batch_size = 700

            def __init__(self, m):
                self.m = m

            def __getitem__(self, idx):
                return self.m[idx]

            def __len__(self):
                return len(self.m)

        y = (dense @ (rng.randn(G * card) * .5) > 0).astype(np.float64)
        params = {"objective": "binary", "verbosity": -1, "num_leaves": 15}
        ds = lgb.Dataset(_MatSeq(dense), label=y, params=params)
        ds.construct()
        assert ds._inner.bundle_info is not None
        assert ds._inner.bundle_info.n_columns < G * card // 4
        bst = lgb.train(dict(params), ds, 4)
        from sklearn.metrics import roc_auc_score
        assert roc_auc_score(y, bst.predict(dense)) > 0.75
