"""Unified telemetry (ISSUE 10): spans, flight recorder, metrics plane.

The acceptance proofs live here:

* a 5-iteration compact (data-parallel) run under ``tpu_trace_dir`` plus
  a warm + served tick touches EVERY span-taxonomy phase, writes a
  profiler trace, and leaves a ``tpu_metrics_path`` JSONL stream whose
  counters bench.py can ingest;
* with telemetry fully enabled (spans + flight recorder + metrics
  stream) the steady-state guards still record 0 recompiles and 0 host
  transfers;
* injected ``kill@step`` and ``hang@swap`` each leave a parseable flight
  dump whose last events name the failing site.
"""
import json
import os
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.analysis import faultinject, guards
from lightgbm_tpu.obs import flight, metrics, summarize
from lightgbm_tpu.obs import spans


def _make_data(n=600, f=8, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f)
    y = (X[:, 0] + 0.3 * X[:, 1] + 0.2 * rng.randn(n) > 0.6).astype(
        np.float64)
    return X, y


# ------------------------------------------------------------------ spans
def test_span_disabled_is_shared_noop():
    """Zero-cost contract: outside a session, host-side span() returns
    ONE shared no-op object (no allocation, nothing recorded)."""
    assert not spans.annotations_enabled()
    s1, s2 = spans.span("zz_unit_off"), spans.span("zz_unit_off2")
    assert s1 is s2
    with s1:
        pass
    assert "zz_unit_off" not in spans.seen_spans()


def test_span_host_session_times_and_records():
    with spans.trace_session(None, "annotations"):
        assert spans.annotations_enabled()
        with spans.span("zz_unit_host"):
            pass
    assert not spans.annotations_enabled()      # nesting unwound
    assert "zz_unit_host" in spans.seen_spans()
    pt = spans.phase_times()["zz_unit_host"]
    assert pt["count"] >= 1 and pt["seconds"] >= 0.0


def test_span_under_trace_is_named_scope():
    """Inside a jit trace span() becomes a named_scope — recorded as seen
    (the device program carries the name) with NO session active, and the
    function still compiles and runs."""

    @jax.jit
    def f(x):
        with spans.span("zz_unit_traced"):
            return x * 2 + 1

    out = f(jnp.ones(3))
    assert float(out[0]) == 3.0
    assert "zz_unit_traced" in spans.seen_spans()


def test_trace_mode_validation():
    assert spans.resolve_trace_mode(None) == "full"
    assert spans.resolve_trace_mode("annotations") == "annotations"
    assert spans.resolve_trace_mode("FULL") == "full"
    assert spans.resolve_trace_mode("bogus") == "full"   # warn + fallback


def test_phase_times_since_is_a_per_run_delta():
    """Two runs in one process must not double-count each other's span
    seconds: engine snapshots phase_times at run start and reports the
    delta in its summary record."""
    with spans.trace_session(None, "annotations"):
        with spans.span("zz_delta_a"):
            pass
    base = spans.phase_times()
    with spans.trace_session(None, "annotations"):
        with spans.span("zz_delta_b"):
            pass
    delta = spans.phase_times_since(base)
    assert "zz_delta_b" in delta and delta["zz_delta_b"]["count"] == 1
    assert "zz_delta_a" not in delta


def test_trace_session_closes_on_error_paths():
    """The satellite-1 contract: a raise inside the session unwinds the
    enablement (annotations mode here; the profiler flavor of the same
    contract is covered by the slow full-trace test — opening a profiler
    session costs a one-time ~10s process init, too heavy for tier-1)."""
    with pytest.raises(RuntimeError):
        with spans.trace_session(None, "annotations"):
            assert spans.annotations_enabled()
            raise RuntimeError("boom")
    assert not spans.annotations_enabled()


# -------------------------------------------------------- flight recorder
def test_flight_ring_is_bounded_and_dump_parses(tmp_path):
    rec = flight.FlightRecorder(capacity=4)
    for i in range(10):
        rec.record("tick", i=i)
    assert len(rec.events()) == 4
    assert [e["i"] for e in rec.events()] == [6, 7, 8, 9]
    out = rec.dump("unit test", path=str(tmp_path / "f.jsonl"))
    lines = flight.read_dump(out)
    header, events = lines[0], lines[1:]
    assert header["event"] == "flight_dump"
    assert header["reason"] == "unit test"
    assert header["dropped"] == 6
    assert [e["i"] for e in events] == [6, 7, 8, 9]


def test_flight_capacity_zero_disables():
    rec = flight.FlightRecorder(capacity=0)
    rec.record("tick")
    assert rec.events() == []


def test_flight_dump_never_raises(tmp_path):
    rec = flight.FlightRecorder(capacity=2)
    rec.record("tick")
    # unwritable destination: dump reports None instead of raising
    assert rec.dump("x", path="/proc/definitely/not/writable.jsonl") is None


# ---------------------------------------------------------- metrics plane
def test_render_prometheus_flattens_nested_numbers():
    text = metrics.render_prometheus(
        {"ready": True, "queue": {"depth": 3}, "p99": 1.5,
         "name": "ignored-string", "rungs": [256, 1024]})
    assert "# TYPE lgbm_tpu_ready gauge" in text
    assert "lgbm_tpu_ready 1" in text
    assert "lgbm_tpu_queue_depth 3" in text
    assert "lgbm_tpu_p99 1.5" in text
    assert "lgbm_tpu_rungs_count 2" in text
    assert "ignored-string" not in text


def test_metrics_server_serves_text_and_json():
    srv = metrics.MetricsServer(lambda: {"up": 1, "depth": {"rows": 7}},
                                port=0)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        body = urllib.request.urlopen(f"{base}/metrics", timeout=5).read()
        assert b"lgbm_tpu_up 1" in body
        assert b"lgbm_tpu_depth_rows 7" in body
        health = json.loads(urllib.request.urlopen(
            f"{base}/healthz", timeout=5).read())
        assert health == {"up": 1, "depth": {"rows": 7}}
    finally:
        srv.stop()


def test_compile_counter_keys_by_phase():
    sentinel = np.random.RandomState(0).randn()  # fresh program per run

    @jax.jit
    def f(x):
        return x * sentinel

    with guards.compile_counter() as cc:
        with guards.compile_phase("zz_unit_phase"):
            f(jnp.ones(9))
    assert cc.lowerings >= 1
    assert cc.by_phase["zz_unit_phase"]["lowerings"] >= 1
    # outside any scope the phase is "other"
    assert guards.current_compile_phase() == "other"


def test_bench_counters_from_stream(tmp_path):
    """obs/summarize.bench_counters diffs the cumulative snapshots the
    bench marks carry — the BENCH-row ingestion path."""
    p = tmp_path / "s.jsonl"
    s = metrics.MetricsStream(str(p))

    def snap(low, back, phase):
        return {"lowerings": low, "backend_compiles": back,
                "by_phase": {phase: {"lowerings": low,
                                     "backend_compiles": back}}}

    s.emit("mark", name="warmup_start", compiles=snap(2, 1, "train_step"),
           cache={"requests": 0, "hits": 0})
    s.emit("iteration", iteration=1, seconds=0.5,
           compiles=snap(10, 5, "train_step"),
           cache={"requests": 4, "hits": 1})
    s.emit("mark", name="warmup_end", compiles=snap(12, 6, "train_step"),
           cache={"requests": 5, "hits": 2})
    s.emit("mark", name="steady_end", compiles=snap(12, 6, "train_step"),
           cache={"requests": 5, "hits": 2})
    s.close()
    row = summarize.bench_counters(str(p))
    assert row["compile_events"] == 10
    assert row["compile_events_steady"] == 0
    assert row["compile_events_by_phase"] == {
        "train_step": {"lowerings": 10, "backend_compiles": 5}}
    assert row["compile_cache"] == {"requests": 5, "hits": 2, "misses": 3}
    assert row["warmup_seconds"] >= 0.0
    # unmarked stream -> None (bench falls back to inline counters)
    q = tmp_path / "bare.jsonl"
    metrics.MetricsStream(str(q)).close()
    assert summarize.bench_counters(str(q)) is None


def test_summarize_table_renders(tmp_path, capsys):
    p = tmp_path / "s.jsonl"
    s = metrics.MetricsStream(str(p))
    s.emit("iteration", iteration=1, seconds=0.25,
           compiles={"lowerings": 3, "backend_compiles": 1,
                     "by_phase": {"train_step": {"lowerings": 3,
                                                 "backend_compiles": 1}}},
           cache={"requests": 1, "hits": 1})
    s.emit("summary", phase_times={"hist_build": {"seconds": 1.0,
                                                  "count": 5}},
           spans_seen=["hist_build"])
    s.emit("collective_program", key="step", bytes={"all-reduce": 128},
           total=128, count=1)
    s.close()
    assert summarize.main([str(p)]) == 0
    out = capsys.readouterr().out
    assert "hist_build" in out
    assert "collective programs" in out
    assert "compiles: 3 lowerings" in out


# ------------------------------------------- the acceptance criterion (A)
def test_taxonomy_trace_metrics_acceptance(tmp_path, monkeypatch):
    """5-iteration compact data-parallel run with spans enabled
    (annotations mode — the device programs carry the named scopes either
    way; the profiler-artifact flavor is the slow test below): the run +
    a warmed serve tick touch EVERY taxonomy span, the metrics stream
    parses, and bench ingestion finds the per-iteration counters. The
    autotune span comes from an armed (stub-timed — the REAL sweep is
    slow-lane, tests/test_registry.py) startup microbench."""
    from lightgbm_tpu.engines import autotune as eng_autotune
    monkeypatch.setattr(eng_autotune, "_time_candidate",
                        lambda fn, *a, reps=0: 1e-3)
    spans.reset()
    X, y = _make_data(800, 8)
    mpath = tmp_path / "metrics.jsonl"
    ckpt = tmp_path / "ckpt"
    params = {
        "objective": "binary", "num_leaves": 7, "verbosity": -1,
        "tpu_grower": "compact", "tree_learner": "data", "num_shards": 2,
        "tpu_trace_mode": "annotations",
        "tpu_metrics_path": str(mpath),
        "tpu_checkpoint_dir": str(ckpt), "tpu_checkpoint_freq": 2,
        "tpu_flight_buffer": 256,
        "tpu_autotune": "first_run",
        "tpu_autotune_cache": str(tmp_path / "autotune.json"),
    }
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    # serving side of the taxonomy: warm the ladder + one coalesced tick
    # (the default device featurizer traces the `featurize` span) + one
    # pred_contrib call for the `contrib` span
    with spans.trace_session(None, "annotations"):
        server = bst.serve(warm_max_rows=256, tick_ms=1.0)
        try:
            out = server.predict(X[:16])
        finally:
            server.close(drain=True)
        bst.predict(X[:4], pred_contrib=True)
    np.testing.assert_allclose(np.asarray(out),
                               bst.predict(X[:16]), rtol=0, atol=0)

    missing = set(spans.SPAN_TAXONOMY) - spans.seen_spans()
    assert not missing, f"taxonomy spans never entered: {missing}"

    # metrics stream: per-iteration records with cumulative compile
    # counts, a final summary with the phase-time table
    recs = metrics.read_stream(str(mpath))
    iters = [r for r in recs if r["kind"] == "iteration"]
    assert len(iters) == 5
    assert [r["iteration"] for r in iters] == [1, 2, 3, 4, 5]
    assert all(r["seconds"] >= 0 for r in iters)
    lows = [r["compiles"]["lowerings"] for r in iters]
    assert lows == sorted(lows) and lows[0] > 0      # cumulative
    assert "train_step" in iters[-1]["compiles"]["by_phase"]
    summaries = [r for r in recs if r["kind"] == "summary"]
    assert summaries, "engine did not emit the run summary record"
    # per-run spans_seen: host spans always re-enter; traced spans only
    # when the program was traced THIS run (a jit-cache reuse keeps its
    # original names) — binning/checkpoint_write are the robust ones
    assert set(summaries[-1]["spans_seen"]) >= {"binning",
                                                "checkpoint_write"}
    # checkpoint_write is a host span: it appears in the phase-time table
    assert "checkpoint_write" in summaries[-1]["phase_times"]

    # bench-style ingestion over the same stream works once marks exist
    s = metrics.stream_for(str(mpath))
    snap = {"compiles": guards.phase_compile_counts(),
            "cache": guards.global_cache_counts()}
    for name in ("warmup_start", "warmup_end", "steady_end"):
        s.emit("mark", name=name, **snap)
    row = summarize.bench_counters(str(mpath))
    assert row is not None and row["compile_events_steady"] == 0

    # checkpoint ticks dumped the flight ring beside the snapshots
    dumps = [f for f in os.listdir(ckpt) if f.startswith("flight_")]
    assert dumps, "checkpoint tick left no flight dump"
    events = flight.read_dump(str(ckpt / dumps[0]))
    kinds = {e["event"] for e in events}
    assert {"flight_dump", "iteration", "snapshot"} <= kinds


@pytest.mark.slow
def test_full_profiler_trace_artifacts(tmp_path):
    """Full tpu_trace_dir mode: a 5-iteration compact (data-parallel)
    run writes real profiler artifacts, the session closes them on the
    way out, and the DEVICE-time analytics round-trip (ISSUE 11
    acceptance): the parsed artifact yields a per-phase device-time
    table covering every taxonomy span that lowered, emitted alongside
    host seconds in the metrics stream. Slow lane: opening the FIRST
    jax profiler session in a process costs a one-time ~10s init
    regardless of content."""
    from lightgbm_tpu.obs import tracing
    spans.reset()
    X, y = _make_data(400, 6)
    trace_dir = tmp_path / "trace"
    mpath = tmp_path / "metrics.jsonl"
    params = {
        "objective": "binary", "num_leaves": 7, "verbosity": -1,
        "tpu_grower": "compact", "tree_learner": "data",
        "tpu_trace_dir": str(trace_dir),
        "tpu_metrics_path": str(mpath),
    }
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    trace_files = [os.path.join(r, f)
                   for r, _, fs in os.walk(trace_dir) for f in fs]
    assert trace_files, "tpu_trace_dir produced no profiler artifacts"
    assert {"binning", "gradient", "hist_build", "split_scan",
            "partition"} <= spans.seen_spans()
    assert not spans.annotations_enabled()

    # the round-trip: engine parsed the artifact post-session and
    # attached/emitted the device-time analysis
    analysis = bst._device_time_analysis
    assert analysis is not None
    lowered = set(analysis["spans_lowered"])
    assert {"gradient", "hist_build", "split_scan",
            "partition", "collective_reduce"} <= lowered
    # EVERY lowered taxonomy span has a device-time row with real time
    for name in lowered:
        row = analysis["phases"][name]
        assert row["device_seconds"] > 0.0 and row["events"] > 0
    # collective op durations measured (data-parallel: psums lowered)
    assert analysis["collectives"], "no collective durations measured"
    d = analysis["decomposition"]
    assert d["busy_seconds"] > 0.0
    assert d["comm_seconds"] > 0.0
    assert d["busy_seconds"] <= d["total_seconds"] + 1e-9
    # ... and the stream carries device_seconds next to host seconds
    recs = metrics.read_stream(str(mpath))
    dt = [r for r in recs if r["kind"] == "device_time"]
    assert len(dt) == 1
    assert dt[0]["phases"] == analysis["phases"]
    assert "host_phase_times" in dt[0]
    # scripts/obs renders the side-by-side table from the same stream
    assert summarize.summarize([str(mpath)])["device_time"] is not None


# ------------------------------------------- the acceptance criterion (B)
@pytest.fixture(scope="module")
def telemetry_booster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("obs_steady")
    X, y = _make_data(1500, 10, seed=7)
    params = {
        "objective": "binary", "num_leaves": 15, "max_bin": 63,
        "verbosity": -1, "tpu_grower": "compact",
        "stop_check_freq": 10_000,          # no mid-loop host flush
        "tpu_metrics_path": str(tmp / "m.jsonl"),
        "tpu_flight_buffer": 128,
    }
    ds = lgb.Dataset(X, label=y, params=params)
    bst = lgb.Booster(params, ds)
    for _ in range(2):
        bst.update()
    return bst


def test_steady_state_guards_hold_with_telemetry_enabled(telemetry_booster):
    """The whole telemetry layer on (spans via an annotations session,
    flight ring, metrics stream): 3 post-warmup compact iterations still
    lower nothing and materialize nothing on the host."""
    bst = telemetry_booster
    with spans.trace_session(None, "annotations"):
        with guards.steady_state_guard("telemetry-on steady state") as cc:
            for _ in range(3):
                bst.update()
    assert cc.lowerings == 0
    assert cc.backend_compiles == 0
    # and the ticks were actually emitted while guarded
    recs = metrics.read_stream(
        str(bst.config.get("tpu_metrics_path")))
    assert sum(r["kind"] == "iteration" for r in recs) >= 5


# ---------------------------------- flight dumps x fault injection (C)
def test_kill_at_step_leaves_parseable_dump(tmp_path, monkeypatch):
    """An injected kill@step (the simulated SIGKILL) escapes every
    handler — but the engine's crash hook dumps the ring first, and the
    dump's tail names the failing site."""
    dump_path = tmp_path / "postmortem.jsonl"
    monkeypatch.setenv("LGBM_TPU_FLIGHT_PATH", str(dump_path))
    X, y = _make_data(400, 6)
    params = {
        "objective": "binary", "num_leaves": 7, "verbosity": -1,
        "tpu_checkpoint_dir": str(tmp_path / "ck"),
        "tpu_checkpoint_freq": 1,
    }
    with faultinject.inject("kill@step=2"):
        with pytest.raises(faultinject.SimulatedKill):
            lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=6)
    events = flight.read_dump(str(dump_path))
    assert events[0]["event"] == "flight_dump"
    assert events[0]["reason"].startswith("crash")
    assert "SimulatedKill" in events[0]["error"]
    tail = events[-5:]
    fires = [e for e in tail if e["event"] == "fault_fire"]
    assert fires and fires[-1]["site"] == "step" \
        and fires[-1]["kind"] == "kill"
    # the crash marker is the final event on the record
    assert events[-1]["event"] == "crash"


@pytest.fixture(scope="module")
def served_booster():
    """One small trained booster shared by the serving-side telemetry
    tests (training is the expensive part; the tests only serve it)."""
    X, y = _make_data(400, 6)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=3)
    return bst, X


def test_construction_crash_dumps_too(tmp_path, monkeypatch):
    """The crash-dump site wraps ALL of lgb.train, not just the boosting
    loop: a death during dataset construction still ships a post-mortem
    (the r05 failure was attributable to nothing on disk)."""
    dump_path = tmp_path / "construct.jsonl"
    monkeypatch.setenv("LGBM_TPU_FLIGHT_PATH", str(dump_path))
    X, _ = _make_data(50, 4)
    bad_y = np.zeros(7)                     # label length mismatch
    with pytest.raises(Exception):
        lgb.train({"objective": "binary", "verbosity": -1},
                  lgb.Dataset(X, label=bad_y), num_boost_round=2)
    events = flight.read_dump(str(dump_path))
    assert events and events[0]["event"] == "flight_dump"
    assert events[0]["reason"].startswith("crash")


def test_hang_at_swap_leaves_parseable_dump(tmp_path, monkeypatch,
                                            served_booster):
    """hang@swap past the commit deadline: the swap rolls back (old model
    stays active) AND the registry dumps the ring naming the swap site."""
    dump_path = tmp_path / "swap.jsonl"
    monkeypatch.setenv("LGBM_TPU_FLIGHT_PATH", str(dump_path))
    bst, X = served_booster
    server = bst.serve(warm_max_rows=256, tick_ms=1.0)
    try:
        from lightgbm_tpu.serving import SwapFailed
        with faultinject.inject("hang@swap=1:seconds=2"):
            with pytest.raises(SwapFailed):
                # same booster under a new version: the registry treats
                # versions, not objects — cheap and sufficient to drive
                # the commit path into the injected hang
                server.deploy("v2", bst, deadline_s=0.3)
        assert server.registry.active_version() == "v0"
        events = flight.read_dump(str(dump_path))
        assert events[0]["event"] == "flight_dump"
        assert "swap" in events[0]["reason"]
        kinds = [e["event"] for e in events]
        assert "swap_failed" in kinds
        fires = [e for e in events if e["event"] == "fault_fire"]
        assert any(e["site"] == "swap" and e["kind"] == "hang"
                   for e in fires)
    finally:
        server.close(drain=True)


# ----------------------------------------------- serving metrics endpoint
def test_prediction_server_metrics_endpoint(served_booster):
    bst, X = served_booster
    server = bst.serve(warm_max_rows=256, tick_ms=1.0, metrics_port=0)
    try:
        assert server.metrics_port is not None
        server.predict(X[:8])
        base = f"http://127.0.0.1:{server.metrics_port}"
        body = urllib.request.urlopen(
            f"{base}/metrics", timeout=5).read().decode()
        assert "lgbm_tpu_ready 1" in body
        assert "lgbm_tpu_stats_served_requests" in body
        assert "lgbm_tpu_compiles_lowerings" in body
        health = json.loads(urllib.request.urlopen(
            f"{base}/healthz", timeout=5).read())
        assert health["active_version"] == "v0"
        # the text API mirrors the HTTP one (no socket needed)
        assert "lgbm_tpu_ready" in server.metrics_text()
    finally:
        server.close(drain=True)
    # endpoint down after close
    with pytest.raises(Exception):
        urllib.request.urlopen(f"{base}/metrics", timeout=1)


# ------------------------------- per-rank attribution (ISSUE 11, leg 2)
@pytest.fixture(scope="module")
def rank_stats_booster(tmp_path_factory):
    """Same shape as telemetry_booster (programs already jit-cached by
    the earlier test) with the sampled rank-stats timers armed."""
    tmp = tmp_path_factory.mktemp("obs_ranks")
    X, y = _make_data(1500, 10, seed=7)
    params = {
        "objective": "binary", "num_leaves": 15, "max_bin": 63,
        "verbosity": -1, "tpu_grower": "compact",
        "stop_check_freq": 10_000,
        "tpu_metrics_path": str(tmp / "m.jsonl"),
        "tpu_rank_stats_every": 2,
        "tpu_straggler_factor": 3.0,
    }
    ds = lgb.Dataset(X, label=y, params=params)
    bst = lgb.Booster(params, ds)
    for _ in range(2):                    # warm: compiles + first sample
        bst.update()
    return bst


def test_rank_stats_sampled_timers_keep_steady_state_guard(
        rank_stats_booster):
    """The acceptance contract for leg 2: with sampling armed
    (tpu_rank_stats_every=2) the steady-state region still lowers
    nothing and materializes nothing on the host — on-sample ticks take
    only block_until_ready (not a transfer) plus the pre-compiled
    probe, off-sample iterations take neither."""
    bst = rank_stats_booster
    assert bst._gbdt._rank_stats is not None
    with spans.trace_session(None, "annotations"):
        with guards.steady_state_guard("rank-stats steady state") as cc:
            for _ in range(4):            # iters 3..6: samples at 4, 6
                bst.update()
    assert cc.lowerings == 0
    assert cc.backend_compiles == 0
    recs = metrics.read_stream(str(bst.config.get("tpu_metrics_path")))
    rs = [r for r in recs if r["kind"] == "rank_stats"]
    # samples at iterations 2, 4, 6
    assert [r["iteration"] for r in rs] == [2, 4, 6]
    assert all(r["world"] == 1 and r["ranks_reporting"] == 1
               for r in rs)
    assert all(r["max_s"] >= r["median_s"] >= 0 for r in rs)
    samples = [e for e in flight.recorder().events()
               if e["event"] == "rank_sample"]
    assert samples and samples[-1]["iteration"] == 6


def test_rank_stats_mesh_probe_does_not_recompile():
    """The collective-arrival probe compiles at construction (outside
    the steady-state region); sampled probes after that lower nothing."""
    from lightgbm_tpu.obs.ranks import RankStats
    from lightgbm_tpu.parallel.mesh import make_mesh
    rs = RankStats(every=1, mesh=make_mesh(), rank=0, world=1)
    assert rs._probe_fn is not None       # 8 virtual devices: live probe
    rs.collective_wait(1)                 # settle any first-call cache
    with guards.compile_counter() as cc:
        w = rs.collective_wait(2)
    assert w >= 0.0
    assert cc.lowerings == 0


def test_training_metrics_endpoint_scrapeable_while_training(tmp_path):
    """Satellite: tpu_metrics_port under lgb.train — a scrape DURING the
    run sees the live training tree (iteration progress, phase-keyed
    compiles, rank-stats gauges), and the endpoint is gone when the run
    ends."""
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    X, y = _make_data(400, 6)
    params = {
        "objective": "binary", "num_leaves": 7, "verbosity": -1,
        "tpu_metrics_port": port,
        "tpu_rank_stats_every": 1,
    }
    seen = {}

    def scrape(env):
        if env.iteration == 2 and not seen:
            base = f"http://127.0.0.1:{port}"
            seen["text"] = urllib.request.urlopen(
                f"{base}/metrics", timeout=5).read().decode()
            seen["health"] = json.loads(urllib.request.urlopen(
                f"{base}/healthz", timeout=5).read())

    lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=4,
              callbacks=[scrape])
    assert "lgbm_tpu_training 1" in seen["text"]
    assert "lgbm_tpu_iteration" in seen["text"]
    assert "lgbm_tpu_compiles_lowerings" in seen["text"]
    assert "lgbm_tpu_rank_stats_median_s" in seen["text"]
    assert seen["health"]["training"] is True
    assert seen["health"]["rank_stats"]["world"] == 1
    # endpoint is torn down with the run
    with pytest.raises(Exception):
        urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                               timeout=1)


# ------------------------------------------- R012 leak regressions
def test_raising_train_leaves_no_open_trace_session(resource_leak_witness):
    """engine.py holds the trace session with ``with`` — a SimulatedKill
    mid-train unwinds the annotation enablement (the runtime complement
    of tpulint R012's PR-10 exception-edge check)."""
    X, y = _make_data(300, 6)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "tpu_trace_mode": "annotations"}
    assert spans.active_sessions() == 0
    with faultinject.inject("kill@iteration=1"):
        with pytest.raises(faultinject.SimulatedKill):
            lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=4)
    assert spans.active_sessions() == 0
    assert not spans.annotations_enabled()
