"""Compile-once training: the bucketed grower-step ladder, the persistent
compilation cache, and the async histogram-collective overlap (ISSUE 8).

The three acceptance claims, verified mechanically:

* **rung budget** — a full compact training run compiles a fixed, small
  number of DISTINCT step programs (one per (leaf rung, depth bucket)
  pair, never one per node or per exact config), and every config in a
  rung lowers byte-identical HLO (same canonical fingerprint), so the
  persistent cache serves one rung's whole neighborhood;
* **ladder parity** — trees and predictions are bit-identical with
  ``tpu_step_buckets`` on vs the exact-keyed ``off`` escape hatch, on the
  compact AND masked growers, including the bagging/GOSS/extra-trees/
  monotone-rescan paths whose PRNG folds must not see the rung padding;
* **overlap parity** — the data-parallel (psum and reduce-scatter) and
  voting learners produce bit-identical trees with ``tpu_hist_overlap``
  on vs off, and the lowered step program moves EXACTLY the same
  collective bytes (the grouping pipelines latency, it never adds
  traffic — the contract twin lives in
  analysis/contracts/*_overlap.json).
"""
import os

import jax
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.analysis import guards
from lightgbm_tpu.analysis.hlo import collective_bytes, fingerprint
from lightgbm_tpu.boosting.gbdt import bucketed_tree_shape
from lightgbm_tpu.ops.grower import depth_rung, leaf_rung

from utils import binary_data

BASE = {"objective": "binary", "max_bin": 31, "min_data_in_leaf": 5,
        "verbosity": -1, "seed": 7, "num_iterations": 6,
        "device_type": "tpu"}


def _strip_knobs(model_text):
    """Model text minus the parameters echo (the only intended delta
    between the two sides of a parity pair is the knob itself)."""
    return "\n".join(l for l in model_text.splitlines()
                     if not l.startswith("[tpu_"))


def _train(extra, n=800, f=12, seed=0):
    X, y = binary_data(n, f, seed)
    params = dict(BASE)
    params.update(extra)
    bst = lgb.train(params, lgb.Dataset(X, label=y))
    return bst, bst.predict(X)


@pytest.fixture
def cache_config_restored():
    """Leave the process-global jax compilation-cache config the way the
    test found it (configure_compile_cache mutates it)."""
    keys = ("jax_compilation_cache_dir",
            "jax_persistent_cache_min_compile_time_secs",
            "jax_persistent_cache_min_entry_size_bytes")
    prev = {k: getattr(jax.config, k) for k in keys}
    yield
    for k, v in prev.items():
        jax.config.update(k, v)
    try:
        # drop the initialized cache object too, or the restored config
        # is ignored: jax caches its is-cache-used decision per task
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:
        pass


# ------------------------------------------------------------- rung units
def test_leaf_rung_powers_of_two():
    assert [leaf_rung(v) for v in (2, 3, 4, 5, 8, 9, 31, 32, 33)] == \
        [2, 4, 4, 8, 8, 16, 32, 32, 64]


def test_depth_rung_two_buckets():
    """Depth only gates candidate gains (no depth-sized arrays), so the
    ladder's depth axis collapses to {unlimited, bounded} — the O(log)
    end of the compile-budget contract."""
    assert depth_rung(-1) == depth_rung(0) == -1
    assert depth_rung(1) == depth_rung(6) == depth_rung(63) == 1


def test_bucketed_tree_shape_modes():
    assert bucketed_tree_shape(True, 13, 7) == (16, 1)
    assert bucketed_tree_shape(True, 16, -1) == (16, -1)
    # the tpu_step_buckets=off escape hatch keys on the exact shape
    assert bucketed_tree_shape(False, 13, 7) == (13, 7)


# ---------------------------------------------------------- ladder parity
@pytest.mark.parametrize("extra", [
    # non-power-of-two leaves, unlimited depth: 3 padded leaf slots
    dict(tpu_grower="compact", num_leaves=13, max_depth=-1),
    # exact rung + bounded depth: zero padding, traced depth gate live
    dict(tpu_grower="compact", num_leaves=16, max_depth=5),
    # masked grower takes the same (rung, bucket) key
    dict(tpu_grower="masked", num_leaves=9, max_depth=4),
    # bagging + GOSS iteration-derived PRNG must not see the padding
    dict(tpu_grower="compact", num_leaves=12, max_depth=6,
         bagging_fraction=0.7, bagging_freq=1),
    dict(tpu_grower="compact", num_leaves=10, max_depth=-1,
         boosting="goss"),
    # extra_trees threshold draws ride the fixed rescan fold stride —
    # the draw stream must be leaf-array-size independent
    dict(tpu_grower="compact", num_leaves=11, max_depth=7,
         extra_trees=True),
    # RF's own train_one_iter feeds the masked grower the traced budgets
    dict(boosting="rf", num_leaves=11, max_depth=5,
         bagging_fraction=0.6, bagging_freq=1, feature_fraction=0.8),
], ids=["compact", "compact-depth", "masked", "bagging", "goss",
        "extra-trees", "rf"])
def test_step_buckets_bit_parity(extra):
    """Rung-padded programs grow the SAME trees as exact-keyed ones:
    inactive leaves are masked zero-weight segments and the budgets ride
    as traced scalars, so padding is invisible to the split math."""
    bst_on, pred_on = _train(dict(extra, tpu_step_buckets="on"))
    bst_off, pred_off = _train(dict(extra, tpu_step_buckets="off"))
    assert _strip_knobs(bst_on.model_to_string()) \
        == _strip_knobs(bst_off.model_to_string())
    np.testing.assert_array_equal(pred_on, pred_off)


def test_monotone_rescan_parity():
    """monotone intermediate re-scans split candidates with fresh
    extra-trees draws; the fold stride is fixed (not the leaf-array
    length), so the rung-padded rescan draws identical thresholds."""
    extra = dict(tpu_grower="compact", num_leaves=9, max_depth=5,
                 extra_trees=True,
                 monotone_constraints=[1, -1] + [0] * 10,
                 monotone_constraints_method="intermediate")
    bst_on, pred_on = _train(dict(extra, tpu_step_buckets="on"))
    bst_off, pred_off = _train(dict(extra, tpu_step_buckets="off"))
    assert _strip_knobs(bst_on.model_to_string()) \
        == _strip_knobs(bst_off.model_to_string())
    np.testing.assert_array_equal(pred_on, pred_off)


# ---------------------------------------------------------- rung budget
def _step_fingerprints(configs, monkeypatch):
    """Canonical fingerprints of every step program the configs lower."""
    monkeypatch.setenv("LGBM_TPU_COMM_ACCOUNTING", "1")
    prints = set()
    for extra in configs:
        bst, _ = _train(extra)
        g = bst._gbdt
        step_keys = [k for k in g._comm_hlo if "step" in k]
        assert step_keys, sorted(g._comm_hlo)
        for k in step_keys:
            # a full run never re-lowers its step: one text per key
            assert len(g._comm_hlo_history[k]) == 1, k
            prints.add(fingerprint(g._comm_hlo[k]))
    return prints


def test_one_program_per_rung_not_per_config(monkeypatch):
    """The fingerprint-history acceptance assertion: a grid of
    (num_leaves, max_depth) configs lowers ONE distinct step program per
    (leaf rung, depth bucket) pair — the exact-keyed escape hatch lowers
    one per config."""
    grid = [dict(tpu_grower="compact", num_leaves=nl, max_depth=md)
            for nl, md in ((5, 3), (7, 6), (12, 9), (14, 2))]
    # rungs: 8, 8, 16, 16 — depth bucket 'bounded' throughout
    on = _step_fingerprints(
        [dict(c, tpu_step_buckets="on") for c in grid], monkeypatch)
    assert len(on) == 2, len(on)
    off = _step_fingerprints(
        [dict(c, tpu_step_buckets="off") for c in grid], monkeypatch)
    assert len(off) == len(grid), len(off)


def test_depth_bucket_shares_program(monkeypatch):
    """Every bounded max_depth at a rung shares one program (the bound is
    a traced scalar); unlimited compiles the gate away — a second,
    distinct program."""
    grid = [dict(tpu_grower="compact", num_leaves=8, max_depth=md,
                 tpu_step_buckets="on") for md in (2, 5, 9, -1)]
    prints = _step_fingerprints(grid, monkeypatch)
    assert len(prints) == 2, len(prints)


def test_steady_state_no_recompile_with_buckets(compile_guard):
    """The traced budgets never re-key the program: post-warmup
    iterations lower nothing (the PR 1 steady-state guard, now on the
    default bucketed path)."""
    X, y = binary_data(800, 12, 0)
    params = dict(BASE, tpu_grower="compact", num_leaves=13, max_depth=7,
                  tpu_step_buckets="on", num_iterations=2)
    bst = lgb.train(params, lgb.Dataset(X, label=y),
                    keep_training_booster=True)
    before = compile_guard.lowerings
    for _ in range(3):
        bst.update()
    bst._gbdt._flush_trees()
    assert compile_guard.lowerings == before


# ------------------------------------------------------ persistent cache
def test_configure_compile_cache_noop_on_empty(cache_config_restored):
    prev = jax.config.jax_compilation_cache_dir
    assert guards.configure_compile_cache("") is False
    assert guards.configure_compile_cache(None) is False
    assert jax.config.jax_compilation_cache_dir == prev


def test_configure_compile_cache_sets_config(tmp_path,
                                             cache_config_restored):
    cache = str(tmp_path / "cc")
    assert guards.configure_compile_cache(cache) is True
    assert jax.config.jax_compilation_cache_dir == cache
    # admission thresholds zeroed so tiny CPU programs qualify
    assert jax.config.jax_persistent_cache_min_compile_time_secs == 0.0
    assert jax.config.jax_persistent_cache_min_entry_size_bytes == 0
    # idempotent re-arm
    assert guards.configure_compile_cache(cache) is True


def test_same_rung_shares_cache_entries(tmp_path, cache_config_restored):
    """The ladder and the cache compose: a config in an already-trained
    rung re-lowers but backend-compiles NOTHING (every request hits the
    entries its rung neighbor wrote); a new rung misses."""
    cache = str(tmp_path / "cc")
    extra = dict(tpu_grower="compact", tpu_compile_cache_dir=cache,
                 tpu_step_buckets="on")
    _train(dict(extra, num_leaves=12, max_depth=6))
    assert os.listdir(cache), "cache dir stayed empty"
    with guards.cache_counter() as warm:
        _train(dict(extra, num_leaves=9, max_depth=3))   # same (16, 1)
    assert warm.requests > 0
    assert warm.misses == 0, (warm.requests, warm.hits)
    with guards.cache_counter() as cold:
        _train(dict(extra, num_leaves=40, max_depth=5))  # rung 64
    assert cold.misses > 0, (cold.requests, cold.hits)


def test_cache_counter_inactive_without_cache_dir(cache_config_restored):
    """No cache dir configured -> no cache lookups counted (the BENCH
    rows' hit/miss columns stay 0/0 instead of lying)."""
    jax.config.update("jax_compilation_cache_dir", None)
    with guards.cache_counter() as cc:
        jax.jit(lambda x: x * 3)(np.arange(8.0)).block_until_ready()
    assert cc.requests == 0 and cc.hits == 0 and cc.misses == 0


# ------------------------------------------------------- overlap parity
needs_mesh = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs the 8-device virtual mesh")


@needs_mesh
@pytest.mark.parametrize("extra", [
    # reduce-scatter reduction: 16 features / 8 shards = 2 owned columns,
    # the smallest live 2-group split
    dict(tpu_grower="compact", tree_learner="data", tpu_hist_scatter="on"),
    # plain psum reduction groups the full feature axis
    dict(tpu_grower="compact", tree_learner="data", tpu_hist_scatter="off"),
    # the masked grower groups inside ops/histogram.histogram itself
    dict(tpu_grower="masked", tree_learner="data"),
    # voting reduces the 2k elected features in groups
    dict(tree_learner="voting", top_k=3),
], ids=["data-scatter", "data-psum", "masked", "voting"])
def test_hist_overlap_bit_parity(extra):
    """Grouping a histogram reduce never changes which shard-local
    addends reach an element: trees bit-identical with overlap on/off."""
    bst_on, pred_on = _train(dict(extra, tpu_hist_overlap="on"), f=16)
    bst_off, pred_off = _train(dict(extra, tpu_hist_overlap="off"), f=16)
    assert _strip_knobs(bst_on.model_to_string()) \
        == _strip_knobs(bst_off.model_to_string())
    np.testing.assert_array_equal(pred_on, pred_off)


@needs_mesh
def test_hist_overlap_same_collective_bytes(monkeypatch):
    """COMM accounting on the live step program: overlap on moves
    byte-for-byte the collectives of overlap off — more collectives
    (one per group, the pipelining mechanism), identical traffic."""
    monkeypatch.setenv("LGBM_TPU_COMM_ACCOUNTING", "1")
    extra = dict(tpu_grower="compact", tree_learner="data",
                 tpu_hist_scatter="on")
    accts = {}
    for mode in ("on", "off"):
        bst, _ = _train(dict(extra, tpu_hist_overlap=mode), f=16)
        g = bst._gbdt
        key = [k for k in g._comm_hlo if "step" in k][0]
        accts[mode] = collective_bytes(g._comm_hlo[key])
    on, off = accts["on"], accts["off"]
    for kind in set(on) | set(off):
        if kind == "count":
            continue
        assert on.get(kind, 0) == off.get(kind, 0), kind
    assert on["count"] > off["count"]
