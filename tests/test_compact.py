"""Compacted (physically partitioned) grower: unit + parity tests.

Mirrors the reference's tree-learner coverage: the compact grower must make
the same trees as the masked grower (both re-implement
SerialTreeLearner::Train semantics), and the partition primitives must be
stable and exact (reference: src/treelearner/data_partition.hpp).
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops.compact import (RowLayout, go_left_pred, pack_rows,
                                      partition_segment, segment_histogram,
                                      segments_to_leaf_vectors, unpack_rows)
from lightgbm_tpu.ops.grower import GrowerParams, grow_tree
from lightgbm_tpu.ops.grower_compact import grow_tree_compact


def _random_problem(rng, n=600, f=6, b=32, cat_feature=True, nans=True):
    binned = rng.randint(0, b, size=(n, f)).astype(np.uint8)
    num_bins = np.full(f, b, np.int32)
    nan_bin = np.full(f, b - 1, np.int32)
    has_nan = np.zeros(f, bool)
    if nans:
        has_nan[1] = True
    is_cat = np.zeros(f, bool)
    if cat_feature:
        is_cat[2] = True
    # exactly-representable grad/hess (multiples of 1/32) so histogram sums
    # are identical regardless of accumulation order -> bit-identical trees
    grad = rng.randint(-64, 64, size=n).astype(np.float32) / 32.0
    hess = rng.randint(1, 64, size=n).astype(np.float32) / 32.0
    cnt = (rng.rand(n) > 0.25).astype(np.float32)
    grad = grad * cnt
    hess = hess * cnt
    return binned, num_bins, nan_bin, has_nan, is_cat, grad, hess, cnt


def _params(**kw):
    defaults = dict(num_leaves=15, max_depth=-1, num_bins=32,
                    min_data_in_leaf=5.0, min_sum_hessian_in_leaf=1e-3,
                    hist_impl="xla", part_block=128, hist_block=128)
    defaults.update(kw)
    return GrowerParams(**defaults)


class TestPartitionSegment:
    def test_stable_partition_matches_numpy(self, rng):
        n, f = 700, 4
        layout = RowLayout(num_features=f, num_extra=1)
        binned = rng.randint(0, 32, size=(n, f)).astype(np.uint8)
        grad = rng.randn(n).astype(np.float32)
        hess = rng.rand(n).astype(np.float32)
        cnt = np.ones(n, np.float32)
        row_id = np.arange(n, dtype=np.float32)
        bs = 128
        work = pack_rows(jnp.asarray(binned), jnp.asarray(grad),
                         jnp.asarray(hess), jnp.asarray(cnt),
                         jnp.asarray(row_id)[None, :], layout, pad_rows=bs)
        scratch = jnp.zeros_like(work)

        s, m = 100, 460           # partition an interior segment
        feat, thr = 2, 11
        pred = binned[s:s + m, feat] <= thr
        n_left = int(pred.sum())

        work2, _ = jax.jit(
            partition_segment, static_argnames=("block_size",))(
            work, scratch, jnp.int32(s), jnp.int32(m), jnp.int32(n_left),
            jnp.int32(feat), jnp.int32(thr), jnp.asarray(False),
            jnp.int32(31), jnp.asarray(False), jnp.zeros((1,), jnp.uint32),
            block_size=bs)

        got_b, got_g, got_h, got_c, got_e = unpack_rows(work2, n, layout)
        got_ids = np.asarray(got_e[0]).astype(np.int64)
        seg_ids = np.arange(s, s + m)
        exp_left = seg_ids[pred]
        exp_right = seg_ids[~pred]
        # stable: relative order preserved within each side
        np.testing.assert_array_equal(got_ids[s:s + n_left], exp_left)
        np.testing.assert_array_equal(got_ids[s + n_left:s + m], exp_right)
        # outside the segment untouched
        np.testing.assert_array_equal(got_ids[:s], np.arange(s))
        np.testing.assert_array_equal(got_ids[s + m:], np.arange(s + m, n))
        # payload moved with its rows (check grad against permuted original)
        np.testing.assert_array_equal(np.asarray(got_g), grad[got_ids])
        np.testing.assert_array_equal(np.asarray(got_b), binned[got_ids])

    def test_nan_default_left_and_categorical(self, rng):
        n, f = 300, 3
        layout = RowLayout(num_features=f, num_extra=1)
        b = 16
        binned = rng.randint(0, b, size=(n, f)).astype(np.uint8)
        ids = np.arange(n, dtype=np.float32)
        bs = 64
        work = pack_rows(jnp.asarray(binned), jnp.zeros(n, jnp.float32),
                         jnp.ones(n, jnp.float32), jnp.ones(n, jnp.float32),
                         jnp.asarray(ids)[None, :], layout, pad_rows=bs)
        part = jax.jit(partition_segment, static_argnames=("block_size",))

        # numerical with default-left NaN routing
        pred = (binned[:, 0] <= 3) | (binned[:, 0] == b - 1)
        nl = int(pred.sum())
        w2, _ = part(work, jnp.zeros_like(work), jnp.int32(0), jnp.int32(n),
                     jnp.int32(nl), jnp.int32(0), jnp.int32(3),
                     jnp.asarray(True), jnp.int32(b - 1), jnp.asarray(False),
                     jnp.zeros((1,), jnp.uint32), block_size=bs)
        got = np.asarray(unpack_rows(w2, n, layout)[4][0]).astype(int)
        np.testing.assert_array_equal(got[:nl], np.arange(n)[pred])

        # categorical via bitset: left = {3, 7, 12}
        pred = np.isin(binned[:, 1], [3, 7, 12])
        nl = int(pred.sum())
        bits = jnp.asarray([(1 << 3) | (1 << 7) | (1 << 12)], jnp.uint32)
        w2, _ = part(work, jnp.zeros_like(work), jnp.int32(0), jnp.int32(n),
                     jnp.int32(nl), jnp.int32(1), jnp.int32(7),
                     jnp.asarray(False), jnp.int32(b - 1), jnp.asarray(True),
                     bits, block_size=bs)
        got = np.asarray(unpack_rows(w2, n, layout)[4][0]).astype(int)
        np.testing.assert_array_equal(got[:nl], np.arange(n)[pred])


class TestSegmentHistogram:
    def test_matches_dense_histogram(self, rng):
        n, f, b = 500, 4, 16
        layout = RowLayout(num_features=f, num_extra=0)
        binned = rng.randint(0, b, size=(n, f)).astype(np.uint8)
        grad = (rng.randint(-64, 64, size=n) / 32.0).astype(np.float32)
        hess = (rng.randint(1, 64, size=n) / 32.0).astype(np.float32)
        cnt = (rng.rand(n) > 0.3).astype(np.float32)
        work = pack_rows(jnp.asarray(binned), jnp.asarray(grad),
                         jnp.asarray(hess), jnp.asarray(cnt),
                         jnp.zeros((0, n), jnp.float32), layout, pad_rows=128)
        s, m = 37, 401
        hist = jax.jit(segment_histogram,
                       static_argnames=("layout", "num_bins", "block_size",
                                        "impl"))(
            work, jnp.int32(s), jnp.int32(m), layout, b, 128, "xla")
        hist = np.asarray(hist)
        exp = np.zeros((f, b, 4), np.float32)
        for i in range(s, s + m):
            for j in range(f):
                exp[j, binned[i, j]] += [grad[i], hess[i], cnt[i], 1.0]
        np.testing.assert_allclose(hist, exp, rtol=0, atol=0)

    def test_leaf_vectors_exact(self):
        starts = jnp.asarray([0, 10, 4, 17], jnp.int32)
        rows = jnp.asarray([4, 7, 6, 3], jnp.int32)
        vals = jnp.asarray([0.125, -3.5, 7.75, 1e-30], jnp.float32)
        row_leaf, row_val = segments_to_leaf_vectors(starts, rows, vals, 20)
        exp_leaf = np.empty(20, np.int32)
        exp_val = np.empty(20, np.float32)
        for l, (s, r, v) in enumerate(zip([0, 10, 4, 17], [4, 7, 6, 3],
                                          np.asarray(vals))):
            exp_leaf[s:s + r] = l
            exp_val[s:s + r] = v
        np.testing.assert_array_equal(np.asarray(row_leaf), exp_leaf)
        np.testing.assert_array_equal(np.asarray(row_val), exp_val)


class TestCompactGrowerParity:
    @pytest.mark.parametrize("num_leaves,max_depth", [(15, -1), (8, 3)])
    def test_same_tree_as_masked(self, rng, num_leaves, max_depth):
        (binned, num_bins, nan_bin, has_nan, is_cat, grad, hess,
         cnt) = _random_problem(rng)
        n, f = binned.shape
        params = _params(num_leaves=num_leaves, max_depth=max_depth)
        feat_mask = np.ones(f, bool)

        args = (jnp.asarray(binned), jnp.asarray(grad), jnp.asarray(hess),
                jnp.asarray(cnt), jnp.asarray(num_bins), jnp.asarray(nan_bin),
                jnp.asarray(has_nan), jnp.asarray(is_cat),
                jnp.asarray(feat_mask))
        tree_m, row_leaf_m = grow_tree(*args, params)

        layout = RowLayout(num_features=f, num_extra=1)
        pad = max(params.part_block, params.hist_block)
        row_id = jnp.arange(n, dtype=jnp.float32)
        work = pack_rows(jnp.asarray(binned), jnp.asarray(grad),
                         jnp.asarray(hess), jnp.asarray(cnt),
                         row_id[None, :], layout, pad_rows=pad)
        tree_c, row_leaf_c, work2, _, starts_c, rows_c = grow_tree_compact(
            work, jnp.zeros_like(work), jnp.asarray(num_bins),
            jnp.asarray(nan_bin), jnp.asarray(has_nan), jnp.asarray(is_cat),
            jnp.asarray(feat_mask), layout, params, n)

        assert int(tree_c.num_nodes) == int(tree_m.num_nodes)
        nn = int(tree_m.num_nodes)
        for field in ("split_feature", "split_bin", "default_left",
                      "left_child", "right_child"):
            np.testing.assert_array_equal(
                np.asarray(getattr(tree_c, field))[:nn],
                np.asarray(getattr(tree_m, field))[:nn], err_msg=field)
        np.testing.assert_allclose(
            np.asarray(tree_c.leaf_value), np.asarray(tree_m.leaf_value),
            rtol=1e-6, atol=1e-7)

        # row->leaf assignment matches through the permutation
        ids = np.asarray(unpack_rows(work2, n, layout)[4][0]).astype(np.int64)
        assert sorted(ids.tolist()) == list(range(n))  # a real permutation
        got_leaf = np.empty(n, np.int64)
        got_leaf[ids] = np.asarray(row_leaf_c)
        np.testing.assert_array_equal(got_leaf, np.asarray(row_leaf_m))
        # segment expansion reproduces leaf_value[row_leaf] exactly
        from lightgbm_tpu.ops.compact import segments_to_leaf_vectors
        _, row_val_c = segments_to_leaf_vectors(
            starts_c, rows_c, tree_c.leaf_value, n)
        np.testing.assert_array_equal(
            np.asarray(row_val_c),
            np.asarray(tree_c.leaf_value)[np.asarray(row_leaf_c)])

    def test_extras_follow_permutation(self, rng):
        (binned, num_bins, nan_bin, has_nan, is_cat, grad, hess,
         cnt) = _random_problem(rng, n=400)
        n, f = binned.shape
        params = _params(num_leaves=7)
        layout = RowLayout(num_features=f, num_extra=3)
        pad = max(params.part_block, params.hist_block)
        extras = np.stack([np.arange(n, dtype=np.float32),
                           rng.randn(n).astype(np.float32),
                           rng.randn(n).astype(np.float32)])
        work = pack_rows(jnp.asarray(binned), jnp.asarray(grad),
                         jnp.asarray(hess), jnp.asarray(cnt),
                         jnp.asarray(extras), layout, pad_rows=pad)
        _, _, work2, _, _, _ = grow_tree_compact(
            work, jnp.zeros_like(work), jnp.asarray(num_bins),
            jnp.asarray(nan_bin), jnp.asarray(has_nan), jnp.asarray(is_cat),
            jnp.ones(f, dtype=bool), layout, params, n)
        got = np.asarray(unpack_rows(work2, n, layout)[4])
        ids = got[0].astype(np.int64)
        assert sorted(ids.tolist()) == list(range(n))
        # every extra column permuted identically (bit-exact)
        np.testing.assert_array_equal(got[1], extras[1][ids])
        np.testing.assert_array_equal(got[2], extras[2][ids])


class TestCompactTraining:
    """Full Booster training through the compact path vs the masked path
    (mirrors the reference's engine-level determinism checks)."""

    def _train(self, X, y, params, num_round=12, **train_kw):
        import lightgbm_tpu as lgb
        ds = lgb.Dataset(X, label=y)
        bst = lgb.train(params, ds, num_round, **train_kw)
        return bst

    @pytest.mark.parametrize("objective", ["binary", "regression", "regression_l1"])
    def test_matches_masked_training(self, objective):
        import lightgbm_tpu as lgb
        from tests.utils import FAST_PARAMS, binary_data, regression_data
        X, y = binary_data() if objective == "binary" else regression_data()
        base = dict(FAST_PARAMS, objective=objective, tpu_part_block=128,
                    tpu_hist_block=256)
        pm = self._train(X, y, dict(base, tpu_grower="masked"))
        pc = self._train(X, y, dict(base, tpu_grower="compact"))
        # same data, same binning, same split algebra -> near-identical models
        np.testing.assert_allclose(pc.predict(X), pm.predict(X),
                                   rtol=1e-4, atol=1e-5)

    def test_bagging_and_multiclass(self):
        import lightgbm_tpu as lgb
        from tests.utils import FAST_PARAMS, multiclass_data
        X, y = multiclass_data()
        params = dict(FAST_PARAMS, objective="multiclass", num_class=3,
                      bagging_fraction=0.7, bagging_freq=2,
                      tpu_grower="compact", tpu_part_block=128,
                      tpu_hist_block=256)
        bst = self._train(X, y, params)
        pred = bst.predict(X)
        assert pred.shape == (len(y), 3)
        acc = (pred.argmax(1) == y).mean()
        assert acc > 0.8

    def test_goss_and_early_stopping(self):
        import lightgbm_tpu as lgb
        from tests.utils import FAST_PARAMS, binary_data, train_test_split_simple
        X, y = binary_data()
        Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
        ds = lgb.Dataset(Xtr, label=ytr)
        dv = ds.create_valid(Xte, label=yte)
        params = dict(FAST_PARAMS, objective="binary", metric="auc",
                      boosting="goss", learning_rate=0.3,
                      tpu_grower="compact", tpu_part_block=128,
                      tpu_hist_block=256)
        bst = lgb.train(params, ds, 25, valid_sets=[dv],
                        callbacks=[lgb.early_stopping(5, verbose=False)])
        from sklearn.metrics import roc_auc_score
        assert roc_auc_score(yte, bst.predict(Xte)) > 0.85


class TestCompactRanking:
    """Lambdarank on the compact grower: gradients compute on-device in
    ORIGINAL query order (scatter by the carried row-id column) and feed the
    step externally (reference: rank objectives always see query-contiguous
    rows, rank_objective.hpp:25)."""

    def _rank_data(self, n=12000, seed=0):
        rng = np.random.RandomState(seed)
        X = rng.randn(n, 6).astype(np.float32)
        rel = X[:, 0] + 0.5 * X[:, 1] + 0.6 * rng.randn(n)
        y = np.digitize(rel, np.quantile(rel, [0.6, 0.85, 0.96])).astype(
            np.float64)
        group = np.full(n // 120, 120, np.int64)
        return X, y, group

    def test_matches_masked(self):
        import lightgbm_tpu as lgb
        X, y, group = self._rank_data()
        params = {"objective": "lambdarank", "metric": "ndcg",
                  "eval_at": [10], "num_leaves": 31, "verbose": -1,
                  "min_data_in_leaf": 10}
        b_m = lgb.train(dict(params, tpu_grower="masked"),
                        lgb.Dataset(X, label=y, group=group), 6)
        b_c = lgb.train(dict(params, tpu_grower="compact"),
                        lgb.Dataset(X, label=y, group=group), 6)
        assert b_c._gbdt._use_compact and b_c._gbdt._ext_grads
        assert np.abs(b_m.predict(X) - b_c.predict(X)).max() < 1e-4

    def test_eval_train_ndcg_permuted(self):
        import lightgbm_tpu as lgb
        X, y, group = self._rank_data(6000, seed=3)
        bst = lgb.Booster({"objective": "lambdarank", "metric": "ndcg",
                           "eval_at": [5], "num_leaves": 15, "verbose": -1,
                           "tpu_grower": "compact"},
                          lgb.Dataset(X, label=y, group=group))
        for _ in range(3):
            bst.update()
        (_, name, v, _), = bst.eval_train()
        assert name == "ndcg@5" and 0.5 < v <= 1.0
