"""Serving warmup x persistent compile cache (ISSUE 9 satellite).

Lives in its own ``zz``-named file ON PURPOSE: the test uses
``jax.clear_caches()`` as the process-restart stand-in, which drops the
in-memory jit cache for the WHOLE process — any test file collected
after it would silently re-lower (and re-backend-compile through the
persistent cache) every program it touches, inflating suite wall time
toward the tier-1 timeout. Alphabetical collection puts this file last,
so the damage lands after everything else has run.
"""
import numpy as np

import lightgbm_tpu as lgb

from utils import FAST_PARAMS, binary_data


def test_second_boot_rearms_ladder_with_zero_cache_misses(tmp_path):
    """With tpu_compile_cache_dir set, a restarted server re-warms its
    FULL predict ladder from the persistent cache — backend compiles
    consult the cache and miss zero times."""
    import jax
    X, _ = binary_data()
    saved = (jax.config.jax_compilation_cache_dir,
             jax.config.jax_persistent_cache_min_compile_time_secs,
             jax.config.jax_persistent_cache_min_entry_size_bytes)
    try:
        params = dict(FAST_PARAMS, objective="binary",
                      tpu_predict_buckets="32,256",
                      tpu_compile_cache_dir=str(tmp_path / "cc"))
        y = (X[:, 0] > 0).astype(float)
        bst = lgb.train(params, lgb.Dataset(X, label=y), 3)
        # boot 1 must BACKEND-compile the whole ladder (earlier tests may
        # have left shape-compatible programs in the in-memory jit cache,
        # which would skip the backend and write nothing to disk)
        jax.clear_caches()
        boot1 = bst.warm_predict_ladder()
        assert boot1["cache"]["requests"] > 0          # cache consulted
        # "process restart": drop every in-memory jit/backend cache, so
        # the second warmup must re-lower and re-ask the backend
        jax.clear_caches()
        boot2 = bst.warm_predict_ladder()
        assert boot2["lowerings"] > 0                  # really re-lowered
        assert boot2["cache"]["requests"] > 0
        assert boot2["cache"]["misses"] == 0, boot2    # zero backend work
        assert boot2["cache"]["hits"] == boot2["cache"]["requests"]
        # warmed-from-cache programs really serve
        out, n = bst.predict_serving(X[:5])
        np.testing.assert_array_equal(out[:n], bst.predict(X[:5]))
    finally:
        jax.config.update("jax_compilation_cache_dir", saved[0])
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          saved[1])
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          saved[2])
        try:
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception:
            pass
