"""Distributed performance observability (ISSUE 11): fast-lane units.

Covers the three legs without a profiler session (the first jax
profiler session costs a one-time ~10s init — tier-1's budget lives in
the slow lane for that; these tests synthesize the xplane artifact with
a tiny protobuf wire encoder instead):

* obs/tracing.py — xplane parse, HLO scope resolution, per-phase device
  time, collective durations, MXU/comm/idle decomposition;
* obs/ranks.py — sampled publish/aggregate over an injected KV,
  straggler flags, heartbeat-miss reporting;
* obs/ledger.py — per-chip efficiency, measured-vs-model, atomic record;
* scripts/obs — trace table, cross-rank merge ordered by (time, rank).
"""
import json
import os

import pytest

from lightgbm_tpu.obs import flight, ledger, summarize, tracing

# ---------------------------------------------------------------- encoder
# minimal protobuf wire encoder: enough XSpace/HloProto to synthesize a
# device trace (field numbers mirror obs/tracing.py's reader)


def _v(n):
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        out += bytes([b | (0x80 if n else 0)])
        if not n:
            return out


def _vi(fn, val):
    return _v((fn << 3) | 0) + _v(val)


def _ld(fn, payload):
    return _v((fn << 3) | 2) + _v(len(payload)) + payload


def _s(fn, text):
    return _ld(fn, text.encode())


def _hlo_proto(instrs):
    """instrs: [(name, opcode, scoped_op_name)] -> serialized HloProto."""
    comp = b""
    for name, opcode, scoped in instrs:
        meta = _s(2, scoped)                      # OpMetadata.op_name
        comp += _ld(2, _s(1, name) + _s(2, opcode) + _ld(7, meta))
    module = _s(1, "m") + _ld(3, comp)            # HloModuleProto
    return _ld(1, module)                         # HloProto.hlo_module


def _event_meta(mid, name, hlo=None):
    body = _vi(1, mid) + _s(2, name)
    if hlo is not None:
        stat = _vi(1, 1) + _ld(6, hlo)            # XStat.bytes_value
        body += _ld(5, stat)                      # XEventMetadata.stats
    return _ld(4, _vi(1, mid) + _ld(2, body))     # map entry in XPlane


def _line(name, ts_ns, events):
    body = _s(2, name) + _vi(3, ts_ns)
    for mid, off_ps, dur_ps in events:
        body += _ld(4, _vi(1, mid) + _vi(2, off_ps) + _vi(3, dur_ps))
    return _ld(3, body)                           # XPlane.lines


def _plane(name, parts):
    return _ld(1, _s(2, name) + b"".join(parts))  # XSpace.planes


_US = 1_000_000  # 1 microsecond in picoseconds


def _device_space():
    """One device plane: four scoped ops + one unscoped, one collective."""
    instrs = [
        ("fusion.1", "fusion", "jit(step)/jit(main)/hist_build/add"),
        ("dot.2", "dot", "jit(step)/jit(main)/hist_build/dot_general"),
        ("all-reduce.3", "all-reduce",
         "jit(step)/jit(main)/collective_reduce/psum"),
        ("reduce.4", "reduce", "jit(step)/jit(main)/split_scan/reduce"),
        ("copy.5", "copy", "copy.5"),             # no scope: unattributed
    ]
    parts = [_event_meta(i + 1, n, _hlo_proto(instrs) if i == 0 else None)
             for i, (n, _, _) in enumerate(instrs)]
    # timeline (ts base 1000ns): events at 0..50us, durations in us
    parts.append(_line("XLA Ops", 1000, [
        (1, 0 * _US, 10 * _US),       # hist_build fusion: 10us
        (2, 10 * _US, 5 * _US),       # hist_build dot:     5us (MXU)
        (3, 15 * _US, 20 * _US),      # collective_reduce: 20us (comm)
        (4, 35 * _US, 8 * _US),       # split_scan:         8us
        (5, 43 * _US, 2 * _US),       # unattributed:       2us
    ]))
    return _plane("/device:TPU:0", parts)


def test_xplane_parse_and_phase_table(tmp_path):
    run = tmp_path / "plugins" / "profile" / "2026_08_04"
    run.mkdir(parents=True)
    (run / "host.xplane.pb").write_bytes(_device_space())
    out = tracing.analyze_trace_dir(str(tmp_path))
    assert out is not None and out["source"] == "device"
    ph = out["phases"]
    assert ph["hist_build"]["device_seconds"] == pytest.approx(15e-6)
    assert ph["hist_build"]["events"] == 2
    assert ph["collective_reduce"]["device_seconds"] == pytest.approx(
        20e-6)
    assert ph["split_scan"]["device_seconds"] == pytest.approx(8e-6)
    assert out["unattributed_seconds"] == pytest.approx(2e-6)
    # collective durations by op stem
    assert out["collectives"]["all-reduce"]["count"] == 1
    assert out["collectives"]["all-reduce"]["seconds"] == pytest.approx(
        20e-6)
    # decomposition: total spans first start to last end = 45us
    d = out["decomposition"]
    assert d["total_seconds"] == pytest.approx(45e-6)
    assert d["busy_seconds"] == pytest.approx(45e-6)
    assert d["mxu_seconds"] == pytest.approx(5e-6)
    assert d["comm_seconds"] == pytest.approx(20e-6)
    assert d["idle_seconds"] == pytest.approx(0.0)
    assert out["spans_lowered"] == ["collective_reduce", "hist_build",
                                    "split_scan"]


def test_host_fallback_counts_only_resolved_ops():
    """No device plane: host events count ONLY when they resolve through
    the HLO instruction map — python frames are not device time."""
    instrs = [("fusion.9", "fusion",
               "jit(f)/jit(main)/partition/scatter")]
    parts = [
        _event_meta(1, "fusion.9", _hlo_proto(instrs)),
        _event_meta(2, "$builtins isinstance"),
        _line("tf_XLAEigen/1", 0, [(1, 0, 7 * _US), (2, 0, 500 * _US)]),
    ]
    out = tracing.analyze_planes(tracing.parse_xspace(
        _plane("/host:CPU", parts)))
    assert out["source"] == "host-xla"
    assert out["phases"] == {"partition": {"device_seconds": 7e-6,
                                           "events": 1}}
    assert out["decomposition"]["busy_seconds"] == pytest.approx(7e-6)


def test_phase_of_outermost_scope_wins():
    assert tracing.phase_of(
        "jit(s)/split_scan/jit(x)/partition/op") == "split_scan"
    assert tracing.phase_of("no taxonomy here") is None


def test_analyze_trace_dir_tolerates_torn_artifacts(tmp_path):
    assert tracing.analyze_trace_dir(str(tmp_path)) is None
    (tmp_path / "torn.xplane.pb").write_bytes(b"\x0a\xff\xff")  # truncated
    assert tracing.analyze_trace_dir(str(tmp_path)) is None


# ----------------------------------------------------------------- ledger
def test_per_chip_efficiency_vs_one_chip_row():
    rows = ledger.per_chip_efficiency([
        {"n_chips": 1, "iters_per_sec": 2.0},
        {"n_chips": 8, "iters_per_sec": 12.0},
    ])
    assert rows[0]["efficiency"] == 1.0
    assert rows[1]["per_chip"] == 1.5
    assert rows[1]["efficiency"] == 0.75
    # no 1-chip row -> efficiency is honest None, never a guess
    rows = ledger.per_chip_efficiency([{"n_chips": 4,
                                        "iters_per_sec": 6.0}])
    assert rows[0]["efficiency"] is None


def test_measured_vs_model_block():
    analysis = {"decomposition": {"busy_seconds": 2.0,
                                  "comm_seconds": 0.5},
                "collectives": {"all-reduce": {"seconds": 0.5,
                                               "count": 10}},
                "source": "device"}
    contract = {"measured": {"total": 1440}, "mode": "data_scatter",
                "num_devices": 8}
    block = ledger.measured_vs_model(analysis, contract, steps=100)
    assert block["measured"]["comm_fraction"] == 0.25
    assert block["model"]["bytes_per_step"] == 1440
    assert block["model"]["bytes_total"] == 144000
    assert block["implied_gbps"] == pytest.approx(144000 / 0.5 / 1e9)


def test_ledger_record_merges_atomically(tmp_path):
    path = tmp_path / "COMM.json"
    path.write_text(json.dumps({"existing": {"all-reduce": 24588}}))
    block = ledger.ledger_block("higgs", 1, 2.0)
    ledger.record(str(path), "higgs_x1", block)
    block8 = ledger.ledger_block(
        "higgs", 8, 12.0,
        prior_rows=ledger.prior_rows(str(path), "higgs"))
    ledger.record(str(path), "higgs_x8", block8)
    data = json.loads(path.read_text())
    assert data["existing"] == {"all-reduce": 24588}   # preserved
    led = data["scaling_ledger"]
    assert led["higgs_x1"]["scaling"][0]["efficiency"] == 1.0
    assert led["higgs_x8"]["scaling"][-1]["efficiency"] == 0.75
    assert led["higgs_x8"]["n_chips"] == 8


def test_load_contract_known_modes():
    c = ledger.load_contract("data_scatter")
    assert c is not None and ledger.model_bytes_per_step(c) == 1440
    assert ledger.load_contract("no_such_mode") is None


# ------------------------------------------------------- rank attribution
class _FakeKV:
    """Dict-backed stand-in for the coordination-service client."""

    def __init__(self):
        self.store = {}
        self.barriers = []

    def key_value_set(self, k, v):
        if k in self.store:
            raise RuntimeError(f"key exists: {k}")
        self.store[k] = v

    def blocking_key_value_get(self, k, timeout_ms):
        if k not in self.store:
            raise TimeoutError(k)
        return self.store[k]

    def wait_at_barrier(self, name, timeout_ms):
        self.barriers.append(name)


def _pair(kv, every=1, factor=3.0):
    from lightgbm_tpu.obs.ranks import RankStats
    r1 = RankStats(every=every, straggler_factor=factor, kv=kv,
                   rank=1, world=2)
    r0 = RankStats(every=every, straggler_factor=factor, kv=kv,
                   rank=0, world=2)
    # the two instances must agree on the KV namespace (in production
    # the run counter advances in program order on every rank)
    r0._run = r1._run
    return r0, r1


def test_rank_stats_aggregate_and_straggler_flag():
    kv = _FakeKV()
    r0, r1 = _pair(kv)
    flight.recorder().clear()
    for i in (1, 2):                      # healthy baseline window
        r1.sample_step(i, 0.01)
        r0.sample_step(i, 0.01)
    r1.sample_step(3, 2.0)                # rank 1 hangs at step 3
    r0.sample_step(3, 0.01)
    agg = r0.latest_tree()
    assert agg["world"] == 2 and agg["ranks_reporting"] == 2
    assert agg["stragglers"] == [1]
    assert agg["max_rank"] == 1
    assert r0.straggler_events == 1
    events = flight.recorder().events()
    st = [e for e in events if e["event"] == "straggler"]
    assert st and st[-1]["rank"] == 1 and st[-1]["iteration"] == 3
    # the arrival barrier was exercised on both ranks
    assert kv.barriers


def test_rank_stats_global_slowdown_is_not_a_straggler():
    kv = _FakeKV()
    r0, r1 = _pair(kv)
    for i in (1, 2):
        r1.sample_step(i, 0.01)
        r0.sample_step(i, 0.01)
    # BOTH ranks slow down 100x: rolling median protects against the
    # false positive — nobody is a straggler relative to the pod
    r1.sample_step(3, 1.0)
    r0.sample_step(3, 1.0)
    assert r0.latest_tree()["stragglers"] == []


def test_rank_stats_missing_rank_reports_heartbeat():
    kv = _FakeKV()
    r0, _ = _pair(kv)
    flight.recorder().clear()
    r0.sample_step(1, 0.01)               # rank 1 never publishes
    agg = r0.latest_tree()
    assert agg["missing"] == [1]
    assert agg["ranks_reporting"] == 1
    misses = [e for e in flight.recorder().events()
              if e["event"] == "rank_missing"]
    assert misses and misses[-1]["rank"] == 1


def test_rank_stats_sampling_cadence():
    from lightgbm_tpu.obs.ranks import RankStats
    rs = RankStats(every=4, kv=_FakeKV(), rank=0, world=1)
    assert [i for i in range(1, 13) if rs.due(i)] == [4, 8, 12]


# ------------------------------------------------------ cross-rank merge
def test_obs_merge_orders_by_time_then_rank(tmp_path, capsys):
    r0 = flight.FlightRecorder(capacity=16)
    r1 = flight.FlightRecorder(capacity=16)
    r0.record("rank_sample", rank=0, iteration=1)
    r1.record("rank_sample", rank=1, iteration=1)
    r1.record("fault_fire", site="step", kind="hang")
    r0.record("straggler", rank=1, iteration=3)
    p0 = r0.dump("end", path=str(tmp_path / "f_rank0.jsonl"))
    p1 = r1.dump("end", path=str(tmp_path / "f_rank1.jsonl"))
    merged = summarize.merge_ranks([p0, p1])
    # every record source-annotated (from the filename tag here)
    assert {r["src_rank"] for r in merged} == {0, 1}
    ts = [(r.get("t", 0.0), r["src_rank"]) for r in merged]
    assert ts == sorted(ts)
    kinds = [summarize._kind(r) for r in merged]
    assert "straggler" in kinds and "fault_fire" in kinds
    # the annotation must NOT clobber a payload rank: rank 0's dump
    # says rank 1 straggled, and the merged record still says so
    st = next(r for r in merged if summarize._kind(r) == "straggler")
    assert st["src_rank"] == 0 and st["rank"] == 1
    # CLI form (jsonl): one parseable record per line
    assert summarize.merge_main([p0, p1, "--jsonl"]) == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == len(merged)
    assert all(isinstance(json.loads(line), dict) for line in out)


def test_obs_trace_cli_renders_table(tmp_path, capsys):
    run = tmp_path / "plugins" / "profile" / "r1"
    run.mkdir(parents=True)
    (run / "host.xplane.pb").write_bytes(_device_space())
    assert summarize.trace_main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "hist_build" in out and "collective_reduce" in out
    assert "all-reduce" in out
    assert "spans lowered:" in out
    assert summarize.trace_main([str(tmp_path / "nope")]) == 2


def test_summary_table_shows_device_next_to_host(tmp_path, capsys):
    """The side-by-side contract: a stream with a summary (host
    seconds) AND a device_time record renders one table with both
    columns."""
    from lightgbm_tpu.obs import metrics
    p = tmp_path / "s.jsonl"
    s = metrics.MetricsStream(str(p))
    s.emit("summary", phase_times={"hist_build": {"seconds": 1.0,
                                                  "count": 5}})
    s.emit("device_time", source="device",
           phases={"hist_build": {"device_seconds": 0.25, "events": 9},
                   "split_scan": {"device_seconds": 0.1, "events": 3}},
           decomposition={"total_seconds": 0.5, "busy_seconds": 0.4,
                          "mxu_seconds": 0.2, "comm_seconds": 0.05,
                          "idle_seconds": 0.1},
           collectives={"all-reduce": {"seconds": 0.05, "count": 4}})
    s.close()
    summary = summarize.summarize([str(p)])
    assert summary["device_time"]["phases"]["hist_build"][
        "device_seconds"] == 0.25
    assert summarize.main([str(p)]) == 0
    out = capsys.readouterr().out
    assert "host_s" in out and "device_s" in out
    assert "0.2500" in out            # device seconds rendered
    assert "device timeline" in out
    assert "collective all-reduce" in out


def test_flight_dump_carries_rank_field(tmp_path):
    rec = flight.FlightRecorder(capacity=4)
    rec.record("tick")
    out = rec.dump("unit", path=str(tmp_path / "f.jsonl"))
    header = flight.read_dump(out)[0]
    assert "rank" in header           # None single-process, int on pods
    assert header["rank"] is None
