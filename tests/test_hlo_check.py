"""HLO contract gate: the four learner-mode step programs verify against
their checked-in contracts (analysis/contracts/*.json), deliberately
broken contracts produce failing actionable findings, and the regenerated
measurement matches the checked-in files (no silent comm-shape drift).

This IS the tier-1 wiring of the hlo_check tentpole: it runs on the CPU
backend (lowered-HLO text, no TPU required) against the same 8-device
virtual mesh the distributed tests use.
"""
import jax
import jax.numpy as jnp
import pytest

from lightgbm_tpu.analysis import hlo, hlo_check

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual CPU mesh")


@pytest.fixture(scope="module")
def captured():
    """Lower every mode's steady-state step program once for the module."""
    return {mode: hlo_check.capture_mode(mode) for mode in hlo_check.MODES}


# ------------------------------------------------------------------ gate
def test_all_contracts_verify_clean(captured):
    for mode in hlo_check.MODES:
        contract = hlo_check.load_contract(mode)
        findings = hlo_check.verify_mode(mode, contract, captured[mode])
        assert not findings, "\n".join(f.render() for f in findings)


def test_no_contract_drift(captured):
    """Regenerating from the live lowering must match the checked-in
    files byte for byte — comm-shape changes are a reviewed --update,
    never an accident. Host-dependent XLA memory-estimate fields are
    normalized out of the fingerprint (drift_fingerprint); the budget
    itself and argument/output bytes stay exact."""
    for mode in hlo_check.MODES:
        fresh = hlo_check.build_contract(mode, captured[mode])
        assert hlo_check.drift_fingerprint(fresh) == \
            hlo_check.drift_fingerprint(hlo_check.load_contract(mode)), (
            f"contract drift in '{mode}': rerun "
            "scripts/verify_contracts.py --update and review the diff")


def test_drift_fingerprint_ignores_estimate_only():
    """Estimate/headroom changes are invisible to the fingerprint;
    budget or argument-byte changes are not."""
    base = {"mode": "m", "memory": {"1": {
        "argument_bytes": 10, "budget_bytes": 100,
        "estimate_bytes": 80, "headroom_bytes": 20, "output_bytes": 4}}}
    est = {"mode": "m", "memory": {"1": {
        "argument_bytes": 10, "budget_bytes": 100,
        "estimate_bytes": 60, "headroom_bytes": 40, "output_bytes": 4}}}
    bud = {"mode": "m", "memory": {"1": {
        "argument_bytes": 10, "budget_bytes": 90,
        "estimate_bytes": 80, "headroom_bytes": 10, "output_bytes": 4}}}
    fp = hlo_check.drift_fingerprint
    assert fp(base) == fp(est)
    assert fp(base) != fp(bud)


def test_fingerprints_stable_across_iterations(captured):
    """The steady-state step lowered exactly once over 4 boosting
    iterations (recompile detection at the HLO level)."""
    for mode, cap in captured.items():
        assert len(cap.history) == 1, (
            f"{mode}: step re-lowered {len(cap.history)}x; fingerprints "
            f"{[hlo.fingerprint(t) for t in cap.history]}")


def test_data_scatter_program_contains_reduce_scatter(captured):
    acct = hlo.collective_bytes(captured["data_scatter"].hlo_text)
    assert acct["reduce-scatter"] > 0
    # the best-split sync is tiny next to the histogram exchange
    assert acct["all-reduce"] < acct["reduce-scatter"]


def test_overlap_contracts_same_bytes_more_collectives(captured):
    """The ISSUE 8 overlap acceptance criterion, contract-level: with
    tpu_hist_overlap on, every collective kind moves EXACTLY the bytes
    the overlap=off baseline moves (overlap hides latency, never adds
    traffic) while the collective count grows (one reduce per feature
    group is the pipelining mechanism)."""
    for mode in ("data_scatter_overlap", "voting_overlap"):
        contract = hlo_check.load_contract(mode)
        cur, base = contract["measured"], contract["measured_baseline"]
        for kind in set(cur) | set(base):
            if kind == "count":
                continue
            assert cur.get(kind, 0) == base.get(kind, 0), (mode, kind)
        assert cur["count"] > base["count"], mode
        # and the LIVE lowering still matches the checked-in accounting
        acct = hlo.collective_bytes(captured[mode].hlo_text)
        assert {k: v for k, v in sorted(acct.items())} == cur, mode


def test_overlap_allows_async_start_twins():
    """The overlap contracts admit each collective's -start half at the
    same byte budget: an async backend lowering the group reduces into
    -start/-done pairs stays in contract; a start op moving MORE than
    its done twin's budget does not."""
    contract = hlo_check.load_contract("data_scatter_overlap")
    allow = contract["collectives"]["allow"]
    budgets = contract["collectives"]["max_bytes"]
    assert "reduce-scatter-start" in allow
    assert budgets["reduce-scatter-start"] == budgets["reduce-scatter"]


# -------------------------------------------------- broken contracts fail
def test_overlap_byte_drift_fails():
    """Tampered overlap accounting — a kind moving different bytes than
    the baseline — produces an overlap-bytes finding."""
    contract = hlo_check.load_contract("data_scatter_overlap")
    contract = dict(contract, measured=dict(
        contract["measured"],
        **{"reduce-scatter": contract["measured"]["reduce-scatter"] * 2}))
    findings = hlo_check.check_overlap_parity(contract)
    assert any(f.check == "overlap-bytes"
               and "reduce-scatter" in f.message for f in findings), \
        [f.render() for f in findings]
    # the untampered contract is clean
    clean = hlo_check.check_overlap_parity(
        hlo_check.load_contract("data_scatter_overlap"))
    assert not clean, [f.render() for f in clean]


def test_forcing_allreduce_with_scatter_contract_fails():
    """The acceptance case: lower the data-parallel step with the
    reduce-scatter reduction disabled and check it against the
    data_scatter contract — must fail with actionable findings."""
    t = dict(hlo_check.MODE_TEMPLATES["data_scatter"])
    t["params"] = dict(t["params"], tpu_hist_scatter="off")
    cap = hlo_check.capture_mode("data_scatter", template=t)
    contract = hlo_check.load_contract("data_scatter")
    findings = hlo_check.check_hlo(cap.hlo_text, contract)
    msgs = "\n".join(f.render() for f in findings)
    assert any(f.check == "collectives" and "reduce-scatter" in f.message
               and "missing" in f.message for f in findings), msgs
    assert any(f.check == "collectives" and "budget" in f.message
               for f in findings), msgs


def test_dropped_preferred_element_type_fails():
    """An int8 histogram contraction without preferred_element_type=int32
    keeps a narrow accumulator in the compiled text — the int-dot check
    must produce a failing finding; the correct form stays clean."""
    a = jnp.ones((8, 16), jnp.int8)
    b = jnp.ones((16, 8), jnp.int8)

    def bad(x, y):
        return jnp.einsum("ij,jk->ik", x, y)

    def good(x, y):
        return jnp.einsum("ij,jk->ik", x, y,
                          preferred_element_type=jnp.int32)

    contract = hlo_check.load_contract("quant_int8")
    bad_txt = jax.jit(bad).lower(a, b).compile().as_text()
    findings = hlo_check.check_int_dots(bad_txt, contract)
    assert findings and "preferred_element_type" in findings[0].message
    good_txt = jax.jit(good).lower(a, b).compile().as_text()
    assert not [f for f in hlo_check.check_int_dots(good_txt, contract)
                if "wraps" in f.message]


def test_quant_contract_requires_live_integer_dot():
    """A quant program that silently fell back to f32 histograms fails
    require_integer_dot."""
    contract = hlo_check.load_contract("quant_int8")
    f32_txt = jax.jit(
        lambda x, y: jnp.einsum("ij,jk->ik", x, y)).lower(
            jnp.ones((8, 16), jnp.float32),
            jnp.ones((16, 8), jnp.float32)).compile().as_text()
    findings = hlo_check.check_int_dots(f32_txt, contract)
    assert any("not live" in f.message for f in findings)


def test_host_op_in_step_fails():
    """infeed/outfeed/callback custom-calls violate the 0-d2h contract."""
    contract = {"mode": "synthetic", "forbid_host_ops": True}
    hlo_text = """
ENTRY %main {
  %p = f32[8]{0} parameter(0)
  %o = token[] outfeed(f32[8]{0} %p, token[] %tok)
  ROOT %cc = f32[8]{0} custom-call(f32[8]{0} %p), custom_call_target="xla_ffi_python_cpu_callback"
}
"""
    findings = hlo_check.check_host_ops(hlo_text, contract)
    assert len(findings) == 2, findings
    assert any("outfeed" in f.message for f in findings)
    assert any("callback" in f.message for f in findings)


def test_fingerprint_check_flags_relowering():
    contract = {"mode": "synthetic", "stable_fingerprint": True}
    t1 = "ENTRY %main { ROOT %a = f32[8]{0} parameter(0) }"
    t2 = "ENTRY %main { ROOT %a = f32[16]{0} parameter(0) }"
    assert not hlo_check.check_fingerprint([t1], contract)
    findings = hlo_check.check_fingerprint([t1, t2], contract)
    assert findings and "CHANGED" in findings[0].message
    same = hlo_check.check_fingerprint([t1, t1], contract)
    assert same and "re-lowered" in same[0].message


# ------------------------------------------------------------ parser unit
def test_parser_reads_async_tuple_result_shapes():
    txt = """
ENTRY %e {
  %ag = (f32[8,64]{1,0}, f32[64,64]{1,0}) all-gather-start(f32[8,64]{1,0} %p), dimensions={0}
  %rs = (f32[64,64]{1,0}, f32[8,64]{1,0}) reduce-scatter-start(f32[64,64]{1,0} %x), dimensions={0}
}
"""
    acct = hlo.collective_bytes(txt)
    assert acct["all-gather-start"] == 64 * 64 * 4      # result, not operand
    assert acct["reduce-scatter-start"] == 8 * 64 * 4   # result, not operand


def test_canonicalize_strips_naming_noise():
    a = "%dot.3 = s32[8]{0} dot(s32[8]{0} %x.1), metadata={op_name=\"m\"}"
    b = "%dot.9 = s32[8]{0} dot(s32[8]{0} %x.2)"
    assert hlo.fingerprint(a) == hlo.fingerprint(b)
    c = "%dot.9 = s8[8]{0} dot(s8[8]{0} %x.2)"
    assert hlo.fingerprint(a) != hlo.fingerprint(c)


# -------------------------------------- per-registry-entry enumeration
def test_registry_entries_all_covered():
    """Every engine-registry entry (engines/registry.py) is pinned: a
    checked-in contract whose filename carries the entry id, or a
    justified TPU-only exemption — the shipped tree enumerates clean."""
    findings = hlo_check.registry_contract_findings()
    assert not findings, "\n".join(f.render() for f in findings)


def test_registry_entry_without_contract_fails():
    """A new engine cannot land unpinned: an entry with neither a
    contract nor a contract_exempt justification is a finding, and a
    CPU-lowerable entry cannot hide behind an exemption."""
    from lightgbm_tpu.engines.registry import EngineEntry
    bare = EngineEntry("new_engine", "xla", "lane", False, "unpinned")
    findings = hlo_check.registry_contract_findings([bare])
    assert len(findings) == 1 and "neither" in findings[0].message
    cheat = bare._replace(contract_exempt="trust me", requires_tpu=False)
    findings = hlo_check.registry_contract_findings([cheat])
    assert len(findings) == 1 and "TPU-only" in findings[0].message
    # a TPU-only Mosaic engine MAY be exempt (the CPU harness cannot
    # lower it) — that is the shipped fused/pallas entries' shape
    exempt = bare._replace(contract_exempt="Mosaic; pinned by parity",
                           requires_tpu=True)
    assert not hlo_check.registry_contract_findings([exempt])


def test_registry_entry_id_must_be_in_filename():
    """Per-entry enumeration needs the entry id visible in
    analysis/contracts/ — naming an unrelated (existing) contract does
    not count as coverage."""
    from lightgbm_tpu.engines.registry import EngineEntry
    sneaky = EngineEntry("new_engine", "xla", "lane", False, "mislabeled",
                         contracts=("serial_compact",))
    findings = hlo_check.registry_contract_findings([sneaky])
    assert len(findings) == 1 and "entry id" in findings[0].message


def test_registry_entry_missing_mesh_block_fails():
    """Per-entry mesh enumeration (ISSUE 15): an entry declaring a mesh
    key its contract has no verified memory block for is a finding —
    the flight-check coverage cannot silently lag the declaration."""
    from lightgbm_tpu.engines.registry import EngineEntry
    wide = EngineEntry("xla_lane", "xla", "lane", False, "declares 4x2",
                       contracts=("xla_lane",), meshes=("1", "4x2"))
    findings = hlo_check.registry_contract_findings([wide])
    assert len(findings) == 1
    assert "no memory block for declared mesh '4x2'" in findings[0].message
    # the shipped declaration ("1") is covered by the native block
    ok = wide._replace(meshes=("1",))
    assert not hlo_check.registry_contract_findings([ok])


def test_xla_lane_entry_contract_is_fully_concretized(captured):
    """The xla_lane entry contract pins the registry-resolved program
    with every engine knob explicit and autotune off; it lowers with no
    collectives and no host ops like the serial baseline."""
    contract = hlo_check.load_contract("xla_lane")
    assert contract["params"]["tpu_hist_impl"] == "xla"
    assert contract["params"]["tpu_autotune"] == "off"
    findings = hlo_check.verify_mode("xla_lane", contract,
                                     captured["xla_lane"])
    assert not findings, "\n".join(f.render() for f in findings)
