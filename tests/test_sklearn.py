"""sklearn-wrapper tests (reference: tests/python_package_test/test_sklearn.py)."""
import numpy as np
from sklearn.metrics import accuracy_score, r2_score, roc_auc_score

import lightgbm_tpu as lgb

from utils import (FAST_PARAMS, binary_data, make_ranking, multiclass_data,
                   regression_data, train_test_split_simple)


def test_regressor():
    X, y = regression_data()
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    model = lgb.LGBMRegressor(n_estimators=50, **FAST_PARAMS)
    model.fit(Xtr, ytr)
    assert r2_score(yte, model.predict(Xte)) > 0.7
    assert model.n_features_ == X.shape[1]
    assert model.feature_importances_.sum() > 0


def test_classifier_binary():
    X, y = binary_data()
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    model = lgb.LGBMClassifier(n_estimators=40, **FAST_PARAMS)
    model.fit(Xtr, ytr)
    proba = model.predict_proba(Xte)
    assert proba.shape == (len(yte), 2)
    assert roc_auc_score(yte, proba[:, 1]) > 0.93
    pred = model.predict(Xte)
    assert accuracy_score(yte, pred) > 0.85
    assert set(model.classes_) == {0.0, 1.0}


def test_classifier_multiclass():
    X, y = multiclass_data()
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    model = lgb.LGBMClassifier(n_estimators=25, **FAST_PARAMS)
    model.fit(Xtr, ytr)
    proba = model.predict_proba(Xte)
    assert proba.shape == (len(yte), 3)
    assert accuracy_score(yte, model.predict(Xte)) > 0.85


def test_classifier_string_labels():
    X, y = binary_data()
    ystr = np.where(y > 0, "pos", "neg")
    model = lgb.LGBMClassifier(n_estimators=10, **FAST_PARAMS)
    model.fit(X, ystr)
    pred = model.predict(X)
    assert set(np.unique(pred)) <= {"pos", "neg"}


def test_ranker():
    X, y, group = make_ranking()
    model = lgb.LGBMRanker(n_estimators=20, min_child_samples=2, **FAST_PARAMS)
    model.fit(X, y, group=group)
    scores = model.predict(X)
    assert scores.shape == (len(y),)
    # higher-relevance docs should get higher scores on average
    assert scores[y == 2].mean() > scores[y == 0].mean()


def test_eval_set_and_early_stopping():
    X, y = binary_data()
    Xtr, ytr, Xte, yte = train_test_split_simple(X, y)
    model = lgb.LGBMClassifier(n_estimators=100, **FAST_PARAMS)
    model.fit(Xtr, ytr, eval_set=[(Xte, yte)],
              callbacks=[lgb.early_stopping(5, verbose=False)])
    assert model.best_iteration_ > 0
    assert "valid_0" in model.evals_result_


def test_get_set_params():
    model = lgb.LGBMRegressor(n_estimators=5, num_leaves=7)
    params = model.get_params()
    assert params["n_estimators"] == 5
    assert params["num_leaves"] == 7
    model.set_params(num_leaves=15)
    assert model.get_params()["num_leaves"] == 15


def test_class_weight_balanced():
    X, y = binary_data()
    # unbalance the data
    keep = np.where((y == 0) | (np.random.RandomState(0).rand(len(y)) < 0.2))[0]
    Xu, yu = X[keep], y[keep]
    model = lgb.LGBMClassifier(n_estimators=20, class_weight="balanced",
                               **FAST_PARAMS)
    model.fit(Xu, yu)
    assert roc_auc_score(yu, model.predict_proba(Xu)[:, 1]) > 0.9
