"""Preemption-safe training: atomic checkpoints, crash/resume parity,
fault injection, collective deadlines.

The contract under test (io/checkpoint.py, analysis/faultinject.py,
parallel/multihost.py, engine.py):

* snapshots land atomically (write-temp-fsync-rename + SHA-256) and a
  corrupted/truncated file is skipped back to the previous valid one;
* a run killed at an arbitrary iteration (via the fault injector — a
  ``kill -9`` stand-in that escapes every ``except Exception``) resumes
  from ``tpu_checkpoint_dir`` to a BIT-IDENTICAL model vs. the
  uninterrupted run — trees and predictions — including with bagging,
  GOSS, DART, the compact/quantized grower, and early stopping;
* checkpointing does not break the steady-state contract: 0 recompiles,
  and device->host transfers happen ONLY at ``tpu_checkpoint_freq``
  ticks;
* a hung collective/step surfaces as a structured
  ``TrainingInterrupted`` with a final snapshot written, not a silent
  hang.
"""
import importlib.util
import os
import pickle
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.analysis import faultinject, guards
from lightgbm_tpu.io import checkpoint as ckpt
from lightgbm_tpu.parallel.multihost import (TrainingInterrupted,
                                             run_with_deadline)

from utils import FAST_PARAMS, binary_data, train_test_split_simple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _trees(bst) -> str:
    """Model text minus the parameter dump (the checkpoint knobs appear
    there by design; tree bit-identity is what resume guarantees)."""
    return bst.model_to_string().split("\nparameters:")[0]


def _params(**kw):
    p = dict(FAST_PARAMS)
    p.update(objective="binary", learning_rate=0.1, seed=7, verbosity=-1)
    p.update(kw)
    return p


def _dataset():
    X, y = binary_data()
    return X, lgb.Dataset(X, label=y)


# ================================================= io/checkpoint.py units
class TestSnapshotFiles:
    def test_write_read_roundtrip(self, tmp_path):
        state = {"iteration": 5, "models": ["t0", "t1"],
                 "arr": np.arange(7.0)}
        path = ckpt.write_snapshot(str(tmp_path), 5, state)
        assert os.path.basename(path) == "snapshot_iter_000000005.ckpt"
        back = ckpt.read_snapshot(path)
        assert back["iteration"] == 5
        assert back["models"] == ["t0", "t1"]
        np.testing.assert_array_equal(back["arr"], state["arr"])

    def test_no_temp_files_left_behind(self, tmp_path):
        ckpt.write_snapshot(str(tmp_path), 1, {"iteration": 1})
        ckpt.write_snapshot(str(tmp_path), 2, {"iteration": 2})
        leftovers = [n for n in os.listdir(tmp_path)
                     if n.startswith(".snapshot_tmp_")]
        assert not leftovers

    def test_truncated_snapshot_detected(self, tmp_path):
        path = ckpt.write_snapshot(str(tmp_path), 1,
                                   {"iteration": 1, "x": list(range(100))})
        faultinject.corrupt_file(path, "truncate")
        with pytest.raises(ckpt.SnapshotCorrupt, match="torn write"):
            ckpt.read_snapshot(path)

    def test_bitflipped_snapshot_detected(self, tmp_path):
        path = ckpt.write_snapshot(str(tmp_path), 1,
                                   {"iteration": 1, "x": list(range(100))})
        faultinject.corrupt_file(path, "flip")
        with pytest.raises(ckpt.SnapshotCorrupt, match="checksum"):
            ckpt.read_snapshot(path)

    def test_bad_magic_detected(self, tmp_path):
        path = tmp_path / "snapshot_iter_000000001.ckpt"
        path.write_bytes(b"NOTACKPT" + b"\x00" * 64)
        with pytest.raises(ckpt.SnapshotCorrupt, match="magic"):
            ckpt.read_snapshot(str(path))

    def test_load_latest_skips_corrupt_to_previous_valid(self, tmp_path):
        ckpt.write_snapshot(str(tmp_path), 4, {"iteration": 4, "tag": "ok"})
        newest = ckpt.write_snapshot(str(tmp_path), 8,
                                     {"iteration": 8, "tag": "newest"})
        faultinject.corrupt_file(newest, "flip")
        state = ckpt.load_latest(str(tmp_path))
        assert state is not None and state["tag"] == "ok"
        assert state["iteration"] == 4

    def test_load_latest_empty(self, tmp_path):
        assert ckpt.load_latest(str(tmp_path)) is None
        assert ckpt.load_latest(str(tmp_path / "missing")) is None

    def test_keep_last_k_rotation(self, tmp_path):
        for i in range(1, 7):
            ckpt.write_snapshot(str(tmp_path), i, {"iteration": i}, keep=3)
        iters = [it for it, _ in ckpt.list_snapshots(str(tmp_path))]
        assert iters == [4, 5, 6]

    def test_keep_nonpositive_keeps_everything(self, tmp_path):
        for i in range(1, 5):
            ckpt.write_snapshot(str(tmp_path), i, {"iteration": i}, keep=0)
        assert len(ckpt.list_snapshots(str(tmp_path))) == 4

    def test_undecodable_payload_detected(self, tmp_path):
        # valid header + checksum over garbage that is not a pickle
        import hashlib
        payload = b"\x00garbage, not a pickle"
        blob = (ckpt.MAGIC + len(payload).to_bytes(8, "big")
                + hashlib.sha256(payload).digest() + payload)
        path = tmp_path / "snapshot_iter_000000003.ckpt"
        path.write_bytes(blob)
        with pytest.raises(ckpt.SnapshotCorrupt, match="undecodable"):
            ckpt.read_snapshot(str(path))


# ============================================== faultinject.py spec units
class TestFaultSpec:
    def test_parse_clauses(self):
        faults = faultinject.parse_spec(
            "kill@iteration=3; hang@step=2:seconds=9.5;"
            "transient@backend_init=1:count=2;"
            "corrupt@snapshot=2:mode=flip")
        kinds = [(f.kind, f.site, f.at) for f in faults]
        assert kinds == [("kill", "iteration", 3), ("hang", "step", 2),
                         ("transient", "backend_init", 1),
                         ("corrupt", "snapshot", 2)]
        assert faults[1].seconds == 9.5
        assert faults[2].count == 2
        assert faults[3].mode == "flip"

    @pytest.mark.parametrize("bad", [
        "kill",                       # no @site
        "vaporize@iteration=1",       # unknown kind
        "kill@nowhere=1",             # unknown site
        "kill@iteration=x",           # non-integer position
        "corrupt@snapshot=1:mode=zap",  # bad corrupt mode
        "kill@iteration=1:wat=1",     # unknown option
    ])
    def test_parse_errors(self, bad):
        with pytest.raises(faultinject.FaultSpecError):
            faultinject.parse_spec(bad)

    def test_fault_fires_then_disarms(self):
        with faultinject.inject("transient@backend_init=*:count=2") as plan:
            for _ in range(2):
                with pytest.raises(RuntimeError,
                                   match="Unable to initialize backend"):
                    plan.fire("backend_init")
            plan.fire("backend_init")       # spent: no-op
            assert plan.faults[0].fired == 2

    def test_inject_restores_previous_plan(self):
        assert isinstance(faultinject.active_plan(), faultinject.NullPlan)
        with faultinject.inject("kill@iteration=1"):
            assert faultinject.active_plan() is not None
            assert not isinstance(faultinject.active_plan(),
                                  faultinject.NullPlan)
        assert isinstance(faultinject.active_plan(), faultinject.NullPlan)

    def test_at_with_count_fires_consecutive_positions(self):
        """The documented 'transient@backend_init=1:count=2' fails the
        first TWO attempts: ``at`` is where firing starts, not a single
        exact match."""
        with faultinject.inject("transient@backend_init=1:count=2") as plan:
            for _ in range(2):
                with pytest.raises(RuntimeError):
                    plan.fire("backend_init")
            plan.fire("backend_init")       # third attempt: recovered
            assert plan.faults[0].fired == 2

    def test_config_spec_reaches_configless_sites(self, tmp_path):
        """tpu_fault_spec armed via params must drive the sites that hold
        no config (snapshot writes): corrupt@snapshot fires from a pure
        config spec."""
        X, y = binary_data()
        params = _params(tpu_fault_spec="corrupt@snapshot=1",
                         tpu_checkpoint_dir=str(tmp_path),
                         tpu_checkpoint_freq=2)
        try:
            lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=4)
        finally:
            # disarm the sticky config plan for later tests
            faultinject.active_plan({"tpu_fault_spec": ""})
        snaps = ckpt.list_snapshots(str(tmp_path))
        assert len(snaps) == 2
        with pytest.raises(ckpt.SnapshotCorrupt):
            ckpt.read_snapshot(snaps[0][1])      # corrupted by the spec
        ckpt.read_snapshot(snaps[1][1])          # count spent: valid

    def test_kill_escapes_except_exception(self):
        """SimulatedKill models kill -9: no `except Exception` cleanup
        handler may swallow it (no mid-death snapshot)."""
        with faultinject.inject("kill@iteration=0"):
            with pytest.raises(faultinject.SimulatedKill):
                try:
                    faultinject.active_plan().fire("iteration", iteration=0)
                except Exception:       # noqa: BLE001 - the point
                    pytest.fail("SimulatedKill caught by except Exception")


# ===================================================== kill/resume parity
def _train(params, rounds, valid=False, callbacks=None):
    X, y = binary_data()
    if valid:
        Xt, yt, Xv, yv = train_test_split_simple(X, y)
        ds = lgb.Dataset(Xt, label=yt)
        vsets = [lgb.Dataset(Xv, label=yv, reference=ds)]
        bst = lgb.train(params, ds, num_boost_round=rounds,
                        valid_sets=vsets, callbacks=list(callbacks or ()))
        return bst, Xt
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(params, ds, num_boost_round=rounds,
                    callbacks=list(callbacks or ()))
    return bst, X


def _kill_and_resume(params, rounds, kill_at, tmp_path, freq=3,
                     valid=False, callbacks=None):
    """Train with checkpointing, die at ``kill_at``, resume; return the
    resumed booster."""
    p = dict(params, tpu_checkpoint_dir=str(tmp_path),
             tpu_checkpoint_freq=freq)
    with faultinject.inject(f"kill@iteration={kill_at}"):
        with pytest.raises(faultinject.SimulatedKill):
            _train(p, rounds, valid=valid, callbacks=callbacks)
    bst, X = _train(p, rounds, valid=valid, callbacks=callbacks)
    return bst, X


PARITY_CONFIGS = {
    "masked": {},
    "compact": {"tpu_grower": "compact", "stop_check_freq": 10_000},
    "bagging": {"bagging_fraction": 0.7, "bagging_freq": 2},
    "goss": {"data_sample_strategy": "goss"},
    "dart": {"boosting": "dart", "drop_rate": 0.5},
    "quantized": {"tpu_grower": "compact", "max_bin": 31,
                  "stop_check_freq": 10_000},
}


class TestKillResumeParity:
    @pytest.mark.parametrize("name", sorted(PARITY_CONFIGS))
    def test_bit_identical_model(self, name, tmp_path):
        params = _params(**PARITY_CONFIGS[name])
        ref, X = _train(params, 10)
        res, _ = _kill_and_resume(params, 10, kill_at=7, tmp_path=tmp_path)
        assert _trees(ref) == _trees(res), \
            f"{name}: resumed trees differ from uninterrupted run"
        np.testing.assert_array_equal(ref.predict(X), res.predict(X))

    @pytest.mark.parametrize("kill_at", [1, 4, 9])
    def test_arbitrary_kill_points(self, kill_at, tmp_path):
        """Death before the first snapshot (restart from 0), right on a
        tick, and mid-interval all resume bit-identically."""
        params = _params()
        ref, X = _train(params, 10)
        res, _ = _kill_and_resume(params, 10, kill_at=kill_at,
                                  tmp_path=tmp_path)
        assert _trees(ref) == _trees(res)
        np.testing.assert_array_equal(ref.predict(X), res.predict(X))

    def test_double_kill_then_resume(self, tmp_path):
        """Two successive deaths (the second during the resumed run)
        still converge to the uninterrupted model."""
        params = _params(tpu_checkpoint_dir=str(tmp_path),
                         tpu_checkpoint_freq=2)
        ref, X = _train(_params(), 12)
        for kill_at in (5, 9):
            with faultinject.inject(f"kill@iteration={kill_at}"):
                with pytest.raises(faultinject.SimulatedKill):
                    _train(params, 12)
        res, _ = _train(params, 12)
        assert _trees(ref) == _trees(res)
        np.testing.assert_array_equal(ref.predict(X), res.predict(X))

    def test_early_stopping_same_iteration(self, tmp_path):
        """A resumed run early-stops at exactly the same iteration with
        the same bests as the uninterrupted run (the callback state rides
        the snapshot)."""
        params = _params(learning_rate=0.3)    # stops around iter 19
        ref, X = _train(params, 40, valid=True,
                        callbacks=[lgb.early_stopping(3, verbose=False)])
        assert 7 < ref.num_trees() < 40        # the kill lands mid-run
        res, _ = _kill_and_resume(
            params, 40, kill_at=7, tmp_path=tmp_path, valid=True,
            callbacks=[lgb.early_stopping(3, verbose=False)])
        assert ref.best_iteration == res.best_iteration
        assert ref.best_score == res.best_score
        assert ref.num_trees() == res.num_trees()
        np.testing.assert_array_equal(ref.predict(X), res.predict(X))

    def test_resume_adds_early_stopping_not_in_killed_run(self, tmp_path):
        """A resumed run may attach callbacks the killed run did not have:
        early_stopping whose state is absent from the snapshot must
        initialize mid-run instead of crashing."""
        params = _params(learning_rate=0.3)
        p = dict(params, tpu_checkpoint_dir=str(tmp_path),
                 tpu_checkpoint_freq=3)
        with faultinject.inject("kill@iteration=7"):
            with pytest.raises(faultinject.SimulatedKill):
                _train(p, 40, valid=True)        # no early stopping
        res, X = _train(p, 40, valid=True,
                        callbacks=[lgb.early_stopping(3, verbose=False)])
        assert res.best_iteration > 6            # stopped, post-resume
        assert res.num_trees() < 40

    def test_resume_from_corrupted_newest_falls_back(self, tmp_path):
        """corrupt@snapshot chaos: the newest snapshot is damaged after
        landing; resume transparently uses the previous valid one and
        still reaches the bit-identical model."""
        params = _params()
        ref, X = _train(params, 10)
        p = dict(params, tpu_checkpoint_dir=str(tmp_path),
                 tpu_checkpoint_freq=2)
        with faultinject.inject(
                "corrupt@snapshot=3:mode=flip;kill@iteration=7"):
            with pytest.raises(faultinject.SimulatedKill):
                _train(p, 10)
        # snapshot 3 (iteration 6) is corrupt: resume starts at 4
        state = ckpt.load_latest(str(tmp_path))
        assert state["iteration"] == 4
        res, _ = _train(p, 10)
        assert _trees(ref) == _trees(res)
        np.testing.assert_array_equal(ref.predict(X), res.predict(X))

    def test_incompatible_snapshot_ignored(self, tmp_path):
        """A snapshot from a structurally different run (num_leaves) is
        rejected with a warning and training starts fresh."""
        p1 = _params(num_leaves=15, tpu_checkpoint_dir=str(tmp_path),
                     tpu_checkpoint_freq=2)
        _train(p1, 6)
        assert ckpt.load_latest(str(tmp_path)) is not None
        p2 = _params(num_leaves=7, tpu_checkpoint_dir=str(tmp_path),
                     tpu_checkpoint_freq=0)      # read-only: no overwrite
        bst, X = _train(p2, 6)
        ref, _ = _train(_params(num_leaves=7), 6)
        assert _trees(bst) == _trees(ref)

    def test_finished_run_snapshot_resumes_to_noop(self, tmp_path):
        """Resuming at num_boost_round trains zero extra iterations."""
        p = _params(tpu_checkpoint_dir=str(tmp_path),
                    tpu_checkpoint_freq=2)
        first, X = _train(p, 6)
        again, _ = _train(p, 6)
        assert again.num_trees() == first.num_trees() == 6
        np.testing.assert_array_equal(first.predict(X), again.predict(X))


# ======================================== steady-state contract under ckpt
def test_steady_state_zero_compiles_transfers_only_at_ticks():
    """With checkpointing enabled the training loop stays at 0 recompiles
    and 0 device->host transfers OUTSIDE snapshot ticks: every update()
    runs under the d2h guard; the guard is lifted only for the
    tpu_checkpoint_freq-boundary save_checkpoint call (the ONE planned
    fetch)."""
    import tempfile
    rng = np.random.RandomState(3)
    X = rng.randn(900, 8).astype(np.float32)
    y = (X[:, 0] - 0.4 * X[:, 1] > 0).astype(np.float64)
    params = _params(tpu_grower="compact", stop_check_freq=10_000)
    ds = lgb.Dataset(X, label=y, params=params)
    bst = lgb.Booster(params, ds)
    for _ in range(3):                  # warmup: compiles happen here
        bst.update()
    with tempfile.TemporaryDirectory() as d:
        with guards.compile_counter() as cc:
            for i in range(6):
                with guards.no_host_transfers():
                    bst.update()
                if (i + 1) % 3 == 0:    # the planned snapshot tick
                    bst.save_checkpoint(d)
        assert len(ckpt.list_snapshots(d)) == 2
    assert cc.lowerings == 0, "checkpointing broke the 0-recompile contract"
    assert cc.backend_compiles == 0


def test_snapshot_capture_is_a_real_host_fetch():
    """Negative control for the tick contract: capturing a snapshot DOES
    materialize device state — under the d2h guard it raises. Transfers
    therefore occur exactly when save_checkpoint is called, i.e. only at
    tpu_checkpoint_freq boundaries in the engine loop."""
    X, y = binary_data()
    params = _params()
    bst = lgb.Booster(params, lgb.Dataset(X, label=y, params=params))
    bst.update()
    with pytest.raises(guards.HostTransferError):
        with guards.no_host_transfers():
            bst._capture_checkpoint()


# ===================================== collective deadlines / watchdog
class TestWatchdog:
    def test_returns_value_inline_when_disabled(self):
        assert run_with_deadline(lambda: 41 + 1, 0.0, "inline") == 42

    def test_returns_value_under_deadline(self):
        assert run_with_deadline(lambda: "ok", 5.0, "fast fn") == "ok"

    def test_deadline_fires_structured(self):
        t0 = time.time()
        with pytest.raises(TrainingInterrupted) as err:
            run_with_deadline(lambda: time.sleep(30), 0.3, "hung step")
        assert time.time() - t0 < 10          # did NOT wait the 30s
        assert err.value.what == "hung step"
        assert err.value.deadline_s == 0.3
        assert "deadline" in str(err.value)

    def test_worker_exception_propagates(self):
        with pytest.raises(ZeroDivisionError):
            run_with_deadline(lambda: 1 // 0, 5.0, "failing fn")

    def test_transient_retries_with_backoff(self, monkeypatch):
        delays = []
        monkeypatch.setattr(time, "sleep", delays.append)
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise RuntimeError("Unable to initialize backend: retry me")
            return "recovered"

        assert run_with_deadline(flaky, 0.0, "bootstrap", retries=3,
                                 backoff_s=1.0) == "recovered"
        assert calls["n"] == 3
        assert delays == [1.0, 2.0]           # exponential backoff

    def test_non_transient_never_retries(self):
        calls = {"n": 0}

        def broken():
            calls["n"] += 1
            raise ValueError("num_leaves must be positive")

        with pytest.raises(ValueError):
            run_with_deadline(broken, 0.0, "bootstrap", retries=5)
        assert calls["n"] == 1

    def test_injected_step_hang_interrupts_with_final_snapshot(
            self, tmp_path, lock_order_witness):
        """The acceptance path: a hang injected into the distributed step
        surfaces as TrainingInterrupted AND a final snapshot lands, so
        resume continues to the bit-identical model.

        Runs under the lock-order witness: the snapshot path (read lock +
        fsync) interleaving with the deadline watchdog must keep the
        observed acquisition graph acyclic."""
        # deadline must clear the compile-heavy early iterations (the
        # watchdog measures wall clock, compiles included) while staying
        # far below the injected 120s hang
        params = _params(tpu_checkpoint_dir=str(tmp_path),
                         tpu_checkpoint_freq=1,
                         tpu_collective_deadline_s=10.0)
        with faultinject.inject("hang@step=4:seconds=120"):
            with pytest.raises(TrainingInterrupted):
                _train(params, 8)
        state = ckpt.load_latest(str(tmp_path))
        assert state is not None and state["iteration"] == 4
        res, X = _train(params, 8)
        ref, _ = _train(_params(), 8)
        assert _trees(ref) == _trees(res)
        np.testing.assert_array_equal(ref.predict(X), res.predict(X))

    def test_barrier_hang_interrupts(self):
        """mesh.sync_barrier under a deadline: an injected never-arriving
        rank surfaces as TrainingInterrupted (single-process dryrun runs
        the same code path the pod does)."""
        from lightgbm_tpu.parallel.mesh import sync_barrier
        sync_barrier("smoke")                  # no deadline: fine
        with faultinject.inject("hang@barrier=2:seconds=60"):
            sync_barrier("ok-tick", deadline_s=5.0)
            with pytest.raises(TrainingInterrupted) as err:
                sync_barrier("hung-tick", deadline_s=0.3)
        assert "hung-tick" in err.value.what

    def test_bootstrap_transient_then_recovery(self, monkeypatch):
        """multihost bootstrap: injected transient backend-init failures
        are retried with backoff (the r05 death mode), then succeed."""
        monkeypatch.setattr(time, "sleep", lambda s: None)
        calls = {"n": 0}

        def fake_bootstrap():
            faultinject.active_plan().fire("backend_init")
            calls["n"] += 1
            return "up"

        with faultinject.inject("transient@backend_init=*:count=2"):
            out = run_with_deadline(fake_bootstrap, 0.0, "bootstrap",
                                    retries=3, backoff_s=0.0)
        assert out == "up" and calls["n"] == 1


# =============================================== bench.py resume satellite
def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_for_ckpt_test", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_resumable_loop_survives_transient_death(tmp_path):
    """bench._resumable_update_loop: a transient backend death mid-run
    rebuilds the booster from the last snapshot and finishes at the
    target iteration count — bit-identical to a straight run."""
    bench = _load_bench()
    X, y = binary_data()
    params = _params()

    ref = lgb.Booster(params, lgb.Dataset(X, label=y, params=params))
    for _ in range(10):
        ref.update()

    ds = lgb.Dataset(X, label=y, params=params)

    def make_booster():
        return lgb.Booster(params, ds)

    bst = make_booster()
    with faultinject.inject("transient@bench_update=7"):
        bst = bench._resumable_update_loop(
            bst, make_booster, 10, str(tmp_path), ckpt_freq=2,
            base_delay_s=0.0)
    assert bst.current_iteration() == 10
    assert _trees(bst) == _trees(ref)


def test_bench_loop_gives_up_without_progress(tmp_path):
    """A persistently-recurring 'transient' failure (no forward progress
    between resumes) exhausts max_retries and re-raises instead of
    busy-looping forever."""
    bench = _load_bench()
    X, y = binary_data()
    params = _params()
    ds = lgb.Dataset(X, label=y, params=params)

    def make_booster():
        return lgb.Booster(params, ds)

    bst = make_booster()
    with faultinject.inject("transient@bench_update=3:count=-1") as plan:
        with pytest.raises(RuntimeError, match="Unable to initialize"):
            bench._resumable_update_loop(
                bst, make_booster, 10, str(tmp_path), ckpt_freq=2,
                max_retries=2, base_delay_s=0.0)
        # initial attempt + 2 capped retries, then give up
        assert plan.faults[0].fired == 3


def test_bench_loop_reraises_without_checkpoint_dir(tmp_path):
    """No checkpoint dir => no resume loop heroics: the transient error
    propagates (the outer stage retry owns it)."""
    bench = _load_bench()
    X, y = binary_data()
    params = _params()
    ds = lgb.Dataset(X, label=y, params=params)
    bst = lgb.Booster(params, ds)
    with faultinject.inject("transient@bench_update=2"):
        with pytest.raises(RuntimeError, match="Unable to initialize"):
            bench._resumable_update_loop(bst, lambda: bst, 5, "")


# ===================================== multihost-dryrun chaos (slow lane)
@pytest.mark.slow
def test_two_process_barrier_hang_surfaces_structured(tmp_path):
    """A real 2-process pod where rank 1 never reaches the barrier: rank 0
    must exit with a structured TrainingInterrupted (not hang) within the
    deadline, and its final snapshot hook must have run."""
    import socket
    import subprocess
    import sys

    worker = tmp_path / "barrier_worker.py"
    worker.write_text("""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")
rank = int(sys.argv[1]); port = sys.argv[2]
jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=2, process_id=rank)
from lightgbm_tpu.parallel.mesh import sync_barrier
from lightgbm_tpu.parallel.multihost import TrainingInterrupted
if rank == 1:
    import time
    time.sleep(120)          # never arrives
    sys.exit(0)
try:
    sync_barrier("chaos", deadline_s=5.0)
except TrainingInterrupted as err:
    print("STRUCTURED_INTERRUPT", err.what, flush=True)
    # hard-exit: the abandoned barrier thread would otherwise wedge the
    # distributed client's atexit shutdown — the production analogue is
    # "snapshot then exit", which engine.py does before re-raising
    os._exit(0)
print("BARRIER_PASSED_UNEXPECTEDLY", flush=True)
os._exit(1)
""")
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        procs.append(subprocess.Popen(
            [sys.executable, str(worker), str(rank), str(port)],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    out0, _ = procs[0].communicate(timeout=120)
    procs[1].kill()
    procs[1].communicate()
    assert procs[0].returncode == 0, f"rank 0 failed:\n{out0}"
    assert "STRUCTURED_INTERRUPT" in out0


# ------------------------------------------- R012 leak regressions
def test_kill_at_snapshot_leaves_no_orphan_tmp(tmp_path,
                                               resource_leak_witness):
    """The write is atomic all the way through a kill at the snapshot
    chaos site: the renamed file is durable, no ``.snapshot_tmp_*``
    orphan survives, and the witness sees no fd growth."""
    with faultinject.inject("kill@snapshot=1"):
        with pytest.raises(faultinject.SimulatedKill):
            ckpt.write_snapshot(str(tmp_path), 1, {"iteration": 1})
    names = os.listdir(tmp_path)
    assert not [n for n in names if n.startswith(".snapshot_tmp_")], names
    assert os.path.basename(ckpt.snapshot_path(str(tmp_path), 1)) in names


def test_simulated_kill_mid_write_unlinks_temp(tmp_path, monkeypatch,
                                               resource_leak_witness):
    """A SimulatedKill BETWEEN mkstemp and the rename takes the
    catch-BaseException cleanup edge (the shape tpulint R012 verifies
    statically): no temp file, no final file, no leaked fd."""
    def grenade(src, dst):
        raise faultinject.SimulatedKill("mid-write replace")
    monkeypatch.setattr(os, "replace", grenade)
    with pytest.raises(faultinject.SimulatedKill):
        ckpt.write_snapshot(str(tmp_path), 2, {"iteration": 2})
    monkeypatch.undo()
    assert os.listdir(str(tmp_path)) == []
