"""Engine registry + startup microbench autotuner (ISSUE 12).

Pins the tentpole's contracts:

* resolve-order precedence — user > env > autotune cache > heuristic
  default — per knob, with provenance in ``Resolution.sources``;
* the autotune cache round-trips atomically, a corrupted cache falls
  back to heuristics (and a sweep-allowed run re-benches + rewrites);
* ``tpu_autotune=first_run`` on a fresh cache runs the microbench
  exactly ONCE; a second run with the same shape-class performs zero
  microbenches (and its setup lowers nothing new);
* ``reset_parameter`` re-resolves every engine knob through the
  registry (a mid-run change is never a silent no-op);
* the steady-state 0-recompile/0-d2h guard holds with autotune armed
  (the sweep runs strictly before the steady window, in the
  ``autotune`` compile phase);
* trees are bit-identical across ``tpu_autotune=off`` vs an autotuned
  selection (engine choice changes speed only).

Fast-lane tests stub ``autotune._time_candidate`` (tier-1 budget); the
REAL timed sweep and the offline CLI live in the ``slow`` lane.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.analysis import guards
from lightgbm_tpu.engines import autotune, registry

from utils import binary_data

SHAPE = registry.DatasetShape(rows=100_000, features=28, num_bins=255,
                              mode="serial")
BASE = {"objective": "binary", "max_bin": 31, "min_data_in_leaf": 5,
        "verbosity": -1, "seed": 7, "num_iterations": 5}


def _strip_knobs(model_text):
    return "\n".join(l for l in model_text.splitlines()
                     if not l.startswith("[tpu_"))


def _stub_timer(monkeypatch, times=None):
    """Replace the candidate timer: deterministic synthetic timings (by
    call order) and no device work — the fast-lane discipline."""
    seq = list(times or [])
    calls = []

    def fake(fn, *args, reps=0):
        calls.append(fn)
        return seq.pop(0) if seq else 1e-3

    monkeypatch.setattr(autotune, "_time_candidate", fake)
    return calls


def _decision_block(winner, platform="cpu", sclass=None):
    return {"winner": winner, "table": [], "platform": platform,
            "shape_class": sclass or registry.shape_class(SHAPE),
            "rows_sampled": 0, "reps": 0, "recorded": "test"}


# ---------------------------------------------------------- resolve order
def test_resolve_order_precedence(tmp_path, monkeypatch):
    """user > env > autotune cache > heuristic default, per knob, with
    the provenance recorded in Resolution.sources."""
    monkeypatch.delenv("LGBM_TPU_HIST_MBATCH", raising=False)
    cache = tmp_path / "at.json"
    autotune.store_decision(
        str(cache), autotune.cache_key("cpu", registry.shape_class(SHAPE)),
        _decision_block({"entry": "xla_lane", "hist_impl": "xla",
                         "hist_layout": "lane", "hist_mbatch": 16}))
    cfg = {"tpu_autotune": "first_run", "tpu_autotune_cache": str(cache)}
    # autotune rung: the cached winner applies where user/env are silent
    res = registry.resolve(cfg, shape=SHAPE, platform="cpu",
                           allow_sweep=False)
    assert res.hist_mbatch == 16
    assert res.sources["hist_mbatch"] == "autotune"
    assert res.hist_impl == "xla"
    assert res.autotuned and res.entry_id == "xla_lane"
    assert res.shape_class == registry.shape_class(SHAPE)
    # env beats the cache
    monkeypatch.setenv("LGBM_TPU_HIST_MBATCH", "4")
    res = registry.resolve(cfg, shape=SHAPE, platform="cpu",
                           allow_sweep=False)
    assert res.hist_mbatch == 4 and res.sources["hist_mbatch"] == "env"
    # user beats the env override
    res = registry.resolve(dict(cfg, tpu_hist_mbatch=12), shape=SHAPE,
                           platform="cpu", allow_sweep=False)
    assert res.hist_mbatch == 12 and res.sources["hist_mbatch"] == "user"
    monkeypatch.delenv("LGBM_TPU_HIST_MBATCH")
    # heuristic default with autotune off: no decision applies
    res = registry.resolve({"tpu_autotune": "off",
                            "tpu_autotune_cache": str(cache)},
                           shape=SHAPE, platform="cpu", allow_sweep=False)
    assert res.hist_mbatch == 8
    assert res.sources["hist_mbatch"] == "default"
    assert not res.autotuned


def test_resolve_unknown_values_warn_like_before():
    """Unknown knob values keep the warn-and-default behavior the old
    _pick_* helpers had (the delegates route through the registry)."""
    from lightgbm_tpu.boosting.gbdt import (_pick_hist_layout,
                                            _pick_hist_mbatch,
                                            _pick_step_buckets)
    assert _pick_hist_layout({"tpu_hist_layout": "bogus"}, 64) == "lane"
    assert _pick_hist_layout({"tpu_hist_layout": "sublane"}, 256) == "lane"
    assert _pick_hist_mbatch({"tpu_hist_mbatch": 99}) == 16
    assert _pick_step_buckets({"tpu_step_buckets": "bogus"}) is True
    assert registry.resolve_overlap({"tpu_hist_overlap": "bogus"}) == 0
    assert autotune.resolve_mode({"tpu_autotune": "bogus"}) == "first_run"


def test_auto_layout_honest_with_cached_sublane_win(tmp_path):
    """The PR 6 sweep measured sublane competitive at B <= 64 but `auto`
    could never select it; with a cached measured win it can — and
    without a cache the conservative lane default holds. A stale
    decision against a wider re-binned shape falls back to lane."""
    shape16 = registry.DatasetShape(rows=1 << 20, features=16,
                                    num_bins=16, mode="serial")
    cache = tmp_path / "at.json"
    autotune.store_decision(
        str(cache), autotune.cache_key("tpu", registry.shape_class(shape16)),
        _decision_block({"entry": "pallas_sublane", "hist_impl": "pallas",
                         "hist_layout": "sublane", "hist_mbatch": 8},
                        platform="tpu",
                        sclass=registry.shape_class(shape16)))
    cfg = {"tpu_autotune": "first_run", "tpu_autotune_cache": str(cache)}
    res = registry.resolve(cfg, shape=shape16, platform="tpu",
                           allow_sweep=False)
    assert res.hist_layout == "sublane"
    assert res.sources["hist_layout"] == "autotune"
    # no cache -> lane (the documented conservative default)
    res = registry.resolve({"tpu_autotune": "off"}, shape=shape16,
                           platform="tpu", allow_sweep=False)
    assert res.hist_layout == "lane"
    # stale sublane decision vs a wide-bin shape: lane, not a blowup
    wide = shape16._replace(num_bins=255)
    autotune.store_decision(
        str(cache), autotune.cache_key("tpu", registry.shape_class(wide)),
        _decision_block({"hist_layout": "sublane", "hist_mbatch": 8},
                        platform="tpu",
                        sclass=registry.shape_class(wide)))
    res = registry.resolve(cfg, shape=wide, platform="tpu",
                           allow_sweep=False)
    assert res.hist_layout == "lane"
    # user knob still beats the cache outright
    res = registry.resolve(dict(cfg, tpu_hist_layout="lane"),
                           shape=shape16, platform="tpu",
                           allow_sweep=False)
    assert res.hist_layout == "lane"
    assert res.sources["hist_layout"] == "user"


def test_shape_class_buckets_like_the_ladder():
    a = registry.DatasetShape(100_000, 28, 255, "serial")
    b = registry.DatasetShape(120_000, 30, 255, "serial")
    assert registry.shape_class(a) == registry.shape_class(b)
    assert registry.shape_class(a) != registry.shape_class(
        a._replace(mode="data"))
    assert registry.shape_class(a) != registry.shape_class(
        a._replace(rows=300_000))
    assert "quant" in registry.shape_class(a._replace(quant=True))


def test_sweep_candidates_respect_platform_and_bins():
    cands = registry.sweep_candidates(SHAPE, "cpu")
    assert cands and all(c.entry.id == "xla_lane" for c in cands)
    assert sorted({c.mbatch for c in cands}) == [1, 8, 16]
    # the default mbatch leads so a tie resolves to today's behavior
    assert cands[0].mbatch == 8
    tpu = registry.sweep_candidates(
        SHAPE._replace(num_bins=16), "tpu")
    ids = {c.entry.id for c in tpu}
    assert "pallas_lane" in ids and "pallas_sublane" in ids
    assert "fused_lane" not in ids          # structural, not swept
    wide = registry.sweep_candidates(SHAPE, "tpu")
    assert "pallas_sublane" not in {c.entry.id for c in wide}  # B > 64


# ------------------------------------------------------------- the cache
def test_cache_roundtrip_corruption_and_always(tmp_path, monkeypatch):
    """first_run: exactly one sweep on a fresh cache, zero on the warm
    rerun; a corrupted cache degrades to heuristics (no-sweep path) or
    re-benches + rewrites (sweep path); always re-sweeps over a hit."""
    _stub_timer(monkeypatch)
    cache = tmp_path / "at.json"
    shape = registry.DatasetShape(rows=512, features=4, num_bins=16,
                                  mode="serial")
    sample = np.zeros((512, 4), np.uint8)
    cfg = {"tpu_autotune": "first_run", "tpu_autotune_cache": str(cache)}
    n0 = autotune.SWEEPS_RUN
    res = registry.resolve(cfg, shape=shape, platform="cpu",
                           sample_provider=lambda n: sample[:n])
    assert autotune.SWEEPS_RUN == n0 + 1 and res.autotuned
    data = json.loads(cache.read_text())
    assert data["version"] == autotune.CACHE_VERSION
    (key, block), = data["entries"].items()
    assert key == f"cpu/{registry.shape_class(shape)}"
    assert block["winner"]["entry"] == "xla_lane"
    assert len(block["table"]) == 3 and all("ms" in r
                                            for r in block["table"])
    # warm rerun: ZERO microbenches, same decision
    res2 = registry.resolve(cfg, shape=shape, platform="cpu",
                            sample_provider=lambda n: sample[:n])
    assert autotune.SWEEPS_RUN == n0 + 1
    assert res2[:7] == res[:7]
    # always: re-sweeps over the cache hit
    res3 = registry.resolve(dict(cfg, tpu_autotune="always"), shape=shape,
                            platform="cpu",
                            sample_provider=lambda n: sample[:n])
    assert autotune.SWEEPS_RUN == n0 + 2 and res3.autotuned
    # corrupted cache, no sweep allowed: heuristic fallback, no raise
    cache.write_text("{definitely not json")
    res4 = registry.resolve(cfg, shape=shape, platform="cpu",
                            allow_sweep=False)
    assert not res4.autotuned and res4.sources["hist_mbatch"] == "default"
    # corrupted cache, sweep allowed: re-bench and rewrite atomically
    res5 = registry.resolve(cfg, shape=shape, platform="cpu",
                            sample_provider=lambda n: sample[:n])
    assert autotune.SWEEPS_RUN == n0 + 3 and res5.autotuned
    assert json.loads(cache.read_text())["entries"]


def test_unwritable_cache_still_uses_measured_winner(tmp_path,
                                                     monkeypatch):
    _stub_timer(monkeypatch)
    shape = registry.DatasetShape(rows=256, features=4, num_bins=16,
                                  mode="serial")
    sample = np.zeros((256, 4), np.uint8)
    bad = tmp_path / "no_dir_here"
    bad.write_text("")      # a FILE where the cache dir path must go
    cfg = {"tpu_autotune": "first_run",
           "tpu_autotune_cache": str(bad / "at.json")}
    res = registry.resolve(cfg, shape=shape, platform="cpu",
                           sample_provider=lambda n: sample[:n])
    assert res.autotuned        # this run still took the measured winner


def test_implicit_arming_stays_inert_on_cpu(monkeypatch):
    """The first_run DEFAULT must not tax CPU runs or small shapes: with
    tpu_autotune unset, nothing sweeps on cpu even at 1M rows, and on
    TPU platforms only shapes >= MIN_AUTOTUNE_ROWS arm."""
    def boom(*a, **k):  # pragma: no cover - the assertion IS the call
        raise AssertionError("sweep ran while unarmed")
    monkeypatch.setattr(autotune, "run_sweep", boom)
    big = registry.DatasetShape(rows=1 << 20, features=28, num_bins=255,
                                mode="serial")
    res = registry.resolve({}, shape=big, platform="cpu",
                           sample_provider=lambda n: np.zeros((n, 28)))
    assert not res.autotuned
    small = registry.DatasetShape(rows=1000, features=28, num_bins=255,
                                  mode="serial")
    res = registry.resolve({}, shape=small, platform="tpu",
                           sample_provider=lambda n: np.zeros((n, 28)))
    assert not res.autotuned


# ----------------------------------------------- booster-level integration
def test_first_run_once_then_zero_microbenches(tmp_path, monkeypatch):
    """The acceptance loop: a fresh cache sweeps exactly once at
    _setup_train; a second booster over the same shape-class resolves
    from the cache with 0 microbenches and no extra autotune-phase
    compiles (stubbed timer -> the sweep itself lowers nothing, so ANY
    autotune-phase compile on the rerun would be a leak)."""
    _stub_timer(monkeypatch)
    cache = tmp_path / "at.json"
    X, y = binary_data(600, 6, seed=1)
    params = dict(BASE, tpu_grower="compact",
                  tpu_autotune="first_run",
                  tpu_autotune_cache=str(cache))
    n0 = autotune.SWEEPS_RUN
    bst = lgb.Booster(params, lgb.Dataset(X, label=y, params=params))
    assert autotune.SWEEPS_RUN == n0 + 1
    assert bst._gbdt._engine_resolution.autotuned
    assert cache.exists()

    def _autotune_compiles():
        return dict(guards.phase_compile_counts()
                    .get("by_phase", {}).get("autotune", {}))

    phase0 = _autotune_compiles()
    bst2 = lgb.Booster(params, lgb.Dataset(X, label=y, params=params))
    assert autotune.SWEEPS_RUN == n0 + 1          # cache hit, no sweep
    assert bst2._gbdt._engine_resolution.autotuned
    assert _autotune_compiles() == phase0


def test_reset_parameter_reresolves_through_registry(tmp_path,
                                                     monkeypatch):
    """A mid-run engine-knob change must actually take effect (the PR 8
    stale-choice fix, now for every engine knob), and a cached autotune
    decision still applies on re-resolve — without re-benching."""
    _stub_timer(monkeypatch)
    cache = tmp_path / "at.json"
    X, y = binary_data(600, 6, seed=2)
    params = dict(BASE, tpu_grower="compact", tpu_autotune="first_run",
                  tpu_autotune_cache=str(cache))
    bst = lgb.Booster(params, lgb.Dataset(X, label=y, params=params))
    bst.update()
    gp = bst._gbdt.grower_params
    assert gp.hist_mbatch == 8      # stub tie -> the default-first cell
    n_swept = autotune.SWEEPS_RUN
    bst.reset_parameter({"tpu_hist_mbatch": 4, "tpu_hist_impl": "xla"})
    gp = bst._gbdt.grower_params
    assert gp.hist_mbatch == 4 and gp.hist_impl == "xla"
    src = bst._gbdt._engine_resolution.sources
    assert src["hist_mbatch"] == "user" and src["hist_impl"] == "user"
    assert autotune.SWEEPS_RUN == n_swept       # re-resolve, no re-bench
    bst.update()                                # trains on under the change
    # layout re-resolves too (warns + falls back on the invalid value)
    bst.reset_parameter({"tpu_hist_layout": "bogus"})
    assert bst._gbdt.grower_params.hist_layout == "lane"


def test_reset_uses_in_memory_decision_not_cache(tmp_path, monkeypatch):
    """The run's measured decision survives reset_parameter WITHOUT a
    cache re-read: an unwritable/deleted/rewritten cache file must
    neither drop nor flip the in-run engine choice, and the training
    loop (stock learning-rate callback calls reset every iteration)
    must not do cache file I/O."""
    _stub_timer(monkeypatch)
    cache = tmp_path / "at.json"
    X, y = binary_data(500, 6, seed=5)
    params = dict(BASE, tpu_grower="compact", tpu_autotune="first_run",
                  tpu_autotune_cache=str(cache))
    bst = lgb.Booster(params, lgb.Dataset(X, label=y, params=params))
    bst.update()
    decision0 = bst._gbdt._engine_resolution.decision
    assert decision0 is not None
    cache.unlink()                      # the file is GONE mid-run

    def no_reads(*a, **k):  # pragma: no cover - the assertion IS the call
        raise AssertionError("reset_parameter re-read the autotune cache")
    monkeypatch.setattr(autotune, "decision_for", no_reads)
    bst.reset_parameter({"learning_rate": 0.05})
    res = bst._gbdt._engine_resolution
    assert res.autotuned and res.decision == decision0
    assert res.hist_mbatch == decision0["hist_mbatch"]
    bst.update()


def test_sweep_skipped_when_all_knobs_pinned(monkeypatch):
    """User/env pinning every swept knob means the microbench cannot
    influence anything — an armed run must not pay for it."""
    def boom(*a, **k):  # pragma: no cover - the assertion IS the call
        raise AssertionError("sweep ran with every knob pinned")
    monkeypatch.setattr(autotune, "run_sweep", boom)
    cfg = {"tpu_autotune": "first_run", "tpu_hist_mbatch": 8,
           "tpu_hist_layout": "lane", "tpu_hist_impl": "xla"}
    shape = registry.DatasetShape(rows=512, features=4, num_bins=16,
                                  mode="serial")
    res = registry.resolve(cfg, shape=shape, platform="cpu",
                           sample_provider=lambda n: np.zeros((n, 4)))
    assert not res.autotuned
    assert res.sources["hist_mbatch"] == "user"
    # one knob left to auto -> the sweep matters again
    cfg2 = dict(cfg)
    del cfg2["tpu_hist_mbatch"]
    with pytest.raises(AssertionError, match="every knob pinned"):
        registry.resolve(cfg2, shape=shape, platform="cpu",
                         sample_provider=lambda n: np.zeros((n, 4)))


def test_sweep_times_the_real_channel_layout():
    """quant shape-classes time int8 code channels (the int8 -> int32
    contraction), pack4 classes time nibble-packed blocks — the cached
    'measured' winner reflects the engine path that actually trains."""
    rng = np.random.RandomState(0)
    sample = rng.randint(0, 16, (512, 4)).astype(np.uint8)
    cands = registry.sweep_candidates(
        registry.DatasetShape(512, 4, 16, "serial"), "cpu")[:1]
    for kw in ({"quant": True}, {"pack4": True}):
        winner, table = autotune.run_sweep(sample, 16, cands, reps=1,
                                           **kw)
        assert winner is not None and "ms" in table[0], (kw, table)


def test_resolve_without_shape_keeps_explicit_layout():
    """No train-set context (loaded booster): the sublane bin-width
    bound cannot be checked, so an explicit layout is not spuriously
    rejected against a made-up width."""
    res = registry.resolve({"tpu_hist_layout": "sublane"}, shape=None,
                           platform="tpu")
    assert res.hist_layout == "sublane"


def test_steady_state_guard_with_autotune_armed(tmp_path):
    """The REAL sweep (no stub — candidates compile and run) on a tiny
    shape, then 4 post-warmup iterations: 0 lowerings, 0 backend
    compiles, 0 d2h. Autotune work lands strictly before the steady
    window, attributed to the 'autotune' compile phase."""
    cache = tmp_path / "at.json"
    X, y = binary_data(900, 6, seed=3)
    params = {
        "objective": "binary", "num_leaves": 15, "max_bin": 31,
        "min_data_in_leaf": 5, "verbosity": -1, "seed": 7,
        "tpu_grower": "compact", "stop_check_freq": 10_000,
        "tpu_autotune": "first_run", "tpu_autotune_cache": str(cache),
    }
    n0 = autotune.SWEEPS_RUN
    bst = lgb.Booster(params, lgb.Dataset(X, label=y, params=params))
    assert autotune.SWEEPS_RUN == n0 + 1
    (block,) = list(json.loads(cache.read_text())["entries"].values())
    assert any("ms" in r for r in block["table"])   # really timed
    # the sweep's compiles are attributed to the 'autotune' phase (one
    # candidate program each), not to train_step
    at = guards.phase_compile_counts().get("by_phase", {}) \
        .get("autotune", {})
    assert at.get("lowerings", 0) >= 3
    for _ in range(2):
        bst.update()
    with guards.steady_state_guard("4 autotuned iterations") as cc:
        for _ in range(4):
            bst.update()
    assert cc.lowerings == 0
    assert cc.backend_compiles == 0
    bst._gbdt._flush_trees()
    assert bst._gbdt.num_total_trees >= 5


# ------------------------------------------------------------------ parity
@pytest.mark.parametrize("mode_extra", [
    {"tpu_grower": "compact"},
    {"tpu_grower": "compact", "tree_learner": "data", "num_shards": 2},
])
def test_tree_parity_off_vs_autotuned(tmp_path, monkeypatch, mode_extra):
    """Engine choice changes speed ONLY: tpu_autotune=off vs an
    autotuned selection that elects a NON-default cell (mbatch 16)
    produce bit-identical models and predictions, per learner mode."""
    X, y = binary_data(700, 8, seed=4)
    params_off = dict(BASE, tpu_autotune="off", **mode_extra)
    ds = lgb.Dataset(X, label=y, params=params_off)
    bst_off = lgb.train(params_off, ds)
    pred_off = bst_off.predict(X)
    # force the autotuned winner to the non-default mbatch-16 cell via
    # a crafted cache for the exact shape-class the booster resolved
    shape = bst_off._gbdt._engine_shape
    cache = tmp_path / "at.json"
    autotune.store_decision(
        str(cache), autotune.cache_key("cpu", registry.shape_class(shape)),
        _decision_block({"entry": "xla_lane", "hist_impl": "xla",
                         "hist_layout": "lane", "hist_mbatch": 16},
                        sclass=registry.shape_class(shape)))
    params_on = dict(BASE, tpu_autotune="first_run",
                     tpu_autotune_cache=str(cache), **mode_extra)
    bst_on = lgb.train(params_on,
                       lgb.Dataset(X, label=y, params=params_on))
    gp = bst_on._gbdt.grower_params
    assert gp.hist_mbatch == 16 and gp.hist_impl == "xla"
    assert bst_on._gbdt._engine_resolution.sources["hist_mbatch"] \
        == "autotune"
    assert _strip_knobs(bst_on.model_to_string()) \
        == _strip_knobs(bst_off.model_to_string())
    np.testing.assert_array_equal(bst_on.predict(X), pred_off)


# ------------------------------------------------------------ bench + CLI
def test_sweep_tables_roundtrip(tmp_path):
    cache = tmp_path / "at.json"
    autotune.store_decision(str(cache), "cpu/serial-r512-f4-b16",
                            _decision_block({"hist_mbatch": 8}))
    autotune.store_decision(str(cache), "cpu/serial-r1024-f8-b16",
                            _decision_block({"hist_mbatch": 16}))
    tables = autotune.sweep_tables(str(cache))
    assert set(tables) == {"cpu/serial-r512-f4-b16",
                           "cpu/serial-r1024-f8-b16"}
    assert autotune.sweep_tables(str(tmp_path / "missing.json")) == {}


def test_bench_arms_autotune_cache(tmp_path, monkeypatch):
    """BENCH_AUTOTUNE=1 arms the same cache the trainer reads and tags
    the recorded row autotuned: true (the bench-side satellite)."""
    import bench
    monkeypatch.setenv("BENCH_AUTOTUNE", "1")
    monkeypatch.setenv("BENCH_AUTOTUNE_CACHE", str(tmp_path / "b.json"))
    params = {}
    path = bench._arm_autotune(params)
    assert path == str(tmp_path / "b.json")
    assert params["tpu_autotune"] == "first_run"
    assert params["tpu_autotune_cache"] == path
    monkeypatch.delenv("BENCH_AUTOTUNE")
    assert bench._arm_autotune({}) is None


@pytest.mark.slow
def test_real_timed_sweep_and_cli(tmp_path):
    """The REAL sweep through the offline CLI (scripts/autotune): a
    synthetic shape sweeps, prints the decision table, and writes the
    cache the trainer can consume."""
    cache = tmp_path / "cli.json"
    out = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                      "scripts", "autotune"),
         "--rows", "2048", "--features", "6", "--max-bin", "16",
         "--reps", "2", "--cache", str(cache)],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert out.returncode == 0, out.stderr
    assert "winner" in out.stdout
    data = json.loads(cache.read_text())
    (block,) = list(data["entries"].values())
    assert any("ms" in r for r in block["table"])
    assert block["winner"]["entry"] == "xla_lane"
