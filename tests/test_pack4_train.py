"""Round-6 training-bandwidth features: pack4 bins through the training hot
path, the bins-on-sublanes Mosaic layout, and per-leaf bit-width narrowing.

Acceptance properties (ISSUE 6):

  * pack4 training (tpu_bin_pack4 + compact grower) produces BIT-IDENTICAL
    trees and predictions vs the u8 path — dense, categorical, EFB-bundled,
    and at non-multiple row counts (partial-block drains);
  * the narrowed quantized engine (acc_bits=16, packed-pair channels) is
    bit-identical to the int8 -> int32 engine, and per-leaf hist-bits
    selection (ops/renew.py hist_bits_in_leaf) mirrors the reference's
    GetHistBitsInLeaf thresholds;
  * the bins-on-sublanes layout (tpu_hist_layout=sublane) matches the lane
    layout exactly for counts/int32 and within f32 regrouping for sums, in
    both the standalone Mosaic kernel and the fused kernel;
  * the steady-state guard holds with tpu_bin_pack4=true training: zero
    recompiles, zero device->host transfers post warmup.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.analysis import guards
from lightgbm_tpu.ops.compact import RowLayout, pack_rows, unpack_rows
from lightgbm_tpu.ops.fused_split import fused_split
from lightgbm_tpu.ops.histogram import histogram_block, narrow_chunk_rows
from lightgbm_tpu.ops.pallas_histogram import pallas_histogram
from lightgbm_tpu.ops.renew import hist_bits_in_leaf

I32 = jnp.int32


def _strip_params(model_text: str) -> str:
    """Model text minus the parameters echo (the only intended delta
    between a pack4 and a u8 run is the knob itself)."""
    return "\n".join(l for l in model_text.splitlines()
                     if not l.startswith("[tpu_"))


def _higgs_like(n, f, seed=7, cat_col=None):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    if cat_col is not None:
        X[:, cat_col] = rng.randint(0, 6, n)
    y = (X[:, 0] - 0.4 * X[:, 2] + 0.3 * rng.randn(n) > 0).astype(np.float64)
    return X, y


def _onehot_wide(n=3000, groups=100, card=3, seed=0):
    """>= 256 sparse one-hot columns so EFB bundling actually triggers."""
    rng = np.random.RandomState(seed)
    cats = rng.randint(0, card, size=(n, groups))
    X = np.zeros((n, groups * card), np.float32)
    for g in range(groups):
        X[np.arange(n), g * card + cats[:, g]] = 1.0
    w = rng.randn(X.shape[1]) * 0.5
    y = ((X @ w + 0.4 * rng.randn(n)) > 0).astype(np.float64)
    return X, y


BASE = {"objective": "binary", "num_leaves": 15, "max_bin": 15,
        "learning_rate": 0.1, "min_data_in_leaf": 20, "verbosity": -1,
        "tpu_grower": "compact", "stop_check_freq": 10_000}


def _train(X, y, extra, n_iter=6):
    p = dict(BASE, **extra)
    return lgb.train(p, lgb.Dataset(X, label=y, params=p), n_iter)


# ------------------------------------------------- pack4 training parity
class TestPack4Training:
    @pytest.mark.parametrize("n", [3072, 3003])  # non-multiple row counts
    def test_dense_bit_identical(self, n):
        X, y = _higgs_like(n, 9, cat_col=3)
        b_u8 = _train(X, y, {"categorical_feature": [3]})
        b_p4 = _train(X, y, {"categorical_feature": [3],
                             "tpu_bin_pack4": True})
        assert b_p4._gbdt._compact["layout"].packed4
        assert not b_u8._gbdt._compact["layout"].packed4
        np.testing.assert_array_equal(b_u8.predict(X), b_p4.predict(X))
        assert _strip_params(b_u8.model_to_string()) \
            == _strip_params(b_p4.model_to_string())

    def test_efb_bundled_bit_identical(self):
        X, y = _onehot_wide()
        p = dict(BASE, num_leaves=31, min_data_in_leaf=10)
        ds_u8 = lgb.Dataset(X, label=y, params=p)
        b_u8 = lgb.train(dict(p), ds_u8, 5)
        p4 = dict(p, tpu_bin_pack4=True)
        ds_p4 = lgb.Dataset(X, label=y, params=p4)
        b_p4 = lgb.train(dict(p4), ds_p4, 5)
        # the bundled matrix must actually be in play AND nibble-packed
        assert ds_p4._inner.bundle_info is not None
        assert b_p4._gbdt._compact["layout"].packed4
        np.testing.assert_array_equal(b_u8.predict(X), b_p4.predict(X))

    def test_fused_interpret_bit_identical(self):
        """pack4 through the fused Mosaic kernel (interpret mode): the
        in-kernel nibble routing + nibble one-hot build must reproduce the
        u8 kernel's trees bit for bit."""
        X, y = _higgs_like(1203, 6, seed=3)
        extra = {"tpu_fused_interpret": True, "tpu_fused_block": 128,
                 "tpu_hist_mbatch": 4}
        b_u8 = _train(X, y, dict(extra), n_iter=3)
        b_p4 = _train(X, y, dict(extra, tpu_bin_pack4=True), n_iter=3)
        assert b_p4._gbdt._compact["layout"].packed4
        np.testing.assert_array_equal(b_u8.predict(X), b_p4.predict(X))

    def test_wide_bins_fall_back_to_u8(self):
        X, y = _higgs_like(1500, 6)
        b = _train(X, y, {"max_bin": 31, "tpu_bin_pack4": True}, n_iter=2)
        assert not b._gbdt._compact["layout"].packed4     # warned + u8
        assert b._gbdt.num_total_trees >= 1

    def test_quantized_pack4_bit_identical(self):
        """nibble bins + int8 gradient codes compose: same trees as u8."""
        X, y = _higgs_like(2048, 8, seed=11)
        q = {"use_quantized_grad": True, "num_grad_quant_bins": 8}
        b_u8 = _train(X, y, dict(q))
        b_p4 = _train(X, y, dict(q, tpu_bin_pack4=True))
        np.testing.assert_array_equal(b_u8.predict(X), b_p4.predict(X))


# ----------------------------------------------- pack4 row-record helpers
def test_packed_layout_roundtrip():
    rng = np.random.RandomState(0)
    n, f = 517, 7                       # odd F exercises the pad nibble
    binned = rng.randint(0, 16, (n, f)).astype(np.uint8)
    g = rng.randn(n).astype(np.float32)
    h = rng.rand(n).astype(np.float32)
    cnt = np.ones(n, np.float32)
    extras = rng.randn(2, n).astype(np.float32)
    layout = RowLayout(num_features=f, num_extra=2, packed4=True)
    assert layout.feat_cols == 4
    work = pack_rows(jnp.asarray(binned), jnp.asarray(g), jnp.asarray(h),
                     jnp.asarray(cnt), jnp.asarray(extras), layout,
                     pad_rows=32)
    b2, g2, h2, c2, e2 = unpack_rows(work, n, layout)
    np.testing.assert_array_equal(np.asarray(b2), binned)
    np.testing.assert_array_equal(np.asarray(g2), g)
    np.testing.assert_array_equal(np.asarray(e2), extras)


# --------------------------------------------- narrowed quantized engine
class TestNarrowedQuantized:
    def _codes(self, n, qmax, seed=0):
        rng = np.random.RandomState(seed)
        codes = np.zeros((n, 4), np.int8)
        codes[:, 0] = rng.randint(-qmax, qmax + 1, n)
        codes[:, 1] = rng.randint(0, qmax + 1, n)     # hess codes >= 0
        codes[:, 2] = rng.rand(n) > 0.3
        codes[:, 3] = 1
        return codes

    @pytest.mark.parametrize("n,qmax", [(1000, 5), (5000, 9), (700, 31)])
    def test_bit_identical_vs_int32_engine(self, n, qmax):
        rng = np.random.RandomState(1)
        b = 16
        binned = rng.randint(0, b, (n, 7)).astype(np.uint8)
        codes = self._codes(n, qmax)
        wide = histogram_block(jnp.asarray(binned), jnp.asarray(codes), b,
                               impl="xla")
        narrow = histogram_block(jnp.asarray(binned), jnp.asarray(codes), b,
                                 impl="xla", acc_bits=16, quant_max=qmax)
        assert narrow.dtype == jnp.int32
        np.testing.assert_array_equal(np.asarray(wide), np.asarray(narrow))

    def test_pack4_narrow_compose(self):
        rng = np.random.RandomState(2)
        n, f, b = 1500, 9, 16
        binned = rng.randint(0, b, (n, f)).astype(np.uint8)
        codes = self._codes(n, 9, seed=3)
        padded = np.pad(binned, ((0, 0), (0, 1)))
        packed = (padded[:, 0::2] | (padded[:, 1::2] << 4)).astype(np.uint8)
        ref = histogram_block(jnp.asarray(binned), jnp.asarray(codes), b,
                              impl="xla")
        out = histogram_block(jnp.asarray(packed), jnp.asarray(codes), b,
                              impl="xla", packed4_features=f, acc_bits=16,
                              quant_max=9)
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))

    def test_narrow_chunk_rows_bounds(self):
        # chunk * qmax must stay under the 4096 radix; too-wide code
        # bounds have no eligible chunk at all
        assert narrow_chunk_rows(5) * 5 < 4096
        assert narrow_chunk_rows(5) % 128 == 0
        assert narrow_chunk_rows(31) >= 128
        assert narrow_chunk_rows(127) == 0
        with pytest.raises(ValueError):
            histogram_block(jnp.zeros((256, 2), jnp.uint8),
                            jnp.zeros((256, 4), jnp.int8), 16,
                            impl="xla", acc_bits=16, quant_max=127)

    def test_invalid_bits_value_warns_to_32(self):
        X, y = _higgs_like(1200, 6, seed=21)
        q = {"use_quantized_grad": True, "num_grad_quant_bins": 8}
        b = _train(X, y, dict(q, tpu_quant_hist_bits=8), n_iter=2)
        assert not b._gbdt._quant_narrow_active   # warned, 32-bit engine

    def test_hist_bits_in_leaf_thresholds(self):
        # reference semantics: narrow while count * qmax fits 2^15
        bits = hist_bits_in_leaf(jnp.asarray([100, 3000, 4000, 100000]), 9)
        np.testing.assert_array_equal(np.asarray(bits), [16, 16, 32, 32])

    def test_training_bit_identical_and_auto(self):
        X, y = _higgs_like(2500, 8, seed=5)
        q = {"use_quantized_grad": True, "num_grad_quant_bins": 8}
        b32 = _train(X, y, dict(q, tpu_quant_hist_bits=32))
        b16 = _train(X, y, dict(q, tpu_quant_hist_bits=16))
        b_auto = _train(X, y, dict(q))
        assert b16._gbdt._quant_narrow_active
        assert not b32._gbdt._quant_narrow_active
        # auto keeps the int8 engine (narrow is the measured opt-in —
        # the sweep shows its radix-capped chunks lose at B <= 64)
        assert not b_auto._gbdt._quant_narrow_active
        np.testing.assert_array_equal(b32.predict(X), b16.predict(X))
        np.testing.assert_array_equal(b32.predict(X), b_auto.predict(X))


# --------------------------------------------------- bins-on-sublanes
class TestSublaneLayout:
    @pytest.mark.parametrize("mbatch", [1, 4])
    def test_pallas_sublane_int8_bit_identical(self, mbatch):
        rng = np.random.RandomState(4)
        n, f, b = 900, 6, 16
        binned = rng.randint(0, b, (n, f)).astype(np.uint8)
        codes = np.stack([rng.randint(-5, 6, n), rng.randint(0, 6, n),
                          np.ones(n), np.ones(n)], axis=1).astype(np.int8)
        lane = pallas_histogram(jnp.asarray(binned), jnp.asarray(codes), b,
                                mode="int8", interpret=True, mbatch=mbatch,
                                row_block=256)
        sub = pallas_histogram(jnp.asarray(binned), jnp.asarray(codes), b,
                               mode="int8", interpret=True, mbatch=mbatch,
                               row_block=256, hist_layout="sublane")
        np.testing.assert_array_equal(np.asarray(lane), np.asarray(sub))

    def test_pallas_sublane_split_close(self):
        rng = np.random.RandomState(5)
        n, f, b = 900, 6, 64
        binned = rng.randint(0, b, (n, f)).astype(np.uint8)
        ch = rng.randn(n, 4).astype(np.float32)
        lane = np.asarray(pallas_histogram(
            jnp.asarray(binned), jnp.asarray(ch), b, interpret=True,
            row_block=256))
        sub = np.asarray(pallas_histogram(
            jnp.asarray(binned), jnp.asarray(ch), b, interpret=True,
            row_block=256, hist_layout="sublane"))
        np.testing.assert_allclose(lane, sub, rtol=3e-3, atol=1e-4)

    def test_pallas_sublane_rejects_wide_bins(self):
        with pytest.raises(ValueError):
            pallas_histogram(jnp.zeros((256, 2), jnp.uint8),
                             jnp.zeros((256, 4), jnp.float32), 128,
                             interpret=True, hist_layout="sublane")

    @pytest.mark.parametrize("quant", [False, True])
    def test_fused_sublane_matches_lane(self, quant):
        rng = np.random.RandomState(6)
        n, f, b, bs = 1408 - 37, 5, 16, 128
        binned = rng.randint(0, b, (n, f)).astype(np.uint8)
        if quant:
            g = rng.randint(-8, 9, n).astype(np.float32)
            h = rng.randint(0, 9, n).astype(np.float32)
        else:
            g = rng.randn(n).astype(np.float32)
            h = (rng.rand(n) + 0.5).astype(np.float32)
        cnt = (rng.rand(n) > 0.25).astype(np.float32)
        layout = RowLayout(num_features=f, num_extra=1)
        extras = np.zeros((1, n), np.float32)
        work = pack_rows(jnp.asarray(binned), jnp.asarray(g),
                         jnp.asarray(h), jnp.asarray(cnt),
                         jnp.asarray(extras), layout, pad_rows=bs + 32)
        zero = jnp.asarray(0, I32)

        def run(hist_layout):
            _, _, hist = fused_split(
                work, jnp.zeros_like(work), jnp.asarray(1, I32), zero,
                jnp.asarray(n, I32), zero, zero, zero, zero, zero, zero,
                jnp.zeros((1,), jnp.uint32), layout, b, bs, 1,
                interpret=True, num_rows=n, quant=quant, mbatch=4,
                hist_layout=hist_layout)
            return np.asarray(hist)

        lane, sub = run("lane"), run("sublane")
        if quant:
            np.testing.assert_array_equal(lane, sub)
        else:
            np.testing.assert_array_equal(lane[:, :, 2:], sub[:, :, 2:])
            np.testing.assert_allclose(lane, sub, rtol=3e-3, atol=1e-4)

    def test_training_sublane_fused_interpret(self):
        """End-to-end: sublane fused training reproduces lane training
        (counts drive partitions, so trees must match exactly)."""
        X, y = _higgs_like(1203, 6, seed=9)
        extra = {"tpu_fused_interpret": True, "tpu_fused_block": 128,
                 "tpu_hist_mbatch": 4, "use_quantized_grad": True,
                 "num_grad_quant_bins": 8}
        b_lane = _train(X, y, dict(extra), n_iter=3)
        b_sub = _train(X, y, dict(extra, tpu_hist_layout="sublane"),
                       n_iter=3)
        assert b_sub._gbdt.grower_params.hist_layout == "sublane"
        np.testing.assert_array_equal(b_lane.predict(X), b_sub.predict(X))

    def test_layout_knob_validation(self):
        from lightgbm_tpu.boosting.gbdt import _pick_hist_layout
        assert _pick_hist_layout({"tpu_hist_layout": "auto"}, 256) == "lane"
        assert _pick_hist_layout({"tpu_hist_layout": "sublane"}, 64) \
            == "sublane"
        # wide bins cannot lay on sublanes — warn + lane
        assert _pick_hist_layout({"tpu_hist_layout": "sublane"}, 256) \
            == "lane"
        assert _pick_hist_layout({"tpu_hist_layout": "bogus"}, 64) == "lane"


# ------------------------------------------------------ steady-state guard
def test_steady_state_guard_with_pack4_training():
    """5 post-warmup compact iterations with tpu_bin_pack4=true: zero
    lowerings, zero backend compiles, zero d2h transfers — the packed bin
    matrix must not smuggle a host round trip or a shape-driven recompile
    into the training loop."""
    X, y = _higgs_like(1200, 8, seed=17)
    params = dict(BASE, tpu_bin_pack4=True)
    ds = lgb.Dataset(X, label=y, params=params)
    bst = lgb.Booster(params, ds)
    for _ in range(2):
        bst.update()
    assert bst._gbdt._compact["layout"].packed4
    with guards.steady_state_guard("5 pack4 iterations") as cc:
        for _ in range(5):
            bst.update()
    assert cc.lowerings == 0
    assert cc.backend_compiles == 0
    bst._gbdt._flush_trees()
    assert bst._gbdt.num_total_trees >= 7


def test_steady_state_guard_with_narrowed_quant():
    """Per-leaf hist-bits narrowing is a lax.cond inside one compiled
    program — leaves crossing the 16/32-bit threshold at run time must not
    trigger recompiles or host syncs."""
    X, y = _higgs_like(1500, 8, seed=19)
    params = dict(BASE, use_quantized_grad=True, num_grad_quant_bins=8,
                  tpu_quant_hist_bits=16)
    ds = lgb.Dataset(X, label=y, params=params)
    bst = lgb.Booster(params, ds)
    for _ in range(2):
        bst.update()
    assert bst._gbdt._quant_narrow_active
    with guards.steady_state_guard("5 narrowed iterations") as cc:
        for _ in range(5):
            bst.update()
    assert cc.lowerings == 0
    assert cc.backend_compiles == 0
