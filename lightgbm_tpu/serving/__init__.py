"""Resilient serving layer over the bucketed inference engine.

ROADMAP item 3's service tier (the robustness analogue of PR 7, aimed at
inference): an async micro-batch coalescer that aggregates concurrent
small ``predict`` requests into one rung-sized device batch per tick
(riding the zero-recompile bucket ladder of ops/predict.py and the
Booster rwlock), bounded admission with structured load shedding,
per-request deadlines, a pre-warmed multi-model registry with atomic
hot-swap and automatic rollback, and health/readiness probes. CLI entry:
``scripts/serve``.

Entry point: ``Booster.serve(...)`` or :class:`PredictionServer`
directly. See README "Serving".
"""
from .coalescer import MicroBatchCoalescer, ServeFuture
from .errors import (ServerClosed, ServerOverloaded, ServingError,
                     ServingTimeout, SwapFailed)
from .registry import ModelRegistry
from .server import PredictionServer

__all__ = [
    "PredictionServer", "ModelRegistry", "MicroBatchCoalescer",
    "ServeFuture", "ServingError", "ServingTimeout", "ServerOverloaded",
    "ServerClosed", "SwapFailed",
]
