"""Multi-model registry: pre-warmed atomic hot-swap with auto-rollback.

The mid-flight model replacement problem: on TPU a cold model is not
just "slower for a moment" — an unwarmed bucket ladder means every rung
the coalescer hits pays an XLA compile IN the request path (the 26-97 s
serving stalls BENCH_SHAPES.json recorded before the bucketed engine).
So a deploy here is warm-then-flip, never flip-then-warm:

  1. the candidate's FULL predict ladder is pre-compiled while the old
     model keeps serving (``Booster.warm_predict_ladder``; with
     ``tpu_compile_cache_dir`` armed the programs come out of the
     persistent cache with zero backend compiles on a restarted server);
  2. a health-check request must produce finite outputs;
  3. only then does the active pointer flip — one write under the same
     reader-writer lock discipline the Booster API uses
     (utils/rwlock.RWLock), guarded by a deadline watchdog
     (parallel/multihost.run_with_deadline) and an epoch token so a
     commit abandoned past its deadline can NEVER land later.

A failure anywhere — warmup raise, non-finite health probe, a hang past
the swap deadline (injected ``hang@swap``) — raises a structured
:class:`SwapFailed` and leaves the registry exactly as it was: the old
model stays active, live traffic never notices. ``rollback()`` restores
the previously active version on demand (bad-canary escape hatch).
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..analysis.faultinject import active_plan
from ..obs import flight
from ..utils import log
from ..utils.rwlock import RWLock
from .errors import ServingError, SwapFailed


class ModelRegistry:
    """Versioned boosters with one atomic ``active`` pointer."""

    def __init__(self):
        self._lock = RWLock()
        # serializes the token+commit phase of deploy (NOT the long
        # warmup, which stays concurrent): without it, two concurrent
        # deploys of DIFFERENT versions would stomp each other's commit
        # token and one would spuriously report "superseded"
        self._deploy_mu = threading.Lock()
        self._models: Dict[str, Any] = {}       # version -> Booster
        self._warm: Dict[str, Dict] = {}        # version -> warmup stats
        self._active: Optional[str] = None
        self._previous: Optional[str] = None
        self._commit_token: Optional[object] = None
        self.swaps = 0
        self.failed_swaps = 0

    # -- reads ---------------------------------------------------------------
    def active(self) -> Tuple[str, Any]:
        """(version, booster) snapshot — the per-tick model pin. A batch
        served from one snapshot is never split across models."""
        with self._lock.read():
            if self._active is None:
                raise ServingError("no active model deployed")
            return self._active, self._models[self._active]

    def get(self, version: str):
        with self._lock.read():
            return self._models[version]

    def versions(self) -> List[str]:
        with self._lock.read():
            return sorted(self._models)

    def active_version(self) -> Optional[str]:
        with self._lock.read():
            return self._active

    def warm_stats(self, version: Optional[str] = None) -> Optional[Dict]:
        with self._lock.read():
            v = version if version is not None else self._active
            return self._warm.get(v)

    def is_warm(self, version: Optional[str] = None) -> bool:
        stats = self.warm_stats(version)
        return bool(stats) and bool(stats.get("rungs"))

    # -- deploy / swap -------------------------------------------------------
    def deploy(self, version: str, booster, *, warm: bool = True,
               warm_max_rows: Optional[int] = None,
               health_check: bool = True,
               deadline_s: float = 30.0,
               prepare_drift: Optional[bool] = None) -> Dict:
        """Register ``booster`` as ``version`` and atomically make it
        active. Returns the candidate's warmup stats.

        The candidate is validated (device-servable), warmed, and
        health-checked BEFORE the commit; any failure raises
        :class:`SwapFailed` with the registry untouched. The commit
        itself runs under a ``deadline_s`` watchdog — a commit that
        hangs (``hang@swap``) is abandoned via an epoch token, so it can
        never flip the pointer after the deadline fired."""
        if version in self._models and self._models[version] is not booster:
            self.failed_swaps += 1
            raise SwapFailed(
                f"version {version!r} is already deployed with a "
                "different model; pick a new version string")
        try:
            inner = booster._device_serving_inner()
        except (NotImplementedError, AttributeError) as err:
            self.failed_swaps += 1
            raise SwapFailed(
                f"candidate {version!r} cannot take the device serving "
                f"path: {err}") from err
        if str(inner.config.get("tpu_predict_engine",
                                "batched")).lower() == "scan":
            # the scan escape hatch recompiles per request shape by
            # design — a server on it could never reach readiness (no
            # warmable ladder), so refuse up front instead of standing
            # up a permanently not-ready service
            self.failed_swaps += 1
            raise SwapFailed(
                f"candidate {version!r} uses tpu_predict_engine=scan "
                "(the per-shape-recompile parity path); the serving "
                "layer requires the batched engine")
        plan = active_plan(inner.config)
        warm_stats: Dict = {"rungs": [], "seconds": 0.0}
        try:
            if warm:
                warm_stats = booster.warm_predict_ladder(
                    max_rows=warm_max_rows)
            drift_armed = (prepare_drift if prepare_drift is not None
                           else int(inner.config.get(
                               "tpu_drift_flush_every", 0) or 0) > 0)
            if drift_armed:
                # the drift reference SHIPS with the model: materialize
                # the training-data bin-occupancy baseline AND the host
                # copy of the training margins here in the warm phase
                # (both cache), so the post-swap monitor attach — and
                # therefore the commit flip — never stalls on a
                # full-data occupancy pass. ``prepare_drift`` carries
                # the server's arming decision (per-server overrides
                # the config knob alone would miss)
                inner.drift_reference()
            if health_check:
                self._health_check(booster, version)
        except Exception as err:
            self.failed_swaps += 1
            flight.note("swap_failed", version=version, stage="warmup",
                        error=repr(err)[:300])
            raise SwapFailed(
                f"candidate {version!r} failed pre-swap warmup/health "
                f"check: {err}") from err

        self._deploy_mu.acquire()       # commit phase: one deploy at a
        #                                 time (warmup above ran outside)
        token = object()
        with self._lock.write():
            self._commit_token = token

        def _commit():
            # the hang/kill injection point sits INSIDE the deadline
            # watchdog, before the flip — the rollback contract under test
            plan.fire("swap", version=version)
            with self._lock.write():
                if self._commit_token is not token:
                    raise SwapFailed(
                        f"swap to {version!r} superseded after its "
                        "deadline; not committing")
                # re-verify the version guard UNDER the lock: the
                # unlocked pre-check races with a concurrent deploy of
                # the same version string during the (long) warmup phase
                if version in self._models \
                        and self._models[version] is not booster:
                    raise SwapFailed(
                        f"version {version!r} was deployed concurrently "
                        "with a different model; pick a new version "
                        "string")
                self._models[version] = booster
                self._warm[version] = warm_stats
                if self._active != version:
                    self._previous = self._active
                self._active = version
                self._commit_token = None

        from ..parallel.multihost import run_with_deadline
        try:
            run_with_deadline(_commit, deadline_s,
                              f"model swap to {version!r}")
        except BaseException as err:
            with self._lock.write():
                # a commit can outlive its deadline by a hair: the
                # watchdog fires while the worker is already inside the
                # write section (we block on it here, so by this read it
                # has finished) — if the flip actually LANDED, report
                # success instead of a phantom rollback that would leave
                # callers (and the server's post-swap rebinding) pinned
                # to a model that is no longer serving
                committed = (self._commit_token is not token
                             and self._models.get(version) is booster
                             and self._active == version)
                if self._commit_token is token:
                    # invalidate the abandoned commit worker: even if its
                    # thread wakes up later, the token check refuses it
                    self._commit_token = None
            if not committed:
                self.failed_swaps += 1
                log.warning(f"[serving] swap to {version!r} rolled back: "
                            f"{err!r}")
                # a blown swap is one of the three flight-dump sites: the
                # ring at this moment names the fault/deadline that killed
                # the commit (analysis/faultinject hang@swap included)
                flight.note("swap_failed", version=version,
                            error=repr(err)[:300])
                flight.dump(f"swap to {version!r} failed")
                if not isinstance(err, Exception):
                    raise               # injected kill: process-fatal
                raise SwapFailed(
                    f"swap to {version!r} did not commit (previous model "
                    f"stays active): {err}") from err
            log.warning(f"[serving] swap to {version!r} committed at the "
                        f"deadline edge ({err!r}); treating as success")
        finally:
            self._deploy_mu.release()
        self.swaps += 1
        flight.note("swap_committed", version=version,
                    rungs=len(warm_stats.get("rungs") or []),
                    endpoints=",".join(warm_stats.get("endpoints") or ()))
        log.info(f"[serving] model {version!r} active "
                 f"(warmed rungs: {warm_stats.get('rungs')}, endpoints: "
                 f"{warm_stats.get('endpoints')})")
        return warm_stats

    def _health_check(self, booster, version: str) -> None:
        """One probe row through the full serving path must be finite."""
        n_feat = booster._gbdt.train_set.num_total_features
        out, n = booster.predict_serving(np.zeros((1, n_feat), np.float32))
        if not np.all(np.isfinite(np.asarray(out)[:n])):
            raise ValueError(
                f"health check produced non-finite predictions for "
                f"{version!r}")

    def warm_active(self, max_rows: Optional[int] = None) -> Dict:
        """Warm (or re-warm) the ACTIVE model's ladder and record the
        stats — the path for servers started with ``warm=False`` to
        reach readiness, and for re-warming after a ladder change."""
        version, booster = self.active()
        stats = booster.warm_predict_ladder(max_rows=max_rows)
        with self._lock.write():
            self._warm[version] = stats
        return stats

    # -- rollback ------------------------------------------------------------
    def rollback(self) -> str:
        """Re-activate the previously active version (bad-canary escape
        hatch); returns the version now active."""
        with self._lock.write():
            if self._previous is None or self._previous not in self._models:
                raise ServingError("no previous model version to roll "
                                   "back to")
            self._active, self._previous = self._previous, self._active
            log.warning(f"[serving] rolled back to model "
                        f"{self._active!r}")
            return self._active

    def retire(self, version: str) -> None:
        """Drop a non-active version from the registry."""
        with self._lock.write():
            if version == self._active:
                raise ServingError(
                    f"cannot retire the active version {version!r}; "
                    "deploy or roll back to another model first")
            self._models.pop(version, None)
            self._warm.pop(version, None)
            if self._previous == version:
                self._previous = None
