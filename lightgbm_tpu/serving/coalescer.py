"""Async micro-batch coalescer: many small requests, one device batch.

The serving tentpole's core loop. Concurrent ``predict`` requests land in
a BOUNDED queue (admission control: a submit that would exceed
``tpu_serve_queue_max`` rows raises :class:`ServerOverloaded` instead of
growing latency without bound); a single worker thread wakes per tick,
sweeps expired requests into :class:`ServingTimeout`, pops a batch no
larger than the largest WARMED ladder rung, and hands it to the server's
serve callback as ONE device dispatch. The reference serves single rows
through its dedicated fast-path configs
(``LGBM_BoosterPredictFor*SingleRowFast``, src/c_api.cpp); on TPU the
same workload wants the opposite shape — aggregate rows until they fill
a bucket rung, because the rung, not the row, is the unit the compiled
program serves for free.

Resilience contract:

  * every admitted request is COMPLETED exactly once — with a response
    from exactly one model version, or with a structured error
    (timeout/closed/serving failure). Nothing hangs;
  * a slow tick (injected ``hang@coalesce_tick``) converts into load
    shedding at the admission edge, never into an unbounded queue;
  * a killed worker (injected ``kill@coalesce_tick``) fails its in-flight
    batch structurally and RESPAWNS — the queue keeps draining
    (``worker_restarts`` in the stats records it);
  * ``close(drain=True)`` stops admission, serves everything already
    queued, then joins the worker (the one deliberate blocking wait in
    the serving layer — R008 allowlist anchor).
"""
from __future__ import annotations

import collections
import copy
import threading
import time
from typing import Callable, List, Optional

from ..analysis.faultinject import active_plan
from ..analysis.guards import compile_phase
from ..obs import flight
from ..obs.spans import span
from ..utils import log
from .errors import (ServerClosed, ServerOverloaded, ServingError,
                     ServingTimeout)


class ServeFuture:
    """Completion handle for one submitted request.

    ``result()`` is deadline-bounded by construction: with no explicit
    ``timeout`` it waits until the request's own deadline plus a small
    grace window (the server guarantees a structured completion by then),
    so no caller of the serving API can block forever (tpulint R008)."""

    #: extra wait past the request deadline before result() gives up —
    #: covers the tick that is busy serving when the deadline passes
    _GRACE_S = 5.0

    def __init__(self, arr, deadline_s: Optional[float],
                 deadline_ms: float, kind: str = "predict"):
        self.arr = arr                      # [n, F] float request rows
        self.n = int(arr.shape[0])
        self.kind = kind                    # predict | leaf | contrib
        self.deadline = (time.monotonic() + deadline_s
                         if deadline_s is not None else None)
        self.deadline_ms = deadline_ms
        self.version = None                 # model version that answered
        self.created_at = time.monotonic()
        # latency-attribution stamps (obs/drift.ServingObserver):
        # popped_at when the tick cuts this request out of the queue,
        # served_at when the device response is host-materialized —
        # queue-wait / featurize+dispatch / slice-return fall out
        self.popped_at: Optional[float] = None
        self.served_at: Optional[float] = None
        self.completed_at: Optional[float] = None
        self._value = None
        self._error: Optional[BaseException] = None
        self._event = threading.Event()
        self._mu = threading.Lock()     # completion CAS: exactly one
        #                                 outcome wins (worker vs the
        #                                 client-side timeout in result)

    # -- completion (worker side) -------------------------------------------
    def _complete(self, version, value) -> None:
        with self._mu:
            if self._event.is_set():
                return
            self.version = version
            self._value = value
            self.arr = None     # release the request rows: callers keep
            #                     futures around for latency/version stats
            self.completed_at = time.monotonic()
            self._event.set()

    def _fail(self, err: BaseException) -> None:
        with self._mu:
            if self._event.is_set():
                return
            self._error = err
            # arr is NOT cleared here: a client-side result() timeout may
            # fire while this future sits in a popped in-flight batch,
            # and the worker still concatenates from arr — only
            # _complete (the worker, done with the rows) releases it
            self.completed_at = time.monotonic()
            self._event.set()

    # -- consumption (client side) ------------------------------------------
    def done(self) -> bool:
        return self._event.is_set()

    @property
    def latency_s(self) -> Optional[float]:
        if self.completed_at is None:
            return None
        return self.completed_at - self.created_at

    def phase_times(self) -> Optional[dict]:
        """Per-request latency attribution: ``queue_wait_s`` (submit ->
        popped into a tick), ``serve_s`` (featurize + device dispatch +
        host materialization), ``complete_s`` (per-request slice/copy +
        completion). None until the request reached a tick (sheds and
        queue-expired timeouts never did).

        Stamps are clamped into ``created <= popped <= served <=
        completed`` order: a client-side result() timeout can complete
        the future BEFORE the worker stamps served_at (the completion
        CAS), and un-clamped that would feed negative phase seconds into
        the cumulative gauges."""
        if self.completed_at is None or self.popped_at is None:
            return None
        done = self.completed_at
        popped = min(self.popped_at, done)
        served = done if self.served_at is None \
            else min(max(self.served_at, popped), done)
        return {"queue_wait_s": popped - self.created_at,
                "serve_s": served - popped,
                "complete_s": done - served}

    def result(self, timeout: Optional[float] = None):
        if timeout is None:
            if self.deadline is not None:
                timeout = max(self.deadline - time.monotonic(), 0.0) \
                    + self._GRACE_S
            else:
                timeout = 60.0          # bounded even without a deadline
        if not self._event.wait(timeout):
            # record the timeout AS the future's outcome (CAS: if the
            # worker completes in this same instant, its result stands) —
            # client-visible state and the future never disagree
            self._fail(ServingTimeout("request", self.deadline_ms
                                      or timeout * 1000.0))
        if self._error is not None:
            # a FRESH copy per raise: concurrent/repeated result() calls
            # must not mutate one shared instance's __traceback__ across
            # threads (errors carry __reduce__ state for exact copies)
            raise copy.copy(self._error)
        return self._value


class MicroBatchCoalescer:
    """The bounded queue + tick worker behind a PredictionServer.

    ``serve_batch`` is called from the worker thread with a non-empty
    list of :class:`ServeFuture` and must complete every one of them
    (the server's implementation snapshots ONE model version per call,
    so a batch is never split across models)."""

    def __init__(self, serve_batch: Callable[[List[ServeFuture]], None],
                 *, tick_ms: float, queue_max_rows: int,
                 max_batch_rows: int, fault_config=None,
                 name: str = "serve", observer=None,
                 background_kinds=()):
        if queue_max_rows < 1:
            raise ValueError("tpu_serve_queue_max must be >= 1 row")
        if max_batch_rows < 1:
            raise ValueError("max_batch_rows must be >= 1")
        self._serve_batch = serve_batch
        self._tick_s = max(float(tick_ms), 0.0) / 1000.0
        self._queue_max_rows = int(queue_max_rows)
        self._max_batch_rows = int(max_batch_rows)
        self._fault_config = fault_config
        # quality-plane hook (obs/drift.ServingObserver): on_future_done
        # per completed/failed future, on_tick_served per served tick
        # (the drift-flush cadence). Best-effort: observer failures must
        # never fail serving (_notify swallows + warns once)
        self._observer = observer
        self._observer_warned = False
        # background-tier request kinds (tpu_serve_background_kinds):
        # a background request only cuts a tick's batch when NO live
        # foreground request is queued — explanation (contrib) traffic
        # soaks idle ticks without touching predict/leaf latency
        self._background_kinds = frozenset(background_kinds)
        self._cv = threading.Condition()
        # each request holds >= 1 row and admission rejects past the row
        # bound first, so maxlen (a hard REQUEST cap) is never the
        # mechanism that drops — it is the structural guarantee R008 asks
        # for: no unbounded request queue in a serving path
        self._q = collections.deque(maxlen=self._queue_max_rows)
        self._rows = 0                      # rows currently queued
        self._closing = False
        self._closed = False
        self.stats = {
            "submitted": 0, "served_requests": 0, "served_rows": 0,
            "ticks": 0, "shed": 0, "timeouts": 0, "errors": 0,
            "worker_restarts": 0, "max_queue_rows": 0,
            # per-endpoint-kind breakdown (ticks pop homogeneous-kind
            # batches, so every counter keys cleanly); the flat keys
            # above stay the aggregates for compatibility
            "kinds": {},
        }
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"lgbm-tpu-{name}-coalescer")
        self._thread.start()

    # -- admission (any thread) ---------------------------------------------
    def submit(self, arr, deadline_s: Optional[float],
               deadline_ms: float, kind: str = "predict") -> ServeFuture:
        n = int(arr.shape[0])
        if n < 1:
            raise ValueError("empty request (0 rows)")
        if n > self._max_batch_rows:
            raise ValueError(
                f"request of {n} rows exceeds the largest warmed serving "
                f"rung ({self._max_batch_rows}); slice it or warm a "
                "larger ladder (tpu_serve_warm_max_rows / "
                "tpu_predict_buckets)")
        if n > self._queue_max_rows:
            # structurally unservable, not transient overload: admission
            # could NEVER accept it, even on an idle server
            raise ValueError(
                f"request of {n} rows exceeds the admission bound "
                f"(tpu_serve_queue_max={self._queue_max_rows}); slice it "
                "or raise the bound")
        fut = ServeFuture(arr, deadline_s, deadline_ms, kind)
        with self._cv:
            if self._closing or self._closed:
                raise ServerClosed("server is draining/closed; "
                                   "request rejected")
            self.stats["submitted"] += 1
            self._kstats(kind)["submitted"] += 1
            if self._rows + n > self._queue_max_rows:
                self.stats["shed"] += 1
                self._kstats(kind)["shed"] += 1
                raise ServerOverloaded(self._rows, self._queue_max_rows)
            self._q.append(fut)
            self._rows += n
            self.stats["max_queue_rows"] = max(
                self.stats["max_queue_rows"], self._rows)
            self._cv.notify_all()
        return fut

    def _kstats(self, kind: str) -> dict:
        """Per-endpoint-kind counter block (created on first use); must
        be called under ``self._cv``."""
        ks = self.stats["kinds"].get(kind)
        if ks is None:
            ks = self.stats["kinds"][kind] = {
                "submitted": 0, "served_requests": 0, "served_rows": 0,
                "shed": 0, "timeouts": 0, "errors": 0}
        return ks

    def stats_snapshot(self) -> dict:
        """Consistent deep copy of the counters (the nested per-kind
        blocks must not alias the live dicts a tick mutates)."""
        with self._cv:
            out = {k: v for k, v in self.stats.items() if k != "kinds"}
            out["kinds"] = {k: dict(v)
                            for k, v in self.stats["kinds"].items()}
            return out

    def _notify(self, fut: ServeFuture) -> None:
        """Hand one completed/failed future to the quality-plane
        observer; never from under ``self._cv``, never raising."""
        if self._observer is None:
            return
        try:
            self._observer.on_future_done(fut)
        except Exception as err:  # noqa: BLE001 - telemetry is best-effort
            if not self._observer_warned:
                self._observer_warned = True
                log.warning(f"[serving] observer failed ({err!r}); "
                            "further failures suppressed")

    def queue_depth_rows(self) -> int:
        with self._cv:
            return self._rows

    def worker_alive(self) -> bool:
        return self._thread.is_alive()

    @property
    def max_batch_rows(self) -> int:
        return self._max_batch_rows

    def set_max_batch_rows(self, rows: int) -> None:
        """Re-bound the per-tick batch after a model swap (the new active
        model's largest warmed rung)."""
        if rows < 1:
            raise ValueError("max_batch_rows must be >= 1")
        with self._cv:
            self._max_batch_rows = int(rows)

    def set_background_kinds(self, kinds) -> None:
        """Re-point the background lane after a model swap (the new
        active model's ``tpu_serve_background_kinds``)."""
        with self._cv:
            self._background_kinds = frozenset(kinds)

    def set_fault_config(self, config) -> None:
        """Re-point the coalesce_tick fault site at the new active
        model's config after a swap — a candidate carrying
        ``tpu_fault_spec`` must arm (and a disarmed one must not stay
        armed) from the moment it serves."""
        self._fault_config = config

    # -- worker -------------------------------------------------------------
    def _pop_batch(self) -> Optional[List[ServeFuture]]:
        """Next batch (possibly empty after a deadline sweep), or None to
        exit. Blocks in SHORT bounded waits so close() is always
        responsive."""
        swept: List[ServeFuture] = []
        batch = self._pop_batch_locked(swept)
        for r in swept:                 # observer runs OUTSIDE the lock
            self._notify(r)
        return batch

    def _pop_batch_locked(self, swept: List[ServeFuture]
                          ) -> Optional[List[ServeFuture]]:
        with self._cv:
            while not self._q:
                if self._closing:
                    return None
                self._cv.wait(timeout=0.05)
            if self._tick_s > 0 and not self._closing:
                # the coalescing window: let concurrent submitters join
                # this tick's batch before it is cut. Re-wait until the
                # FULL window elapses — each submit's notify would
                # otherwise cut the wait (and the batch) at the first
                # concurrent arrival — but cut immediately once the
                # queue already fills the batch (waiting longer can only
                # add latency: nothing more fits this tick)
                end = time.monotonic() + self._tick_s
                while not self._closing \
                        and self._rows < self._max_batch_rows:
                    remaining = end - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(timeout=remaining)
            now = time.monotonic()
            batch: List[ServeFuture] = []
            rows = 0
            bg = self._background_kinds
            # a background request only cuts a batch when no LIVE
            # foreground request is queued (expired ones sweep this pass
            # and must not pin the background lane another tick)
            has_fg = any(r.kind not in bg
                         and (r.deadline is None or now < r.deadline)
                         for r in self._q)
            kept: List[ServeFuture] = []
            stop = False
            while self._q:
                r = self._q.popleft()
                if stop:
                    kept.append(r)
                    continue
                if r.deadline is not None and now >= r.deadline:
                    self._rows -= r.n
                    self.stats["timeouts"] += 1
                    self._kstats(r.kind)["timeouts"] += 1
                    r._fail(ServingTimeout("request expired in queue",
                                           r.deadline_ms))
                    swept.append(r)
                    continue
                if r.n > self._max_batch_rows:
                    # admitted before a hot-swap shrank the warmed-rung
                    # bound: serving it now would compile in the request
                    # path — fail structurally instead
                    self._rows -= r.n
                    self.stats["errors"] += 1
                    self._kstats(r.kind)["errors"] += 1
                    r._fail(ServingError(
                        f"request of {r.n} rows exceeds the active "
                        f"model's largest warmed rung "
                        f"({self._max_batch_rows}) after a model swap; "
                        "resubmit in smaller slices"))
                    swept.append(r)
                    continue
                if bg and has_fg and r.kind in bg:
                    # background lane: skipped (in place, order kept)
                    # while foreground traffic is queued — it serves on
                    # the first tick with an empty foreground queue
                    kept.append(r)
                    continue
                if batch and r.kind != batch[0].kind:
                    # one endpoint per tick: a batch is ONE device
                    # dispatch, and predict/leaf/contrib are distinct
                    # programs — mixed traffic serves FIFO on
                    # consecutive ticks instead of splitting a tick
                    kept.append(r)
                    stop = True
                    continue
                if batch and rows + r.n > self._max_batch_rows:
                    kept.append(r)
                    stop = True             # next tick's batch
                    continue
                self._rows -= r.n
                r.popped_at = now
                batch.append(r)
                rows += r.n
            for r in reversed(kept):
                self._q.appendleft(r)
            return batch

    def _drain_loop(self) -> None:
        while True:
            batch = self._pop_batch()
            if batch is None:
                return
            if not batch:
                continue
            rows = sum(r.n for r in batch)
            kind = batch[0].kind            # ticks are kind-homogeneous
            # count BEFORE the futures complete: clients synchronize on
            # result(), so a stats read right after it must already see
            # this batch (rolled back below if the tick fails)
            with self._cv:
                self.stats["ticks"] += 1
                self.stats["served_requests"] += len(batch)
                self.stats["served_rows"] += rows
                ks = self._kstats(kind)
                ks["served_requests"] += len(batch)
                ks["served_rows"] += rows
            try:
                # the slow-tick / worker-kill injection point: fired
                # OUTSIDE the queue lock, so a hanging tick converts into
                # admission-side shedding, never into blocked submitters
                active_plan(self._fault_config).fire(
                    "coalesce_tick", requests=len(batch))
                # compiles in a tick are attributed to the serving phase
                # (a steady-state serving compile is a bug the metrics
                # plane must point at, not fold into a global count)
                with compile_phase("serving"), span("serve_tick"):
                    self._serve_batch(batch)
            except BaseException as err:  # noqa: BLE001 - classified below
                with self._cv:
                    self.stats["ticks"] -= 1
                    self.stats["served_requests"] -= len(batch)
                    self.stats["served_rows"] -= rows
                    self.stats["errors"] += 1
                    ks = self._kstats(kind)
                    ks["served_requests"] -= len(batch)
                    ks["served_rows"] -= rows
                    ks["errors"] += 1
                flight.note("serve_tick_error", requests=len(batch),
                            rows=rows, error=repr(err)[:200])
                # one FRESH exception per future: concurrent result()
                # raises would otherwise mutate a shared instance's
                # __traceback__/__context__ across client threads
                msg = (str(err) if isinstance(err, ServingError)
                       else f"serving tick failed: {err!r}")
                for r in batch:
                    r._fail(ServingError(msg))
                    self._notify(r)
                if not isinstance(err, Exception):
                    raise           # a worker kill: respawn boundary below
                continue
            # success: futures first (their latency/SLO outcomes), then
            # the tick boundary — the drift-flush cadence sees this
            # tick's window fully accumulated
            for r in batch:
                self._notify(r)
            if self._observer is not None:
                try:
                    self._observer.on_tick_served(kind)
                except Exception as err:  # noqa: BLE001 - best-effort
                    if not self._observer_warned:
                        self._observer_warned = True
                        log.warning(f"[serving] observer tick hook "
                                    f"failed ({err!r}); further failures "
                                    "suppressed")

    def _run(self) -> None:
        while True:
            try:
                self._drain_loop()
                return                      # clean drain/close exit
            except BaseException as err:  # noqa: BLE001 - supervisor
                # the injected worker kill (faultinject.SimulatedKill) or
                # an unexpected crash: the in-flight batch already failed
                # structurally in _drain_loop; respawn so the queue keeps
                # draining instead of wedging
                log.warning(f"[serving] worker died ({err!r}); respawning")
                flight.note("worker_restart", error=repr(err)[:200])
                with self._cv:
                    self.stats["worker_restarts"] += 1
                    if self._closing:
                        return

    # -- shutdown -----------------------------------------------------------
    def close(self, drain: bool = True,
              timeout_s: Optional[float] = None) -> None:
        """Stop admission; serve (``drain=True``) or fail (``False``)
        whatever is queued; join the worker. Safe to call twice."""
        with self._cv:
            if self._closed:
                return
            self._closing = True
            if not drain:
                while self._q:
                    r = self._q.popleft()
                    self._rows -= r.n
                    r._fail(ServerClosed("server closed before serving "
                                         "this request"))
            self._cv.notify_all()
        if timeout_s is not None:
            self._thread.join(timeout_s)
        else:
            # the deliberate blocking drain: every queued request is
            # served (or structurally failed) before close returns
            self._thread.join()             # R008 allowlist anchor: drain
        with self._cv:
            self._closed = True
            while self._q:                  # worker died / join timed out
                r = self._q.popleft()
                self._rows -= r.n
                r._fail(ServerClosed("server closed before serving this "
                                     "request"))
