"""``scripts/serve`` — stand up a PredictionServer from the command line.

A thin operational wrapper over the library API (the reference ships the
same split: ``lightgbm`` the CLI vs the C API serving entry points).
Trains (or auto-resumes, via ``tpu_checkpoint_dir``) a booster on a CSV,
pre-warms the serving ladder, then either:

  * ``--probe``: print the health/readiness JSON and exit 0 iff ready
    (the k8s-style readiness gate — wire it to your orchestrator); or
  * serve: read CSV feature rows from stdin (one request per line),
    micro-batch them through the coalescer, print one prediction per
    line; EOF drains gracefully and dumps the serving stats to stderr.

Example::

    scripts/serve train.csv --rounds 50 --param num_leaves=63 \
        --tick-ms 2 --deadline-ms 500 --probe
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def _parse_params(pairs: List[str]) -> dict:
    out: dict = {}
    for pair in pairs or ():
        key, sep, value = pair.partition("=")
        if not sep:
            raise SystemExit(f"--param expects key=value, got {pair!r}")
        for cast in (int, float):
            try:
                out[key] = cast(value)
                break
            except ValueError:
                continue
        else:
            out[key] = value
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="serve", description=__doc__.splitlines()[0])
    ap.add_argument("data", help="training CSV (label in --label-col)")
    ap.add_argument("--label-col", type=int, default=0,
                    help="label column index in the CSV (default 0)")
    ap.add_argument("--rounds", type=int, default=20,
                    help="boosting rounds to train before serving")
    ap.add_argument("--param", action="append", default=[],
                    help="extra training param key=value (repeatable), "
                         "e.g. --param objective=binary")
    ap.add_argument("--tick-ms", type=float, default=None,
                    help="coalescer tick (tpu_serve_tick_ms)")
    ap.add_argument("--queue-max", type=int, default=None,
                    help="admission bound in rows (tpu_serve_queue_max)")
    ap.add_argument("--deadline-ms", type=float, default=None,
                    help="per-request deadline (tpu_serve_deadline_ms)")
    ap.add_argument("--warm-max-rows", type=int, default=None,
                    help="cap the warmed ladder rungs "
                         "(tpu_serve_warm_max_rows; 0 = full ladder)")
    ap.add_argument("--checkpoint-dir", default="",
                    help="tpu_checkpoint_dir: training auto-resumes from "
                         "the newest valid snapshot (PR 7) and, combined "
                         "with --compile-cache-dir, a restarted server "
                         "re-arms its ladder with zero backend compiles")
    ap.add_argument("--compile-cache-dir", default="",
                    help="tpu_compile_cache_dir: persistent XLA cache for "
                         "warmup across restarts")
    ap.add_argument("--raw-score", action="store_true",
                    help="serve raw scores (skip objective conversion)")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="expose GET /metrics (Prometheus text) and "
                         "/healthz on this port (0 = ephemeral; printed "
                         "to stderr at startup)")
    ap.add_argument("--probe", action="store_true",
                    help="print health JSON and exit 0 iff ready")
    args = ap.parse_args(argv)

    import numpy as np

    import lightgbm_tpu as lgb

    arr = np.loadtxt(args.data, delimiter=",", ndmin=2)
    y = arr[:, args.label_col]
    x = np.delete(arr, args.label_col, axis=1)
    params = {"verbosity": -1}
    params.update(_parse_params(args.param))
    if args.checkpoint_dir:
        params.setdefault("tpu_checkpoint_dir", args.checkpoint_dir)
        params.setdefault("tpu_checkpoint_freq",
                          max(args.rounds // 4, 1))
    if args.compile_cache_dir:
        params.setdefault("tpu_compile_cache_dir", args.compile_cache_dir)
    booster = lgb.train(params, lgb.Dataset(x, label=y, params=params),
                        num_boost_round=args.rounds)
    server = booster.serve(
        tick_ms=args.tick_ms, queue_max=args.queue_max,
        deadline_ms=args.deadline_ms, warm_max_rows=args.warm_max_rows,
        raw_score=args.raw_score, metrics_port=args.metrics_port)
    try:
        if server.metrics_port is not None:
            sys.stderr.write(f"[serve] metrics on "
                             f"http://127.0.0.1:{server.metrics_port}"
                             f"/metrics\n")
        health = server.health()
        if args.probe:
            print(json.dumps(health, indent=1, sort_keys=True, default=str))
            return 0 if health["ready"] else 1
        sys.stderr.write(
            f"[serve] ready={health['ready']} warm_rungs="
            f"{health['warm_rungs']}; reading CSV rows from stdin\n")
        for line in sys.stdin:
            line = line.strip()
            if not line:
                continue
            row = np.array([float(t) for t in line.split(",")],
                           np.float64)
            out = server.predict(row.reshape(1, -1))
            val = np.asarray(out).ravel()
            print(",".join(f"{v:.10g}" for v in val), flush=True)
        return 0
    finally:
        server.close(drain=True)
        sys.stderr.write(f"[serve] stats: {json.dumps(server.stats)}\n")


if __name__ == "__main__":
    sys.exit(main())
