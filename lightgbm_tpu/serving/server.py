"""PredictionServer: the resilient serving facade over one booster fleet.

Composes the three serving pieces — the bounded micro-batch coalescer
(coalescer.py), the pre-warmed hot-swap registry (registry.py), and the
device fast path (``Booster.predict_serving``) — into the service layer
ROADMAP item 3 asks for: concurrent small requests aggregate into one
rung-sized device batch per tick, admission is bounded, every request
carries a deadline, models swap atomically with rollback, and liveness
is observable through ``health()``/``ready()`` probes.

Typical use::

    server = booster.serve(tick_ms=2.0, deadline_ms=500)
    fut = server.submit(X_small)             # async, micro-batched
    y = fut.result()                         # == booster.predict(X_small)
    server.deploy("v2", retrained_booster)   # pre-warmed atomic swap
    server.close(drain=True)                 # graceful shutdown

Throughput/latency numbers live in BENCH_SHAPES.json["serving"]
(bench.py BENCH_SERVING=1).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from ..analysis import guards
from ..analysis.faultinject import active_plan
from ..obs.drift import ServingObserver
from ..ops.predict import parse_bucket_ladder, warmup_rungs
from .coalescer import MicroBatchCoalescer, ServeFuture
from .errors import ServerOverloaded, ServingError
from .registry import ModelRegistry


class PredictionServer:
    """Micro-batching, deadline-aware, hot-swappable serving front.

    The serving-quality plane (obs/drift.ServingObserver) rides along:
    per-request latency attribution histograms always; the on-device
    drift monitor when ``tpu_drift_flush_every > 0`` (or
    ``drift_flush_every=``), the SLO burn-rate tracker when
    ``tpu_serve_slo_ms > 0`` (or ``slo_ms=``)."""

    def __init__(self, booster=None, *, registry: Optional[ModelRegistry]
                 = None, version: str = "v0",
                 tick_ms: Optional[float] = None,
                 queue_max: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 warm: bool = True, warm_max_rows: Optional[int] = None,
                 raw_score: bool = False, swap_deadline_s: float = 30.0,
                 metrics_port: Optional[int] = None,
                 slo_ms: Optional[float] = None,
                 slo_target: Optional[float] = None,
                 drift_flush_every: Optional[int] = None,
                 drift_psi_threshold: Optional[float] = None):
        self._registry = registry if registry is not None else ModelRegistry()
        self._raw_score = bool(raw_score)
        self._swap_deadline_s = float(swap_deadline_s)
        self._closed = False
        self._mu = threading.Lock()
        if booster is not None:
            self._registry.deploy(
                version, booster, warm=warm,
                warm_max_rows=warm_max_rows,
                deadline_s=self._swap_deadline_s,
                prepare_drift=(drift_flush_every > 0
                               if drift_flush_every is not None else None))
        _, active = self._registry.active()     # requires a deployed model
        cfg = active._gbdt.config
        self._fault_config = cfg
        self._endpoints = active._serve_endpoints()
        tick_ms = (float(cfg.get("tpu_serve_tick_ms", 5.0))
                   if tick_ms is None else float(tick_ms))
        queue_max = (int(cfg.get("tpu_serve_queue_max", 8192))
                     if queue_max is None else int(queue_max))
        self._deadline_ms = (float(cfg.get("tpu_serve_deadline_ms", 1000.0))
                             if deadline_ms is None else float(deadline_ms))
        if warm_max_rows is None:
            warm_max_rows = int(cfg.get("tpu_serve_warm_max_rows", 0) or 0)
        self._warm_max_rows = warm_max_rows
        self._n_features = active._gbdt.train_set.num_total_features
        # the serving-quality plane: built BEFORE the coalescer (whose
        # worker notifies it) and attached to the active model after
        self._obs = ServingObserver(
            cfg, slo_ms=slo_ms, slo_target=slo_target,
            drift_flush_every=drift_flush_every,
            drift_psi_threshold=drift_psi_threshold)
        self._coalescer = MicroBatchCoalescer(
            self._serve_batch, tick_ms=tick_ms, queue_max_rows=queue_max,
            max_batch_rows=self._resolve_max_batch(active),
            fault_config=cfg, observer=self._obs,
            background_kinds=self._background_kinds(cfg))
        try:
            self._attach_obs_model()
            # metrics plane (obs/metrics.py): pull-based Prometheus text
            # over stdlib HTTP. None = off; 0 = ephemeral port
            # (.metrics_port tells)
            self._metrics_server = None
            if metrics_port is None:
                port_cfg = int(cfg.get("tpu_metrics_port", 0) or 0)
                metrics_port = port_cfg if port_cfg > 0 else None
            if metrics_port is not None:
                # a taken port must not take down SERVING: the coalescer
                # worker is already running — serve without the endpoint
                # instead (an explicit serve_metrics() call still raises,
                # the caller asked for that port specifically)
                try:
                    self.serve_metrics(metrics_port)
                except OSError as err:
                    from ..utils import log
                    log.warning(f"[serving] metrics port {metrics_port} "
                                f"unavailable ({err}); serving WITHOUT "
                                "the metrics endpoint")
        except BaseException:
            # the coalescer worker is already running: a raise in the
            # rest of __init__ (drift warm compile, a non-OSError from
            # serve_metrics) would orphan the thread with no handle to
            # close() — release everything acquired so far and re-raise
            # (R012 constructor exception edge)
            self._closed = True
            try:
                self._coalescer.close(drain=False)
            finally:
                ms = getattr(self, "_metrics_server", None)
                self._metrics_server = None
                if ms is not None:
                    ms.stop()
            raise

    @staticmethod
    def _background_kinds(cfg) -> frozenset:
        """Resolved ``tpu_serve_background_kinds``: request kinds demoted
        to the background tier (they only cut a coalescer tick when no
        foreground request is queued). ``predict`` can never be demoted
        — it is THE latency-path endpoint the tier protects."""
        from ..utils import log
        raw = str(cfg.get("tpu_serve_background_kinds", "") or "")
        kinds = {k.strip().lower() for k in raw.split(",") if k.strip()}
        unknown = kinds - {"leaf", "contrib"}
        if unknown:
            log.warning(f"unknown tpu_serve_background_kinds "
                        f"{sorted(unknown)}; valid: leaf, contrib "
                        "(predict cannot be demoted)")
            kinds -= unknown
        return frozenset(kinds)

    # -- batch bound ---------------------------------------------------------
    def _resolve_max_batch(self, booster, version: Optional[str] = None
                           ) -> int:
        """The largest batch a tick may cut: the given (default: active)
        model's largest WARMED rung (so steady state never compiles),
        falling back to the largest rung warmup WOULD cover when warm
        stats are absent (an unwarmed server pays its compiles in the
        first ticks)."""
        stats = self._registry.warm_stats(version)
        if stats and stats.get("rungs"):
            return int(max(stats["rungs"]))
        ladder = parse_bucket_ladder(
            booster._gbdt.config.get("tpu_predict_buckets", "auto"))
        return int(max(warmup_rungs(ladder, self._warm_max_rows)))

    # -- request path --------------------------------------------------------
    def submit(self, data, deadline_ms: Optional[float] = None,
               kind: str = "predict") -> ServeFuture:
        """Enqueue one request; returns its :class:`ServeFuture`.

        ``kind`` selects the endpoint: ``predict`` (scores), ``leaf``
        (per-tree leaf indices, reference PredictLeafIndex) or
        ``contrib`` (exact TreeSHAP contributions) — all through the
        same coalescer/deadline/ladder machinery, one device dispatch
        per tick. Endpoints are warmed per ``tpu_serve_endpoints``;
        submitting to an unlisted one raises structurally (serving it
        cold would compile in the request path).

        Raises structured errors at the admission edge:
        ``ServerOverloaded`` (bounded queue full), ``ServerClosed``
        (draining), ``ValueError`` (shape/size/endpoint). ``deadline_ms``
        overrides ``tpu_serve_deadline_ms``; ``<= 0`` disables the
        deadline for this request (the future still bounds its own
        ``result()`` wait)."""
        if kind not in self._endpoints:
            raise ValueError(
                f"endpoint {kind!r} is not enabled on the active model "
                f"(tpu_serve_endpoints={','.join(self._endpoints)}); "
                "serving it unwarmed would compile in the request path")
        active_plan(self._fault_config).fire("request")
        # snapshot the request: submit is async, and np.asarray aliases a
        # caller-owned buffer — a client reusing its buffer would
        # otherwise have queued requests served with overwritten rows.
        # float32 IS the serving wire format (predict_serving casts
        # anyway; copying f32 here halves the queue's footprint)
        arr = np.array(data, dtype=np.float32, copy=True)
        if arr.ndim == 1:
            arr = arr.reshape(1, -1)
        if arr.ndim != 2 or arr.shape[1] != self._n_features:
            raise ValueError(
                f"request shape {arr.shape} does not match the active "
                f"model's {self._n_features} features")
        if deadline_ms is None:
            deadline_ms = self._deadline_ms
        deadline_s = (deadline_ms / 1000.0) if deadline_ms > 0 else None
        try:
            return self._coalescer.submit(
                arr, deadline_s, deadline_ms if deadline_ms > 0 else 0.0,
                kind)
        except ServerOverloaded:
            # a shed IS a failed request from the client's side: it must
            # burn the SLO error budget even though no future exists.
            # Guarded like every observer hook — a telemetry failure
            # must not replace the structured error clients catch
            try:
                self._obs.on_shed(kind)
            except Exception:  # noqa: BLE001 - telemetry is best-effort
                pass
            raise

    def submit_leaf(self, data, deadline_ms: Optional[float] = None
                    ) -> ServeFuture:
        """Enqueue one ``pred_leaf`` request (leaf-index embeddings)."""
        return self.submit(data, deadline_ms, kind="leaf")

    def submit_contrib(self, data, deadline_ms: Optional[float] = None
                       ) -> ServeFuture:
        """Enqueue one ``pred_contrib`` request (exact TreeSHAP)."""
        return self.submit(data, deadline_ms, kind="contrib")

    def predict(self, data, deadline_ms: Optional[float] = None,
                timeout: Optional[float] = None):
        """Synchronous convenience: ``submit(...).result(...)`` —
        micro-batched with every other in-flight request, equal to the
        active booster's ``predict(float32(data))`` (float32 is the
        serving wire format; ``submit`` casts there)."""
        return self.submit(data, deadline_ms).result(timeout=timeout)

    def predict_leaf(self, data, deadline_ms: Optional[float] = None,
                     timeout: Optional[float] = None):
        """Synchronous ``pred_leaf``: equals the active booster's
        ``predict(float32(data), pred_leaf=True)``."""
        return self.submit_leaf(data, deadline_ms).result(timeout=timeout)

    def predict_contrib(self, data, deadline_ms: Optional[float] = None,
                        timeout: Optional[float] = None):
        """Synchronous ``pred_contrib``: the device TreeSHAP twin of the
        active booster's ``predict(float32(data), pred_contrib=True)``
        (matches within documented f32 tolerance)."""
        return self.submit_contrib(data, deadline_ms).result(timeout=timeout)

    def _serve_batch(self, batch) -> None:
        """One tick: pin ONE model snapshot, run the concatenated batch
        through the device engine at a warmed rung, slice per-request
        rows on the host. A request is never split across models; the
        coalescer pops homogeneous-kind batches, so one tick is one
        endpoint's single device dispatch."""
        version, booster = self._registry.active()
        rows = sum(r.n for r in batch)
        if rows > self._resolve_max_batch(booster, version):
            # the batch was cut under the PREVIOUS model's warmed-rung
            # bound and a swap landed before this pin: serving it would
            # compile in the request path (or overflow the new ladder) —
            # raise, and the coalescer fails every request structurally
            # (and counts the tick as an error, not as served)
            raise ServingError(
                f"batch of {rows} rows exceeds model {version!r}'s "
                "largest warmed rung (hot-swap landed mid-tick); "
                "resubmit")
        kind = batch[0].kind
        if kind not in booster._serve_endpoints():
            # admitted under the PREVIOUS model's endpoint set and a swap
            # landed before this pin: the new model never warmed this
            # kind's programs, so serving it would compile in the request
            # path — fail structurally, like the oversized-rows case
            raise ServingError(
                f"endpoint {kind!r} is not enabled on model {version!r} "
                "(hot-swap landed mid-queue); resubmit against the new "
                "model's tpu_serve_endpoints")
        if len(batch) == 1:
            x = batch[0].arr
        else:
            x = np.concatenate([r.arr for r in batch], axis=0)
        # drift window: the tick's binned matrix (and, for predict, the
        # raw margins) fold into the active monitor's device accumulators
        # — only when the monitor matches this tick's pinned version (a
        # swap landing mid-queue must not mix models' windows)
        drift = self._obs.drift_for(version)
        if kind == "leaf":
            out, _ = booster.predict_leaf_serving(x, observe=drift)
        elif kind == "contrib":
            out, _ = booster.predict_contrib_serving(x, observe=drift)
        else:
            out, _ = booster.predict_serving(x, raw_score=self._raw_score,
                                             observe=drift)
        # latency attribution: `out` is host-materialized above (the
        # serving calls return numpy), so this stamp brackets completed
        # device work — R009 allowlist anchor, not an async-dispatch lie
        served_at = time.monotonic()
        off = 0
        for r in batch:
            r.served_at = served_at
            # copy: the padded rung buffer must not stay pinned by views
            r._complete(version, np.array(out[off:off + r.n]))
            off += r.n

    # -- model management ----------------------------------------------------
    def deploy(self, version: str, booster, *, warm: bool = True,
               deadline_s: Optional[float] = None) -> Dict:
        """Pre-warm ``booster`` and atomically hot-swap it in (see
        ModelRegistry.deploy); live traffic keeps flowing on the old
        model until the commit lands, and a failed warmup/health check/
        deadline rolls back automatically."""
        stats = self._registry.deploy(
            version, booster, warm=warm, warm_max_rows=self._warm_max_rows,
            deadline_s=self._swap_deadline_s if deadline_s is None
            else float(deadline_s),
            # this server's drift arming (per-server override included)
            # decides whether the candidate's reference distributions
            # must materialize in the warm phase — the config knob alone
            # would miss booster.serve(drift_flush_every=...) servers
            prepare_drift=self._obs.flush_every > 0)
        self._after_model_change()
        return stats

    def rollback(self) -> str:
        """Re-activate the previously active model version."""
        v = self._registry.rollback()
        self._after_model_change()
        return v

    def warm(self) -> Dict:
        """Warm the active model's ladder now (servers constructed with
        ``warm=False`` are not ready() until this runs)."""
        stats = self._registry.warm_active(self._warm_max_rows)
        self._after_model_change()
        return stats

    def _after_model_change(self) -> None:
        _, active = self._registry.active()
        with self._mu:
            self._n_features = active._gbdt.train_set.num_total_features
            self._fault_config = active._gbdt.config
            self._endpoints = active._serve_endpoints()
            self._coalescer.set_fault_config(active._gbdt.config)
            self._coalescer.set_max_batch_rows(
                self._resolve_max_batch(active))
            self._coalescer.set_background_kinds(
                self._background_kinds(active._gbdt.config))
        self._attach_obs_model()

    def _attach_obs_model(self) -> None:
        """(Re)point the quality plane at the active model: fresh drift
        reference + warmed accumulate programs per warmed rung."""
        version, active = self._registry.active()
        warm = self._registry.warm_stats(version) or {}
        self._obs.attach_model(version, active, warm.get("rungs") or [])

    @property
    def registry(self) -> ModelRegistry:
        return self._registry

    # -- probes --------------------------------------------------------------
    def health(self) -> Dict[str, Any]:
        """Liveness/readiness snapshot: device reachability, warm-program
        presence, queue depth, counters. Never raises — a health probe
        must answer during the exact failures it exists to surface."""
        device = guards.device_healthcheck()
        active = self._registry.active_version()
        warm = self._registry.warm_stats(active) or {}
        stats = self._coalescer.stats_snapshot()
        ready = bool(device["ok"] and active is not None
                     and warm.get("rungs") and not self._closed
                     and self._coalescer.worker_alive())
        return {
            "ready": ready,
            "closed": self._closed,
            "device": device,
            "active_version": active,
            "versions": self._registry.versions(),
            "warm_rungs": list(warm.get("rungs") or []),
            "endpoints": list(self._endpoints),
            "queue_depth_rows": self._coalescer.queue_depth_rows(),
            "max_batch_rows": self._coalescer.max_batch_rows,
            "worker_alive": self._coalescer.worker_alive(),
            "swaps": self._registry.swaps,
            "failed_swaps": self._registry.failed_swaps,
            "stats": stats,
        }

    def ready(self) -> bool:
        """Readiness gate: device up, a warmed model active, worker
        alive, not draining."""
        return self.health()["ready"]

    # -- metrics plane -------------------------------------------------------
    def metrics(self) -> Dict[str, Any]:
        """The nested numeric view behind ``GET /metrics``: the health
        snapshot plus process-lifetime phase-keyed compile counts,
        persistent-cache counters, and the serving-quality scalars
        (drift flush/score summary, SLO burn rates) — one schema with
        the training metrics stream (same counter names, same
        attribution). The labeled series (per-feature PSI, latency
        histograms) ride the exposition text, not this tree."""
        out = self.health()
        out["compiles"] = guards.phase_compile_counts()
        out["compile_cache"] = guards.global_cache_counts()
        out["serving_obs"] = self._obs.snapshot()
        return out

    def metrics_text(self) -> str:
        """Prometheus text exposition of :meth:`metrics` plus the
        labeled serving-quality series (latency histograms per
        endpoint/version, per-feature drift PSI, SLO gauges)."""
        from ..obs import metrics as obs_metrics
        return (obs_metrics.render_prometheus(self.metrics())
                + self._obs.prometheus_text())

    def serve_metrics(self, port: int = 0) -> int:
        """Start the ``/metrics`` + ``/healthz`` HTTP endpoint; returns
        the bound port (``--metrics-port`` on ``scripts/serve``; ``0``
        binds an ephemeral port). Asking for a SPECIFIC port while the
        endpoint is already bound elsewhere raises — silently returning
        the old port would point the caller's scrape config at nothing."""
        from ..obs import metrics as obs_metrics
        with self._mu:          # check-then-create must not race: the
            #                     losing endpoint would leak its bound
            #                     port + thread with no handle to stop()
            if self._metrics_server is not None:
                bound = self._metrics_server.port
                if port not in (0, bound):
                    raise ValueError(
                        f"metrics endpoint already bound on port {bound}; "
                        f"cannot rebind to {port} (close() the server "
                        "first)")
                return bound
            self._metrics_server = obs_metrics.MetricsServer(
                self.metrics, port=port,
                text_extra=self._obs.prometheus_text)
            return self._metrics_server.port

    @property
    def metrics_port(self) -> Optional[int]:
        return None if self._metrics_server is None \
            else self._metrics_server.port

    @property
    def stats(self) -> Dict[str, Any]:
        return self._coalescer.stats_snapshot()

    @property
    def observer(self) -> ServingObserver:
        """The serving-quality plane: latency histograms, drift monitor
        (``observer.drift``), SLO tracker (``observer.slo``)."""
        return self._obs

    # -- lifecycle -----------------------------------------------------------
    def close(self, drain: bool = True,
              timeout_s: Optional[float] = None) -> None:
        """Graceful shutdown: stop admission, drain (or fail) the queue,
        join the worker, flush any pending drift window, stop the
        metrics endpoint."""
        self._closed = True
        self._coalescer.close(drain=drain, timeout_s=timeout_s)
        if not self._coalescer.worker_alive():
            # only after the worker actually exited: a timed-out join
            # (hung tick) leaves it running, and a concurrent final
            # flush would race its unsynchronized window accumulation
            self._obs.final_flush()
        with self._mu:
            # stop AND clear: a later serve_metrics() must bind fresh,
            # not report the port of a dead endpoint as already-bound
            ms, self._metrics_server = self._metrics_server, None
        if ms is not None:
            ms.stop()

    def __enter__(self) -> "PredictionServer":
        return self

    def __exit__(self, *exc) -> bool:
        self.close(drain=exc == (None, None, None))
        return False
