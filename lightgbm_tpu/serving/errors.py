"""Structured serving errors.

The resilient-serving contract (the inference analogue of PR 7's
``TrainingInterrupted``): overload, deadline misses, shutdown, and failed
hot-swaps surface as TYPED errors a caller can branch on, never as
unbounded latency or a wedged queue. The reference's C API reports the
same classes of failure through ``LGBM_GetLastError`` strings
(src/c_api.cpp API_BEGIN/API_END); here they are first-class exceptions.
"""
from __future__ import annotations


class ServingError(RuntimeError):
    """Base class for all structured serving failures."""


class ServingTimeout(ServingError):
    """A request's deadline passed before a response was produced.

    Raised by ``ServeFuture.result`` and attached to requests the
    coalescer sweeps out of the queue after their deadline (a slow tick
    must convert waiting into a bounded, typed failure)."""

    def __init__(self, what: str, deadline_ms: float):
        super().__init__(
            f"{what}: deadline of {deadline_ms:.0f} ms exceeded")
        self.what = what
        self.deadline_ms = deadline_ms

    def __reduce__(self):
        # copy/pickle must reconstruct through the real ctor (args holds
        # the FORMATTED message, not the ctor signature) — ServeFuture
        # raises a fresh copy per result() call
        return (type(self), (self.what, self.deadline_ms))


class ServerOverloaded(ServingError):
    """Admission control rejected a request: the bounded queue is full.

    Load shedding — the queue never grows past ``tpu_serve_queue_max``
    rows; callers back off or retry elsewhere instead of stacking
    unbounded latency onto every in-flight request."""

    def __init__(self, queued_rows: int, queue_max: int):
        super().__init__(
            f"serving queue full ({queued_rows}/{queue_max} rows queued); "
            "request shed")
        self.queued_rows = queued_rows
        self.queue_max = queue_max

    def __reduce__(self):
        return (type(self), (self.queued_rows, self.queue_max))


class ServerClosed(ServingError):
    """The server is draining or shut down; no new requests admitted."""


class SwapFailed(ServingError):
    """A model hot-swap did not commit; the previous model stays active.

    Raised when the candidate's warmup or health check fails, or when
    the commit blows its deadline (an injected hang-mid-swap) — in every
    case the registry rolls back automatically and live traffic keeps
    serving the old model."""
