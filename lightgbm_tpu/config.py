"""Parameter schema for lightgbm_tpu.

TPU-native re-design of the reference's config system: a single ``Config``
dataclass-like object with defaults, ~180 aliases, and consistency checks
(reference: include/LightGBM/config.h:39, src/io/config.cpp:286 ``Config::Set``,
generated alias table in src/io/config_auto.cpp). Unlike the reference we keep the
schema in one Python table (PARAMS below) from which aliases, defaults and docs are
derived — same "schema as single source of truth" idea, no codegen step needed.
"""
from __future__ import annotations

import copy
from typing import Any, Dict, Iterable, List, Optional, Tuple

from .utils import log

# ---------------------------------------------------------------------------
# Schema: name -> (default, type, aliases)
# Mirrors the parameter surface documented in the reference's
# include/LightGBM/config.h doc-comments / docs/Parameters.rst.
# ---------------------------------------------------------------------------
PARAMS: Dict[str, Tuple[Any, type, Tuple[str, ...]]] = {
    # core
    "objective": ("regression", str, ("objective_type", "app", "application", "loss")),
    "boosting": ("gbdt", str, ("boosting_type", "boost")),
    "data_sample_strategy": ("bagging", str, ()),
    "num_iterations": (100, int, (
        "num_iteration", "n_iter", "num_tree", "num_trees", "num_round", "num_rounds",
        "nrounds", "num_boost_round", "n_estimators", "max_iter")),
    "learning_rate": (0.1, float, ("shrinkage_rate", "eta")),
    "num_leaves": (31, int, ("num_leaf", "max_leaves", "max_leaf", "max_leaf_nodes")),
    "tree_learner": ("serial", str, ("tree", "tree_type", "tree_learner_type")),
    "num_threads": (0, int, ("num_thread", "nthread", "nthreads", "n_jobs")),
    "device_type": ("tpu", str, ("device",)),
    "seed": (None, int, ("random_seed", "random_state")),
    "deterministic": (False, bool, ()),
    # learning control
    "stop_check_freq": (1, int, ()),  # TPU extension: batched stop checks
    "force_col_wise": (False, bool, ()),
    "force_row_wise": (False, bool, ()),
    "max_depth": (-1, int, ()),
    "min_data_in_leaf": (20, int, (
        "min_data_per_leaf", "min_data", "min_child_samples", "min_samples_leaf")),
    "min_sum_hessian_in_leaf": (1e-3, float, (
        "min_sum_hessian_per_leaf", "min_sum_hessian", "min_hessian", "min_child_weight")),
    "bagging_fraction": (1.0, float, ("sub_row", "subsample", "bagging")),
    "pos_bagging_fraction": (1.0, float, ("pos_sub_row", "pos_subsample", "pos_bagging")),
    "neg_bagging_fraction": (1.0, float, ("neg_sub_row", "neg_subsample", "neg_bagging")),
    "bagging_freq": (0, int, ("subsample_freq",)),
    "bagging_seed": (3, int, ("bagging_fraction_seed",)),
    "bagging_by_query": (False, bool, ()),
    "feature_fraction": (1.0, float, ("sub_feature", "colsample_bytree")),
    "feature_fraction_bynode": (1.0, float, ("sub_feature_bynode", "colsample_bynode")),
    "feature_fraction_seed": (2, int, ()),
    "extra_trees": (False, bool, ("extra_tree",)),
    "extra_seed": (6, int, ()),
    "early_stopping_round": (0, int, (
        "early_stopping_rounds", "early_stopping", "n_iter_no_change")),
    "early_stopping_min_delta": (0.0, float, ()),
    "first_metric_only": (False, bool, ()),
    "max_delta_step": (0.0, float, ("max_tree_output", "max_leaf_output")),
    "lambda_l1": (0.0, float, ("reg_alpha", "l1_regularization")),
    "lambda_l2": (0.0, float, ("reg_lambda", "lambda", "l2_regularization")),
    "linear_lambda": (0.0, float, ()),
    "min_gain_to_split": (0.0, float, ("min_split_gain",)),
    # dart
    "drop_rate": (0.1, float, ("rate_drop",)),
    "max_drop": (50, int, ()),
    "skip_drop": (0.5, float, ()),
    "xgboost_dart_mode": (False, bool, ()),
    "uniform_drop": (False, bool, ()),
    "drop_seed": (4, int, ()),
    # voting-parallel (PV-Tree) vote size (reference: config.h top_k)
    "top_k": (20, int, ("topk",)),
    # goss
    "top_rate": (0.2, float, ()),
    "other_rate": (0.1, float, ()),
    # cat
    "min_data_per_group": (100, int, ()),
    "max_cat_threshold": (32, int, ()),
    "cat_l2": (10.0, float, ()),
    "cat_smooth": (10.0, float, ()),
    "max_cat_to_onehot": (4, int, ()),
    # constraints
    "monotone_constraints": (None, object, ("mc", "monotone_constraint")),
    "monotone_constraints_method": ("basic", str, ("monotone_constraining_method", "mc_method")),
    "monotone_penalty": (0.0, float, ("monotone_splits_penalty", "ms_penalty", "mc_penalty")),
    "feature_contri": (None, object, ("feature_contrib", "fc", "fp", "feature_penalty")),
    "interaction_constraints": (None, object, ()),
    "forcedsplits_filename": ("", str, ("fs", "forced_splits_filename", "forced_splits_file", "forced_splits")),
    "refit_decay_rate": (0.9, float, ()),
    # cegb
    "cegb_tradeoff": (1.0, float, ()),
    "cegb_penalty_split": (0.0, float, ()),
    "cegb_penalty_feature_lazy": (None, object, ()),
    "cegb_penalty_feature_coupled": (None, object, ()),
    # misc learning
    "path_smooth": (0.0, float, ()),
    "verbosity": (1, int, ("verbose",)),
    "use_quantized_grad": (False, bool, ()),
    "num_grad_quant_bins": (4, int, ()),
    "quant_train_renew_leaf": (False, bool, ()),
    "stochastic_rounding": (True, bool, ()),
    # dataset
    "linear_tree": (False, bool, ("linear_trees",)),
    "max_bin": (255, int, ("max_bins",)),
    "max_bin_by_feature": (None, object, ()),
    "min_data_in_bin": (3, int, ()),
    "bin_construct_sample_cnt": (200000, int, ("subsample_for_bin",)),
    "data_random_seed": (1, int, ("data_seed",)),
    "is_enable_sparse": (True, bool, ("is_sparse", "enable_sparse", "sparse")),
    "enable_bundle": (True, bool, ("is_enable_bundle", "bundle")),
    "use_missing": (True, bool, ()),
    "zero_as_missing": (False, bool, ()),
    "feature_pre_filter": (True, bool, ()),
    "pre_partition": (False, bool, ("is_pre_partition",)),
    "two_round": (False, bool, ("two_round_loading", "use_two_round_loading")),
    "header": (False, bool, ("has_header",)),
    "label_column": ("", str, ("label",)),
    "weight_column": ("", str, ("weight",)),
    "group_column": ("", str, ("group", "group_id", "query_column", "query", "query_id")),
    "ignore_column": ("", str, ("ignore_feature", "blacklist")),
    "categorical_feature": ("", object, ("cat_feature", "categorical_column", "cat_column", "categorical_features")),
    "forcedbins_filename": ("", str, ()),
    "save_binary": (False, bool, ("is_save_binary", "is_save_binary_file")),
    "precise_float_parser": (False, bool, ()),
    "parser_config_file": ("", str, ()),
    # predict
    "start_iteration_predict": (0, int, ()),
    "num_iteration_predict": (-1, int, ()),
    "predict_raw_score": (False, bool, ("is_predict_raw_score", "predict_rawscore", "raw_score")),
    "predict_leaf_index": (False, bool, ("is_predict_leaf_index", "leaf_index")),
    "predict_contrib": (False, bool, ("is_predict_contrib", "contrib")),
    "predict_disable_shape_check": (False, bool, ()),
    "pred_early_stop": (False, bool, ()),
    "pred_early_stop_freq": (10, int, ()),
    "pred_early_stop_margin": (10.0, float, ()),
    # objective
    "num_class": (1, int, ("num_classes",)),
    "is_unbalance": (False, bool, ("unbalance", "unbalanced_sets")),
    "scale_pos_weight": (1.0, float, ()),
    "sigmoid": (1.0, float, ()),
    "boost_from_average": (True, bool, ()),
    "reg_sqrt": (False, bool, ()),
    "alpha": (0.9, float, ()),
    "fair_c": (1.0, float, ()),
    "poisson_max_delta_step": (0.7, float, ()),
    "tweedie_variance_power": (1.5, float, ()),
    "lambdarank_truncation_level": (30, int, ()),
    "lambdarank_norm": (True, bool, ()),
    "label_gain": (None, object, ()),
    "lambdarank_position_bias_regularization": (0.0, float, ()),
    "objective_seed": (5, int, ()),
    # metric
    "metric": (None, object, ("metrics", "metric_types")),
    "metric_freq": (1, int, ("output_freq",)),
    "is_provide_training_metric": (False, bool, ("training_metric", "is_training_metric", "train_metric")),
    "eval_at": ((1, 2, 3, 4, 5), object, ("ndcg_eval_at", "ndcg_at", "map_eval_at", "map_at")),
    "multi_error_top_k": (1, int, ()),
    "auc_mu_weights": (None, object, ()),
    # network (reference: socket/MPI config; here: jax.distributed / mesh shape)
    "num_machines": (1, int, ("num_machine",)),
    "local_listen_port": (12400, int, ("local_port", "port")),
    "time_out": (120, int, ()),
    "machine_list_filename": ("", str, ("machine_list_file", "machine_list", "mlist")),
    "machines": ("", str, ("workers", "nodes")),
    # tpu-specific (new in this framework; no reference analogue)
    "tpu_hist_impl": ("auto", str, ()),     # auto | xla | pallas
    # serial-learner row storage: 'compact' physically partitions rows into
    # per-leaf segments (O(N*depth)/tree), 'masked' streams all rows per
    # split (O(N*num_leaves)/tree); 'auto' picks compact for large data
    "tpu_grower": ("auto", str, ()),        # auto | compact | masked
    # observability (lightgbm_tpu/obs): phase-named device traces, the
    # flight-recorder ring, and the metrics plane. tpu_trace_dir writes a
    # jax.profiler trace of the run (Perfetto/TensorBoard) with every
    # program carrying its span taxonomy name (obs/spans.py);
    # tpu_trace_mode=annotations enables the span names + host phase
    # table WITHOUT the full profiler trace
    "tpu_trace_dir": ("", str, ()),
    "tpu_trace_mode": ("full", str, ("trace_mode",)),  # full | annotations
    # per-iteration JSONL metrics stream (obs/metrics.py): one record per
    # update with wall seconds + cumulative phase-keyed compile counts +
    # compile-cache counters; bench.py derives its BENCH-row counters
    # from it and scripts/obs prints the per-phase rollup
    "tpu_metrics_path": ("", str, ("metrics_path",)),
    # flight recorder (obs/flight.py): bounded in-memory ring of
    # structured events dumped as JSONL on TrainingInterrupted / crash,
    # on a blown hot-swap, and at checkpoint ticks; 0 disables
    "tpu_flight_buffer": (512, int, ("flight_buffer",)),
    # metrics endpoint (GET /metrics Prometheus text + /healthz): bound
    # at PredictionServer start AND for the duration of lgb.train when
    # > 0 (scripts/serve --metrics-port overrides) — a pod run is
    # scrapeable while it trains (iteration progress, phase-keyed
    # compile counters, rank-stats aggregate incl. straggler flags)
    "tpu_metrics_port": (0, int, ("metrics_port",)),
    # per-rank runtime attribution (obs/ranks.py): every N iterations
    # the booster blocks on the step (true step wall), times one
    # collective-arrival probe, and publishes both through the
    # coordination-service KV; rank 0 aggregates median/p99/max and
    # flags stragglers into the flight recorder + metrics stream.
    # 0 disables (default) — off-sample iterations are untouched, so
    # the steady-state 0-d2h contract holds between samples
    "tpu_rank_stats_every": (0, int, ("rank_stats_every",)),
    # straggler threshold: a rank is flagged when its sampled iteration
    # wall exceeds this factor x the rolling cross-rank median
    "tpu_straggler_factor": (3.0, float, ("straggler_factor",)),
    "tpu_part_block": (2048, int, ()),      # compact partition stream block
    "tpu_hist_block": (16384, int, ()),     # compact histogram stream block
    # batched-M histogram depth: K row blocks per one-hot contraction fill
    # M = 8K of the 128 MXU rows (ops/fused_split.py hist_flush; 1 = the
    # sync reference path). The pending ring multiplies histogram-side
    # VMEM residency by K, so tpu_fused_block is re-clamped against it
    "tpu_hist_mbatch": (8, int, ("hist_mbatch",)),
    # Mosaic one-hot register layout for the histogram engines: "lane"
    # keeps bins along lanes (channel-major output, the batched-M
    # block-diagonal path), "sublane" lays bins along sublanes for
    # B <= 64 so the one-hot compare fills the register tile
    # (ops/pallas_histogram.py _hist_kernel_sublane, ops/fused_split.py
    # hist_flush). auto = lane; pick per-shape from the
    # BENCH_SHAPES.json["hist_micro"]["layout_sweep"] measurements
    "tpu_hist_layout": ("auto", str, ("hist_layout",)),
    # per-leaf narrowed quantized accumulation (reference:
    # GetHistBitsInLeaf): 0 = auto (currently the int8 -> int32 engine
    # everywhere — the measured layout sweep shows the packed-pair
    # engine's radix-capped chunks lose at B <= 64, so narrow is the
    # measured OPT-IN), 16 = narrow where eligible (small leaves take
    # the packed-pair engine: grad/hess and inbag/raw pairs share one
    # f32 channel each — half the contraction work, bit-identical
    # sums), 32 = always the int8 -> int32 engine
    "tpu_quant_hist_bits": (0, int, ("quant_hist_bits",)),
    # startup microbench autotuner (lightgbm_tpu/engines/autotune.py):
    # at _setup_train the eligible engine-registry candidates ({xla,
    # pallas} x {lane, sublane} x batched-M) are timed on a strided
    # sample of the real binned data and the per-shape-class winner is
    # persisted to tpu_autotune_cache (atomic JSON; default
    # ~/.cache/lightgbm_tpu/autotune.json) — repeat runs with the same
    # shape-class resolve with ZERO microbenches. Resolve order:
    # user > env > autotune cache > heuristic default. first_run (the
    # default) arms implicitly on TPU backends for shapes >= 64k rows
    # (or anywhere when set explicitly); always re-sweeps over a cache
    # hit; off is the pure-heuristic escape hatch (bit-identical trees
    # either way — engine choice changes speed only)
    "tpu_autotune": ("first_run", str, ("autotune",)),  # off | first_run | always
    "tpu_autotune_cache": ("", str, ("autotune_cache",)),
    # data-parallel histogram reduction: reduce-scatter over the feature
    # axis + best-split all-gather vs full-histogram all-reduce
    # (ops/grower_compact.py hist_scatter)
    "tpu_hist_scatter": ("auto", str, ()),  # auto | on | off
    # training-mesh shape: "" = all devices on a 1-D row axis (the
    # default), "N" = first N devices 1-D, "RxC" = 2-D rows x features
    # (the wide one-hot shape: the masked grower's binned matrix shards
    # over BOTH axes; compact/feature learners are row-mesh only). The
    # spmd flight check (analysis/spmd_check.py) lowers every learner
    # mode under faked values of this knob before a pod is rented.
    "tpu_mesh_shape": ("", str, ("mesh_shape",)),  # "" | "N" | "RxC"
    # bucketed grower-step ladder (compile-once training): the step
    # program's jit key carries the power-of-two leaf RUNG and the
    # {unlimited, bounded} depth bucket instead of the exact
    # (num_leaves, max_depth) pair — actual budgets ride as traced
    # scalars, so a full run compiles O(1) step programs and every
    # config in a rung shares one persistent-cache entry
    # (ops/grower.py leaf_rung/depth_rung). off = exact-keyed parity path
    "tpu_step_buckets": ("auto", str, ("step_buckets",)),  # auto | on | off
    # persistent XLA compilation cache: resumed/checkpointed runs and
    # repeated bench rounds skip backend compilation entirely
    # (jax_compilation_cache_dir; hits/misses counted by
    # analysis/guards.cache_counter and recorded in BENCH rows)
    "tpu_compile_cache_dir": ("", str, ("compile_cache_dir",)),
    # async histogram-collective overlap (data-parallel / voting): build
    # each leaf histogram in 2 feature groups and reduce each group
    # separately — group g's psum_scatter/all-reduce issues while group
    # g+1 still accumulates (double-buffered hist slots); collective
    # bytes unchanged, trees bit-identical (ops/grower_compact.py)
    "tpu_hist_overlap": ("auto", str, ("hist_overlap",)),  # auto | on | off
    # fused per-split Mosaic kernel (partition + smaller-child histogram in
    # one streamed walk, ops/fused_split.py): auto = on with a TPU backend
    "tpu_fused": ("auto", str, ()),         # auto | on | off
    "tpu_fused_block": (512, int, ()),      # fused kernel block size (x32)
    "tpu_fused_interpret": (False, bool, ()),  # CI: Pallas interpret on CPU
    "num_shards": (0, int, ()),             # 0 = use all local devices when tree_learner != serial
    # inference engine (ops/predict.py): trees walked tbatch at a time so
    # each depth step is one [Tb, N] gather dispatch
    "tpu_predict_tbatch": (16, int, ("predict_tbatch",)),
    # row-bucket ladder for zero-recompile serving: requests pad up to a
    # geometric rung ("auto" = x2 from 1k to 1M) and the jitted predict
    # program is keyed on (row rung, tree bucket, depth bucket, num_class)
    "tpu_predict_buckets": ("auto", str, ("predict_buckets",)),
    # serving-engine selector (engines/registry.py serving entries):
    # "batched"/"walk" = the depth-batched pointer walk, "level" = the
    # level-order heap relayout (contiguous per-depth slabs; falls back
    # to the walk past tpu_level_depth_cap), "auto" = registry resolve
    # order (user > env LGBM_TPU_PREDICT_ENGINE > autotune cache >
    # depth heuristic), "scan" = the pre-engine serial tree scan
    # (recompiles per batch shape; parity/bench reference)
    "tpu_predict_engine": ("batched", str, ()),
    # level-engine heap depth cap: per-level slab memory is O(2^D) per
    # tree, so buckets deeper than this keep the pointer walk
    "tpu_level_depth_cap": (10, int, ()),
    # opt-in serving leaf-value quantization ("off" | "int8" | "f16"):
    # narrower leaf slabs for the score gather, with a RECORDED
    # max-score-error bound shipped in the model stack
    # (GBDT.leaf_quant_bound); pred_leaf/pred_contrib stay exact f32
    "tpu_leaf_quant": ("off", str, ()),
    # 4-bit nibble packing of served request matrices when every feature
    # has <= 16 bins (io/dataset.py pack4_matrix; halves request HBM)
    "tpu_bin_pack4": (False, bool, ("bin_pack4",)),
    # serving layer (lightgbm_tpu/serving/): the async micro-batch
    # coalescer aggregates concurrent predict requests into one
    # rung-sized device batch per tick, with per-request deadlines,
    # a bounded admission queue (structured ServerOverloaded instead of
    # unbounded latency), and pre-warmed hot-swappable models
    "tpu_serve_tick_ms": (5.0, float, ("serve_tick_ms",)),
    # admission bound, in ROWS queued (not requests): a submit that would
    # push the queue past it raises ServerOverloaded (load shedding)
    "tpu_serve_queue_max": (8192, int, ("serve_queue_max",)),
    # default per-request deadline: a request not served by then gets a
    # structured ServingTimeout instead of waiting forever
    "tpu_serve_deadline_ms": (1000.0, float, ("serve_deadline_ms",)),
    # cap (in rows) on the ladder rungs pre-compiled at deploy/warmup
    # time; 0 warms the FULL tpu_predict_buckets ladder (on the auto
    # ladder that is rungs up to 1M rows — minutes of compiles and a
    # 1M-row dummy request per rung, so the default caps at 16k and the
    # full warm is an explicit opt-in). The coalescer never builds a
    # batch larger than its largest warmed rung, so the post-warmup
    # serving steady state compiles nothing
    "tpu_serve_warm_max_rows": (16384, int, ("serve_warm_max_rows",)),
    # serving featurization: "device" (default) bins a request with the
    # jitted raw->binned program (ops/device_bin.py) so a serving batch
    # is ONE host->device copy of raw float32; "host" keeps the
    # bin_columns numpy path (bit-identical parity/escape hatch)
    "tpu_serve_featurize": ("device", str, ("serve_featurize",)),
    # endpoints a server warms and accepts through the coalescer ladder:
    # comma list of predict / leaf / contrib. Warming compiles one
    # program per (endpoint, rung), so the non-default endpoints are
    # opt-in; submitting to an unlisted endpoint raises structurally
    # (serving it cold would compile in the request path)
    "tpu_serve_endpoints": ("predict", str, ("serve_endpoints",)),
    # background-tier coalescer lanes: a comma list of request kinds
    # (e.g. "contrib") whose batches only cut when NO foreground
    # (predict/leaf) rows are queued — explanation throughput must not
    # touch predict p99. "" (default) keeps every kind foreground FIFO.
    "tpu_serve_background_kinds": ("", str, ("serve_background_kinds",)),
    # precomputed TreeSHAP UNWIND tables (ops/treeshap_device.py):
    # "auto" (default) builds the per-leaf mask tables at deploy time
    # when they fit tpu_shap_table_mb and collapses the per-row kernel
    # to agreement-bits + table lookups; "off" keeps the EXTEND/UNWIND
    # loops; "on" forces tables (errors when over budget)
    "tpu_shap_tables": ("auto", str, ()),
    # HBM budget (MiB) for the deploy-time UNWIND table cache — the
    # R012 bound the witness cache probe reports against
    "tpu_shap_table_mb": (64, int, ()),
    # serving drift monitors (obs/drift.py): every served batch's binned
    # matrix folds into a device-resident [F, B] bin-occupancy
    # accumulator (plus a fixed-edge histogram of raw margins) with pure
    # on-device adds; every N serving ticks the window flushes to host
    # (the ONE declared d2h), PSI/KL per feature and score drift are
    # computed against the training-data reference distribution, and
    # hysteresis-gated drift_detected events land in the flight recorder
    # + Prometheus gauges. 0 disables (default) — the machine-readable
    # "model went stale / traffic shifted" refit trigger of ROADMAP 4
    "tpu_drift_flush_every": (0, int, ("drift_flush_every",)),
    # PSI above this marks a feature (or the score distribution) drifted
    # (drift_detected event); it un-marks (drift_cleared) only below
    # half the threshold — the hysteresis band that stops flapping.
    # 0.2 is the conventional "significant shift" PSI cut
    "tpu_drift_psi_threshold": (0.2, float, ("drift_psi_threshold",)),
    # fixed-edge bin count of the raw-margin (score) histogram; edges
    # come from the training-score reference range at attach time
    "tpu_drift_score_bins": (32, int, ("drift_score_bins",)),
    # PSI compares ~equal-reference-mass GROUPS of adjacent bins, not
    # the raw mapper bins (a finite window leaves most of a 255-bin
    # quantile mapper empty and unshifted traffic would read as
    # drifted); 10-20 is the conventional PSI bucket count
    "tpu_drift_bins": (16, int, ("drift_bins",)),
    # minimum rows a flush window needs before drift EVENTS fire (PSI
    # sampling noise has expectation ~(G-1)/rows, so a low-traffic
    # window would cry wolf on unshifted traffic); gauges/records still
    # update every flush. 0 = auto: 20 x tpu_drift_bins
    "tpu_drift_min_rows": (0, int, ("drift_min_rows",)),
    # serving SLO tracker (obs/drift.py): a served request is "good"
    # when it completes within tpu_serve_slo_ms; rolling good/bad counts
    # feed multi-window (5 m / 1 h) error-budget burn rates exposed as
    # gauges, with slo_burn flight events on sustained burn > 1.
    # 0 disables (default)
    "tpu_serve_slo_ms": (0.0, float, ("serve_slo_ms",)),
    # target good fraction of the SLO (burn rate 1.0 == exactly spending
    # the 1 - target error budget)
    "tpu_serve_slo_target": (0.99, float, ("serve_slo_target",)),
    # fault tolerance (io/checkpoint.py, parallel/multihost.py watchdog,
    # analysis/faultinject.py): atomic full-state snapshots every
    # tpu_checkpoint_freq iterations into tpu_checkpoint_dir (keep-last-k
    # rotation); lgb.train auto-resumes from the latest valid snapshot.
    # Unlike snapshot_freq (model text only), these snapshots carry the
    # complete optimizer state and resume BIT-IDENTICALLY.
    "tpu_checkpoint_dir": ("", str, ("checkpoint_dir",)),
    "tpu_checkpoint_freq": (0, int, ("checkpoint_freq",)),
    "tpu_checkpoint_keep": (3, int, ("checkpoint_keep",)),
    # collective watchdog: a multihost bootstrap / training step that
    # exceeds the deadline raises a structured TrainingInterrupted (after
    # a final snapshot) instead of hanging the pod; 0 disables
    "tpu_collective_deadline_s": (0.0, float, ("collective_deadline",)),
    "tpu_collective_retries": (3, int, ()),
    # deterministic chaos spec (analysis/faultinject.py), e.g.
    # "kill@iteration=3;corrupt@snapshot=2"; env LGBM_TPU_FAULTS wins
    "tpu_fault_spec": ("", str, ()),
    # snapshot / continue
    "snapshot_freq": (-1, int, ("save_period",)),
    "input_model": ("", str, ("model_input", "model_in")),
    "output_model": ("LightGBM_model.txt", str, ("model_output", "model_out")),
    # gpu compat (accepted, ignored)
    "gpu_platform_id": (-1, int, ()),
    "gpu_device_id": (-1, int, ()),
    "gpu_use_dp": (False, bool, ()),
    "num_gpu": (1, int, ()),
}

OBJECTIVE_ALIASES: Dict[str, str] = {
    "regression": "regression",
    "regression_l2": "regression",
    "l2": "regression",
    "mean_squared_error": "regression",
    "mse": "regression",
    "l2_root": "regression",
    "root_mean_squared_error": "regression",
    "rmse": "regression",
    "regression_l1": "regression_l1",
    "l1": "regression_l1",
    "mean_absolute_error": "regression_l1",
    "mae": "regression_l1",
    "huber": "huber",
    "fair": "fair",
    "poisson": "poisson",
    "quantile": "quantile",
    "mape": "mape",
    "mean_absolute_percentage_error": "mape",
    "gamma": "gamma",
    "tweedie": "tweedie",
    "binary": "binary",
    "multiclass": "multiclass",
    "softmax": "multiclass",
    "multiclassova": "multiclassova",
    "multiclass_ova": "multiclassova",
    "ova": "multiclassova",
    "ovr": "multiclassova",
    "xentropy": "xentropy",
    "cross_entropy": "xentropy",
    "xentlambda": "xentlambda",
    "cross_entropy_lambda": "xentlambda",
    "lambdarank": "lambdarank",
    "rank_xendcg": "rank_xendcg",
    "xendcg": "rank_xendcg",
    "xe_ndcg": "rank_xendcg",
    "xe_ndcg_mart": "rank_xendcg",
    "xendcg_mart": "rank_xendcg",
    "custom": "custom",
    "none": "custom",
    "null": "custom",
    "na": "custom",
}

METRIC_ALIASES: Dict[str, str] = {
    "l1": "l1", "mean_absolute_error": "l1", "mae": "l1", "regression_l1": "l1",
    "l2": "l2", "mean_squared_error": "l2", "mse": "l2", "regression_l2": "l2",
    "regression": "l2",
    "rmse": "rmse", "root_mean_squared_error": "rmse", "l2_root": "rmse",
    "quantile": "quantile",
    "mape": "mape", "mean_absolute_percentage_error": "mape",
    "huber": "huber",
    "fair": "fair",
    "poisson": "poisson",
    "gamma": "gamma",
    "gamma_deviance": "gamma_deviance",
    "tweedie": "tweedie",
    "ndcg": "ndcg", "lambdarank": "ndcg", "rank_xendcg": "ndcg",
    "xendcg": "ndcg", "xe_ndcg": "ndcg", "xe_ndcg_mart": "ndcg", "xendcg_mart": "ndcg",
    "map": "map", "mean_average_precision": "map",
    "auc": "auc",
    "average_precision": "average_precision",
    "binary_logloss": "binary_logloss", "binary": "binary_logloss",
    "binary_error": "binary_error",
    "auc_mu": "auc_mu",
    "multi_logloss": "multi_logloss", "multiclass": "multi_logloss",
    "softmax": "multi_logloss", "multiclassova": "multi_logloss",
    "multiclass_ova": "multi_logloss", "ova": "multi_logloss", "ovr": "multi_logloss",
    "multi_error": "multi_error",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda", "xentlambda": "cross_entropy_lambda",
    "kullback_leibler": "kldiv", "kldiv": "kldiv",
    "none": "none", "na": "none", "null": "none", "custom": "none",
}

# Parameters accepted (for reference drop-in compatibility) but NOT implemented
# yet. Setting one to a non-default value warns loudly so a user migrating from
# the reference is never silently handed a different model (the reference
# rejects inconsistent configs outright, src/io/config.cpp:286). Entries are
# removed from this set as the corresponding feature lands.
UNIMPLEMENTED_PARAMS: Dict[str, str] = {
    "pre_partition": "pre-partitioned distributed data",
}

# alias -> canonical param name
_ALIAS_TABLE: Dict[str, str] = {}
for _name, (_d, _t, _aliases) in PARAMS.items():
    _ALIAS_TABLE[_name] = _name
    for _a in _aliases:
        _ALIAS_TABLE[_a] = _name


def alias_table() -> Dict[str, str]:
    return dict(_ALIAS_TABLE)


def _coerce(name: str, value: Any, typ: type) -> Any:
    if value is None:
        return None
    if name == "objective" and callable(value):
        return value  # custom objective function passes through untouched
    if typ is bool:
        if isinstance(value, str):
            return value.lower() in ("true", "1", "+", "yes")
        return bool(value)
    if typ is int:
        return int(value)
    if typ is float:
        return float(value)
    if typ is str:
        return str(value)
    return value


class Config:
    """Resolved parameter set (reference: struct Config, include/LightGBM/config.h:39)."""

    def __init__(self, params: Optional[Dict[str, Any]] = None):
        self._explicit: set = set()
        for name, (default, _typ, _aliases) in PARAMS.items():
            setattr(self, name, copy.copy(default))
        if params:
            self.set(params)

    def set(self, params: Dict[str, Any]) -> None:
        # resolve aliases first: explicit canonical name wins over aliases
        # (reference behavior: Config::KeepFirstValues in src/io/config.cpp)
        resolved: Dict[str, Any] = {}
        unknown: Dict[str, Any] = {}
        for key, value in params.items():
            canon = _ALIAS_TABLE.get(key)
            if canon is None:
                unknown[key] = value
                continue
            if canon in resolved and key != canon:
                continue  # first occurrence / canonical wins
            if canon in resolved and key == canon:
                resolved[canon] = value
                continue
            resolved[canon] = value
        for key, value in resolved.items():
            default, typ, _ = PARAMS[key]
            try:
                setattr(self, key, _coerce(key, value, typ))
            except (TypeError, ValueError) as e:
                log.fatal(f"Bad value {value!r} for parameter {key}: {e}")
            self._explicit.add(key)
        for key in unknown:
            log.warning(f"Unknown parameter: {key}")
        for key in resolved:
            feature = UNIMPLEMENTED_PARAMS.get(key)
            if feature is None:
                continue
            default = PARAMS[key][0]
            value = getattr(self, key)
            # 0/0.0 are meaningful values and must still warn (they compare
            # equal to False), so use identity checks for the "unset" sentinels
            unset = value is None or value == "" or value is False
            if value != default and not unset:
                log.warning(
                    f"Parameter {key}={value!r} is accepted for compatibility "
                    f"but {feature} is NOT implemented yet — it has no "
                    "effect; results will differ from the reference LightGBM")
        self._check_consistency()

    def is_explicit(self, name: str) -> bool:
        return name in self._explicit

    def get(self, name: str, default: Any = None) -> Any:
        """Dict-style parameter access used across the objective/metric/boosting
        layers; falls back to ``default`` when the value is unset (None)."""
        value = getattr(self, name, None)
        return default if value is None else value

    def _check_consistency(self) -> None:
        # objective canonicalization (reference: ParseObjectiveAlias, config.h)
        obj = self.objective
        if obj is None or (isinstance(obj, str) and obj.lower() in OBJECTIVE_ALIASES):
            if isinstance(obj, str):
                self.objective = OBJECTIVE_ALIASES[obj.lower()]
        elif callable(obj):
            pass  # custom objective function
        else:
            log.fatal(f"Unknown objective: {obj!r}")
        # boosting alias: goss as boosting type rewrites to sample strategy
        # (reference: config.cpp:119-145)
        if self.boosting == "goss":
            self.boosting = "gbdt"
            self.data_sample_strategy = "goss"
        if self.boosting not in ("gbdt", "gbrt", "dart", "rf", "random_forest"):
            log.fatal(f"Unknown boosting type: {self.boosting}")
        if self.boosting == "gbrt":
            self.boosting = "gbdt"
        if self.boosting == "random_forest":
            self.boosting = "rf"
        if self.objective in ("multiclass", "multiclassova") and self.num_class <= 1:
            log.fatal("Number of classes should be specified and greater than 1 for multiclass training")
        if self.objective not in ("multiclass", "multiclassova") and self.is_explicit("num_class") and self.num_class != 1:
            log.fatal("Number of classes must be 1 for non-multiclass training")
        if self.bagging_freq > 0 and (self.bagging_fraction >= 1.0 or self.bagging_fraction <= 0.0) \
                and self.data_sample_strategy == "bagging" and not self.bagging_by_query:
            self.bagging_freq = 0
        if self.early_stopping_round < 0:
            self.early_stopping_round = 0
        if self.num_leaves < 2:
            self.num_leaves = 2
        if self.max_bin < 2:
            log.fatal("max_bin should be >= 2")
        if self.verbosity is not None:
            log.set_verbosity(self.verbosity)
        # metric list resolution
        self.metric = resolve_metrics(self.metric, self.objective)

    def to_dict(self) -> Dict[str, Any]:
        return {name: getattr(self, name) for name in PARAMS}


def default_metric_for_objective(objective: Any) -> Optional[str]:
    if not isinstance(objective, str):
        return None
    table = {
        "regression": "l2",
        "regression_l1": "l1",
        "huber": "huber",
        "fair": "fair",
        "poisson": "poisson",
        "quantile": "quantile",
        "mape": "mape",
        "gamma": "gamma",
        "tweedie": "tweedie",
        "binary": "binary_logloss",
        "multiclass": "multi_logloss",
        "multiclassova": "multi_logloss",
        "xentropy": "cross_entropy",
        "xentlambda": "cross_entropy_lambda",
        "lambdarank": "ndcg",
        "rank_xendcg": "ndcg",
    }
    return table.get(objective)


def resolve_metrics(metric: Any, objective: Any) -> List[str]:
    """Resolve the ``metric`` parameter into a canonical list."""
    if metric is None or metric == "" or metric == []:
        m = default_metric_for_objective(objective)
        return [m] if m else []
    if isinstance(metric, str):
        metric = [m.strip() for m in metric.split(",") if m.strip()]
    out: List[str] = []
    for m in metric:
        if not isinstance(m, str):
            continue
        canon = METRIC_ALIASES.get(m.lower())
        if canon is None:
            log.warning(f"Unknown metric: {m}")
            continue
        if canon == "none":
            return []
        if canon not in out:
            out.append(canon)
    return out
