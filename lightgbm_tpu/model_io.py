"""Model serialization: LightGBM-compatible model text, JSON dump, loading.

Mirror of the reference's model IO
(reference: src/boosting/gbdt_model_text.cpp — SaveModelToString, DumpModel,
LoadModelFromString; per-tree text in src/io/tree.cpp Tree::ToString /
Tree::ToJSON / Tree::Tree(const char*)).

The emitted format is the reference's ``v4`` text format (``tree`` header,
``Tree=<i>`` blocks, decision_type bit encoding kCategoricalMask=1 /
kDefaultLeftMask=2 / missing_type<<2 — include/LightGBM/tree.h:20-21,262-282)
so models interchange with the reference's Python/CLI tooling in both
directions. Loaded models predict via exact float64 host routing
(reference semantics: Tree::NumericalDecision tree.h:334-351).
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import numpy as np

from .config import Config
from .io.binning import MISSING_NAN, MISSING_NONE, MISSING_ZERO
from .objectives import create_objective
from .utils import log

_MISSING_NAMES = {MISSING_NONE: "none", MISSING_ZERO: "zero", MISSING_NAN: "nan"}
_MISSING_CODES = {"none": 0, "zero": 1, "nan": 2}


def _fmt(x: float) -> str:
    return f"{float(x):.17g}"


def _objective_string(gbdt) -> str:
    obj = gbdt.objective
    if obj is None:
        return "custom"
    name = obj.name
    parts = [name]
    if name in ("multiclass", "multiclassova"):
        parts.append(f"num_class:{obj.num_class}")
    if hasattr(obj, "sigmoid"):
        parts.append(f"sigmoid:{obj.sigmoid:g}")
    if name == "tweedie":
        parts.append(f"tweedie_variance_power:{obj.rho:g}")
    if name in ("quantile", "huber"):
        parts.append(f"alpha:{obj.alpha:g}")
    return " ".join(parts)


def _bitset_cats(host, node: int, mapper) -> List[int]:
    """Category values whose bins are set in a node's bin bitset."""
    words = host.cat_bitset[node]
    cats = []
    for b, cat in enumerate(mapper.bin_to_cat):
        if b // 32 < len(words) and (int(words[b // 32]) >> (b % 32)) & 1:
            cats.append(int(cat))
    return sorted(cats)


def _tree_to_text(host, tree_idx: int, mappers) -> str:
    """One ``Tree=i`` block (reference: Tree::ToString, src/io/tree.cpp)."""
    nl = host.num_leaves
    nn = host.num_nodes
    lines = [f"Tree={tree_idx}", f"num_leaves={nl}"]

    cat_boundaries: List[int] = [0]
    cat_thresholds: List[int] = []
    split_features = []
    thresholds = []
    decision_types = []
    num_cat = 0
    for i in range(nn):
        f = int(host.split_feature[i])
        b = int(host.split_bin[i])
        m = mappers[f]
        dt = 0
        if m.is_categorical:
            dt |= 1  # kCategoricalMask
            # bin bitset -> category-value bitset (reference:
            # Common::ConstructBitset over SplitInfo::cat_threshold)
            cats = _bitset_cats(host, i, m)
            word_count = (max(cats) // 32 + 1) if cats else 1
            words = [0] * word_count
            for cat in cats:
                words[cat // 32] |= 1 << (cat % 32)
            thresholds.append(str(num_cat))
            cat_thresholds.extend(words)
            cat_boundaries.append(len(cat_thresholds))
            num_cat += 1
        else:
            if bool(host.default_left[i]):
                dt |= 2  # kDefaultLeftMask
            mt = 2 if m.missing_type == MISSING_NAN else 0
            dt |= mt << 2
            thresholds.append(_fmt(m.bin_to_threshold(b)))
        split_features.append(str(f))
        decision_types.append(str(dt))

    def join(vals):
        return " ".join(str(v) for v in vals)

    lines.append(f"num_cat={num_cat}")
    lines.append("split_feature=" + join(split_features))
    lines.append("split_gain=" + join(_fmt(host.split_gain[i]) for i in range(nn)))
    lines.append("threshold=" + join(thresholds))
    lines.append("decision_type=" + join(decision_types))
    lines.append("left_child=" + join(int(host.left_child[i]) for i in range(nn)))
    lines.append("right_child=" + join(int(host.right_child[i]) for i in range(nn)))
    lines.append("leaf_value=" + join(_fmt(host.leaf_value[i]) for i in range(nl)))
    lines.append("leaf_weight=" + join(_fmt(host.leaf_weight[i]) for i in range(nl)))
    lines.append("leaf_count=" + join(int(round(float(host.leaf_count[i])))
                                      for i in range(nl)))
    lines.append("internal_value=" + join(_fmt(host.internal_value[i])
                                          for i in range(nn)))
    lines.append("internal_weight=" + join(_fmt(host.internal_weight[i])
                                           for i in range(nn)))
    lines.append("internal_count=" + join(int(round(float(host.internal_count[i])))
                                          for i in range(nn)))
    if num_cat > 0:
        lines.append("cat_boundaries=" + join(cat_boundaries))
        lines.append("cat_threshold=" + join(cat_thresholds))
    if getattr(host, "is_linear", False):
        # (reference: Tree::ToString linear block, src/io/tree.cpp:377-399)
        lines.append("is_linear=1")
        lines.append("leaf_const=" + join(
            _fmt(v) for v in host.leaf_const[:nl]))
        lines.append("num_features=" + join(
            len(host.leaf_features[i]) for i in range(nl)))
        lines.append("leaf_features=" + join(
            str(f) for i in range(nl) for f in host.leaf_features[i]))
        lines.append("leaf_coeff=" + join(
            _fmt(c) for i in range(nl) for c in host.leaf_coeff[i]))
    else:
        lines.append("is_linear=0")
    lines.append(f"shrinkage={host.shrinkage:g}")
    lines.append("")
    return "\n".join(lines)


def booster_to_string(booster, num_iteration: Optional[int] = None) -> str:
    """(reference: GBDT::SaveModelToString, gbdt_model_text.cpp)"""
    gbdt = booster._gbdt
    if hasattr(gbdt, "original_text") and gbdt.original_text is not None:
        return gbdt.original_text
    gbdt._flush_trees()
    ds = gbdt.train_set
    mappers = ds.mappers
    models = gbdt.models
    # num_iteration == 0 means "no trees" (continue-training cuts that fall
    # entirely inside the loaded model); None means "all"
    if num_iteration is not None and num_iteration >= 0:
        models = models[: num_iteration * gbdt.num_tree_per_iteration]

    feature_infos = []
    for m in mappers:
        if m.is_trivial:
            feature_infos.append("none")
        elif m.is_categorical:
            feature_infos.append(
                ":".join(str(int(c)) for c in m.bin_to_cat[1:]))
        else:
            feature_infos.append(f"[{m.min_value:g}:{m.max_value:g}]")

    header = [
        "tree",
        "version=v4",
        f"num_class={gbdt.num_tree_per_iteration}",
        f"num_tree_per_iteration={gbdt.num_tree_per_iteration}",
        "label_index=0",
        f"max_feature_idx={ds.num_total_features - 1}",
        f"objective={_objective_string(gbdt)}",
    ]
    if gbdt.average_output:
        header.append("average_output")
    header.append("feature_names=" + " ".join(ds.feature_names))
    header.append("feature_infos=" + " ".join(feature_infos))

    tree_blocks = [_tree_to_text(m, i, mappers) for i, m in enumerate(models)]
    tree_sizes = [len(b) + 1 for b in tree_blocks]
    header.append("tree_sizes=" + " ".join(str(s) for s in tree_sizes))
    header.append("")

    body = "\n".join(tree_blocks)
    footer = ["", "end of trees", ""]
    imp = gbdt.feature_importance("split")
    order = np.argsort(-imp, kind="stable")
    footer.append("feature_importances:")
    for j in order:
        if imp[j] > 0:
            footer.append(f"{ds.feature_names[j]}={int(imp[j])}")
    footer.append("")
    footer.append("parameters:")
    for key, value in sorted(booster.params.items()):
        footer.append(f"[{key}: {value}]")
    footer.append("end of parameters")
    footer.append("")
    footer.append("pandas_categorical:null")
    return "\n".join(header) + "\n" + body + "\n".join(footer) + "\n"


def _node_to_json(host, mappers, node: int) -> Dict[str, Any]:
    """(reference: Tree::ToJSON / NodeToJSON, src/io/tree.cpp)"""
    if node < 0:
        leaf = -(node + 1)
        return {
            "leaf_index": int(leaf),
            "leaf_value": float(host.leaf_value[leaf]),
            "leaf_weight": float(host.leaf_weight[leaf]),
            "leaf_count": int(round(float(host.leaf_count[leaf]))),
        }
    f = int(host.split_feature[node])
    m = mappers[f]
    out = {
        "split_index": int(node),
        "split_feature": f,
        "split_gain": float(host.split_gain[node]),
        "internal_value": float(host.internal_value[node]),
        "internal_weight": float(host.internal_weight[node]),
        "internal_count": int(round(float(host.internal_count[node]))),
    }
    if m.is_categorical:
        cats = _bitset_cats(host, node, m)
        out["decision_type"] = "=="
        out["threshold"] = "||".join(str(c) for c in cats)
        out["default_left"] = False
        out["missing_type"] = "None"
    else:
        out["decision_type"] = "<="
        out["threshold"] = float(m.bin_to_threshold(int(host.split_bin[node])))
        out["default_left"] = bool(host.default_left[node])
        out["missing_type"] = _MISSING_NAMES.get(m.missing_type, "none").capitalize()
    out["left_child"] = _node_to_json(host, mappers, int(host.left_child[node]))
    out["right_child"] = _node_to_json(host, mappers, int(host.right_child[node]))
    return out


def booster_to_dict(booster, num_iteration: Optional[int] = None) -> Dict[str, Any]:
    """(reference: GBDT::DumpModel, gbdt_model_text.cpp)"""
    gbdt = booster._gbdt
    gbdt._flush_trees()
    ds = gbdt.train_set
    models = gbdt.models
    if num_iteration is not None and num_iteration > 0:
        models = models[: num_iteration * gbdt.num_tree_per_iteration]
    trees = []
    for i, host in enumerate(models):
        root = _node_to_json(host, ds.mappers, 0 if host.num_nodes > 0 else -1)
        trees.append({
            "tree_index": i,
            "num_leaves": host.num_leaves,
            "num_cat": 0,
            "shrinkage": host.shrinkage,
            "tree_structure": root,
        })
    return {
        "name": "tree",
        "version": "v4",
        "num_class": gbdt.num_tree_per_iteration,
        "num_tree_per_iteration": gbdt.num_tree_per_iteration,
        "label_index": 0,
        "max_feature_idx": ds.num_total_features - 1,
        "objective": _objective_string(gbdt),
        "average_output": gbdt.average_output,
        "feature_names": list(ds.feature_names),
        "monotone_constraints": [],
        "feature_infos": {},
        "tree_info": trees,
    }


# ---------------------------------------------------------------------------
# Loading (reference: GBDT::LoadModelFromString, gbdt_model_text.cpp; per-tree
# parser Tree::Tree(const char*), src/io/tree.cpp)
# ---------------------------------------------------------------------------
class LoadedTree:
    __slots__ = ("is_linear", "leaf_const", "leaf_features", "leaf_coeff",
                 "num_leaves", "num_cat", "split_feature", "split_gain",
                 "threshold", "decision_type", "left_child", "right_child",
                 "leaf_value", "leaf_weight", "leaf_count", "internal_value",
                 "internal_count", "cat_boundaries", "cat_threshold",
                 "shrinkage", "num_nodes")

    def decision_scalar(self, node: int, row: np.ndarray) -> bool:
        """One node's go-left decision for one raw-value row; MUST agree
        with ``route`` (tests pin the two together). Used by the
        model-only TreeSHAP path (ops/treeshap.py)."""
        f = int(self.split_feature[node])
        v = float(row[f])
        dt = int(self.decision_type[node])
        if dt & 1:  # categorical
            ci = int(self.threshold[node])
            lo = int(self.cat_boundaries[ci])
            hi = int(self.cat_boundaries[ci + 1])
            words = self.cat_threshold[lo:hi]
            iv = int(v) if np.isfinite(v) else -1
            if not (0 <= iv < 32 * len(words)):
                return False
            return bool((int(words[iv // 32]) >> (iv % 32)) & 1)
        default_left = bool(dt & 2)
        missing_type = (dt >> 2) & 3
        isnan = np.isnan(v)
        if missing_type != 2 and isnan:
            v = 0.0
        if missing_type == 1:
            miss = abs(v) <= 1e-35
        elif missing_type == 2:
            miss = isnan
        else:
            miss = False
        return default_left if miss else bool(v <= float(self.threshold[node]))

    def route(self, x: np.ndarray) -> np.ndarray:
        """Leaf index per row; float64-exact level-synchronous routing."""
        n = x.shape[0]
        if self.num_nodes == 0:
            return np.zeros(n, np.int64)
        cur = np.zeros(n, np.int64)
        for k in range(self.num_nodes):
            at = cur == k
            if not at.any():
                continue
            f = self.split_feature[k]
            v = x[at, f]
            dt = self.decision_type[k]
            if dt & 1:  # categorical
                ci = int(self.threshold[k])
                lo, hi = self.cat_boundaries[ci], self.cat_boundaries[ci + 1]
                words = self.cat_threshold[lo:hi]
                iv = np.where(np.isfinite(v), v, -1).astype(np.int64)
                in_set = np.zeros(len(iv), bool)
                ok = (iv >= 0) & (iv < 32 * len(words))
                idx = iv[ok]
                in_set[ok] = (words[idx // 32] >> (idx % 32)) & 1 > 0
                go_left = in_set
            else:
                default_left = bool(dt & 2)
                missing_type = (dt >> 2) & 3
                isnan = np.isnan(v)
                if missing_type != 2:
                    v = np.where(isnan, 0.0, v)
                if missing_type == 1:
                    miss = np.abs(v) <= 1e-35
                elif missing_type == 2:
                    miss = isnan
                else:
                    miss = np.zeros(len(v), bool)
                go_left = np.where(miss, default_left, v <= self.threshold[k])
            nxt = np.where(go_left, self.left_child[k], self.right_child[k])
            cur[at] = nxt
        return -(cur + 1)


def _parse_block(lines: List[str]) -> Dict[str, str]:
    out = {}
    for line in lines:
        if "=" in line:
            key, _, value = line.partition("=")
            out[key.strip()] = value.strip()
        elif line.strip():
            out[line.strip()] = ""
    return out


def _arr(d: Dict[str, str], key: str, dtype, n: int):
    s = d.get(key, "")
    if not s:
        return np.zeros(n, dtype)
    return np.fromstring(s, dtype=dtype, sep=" ") if False else \
        np.array(s.split(), dtype=dtype)


class LoadedGBDT:
    """Prediction-only model handle built from model text."""

    def __init__(self, model_str: str):
        if not model_str.lstrip().startswith("tree"):
            raise ValueError(
                "Model string is not a LightGBM model (missing 'tree' header)")
        self.original_text = model_str
        lines = model_str.split("\n")
        # split into header / tree blocks / footer on 'Tree=' markers
        header_lines: List[str] = []
        tree_chunks: List[List[str]] = []
        footer_lines: List[str] = []
        cur: Optional[List[str]] = None
        rest_at: Optional[int] = None
        for li, line in enumerate(lines):
            if line.startswith("Tree="):
                if cur is not None:
                    tree_chunks.append(cur)
                cur = [line]
            elif line.strip() == "end of trees":
                if cur is not None:
                    tree_chunks.append(cur)
                cur = None
                rest_at = li
                break
            elif cur is not None:
                cur.append(line)
            else:
                header_lines.append(line)
        if cur is not None:
            tree_chunks.append(cur)
        if rest_at is not None:
            footer_lines = lines[rest_at:]
        # raw pieces retained for faithful re-emission (continue-training
        # merges and refit re-save; reference keeps the file as-is too)
        self._header_lines = [l for l in header_lines
                              if not l.startswith("tree_sizes=")]
        while self._header_lines and not self._header_lines[-1].strip():
            self._header_lines.pop()
        self._tree_chunks = tree_chunks
        self._footer_lines = footer_lines

        hdr = _parse_block(header_lines)
        self.num_class = int(hdr.get("num_class", 1))
        self.num_tree_per_iteration = int(hdr.get("num_tree_per_iteration",
                                                  self.num_class))
        self.max_feature_idx = int(hdr.get("max_feature_idx", 0))
        self.feature_names = hdr.get("feature_names", "").split()
        self.average_output = "average_output" in hdr
        obj_str = hdr.get("objective", "custom")
        self.objective = _objective_from_string(obj_str)
        self.objective_str = obj_str

        self.models: List[LoadedTree] = []
        for chunk in tree_chunks:
            d = _parse_block(chunk)
            t = LoadedTree()
            nl = int(d.get("num_leaves", 1))
            nn = max(nl - 1, 0)
            t.num_leaves = nl
            t.num_nodes = nn
            t.num_cat = int(d.get("num_cat", 0))
            t.split_feature = _arr(d, "split_feature", np.int32, nn)
            t.split_gain = _arr(d, "split_gain", np.float64, nn)
            t.threshold = _arr(d, "threshold", np.float64, nn)
            t.decision_type = _arr(d, "decision_type", np.int32, nn)
            t.left_child = _arr(d, "left_child", np.int32, nn)
            t.right_child = _arr(d, "right_child", np.int32, nn)
            t.leaf_value = _arr(d, "leaf_value", np.float64, nl)
            t.leaf_weight = _arr(d, "leaf_weight", np.float64, nl)
            t.leaf_count = _arr(d, "leaf_count", np.float64, nl)
            t.internal_value = _arr(d, "internal_value", np.float64, nn)
            t.internal_count = _arr(d, "internal_count", np.float64, nn)
            t.cat_boundaries = _arr(d, "cat_boundaries", np.int64,
                                    1 + t.num_cat) if t.num_cat else np.zeros(1, np.int64)
            t.cat_threshold = _arr(d, "cat_threshold", np.uint32, 0) \
                if t.num_cat else np.zeros(0, np.uint32)
            t.shrinkage = float(d.get("shrinkage", 1.0))
            t.is_linear = bool(int(d.get("is_linear", "0") or 0))
            if t.is_linear:
                t.leaf_const = _arr(d, "leaf_const", np.float64, nl)
                counts = _arr(d, "num_features", np.int64, nl)
                feats = _arr(d, "leaf_features", np.int64, 0)
                coeffs = _arr(d, "leaf_coeff", np.float64, 0)
                t.leaf_features = []
                t.leaf_coeff = []
                pos = 0
                for c in counts:
                    t.leaf_features.append(
                        [int(f) for f in feats[pos:pos + int(c)]])
                    t.leaf_coeff.append(
                        [float(v) for v in coeffs[pos:pos + int(c)]])
                    pos += int(c)
            self.models.append(t)

    # Booster-compat surface -------------------------------------------------
    @property
    def current_iteration(self) -> int:
        return len(self.models) // max(self.num_tree_per_iteration, 1)

    def to_if_else(self) -> str:
        """C++ if-else prediction code for the whole model (reference:
        Tree::ToIfElse src/io/tree.cpp, surfaced by task=convert_model with
        convert_model_language=cpp, application.cpp:215). Leaf values are
        post-shrinkage, so summing tree outputs reproduces predict_raw."""
        out = [
            "// generated by lightgbm_tpu task=convert_model",
            "#include <cmath>",
            "#include <cstdint>",
            "#include <limits>",
            "",
            "namespace lightgbm_tpu_model {",
            "",
            "static inline bool CatInSet(const uint32_t* w, int n, "
            "double v) {",
            "  if (std::isnan(v) || v < 0) return false;",
            "  int iv = static_cast<int>(v);",
            "  if (iv >= 32 * n) return false;",
            "  return (w[iv / 32] >> (iv % 32)) & 1u;",
            "}",
            "",
        ]

        def cpp_double(x) -> str:
            # non-finite values must compile as C++ (bare `inf`/`nan` tokens
            # do not; the reference Tree::ToIfElse always emits literals)
            x = float(x)
            if x != x:
                return "std::numeric_limits<double>::quiet_NaN()"
            if x == float("inf"):
                return "std::numeric_limits<double>::infinity()"
            if x == float("-inf"):
                return "-std::numeric_limits<double>::infinity()"
            return repr(x)

        def emit_node(t, node, depth, lines):
            ind = "  " * (depth + 1)
            if node < 0:
                leaf = -(node + 1)
                lines.append(f"{ind}return {cpp_double(t.leaf_value[leaf])};")
                return
            f = int(t.split_feature[node])
            dt = int(t.decision_type[node])
            if dt & 1:
                ci = int(t.threshold[node])
                lo = int(t.cat_boundaries[ci])
                hi = int(t.cat_boundaries[ci + 1])
                words = ", ".join(f"{int(w)}u"
                                  for w in t.cat_threshold[lo:hi])
                lines.append(
                    f"{ind}static const uint32_t cats_{node}[] = "
                    f"{{{words}}};")
                cond = (f"CatInSet(cats_{node}, {hi - lo}, x[{f}])")
            else:
                default_left = "true" if dt & 2 else "false"
                missing_type = (dt >> 2) & 3
                thr = cpp_double(t.threshold[node])
                if missing_type == 2:      # NaN
                    cond = (f"(std::isnan(x[{f}]) ? {default_left} : "
                            f"(x[{f}] <= {thr}))")
                elif missing_type == 1:    # zero-as-missing
                    cond = (f"((std::isnan(x[{f}]) || std::fabs(x[{f}]) "
                            f"<= 1e-35) ? {default_left} : "
                            f"(x[{f}] <= {thr}))")
                else:
                    cond = (f"((std::isnan(x[{f}]) ? 0.0 : x[{f}]) "
                            f"<= {thr})")
            lines.append(f"{ind}if ({cond}) {{")
            emit_node(t, int(t.left_child[node]), depth + 1, lines)
            lines.append(f"{ind}}} else {{")
            emit_node(t, int(t.right_child[node]), depth + 1, lines)
            lines.append(f"{ind}}}")

        for i, t in enumerate(self.models):
            out.append(f"double PredictTree{i}(const double* x) {{")
            if t.num_nodes == 0:
                out.append(f"  return {cpp_double(t.leaf_value[0])};")
            else:
                lines: List[str] = []
                emit_node(t, 0, 0, lines)
                out.extend(lines)
            out.append("}")
            out.append("")
        k = max(self.num_tree_per_iteration, 1)
        out.append(f"const int kNumClass = {k};")
        out.append(f"const int kNumTrees = {len(self.models)};")
        out.append("")
        out.append("void Predict(const double* x, double* output) {")
        out.append("  for (int c = 0; c < kNumClass; ++c) output[c] = 0.0;")
        for i in range(len(self.models)):
            out.append(f"  output[{i % k}] += PredictTree{i}(x);")
        if self.average_output:
            out.append(f"  for (int c = 0; c < kNumClass; ++c) "
                       f"output[c] /= {max(len(self.models) // k, 1)};")
        out.append("}")
        out.append("")
        out.append("}  // namespace lightgbm_tpu_model")
        return "\n".join(out) + "\n"

    def predict_raw_matrix(self, arr: np.ndarray,
                           num_iteration: Optional[int] = None,
                           start_iteration: int = 0,
                           early_stop=None) -> np.ndarray:
        if early_stop is not None:
            log.warning("pred_early_stop is ignored for models loaded from "
                        "file (host prediction path)")
        arr = np.asarray(arr, np.float64)
        if arr.ndim == 1:
            arr = arr.reshape(1, -1)
        models = self.models
        if start_iteration > 0:
            models = models[start_iteration * self.num_tree_per_iteration:]
        if num_iteration is not None and num_iteration > 0:
            models = models[: num_iteration * self.num_tree_per_iteration]
        k = self.num_tree_per_iteration
        out = np.zeros((k, arr.shape[0]), np.float64)
        for i, t in enumerate(models):
            leaf = t.route(arr)
            if getattr(t, "is_linear", False):
                from .boosting.linear import linear_leaf_outputs
                out[i % k] += linear_leaf_outputs(t, arr, leaf)
            else:
                out[i % k] += t.leaf_value[leaf]
        if self.average_output:
            out /= max(len(models) // k, 1)
        return out.astype(np.float32)

    def predict_leaf_matrix(self, arr: np.ndarray,
                            num_iteration: Optional[int] = None,
                            start_iteration: int = 0) -> np.ndarray:
        arr = np.asarray(arr, np.float64)
        models = self.models
        if start_iteration > 0:
            models = models[start_iteration * self.num_tree_per_iteration:]
        if num_iteration is not None and num_iteration > 0:
            models = models[: num_iteration * self.num_tree_per_iteration]
        return np.stack([t.route(arr) for t in models], axis=1)

    def feature_importance(self, importance_type: str = "split",
                           iteration: Optional[int] = None) -> np.ndarray:
        out = np.zeros(self.max_feature_idx + 1, np.float64)
        for t in self.models:
            for i in range(t.num_nodes):
                if importance_type == "split":
                    out[t.split_feature[i]] += 1
                else:
                    out[t.split_feature[i]] += max(float(t.split_gain[i]), 0.0)
        return out


def _objective_from_string(obj_str: str):
    parts = obj_str.split()
    if not parts or parts[0] == "custom":
        return None
    name = parts[0]
    params: Dict[str, Any] = {"objective": name}
    for p in parts[1:]:
        if ":" in p:
            key, _, value = p.partition(":")
            params[key] = value
    cfg = Config(params)
    try:
        return create_objective(cfg.objective, cfg)
    except ValueError:
        log.warning(f"Unknown objective in model file: {name}")
        return None


def _emit_loaded(header_lines, chunks, models, footer_lines,
                 feature_names) -> str:
    """Re-emit a parsed model: raw header + renumbered tree chunks (with
    leaf_value refreshed from the in-memory trees) + raw footer with
    feature_importances recomputed."""
    blocks = []
    for i, (chunk, t) in enumerate(zip(chunks, models)):
        out = []
        for line in chunk:
            if line.startswith("Tree="):
                out.append(f"Tree={i}")
            elif line.startswith("leaf_value="):
                out.append("leaf_value=" + " ".join(
                    _fmt(v) for v in t.leaf_value))
            else:
                out.append(line)
        while out and not out[-1].strip():
            out.pop()
        blocks.append("\n".join(out) + "\n")
    sizes = [len(b) + 1 for b in blocks]
    header = list(header_lines)
    header.append("tree_sizes=" + " ".join(str(sz) for sz in sizes))
    header.append("")

    # recompute the informational importance footer over ALL trees
    imp_arr = _split_importance(models)
    imp: Dict[int, int] = {f: int(v) for f, v in enumerate(imp_arr) if v > 0}
    footer = []
    in_imp = False
    for line in footer_lines:
        if line.strip() == "feature_importances:":
            in_imp = True
            footer.append(line)
            for f in sorted(imp, key=lambda j: -imp[j]):
                name = (feature_names[f] if f < len(feature_names)
                        else f"Column_{f}")
                footer.append(f"{name}={imp[f]}")
            continue
        if in_imp:
            if "=" in line and not line.startswith("["):
                continue  # old importance entries
            in_imp = False
        footer.append(line)
    return "\n".join(header) + "\n" + "\n".join(blocks) \
        + "\n".join(footer)


def loaded_to_string(loaded: "LoadedGBDT") -> str:
    """Serialize a (possibly refitted) loaded model back to v4 text."""
    return _emit_loaded(loaded._header_lines, loaded._tree_chunks,
                        loaded.models, loaded._footer_lines,
                        loaded.feature_names)


def merge_model_texts(pre, new_text: str,
                      pre_num_iteration: Optional[int] = None) -> str:
    """Continue-training save: the loaded model's tree blocks followed by the
    newly trained ones, under the new model's header/footer (reference:
    models_ holds loaded + new trees, gbdt_model_text.cpp emits them all).
    ``pre`` is an already-parsed LoadedGBDT or raw model text."""
    if not isinstance(pre, LoadedGBDT):
        pre = LoadedGBDT(pre)
    new = LoadedGBDT(new_text)
    take = len(pre.models)
    if pre_num_iteration is not None:
        take = pre_num_iteration * max(pre.num_tree_per_iteration, 1)
    return _emit_loaded(new._header_lines,
                        pre._tree_chunks[:take] + new._tree_chunks,
                        pre.models[:take] + new.models,
                        new._footer_lines, new.feature_names)


def _split_importance(models) -> np.ndarray:
    """Split-count importance over LoadedTree lists (shared by the emitter's
    footer recompute and LoadedGBDT.feature_importance)."""
    max_f = 0
    for t in models:
        if t.num_nodes:
            max_f = max(max_f, int(np.max(t.split_feature[:t.num_nodes])))
    out = np.zeros(max_f + 1, np.float64)
    for t in models:
        for i in range(t.num_nodes):
            f = int(t.split_feature[i])
            if f >= 0:
                out[f] += 1
    return out


def _loaded_node_json(t: "LoadedTree", node: int):
    if node < 0:
        leaf = -(node + 1)
        return {
            "leaf_index": int(leaf),
            "leaf_value": float(t.leaf_value[leaf]),
            "leaf_weight": float(t.leaf_weight[leaf])
            if len(t.leaf_weight) > leaf else 0.0,
            "leaf_count": int(t.leaf_count[leaf])
            if len(t.leaf_count) > leaf else 0,
        }
    dt = int(t.decision_type[node])
    out = {
        "split_index": int(node),
        "split_feature": int(t.split_feature[node]),
        "split_gain": float(t.split_gain[node]),
        "internal_value": float(t.internal_value[node])
        if len(t.internal_value) > node else 0.0,
    }
    if dt & 1:
        ci = int(t.threshold[node])
        lo, hi = int(t.cat_boundaries[ci]), int(t.cat_boundaries[ci + 1])
        cats = []
        for wi in range(lo, hi):
            word = int(t.cat_threshold[wi])
            for bit in range(32):
                if (word >> bit) & 1:
                    cats.append((wi - lo) * 32 + bit)
        out["decision_type"] = "=="
        out["threshold"] = "||".join(str(c) for c in cats)
        out["default_left"] = False
        out["missing_type"] = "None"
    else:
        out["decision_type"] = "<="
        out["threshold"] = float(t.threshold[node])
        out["default_left"] = bool(dt & 2)
        out["missing_type"] = {0: "None", 1: "Zero", 2: "NaN"}.get(
            (dt >> 2) & 3, "None")
    out["left_child"] = _loaded_node_json(t, int(t.left_child[node]))
    out["right_child"] = _loaded_node_json(t, int(t.right_child[node]))
    return out


def loaded_dump(loaded: "LoadedGBDT"):
    """JSON dump of a parsed model (reference: GBDT::DumpModel)."""
    tree_info = []
    for i, t in enumerate(loaded.models):
        root = (_loaded_node_json(t, 0) if t.num_nodes > 0
                else _loaded_node_json(t, -1))
        tree_info.append({
            "tree_index": i,
            "num_leaves": int(t.num_leaves),
            "num_cat": int(t.num_cat),
            "shrinkage": float(t.shrinkage),
            "tree_structure": root,
        })
    return {
        "name": "tree",
        "version": "v4",
        "num_class": loaded.num_class,
        "num_tree_per_iteration": loaded.num_tree_per_iteration,
        "label_index": 0,
        "max_feature_idx": loaded.max_feature_idx,
        "objective": loaded.objective_str,
        "average_output": loaded.average_output,
        "feature_names": loaded.feature_names,
        "tree_info": tree_info,
    }


def load_booster(booster, model_str: str, params) -> None:
    gbdt = LoadedGBDT(model_str)
    booster._gbdt = gbdt
    booster.train_set = None
    booster.config = None
    booster._valid_names = []
