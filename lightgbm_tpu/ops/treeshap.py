"""TreeSHAP feature contributions (pred_contrib).

Host-side implementation of the exact tree SHAP path-attribution algorithm
(Lundberg et al., "Consistent Individualized Feature Attribution for Tree
Ensembles"), the same algorithm the reference runs per tree for
``pred_contrib`` (reference: Tree::TreeSHAP / TreeSHAPByMap in
src/io/tree.cpp, driven from GBDT::PredictContrib gbdt_prediction.cpp).

Trees are tiny and SHAP is an interpretation tool, not a training hot path,
so this runs in numpy on the host over the booster's struct-of-array trees
(bin-space thresholds; rows are routed exactly like training/prediction).
Complexity O(rows * trees * leaves * depth^2).
"""
from __future__ import annotations

import numpy as np


class _Path:
    """Decision-path state for the EXTEND/UNWIND recursion."""

    __slots__ = ("feature", "zero_fraction", "one_fraction", "pweight")

    def __init__(self, depth_cap: int):
        self.feature = np.full(depth_cap, -1, np.int64)
        self.zero_fraction = np.zeros(depth_cap)
        self.one_fraction = np.zeros(depth_cap)
        self.pweight = np.zeros(depth_cap)

    def copy_to(self, other: "_Path", n: int) -> None:
        other.feature[:n] = self.feature[:n]
        other.zero_fraction[:n] = self.zero_fraction[:n]
        other.one_fraction[:n] = self.one_fraction[:n]
        other.pweight[:n] = self.pweight[:n]


def _extend(p: _Path, unique_depth: int, zero_fraction: float,
            one_fraction: float, feature: int) -> None:
    p.feature[unique_depth] = feature
    p.zero_fraction[unique_depth] = zero_fraction
    p.one_fraction[unique_depth] = one_fraction
    p.pweight[unique_depth] = 1.0 if unique_depth == 0 else 0.0
    ud = unique_depth
    for i in range(ud - 1, -1, -1):
        p.pweight[i + 1] += one_fraction * p.pweight[i] * (i + 1) / (ud + 1)
        p.pweight[i] = zero_fraction * p.pweight[i] * (ud - i) / (ud + 1)


def _unwind(p: _Path, unique_depth: int, path_index: int) -> None:
    one = p.one_fraction[path_index]
    zero = p.zero_fraction[path_index]
    ud = unique_depth
    next_one_portion = p.pweight[ud]
    for i in range(ud - 1, -1, -1):
        if one != 0.0:
            tmp = p.pweight[i]
            p.pweight[i] = next_one_portion * (ud + 1) / ((i + 1) * one)
            next_one_portion = tmp - p.pweight[i] * zero * (ud - i) / (ud + 1)
        else:
            p.pweight[i] = p.pweight[i] * (ud + 1) / (zero * (ud - i))
    for i in range(path_index, ud):
        p.feature[i] = p.feature[i + 1]
        p.zero_fraction[i] = p.zero_fraction[i + 1]
        p.one_fraction[i] = p.one_fraction[i + 1]


def _unwound_sum(p: _Path, unique_depth: int, path_index: int) -> float:
    one = p.one_fraction[path_index]
    zero = p.zero_fraction[path_index]
    ud = unique_depth
    total = 0.0
    next_one_portion = p.pweight[ud]
    for i in range(ud - 1, -1, -1):
        if one != 0.0:
            tmp = next_one_portion * (ud + 1) / ((i + 1) * one)
            total += tmp
            next_one_portion = p.pweight[i] - tmp * zero * (ud - i) / (ud + 1)
        else:
            total += p.pweight[i] / (zero * (ud - i) / (ud + 1))
    return total


def tree_expected_value(left_child, right_child, leaf_value, node_count,
                        leaf_count, num_nodes: int) -> float:
    """Cover-weighted mean prediction of a tree (row-independent; hoisted
    out of the per-row loop)."""
    if num_nodes == 0:
        return float(leaf_value[0])

    def cover(node: int) -> float:
        if node < 0:
            return max(float(leaf_count[-(node + 1)]), 1e-12)
        return max(float(node_count[node]), 1e-12)

    def value(node: int) -> float:
        if node < 0:
            return float(leaf_value[-(node + 1)])
        lc, rc = int(left_child[node]), int(right_child[node])
        cl, cr = cover(lc), cover(rc)
        return (value(lc) * cl + value(rc) * cr) / (cl + cr)

    return value(0)


def tree_shap_one_row(go_left_fn, split_feature, left_child, right_child,
                      leaf_value, node_count, leaf_count, num_nodes: int,
                      phi: np.ndarray, max_depth: int,
                      expected_value: float) -> None:
    """Accumulate one tree's SHAP values for one row into ``phi`` [F+1]."""
    if num_nodes == 0:
        phi[-1] += float(leaf_value[0])
        return
    depth_cap = max_depth + 2

    def cover(node: int) -> float:
        if node < 0:
            return max(float(leaf_count[-(node + 1)]), 1e-12)
        return max(float(node_count[node]), 1e-12)

    def recurse(node: int, path: _Path, unique_depth: int,
                parent_zero: float, parent_one: float,
                parent_feature: int) -> None:
        p = _Path(depth_cap)
        path.copy_to(p, unique_depth)
        _extend(p, unique_depth, parent_zero, parent_one, parent_feature)
        if node < 0:
            leaf = -(node + 1)
            for i in range(1, unique_depth + 1):
                w = _unwound_sum(p, unique_depth, i)
                phi[p.feature[i]] += (
                    w * (p.one_fraction[i] - p.zero_fraction[i])
                    * float(leaf_value[leaf]))
            return
        f = int(split_feature[node])
        hot = int(left_child[node]) if go_left_fn(node) \
            else int(right_child[node])
        cold = int(right_child[node]) if go_left_fn(node) \
            else int(left_child[node])
        node_cover = cover(node)
        hot_zero = cover(hot) / node_cover
        cold_zero = cover(cold) / node_cover
        incoming_zero, incoming_one = 1.0, 1.0
        new_depth = unique_depth + 1
        # feature already on the path: undo its previous element first
        prev = -1
        for i in range(1, unique_depth + 1):
            if p.feature[i] == f:
                prev = i
                break
        if prev >= 0:
            incoming_zero = p.zero_fraction[prev]
            incoming_one = p.one_fraction[prev]
            _unwind(p, unique_depth, prev)
            new_depth = unique_depth
        recurse(hot, p, new_depth, hot_zero * incoming_zero,
                incoming_one, f)
        recurse(cold, p, new_depth, cold_zero * incoming_zero, 0.0, f)

    # expected value of the tree goes to the bias slot
    phi[-1] += expected_value
    root = _Path(depth_cap)
    recurse(0, root, 0, 1.0, 1.0, -1)


def booster_contrib(models, binned: np.ndarray, nan_bin, is_cat,
                    go_left_pred_np, num_tree_per_iteration: int,
                    num_features: int) -> np.ndarray:
    """SHAP contributions [N, K*(F+1)] over all trees of a booster."""
    n = binned.shape[0]
    k = max(num_tree_per_iteration, 1)
    out = np.zeros((n, k, num_features + 1))
    for t_idx, m in enumerate(models):
        cls = t_idx % k
        depth = int(np.max(m.leaf_depth[: m.num_leaves])) \
            if m.num_nodes > 0 else 0
        ev = tree_expected_value(m.left_child, m.right_child, m.leaf_value,
                                 m.internal_count, m.leaf_count, m.num_nodes)
        for r in range(n):
            row = binned[r]

            def go_left(node: int) -> bool:
                f = int(m.split_feature[node])
                return bool(go_left_pred_np(
                    int(row[f]), int(m.split_bin[node]),
                    bool(m.default_left[node]), int(nan_bin[f]),
                    bool(is_cat[f]), m.cat_bitset[node]))

            tree_shap_one_row(
                go_left, m.split_feature, m.left_child, m.right_child,
                m.leaf_value, m.internal_count, m.leaf_count, m.num_nodes,
                out[r, cls], depth, ev)
    return out.reshape(n, k * (num_features + 1))


# ---------------------------------------------------------------------------
# Model-only path: SHAP from the parsed model text alone (raw-value
# thresholds), no training dataset required — the reference computes
# pred_contrib the same way on loaded models (Tree::PredictContrib routes on
# raw feature values, include/LightGBM/tree.h:668).
# ---------------------------------------------------------------------------
def _loaded_tree_depth(t) -> int:
    """Max leaf depth (internal nodes on the path) of a LoadedTree."""
    if t.num_nodes == 0:
        return 0
    best = 0
    stack = [(0, 1)]
    while stack:
        node, d = stack.pop()
        for child in (int(t.left_child[node]), int(t.right_child[node])):
            if child < 0:
                best = max(best, d)
            else:
                stack.append((child, d + 1))
    return best


def loaded_booster_contrib(models, X: np.ndarray,
                           num_tree_per_iteration: int,
                           num_features: int) -> np.ndarray:
    """SHAP contributions [N, K*(F+1)] from parsed model-text trees.

    Linear trees attribute their constant leaf outputs, exactly like the
    reference (TreeSHAP reads leaf_value_, never the leaf coefficients —
    src/io/tree.cpp)."""
    X = np.ascontiguousarray(X, np.float64)
    n = X.shape[0]
    k = max(num_tree_per_iteration, 1)
    out = np.zeros((n, k, num_features + 1))
    for t_idx, t in enumerate(models):
        cls = t_idx % k
        depth = _loaded_tree_depth(t)
        ev = tree_expected_value(t.left_child, t.right_child, t.leaf_value,
                                 t.internal_count, t.leaf_count, t.num_nodes)
        for r in range(n):
            row = X[r]

            def go_left(node: int) -> bool:
                return t.decision_scalar(node, row)

            tree_shap_one_row(
                go_left, t.split_feature, t.left_child, t.right_child,
                t.leaf_value, t.internal_count, t.leaf_count, t.num_nodes,
                out[r, cls], depth, ev)
    return out.reshape(n, k * (num_features + 1))
