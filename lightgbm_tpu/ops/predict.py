"""On-device prediction over struct-of-arrays trees.

TPU-native re-design of the reference's prediction path
(reference: Tree::Predict pointer-chasing threshold walk include/LightGBM/tree.h:134,
GBDT::PredictRaw src/boosting/gbdt_prediction.cpp, OMP-over-rows Predictor
src/application/predictor.hpp:244).

Pointer-chasing is hostile to TPUs; instead rows are routed *level-synchronously*:
internal nodes are created in monotonically increasing index order (children
always have a larger node id than their parent — grower.py invariant), so a
single in-order sweep ``k = 0..L-2`` over nodes routes every row with one
feature-column gather per step. All rows move in lockstep; there is no
data-dependent control flow, so the whole multi-tree prediction compiles to one
XLA program (scan over trees) with zero host syncs.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax


class StackedTrees(NamedTuple):
    """All trees of a model stacked along a leading T axis (pytree-of-arrays).

    The reference keeps ``std::vector<std::unique_ptr<Tree>>`` (gbdt.h) and loops
    trees serially per row; here the T axis is a ``lax.scan`` axis.
    """
    split_feature: jax.Array   # [T, L-1] i32
    split_bin: jax.Array       # [T, L-1] i32
    cat_bitset: jax.Array      # [T, L-1, W] u32 (categorical splits)
    default_left: jax.Array    # [T, L-1] bool
    left_child: jax.Array      # [T, L-1] i32
    right_child: jax.Array     # [T, L-1] i32
    leaf_value: jax.Array      # [T, L] f32
    num_nodes: jax.Array       # [T] i32

    @property
    def num_trees(self) -> int:
        return self.split_feature.shape[0]

    @property
    def max_nodes(self) -> int:
        return self.split_feature.shape[1]


@jax.jit
def route_one_tree(
    binned: jax.Array,        # [N, F] uint8/16
    split_feature: jax.Array,  # [L-1]
    split_bin: jax.Array,
    cat_bitset: jax.Array,    # [L-1, W] u32
    default_left: jax.Array,
    left_child: jax.Array,
    right_child: jax.Array,
    num_nodes: jax.Array,
    nan_bin_arr: jax.Array,   # [F] i32
    is_cat_arr: jax.Array,    # [F] bool
    col_of: Optional[jax.Array] = None,   # [F] i32: EFB feature -> column
) -> jax.Array:
    """Return the leaf index [N] each row lands in for one tree.

    ``col_of`` translates original feature ids to stored-column ids when the
    binned matrix is EFB-bundled (io/efb.py); bundled features must then have
    is_cat_arr True (they route by the bitset the grower recorded)."""
    from .split import go_left_pred

    n = binned.shape[0]
    max_nodes = split_feature.shape[0]
    # rows start at node 0 when it exists, else directly at leaf 0 (~0 == -1)
    start = jnp.where(num_nodes > 0, 0, -1)
    cur = jnp.full((n,), start, jnp.int32)

    def body(k, cur):
        f = split_feature[k]
        safe_f = jnp.maximum(f, 0)
        t = split_bin[k]
        dl = default_left[k]
        col = safe_f if col_of is None else col_of[safe_f]
        fcol = jnp.take(binned, col, axis=1).astype(jnp.int32)
        nb = nan_bin_arr[safe_f]
        iscat = is_cat_arr[safe_f]
        go_left = go_left_pred(fcol, t, dl, nb, iscat, cat_bitset[k])
        nxt = jnp.where(go_left, left_child[k], right_child[k])
        return jnp.where(cur == k, nxt, cur)

    cur = lax.fori_loop(0, max_nodes, body, cur)
    # negative encoding: leaf = -(cur + 1)
    return -(cur + 1)


@functools.partial(jax.jit, static_argnames=(
    "num_class", "early_stop_margin", "early_stop_freq"))
def predict_raw(
    binned: jax.Array,         # [N, F]
    trees: StackedTrees,
    nan_bin_arr: jax.Array,    # [F] i32
    is_cat_arr: jax.Array,     # [F] bool
    num_model_per_iteration: jax.Array,  # scalar i32 (K trees interleaved per iter)
    num_class: int = 1,
    early_stop_margin: float = 0.0,
    early_stop_freq: int = 0,
) -> jax.Array:
    """Accumulate raw scores over all trees; returns [num_class, N].

    Trees are stored iteration-major (reference: GBDT::models_ ordering — tree
    ``t`` belongs to class ``t % num_class``), matching gbdt_prediction.cpp.

    Prediction early stopping (reference: prediction_early_stop.cpp): every
    ``early_stop_freq`` trees, rows whose decided margin exceeds
    ``early_stop_margin`` stop accumulating — binary: |score|; multiclass:
    best minus second-best. Per-row freezing replaces the reference's
    per-row tree-loop break (all rows ride the same scan on TPU).
    """
    n = binned.shape[0]
    t_total = trees.num_trees
    use_stop = early_stop_freq > 0 and early_stop_margin > 0.0

    def margin_of(scores):
        if num_class == 1:
            # reference binary margin: 2*|score|
            # (prediction_early_stop.cpp CreatePredictionEarlyStopInstance)
            return 2.0 * jnp.abs(scores[0])
        top2 = jnp.sort(scores, axis=0)[-2:]
        return top2[1] - top2[0]

    def step(carry, tree_slice):
        scores, done, t_idx = carry
        (sf, sb, cb, dl, lc, rc, lv, nn, class_id) = tree_slice
        leaf = route_one_tree(binned, sf, sb, cb, dl, lc, rc, nn,
                              nan_bin_arr, is_cat_arr)
        add = lv[leaf]
        if use_stop:
            add = jnp.where(done, 0.0, add)
        scores = scores.at[class_id].add(add)
        if use_stop:
            # freq counts ITERATIONS (k trees each), checked at iteration
            # boundaries only (reference: gbdt_prediction.cpp round counter)
            k_it = jnp.maximum(num_model_per_iteration, 1)
            at_boundary = (t_idx + 1) % k_it == 0
            it_done = (t_idx + 1) // k_it
            check = at_boundary & (it_done % early_stop_freq == 0)
            done = done | (check & (margin_of(scores) > early_stop_margin))
        return (scores, done, t_idx + 1), None

    class_ids = (jnp.arange(t_total, dtype=jnp.int32)
                 % jnp.maximum(num_model_per_iteration, 1))
    scores0 = jnp.zeros((num_class, n), jnp.float32)
    done0 = jnp.zeros((n,), bool)
    (scores, _, _), _ = lax.scan(
        step, (scores0, done0, jnp.asarray(0, jnp.int32)),
        (trees.split_feature, trees.split_bin, trees.cat_bitset,
         trees.default_left, trees.left_child, trees.right_child,
         trees.leaf_value, trees.num_nodes, class_ids),
    )
    return scores


@jax.jit
def predict_leaf_index(
    binned: jax.Array,
    trees: StackedTrees,
    nan_bin_arr: jax.Array,
    is_cat_arr: jax.Array,
) -> jax.Array:
    """Per-tree leaf index for every row: [T, N] (reference: PredictLeafIndex)."""

    def step(_, tree_slice):
        (sf, sb, cb, dl, lc, rc, nn) = tree_slice
        leaf = route_one_tree(binned, sf, sb, cb, dl, lc, rc, nn,
                              nan_bin_arr, is_cat_arr)
        return _, leaf

    _, leaves = lax.scan(
        step, 0,
        (trees.split_feature, trees.split_bin, trees.cat_bitset,
         trees.default_left, trees.left_child, trees.right_child,
         trees.num_nodes),
    )
    return leaves
