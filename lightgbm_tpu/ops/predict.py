"""On-device inference engine over struct-of-arrays trees.

TPU-native re-design of the reference's prediction path
(reference: Tree::Predict pointer-chasing threshold walk include/LightGBM/tree.h:134,
GBDT::PredictRaw src/boosting/gbdt_prediction.cpp, OMP-over-rows Predictor
src/application/predictor.hpp:244).

Three stacked designs live here:

  * ``route_one_tree`` / ``predict_raw_scan`` — the level-synchronous node
    sweep: every node ``k = 0..L-2`` is visited in creation order and rows
    sitting on it move to a child. O(T*L*N), one column slice per node.
    Kept as the bit-exact reference path (parity tests, bench baseline,
    and per-tree routing during training where L is the natural bound).
  * ``predict_raw_batched`` / ``predict_leaf_batched`` — the serving
    engine. Each row carries its current node id and takes D steps
    (D = the stacked model's max depth, recorded at tree-stacking time);
    every step is ONE gather of the packed per-node record
    (col/bin/children/defaults) by node id plus one binned-column gather,
    and leaves self-loop through the negative child encoding. Trees run
    ``tbatch`` at a time so the per-step gathers are ``[Tb, N]``-shaped
    single dispatches instead of T sequential scan steps — O(T*D*N) with
    leaf indices bit-identical to the sweep.
  * bucket ladders (``parse_bucket_ladder`` & friends) — callers pad the
    row count, tree count, and walk depth up to geometric rungs so the
    jitted program is keyed on (row bucket, tree bucket, depth bucket,
    num_class) and steady-state serving hits a warm jit cache: mixed
    request sizes compile once per rung, then never again.

A fourth, serving-only design (ROADMAP item 4) is the LEVEL-ORDER
engine (``build_level_layout`` / ``predict_raw_level``): at stack time
each tree is re-numbered breadth-first into a complete-binary-tree heap
so depth step ``d`` reads the contiguous ``[Tb, 2^d]`` per-level slab
``heap[:, 2^d-1 : 2^(d+1)-1]`` instead of gathering from the scattered
``[Tb, L-1]`` node array; rows carry their in-level position and move
``p -> 2p + (1 - go_left)``. Slots under an already-reached leaf hold a
pass-through record (threshold ``INT32_MAX`` routes every row left), so
the final position at the padded depth maps through a per-tree
``slot_leaf`` table to the exact leaf the walk lands on — bit identity
by construction. Deep/ragged buckets (max depth over the heap cap)
keep the walk. Leaf-value slabs may be int8/f16-quantized for serving
(``quantize_leaves``) with a recorded max-score-error bound.

All rows move in lockstep; there is no data-dependent control flow, so
prediction compiles to one XLA program with zero host syncs.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .packed import gather_bin


class StackedTrees(NamedTuple):
    """All trees of a model stacked along a leading T axis (pytree-of-arrays).

    The reference keeps ``std::vector<std::unique_ptr<Tree>>`` (gbdt.h) and
    loops trees serially per row; here the T axis is either a ``lax.scan``
    axis (reference path) or chunked ``tbatch`` trees at a time (engine).
    """
    split_feature: jax.Array   # [T, L-1] i32
    split_bin: jax.Array       # [T, L-1] i32
    cat_bitset: jax.Array      # [T, L-1, W] u32 (categorical splits)
    default_left: jax.Array    # [T, L-1] bool
    left_child: jax.Array      # [T, L-1] i32
    right_child: jax.Array     # [T, L-1] i32
    leaf_value: jax.Array      # [T, L] f32
    num_nodes: jax.Array       # [T] i32

    @property
    def num_trees(self) -> int:
        return self.split_feature.shape[0]

    @property
    def max_nodes(self) -> int:
        return self.split_feature.shape[1]


# ---------------------------------------------------------------------------
# bucket ladders: the zero-recompile serving contract
# ---------------------------------------------------------------------------

#: default row-bucket ladder: x2 from 1k up to 1M (tpu_predict_buckets)
DEFAULT_MIN_BUCKET = 1024
DEFAULT_MAX_BUCKET = 1 << 20


def parse_bucket_ladder(spec) -> Tuple[int, ...]:
    """Resolve ``tpu_predict_buckets`` into a sorted rung tuple.

    ``"auto"`` (default) is the geometric x2 ladder 1k..1M; a comma string
    or int sequence gives an explicit ladder. Rows pad up to the smallest
    rung that fits, so every rung is one jit cache entry — the recompile
    contract is "at most len(ladder) compiles per (tree bucket, depth
    bucket, num_class), ever".
    """
    if spec is None or (isinstance(spec, str)
                        and spec.strip().lower() in ("", "auto")):
        out, b = [], DEFAULT_MIN_BUCKET
        while b <= DEFAULT_MAX_BUCKET:
            out.append(b)
            b *= 2
        return tuple(out)
    if isinstance(spec, str):
        rungs = [int(float(t)) for t in spec.split(",") if t.strip()]
    else:
        rungs = [int(t) for t in spec]
    rungs = sorted({r for r in rungs if r > 0})
    if not rungs:
        raise ValueError(f"tpu_predict_buckets={spec!r} has no positive rungs")
    return tuple(rungs)


def bucket_rows(n: int, ladder: Sequence[int]) -> Optional[int]:
    """Smallest rung >= n, or None when n overflows the ladder (callers
    then slice the request into max-rung pieces or row-shard it)."""
    for rung in ladder:
        if n <= rung:
            return rung
    return None


def warmup_rungs(ladder: Sequence[int],
                 max_rows: Optional[int] = None) -> Tuple[int, ...]:
    """The row rungs a serving warmup pre-compiles (smallest first).

    One warm predict per returned rung compiles the full program set a
    coalescer can hit in steady state: with the model's tree bucket and
    depth bucket fixed, the row rung is the only remaining jit-key axis.
    ``max_rows`` caps the enumeration (warming the 1M rung host-pads a
    1M-row dummy request, which a small serving box may not want);
    ``None``/``0`` warms the full ladder, and at least the smallest rung
    is always returned so a warmed server has a usable batch bound.
    """
    rungs = tuple(r for r in ladder
                  if not max_rows or max_rows <= 0 or r <= max_rows)
    return rungs if rungs else (min(ladder),)


def tree_bucket(t: int, tbatch: int) -> int:
    """Tree-count bucket: the smallest ``tbatch * 2**j`` >= t.

    Mid-training predict crosses a rung only O(log T) times, so the
    in-training predict program recompiles logarithmically instead of
    once per iteration; the padded tail is all-constant trees
    (num_nodes == 0) that contribute exactly 0.
    """
    b = max(tbatch, 1)
    while b < t:
        b *= 2
    return b


def depth_bucket(d: int) -> int:
    """Walk-depth bucket: the smallest ``4 * 2**j`` >= d. Extra steps are
    free of semantics (leaves self-loop), so padding the depth keeps the
    jit key stable while trees deepen during training."""
    b = 4
    while b < d:
        b *= 2
    return b


def early_stop_tbatch(k: int, freq: int, tbatch: int) -> int:
    """Largest tree-chunk size that lands a chunk boundary on EVERY
    iteration multiple of ``freq`` (reference: the per-round counter in
    gbdt_prediction.cpp checks at exact iteration boundaries).

    Chunks are ``k * f`` trees with ``f`` a divisor of ``freq`` no larger
    than the configured batch, so the margin check runs at precisely the
    reference's boundaries and tree batching never skips or adds one.
    """
    k = max(k, 1)
    freq = max(freq, 1)
    best = 1
    f = 1
    while f * f <= freq:
        if freq % f == 0:
            for d in (f, freq // f):
                if k * d <= max(tbatch, k) and d > best:
                    best = d
        f += 1
    return k * best


# ---------------------------------------------------------------------------
# reference path: level-synchronous node sweep
# ---------------------------------------------------------------------------

@jax.jit
def route_one_tree(
    binned: jax.Array,        # [N, F] uint8/16
    split_feature: jax.Array,  # [L-1]
    split_bin: jax.Array,
    cat_bitset: jax.Array,    # [L-1, W] u32
    default_left: jax.Array,
    left_child: jax.Array,
    right_child: jax.Array,
    num_nodes: jax.Array,
    nan_bin_arr: jax.Array,   # [F] i32
    is_cat_arr: jax.Array,    # [F] bool
    col_of: Optional[jax.Array] = None,   # [F] i32: EFB feature -> column
) -> jax.Array:
    """Return the leaf index [N] each row lands in for one tree.

    Node-sweep routing: internal nodes are created in monotonically
    increasing index order (children always have a larger node id than
    their parent — grower.py invariant), so a single in-order sweep
    ``k = 0..L-2`` routes every row with one feature-column gather per
    step. This is the bit-exactness reference for the depth walk below
    and the natural per-tree router during training (valid-set score
    updates, rollback), where one tree is routed at a time.

    ``col_of`` translates original feature ids to stored-column ids when
    the binned matrix is EFB-bundled (io/efb.py); bundled features must
    then have is_cat_arr True (they route by the bitset the grower
    recorded)."""
    from .split import go_left_pred

    n = binned.shape[0]
    max_nodes = split_feature.shape[0]
    # rows start at node 0 when it exists, else directly at leaf 0 (~0 == -1)
    start = jnp.where(num_nodes > 0, 0, -1)
    cur = jnp.full((n,), start, jnp.int32)

    def body(k, cur):
        f = split_feature[k]
        safe_f = jnp.maximum(f, 0)
        t = split_bin[k]
        dl = default_left[k]
        col = safe_f if col_of is None else col_of[safe_f]
        fcol = jnp.take(binned, col, axis=1).astype(jnp.int32)
        nb = nan_bin_arr[safe_f]
        iscat = is_cat_arr[safe_f]
        go_left = go_left_pred(fcol, t, dl, nb, iscat, cat_bitset[k])
        nxt = jnp.where(go_left, left_child[k], right_child[k])
        return jnp.where(cur == k, nxt, cur)

    cur = lax.fori_loop(0, max_nodes, body, cur)
    # negative encoding: leaf = -(cur + 1)
    return -(cur + 1)


@functools.partial(jax.jit, static_argnames=(
    "num_class", "early_stop_margin", "early_stop_freq"))
def predict_raw_scan(
    binned: jax.Array,         # [N, F]
    trees: StackedTrees,
    nan_bin_arr: jax.Array,    # [F] i32
    is_cat_arr: jax.Array,     # [F] bool
    num_model_per_iteration: jax.Array,  # scalar i32 (K trees interleaved per iter)
    num_class: int = 1,
    early_stop_margin: float = 0.0,
    early_stop_freq: int = 0,
) -> jax.Array:
    """Accumulate raw scores over all trees serially; returns [num_class, N].

    The pre-engine path: ``lax.scan`` over trees, each routed with the
    O(L) node sweep, jitted on the CONCRETE batch shape. Kept as the
    semantic reference for parity tests and as the bench baseline; the
    serving path is ``predict_raw_batched``.

    Trees are stored iteration-major (reference: GBDT::models_ ordering — tree
    ``t`` belongs to class ``t % num_class``), matching gbdt_prediction.cpp.

    Prediction early stopping (reference: prediction_early_stop.cpp): every
    ``early_stop_freq`` trees, rows whose decided margin exceeds
    ``early_stop_margin`` stop accumulating — binary: |score|; multiclass:
    best minus second-best. Per-row freezing replaces the reference's
    per-row tree-loop break (all rows ride the same scan on TPU).
    """
    n = binned.shape[0]
    use_stop = early_stop_freq > 0 and early_stop_margin > 0.0

    def step(carry, tree_slice):
        scores, done, t_idx = carry
        (sf, sb, cb, dl, lc, rc, lv, nn, class_id) = tree_slice
        leaf = route_one_tree(binned, sf, sb, cb, dl, lc, rc, nn,
                              nan_bin_arr, is_cat_arr)
        add = lv[leaf]
        if use_stop:
            add = jnp.where(done, 0.0, add)
        scores = scores.at[class_id].add(add)
        if use_stop:
            # freq counts ITERATIONS (k trees each), checked at iteration
            # boundaries only (reference: gbdt_prediction.cpp round counter)
            k_it = jnp.maximum(num_model_per_iteration, 1)
            at_boundary = (t_idx + 1) % k_it == 0
            it_done = (t_idx + 1) // k_it
            check = at_boundary & (it_done % early_stop_freq == 0)
            done = done | (check & (_margin_of(scores, num_class)
                                    > early_stop_margin))
        return (scores, done, t_idx + 1), None

    t_total = trees.num_trees
    class_ids = (jnp.arange(t_total, dtype=jnp.int32)
                 % jnp.maximum(num_model_per_iteration, 1))
    scores0 = jnp.zeros((num_class, n), jnp.float32)
    done0 = jnp.zeros((n,), bool)
    (scores, _, _), _ = lax.scan(
        step, (scores0, done0, jnp.asarray(0, jnp.int32)),
        (trees.split_feature, trees.split_bin, trees.cat_bitset,
         trees.default_left, trees.left_child, trees.right_child,
         trees.leaf_value, trees.num_nodes, class_ids),
    )
    return scores


@jax.jit
def predict_leaf_index(
    binned: jax.Array,
    trees: StackedTrees,
    nan_bin_arr: jax.Array,
    is_cat_arr: jax.Array,
) -> jax.Array:
    """Per-tree leaf index for every row via the node sweep: [T, N]
    (reference: PredictLeafIndex). Parity baseline for the walk engine."""

    def step(_, tree_slice):
        (sf, sb, cb, dl, lc, rc, nn) = tree_slice
        leaf = route_one_tree(binned, sf, sb, cb, dl, lc, rc, nn,
                              nan_bin_arr, is_cat_arr)
        return _, leaf

    _, leaves = lax.scan(
        step, 0,
        (trees.split_feature, trees.split_bin, trees.cat_bitset,
         trees.default_left, trees.left_child, trees.right_child,
         trees.num_nodes),
    )
    return leaves


# ---------------------------------------------------------------------------
# serving engine: depth-iteration pointer walk over tree chunks
# ---------------------------------------------------------------------------

def _margin_of(scores, num_class: int):
    """Decided prediction margin (reference:
    prediction_early_stop.cpp CreatePredictionEarlyStopInstance)."""
    if num_class == 1:
        # reference binary margin: 2*|score|
        return 2.0 * jnp.abs(scores[0])
    top2 = jnp.sort(scores, axis=0)[-2:]
    return top2[1] - top2[0]


#: per-node record lanes packed for the walk's single node gather
_REC_COL, _REC_BIN, _REC_DL, _REC_LC, _REC_RC, _REC_NAN, _REC_CAT = range(7)


def _pack_node_records(trees: StackedTrees, nan_bin_arr, is_cat_arr,
                       col_of) -> jax.Array:
    """[T, L-1, 7] i32: (stored column, threshold bin, default_left,
    left child, right child, nan bin, is_categorical) per node.

    The per-feature lookups (nan bin, cat flag, EFB column translation)
    are resolved HERE, once per node over the tiny [T, L-1] tree arrays,
    so each walk step gathers one record instead of chasing three [F]
    tables per row. XLA CSEs this across the chunk scan.
    """
    sf = trees.split_feature
    safe_f = jnp.maximum(sf, 0)
    col = safe_f if col_of is None else col_of[safe_f]
    return jnp.stack([
        col.astype(jnp.int32),
        trees.split_bin.astype(jnp.int32),
        trees.default_left.astype(jnp.int32),
        trees.left_child.astype(jnp.int32),
        trees.right_child.astype(jnp.int32),
        nan_bin_arr[safe_f].astype(jnp.int32),
        is_cat_arr[safe_f].astype(jnp.int32),
    ], axis=-1)


def _walk_chunk(binned, rec, cat_bitset, num_nodes, depth: int,
                any_cat: bool, packed: bool) -> jax.Array:
    """Leaf index [Tb, N] for one chunk of Tb trees.

    Each row holds its current node id; a step gathers the node record
    ([Tb, N, 7], ONE gather), gathers the row's bin for the node's
    column, evaluates the shared routing predicate and moves to a child.
    Leaves (negative ids) self-loop, so running the loop to the padded
    depth bucket is semantics-free. The predicate mirrors
    ops/split.py go_left_pred bit-for-bit (the parity tests in
    tests/test_predict_engine.py hold both to the same leaves).
    """
    tb, n = rec.shape[0], binned.shape[0]
    start = jnp.where(num_nodes > 0, 0, -1).astype(jnp.int32)     # [Tb]
    cur = jnp.broadcast_to(start[:, None], (tb, n))
    rows = jnp.arange(n, dtype=jnp.int32)[None, :]

    def step(_, cur):
        node = jnp.maximum(cur, 0)
        r = jnp.take_along_axis(rec, node[..., None], axis=1)     # [Tb, N, 7]
        fcol = gather_bin(binned, rows, r[..., _REC_COL], packed)
        bin_ = r[..., _REC_BIN]
        go_left = (fcol <= bin_) | ((r[..., _REC_DL] != 0)
                                    & (fcol == r[..., _REC_NAN]))
        if any_cat:
            w = cat_bitset.shape[-1]
            idx = jnp.broadcast_to(node[..., None], (tb, n, w))
            words = jnp.take_along_axis(cat_bitset, idx, axis=1)  # [Tb, N, W]
            word_id = (fcol // 32).astype(jnp.uint32)
            sel = jnp.zeros_like(fcol, dtype=jnp.uint32)
            for j in range(w):
                sel = jnp.where(word_id == j, words[..., j], sel)
            in_set = ((sel >> (fcol.astype(jnp.uint32) % 32)) & 1) != 0
            go_left = jnp.where(r[..., _REC_CAT] != 0, in_set, go_left)
        nxt = jnp.where(go_left, r[..., _REC_LC], r[..., _REC_RC])
        return jnp.where(cur >= 0, nxt, cur)

    cur = lax.fori_loop(0, depth, step, cur)
    return -(cur + 1)


def _chunked(arr: jax.Array, chunks: int) -> jax.Array:
    return arr.reshape(chunks, arr.shape[0] // chunks, *arr.shape[1:])


@functools.partial(jax.jit, static_argnames=(
    "num_class", "depth", "tbatch", "early_stop_margin", "early_stop_freq",
    "any_cat", "packed"))
def predict_raw_batched(
    binned: jax.Array,         # [N, F] u8/u16, or [N, ceil(F/2)] u8 packed
    trees: StackedTrees,       # T padded to a multiple of tbatch
    nan_bin_arr: jax.Array,    # [F] i32
    is_cat_arr: jax.Array,     # [F] bool
    num_model_per_iteration: jax.Array,  # scalar i32
    num_class: int = 1,
    depth: int = 8,            # depth bucket >= the stacked max depth
    tbatch: int = 16,
    early_stop_margin: float = 0.0,
    early_stop_freq: int = 0,
    any_cat: bool = False,
    packed: bool = False,
    col_of: Optional[jax.Array] = None,
    leaf_scale: Optional[jax.Array] = None,   # [T] f32 for int8 leaf slabs
) -> jax.Array:
    """Raw scores [num_class, N] via the tree-batched depth walk.

    Callers pad N to a row bucket, T to a tree bucket (all-constant
    padding trees add exactly 0) and pass the model's depth bucket, so
    the compiled program is keyed purely on rungs — the serving cache
    contract (see module docstring). With early stopping, ``tbatch``
    must come from ``early_stop_tbatch`` so chunk boundaries land on the
    reference's exact iteration-multiple-of-freq checkpoints.
    """
    n = binned.shape[0]
    t_total = trees.num_trees
    chunks = t_total // tbatch
    use_stop = early_stop_freq > 0 and early_stop_margin > 0.0
    k_it = jnp.maximum(num_model_per_iteration, 1)

    rec = _pack_node_records(trees, nan_bin_arr, is_cat_arr, col_of)
    class_ids = (jnp.arange(t_total, dtype=jnp.int32) % k_it)
    scale = (jnp.ones((t_total,), jnp.float32)
             if leaf_scale is None else leaf_scale)
    xs = (_chunked(rec, chunks), _chunked(trees.cat_bitset, chunks),
          _chunked(trees.num_nodes, chunks),
          _chunked(trees.leaf_value, chunks), _chunked(scale, chunks),
          _chunked(class_ids, chunks))

    def chunk_step(carry, x):
        scores, done, t_idx = carry
        rec_b, cat_b, nn_b, lv_b, sc_b, cid_b = x
        leaf = _walk_chunk(binned, rec_b, cat_b, nn_b, depth, any_cat,
                           packed)
        add = _leaf_add(lv_b, leaf, sc_b)                         # [Tb, N]
        if use_stop:
            add = jnp.where(done[None, :], 0.0, add)
        if num_class == 1:
            scores = scores + add.sum(axis=0)[None, :]
        else:
            # per-chunk class scatter-add (trees are iteration-major)
            scores = scores.at[cid_b].add(add)
        t_idx = t_idx + tbatch
        if use_stop:
            at_boundary = t_idx % k_it == 0
            it_done = t_idx // k_it
            check = at_boundary & (it_done % early_stop_freq == 0)
            done = done | (check & (_margin_of(scores, num_class)
                                    > early_stop_margin))
        return (scores, done, t_idx), None

    scores0 = jnp.zeros((num_class, n), jnp.float32)
    done0 = jnp.zeros((n,), bool)
    (scores, _, _), _ = lax.scan(
        chunk_step, (scores0, done0, jnp.asarray(0, jnp.int32)), xs)
    return scores


@functools.partial(jax.jit, static_argnames=(
    "depth", "tbatch", "any_cat", "packed"))
def predict_leaf_batched(
    binned: jax.Array,
    trees: StackedTrees,
    nan_bin_arr: jax.Array,
    is_cat_arr: jax.Array,
    depth: int = 8,
    tbatch: int = 16,
    any_cat: bool = False,
    packed: bool = False,
    col_of: Optional[jax.Array] = None,
) -> jax.Array:
    """Per-tree leaf index [T, N] via the depth walk (engine twin of
    ``predict_leaf_index``; bit-identical leaves, O(D) instead of O(L))."""
    t_total = trees.num_trees
    chunks = t_total // tbatch
    rec = _pack_node_records(trees, nan_bin_arr, is_cat_arr, col_of)
    xs = (_chunked(rec, chunks), _chunked(trees.cat_bitset, chunks),
          _chunked(trees.num_nodes, chunks))

    def chunk_step(_, x):
        rec_b, cat_b, nn_b = x
        return _, _walk_chunk(binned, rec_b, cat_b, nn_b, depth, any_cat,
                              packed)

    _, leaves = lax.scan(chunk_step, 0, xs)
    return leaves.reshape(t_total, binned.shape[0])


# ---------------------------------------------------------------------------
# serving engine: level-order (breadth-first heap) relayout
# ---------------------------------------------------------------------------

#: heap depth cap for the level engine: slab memory is O(2^D) per tree,
#: so ragged/deep buckets beyond this keep the pointer walk
#: (tpu_level_depth_cap overrides).
DEFAULT_LEVEL_DEPTH_CAP = 10

#: pass-through record for heap slots below an already-reached leaf:
#: threshold INT32_MAX makes ``fcol <= bin`` true for every row, so dead
#: slots deterministically route LEFT and the final position stays
#: ``p * 2^(D-d)`` — exactly the slot the leaf table was scattered to.
_PASS_BIN = 2**31 - 1


class LevelTrees(NamedTuple):
    """Breadth-first complete-binary-heap relayout of a tree stack.

    ``rec`` holds the same 7-lane packed node record as the walk, but
    indexed by heap position ``(2^d - 1) + p`` instead of creation
    order: depth step ``d`` reads the contiguous ``[T, 2^d]`` slab
    ``rec[:, 2^d-1 : 2^(d+1)-1]``. ``slot_leaf`` maps the final
    position at the padded depth back to the creation-order leaf id, so
    leaf values (and pred_leaf output) stay bit-identical to the walk.
    """
    rec: jax.Array        # [T, 2^D - 1, 7] i32 heap node records
    cat_bitset: jax.Array  # [T, 2^D - 1, W] u32 heap cat bitsets
    slot_leaf: jax.Array  # [T, 2^D] i32: final slot -> leaf id

    @property
    def depth(self) -> int:
        return int(self.slot_leaf.shape[1]).bit_length() - 1


@functools.partial(jax.jit, static_argnames=("depth",))
def build_level_layout(
    trees: StackedTrees,
    nan_bin_arr: jax.Array,
    is_cat_arr: jax.Array,
    depth: int,
    col_of: Optional[jax.Array] = None,
) -> LevelTrees:
    """Re-number a tree stack breadth-first into per-depth heap slabs.

    Children always carry a larger creation-order id than their parent
    (grower invariant — the same one ``route_one_tree`` sweeps on), so
    one in-order pass over nodes propagates (level, in-level position)
    from the root: node ``k`` at ``(d, p)`` puts its left child at
    ``(d+1, 2p)`` and its right child at ``(d+1, 2p+1)``. A leaf child
    reached at ``(d, p)`` owns the final slot ``p << (D - d)`` (dead
    slots below it all route left). Runs on device at stack time; the
    caller gates on the stack's max depth <= ``depth`` (deeper buckets
    keep the walk, so the clip guards below never fire for used
    layouts).
    """
    rec = _pack_node_records(trees, nan_bin_arr, is_cat_arr, col_of)
    t_total, lm1 = rec.shape[0], rec.shape[1]
    heap_n = (1 << depth) - 1
    t_idx = jnp.arange(t_total, dtype=jnp.int32)

    # (level, position) per creation-order node; -1 level = not present
    lvl0 = jnp.full((t_total, lm1), -1, jnp.int32)
    lvl0 = lvl0.at[:, 0].set(jnp.where(trees.num_nodes > 0, 0, -1))
    pos0 = jnp.zeros((t_total, lm1), jnp.int32)
    slot0 = jnp.zeros((t_total, 1 << depth), jnp.int32)

    def body(k, st):
        lvl, pos, slot_leaf = st
        plvl, ppos = lvl[:, k], pos[:, k]
        live = plvl >= 0
        clvl = plvl + 1
        for child, cpos in ((trees.left_child[:, k], 2 * ppos),
                            (trees.right_child[:, k], 2 * ppos + 1)):
            is_int = live & (child >= 0)
            safe_c = jnp.clip(child, 0, lm1 - 1)
            lvl = lvl.at[t_idx, safe_c].set(
                jnp.where(is_int, clvl, lvl[t_idx, safe_c]))
            pos = pos.at[t_idx, safe_c].set(
                jnp.where(is_int, cpos, pos[t_idx, safe_c]))
            is_leaf = live & (child < 0) & (clvl <= depth)
            fslot = jnp.clip(cpos << jnp.maximum(depth - clvl, 0),
                             0, (1 << depth) - 1)
            slot_leaf = slot_leaf.at[t_idx, fslot].set(
                jnp.where(is_leaf, -(child + 1),
                          slot_leaf[t_idx, fslot]))
        return lvl, pos, slot_leaf

    lvl, pos, slot_leaf = lax.fori_loop(0, lm1, body, (lvl0, pos0, slot0))

    # scatter creation-order records into heap order (+1 dump row for
    # absent/overflow nodes)
    valid = (lvl >= 0) & (lvl < depth)
    hidx = jnp.where(valid, (1 << jnp.maximum(lvl, 0)) - 1 + pos, heap_n)
    fill = jnp.array([0, _PASS_BIN, 0, 0, 0, -1, 0], jnp.int32)
    heap = jnp.broadcast_to(fill, (t_total, heap_n + 1, 7))
    heap = heap.at[t_idx[:, None], hidx].set(rec)[:, :heap_n]
    w = trees.cat_bitset.shape[-1]
    cat_h = jnp.zeros((t_total, heap_n + 1, w), jnp.uint32)
    cat_h = cat_h.at[t_idx[:, None], hidx].set(trees.cat_bitset)[:, :heap_n]
    return LevelTrees(rec=heap, cat_bitset=cat_h, slot_leaf=slot_leaf)


def _level_chunk(binned, rec_h, cat_h, depth: int, any_cat: bool,
                 packed: bool) -> jax.Array:
    """Final heap position [Tb, N] for one chunk of Tb trees.

    The depth loop is unrolled (depth <= the heap cap), so the per-level
    slab slice is STATIC: step ``d`` reads ``rec_h[:, 2^d-1:2^(d+1)-1]``
    — a contiguous [Tb, 2^d, 7] window — and the position gather stays
    inside it. Same routing predicate as ``_walk_chunk`` bit-for-bit.
    """
    tb, n = rec_h.shape[0], binned.shape[0]
    pos = jnp.zeros((tb, n), jnp.int32)
    rows = jnp.arange(n, dtype=jnp.int32)[None, :]
    for d in range(depth):
        base = (1 << d) - 1
        slab = rec_h[:, base:base + (1 << d)]                     # [Tb, 2^d, 7]
        r = jnp.take_along_axis(slab, pos[..., None], axis=1)
        fcol = gather_bin(binned, rows, r[..., _REC_COL], packed)
        bin_ = r[..., _REC_BIN]
        go_left = (fcol <= bin_) | ((r[..., _REC_DL] != 0)
                                    & (fcol == r[..., _REC_NAN]))
        if any_cat:
            w = cat_h.shape[-1]
            cslab = cat_h[:, base:base + (1 << d)]                # [Tb, 2^d, W]
            idx = jnp.broadcast_to(pos[..., None], (tb, n, w))
            words = jnp.take_along_axis(cslab, idx, axis=1)
            word_id = (fcol // 32).astype(jnp.uint32)
            sel = jnp.zeros_like(fcol, dtype=jnp.uint32)
            for j in range(w):
                sel = jnp.where(word_id == j, words[..., j], sel)
            in_set = ((sel >> (fcol.astype(jnp.uint32) % 32)) & 1) != 0
            go_left = jnp.where(r[..., _REC_CAT] != 0, in_set, go_left)
        pos = 2 * pos + (1 - go_left.astype(jnp.int32))
    return pos


def _leaf_add(lv_b: jax.Array, leaf: jax.Array,
              scale_b: Optional[jax.Array]) -> jax.Array:
    """Gather per-row leaf values [Tb, N] from a (possibly quantized)
    leaf slab and dequantize: int8 slabs scale by the per-tree factor,
    f16 slabs widen — the serving-bandwidth half of the pack4 story."""
    add = jnp.take_along_axis(lv_b, leaf, axis=1)
    if add.dtype == jnp.int8:
        add = add.astype(jnp.float32) * scale_b[:, None]
    elif add.dtype != jnp.float32:
        add = add.astype(jnp.float32)
    return add


@functools.partial(jax.jit, static_argnames=(
    "num_class", "depth", "tbatch", "early_stop_margin", "early_stop_freq",
    "any_cat", "packed"))
def predict_raw_level(
    binned: jax.Array,          # [N, F] u8/u16, or packed
    level: LevelTrees,          # T padded to a multiple of tbatch
    leaf_value: jax.Array,      # [T, L] f32 | f16 | int8
    num_model_per_iteration: jax.Array,
    num_class: int = 1,
    depth: int = 8,
    tbatch: int = 16,
    early_stop_margin: float = 0.0,
    early_stop_freq: int = 0,
    any_cat: bool = False,
    packed: bool = False,
    leaf_scale: Optional[jax.Array] = None,   # [T] f32 for int8 slabs
) -> jax.Array:
    """Raw scores [num_class, N] via the level-order engine.

    Same chunking, class scatter and early-stop semantics as
    ``predict_raw_batched``; only the per-chunk router differs. Leaf
    indices are bit-identical to the walk (LevelTrees invariant), so
    with an f32 slab the scores match bit-for-bit; quantized slabs stay
    within the recorded bound shipped next to them.
    """
    n = binned.shape[0]
    t_total = level.rec.shape[0]
    chunks = t_total // tbatch
    use_stop = early_stop_freq > 0 and early_stop_margin > 0.0
    k_it = jnp.maximum(num_model_per_iteration, 1)

    class_ids = (jnp.arange(t_total, dtype=jnp.int32) % k_it)
    scale = (jnp.ones((t_total,), jnp.float32)
             if leaf_scale is None else leaf_scale)
    xs = (_chunked(level.rec, chunks), _chunked(level.cat_bitset, chunks),
          _chunked(level.slot_leaf, chunks), _chunked(leaf_value, chunks),
          _chunked(scale, chunks), _chunked(class_ids, chunks))

    def chunk_step(carry, x):
        scores, done, t_idx = carry
        rec_b, cat_b, slot_b, lv_b, sc_b, cid_b = x
        fpos = _level_chunk(binned, rec_b, cat_b, depth, any_cat, packed)
        leaf = jnp.take_along_axis(slot_b, fpos, axis=1)
        add = _leaf_add(lv_b, leaf, sc_b)
        if use_stop:
            add = jnp.where(done[None, :], 0.0, add)
        if num_class == 1:
            scores = scores + add.sum(axis=0)[None, :]
        else:
            scores = scores.at[cid_b].add(add)
        t_idx = t_idx + tbatch
        if use_stop:
            at_boundary = t_idx % k_it == 0
            it_done = t_idx // k_it
            check = at_boundary & (it_done % early_stop_freq == 0)
            done = done | (check & (_margin_of(scores, num_class)
                                    > early_stop_margin))
        return (scores, done, t_idx), None

    scores0 = jnp.zeros((num_class, n), jnp.float32)
    done0 = jnp.zeros((n,), bool)
    (scores, _, _), _ = lax.scan(
        chunk_step, (scores0, done0, jnp.asarray(0, jnp.int32)), xs)
    return scores


@functools.partial(jax.jit, static_argnames=(
    "depth", "tbatch", "any_cat", "packed"))
def predict_leaf_level(
    binned: jax.Array,
    level: LevelTrees,
    depth: int = 8,
    tbatch: int = 16,
    any_cat: bool = False,
    packed: bool = False,
) -> jax.Array:
    """Per-tree leaf index [T, N] via the level engine (bit-identical to
    ``predict_leaf_batched`` — the slot_leaf table restores creation-
    order leaf ids)."""
    t_total = level.rec.shape[0]
    chunks = t_total // tbatch
    xs = (_chunked(level.rec, chunks), _chunked(level.cat_bitset, chunks),
          _chunked(level.slot_leaf, chunks))

    def chunk_step(_, x):
        rec_b, cat_b, slot_b = x
        fpos = _level_chunk(binned, rec_b, cat_b, depth, any_cat, packed)
        return _, jnp.take_along_axis(slot_b, fpos, axis=1)

    _, leaves = lax.scan(chunk_step, 0, xs)
    return leaves.reshape(t_total, binned.shape[0])


# ---------------------------------------------------------------------------
# serving leaf-value quantization (tpu_leaf_quant)
# ---------------------------------------------------------------------------

def quantize_leaves(leaf_value: jax.Array, class_ids: jax.Array,
                    mode: str, num_class: int = 1
                    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Quantize the [T, L] leaf slab for serving; returns
    ``(slab, scale[T], bound)``.

    ``mode`` is ``"int8"`` (per-tree symmetric scale ``max|v| / 127``)
    or ``"f16"`` (cast; scale stays 1). ``bound`` is the RECORDED
    max-score-error bound the model stack ships: per-tree worst-case
    dequantization error, summed per class (trees are iteration-major)
    and maxed over classes — an exact bound on ``|quantized_score -
    f32_score|`` for any row, because each row receives exactly one leaf
    per tree. Padding trees quantize to 0 exactly, contributing 0.
    """
    v = leaf_value.astype(jnp.float32)
    if mode == "f16":
        slab = v.astype(jnp.float16)
        scale = jnp.ones((v.shape[0],), jnp.float32)
        err_t = jnp.max(jnp.abs(slab.astype(jnp.float32) - v), axis=1)
    elif mode == "int8":
        amax = jnp.max(jnp.abs(v), axis=1)
        scale = jnp.where(amax > 0, amax / 127.0, 1.0)
        q = jnp.clip(jnp.round(v / scale[:, None]), -127, 127)
        slab = q.astype(jnp.int8)
        err_t = jnp.max(jnp.abs(q * scale[:, None] - v), axis=1)
    else:
        raise ValueError(f"tpu_leaf_quant={mode!r}: expected int8|f16")
    per_class = jax.ops.segment_sum(err_t, class_ids.astype(jnp.int32),
                                    num_segments=max(num_class, 1))
    return slab, scale, jnp.max(per_class)
