"""Leaf-wise tree growth over physically compacted row segments.

TPU-native re-design of the reference's single-device tree learner
(reference: CUDASingleGPUTreeLearner::Train,
src/treelearner/cuda/cuda_single_gpu_tree_learner.cpp:158-345 — the loop
ConstructHistogramForLeaf -> SubtractHistogramForLeaf -> FindBestSplitsForLeaf
-> FindBestFromAllSplits -> Split; CPU analogue SerialTreeLearner::Train,
src/treelearner/serial_tree_learner.cpp:179 with the smaller-child histogram
trick at :404).

This is the serial (single-chip) fast path. Where the masked grower
(ops/grower.py) streams ALL N rows per split — O(N * num_leaves) per tree —
this grower keeps every leaf's rows in a contiguous segment of a packed
row-record array (ops/compact.py):

  * each split streams only the parent's segment once to stably partition it
    (contiguous DMA + one-hot MXU compaction, no gathers/scatters);
  * the smaller child's histogram streams only that child's contiguous rows;
    the larger child is parent - smaller (histogram subtraction);
  * per-tree work is O(N * depth) instead of O(N * num_leaves) — at 255
    leaves that is a ~30-60x reduction, and it is what makes the
    Higgs-10.5M/255-leaf configuration tractable on one chip.

Carried ``extras`` columns (scores, label, weight) ride along through every
partition, so between trees all per-row state lives in the same permuted
order and nothing ever needs to be gathered back. The canonical (user-facing)
row order is only used at dataset construction and prediction time.

The whole tree grows inside one ``lax.fori_loop`` — zero host syncs per tree
(the CUDA learner ships one SplitInfo struct to host per split; we ship none).
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..obs.spans import span
from .compact import (RowLayout, partition_segment, segment_histogram,
                      segments_to_leaf_vectors)
from .fused_split import fused_split
from .grower import _RESCAN_FOLD_STRIDE, GrowerParams, TreeArrays, _NEG_INF
from .split import (apply_efb_bitset, best_split, child_output, depth_gate,
                    extend_hist_efb, leaf_output, left_rows_of_split)


class CompactState(NamedTuple):
    done: jnp.ndarray
    num_nodes: jnp.ndarray
    work: jnp.ndarray        # [N + pad, C] u8 row records (shard-local)
    scratch: jnp.ndarray     # [N + pad, C] u8 partition staging
    # per-leaf histograms are stored FLAT [L, F, B*4]: a trailing dim of 4
    # would be tiled to 128 lanes in HBM (f32 T(8,128) on the minor dims),
    # inflating the cache 32x — 17.7GB at F=529. Views reshape per split.
    leaf_hist: jnp.ndarray   # [L, F, B*4] per-leaf GLOBAL histograms
    leaf_hist_loc: jnp.ndarray  # [L, F, B*4] shard-local (data-parallel;
    #                             dummy [1,1,1] on the serial path)
    leaf_start: jnp.ndarray  # [L] i32 shard-local segment starts
    leaf_nrows: jnp.ndarray  # [L] i32 shard-local segment raw row counts
    leaf_nrows_g: jnp.ndarray  # [L] i32 GLOBAL raw row counts
    leaf_side: jnp.ndarray   # [L] i32 residency array of each segment
    #                          (0 = work, 1 = scratch; fused path only —
    #                          dual residency, ops/fused_split.py)
    # intermediate monotone method state (dummies when off; reference:
    # IntermediateLeafConstraints, monotone_constraints.hpp:516)
    leaf_in_mono: jnp.ndarray   # [L] bool: leaf under a monotone split
    node_parent: jnp.ndarray    # [L-1] i32 parent node (-1 = root)
    node_is_cat: jnp.ndarray    # [L-1] bool categorical split
    leaf_fmask: jnp.ndarray     # [L, F_scan] bool: scan-time feature masks
    #                             (rescans must reuse the original draw)
    # tree arrays under construction
    split_feature: jnp.ndarray
    split_bin: jnp.ndarray
    cat_bitset: jnp.ndarray    # [L-1, W] u32
    split_gain: jnp.ndarray
    default_left: jnp.ndarray
    left_child: jnp.ndarray
    right_child: jnp.ndarray
    leaf_parent: jnp.ndarray
    leaf_parent_side: jnp.ndarray
    leaf_depth: jnp.ndarray
    # per-internal-node aggregates
    node_grad: jnp.ndarray
    node_hess: jnp.ndarray
    node_cnt: jnp.ndarray
    # per-leaf aggregates
    leaf_grad: jnp.ndarray
    leaf_hess: jnp.ndarray
    leaf_cnt: jnp.ndarray
    # per-leaf cached best splits
    bs_gain: jnp.ndarray
    bs_feature: jnp.ndarray
    bs_bin: jnp.ndarray
    bs_default_left: jnp.ndarray
    bs_left_grad: jnp.ndarray
    bs_left_hess: jnp.ndarray
    bs_left_cnt: jnp.ndarray
    bs_left_rows: jnp.ndarray
    bs_bitset: jnp.ndarray     # [L, W] u32 cached categorical bitsets
    bs_cat_l2: jnp.ndarray     # [L] bool (sorted-cat split: l2 += cat_l2)
    leaf_out: jnp.ndarray      # [L] f32 outputs fixed at split time
    leaf_cmin: jnp.ndarray     # [L] f32 monotone output bounds
    leaf_cmax: jnp.ndarray     # [L] f32
    leaf_used: jnp.ndarray     # [L, F] bool path features (interaction)
    leaf_pout: jnp.ndarray     # [L] f32 smoothing context
    cegb_used: jnp.ndarray     # [F] bool (CEGB coupled costs paid once)


@functools.partial(jax.jit,
                   static_argnames=("layout", "params", "n_real"))
def grow_tree_compact(
    work: jnp.ndarray,        # [N + pad, C] u8 packed rows (current order)
    scratch: jnp.ndarray,     # [N + pad, C] u8
    num_bins_arr: jnp.ndarray,
    nan_bin_arr: jnp.ndarray,
    has_nan_arr: jnp.ndarray,
    is_cat_arr: jnp.ndarray,
    feat_mask: jnp.ndarray,
    layout: RowLayout,
    params: GrowerParams,
    n_real: int,
    mono_types: jnp.ndarray = None,
    inter_sets: jnp.ndarray = None,
    bynode_key: jnp.ndarray = None,
    cegb_coupled: jnp.ndarray = None,
    cegb_used0: jnp.ndarray = None,
    extra_key: jnp.ndarray = None,
    feature_contri: jnp.ndarray = None,
    efb=None,   # (col_of_ext, route_cat_ext, off_ext, nb_ext, dbin_ext,
    #              orig_of_ext) — see io/efb.py / gbdt._setup_efb
    quant_scales=None,   # (g_scale, h_scale) traced f32 (params.quant_hist)
    leaf_budget=None,    # i32 traced actual leaf budget (step_buckets)
    depth_budget=None,   # i32 traced actual depth bound (step_buckets)
):
    """Grow one tree; returns (TreeArrays, row_leaf [N], work', scratch',
    leaf_start [L], leaf_nrows [L]) — per-row outputs in the post-tree
    permuted row order. (Callers expand per-row leaf values themselves via
    segments_to_leaf_vectors once shrinkage/renewal are applied.)

    ``params.quant_hist``: the grad/hess row columns carry integer
    discretizer codes; every histogram accumulates int8 x int8 -> int32 on
    the MXU and stays int32 through caching/subtraction/reduction (exact
    integer arithmetic while global num_data * quant_bins < 2^31; the
    GBDT gates the path on that bound), dequantizing with
    ``quant_scales`` only at the split scan and the scalar leaf sums.

    ``params.hist_scatter`` = S > 1 (data-parallel): per-leaf histograms
    reduce with ``lax.psum_scatter`` over the feature axis — each shard
    owns the GLOBAL histogram of F/S features, scans its own slice, and
    the tiny winning candidates sync with an all-gather (the reference's
    ReduceScatter + SyncUpGlobalBestSplit protocol,
    data_parallel_tree_learner.cpp:223-300) — instead of all-reducing the
    full [F, B, 4] histogram to every shard. Requires efb_virtual == 0 and
    mono_intermediate off (their scans need cross-feature histogram
    access)."""
    n = n_real
    L = params.num_leaves
    B = params.num_bins
    if params.step_buckets and leaf_budget is None:
        raise ValueError("params.step_buckets needs the traced leaf_budget "
                         "(the rung is the jit key, not the leaf count)")
    if params.step_buckets and params.max_depth > 0 and depth_budget is None:
        raise ValueError("params.step_buckets with the bounded depth "
                         "bucket needs the traced depth_budget (max_depth "
                         "is the bucket sentinel, not the actual bound)")
    dbudget = depth_budget if (params.step_buckets
                               and params.max_depth > 0) else None
    if layout.packed4 and B > 16:
        raise ValueError(
            f"RowLayout.packed4 needs every bin value to fit a nibble "
            f"(num_bins <= 16, got {B}) — tpu_bin_pack4 training is only "
            "eligible when all stored columns realize <= 16 bins")
    if bool(params.bin_pack4) != bool(layout.packed4):
        raise ValueError(
            "GrowerParams.bin_pack4 and RowLayout.packed4 disagree — the "
            "trainer must thread the pack4 decision through both (the "
            "layout drives the kernels, the param the analysis rules)")
    F = layout.num_features          # stored columns (histogram space)
    F_scan = F + params.efb_virtual  # + virtual EFB features (scan space)
    feat_info = (num_bins_arr, nan_bin_arr, has_nan_arr, is_cat_arr)
    sp_params = params.split_params()
    i32 = jnp.int32
    quant = params.quant_hist
    if quant and quant_scales is None:
        raise ValueError("params.quant_hist needs quant_scales=(g_s, h_s)")
    hdtype = jnp.float32
    if quant:
        hdtype = jnp.int32
        g_scale, h_scale = quant_scales

    def dq_g(x):    # dequantize scalar/array grad code sums
        return x.astype(jnp.float32) * g_scale if quant else x

    def dq_h(x):
        return x.astype(jnp.float32) * h_scale if quant else x

    def dq_c(x):    # count channels: exact integer -> f32 cast
        return x.astype(jnp.float32) if quant else x

    if mono_types is None:
        mono_types = jnp.zeros((F_scan,), jnp.int8)
    if inter_sets is None:
        inter_sets = jnp.zeros((0, F_scan), bool)
    if bynode_key is None:
        bynode_key = jax.random.PRNGKey(0)
    if cegb_coupled is None:
        cegb_coupled = jnp.zeros((F_scan,), jnp.float32)
    if cegb_used0 is None:
        cegb_used0 = jnp.zeros((F_scan,), bool)
    if extra_key is None:
        extra_key = jax.random.PRNGKey(6)
    big = jnp.float32(3.4e38)

    W = params.bitset_words
    zero = jnp.asarray(0, i32)
    ax = params.axis_name

    # ---- feature-scattered histogram reduction (data-parallel) ----
    scatter = params.hist_scatter > 1
    if scatter and ax is None:
        raise ValueError("hist_scatter needs a data-parallel mesh axis")
    if scatter and (params.efb_virtual or params.mono_intermediate):
        raise ValueError("hist_scatter is incompatible with EFB bundles "
                         "and monotone_constraints_method=intermediate")
    if scatter:
        S_sc = params.hist_scatter
        F_loc = -(-F // S_sc)          # features owned per shard
        f_pad_sc = F_loc * S_sc - F
        shard_i = lax.axis_index(ax)

        def _pad_f(a, fill):
            return jnp.pad(a, (0, f_pad_sc), constant_values=fill) \
                if f_pad_sc else a

        # metadata slices for the shard's own features (pad features get
        # num_bins=1 + mask False, so they can never win a split)
        def _fslice(a):
            return lax.dynamic_slice_in_dim(a, shard_i * F_loc, F_loc)

        meta_sl = tuple(_fslice(_pad_f(a, fill)) for a, fill in (
            (num_bins_arr, 1), (nan_bin_arr, 0), (has_nan_arr, False),
            (is_cat_arr, False)))
        mono_sl = _fslice(_pad_f(mono_types, 0))
        contri_sl = (_fslice(_pad_f(feature_contri, 1.0))
                     if feature_contri is not None else None)
        F_h = F_loc                    # cached-histogram feature width
    else:
        F_h = F

    def reduce_hist(local):
        """[F, B, 4] shard-local -> globally-summed histogram (full copy,
        or this shard's [F_loc, B, 4] feature slice under hist_scatter)."""
        if not ax:
            return local
        with span("collective_reduce"):
            if scatter:
                padded = jnp.pad(local, ((0, f_pad_sc), (0, 0), (0, 0))) \
                    if f_pad_sc else local
                return lax.psum_scatter(padded, ax, scatter_dimension=0,
                                        tiled=True)
            return lax.psum(local, ax)

    def sync_split(sp):
        """All-gather the per-shard best-split candidates and return the
        global winner on every shard (reference: SyncUpGlobalBestSplit,
        parallel_tree_learner.h) — a few dozen bytes instead of the full
        histogram."""
        gains = lax.all_gather(sp.gain, ax)                 # [S]
        win = jnp.argmax(gains).astype(i32)
        return type(sp)(*(lax.all_gather(v, ax)[win] for v in sp))

    def leaf_best(hist, pg, ph, pc, depth, fm, cmn, cmx, po, cegb_pen=None,
                  ek=None):
        with span("split_scan"):
            return _leaf_best(hist, pg, ph, pc, depth, fm, cmn, cmx, po,
                              cegb_pen, ek)

    def _leaf_best(hist, pg, ph, pc, depth, fm, cmn, cmx, po, cegb_pen,
                   ek):
        if params.efb_virtual:
            # scan axis = stored columns + one virtual row per bundled
            # original feature (io/efb.py); exact in int32 when quantized
            hist = extend_hist_efb(hist, efb, params.efb_virtual,
                                   params.efb_bmax)
        qs = quant_scales if quant else None
        if scatter:
            sp = best_split(hist, pg, ph, pc, *meta_sl,
                            _fslice(_pad_f(fm, False)), sp_params,
                            mono_sl, cmn, cmx, po, depth,
                            (_fslice(_pad_f(cegb_pen, 0.0))
                             if cegb_pen is not None else None),
                            ek, contri_sl, quant_scales=qs)
            # local winner -> global feature id, then the tiny cross-shard
            # candidate exchange picks one winner bit-identically everywhere
            sp = sp._replace(feature=shard_i * F_loc + sp.feature)
            sp = sync_split(sp)
        else:
            sp = best_split(hist, pg, ph, pc, *feat_info, fm, sp_params,
                            mono_types, cmn, cmx, po, depth, cegb_pen, ek,
                            feature_contri, quant_scales=qs)
        if params.efb_virtual:
            # a bundled winner routes as a ready-made bitset on its column
            sp = apply_efb_bitset(sp, efb, F, B)
        return sp._replace(gain=depth_gate(sp.gain, depth, params.max_depth,
                                           dbudget))

    def seg_hist(work, start, count, cols=None):
        with span("hist_build"):
            return _seg_hist(work, start, count, cols)

    def _seg_hist(work, start, count, cols=None):
        # ``cols``: static stored-column subset of a hist_overlap feature
        # group; chunk_f pins the engines' row chunking to the full width
        # so the group build matches the ungrouped histogram bitwise
        chunk_f = F if cols is not None else 0

        def hist_with(acc_bits):
            def fn(args):
                w, s_, c_ = args
                return segment_histogram(
                    w, s_, c_, layout, B, params.hist_block,
                    params.hist_impl, quantized=quant,
                    mbatch=params.hist_mbatch, acc_bits=acc_bits,
                    quant_max=params.quant_max,
                    hist_layout=params.hist_layout,
                    feat_idx=cols, chunk_f=chunk_f)
            return fn

        if quant and params.quant_narrow:
            # per-leaf hist-bits renewal (reference: GetHistBitsInLeaf,
            # renewed as leaves shrink): narrow leaves take the packed-pair
            # 16-bit engine, wide leaves the int8/int32 engine — both
            # branches return identical int32 [F, B, 4] sums, so the cond
            # is a pure engine-selection with bit-identical results
            from .renew import hist_bits_in_leaf
            bits = hist_bits_in_leaf(count, params.quant_max)
            return lax.cond(bits == 16, hist_with(16), hist_with(32),
                            (work, start, count))
        return hist_with(32)((work, start, count))

    # ---- async histogram-collective overlap (tpu_hist_overlap) ----
    # Build the per-leaf histogram in G feature groups and reduce each
    # group with its OWN collective, issued while the next group's walk
    # still accumulates — XLA's async scheduler hides the psum/
    # psum_scatter under the remaining MXU contraction. Grouping never
    # changes which shard-local addends reach an element, so trees stay
    # bit-identical and total collective bytes are unchanged.
    G = params.hist_overlap if (ax and params.hist_overlap > 1) else 0
    if G:
        from .histogram import overlap_groups
        _gb = overlap_groups(F_h, G)      # bounds over the owned width
        if len(_gb) < 2:
            G = 0                          # one feature: nothing to group
    # the fused Mosaic kernel and packed4 walks produce the local
    # histogram whole — they keep the single build and group only the
    # reduction (collective-collective pipelining, no compute overlap)
    grouped_build = bool(G) and not params.fused_block \
        and not layout.packed4

    def _reduce_group(part):
        with span("collective_reduce"):
            if scatter:
                return lax.psum_scatter(part, ax, scatter_dimension=0,
                                        tiled=True)
            return lax.psum(part, ax)

    def _grouped_reduce(local):
        """reduce_hist with one collective per feature group (the
        precomputed-local path: fused kernel / packed4 walks)."""
        parts = []
        if scatter:
            padded = jnp.pad(local, ((0, f_pad_sc), (0, 0), (0, 0))) \
                if f_pad_sc else local
            resh = padded.reshape(S_sc, F_loc, B, 4)
            for lo, hi in _gb:
                parts.append(_reduce_group(
                    resh[:, lo:hi].reshape(S_sc * (hi - lo), B, 4)))
        else:
            for lo, hi in _gb:
                parts.append(_reduce_group(local[lo:hi]))
        return jnp.concatenate(parts, axis=0)

    def reduce_any(local):
        return _grouped_reduce(local) if G else reduce_hist(local)

    def seg_hist_reduced(work, start, count):
        """(local [F, B, 4], reduced [F_h, B, 4]) histogram of one leaf
        segment. Under hist_overlap each feature group's collective is
        constructed right after that group's streamed walk, dependence-
        free of the later groups — the overlap the reference gets from
        its socket ReduceScatter running beside the next group's kernel
        (data_parallel_tree_learner.cpp:223-300)."""
        if not grouped_build:
            loc = seg_hist(work, start, count)
            return loc, reduce_any(loc)
        parts_loc, parts_red, all_cols = [], [], []
        for lo, hi in _gb:
            if scatter:
                # group g owns sub-range [lo, hi) of EVERY shard's feature
                # slice, so the reassembled scatter output keeps the
                # ownership map (shard i <-> global [i*F_loc, (i+1)*F_loc))
                pos = [i * F_loc + t
                       for i in range(S_sc) for t in range(lo, hi)]
                cols = [p for p in pos if p < F]
            else:
                pos = cols = list(range(lo, hi))
            loc_g = seg_hist(work, start, count, cols=tuple(cols))
            part = loc_g
            if len(cols) < len(pos):
                # pad features (scatter rounding) carry zero histograms
                idx = [j for j, p in enumerate(pos) if p < F]
                part = jnp.zeros((len(pos), B, 4), loc_g.dtype) \
                    .at[jnp.asarray(idx, i32)].set(loc_g)
            parts_loc.append(loc_g)
            all_cols.extend(cols)
            parts_red.append(_reduce_group(part))
        loc_cat = jnp.concatenate(parts_loc, axis=0)
        if scatter:
            loc_full = jnp.zeros((F, B, 4), loc_cat.dtype) \
                .at[jnp.asarray(all_cols, i32)].set(loc_cat)
        else:
            loc_full = loc_cat
        return loc_full, jnp.concatenate(parts_red, axis=0)

    # ---- root ----
    if params.fused_block:
        # hist-only mode of the fused Mosaic kernel (ops/fused_split.py)
        with span("hist_build"):
            work, scratch, root_loc = fused_split(
                work, scratch, jnp.asarray(1, i32), zero,
                jnp.asarray(n, i32), zero, zero, zero, zero, zero, zero,
                jnp.zeros((W,), jnp.uint32), layout, B, params.fused_block,
                W, interpret=params.fused_interpret, dual=params.fused_dual,
                hist_debug=params.fused_hist_debug, num_rows=n, quant=quant,
                mbatch=params.hist_mbatch, hist_layout=params.hist_layout)
        root_hist = reduce_any(root_loc)
    else:
        # data-parallel: histograms reduce over the mesh axis (reference:
        # the ReduceScatter of per-feature histograms,
        # data_parallel_tree_learner.cpp:223-300); split decisions then
        # replicate bit-identically
        root_loc, root_hist = seg_hist_reduced(
            work, jnp.asarray(0, i32), jnp.asarray(n, i32))
    # every feature's bins sum to the global totals (each row lands in
    # exactly one bin per feature), so feature 0 gives the root sums;
    # under hist_scatter the shard's slice may be all padding, so the
    # totals come from the LOCAL histogram + a tiny scalar psum instead
    if scatter:
        sums = jnp.stack([root_loc[0, :, 0].sum(), root_loc[0, :, 1].sum(),
                          root_loc[0, :, 2].sum()])
        sums = lax.psum(sums, ax)
        root_g = dq_g(sums[0])
        root_h = dq_h(sums[1])
        root_c = dq_c(sums[2])
    else:
        root_g = dq_g(root_hist[0, :, 0].sum())
        root_h = dq_h(root_hist[0, :, 1].sum())
        root_c = dq_c(root_hist[0, :, 2].sum())
    from .grower import node_feature_mask
    root_fm = node_feature_mask(
        feat_mask, jnp.zeros((F_scan,), bool), inter_sets,
        jax.random.fold_in(bynode_key, 0), params)
    # path smoothing at the root smooths toward the root's own output
    # (reference: GetParentOutput, serial_tree_learner.cpp:1005-1016)
    root_out = leaf_output(root_g, root_h, sp_params)
    sp0 = leaf_best(root_hist, root_g, root_h, root_c, jnp.asarray(0, i32),
                    root_fm, -big, big, root_out,
                    cegb_coupled * jnp.logical_not(cegb_used0),
                    jax.random.fold_in(extra_key, 0))

    n_g = (n * lax.psum(jnp.asarray(1, i32), ax)) if ax \
        else jnp.asarray(n, i32)
    st = CompactState(
        done=jnp.asarray(False),
        num_nodes=jnp.asarray(0, i32),
        work=work,
        scratch=scratch,
        leaf_hist=jnp.zeros((L, F_h, B * 4), hdtype).at[0]
        .set(root_hist.reshape(F_h, B * 4)),
        leaf_hist_loc=(jnp.zeros((L, F, B * 4), hdtype).at[0]
                       .set(root_loc.reshape(F, B * 4)) if ax
                       else jnp.zeros((1, 1, 1), hdtype)),
        leaf_start=jnp.zeros((L,), i32),
        leaf_nrows=jnp.zeros((L,), i32).at[0].set(n),
        leaf_nrows_g=(jnp.zeros((L,), i32).at[0].set(n_g) if ax
                      else jnp.zeros((1,), i32)),
        leaf_side=jnp.zeros((L,), i32),
        leaf_in_mono=(jnp.zeros((L,), bool) if params.mono_intermediate
                      else jnp.zeros((1,), bool)),
        node_parent=(jnp.full((L - 1,), -1, i32) if params.mono_intermediate
                     else jnp.zeros((1,), i32)),
        node_is_cat=(jnp.zeros((L - 1,), bool) if params.mono_intermediate
                     else jnp.zeros((1,), bool)),
        leaf_fmask=(jnp.zeros((L, F_scan), bool).at[0].set(root_fm)
                    if params.mono_intermediate
                    else jnp.zeros((1, 1), bool)),
        split_feature=jnp.full((L - 1,), -1, i32),
        split_bin=jnp.zeros((L - 1,), i32),
        cat_bitset=jnp.zeros((L - 1, W), jnp.uint32),
        split_gain=jnp.zeros((L - 1,), jnp.float32),
        default_left=jnp.zeros((L - 1,), bool),
        left_child=jnp.full((L - 1,), -1, i32),
        right_child=jnp.full((L - 1,), -1, i32),
        leaf_parent=jnp.full((L,), -1, i32),
        leaf_parent_side=jnp.zeros((L,), i32),
        leaf_depth=jnp.zeros((L,), i32),
        node_grad=jnp.zeros((L - 1,), jnp.float32),
        node_hess=jnp.zeros((L - 1,), jnp.float32),
        node_cnt=jnp.zeros((L - 1,), jnp.float32),
        leaf_grad=jnp.zeros((L,), jnp.float32).at[0].set(root_g),
        leaf_hess=jnp.zeros((L,), jnp.float32).at[0].set(root_h),
        leaf_cnt=jnp.zeros((L,), jnp.float32).at[0].set(root_c),
        bs_gain=jnp.full((L,), _NEG_INF, jnp.float32).at[0].set(sp0.gain),
        bs_feature=jnp.zeros((L,), i32).at[0].set(sp0.feature),
        bs_bin=jnp.zeros((L,), i32).at[0].set(sp0.bin),
        bs_default_left=jnp.zeros((L,), bool).at[0].set(sp0.default_left),
        bs_left_grad=jnp.zeros((L,), jnp.float32).at[0].set(sp0.left_grad),
        bs_left_hess=jnp.zeros((L,), jnp.float32).at[0].set(sp0.left_hess),
        bs_left_cnt=jnp.zeros((L,), jnp.float32).at[0].set(sp0.left_count),
        bs_left_rows=jnp.zeros((L,), i32).at[0].set(
            sp0.left_rows.astype(i32)),
        bs_bitset=jnp.zeros((L, W), jnp.uint32).at[0].set(sp0.cat_bitset),
        bs_cat_l2=jnp.zeros((L,), bool).at[0].set(sp0.is_cat_l2),
        leaf_out=jnp.zeros((L,), jnp.float32).at[0].set(root_out),
        leaf_cmin=jnp.full((L,), -3.4e38, jnp.float32),
        leaf_cmax=jnp.full((L,), 3.4e38, jnp.float32),
        leaf_used=jnp.zeros((L, F_scan), bool),
        leaf_pout=jnp.zeros((L,), jnp.float32).at[0].set(root_out),
        cegb_used=cegb_used0,
    )

    def body(k, st: CompactState) -> CompactState:
        # ---- FindBestFromAllSplits (reference: cuda_best_split_finder.cu:2113) ----
        leaf_alive = jnp.arange(L) <= k
        gains = jnp.where(leaf_alive, st.bs_gain, _NEG_INF)
        best_leaf = jnp.argmax(gains).astype(i32)
        valid = gains[best_leaf] > 0.0
        if params.step_buckets:
            # rounds past the traced leaf budget are inert: the rung's
            # remaining iterations stream zero-trip partition/histogram
            # walks, exactly like a post-early-stop round
            valid = jnp.logical_and(valid, k < leaf_budget - 1)
        applied = jnp.logical_and(valid, jnp.logical_not(st.done))
        done = jnp.logical_or(st.done, jnp.logical_not(valid))

        node = k
        new_leaf = jnp.asarray(k + 1, i32)

        f_ = st.bs_feature[best_leaf]
        b_ = st.bs_bin[best_leaf]
        dl = st.bs_default_left[best_leaf]
        n_left = st.bs_left_rows[best_leaf]
        bits = st.bs_bitset[best_leaf]
        catl2 = st.bs_cat_l2[best_leaf]
        if params.efb_virtual:
            # EFB: the scan index translates to (stored column, routing
            # mode, original feature id); bundled winners carry a ready
            # bitset (apply_efb_bitset) and route like categorical splits
            f_col = efb[0][f_]
            f_cat = efb[1][f_]
            f_orig = efb[5][f_]
        else:
            f_col = f_
            f_cat = is_cat_arr[f_]
            f_orig = f_

        # ---- record split; wire tree structure ----
        split_feature = st.split_feature.at[node].set(
            jnp.where(applied, f_orig, -1))
        split_bin = st.split_bin.at[node].set(jnp.where(applied, b_, 0))
        cat_bitset = st.cat_bitset.at[node].set(jnp.where(applied, bits, 0))
        split_gain = st.split_gain.at[node].set(
            jnp.where(applied, st.bs_gain[best_leaf], 0.0))
        default_left = st.default_left.at[node].set(jnp.where(applied, dl, False))
        p = st.leaf_parent[best_leaf]
        side = st.leaf_parent_side[best_leaf]
        p_idx = jnp.maximum(p, 0)
        left_child = st.left_child.at[p_idx].set(
            jnp.where(applied & (p >= 0) & (side == 0), node,
                      st.left_child[p_idx]))
        right_child = st.right_child.at[p_idx].set(
            jnp.where(applied & (p >= 0) & (side == 1), node,
                      st.right_child[p_idx]))
        left_child = left_child.at[node].set(
            jnp.where(applied, -(best_leaf + 1), left_child[node]))
        right_child = right_child.at[node].set(
            jnp.where(applied, -(new_leaf + 1), right_child[node]))
        leaf_parent = st.leaf_parent.at[best_leaf].set(
            jnp.where(applied, node, st.leaf_parent[best_leaf]))
        leaf_parent = leaf_parent.at[new_leaf].set(
            jnp.where(applied, node, leaf_parent[new_leaf]))
        leaf_parent_side = st.leaf_parent_side.at[best_leaf].set(
            jnp.where(applied, 0, st.leaf_parent_side[best_leaf]))
        leaf_parent_side = leaf_parent_side.at[new_leaf].set(
            jnp.where(applied, 1, leaf_parent_side[new_leaf]))

        # ---- per-leaf aggregates for the two children ----
        lg, lh, lc = (st.bs_left_grad[best_leaf], st.bs_left_hess[best_leaf],
                      st.bs_left_cnt[best_leaf])
        pg, ph, pc = (st.leaf_grad[best_leaf], st.leaf_hess[best_leaf],
                      st.leaf_cnt[best_leaf])
        rg, rh, rc = pg - lg, ph - lh, pc - lc
        node_grad = st.node_grad.at[node].set(jnp.where(applied, pg, 0.0))
        node_hess = st.node_hess.at[node].set(jnp.where(applied, ph, 0.0))
        node_cnt = st.node_cnt.at[node].set(jnp.where(applied, pc, 0.0))
        d_child = st.leaf_depth[best_leaf] + 1
        leaf_grad = st.leaf_grad.at[best_leaf].set(jnp.where(applied, lg, pg))
        leaf_grad = leaf_grad.at[new_leaf].set(
            jnp.where(applied, rg, leaf_grad[new_leaf]))
        leaf_hess = st.leaf_hess.at[best_leaf].set(jnp.where(applied, lh, ph))
        leaf_hess = leaf_hess.at[new_leaf].set(
            jnp.where(applied, rh, leaf_hess[new_leaf]))
        leaf_cnt = st.leaf_cnt.at[best_leaf].set(jnp.where(applied, lc, pc))
        leaf_cnt = leaf_cnt.at[new_leaf].set(
            jnp.where(applied, rc, leaf_cnt[new_leaf]))
        leaf_depth = st.leaf_depth.at[best_leaf].set(
            jnp.where(applied, d_child, st.leaf_depth[best_leaf]))
        leaf_depth = leaf_depth.at[new_leaf].set(
            jnp.where(applied, d_child, leaf_depth[new_leaf]))
        l2_used = params.lambda_l2 + params.cat_l2 * catl2.astype(jnp.float32)
        cminp = st.leaf_cmin[best_leaf]
        cmaxp = st.leaf_cmax[best_leaf]
        poutp = st.leaf_pout[best_leaf]
        lw = child_output(lg, lh, lc, sp_params, l2_used, poutp, cminp, cmaxp)
        rw = child_output(rg, rh, rc, sp_params, l2_used, poutp, cminp, cmaxp)
        leaf_out = st.leaf_out.at[best_leaf].set(
            jnp.where(applied, lw, st.leaf_out[best_leaf]))
        leaf_out = leaf_out.at[new_leaf].set(
            jnp.where(applied, rw, leaf_out[new_leaf]))
        leaf_pout = st.leaf_pout.at[best_leaf].set(
            jnp.where(applied, lw, poutp))
        leaf_pout = leaf_pout.at[new_leaf].set(
            jnp.where(applied, rw, leaf_pout[new_leaf]))
        iscat_split = is_cat_arr[f_]
        if params.use_monotone:
            mt = mono_types[f_].astype(jnp.int32)
            act = applied & jnp.logical_not(iscat_split)
            if params.mono_intermediate:
                # intermediate method: children bound by the SIBLING's
                # actual output, not the midpoint (reference:
                # UpdateConstraintsWithOutputs, monotone_constraints
                # .hpp:546-560)
                cmax_l = jnp.where(act & (mt > 0),
                                   jnp.minimum(cmaxp, rw), cmaxp)
                cmin_l = jnp.where(act & (mt < 0),
                                   jnp.maximum(cminp, rw), cminp)
                cmin_r = jnp.where(act & (mt > 0),
                                   jnp.maximum(cminp, lw), cminp)
                cmax_r = jnp.where(act & (mt < 0),
                                   jnp.minimum(cmaxp, lw), cmaxp)
            else:
                mid = 0.5 * (lw + rw)
                cmax_l = jnp.where(act & (mt > 0),
                                   jnp.minimum(cmaxp, mid), cmaxp)
                cmin_l = jnp.where(act & (mt < 0),
                                   jnp.maximum(cminp, mid), cminp)
                cmin_r = jnp.where(act & (mt > 0),
                                   jnp.maximum(cminp, mid), cminp)
                cmax_r = jnp.where(act & (mt < 0),
                                   jnp.minimum(cmaxp, mid), cmaxp)
        else:
            cmax_l = cmax_r = cmaxp
            cmin_l = cmin_r = cminp
        leaf_cmin = st.leaf_cmin.at[best_leaf].set(
            jnp.where(applied, cmin_l, cminp))
        leaf_cmin = leaf_cmin.at[new_leaf].set(
            jnp.where(applied, cmin_r, leaf_cmin[new_leaf]))
        leaf_cmax = st.leaf_cmax.at[best_leaf].set(
            jnp.where(applied, cmax_l, cmaxp))
        leaf_cmax = leaf_cmax.at[new_leaf].set(
            jnp.where(applied, cmax_r, leaf_cmax[new_leaf]))
        used_child = st.leaf_used[best_leaf] | (jnp.arange(F_scan) == f_)
        leaf_used = st.leaf_used.at[best_leaf].set(
            jnp.where(applied, used_child, st.leaf_used[best_leaf]))
        leaf_used = leaf_used.at[new_leaf].set(
            jnp.where(applied, used_child, leaf_used[new_leaf]))
        cegb_used = st.cegb_used | (applied & (jnp.arange(F_scan) == f_))

        # ---- physical partition + children histograms + best splits ----
        # NO lax.cond around the heavy buffers: a cond output forces XLA to
        # copy the carried work/scratch arrays (~1.4 GB) every split. The
        # not-applied case instead zeroes the loop trip counts, so the same
        # program runs with empty partition/histogram walks.
        s_ = st.leaf_start[best_leaf]
        m_loc = st.leaf_nrows[best_leaf]
        if ax:
            # global split decision, LOCAL partition offsets: this shard's
            # left count comes from its own histogram (reference keeps
            # global_data_count_in_leaf_ beside the local partition,
            # data_parallel_tree_learner.cpp:300-340)
            m_g = st.leaf_nrows_g[best_leaf]
            parent_loc = st.leaf_hist_loc[best_leaf].reshape(F, B, 4)
            n_left_loc = left_rows_of_split(
                parent_loc, f_col, b_, dl, nan_bin_arr[f_], f_cat, bits)
        else:
            m_g = m_loc
            parent_loc = None
            n_left_loc = n_left
        n_right_g = m_g - n_left
        n_right_loc = m_loc - n_left_loc
        # the GLOBALLY smaller child is streamed on every shard, so the
        # psum-ed histograms all describe the same child
        left_smaller = n_left <= n_right_g
        m_eff = jnp.where(applied, m_loc, 0)
        n_left_eff = jnp.where(applied, n_left_loc, 0)

        # stable partition of the parent's contiguous segment
        # (reference: DataPartition::Split / cuda_data_partition.cu:907)
        side_p = st.leaf_side[best_leaf]
        if params.fused_block:
            # one fused Mosaic kernel: partition + smaller-child histogram
            # in a single streamed walk (ops/fused_split.py); the left child
            # stays in the parent's residency array, the right child lands
            # in the other one (dual residency — no copy-back pass)
            with span("partition"), span("hist_build"):
                work, scratch, hist_small_fused = fused_split(
                    st.work, st.scratch, jnp.asarray(0, i32), s_, m_eff,
                    n_left_eff, f_col, b_, dl, nan_bin_arr[f_], f_cat,
                    bits, layout, B, params.fused_block, W,
                    interpret=params.fused_interpret,
                    smaller_left=left_smaller.astype(i32), side=side_p,
                    dual=params.fused_dual,
                    hist_debug=params.fused_hist_debug,
                    num_rows=n, quant=quant, mbatch=params.hist_mbatch,
                    hist_layout=params.hist_layout)
        else:
            with span("partition"):
                work, scratch = partition_segment(
                    st.work, st.scratch, s_, m_eff, n_left_eff, f_col, b_,
                    dl, nan_bin_arr[f_], f_cat, bits, params.part_block,
                    packed4=layout.packed4)
        leaf_start = st.leaf_start.at[best_leaf].set(
            jnp.where(applied, s_, st.leaf_start[best_leaf]))
        leaf_start = leaf_start.at[new_leaf].set(
            jnp.where(applied, s_ + n_left_loc, leaf_start[new_leaf]))
        leaf_nrows = st.leaf_nrows.at[best_leaf].set(
            jnp.where(applied, n_left_loc, st.leaf_nrows[best_leaf]))
        leaf_nrows = leaf_nrows.at[new_leaf].set(
            jnp.where(applied, n_right_loc, leaf_nrows[new_leaf]))
        if ax:
            leaf_nrows_g = st.leaf_nrows_g.at[best_leaf].set(
                jnp.where(applied, n_left, st.leaf_nrows_g[best_leaf]))
            leaf_nrows_g = leaf_nrows_g.at[new_leaf].set(
                jnp.where(applied, n_right_g, leaf_nrows_g[new_leaf]))
        else:
            leaf_nrows_g = st.leaf_nrows_g
        if params.fused_block and params.fused_dual:
            leaf_side = st.leaf_side.at[new_leaf].set(
                jnp.where(applied, 1 - side_p, st.leaf_side[new_leaf]))
        else:
            leaf_side = st.leaf_side

        # one streamed pass over the SMALLER child only; the larger child
        # is parent - smaller (reference: SubtractHistogramForLeaf,
        # cuda_histogram_constructor.cu:723); exact in int32 when quantized
        parent_hist = st.leaf_hist[best_leaf].reshape(F_h, B, 4)
        if params.fused_block:
            hist_small_loc = hist_small_fused
            hist_small = reduce_any(hist_small_loc)
        else:
            s_small = jnp.where(left_smaller, s_, s_ + n_left_loc)
            m_small = jnp.where(left_smaller, n_left_eff,
                                m_eff - n_left_eff)
            hist_small_loc, hist_small = seg_hist_reduced(
                work, s_small, m_small)
        hist_large = parent_hist - hist_small
        hist_left = jnp.where(left_smaller, hist_small, hist_large)
        hist_right = jnp.where(left_smaller, hist_large, hist_small)
        leaf_hist = st.leaf_hist.at[best_leaf].set(
            jnp.where(applied, hist_left, parent_hist).reshape(F_h, B * 4))
        leaf_hist = leaf_hist.at[new_leaf].set(
            jnp.where(applied, hist_right.reshape(F_h, B * 4),
                      leaf_hist[new_leaf]))
        if ax:
            large_loc = parent_loc - hist_small_loc
            left_loc = jnp.where(left_smaller, hist_small_loc, large_loc)
            right_loc = jnp.where(left_smaller, large_loc, hist_small_loc)
            leaf_hist_loc = st.leaf_hist_loc.at[best_leaf].set(
                jnp.where(applied, left_loc, parent_loc)
                .reshape(F, B * 4))
            leaf_hist_loc = leaf_hist_loc.at[new_leaf].set(
                jnp.where(applied, right_loc.reshape(F, B * 4),
                          leaf_hist_loc[new_leaf]))
        else:
            leaf_hist_loc = st.leaf_hist_loc

        fm_l = node_feature_mask(
            feat_mask, used_child, inter_sets,
            jax.random.fold_in(bynode_key, 2 * k + 1), params)
        fm_r = node_feature_mask(
            feat_mask, used_child, inter_sets,
            jax.random.fold_in(bynode_key, 2 * k + 2), params)
        pen = cegb_coupled * jnp.logical_not(cegb_used)
        spl = leaf_best(hist_left, lg, lh, lc, d_child, fm_l,
                        cmin_l, cmax_l, lw, pen,
                        jax.random.fold_in(extra_key, 2 * k + 1))
        spr = leaf_best(hist_right, rg, rh, rc, d_child, fm_r,
                        cmin_r, cmax_r, rw, pen,
                        jax.random.fold_in(extra_key, 2 * k + 2))
        (bs_gain, bs_feature, bs_bin, bs_dl, bs_lg, bs_lh, bs_lc, bs_lr,
         bs_bits, bs_catl2) = (st.bs_gain, st.bs_feature, st.bs_bin,
                               st.bs_default_left, st.bs_left_grad,
                               st.bs_left_hess, st.bs_left_cnt,
                               st.bs_left_rows, st.bs_bitset, st.bs_cat_l2)
        for leaf, sp in ((best_leaf, spl), (new_leaf, spr)):
            bs_gain = bs_gain.at[leaf].set(
                jnp.where(applied, sp.gain, bs_gain[leaf]))
            bs_feature = bs_feature.at[leaf].set(
                jnp.where(applied, sp.feature, bs_feature[leaf]))
            bs_bin = bs_bin.at[leaf].set(
                jnp.where(applied, sp.bin, bs_bin[leaf]))
            bs_dl = bs_dl.at[leaf].set(
                jnp.where(applied, sp.default_left, bs_dl[leaf]))
            bs_lg = bs_lg.at[leaf].set(
                jnp.where(applied, sp.left_grad, bs_lg[leaf]))
            bs_lh = bs_lh.at[leaf].set(
                jnp.where(applied, sp.left_hess, bs_lh[leaf]))
            bs_lc = bs_lc.at[leaf].set(
                jnp.where(applied, sp.left_count, bs_lc[leaf]))
            bs_lr = bs_lr.at[leaf].set(
                jnp.where(applied, sp.left_rows.astype(i32), bs_lr[leaf]))
            bs_bits = bs_bits.at[leaf].set(
                jnp.where(applied, sp.cat_bitset, bs_bits[leaf]))
            bs_catl2 = bs_catl2.at[leaf].set(
                jnp.where(applied, sp.is_cat_l2, bs_catl2[leaf]))

        if params.mono_intermediate:
            # ---- intermediate monotone: tighten contiguous leaves ----
            # (reference: IntermediateLeafConstraints::Update +
            # GoUpToFindLeavesToUpdate / GoDownToFindLeavesToUpdate,
            # src/treelearner/monotone_constraints.hpp:560-858). Walk up
            # from the new split; at every monotone ancestor whose opposite
            # branch is still contiguous, walk down it and clamp each
            # contiguous leaf's bound against the new children's ACTUAL
            # outputs; leaves whose bounds changed get their cached best
            # split recomputed (it may now violate the tighter bound).
            mono_i32 = mono_types.astype(i32)
            mt_i = mono_i32[f_]
            in_mono_here = jnp.logical_or(mt_i != 0,
                                          st.leaf_in_mono[best_leaf])
            eff = jnp.logical_and(applied, in_mono_here)
            leaf_in_mono = st.leaf_in_mono.at[best_leaf].set(
                jnp.where(applied, in_mono_here,
                          st.leaf_in_mono[best_leaf]))
            leaf_in_mono = leaf_in_mono.at[new_leaf].set(
                jnp.where(applied, in_mono_here, leaf_in_mono[new_leaf]))
            node_parent = st.node_parent.at[node].set(
                jnp.where(applied, p, st.node_parent[node]))
            node_is_cat = st.node_is_cat.at[node].set(
                jnp.where(applied, iscat_split, st.node_is_cat[node]))
            leaf_fmask = st.leaf_fmask.at[best_leaf].set(
                jnp.where(applied, fm_l, st.leaf_fmask[best_leaf]))
            leaf_fmask = leaf_fmask.at[new_leaf].set(
                jnp.where(applied, fm_r, leaf_fmask[new_leaf]))

            arangeL = jnp.arange(L, dtype=i32)
            thr_split = b_
            lo_out = jnp.minimum(lw, rw)
            hi_out = jnp.maximum(lw, rw)

            def up_cond(c):
                return c[1] >= 0

            def up_body(c):
                (cur, par, d, n_pend, feats_u, thrs_u, wasr_u, pend_root,
                 pend_umax, pend_d) = c
                pf = split_feature[par]
                pt = split_bin[par]
                p_num = jnp.logical_not(node_is_cat[par])
                mt_p = mono_i32[pf]
                is_right = right_child[par] == cur
                # contiguity optimization: a second climb on the same side
                # of the same feature cannot reach new contiguous leaves
                clash = jnp.any((feats_u == pf) & (wasr_u == is_right)
                                & (arangeL < d))
                opp_should = p_num & jnp.logical_not(clash)
                do_pend = opp_should & (mt_p != 0)
                left_is_cur = left_child[par] == cur
                opp = jnp.where(left_is_cur, right_child[par],
                                left_child[par])
                umax = jnp.where(mt_p < 0, left_is_cur,
                                 jnp.logical_not(left_is_cur))
                ip = jnp.minimum(n_pend, L - 1)
                pend_root = pend_root.at[ip].set(
                    jnp.where(do_pend, opp, pend_root[ip]))
                pend_umax = pend_umax.at[ip].set(
                    jnp.where(do_pend, umax, pend_umax[ip]))
                pend_d = pend_d.at[ip].set(
                    jnp.where(do_pend, d, pend_d[ip]))
                n_pend = n_pend + do_pend.astype(i32)
                idx = jnp.minimum(d, L - 1)
                feats_u = feats_u.at[idx].set(
                    jnp.where(opp_should, pf, feats_u[idx]))
                thrs_u = thrs_u.at[idx].set(
                    jnp.where(opp_should, pt, thrs_u[idx]))
                wasr_u = wasr_u.at[idx].set(
                    jnp.where(opp_should, is_right, wasr_u[idx]))
                d = d + opp_should.astype(i32)
                return (par, node_parent[par], d, n_pend, feats_u, thrs_u,
                        wasr_u, pend_root, pend_umax, pend_d)

            up0 = (node, jnp.where(eff, p, jnp.asarray(-1, i32)),
                   jnp.asarray(0, i32), jnp.asarray(0, i32),
                   jnp.full((L,), -1, i32), jnp.zeros((L,), i32),
                   jnp.zeros((L,), bool), jnp.zeros((L,), i32),
                   jnp.zeros((L,), bool), jnp.zeros((L,), i32))
            (_, _, _, n_pend, feats_u, thrs_u, wasr_u, pend_root,
             pend_umax, pend_d) = lax.while_loop(up_cond, up_body, up0)

            def down_one(j, carry):
                lcm0, lcx0, rs0 = carry
                dj = pend_d[j]
                umax = pend_umax[j]
                mask_u = arangeL < dj

                def d_cond(s):
                    return s[0] > 0

                def d_body(s):
                    sp_, st_n, st_ul, st_ur, lcm, lcx, rs = s
                    sp_ = sp_ - 1
                    nd = st_n[sp_]
                    ul = st_ul[sp_]
                    ur = st_ur[sp_]
                    is_leaf = nd < 0
                    leafi = jnp.maximum(-(nd + 1), 0)
                    both = jnp.logical_and(ul, ur)
                    # update_max clamps with the SMALLER contiguous output,
                    # update_min with the larger (reference minmax pair)
                    bnd_max = jnp.where(both, lo_out, jnp.where(ur, rw, lw))
                    bnd_min = jnp.where(both, hi_out, jnp.where(ur, rw, lw))
                    gain_ok = bs_gain[leafi] > _NEG_INF / 2
                    newmax = jnp.minimum(lcx[leafi], bnd_max)
                    newmin = jnp.maximum(lcm[leafi], bnd_min)
                    chg = jnp.where(umax, newmax < lcx[leafi],
                                    newmin > lcm[leafi])
                    upd = is_leaf & gain_ok
                    lcx = lcx.at[leafi].set(
                        jnp.where(upd & umax, newmax, lcx[leafi]))
                    lcm = lcm.at[leafi].set(
                        jnp.where(upd & jnp.logical_not(umax), newmin,
                                  lcm[leafi]))
                    rs = rs.at[leafi].set(rs[leafi] | (upd & chg))
                    ndi = jnp.maximum(nd, 0)
                    nf_n = split_feature[ndi]
                    nt_n = split_bin[ndi]
                    n_num = jnp.logical_not(node_is_cat[ndi])
                    same = (feats_u == nf_n) & mask_u
                    kg_r = jnp.logical_not(jnp.any(
                        same & (nt_n >= thrs_u)
                        & jnp.logical_not(wasr_u))) | jnp.logical_not(n_num)
                    kg_l = jnp.logical_not(jnp.any(
                        same & (nt_n <= thrs_u) & wasr_u)) \
                        | jnp.logical_not(n_num)
                    ul4r = jnp.logical_not(n_num & (nf_n == f_)
                                           & (nt_n >= thr_split))
                    ur4l = jnp.logical_not(n_num & (nf_n == f_)
                                           & (nt_n <= thr_split))
                    push_l = jnp.logical_not(is_leaf) & kg_l
                    st_n = st_n.at[sp_].set(
                        jnp.where(push_l, left_child[ndi], st_n[sp_]))
                    st_ul = st_ul.at[sp_].set(
                        jnp.where(push_l, ul, st_ul[sp_]))
                    st_ur = st_ur.at[sp_].set(
                        jnp.where(push_l, ur & ur4l, st_ur[sp_]))
                    sp_ = sp_ + push_l.astype(i32)
                    push_r = jnp.logical_not(is_leaf) & kg_r
                    st_n = st_n.at[sp_].set(
                        jnp.where(push_r, right_child[ndi], st_n[sp_]))
                    st_ul = st_ul.at[sp_].set(
                        jnp.where(push_r, ul & ul4r, st_ul[sp_]))
                    st_ur = st_ur.at[sp_].set(
                        jnp.where(push_r, ur, st_ur[sp_]))
                    sp_ = sp_ + push_r.astype(i32)
                    return (sp_, st_n, st_ul, st_ur, lcm, lcx, rs)

                out = lax.while_loop(
                    d_cond, d_body,
                    (jnp.asarray(1, i32),
                     jnp.zeros((2 * L,), i32).at[0].set(pend_root[j]),
                     jnp.zeros((2 * L,), bool).at[0].set(True),
                     jnp.zeros((2 * L,), bool).at[0].set(True),
                     lcm0, lcx0, rs0))
                return out[4], out[5], out[6]

            leaf_cmin, leaf_cmax, resc = lax.fori_loop(
                0, n_pend, down_one,
                (leaf_cmin, leaf_cmax, jnp.zeros((L,), bool)))

            # rescan every leaf whose bounds tightened — its cached split
            # may now be invalid (reference: leaves_to_update_ re-entering
            # FindBestSplitsFromHistograms)
            pen_cur = cegb_coupled * jnp.logical_not(cegb_used)

            def rescan_body(i, carry):
                (g_a, f_a, b_a, d_a, lg_a, lh_a, lc_a, lr_a, bb_a,
                 cl_a, cmn_a, cmx_a) = carry

                def do(_):
                    sp = leaf_best(
                        leaf_hist[i].reshape(F_h, B, 4), leaf_grad[i],
                        leaf_hess[i], leaf_cnt[i], leaf_depth[i],
                        leaf_fmask[i], cmn_a[i], cmx_a[i], leaf_pout[i],
                        pen_cur,
                        # chained fold under a fixed domain separator:
                        # rescan draws must not depend on the leaf-array
                        # size, or a rung-padded program (step_buckets)
                        # would draw different extra_trees thresholds than
                        # the exact-keyed one; folding (separator, k, i)
                        # stepwise instead of a (3+k)*stride+i product
                        # keeps traced-i32 arithmetic in range at any
                        # num_leaves and cannot re-enter the node-draw
                        # fold domain (2k+2 < the separator)
                        jax.random.fold_in(jax.random.fold_in(
                            jax.random.fold_in(
                                extra_key, _RESCAN_FOLD_STRIDE), k), i))
                    return (sp.gain, sp.feature, sp.bin, sp.default_left,
                            sp.left_grad, sp.left_hess, sp.left_count,
                            sp.left_rows.astype(i32), sp.cat_bitset,
                            sp.is_cat_l2)

                def dont(_):
                    return (g_a[i], f_a[i], b_a[i], d_a[i], lg_a[i],
                            lh_a[i], lc_a[i], lr_a[i], bb_a[i], cl_a[i])

                vals = lax.cond(resc[i], do, dont, 0)
                return (g_a.at[i].set(vals[0]), f_a.at[i].set(vals[1]),
                        b_a.at[i].set(vals[2]), d_a.at[i].set(vals[3]),
                        lg_a.at[i].set(vals[4]), lh_a.at[i].set(vals[5]),
                        lc_a.at[i].set(vals[6]), lr_a.at[i].set(vals[7]),
                        bb_a.at[i].set(vals[8]), cl_a.at[i].set(vals[9]),
                        cmn_a, cmx_a)

            (bs_gain, bs_feature, bs_bin, bs_dl, bs_lg, bs_lh, bs_lc,
             bs_lr, bs_bits, bs_catl2, leaf_cmin, leaf_cmax) = lax.fori_loop(
                0, L, rescan_body,
                (bs_gain, bs_feature, bs_bin, bs_dl, bs_lg, bs_lh, bs_lc,
                 bs_lr, bs_bits, bs_catl2, leaf_cmin, leaf_cmax))
        else:
            leaf_in_mono = st.leaf_in_mono
            node_parent = st.node_parent
            node_is_cat = st.node_is_cat
            leaf_fmask = st.leaf_fmask

        return CompactState(
            done=done,
            num_nodes=st.num_nodes + jnp.where(applied, 1, 0).astype(i32),
            work=work,
            scratch=scratch,
            leaf_hist=leaf_hist,
            leaf_hist_loc=leaf_hist_loc,
            leaf_start=leaf_start,
            leaf_nrows=leaf_nrows,
            leaf_nrows_g=leaf_nrows_g,
            leaf_side=leaf_side,
            split_feature=split_feature,
            split_bin=split_bin,
            cat_bitset=cat_bitset,
            split_gain=split_gain,
            default_left=default_left,
            left_child=left_child,
            right_child=right_child,
            leaf_parent=leaf_parent,
            leaf_parent_side=leaf_parent_side,
            leaf_depth=leaf_depth,
            node_grad=node_grad,
            node_hess=node_hess,
            node_cnt=node_cnt,
            leaf_grad=leaf_grad,
            leaf_hess=leaf_hess,
            leaf_cnt=leaf_cnt,
            bs_gain=bs_gain,
            bs_feature=bs_feature,
            bs_bin=bs_bin,
            bs_default_left=bs_dl,
            bs_left_grad=bs_lg,
            bs_left_hess=bs_lh,
            bs_left_cnt=bs_lc,
            bs_left_rows=bs_lr,
            bs_bitset=bs_bits,
            bs_cat_l2=bs_catl2,
            leaf_out=leaf_out,
            leaf_cmin=leaf_cmin,
            leaf_cmax=leaf_cmax,
            leaf_used=leaf_used,
            leaf_pout=leaf_pout,
            cegb_used=cegb_used,
            leaf_in_mono=leaf_in_mono,
            node_parent=node_parent,
            node_is_cat=node_is_cat,
            leaf_fmask=leaf_fmask,
        )

    st = lax.fori_loop(0, L - 1, body, st)

    if params.fused_block and params.fused_dual:
        # dual residency: consolidate scratch-resident segments back into
        # work once per tree (the copy-back variant does this after EVERY
        # split, re-streaming the whole right child each time)
        _, row_side = segments_to_leaf_vectors(
            st.leaf_start, st.leaf_nrows, st.leaf_side.astype(jnp.float32), n)
        in_scratch = jnp.zeros((st.work.shape[0],), bool) \
            .at[:n].set(row_side > 0.5)
        st = st._replace(
            work=jnp.where(in_scratch[:, None], st.scratch, st.work))

    leaf_value = st.leaf_out
    tree = TreeArrays(
        split_feature=st.split_feature,
        split_bin=st.split_bin,
        cat_bitset=st.cat_bitset,
        split_gain=st.split_gain,
        default_left=st.default_left,
        left_child=st.left_child,
        right_child=st.right_child,
        leaf_value=leaf_value,
        leaf_weight=st.leaf_hess,
        leaf_count=st.leaf_cnt,
        leaf_parent=st.leaf_parent,
        leaf_depth=st.leaf_depth,
        internal_value=leaf_output(st.node_grad, st.node_hess, sp_params),
        internal_weight=st.node_hess,
        internal_count=st.node_cnt,
        num_leaves=st.num_nodes + 1,
        num_nodes=st.num_nodes,
    )
    row_leaf, _ = segments_to_leaf_vectors(
        st.leaf_start, st.leaf_nrows, leaf_value, n)
    return (tree, row_leaf, st.work, st.scratch, st.leaf_start,
            st.leaf_nrows)
