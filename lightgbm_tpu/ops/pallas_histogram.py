"""Pallas TPU histogram kernel.

TPU-native re-design of the reference's histogram kernels (reference: CUDA
shared-memory atomicAdd kernels, src/treelearner/cuda/
cuda_histogram_constructor.cu:17-68 CUDAConstructHistogramDenseKernel).

The XLA fallback (ops/histogram.py) materializes the row-block one-hot in HBM
(~B× expansion of the bin matrix) and goes HBM-bandwidth-bound. This kernel
forms the one-hot **in VMEM** per (row-block, feature-chunk) — a broadcast
compare against a bin iota — feeds it straight to the MXU, and accumulates the
[F*B, K] histogram in an output block that stays resident in VMEM across the
whole row grid. HBM traffic drops to reading bins and channels once per pass.
Measured on v5e at [1M, 28] x B=256: ~0.59 Telem/s of one-hot work vs ~0.007
for the XLA path.

Where the CUDA kernel resolves collisions with atomicAdd into shared memory,
the one-hot contraction has no collisions by construction: each row contributes
to exactly one bin column per feature, and the MXU reduces over rows.

Batched-M issue (round 6, shared design with ops/fused_split.py hist_flush):
the contraction's natural output has only 8 rows (the padded channel count),
so each MXU issue ran at M=8 of 128 rows. Channels now arrive CHANNEL-MAJOR
([KP, N], transposed once on the XLA side — no in-kernel relayout), each
grid step's row block subdivides into ``mbatch`` windows, and the kernel
builds a block-diagonal [8K, R] channel LHS (tile the [KP, R] slab K times
along sublanes, mask each 8-row band to its own lane window) contracted in
ONE matmul per feature chunk with M = 8*mbatch rows; the K per-window
partial sums reduce with K-1 vector adds. Counts and int32 sums are
bit-identical to mbatch=1; f32/split sums regroup within ~1 ulp.

Precision modes (the one-hot itself is exact in bf16 — values 0/1):

  * ``split`` (default) — channels decompose as hi+lo bf16 pairs occupying the
    8 padded lanes (hi = bf16(x), lo = bf16(x - hi)); both halves contract at
    full MXU rate with f32 accumulation and are summed after the kernel.
    Error ~2^-17 relative — between f32 (2^-24) and the reference's own int8
    quantized-histogram mode (src/treelearner/gradient_discretizer.cpp).
    Integer-valued count channels stay exact (lo == 0, f32 accumulate).
  * ``bf16`` — channels rounded to bf16; fastest, ~2^-9 relative error.
  * ``f32``  — fp32-accurate MXU mode (3-pass); ~5x slower, for bit-level
    comparisons against the XLA path.
  * ``int8`` — quantized-gradient mode (reference:
    cuda_histogram_constructor.cu:249-524): channels are int8 grad/hess
    codes, the one-hot forms in int8, and the contraction runs
    int8 x int8 -> int32 (``preferred_element_type=int32``) at 2x the bf16
    MXU rate with EXACT integer sums — no hi/lo split needed.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

try:  # pallas is TPU/Mosaic only; CPU tests use interpret mode
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

# K channels padded to the f32 sublane width
_K_PAD = 8


def _hist_kernel(bins_ref, ch_ref, out_ref, *, num_bins: int, f_chunk: int,
                 mode: str, mbatch: int):
    """One grid step: accumulate a row-block into the [KP, F*B] histogram.

    The output is CHANNEL-major: [KP, F*B] keeps the lane dimension wide
    (F*B) instead of padding an 8-lane channel dimension to 128, so the
    VMEM-resident accumulator costs 8 x F*B x 4B (1.1MB at F=137, B=256)
    rather than 32x that.

    ``ch_ref`` is the CHANNEL-MAJOR [KP, R] slab of this row block; with
    ``mbatch`` > 1 the block subdivides into K row windows of R/K rows and
    the channel LHS becomes block-diagonal [8K, R] so every matmul issues
    M = 8K MXU rows (see module docstring). The drain of a ragged tail
    needs no special casing here: padding rows carry zero channels, so
    whatever they one-hot into sums to zero. pushes % mbatch == 0 always
    holds because the window partition is exact (R % mbatch == 0,
    enforced by the wrapper).

    The unrolled chunk loop makes the register allocator spill the one-hot
    temporaries to the VMEM stack when F*B is large (measured on v5e at
    B=256: F=200 compiles, F=320 wants 149MB of spill slots against the
    128MB budget); the auto dispatch (ops/histogram.py _resolve_impl)
    routes such configs to the XLA path instead."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    # uint8 -> int32 (Mosaic has no direct uint8 -> float cast)
    bins = bins_ref[:].astype(jnp.int32)          # [R, F]
    ch = ch_ref[:]                                # [KP, R] f32/int8
    r = bins.shape[0]
    f = bins.shape[1]
    b = num_bins
    w = f_chunk
    assert f % w == 0
    assert r % mbatch == 0
    sub = r // mbatch

    if mode == "int8":
        oh_dtype = jnp.int8
        acc_dtype = jnp.int32
        precision = None
    else:
        oh_dtype = jnp.float32 if mode == "f32" else jnp.bfloat16
        acc_dtype = jnp.float32
        if mode != "f32":
            ch = ch.astype(jnp.bfloat16)
        precision = (lax.Precision.HIGHEST if mode == "f32"
                     else lax.Precision.DEFAULT)
    if mbatch > 1:
        # block-diagonal [8K, R] channel LHS: K sublane-tiled copies of the
        # [KP, R] slab, each 8-row band masked to its own lane window
        tiled = jnp.concatenate([ch] * mbatch, axis=0)        # [8K, R]
        band = lax.broadcasted_iota(jnp.int32, tiled.shape, 0) // _K_PAD
        win = lax.broadcasted_iota(jnp.int32, tiled.shape, 1) // sub
        ch_lhs = jnp.where(band == win, tiled, jnp.zeros_like(tiled))
    else:
        ch_lhs = ch
    iota_b = lax.broadcasted_iota(jnp.int32, (r, b), 1)

    for fc in range(0, f, w):
        # one-hot for w features side by side: [R, W*B] built by broadcast
        # compares in VMEM (never touches HBM)
        oh = jnp.concatenate(
            [(bins[:, fc + j:fc + j + 1] == iota_b).astype(oh_dtype)
             for j in range(w)], axis=1)
        # MXU contraction over rows: [8K, R] x [R, W*B] -> [8K, W*B]
        # (int8 mode: int8 x int8 -> int32, preferred_element_type pins the
        # accumulator so the int8 operands cannot narrow the output)
        part = lax.dot_general(
            ch_lhs, oh,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=acc_dtype,
            precision=precision,
        )
        red = part[0:_K_PAD]
        for t in range(1, mbatch):
            red = red + part[_K_PAD * t:_K_PAD * (t + 1)]
        out_ref[:, fc * b:(fc + w) * b] += red


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


# sublane-layout constraint: bins lie along sublanes, so the padded per-
# feature bin stride must leave room for at least one feature per 128-row
# MXU tile — B <= 64 (the README's "bins-on-sublanes for B <= 64" case)
_SUBLANE_MAX_BINS = 64


def sublane_bin_stride(num_bins: int, mode: str) -> int:
    """Per-feature sublane stride of the bins-on-sublanes one-hot.

    Rounded up to the one-hot dtype's sublane tile (int8: 32, bf16: 16,
    f32: 8) so the per-feature [stride, R] compare tiles concatenate along
    sublanes without relayouts."""
    tile = 32 if mode == "int8" else (8 if mode == "f32" else 16)
    return _round_up(num_bins, tile)


def _hist_kernel_sublane(bins_ref, ch_ref, out_ref, *, num_bins: int,
                         b_sub: int, f_group: int, mode: str, mbatch: int):
    """Bins-on-sublanes grid step (tpu_hist_layout=sublane, B <= 64).

    The lane layout's per-feature one-hot compare produces a [R, B] tile —
    at B <= 64 that fills under half of the 128 register lanes, and the
    output M dimension is the 8 padded channels. Here the bins input
    arrives FEATURE-major ([F, N], one XLA-side transpose like the channel
    slab of the lane kernel), so the compare runs as
    ``bins[f:f+1, :] == iota_sublane`` — a [b_sub, R] tile whose LANE
    dimension is the full row block. A group of ``f_group`` features
    concatenates along sublanes into the [f_group * b_sub, R] one-hot LHS
    (M = 128 output rows at b_sub * f_group = 128), contracted against a
    block-diagonal [R, KP * mbatch] channel RHS whose lane bands hold the
    mbatch row windows — N = 8 * mbatch lanes. The per-window partial sums
    land in separate lane bands of the [F * b_sub, KP * mbatch] output and
    are reduced band-wise on the XLA side (exact for int32; f32 regroups
    within ~1 ulp, same contract as the lane kernel's batched-M reduce).
    """
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    bins = bins_ref[:].astype(jnp.int32)          # [F, R] feature-major
    ch = ch_ref[:]                                # [R, KP] row-major
    f, r = bins.shape
    assert f % f_group == 0
    assert r % mbatch == 0
    sub = r // mbatch

    if mode == "int8":
        oh_dtype, acc_dtype, precision = jnp.int8, jnp.int32, None
    else:
        oh_dtype = jnp.float32 if mode == "f32" else jnp.bfloat16
        acc_dtype = jnp.float32
        if mode != "f32":
            ch = ch.astype(jnp.bfloat16)
        precision = (lax.Precision.HIGHEST if mode == "f32"
                     else lax.Precision.DEFAULT)
    if mbatch > 1:
        # block-diagonal [R, KP*mb] channel RHS: the KP lanes tile mb
        # times and each band keeps only its own row window
        tiled = jnp.concatenate([ch] * mbatch, axis=1)       # [R, KP*mb]
        band = lax.broadcasted_iota(jnp.int32, tiled.shape, 1) // _K_PAD
        win = lax.broadcasted_iota(jnp.int32, tiled.shape, 0) // sub
        ch_rhs = jnp.where(band == win, tiled, jnp.zeros_like(tiled))
    else:
        ch_rhs = ch
    # bins-on-SUBLANES iota: dimension 0 (pad sublanes past num_bins can
    # never match a bin value, so they contribute exact zeros)
    iota_b = lax.broadcasted_iota(jnp.int32, (b_sub, r), 0)

    for fc in range(0, f, f_group):
        oh = jnp.concatenate(
            [(bins[fc + j:fc + j + 1, :] == iota_b).astype(oh_dtype)
             for j in range(f_group)], axis=0)    # [G*b_sub, R]
        part = lax.dot_general(
            oh, ch_rhs,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=acc_dtype,
            precision=precision,
        )                                          # [G*b_sub, KP*mb]
        out_ref[fc * b_sub:(fc + f_group) * b_sub, :] += part


def _resolve_mbatch(mbatch: int, row_block: int) -> int:
    """Clamp the batched-M depth to a divisor of the row block (exact
    window partition) with 8*K <= 128 MXU rows and windows >= 128 lanes."""
    mb = max(1, min(int(mbatch), 16, row_block // 128))
    while mb > 1 and row_block % mb:
        mb -= 1
    return mb


@functools.partial(
    jax.jit,
    static_argnames=("num_bins", "row_block", "f_chunk", "mode", "interpret",
                     "mbatch", "hist_layout"))
def pallas_histogram(
    binned: jax.Array,       # [N, F] uint8/int32
    channels: jax.Array,     # [N, K] f32 (int8 for mode='int8'), K <= 8
    #                          (K <= 4 for mode='split')
    num_bins: int,
    row_block: int = 2048,   # v5e sweet spot (with f_chunk=2): 0.59 Telem/s
    f_chunk: int = 2,
    mode: str = "split",     # split | bf16 | f32 | int8 (see module doc)
    interpret: bool = False,
    mbatch: int = 1,         # batched-M windows per row block (1-16)
    hist_layout: str = "lane",   # lane | sublane (tpu_hist_layout)
) -> jax.Array:              # [F, B, K] f32 (int32 for mode='int8')
    n, f_in = binned.shape
    k = channels.shape[1]
    b = num_bins
    if hist_layout == "sublane" and b > _SUBLANE_MAX_BINS:
        raise ValueError(
            f"hist_layout=sublane supports num_bins <= {_SUBLANE_MAX_BINS} "
            f"(got {b}): bins lie along sublanes, and wider bin counts "
            "leave no room to group features into the 128 MXU rows")
    # Mosaic VMEM scales ~ row_block * F * B * 0.83B (measured on v5e:
    # 138.7MB at [2048, 320] x B=256 against the 128MB budget); clamp the
    # row block so wide-F configs compile instead of OOMing vmem
    rb_cap = max(128, (121_000_000 // max(1, f_in * b)) // 128 * 128)
    row_block = min(row_block, rb_cap)
    mbatch = _resolve_mbatch(mbatch, row_block)

    if mode == "int8" and not jnp.issubdtype(channels.dtype, jnp.integer):
        raise ValueError("mode='int8' needs integer channels (grad/hess "
                         "codes from the gradient discretizer)")
    if mode == "int8":
        channels = channels.astype(jnp.int8)
    if mode == "split":
        if 2 * k > _K_PAD:
            raise ValueError(f"mode='split' supports K<={_K_PAD // 2}, got {k}")
        # reduce_precision, NOT a bf16 cast round-trip: under
        # --xla_allow_excess_precision (set on TPU by default) XLA elides
        # f32->bf16->f32 as identity, which silently folds lo to zero
        hi = lax.reduce_precision(channels, exponent_bits=8, mantissa_bits=7)
        lo = channels - hi
        channels = jnp.concatenate([hi, lo], axis=1)  # [N, 2K]

    # pad rows to the block size (zero channels contribute nothing), features
    # to the chunk/group width, and channels to the sublane width
    b_sub = sublane_bin_stride(b, mode)
    f_group = max(1, 128 // b_sub)
    f_unit = f_group if hist_layout == "sublane" else f_chunk
    n_pad = (-n) % row_block
    f_pad = (-f_in) % f_unit
    if n_pad or f_pad:
        binned = jnp.pad(binned, ((0, n_pad), (0, f_pad)))
    if n_pad:
        channels = jnp.pad(channels, ((0, n_pad), (0, 0)))
    kc = channels.shape[1]
    if kc < _K_PAD:
        channels = jnp.pad(channels, ((0, 0), (0, _K_PAD - kc)))
    n_tot = n + n_pad
    f = f_in + f_pad

    if hist_layout == "sublane":
        # bins feed FEATURE-major (one XLA transpose — the mirror of the
        # lane layout's channel slab) and channels stay row-major: the
        # kernel's compare tiles then span the full row block on lanes
        kernel = functools.partial(
            _hist_kernel_sublane, num_bins=b, b_sub=b_sub, f_group=f_group,
            mode=mode, mbatch=mbatch)
        acc_dtype = jnp.int32 if mode == "int8" else jnp.float32
        out = pl.pallas_call(
            kernel,
            grid=(n_tot // row_block,),
            in_specs=[
                pl.BlockSpec((f, row_block), lambda i: (0, i)),
                pl.BlockSpec((row_block, _K_PAD), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((f * b_sub, _K_PAD * mbatch),
                                   lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((f * b_sub, _K_PAD * mbatch),
                                           acc_dtype),
            interpret=interpret,
        )(binned.T, channels)
        # band-wise reduction of the mbatch row windows, then bin-major ->
        # [F, B, K] (int32 adds exact; f32 regroups within ~1 ulp)
        out = out.reshape(f, b_sub, mbatch, _K_PAD).sum(axis=2)
        out = out[:f_in, :b, :]
        if mode == "split":
            return out[:, :, :k] + out[:, :, k:2 * k]
        return out[:, :, :k]

    # channel-major slab: ONE XLA-side transpose instead of an in-kernel
    # Mosaic relayout per block (relayouts dominate on this toolchain)
    channels_t = channels.T                       # [KP, N]

    kernel = functools.partial(
        _hist_kernel, num_bins=b, f_chunk=f_chunk, mode=mode, mbatch=mbatch)

    acc_dtype = jnp.int32 if mode == "int8" else jnp.float32
    out = pl.pallas_call(
        kernel,
        grid=(n_tot // row_block,),
        in_specs=[
            pl.BlockSpec((row_block, f), lambda i: (i, 0)),
            pl.BlockSpec((_K_PAD, row_block), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((_K_PAD, f * b), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((_K_PAD, f * b), acc_dtype),
        interpret=interpret,
    )(binned, channels_t)
    out = jnp.transpose(out.reshape(_K_PAD, f, b), (1, 2, 0))[:f_in]
    if mode == "split":
        return out[:, :, :k] + out[:, :, k:2 * k]
    return out[:, :, :k]


def pallas_available() -> bool:
    """Pallas Mosaic kernels need a real TPU backend."""
    if not _HAS_PALLAS:
        return False
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:  # pragma: no cover
        return False
