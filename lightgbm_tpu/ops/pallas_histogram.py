"""Pallas TPU histogram kernel.

TPU-native re-design of the reference's histogram kernels (reference: CUDA
shared-memory atomicAdd kernels, src/treelearner/cuda/
cuda_histogram_constructor.cu:17-68 CUDAConstructHistogramDenseKernel).

The XLA fallback (ops/histogram.py) materializes the row-block one-hot in HBM
(~B× expansion of the bin matrix — measured 14.6 GB of traffic per histogram at
Higgs-1M scale, 20+ ms). This kernel forms the one-hot **in VMEM** per
(row-block, feature-chunk), feeds it straight to the MXU, and accumulates the
[F*B, K] histogram in the output block that stays resident in VMEM across the
whole row grid — HBM traffic drops to reading bins + channels once.

Where the CUDA kernel resolves collisions with atomicAdd into shared memory,
the one-hot contraction has no collisions by construction: each row contributes
to exactly one (bin) column per feature, and the MXU reduces over rows.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

try:  # pallas is TPU/Mosaic only; CPU tests use interpret mode
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

# K channels padded to the f32 sublane width
_K_PAD = 8


def _hist_kernel(bins_ref, ch_ref, out_ref, *, num_bins: int, f_chunk: int,
                 precision):
    """One grid step: accumulate a row-block into the [F*B, K] histogram."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    # uint8 -> f32 is not a supported Mosaic cast; go via int32 (bins < 2^24)
    bins = bins_ref[:].astype(jnp.int32).astype(jnp.float32)   # [R, F]
    ch = ch_ref[:]                                # [R, KP] f32
    r = bins.shape[0]
    f = bins.shape[1]
    b = num_bins

    assert f % f_chunk == 0
    w = f_chunk
    # loop-invariant constants (hoisted so Mosaic allocates them once)
    col = lax.broadcasted_iota(jnp.int32, (w, w * b), 1)
    row = lax.broadcasted_iota(jnp.int32, (w, w * b), 0)
    expand = (col // b == row).astype(jnp.float32)          # [W, W*B]
    bin_of_col = (lax.broadcasted_iota(jnp.int32, (r, w * b), 1) % b
                  ).astype(jnp.float32)

    for fc in range(0, f, w):
        blk = bins[:, fc:fc + w]                  # [R, W]
        # expand each feature column B times via a constant selection matmul
        # (Mosaic has no vector reshape for the [R, W, B] -> [R, W*B] path)
        bins_e = lax.dot_general(
            blk, expand, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=lax.Precision.HIGHEST,
        )                                          # [R, W*B]
        onehot = (bins_e == bin_of_col).astype(jnp.float32)  # VMEM only
        # MXU contraction over rows: [W*B, R] x [R, KP] -> [W*B, KP]
        part = lax.dot_general(
            onehot, ch,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision,
        )
        out_ref[fc * b:(fc + w) * b, :] += part


@functools.partial(
    jax.jit,
    static_argnames=("num_bins", "row_block", "f_chunk", "fast", "interpret"))
def pallas_histogram(
    binned: jax.Array,       # [N, F] uint8/int32
    channels: jax.Array,     # [N, K] f32
    num_bins: int,
    row_block: int = 1024,
    f_chunk: int = 4,
    fast: bool = False,      # True: single-pass bf16 MXU (~0.2% hist error)
    interpret: bool = False,
) -> jax.Array:              # [F, B, K] f32
    n, f_in = binned.shape
    k = channels.shape[1]
    b = num_bins

    # pad rows to the block size (zero channels contribute nothing), features
    # to the chunk width, and channels to the sublane width
    n_pad = (-n) % row_block
    f_pad = (-f_in) % f_chunk
    if n_pad or f_pad:
        binned = jnp.pad(binned, ((0, n_pad), (0, f_pad)))
    if n_pad:
        channels = jnp.pad(channels, ((0, n_pad), (0, 0)))
    if k < _K_PAD:
        channels = jnp.pad(channels, ((0, 0), (0, _K_PAD - k)))
    n_tot = n + n_pad
    f = f_in + f_pad

    precision = lax.Precision.DEFAULT if fast else lax.Precision.HIGHEST
    kernel = functools.partial(
        _hist_kernel, num_bins=b, f_chunk=f_chunk, precision=precision)

    out = pl.pallas_call(
        kernel,
        grid=(n_tot // row_block,),
        in_specs=[
            pl.BlockSpec((row_block, f), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((row_block, _K_PAD), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((f * b, _K_PAD), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((f * b, _K_PAD), jnp.float32),
        interpret=interpret,
    )(binned, channels)
    return out.reshape(f, b, _K_PAD)[:f_in, :, :k]


def pallas_available() -> bool:
    """Pallas Mosaic kernels need a real TPU backend."""
    if not _HAS_PALLAS:
        return False
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:  # pragma: no cover
        return False
