"""Per-leaf output renewal (quantile/median of residuals), on device.

TPU-native re-design of the reference's RenewTreeOutput for L1/quantile/MAPE
objectives (reference: RegressionL1loss::RenewTreeOutput
src/objective/regression_objective.hpp:197-232, PercentileFun
regression_objective.hpp:23-55; called from GBDT::TrainOneIter gbdt.cpp:409).

The reference gathers each leaf's rows and nth-elements the residuals on CPU.
Here: one global sort of residuals (XLA sort), then a sequential ``lax.map``
over the (small, static) leaf axis computes each leaf's weighted quantile with a
masked cumulative-sum scan — no per-leaf gather, no dynamic shapes.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


# largest per-leaf (count * quant_max) product whose packed-pair chunk sums
# stay exact — mirrors ops/histogram.py narrow_chunk_rows' radix bound at
# the 16-bit hist-bits level (reference threshold: leaf sums that fit the
# narrow histogram entry, gradient_discretizer.cpp GetHistBitsInLeaf)
_NARROW_LEAF_MAX = 1 << 15


def hist_bits_in_leaf(leaf_count, quant_max: int):
    """Per-leaf histogram bit width for the quantized pipeline — 16 where
    the leaf's worst-case code sums fit the narrow accumulate, else 32.

    TPU-native port of GradientDiscretizer::GetHistBitsInLeaf
    (gradient_discretizer.cpp): the reference renews each leaf's hist
    bits from its row count after every split so shrinking leaves drop to
    the narrow (packed) histogram. Here the decision is a traced scalar
    the compact grower feeds to a ``lax.cond`` over the two statically
    compiled segment-histogram variants (ops/grower_compact.py seg_hist):
    narrow leaves take the packed-pair engine, wide leaves the int8/int32
    engine — one program, per-leaf narrowing at run time.

    ``leaf_count`` may be traced (i32/f32 row count); ``quant_max`` is the
    static |code| bound (num_grad_quant_bins + 1)."""
    cnt = jnp.asarray(leaf_count).astype(jnp.float32)
    narrow = cnt * float(quant_max) < float(_NARROW_LEAF_MAX)
    return jnp.where(narrow, 16, 32).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("num_leaves", "alpha"))
def renew_leaf_quantile(
    residual: jax.Array,    # [N] f32 (label - current score)
    weight: jax.Array,      # [N] f32: row weight * in-bag mask (0 excludes row)
    row_leaf: jax.Array,    # [N] i32
    num_leaves: int,
    alpha: float,
) -> jax.Array:             # [L] f32 renewed leaf outputs
    order = jnp.argsort(residual)
    r_s = residual[order]
    leaf_s = row_leaf[order]
    w_s = weight[order]

    def one_leaf(l):
        m = jnp.where(leaf_s == l, w_s, 0.0)
        cw = jnp.cumsum(m)
        total = cw[-1]
        target = alpha * total
        # first row (in residual order) where cumulative weight crosses target
        ok = (cw >= target) & (m > 0.0)
        idx = jnp.argmax(ok)
        val = r_s[idx]
        return jnp.where(total > 0.0, val, 0.0)

    return lax.map(one_leaf, jnp.arange(num_leaves, dtype=jnp.int32))
