"""Per-leaf output renewal (quantile/median of residuals), on device.

TPU-native re-design of the reference's RenewTreeOutput for L1/quantile/MAPE
objectives (reference: RegressionL1loss::RenewTreeOutput
src/objective/regression_objective.hpp:197-232, PercentileFun
regression_objective.hpp:23-55; called from GBDT::TrainOneIter gbdt.cpp:409).

The reference gathers each leaf's rows and nth-elements the residuals on CPU.
Here: one global sort of residuals (XLA sort), then a sequential ``lax.map``
over the (small, static) leaf axis computes each leaf's weighted quantile with a
masked cumulative-sum scan — no per-leaf gather, no dynamic shapes.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


@functools.partial(jax.jit, static_argnames=("num_leaves", "alpha"))
def renew_leaf_quantile(
    residual: jax.Array,    # [N] f32 (label - current score)
    weight: jax.Array,      # [N] f32: row weight * in-bag mask (0 excludes row)
    row_leaf: jax.Array,    # [N] i32
    num_leaves: int,
    alpha: float,
) -> jax.Array:             # [L] f32 renewed leaf outputs
    order = jnp.argsort(residual)
    r_s = residual[order]
    leaf_s = row_leaf[order]
    w_s = weight[order]

    def one_leaf(l):
        m = jnp.where(leaf_s == l, w_s, 0.0)
        cw = jnp.cumsum(m)
        total = cw[-1]
        target = alpha * total
        # first row (in residual order) where cumulative weight crosses target
        ok = (cw >= target) & (m > 0.0)
        idx = jnp.argmax(ok)
        val = r_s[idx]
        return jnp.where(total > 0.0, val, 0.0)

    return lax.map(one_leaf, jnp.arange(num_leaves, dtype=jnp.int32))
