"""On-device featurization: raw float32 request rows -> bin codes.

The serving hot path's missing half (ISSUE 13 / ROADMAP item 3): before
this module every coalescer tick ran ``io/binning.bin_columns`` — a numpy
searchsorted sweep — on the host, so a "one device dispatch per tick"
server still paid O(rows * features) host work per tick. Here the
per-feature binning state (interior upper bounds, NaN / MissingType-Zero
handling, categorical code->bin lookup) is stacked once into
device-resident arrays (io/binning.export_featurize_state — the analogue
of the reference's cached single-row fast-path state, ``SingleRowPredictor``
+ ``FastConfig``, src/c_api.cpp:117) and a request becomes ONE host->device
copy of raw float32 followed by one jitted program:

  * numerical: ``sum(value > bounds)`` per feature — the broadcast
    compare-and-sum that equals ``np.searchsorted(bounds, v, 'left')``
    exactly, the same trick ``bin_columns`` uses on the host. Bounds are
    round-down float32 thresholds (io/binning.round_down_f32), so for
    float32 requests the device bins are bit-identical to the host path's
    float64-upcast comparisons;
  * NaN rows overwrite with the per-feature nan bin (which for
    MissingType Zero IS the zero bin — the same fill ``bin_columns``
    applies);
  * categorical: equality-match against the per-feature sorted code
    table (padded with a sentinel no request can produce); codes outside
    int32 or non-finite values map to bin 0, like the host lookup;
  * optional 4-bit nibble packing (``pack4_device``) so a pack4-serving
    model's featurized matrix enters the predict walk in the SAME packed
    layout the host path produces with io/dataset.pack4_matrix.

The program is keyed on the (row rung, feature count, state widths)
shapes only — all rung-padded by the caller — so a warmed serving ladder
compiles one featurize program per rung and the coalescer tick lowers
nothing new.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp


class DeviceBinState(NamedTuple):
    """Device-resident twin of io/binning.FeaturizeState."""

    bounds32: jax.Array      # [F, Kb] f32 round-down thresholds, +inf pad
    nan_bins: jax.Array      # [F] i32
    is_cat: jax.Array        # [F] bool
    cat_keys: jax.Array      # [F, Kc] i32, CAT_PAD padded
    cat_vals: jax.Array      # [F, Kc] i32, 0 padded


def device_bin_state(state) -> DeviceBinState:
    """Upload a host FeaturizeState once (deploy/warm time, not per tick)."""
    if state.reason is not None:
        raise ValueError(f"model is not device-featurizable: {state.reason}")
    return DeviceBinState(
        jnp.asarray(state.bounds32), jnp.asarray(state.nan_bins),
        jnp.asarray(state.is_cat), jnp.asarray(state.cat_keys),
        jnp.asarray(state.cat_vals))


def pack4_device(bins: jax.Array) -> jax.Array:
    """[N, F] u8 (< 16) -> [N, ceil(F/2)] u8, the io/dataset.pack4_matrix
    layout (column 2j in the low nibble, 2j+1 in the high nibble) so the
    predict walk's nibble gather (ops/packed.gather_bin) inverts it."""
    if bins.shape[1] % 2:
        bins = jnp.pad(bins, ((0, 0), (0, 1)))
    return bins[:, 0::2] | (bins[:, 1::2] << 4)


#: float32 values with |v| >= 2**31 cannot be categorical codes; the host
#: lookup int64-casts them to values no table contains, the device path
#: masks them to "no match" before its int32 cast
_CAT_RANGE = 2.0 ** 31


@functools.partial(jax.jit, static_argnames=("out_dtype", "packed"))
def bin_rows_device(raw: jax.Array, state: DeviceBinState,
                    n_valid: jax.Array,
                    out_dtype: str = "uint8",
                    packed: bool = False) -> jax.Array:
    """Featurize rung-padded raw rows on device: [N, F] f32 -> bin codes.

    Returns [N, F] ``out_dtype`` (or [N, ceil(F/2)] u8 when ``packed``),
    bit-identical to ``bin_columns(mappers, raw_f32)`` on the host —
    the device/host parity contract tests/test_device_serving.py pins
    across NaN, MissingType-Zero, categorical, EFB-bundled and
    pack4-stored models. ``n_valid`` (traced, so it never keys the jit
    cache) zeroes the padding rows' bins, exactly what the host path's
    pad-after-binning produces — device and host featurize are then
    byte-identical on the FULL padded rung, tail included.
    """
    from ..obs.spans import span
    with span("featurize"):
        nan_mask = jnp.isnan(raw)
        # numerical: sum(bounds < v) == searchsorted(bounds, v, 'left');
        # the +inf padding never counts, so ragged bound lists batch
        num = (raw[:, :, None] > state.bounds32[None, :, :]).sum(
            axis=2, dtype=jnp.int32)
        num = jnp.where(nan_mask, state.nan_bins[None, :], num)
        # categorical: exact equality against the sorted code table
        # (codes are unique per feature, so the masked sum IS the match)
        in_range = jnp.isfinite(raw) & (jnp.abs(raw) < _CAT_RANGE)
        iv = jnp.where(in_range, raw, 0.0).astype(jnp.int32)
        hit = (state.cat_keys[None, :, :] == iv[:, :, None]) \
            & in_range[:, :, None]
        cat = jnp.sum(jnp.where(hit, state.cat_vals[None, :, :], 0),
                      axis=2, dtype=jnp.int32)
        bins = jnp.where(state.is_cat[None, :], cat, num)
        live = jnp.arange(raw.shape[0], dtype=jnp.int32) < n_valid
        bins = jnp.where(live[:, None], bins, 0)
        bins = bins.astype(jnp.dtype(out_dtype))
        if packed:
            bins = pack4_device(bins)
        return bins
